"""Whole-CASE Pallas kernel: the full round LOOP in VMEM.

This is the final residency step past ``fused_round_single``
(ops/pallas_kernels.py, which fuses one round's applies): here the
scheduler's weighted pick, the applicability predicates, the per-round
tables (line spans, digit runs, widenable/binarish scans, sizer
candidates, fuse jump pairs) and ALL 31 device param generators run
INSIDE one pallas_call, so a sample's bytes
enter VMEM once, take every mutation round there, and leave once. Per-
round HBM traffic is zero on hardware (random bits come from the TPU
PRNG; the portable build passes precomputed threefry bits as operands and
runs under interpret mode for CPU CI).

A second structural win over the vmapped jnp engines: the rounds count is
the kernel's OWN fori_loop trip, so each sample pays exactly its drawn
rounds — no max-over-batch lane masking (ops/pipeline.py pays
max(rounds) across the vmap batch).

Primitive discipline follows pallas_kernels.py: rolls by traced scalars,
iota masks, cumulative scans, one-hot sums instead of vector gathers or
dynamic scalar VMEM access (r5: Fisher-Yates swaps ride a register-tile
window, the number parser reads a rolled digit window, byte probes are
one-hot reductions). PERM_LINES is new here: up to 64 whole-line
segments move via 64 static conditional rolls.

Determinism: reproducible for a fixed (seed, case, sample); bitstreams
diverge from the jnp engines (documented divergence class — raw-bits
modulo draws vs jax.random.randint, shared scalar slots vs tagged
subkeys). Distributions mirror erlamsa_rnd semantics (rand/erand/
rand_log/rand_delta shapes, the mask nom==1 quirk).

Enabled with ERLAMSA_PALLAS=2 (level 1 = per-round applies kernel).
Reference being re-expressed: the per-case mutation loop of
src/erlamsa_main.erl:180-221 over mux_fuzzers
(src/erlamsa_mutations.erl:1256-1280).

STATUS: interpret-mode tested end-to-end (CPU CI). Hardened for Mosaic
lowering without a chip to iterate against, per the pallas guide's
constraints: no 1D iota (2D-derived index vectors), no int64 anywhere
(the num path runs on int32-pair scalar math, _p_* helpers), no vector
gathers or dynamic table slices (one-hot sums), traced-shift rolls via
pltpu.roll, first-index reductions instead of 1D argmax, and — since r5
— no dynamic scalar VMEM reads/writes anywhere (Fisher-Yates went
vector-register one-hot, the number parser reads a rolled window, the
dash scan and applied-log store are row ops). Remaining hardware risk:
the [65, L] line-window reduction shape. Validation on a live chip
still pending — bin/tpu_evidence.py stage pallas2_small banks the
compile/run outcome the first healthy relay window.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pallas TPU backend is optional off-TPU
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from ..constants import ABSMAX_BINARY_BLOCK, MAX_BURST_MUTATIONS, MAX_SCORE, MIN_SCORE
from . import payloads, prng
from .fuse_mutators import MATCH_DEPTH
from .payload_mutators import _AAA_COUNTS
from .fused import (
    K_MASK,
    K_NONE,
    K_PERM_BYTES,
    K_PERM_LINES,
    K_SPLICE,
    K_SWAP,
    PERM_WINDOW,
    SCRATCH,
    SRC_LIT,
    SRC_SPAN,
)
from .num_mutators import (
    _INTERESTING_NP,
    _MAX_PARSE_DIGITS,
    _SCRATCH,
    INT64_MAX,
)
from .pallas_kernels import _roll
from .registry import DEVICE_CODES, DEVICE_MUTATORS, NUM_DEVICE_MUTATORS
from .registry import (
    P_HAS_DIGIT,
    P_N4,
    P_NEVER,
    P_NONEMPTY,
    P_PAIR,
    P_SIZERQ,
    P_TEXT,
    P_TEXT_2L,
    P_TEXT_3L,
    P_WIDENABLE,
)
from .payload_mutators import payload_tables
from .utf8_mutators import funny_tables

R_MAX = MAX_BURST_MUTATIONS
M = NUM_DEVICE_MUTATORS
_PERM_LINES_W = 64  # line-permute window (== fused.PERM_LINES)

_IDX = {c: k for k, c in enumerate(DEVICE_CODES)}

# the kernel's setp() calls mirror fused._PARAM_GENS mutator-for-mutator;
# guard the shared index space against registry/fused drift
from .fused import _PARAM_GENS as _FUSED_PGS  # noqa: E402

assert tuple(_FUSED_PGS) == DEVICE_CODES, (
    "pallas_rounds param generators are ordered by DEVICE_CODES; "
    "fused._PARAM_GENS drifted"
)

# scalar-draw slots in the per-round [64] uint32 row. Slots 0..M-1 are the
# weighted-pick draws; the rest are PER-PURPOSE and SHARED between param
# generators (only the applied generator's params are ever used, so
# overlap is harmless and keeps the row small).
_SB_POS = M  # primary position / which-run / which-line
_SB_VAL = M + 1  # value / donor row / repeat magnitude
_SB_LEN = M + 2  # span length / count
_SB_AUX = M + 3  # secondary line (donor for lis/lrs) / fo skip-ahead
_SB_DELTA = M + 4  # rand_delta sign bit
_SB_MASKOP = M + 5
_SB_PROB = M + 6
_SB_LOG2 = M + 7  # rand_log second draw
_SB_NUM = M + 8  # ..+17: the textual-number mutator's draws
# r5 structured-mutator slots (slots within one generator are distinct;
# cross-generator sharing is harmless — only the applied row is used)
_SB_PAYV = M + 18  # ab/ad variant draw
_SB_PAYROW = M + 19  # payload-table row draw
_SB_PAYREP = M + 20  # ab repeat count / aaas length-class draw
_SB_PAYAUX = M + 21  # aaas fallback / traversal reps / ad shell row
_SB_LENT = M + 22  # len variant t
_SB_LENV = M + 23  # len random new-length bits
_SB_LENPICK = M + 24  # len candidate pick
_SB_LENR = M + 25  # len expand reps (rand_log b1; b2 = _SB_LOG2)
_SB_LENF0 = M + 26  # len expand fill bytes 0-3
_SB_LENF1 = M + 27  # len expand fill bytes 4-7
_SB_FUSEP = M + 28  # fuse jump-out p
_SB_FUSEDEP = M + 29  # fuse depth (rand_log b1; b2 = _SB_LOG2)
_SB_FUSEPICK = M + 30  # fuse jump-in pick
_SB_FUSEFB = M + 31  # fuse fallback q
_SB_FUSELEN = M + 32  # fn/fo spliced span length
_SB_ROW_LEN = 64
assert _SB_FUSELEN < _SB_ROW_LEN, "scalar-draw row overflow"

# vector-bit rows in the per-round [6, L] uint32 block
_VB_MASK0, _VB_MASK1, _VB_MASK2, _VB_FY, _VB_WIDE, _VB_LPERM = range(6)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _arange1d(n: int):
    """1D index vector derived from a 2D iota (Mosaic rejects 1D iota —
    pallas_guide 'Common Pitfalls #4'; 1D *vectors* are fine)."""
    return jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)[0]


def _first_idx(mask2d, i2d, none_val):
    """First index where mask is True (2D reduction form of
    jnp.argmax(mask.reshape(-1)) with an explicit empty-mask value)."""
    L = i2d.shape[-1]
    hit = jnp.min(jnp.where(mask2d, i2d, L)).astype(jnp.int32)
    return jnp.where(jnp.any(mask2d), hit, jnp.asarray(none_val, jnp.int32))


# --- raw-bit draw helpers (erlamsa_rnd distribution shapes) ---------------


def _krand(b, n):
    """rand: uniform-ish int32 in [0, N) from one uint32 (modulo draw);
    0 when N <= 0 (erlamsa_rnd:rand/1 shape)."""
    n = jnp.asarray(n, jnp.int32)
    safe = jnp.maximum(n, 1).astype(jnp.uint32)
    return jnp.where(n <= 0, 0, (b % safe).astype(jnp.int32))


def _kerand(b, n):
    """erand: [1, N]; 0 when N <= 0."""
    n = jnp.asarray(n, jnp.int32)
    return jnp.where(n <= 0, 0, _krand(b, n) + 1)


def _krand_range(b, lo, hi):
    """[lo, hi); lo when hi == lo; 0 when hi < lo."""
    lo = jnp.asarray(lo, jnp.int32)
    hi = jnp.asarray(hi, jnp.int32)
    v = _krand(b, hi - lo) + lo
    return jnp.where(hi > lo, v, jnp.where(hi == lo, lo, 0))


def _krand_log(b1, b2, n):
    """2^rand(n)-scale magnitude (int32 range; n <= 30). A 0-bit draw
    yields 0, matching prng.rand_log / erlamsa_rnd:rand_log."""
    bits = _krand(b1, n)
    hi = jnp.left_shift(jnp.int32(1), jnp.maximum(bits - 1, 0))
    v = hi | _krand(b2, hi)
    v = jnp.where(bits == 0, 0, v)
    return jnp.where(jnp.asarray(n, jnp.int32) <= 0, 0, v)


def _kdelta(b):
    """+1/-1 from one bit (erlamsa_rnd:rand_delta shape)."""
    return jnp.where((b & jnp.uint32(1)) == 1, -1, 1).astype(jnp.int32)


# --- 64-bit scalar math on int32 pairs ------------------------------------
#
# Mosaic's scalar core is 32-bit: jnp.int64 inside a TPU kernel does not
# lower. The textual-number mutator needs true 64-bit semantics (the
# reference's interesting numbers reach 2^63), so the kernel carries
# values as (hi: int32 with the sign, lo: int32 reinterpreted unsigned).
# All helpers are scalar-only; the interpret-mode tests lock them against
# the int64 jnp engine draw-for-draw.


def _p_mk(hi, lo):
    return (jnp.asarray(hi, jnp.int32), jnp.asarray(lo, jnp.int32))


def _p_const(v: int):
    v &= (1 << 64) - 1
    hi = (v >> 32) & 0xFFFFFFFF
    lo = v & 0xFFFFFFFF
    # python ints -> wrapped int32 constants
    return (jnp.int32(hi - (1 << 32) if hi >= (1 << 31) else hi),
            jnp.int32(lo - (1 << 32) if lo >= (1 << 31) else lo))


def _p_u(x):
    return x.astype(jnp.uint32)


def _p_add(a, b):
    lo = _p_u(a[1]) + _p_u(b[1])
    carry = (lo < _p_u(a[1])).astype(jnp.int32)
    hi = a[0] + b[0] + carry
    return (hi.astype(jnp.int32), lo.astype(jnp.int32))


def _p_not(a):
    return ((~a[0]).astype(jnp.int32), (~a[1]).astype(jnp.int32))


def _p_neg(a):
    return _p_add(_p_not(a), _p_mk(0, 1))


def _p_sub(a, b):
    return _p_add(a, _p_neg(b))


def _p_is_neg(a):
    return a[0] < 0


def _p_lt(a, b):
    """Signed a < b."""
    return (a[0] < b[0]) | ((a[0] == b[0]) & (_p_u(a[1]) < _p_u(b[1])))


def _p_ult(a, b):
    """Unsigned a < b."""
    return (_p_u(a[0]) < _p_u(b[0])) | (
        (a[0] == b[0]) & (_p_u(a[1]) < _p_u(b[1]))
    )


def _p_eq0(a):
    return (a[0] == 0) & (a[1] == 0)


def _p_sel(c, a, b):
    return (jnp.where(c, a[0], b[0]).astype(jnp.int32),
            jnp.where(c, a[1], b[1]).astype(jnp.int32))


def _p_abs(a):
    return _p_sel(_p_is_neg(a), _p_neg(a), a)


def _p_shl1(a):
    hi = (_p_u(a[0]) << 1) | (_p_u(a[1]) >> 31)
    lo = _p_u(a[1]) << 1
    return (hi.astype(jnp.int32), lo.astype(jnp.int32))


def _p_shl(a, k):
    """Logical left shift by a TRACED k in [0, 63]."""
    ku = jnp.asarray(k, jnp.int32)
    big = ku >= 32
    ks = jnp.clip(jnp.where(big, ku - 32, ku), 0, 31).astype(jnp.uint32)
    lo_u, hi_u = _p_u(a[1]), _p_u(a[0])
    # k < 32 case (spill guarded against ks == 0: x >> 32 is UB-ish)
    spill = jnp.where(ks == 0, jnp.uint32(0), lo_u >> (32 - ks))
    hi_s = (hi_u << ks) | spill
    lo_s = lo_u << ks
    # k >= 32 case
    hi_b = lo_u << ks
    return (jnp.where(big, hi_b, hi_s).astype(jnp.int32),
            jnp.where(big, jnp.uint32(0), lo_s).astype(jnp.int32))


def _p_or(a, b):
    return ((a[0] | b[0]).astype(jnp.int32), (a[1] | b[1]).astype(jnp.int32))


def _p_mul10_add(a, digit):
    """a * 10 + digit for a >= 0 (the parse accumulator)."""
    x2 = _p_shl1(a)
    x8 = _p_shl1(_p_shl1(x2))
    return _p_add(_p_add(x8, x2), _p_mk(0, digit))


def _p_divmod10(a):
    """(a // 10, a % 10) for a >= 0, via base-2^16 long division."""
    hi_u, lo_u = _p_u(a[0]), _p_u(a[1])
    q_hi = hi_u // 10
    r1 = hi_u % 10
    d1 = (r1 << 16) | (lo_u >> 16)
    q1 = d1 // 10
    r2 = d1 % 10
    d2 = (r2 << 16) | (lo_u & 0xFFFF)
    q2 = d2 // 10
    rem = d2 % 10
    q_lo = (q1 << 16) | q2
    return (q_hi.astype(jnp.int32), q_lo.astype(jnp.int32)), rem.astype(
        jnp.int32
    )


def _p_umod(a, d):
    """Unsigned a % d (d >= 1) by 64-step shift-subtract long division
    (rolled fori_loop: ~15 scalar ops per step, tiny trace)."""

    def step(t, rem):
        bit = 63 - t
        word = jnp.where(bit >= 32, a[0], a[1])
        sh = jnp.clip(bit % 32, 0, 31).astype(jnp.uint32)
        b = (_p_u(word) >> sh) & jnp.uint32(1)
        rem = _p_or(_p_shl1(rem), _p_mk(0, b.astype(jnp.int32)))
        ge = ~_p_ult(rem, d)
        return _p_sel(ge, _p_sub(rem, d), rem)

    return jax.lax.fori_loop(0, 64, step, _p_mk(0, 0))


# --- in-kernel scans ------------------------------------------------------


def _binarish(sref, n):
    """erlamsa_utils:binarish on the first 8 bytes via scalar ref reads
    (num_mutators._device_binarish semantics)."""
    L = sref.shape[-1]
    b = [sref[0, min(k, L - 1)].astype(jnp.int32) for k in range(10)]
    first_bad = jnp.int32(8)
    first_bom = jnp.int32(8)
    for k in reversed(range(8)):
        v = k < jnp.minimum(n, 8)
        bad = ((b[k] == 0) | (b[k] >= 128)) & v
        bom = (
            ((b[k] == 0xEF) & (b[k + 1] == 0xBB) & (b[k + 2] == 0xBF))
            | ((b[k] == 0xFE) & (b[k + 1] == 0x0F))
        ) & v
        first_bad = jnp.where(bad, k, first_bad)
        first_bom = jnp.where(bom, k, first_bom)
    return (first_bad < 8) & (first_bad < first_bom)


# --- the per-round body ---------------------------------------------------


def _round(sref, log_ref, tables, r, n, scores, pri_vec, sb, vb):
    """One mutation event on the VMEM-resident sample.

    sref: uint8[1, L] working row (read AND written). log_ref: the
    int32[1, R] applied-log output ref. tables: (funny_table[179,4] u8,
    funny_lens[179] i32, interesting[33] i64) constant operands (pallas
    kernels cannot capture array constants). n: current length.
    scores/pri_vec: int32[M]. sb: uint32[64] scalar draws. vb: uint32[6, L]
    vector draws. Returns (n', scores').
    """
    L = sref.shape[-1]
    d = sref[...]
    i = jax.lax.broadcasted_iota(jnp.int32, (1, L), 1)
    valid = i < n
    di = d.astype(jnp.int32)

    # ---- tables (line segments, digit runs, widenable) ----
    is_nl = (di == 10) & valid
    prev_nl = jnp.roll(is_nl, 1, axis=1) & (i > 0)  # static shift: safe
    start_mask = valid & ((i == 0) | prev_nl)
    rank = jnp.cumsum(start_mask.astype(jnp.int32), axis=1) - 1
    nlines = jnp.sum(start_mask.astype(jnp.int32)).astype(jnp.int32)
    is_digit = (di >= 48) & (di <= 57) & valid
    prev_digit = jnp.roll(is_digit, 1, axis=1) & (i > 0)
    digit_starts = is_digit & ~prev_digit
    run_count = jnp.sum(digit_starts.astype(jnp.int32)).astype(jnp.int32)
    widenable = ((di & 0x3F) == di) & valid
    binarish = _binarish(sref, n)
    nonempty = n > 0
    text = nonempty & ~binarish

    def start_of(k):
        return _first_idx(start_mask & (rank == k), i, 0)

    def line_span(k):
        k = jnp.clip(k, 0, jnp.maximum(nlines - 1, 0))
        s = start_of(k)
        e = jnp.where(k == nlines - 1, n, start_of(k + 1))
        return s, jnp.maximum(e - s, 0)

    # ---- length-field candidates (len mutator + pred P_SIZERQ) ----
    # tail/near-tail static-mask subset of ops/sizer.detect_sizer: the jnp
    # engines add keyed interior probes; level-2 bitstreams diverge by
    # design (module docstring). Forward bytes via rolls (circular: a
    # candidate whose field straddles n is rejected by the i + w <= n
    # guard, so wrap reads cannot fabricate one within data).
    b1v, b2v, b3v = _roll(di, -1), _roll(di, -2), _roll(di, -3)
    sz_vals = (
        di,
        di * 256 + b1v,
        b1v * 256 + di,
        ((di * 256 + b1v) * 256 + b2v) * 256 + b3v,
        ((b3v * 256 + b2v) * 256 + b1v) * 256 + di,
    )
    sz_widths = (1, 2, 2, 4, 4)
    sz_cands = []
    for vv, ww in zip(sz_vals, sz_widths):
        dlt = n - (vv + i + ww)
        if ww == 1:
            near = (dlt >= 0) & (dlt <= 8)
        else:
            near = (dlt == 0) | (dlt == 1) | (dlt == 2) | (dlt == 4) | (dlt == 8)
        sz_cands.append((vv > 2) & near & (i + ww <= n) & valid)
    sizer_any = jnp.bool_(False)
    for ck in sz_cands:
        sizer_any = sizer_any | jnp.any(ck)

    # uniform pick among all candidates (flat cumsum order, one draw)
    sz_total = jnp.int32(0)
    for ck in sz_cands:
        sz_total = sz_total + jnp.sum(ck.astype(jnp.int32))
    r_sz = _krand(sb[_SB_LENPICK], sz_total)
    running = jnp.int32(0)
    len_found = jnp.bool_(False)
    len_a = jnp.int32(0)
    len_w = jnp.int32(1)
    len_kind = jnp.int32(0)
    len_val = jnp.int32(0)
    for kk, (ck, vv, ww) in enumerate(zip(sz_cands, sz_vals, sz_widths)):
        cum_k = jnp.cumsum(ck.astype(jnp.int32), axis=1) + running
        hit = ck & (cum_k == r_sz + 1)
        anyh = jnp.any(hit)
        len_a = jnp.where(anyh, _first_idx(hit, i, 0), len_a)
        len_w = jnp.where(anyh, ww, len_w)
        len_kind = jnp.where(anyh, kk, len_kind)
        len_val = jnp.where(anyh, jnp.sum(jnp.where(hit, vv, 0)), len_val)
        len_found = len_found | anyh
        running = running + jnp.sum(ck.astype(jnp.int32))
    len_end = jnp.minimum(len_val + len_a + len_w, n)

    # ---- fuse jump pair (ft fn fo): context match scan ----
    # (ops/fuse_mutators.fuse_scan in kernel form; scalar probe bytes via
    # one-hot sums, not dynamic VMEM reads)
    p_f = _krand(sb[_SB_FUSEP], n)
    k_f = jnp.minimum(
        1 + _krand_log(sb[_SB_FUSEDEP], sb[_SB_LOG2], 3), MATCH_DEPTH
    ).astype(jnp.int32)
    match_f = jnp.ones((1, L), bool)
    for dd in range(MATCH_DEPTH):
        fwd = _roll(di, -dd)
        probe_idx = jnp.clip(p_f + dd, 0, L - 1)
        b_probe = jnp.sum(jnp.where(i == probe_idx, di, 0)).astype(jnp.int32)
        match_f = match_f & ((dd >= k_f) | (fwd == b_probe))
    match_f = match_f & (i < n) & (i != p_f)
    tot_f = jnp.sum(match_f.astype(jnp.int32)).astype(jnp.int32)
    r_f = _krand(sb[_SB_FUSEPICK], tot_f)
    cum_f = jnp.cumsum(match_f.astype(jnp.int32), axis=1)
    q_hit = _first_idx(match_f & (cum_f == r_f + 1), i, 0)
    # fallback over [0, n) \ {p_f} (fuse_mutators.fuse_scan rule)
    q_fb = _krand(sb[_SB_FUSEFB], jnp.maximum(n - 1, 1))
    q_fb = q_fb + (q_fb >= p_f).astype(jnp.int32)
    q_f = jnp.where(tot_f > 0, q_hit, q_fb)

    # ---- applicability + weighted pick (scheduler.weighted_pick) ----
    preds = {
        P_NONEMPTY: nonempty,
        P_PAIR: n >= 2,
        P_HAS_DIGIT: (run_count > 0) & nonempty,
        P_TEXT: text,
        P_TEXT_2L: text & (nlines >= 2),
        P_TEXT_3L: text & (nlines >= 3),
        P_WIDENABLE: jnp.any(widenable) & nonempty,
        P_NEVER: jnp.bool_(False),
        P_SIZERQ: sizer_any,
        P_N4: n >= 4,
    }
    applicable = jnp.stack([preds[m.pred] for m in DEVICE_MUTATORS]) & (
        pri_vec > 0
    )
    bits_m = sb[:M].astype(jnp.uint32)
    bounds = jnp.maximum(scores * pri_vec, 1).astype(jnp.uint32)
    draws = (bits_m % bounds).astype(jnp.int32)
    midx = _arange1d(M)
    best = jnp.max(jnp.where(applicable, draws, -1))
    pick_m = applicable & (draws == best)
    # first True == min index (argmax-on-bool without a 1D argmax)
    applied = jnp.min(jnp.where(pick_m, midx, M)).astype(jnp.int32)
    any_app = jnp.any(applicable)
    applied = jnp.where(any_app, applied, 0)
    d_app = jnp.sum(jnp.where(midx == applied, draws, 0))
    # tried-and-failed = earlier in the descending stable order
    tried_before = ((draws > d_app) | ((draws == d_app) & (midx < applied))) \
        & any_app

    # ---- param generation (all M sets; one-hot select by `applied`) ----
    # every generator is scalar work over the shared tables; mirrors
    # fused._PARAM_GENS order exactly (asserted at import below)
    delta_c = _kdelta(sb[_SB_DELTA])

    def span_draw():
        s = _krand(sb[_SB_POS], n)
        ln = _krand(sb[_SB_LEN], n - s) + 1
        return s, ln

    pos_u = _krand(sb[_SB_POS], n)  # shared single-position draw
    # scalar byte probe via one-hot sum (no dynamic scalar VMEM read —
    # the docstring's named Mosaic risk)
    b_at = jnp.sum(jnp.where(i == jnp.clip(pos_u, 0, L - 1), di, 0)).astype(
        jnp.int32
    )
    s_sp, l_sp = span_draw()

    z = jnp.int32(0)
    P = {
        f: jnp.zeros(M, jnp.int32)
        for f in (
            "kind", "pos", "drop", "src", "src_start", "src_len", "reps",
            "lit_len", "a1", "l1", "l2", "ps", "pl", "mask_op", "mask_prob",
            "delta",
        )
    }

    def setp(code, **kw):
        k = _IDX[code]
        for f, v in kw.items():
            P[f] = P[f].at[k].set(jnp.asarray(v, jnp.int32))

    # byte ops (splices with span/literal sources)
    setp("bd", kind=K_SPLICE, pos=pos_u, drop=1, delta=delta_c)
    nb_flip = b_at ^ jnp.left_shift(1, _krand(sb[_SB_VAL], 8))
    nb_rand = _krand(sb[_SB_VAL], 256)
    for code in ("bei", "bed", "bf", "ber"):  # literal byte built below
        setp(code, kind=K_SPLICE, pos=pos_u, drop=1, src=SRC_LIT, lit_len=1,
             delta=delta_c)
    setp("bi", kind=K_SPLICE, pos=pos_u, drop=1, src=SRC_LIT, lit_len=2,
         delta=delta_c)
    setp("br", kind=K_SPLICE, pos=pos_u, drop=0, src=SRC_SPAN,
         src_start=pos_u, src_len=1, reps=1, delta=delta_c)

    # seq ops
    W = min(PERM_WINDOW, L)
    lmax_sp = jnp.minimum(n - pos_u, W)
    setp("sp", kind=K_PERM_BYTES, ps=pos_u,
         pl=_krand(sb[_SB_LEN], lmax_sp) + 1, delta=delta_c)
    reps_sr = jnp.maximum(2, _krand_log(sb[_SB_VAL], sb[_SB_LOG2], 10))
    setp("sr", kind=K_SPLICE, pos=s_sp, drop=l_sp, src=SRC_SPAN,
         src_start=s_sp, src_len=l_sp, reps=reps_sr, delta=delta_c)
    setp("sd", kind=K_SPLICE, pos=s_sp, drop=l_sp, delta=delta_c)
    setp("snand", kind=K_MASK, ps=s_sp, pl=l_sp,
         mask_op=_krand(sb[_SB_MASKOP], 3),
         mask_prob=_kerand(sb[_SB_PROB], 100), delta=delta_c)
    setp("srnd", kind=K_MASK, ps=s_sp, pl=l_sp, mask_op=3,
         mask_prob=_kerand(sb[_SB_PROB], 100), delta=delta_c)

    # utf8
    wide_keys = jnp.where(widenable, vb[_VB_WIDE : _VB_WIDE + 1], 0)
    # first position holding the max key == argmax (2D reduction form)
    mx_uw = jnp.max(wide_keys)
    pos_uw = _first_idx(wide_keys == mx_uw, i, 0)
    b_uw = jnp.sum(
        jnp.where(i == jnp.clip(pos_uw, 0, L - 1), di, 0)
    ).astype(jnp.uint8)
    setp("uw", kind=K_SPLICE, pos=pos_uw, drop=1, src=SRC_LIT, lit_len=2,
         delta=delta_c)
    funny_t, funny_l, itbl_hi, itbl_lo, pay_t, pay_l = tables
    n_funny = funny_t.shape[0]
    row_ui = _krand(sb[_SB_VAL], n_funny)
    # row select via one-hot sums over static columns (no dynamic sublane
    # slice, no vector gather): 4 scalar reductions over (n_funny, 1)
    rows_col = jax.lax.broadcasted_iota(jnp.int32, (n_funny, 1), 0)
    row_hit = rows_col == row_ui
    seq_ui = [
        jnp.sum(
            jnp.where(row_hit, funny_t[:, k : k + 1].astype(jnp.int32), 0)
        ).astype(jnp.uint8)
        for k in range(4)
    ]
    flen_iota = jax.lax.broadcasted_iota(jnp.int32, funny_l.shape, 1)
    len_ui = jnp.sum(
        jnp.where(flen_iota == row_ui, funny_l, 0)
    ).astype(jnp.int32)
    setp("ui", kind=K_SPLICE, pos=pos_u + 1, src=SRC_LIT, lit_len=len_ui,
         delta=delta_c)

    # num: parse -> mutate (int64 scalar math) -> render
    which = _krand(sb[_SB_POS], run_count)
    target = run_count - 1 - which
    csum = jnp.cumsum(digit_starts.astype(jnp.int32), axis=1)
    a_num = _first_idx(digit_starts & (csum == target + 1), i, 0)
    b_end = _first_idx((i >= a_num) & ~is_digit, i, n)

    # dash run immediately before a_num, vectorized (the historical
    # while_loop probed one scalar VMEM byte per step — the docstring's
    # named Mosaic risk). Roll the dash mask so original index a_num-1-c
    # lands at lane L-1-c; the run length is then the all-true suffix,
    # found via the last False lane. Lanes outside the valid window
    # (c >= a_num) read wrapped bytes but are forced False.
    dash_roll = _roll(((di == 45) & (i < a_num)).astype(jnp.int32),
                      L - a_num)
    last_false = jnp.max(jnp.where(dash_roll == 0, i, -1))
    dash_count = jnp.maximum(L - 1 - last_false, 0).astype(jnp.int32)
    neg_in = dash_count > 0
    a_ext = a_num - dash_count

    # digit window via one roll: parse reads become STATIC lane indices
    # (wrapped bytes beyond b_end are never taken)
    num_win = _roll(di, -a_num)  # num_win[0, k] = d[a_num + k]

    def parse_body(k, vp):
        take = (a_num + k < b_end) & (k < _MAX_PARSE_DIGITS)
        dig = jnp.sum(jnp.where(i == k, num_win, 0)).astype(jnp.int32) - 48
        nv = _p_mul10_add(vp, dig)
        return _p_sel(take, nv, vp)

    mag = jax.lax.fori_loop(
        0, _MAX_PARSE_DIGITS, parse_body, _p_mk(0, 0)
    )
    value = _p_sel(neg_in, _p_neg(mag), mag)
    new_value = _mutate_num_bits(sb, value, itbl_hi, itbl_lo)
    num_digits, len_num = _render_scalars(new_value)
    setp("num", kind=K_SPLICE, pos=a_ext, drop=b_end - a_ext, src=SRC_LIT,
         lit_len=len_num, delta=2)  # real num delta recomputed post-apply

    # line ops (spans via the scalar line-table queries)
    k_ld = _kerand(sb[_SB_POS], nlines) - 1
    s_ld, l_ld = line_span(k_ld)
    setp("ld", kind=K_SPLICE, pos=s_ld, drop=l_ld, delta=1)
    start_lds = _kerand(sb[_SB_POS], nlines)
    cnt_lds = _kerand(sb[_SB_LEN], nlines - start_lds + 1)
    s0_lds, _ = line_span(start_lds - 1)
    s2_lds, l2_lds = line_span(start_lds - 1 + cnt_lds - 1)
    setp("lds", kind=K_SPLICE, pos=s0_lds, drop=s2_lds + l2_lds - s0_lds,
         delta=1)
    setp("lr2", kind=K_SPLICE, pos=s_ld, drop=0, src=SRC_SPAN,
         src_start=s_ld, src_len=l_ld, reps=1, delta=1)
    frm_lri = _kerand(sb[_SB_POS], nlines) - 1
    to_lri = _kerand(sb[_SB_VAL], nlines) - 1
    fs_lri, fl_lri = line_span(frm_lri)
    ts_lri, tl_lri = line_span(to_lri)
    setp("lri", kind=K_SPLICE, pos=ts_lri, drop=tl_lri, src=SRC_SPAN,
         src_start=fs_lri, src_len=fl_lri, reps=1, delta=1)
    reps_lr = jnp.maximum(2, _krand_log(sb[_SB_VAL], sb[_SB_LOG2], 10))
    setp("lr", kind=K_SPLICE, pos=s_ld, drop=l_ld, src=SRC_SPAN,
         src_start=s_ld, src_len=l_ld, reps=reps_lr, delta=1)
    k_ls = _kerand(sb[_SB_POS], jnp.maximum(nlines - 1, 0)) - 1
    s1_ls, l1_ls = line_span(k_ls)
    _s2_ls, l2_ls = line_span(k_ls + 1)
    setp("ls", kind=K_SWAP, a1=s1_ls, l1=l1_ls, l2=l2_ls, delta=1)
    frm_lp = _kerand(sb[_SB_POS], jnp.maximum(nlines - 1, 0)) - 1
    a_lp = _krand_range(sb[_SB_LEN], 2, jnp.maximum(nlines - frm_lp - 1, 2))
    b_lp = _krand_log(sb[_SB_VAL], sb[_SB_LOG2], 10)
    cnt_lp = jnp.clip(
        jnp.maximum(2, jnp.minimum(a_lp, b_lp)), 0, _PERM_LINES_W
    )
    setp("lp", kind=K_PERM_LINES, ps=frm_lp, pl=cnt_lp, delta=1)
    don_lis = _kerand(sb[_SB_AUX], nlines) - 1
    to_lis = _kerand(sb[_SB_POS], nlines) - 1
    ds_lis, dl_lis = line_span(don_lis)
    ts_lis, tl_lis = line_span(to_lis)
    setp("lis", kind=K_SPLICE, pos=ts_lis, drop=0, src=SRC_SPAN,
         src_start=ds_lis, src_len=dl_lis, reps=1, delta=1)
    setp("lrs", kind=K_SPLICE, pos=ts_lis, drop=tl_lis, src=SRC_SPAN,
         src_start=ds_lis, src_len=dl_lis, reps=1, delta=1)

    # ---- r5 structured mutators (ab ad len ft fn fo) ----
    # payload-row length lookup helper (one-hot sum, no dynamic slice)
    n_pay = pay_l.shape[-1]
    pay_iota = jax.lax.broadcasted_iota(jnp.int32, pay_l.shape, 1)

    def pay_len_of(row):
        return jnp.sum(jnp.where(pay_iota == row, pay_l, 0)).astype(jnp.int32)

    # ab (payload_mutators.draw_ab shape)
    v_ab = _krand(sb[_SB_PAYV], 5)
    silly_row = payloads.SILLY0 + _krand(sb[_SB_PAYROW], payloads.N_SILLY)
    silly_reps = _krand(sb[_SB_PAYREP], 20) + 1
    t_aaa = _krand(sb[_SB_PAYREP], 11)
    aaa_tab = jnp.int32(0)
    for idx, cnt in enumerate(_AAA_COUNTS):
        aaa_tab = jnp.where(t_aaa == idx, cnt, aaa_tab)
    aaa_reps = jnp.where(t_aaa < 10, aaa_tab, _krand(sb[_SB_PAYAUX], 1024))
    trav_row = payloads.TRAV0 + _krand(sb[_SB_PAYROW], 2)
    trav_reps = _kerand(sb[_SB_PAYAUX], 10)
    row_ab = jnp.where(
        v_ab <= 1, silly_row,
        jnp.where(v_ab == 2, payloads.AAA_ROW,
                  jnp.where(v_ab == 3, trav_row, payloads.NULL_ROW)),
    ).astype(jnp.int32)
    reps_ab = jnp.where(
        v_ab <= 1, silly_reps,
        jnp.where(v_ab == 2, aaa_reps,
                  jnp.where(v_ab == 3, trav_reps, 1)),
    ).astype(jnp.int32)
    ll_ab = pay_len_of(row_ab)
    setp("ab", kind=K_SPLICE, pos=jnp.where(v_ab == 4, n, pos_u),
         drop=jnp.where(v_ab == 1, ll_ab * reps_ab, 0), src=SRC_LIT,
         lit_len=ll_ab, reps=reps_ab, delta=delta_c)

    # ad (payload_mutators.draw_ad shape)
    v_ad = _krand(sb[_SB_PAYV], 4)
    row_ad = jnp.where(
        v_ad < 3,
        payloads.DELIM0 + _krand(sb[_SB_PAYROW], payloads.N_DELIM),
        payloads.SHELL0 + _krand(sb[_SB_PAYAUX], payloads.N_SHELL),
    ).astype(jnp.int32)
    ll_ad = pay_len_of(row_ad)
    setp("ad", kind=K_SPLICE, pos=pos_u, drop=0, src=SRC_LIT,
         lit_len=ll_ad, reps=1, delta=delta_c)

    # len (lenfield.draw_len shape over the in-kernel candidate pick)
    t_len = _krand(sb[_SB_LENT], 7)
    new_len = jnp.minimum(
        ((sb[_SB_LENV] >> 2).astype(jnp.int32) * 2) & 0x7FFFFFFF,
        ABSMAX_BINARY_BLOCK,
    )
    len_expand = t_len == 2
    # field-byte image computed in the lit section below (needs len_w/kind)
    _LEN_FILL_W = 8  # expand fill: 8 bytes from 2 scalar slots, tiled
    setp("len",
         kind=jnp.where(len_found, K_SPLICE, K_NONE),
         pos=jnp.where(len_expand, len_end, len_a),
         drop=jnp.where(
             len_expand, 0, jnp.where(t_len == 3, len_end - len_a, len_w)
         ),
         src=SRC_LIT,
         lit_len=jnp.where(len_expand, _LEN_FILL_W, len_w),
         reps=jnp.where(
             len_expand,
             1 + _krand_log(sb[_SB_LENR], sb[_SB_LOG2], 8),
             1,
         ),
         delta=jnp.where(len_found, 1, -1))

    # ft fn fo (fuse_mutators draw shapes over the in-kernel jump pair)
    sl_fuse = jnp.maximum(n - q_f, 1)
    setp("ft", kind=K_SPLICE, pos=p_f, drop=n - p_f, src=SRC_SPAN,
         src_start=q_f, src_len=sl_fuse, reps=1, delta=delta_c)
    l_fuse = 1 + _krand(sb[_SB_FUSELEN], jnp.maximum(n - q_f, 1))
    setp("fn", kind=K_SPLICE, pos=p_f, drop=0, src=SRC_SPAN,
         src_start=q_f, src_len=l_fuse, reps=1, delta=delta_c)
    d_fo = _kerand(sb[_SB_AUX], jnp.maximum(n - p_f, 1))
    setp("fo", kind=K_SPLICE, pos=p_f, drop=d_fo, src=SRC_SPAN,
         src_start=q_f, src_len=l_fuse, reps=1, delta=delta_c)
    # "nil": all-zero row (K_NONE) already

    # select the applied row (+ gate to no-op when nothing applicable)
    def sel(f):
        return jnp.sum(jnp.where(midx == applied, P[f], 0)).astype(jnp.int32)

    kind = jnp.where(any_app, sel("kind"), K_NONE)
    pos, drop = sel("pos"), sel("drop")
    src, src_start, src_len = sel("src"), sel("src_start"), sel("src_len")
    reps, lit_len = sel("reps"), sel("lit_len")
    a1, l1, l2 = sel("a1"), sel("l1"), sel("l2")
    ps, plen = sel("ps"), sel("pl")
    mask_op, mask_prob = sel("mask_op"), sel("mask_prob")
    delta_sel = sel("delta")

    # literal bytes for the applied splice (byte ops / uw / ui / num /
    # payload rows / len field image) as a python list of SCRATCH (48)
    # traced SCALARS — no vector gather, no 1D scratch
    is_bi = applied == _IDX["bi"]
    byte0 = jnp.select(
        [applied == _IDX["bei"], applied == _IDX["bed"],
         applied == _IDX["bf"], applied == _IDX["ber"]],
        [(b_at + 1) % 256, (b_at - 1) % 256, nb_flip, nb_rand],
        nb_rand,  # bi's inserted byte is the same rand_byte draw
    ).astype(jnp.uint8)
    z8 = jnp.uint8(0)
    at_pos = b_at.astype(jnp.uint8)  # same probe as the byte ops
    is_num = applied == _IDX["num"]
    is_ui = applied == _IDX["ui"]
    is_uw = applied == _IDX["uw"]
    is_pay = (applied == _IDX["ab"]) | (applied == _IDX["ad"])
    is_len_m = applied == _IDX["len"]
    row_pay = jnp.where(applied == _IDX["ab"], row_ab, row_ad)
    pay_rows_col = jax.lax.broadcasted_iota(jnp.int32, (n_pay, 1), 0)
    pay_row_hit = pay_rows_col == row_pay
    is_le_len = (len_kind == 2) | (len_kind == 4)  # u16le / u32le
    lit = []
    for k in range(SCRATCH):
        byte_k = byte0 if k == 0 else (
            jnp.where(is_bi, at_pos, z8) if k == 1 else z8
        )
        uw_k = jnp.uint8(0xC0) if k == 0 else (
            (b_uw | jnp.uint8(0x80)) if k == 1 else z8
        )
        ui_k = seq_ui[k] if k < 4 else z8
        num_k = num_digits[k] if k < _SCRATCH else z8
        pay_k = jnp.sum(
            jnp.where(pay_row_hit, pay_t[:, k : k + 1].astype(jnp.int32), 0)
        ).astype(jnp.uint8)
        if k < 4:  # field image: zeros / saturate / new-length bytes
            shift = jnp.where(is_le_len, k * 8, (len_w - 1 - k) * 8)
            fb_k = jnp.where(
                t_len == 0, 0,
                jnp.where(
                    t_len == 1, 0xFF,
                    jnp.right_shift(new_len, jnp.clip(shift, 0, 31)) & 0xFF,
                ),
            ).astype(jnp.uint8)
        else:
            fb_k = z8
        if k < 8:  # expand fill: 8 random bytes from 2 scalar slots
            src_slot = sb[_SB_LENF0] if k < 4 else sb[_SB_LENF1]
            fill_k = ((src_slot >> ((k % 4) * 8)) & 0xFF).astype(jnp.uint8)
        else:
            fill_k = z8
        len_k = jnp.where(len_expand, fill_k, fb_k)
        lit.append(jnp.where(
            is_num, num_k,
            jnp.where(
                is_ui, ui_k,
                jnp.where(
                    is_uw, uw_k,
                    jnp.where(
                        is_pay, pay_k, jnp.where(is_len_m, len_k, byte_k)
                    ),
                ),
            ),
        ).astype(jnp.uint8))

    # ---- applies (pallas_kernels._round_logic discipline) ----
    pos_c = jnp.clip(pos, 0, n)
    drop_c = jnp.clip(drop, 0, n - pos_c)
    rlen = jnp.where(
        src == SRC_SPAN, src_len * reps,
        jnp.where(src == SRC_LIT, lit_len * jnp.maximum(reps, 1), 0),
    )
    rlen = jnp.clip(rlen, 0, L)
    sl_c = jnp.maximum(src_len, 1)
    o = i - pos_c
    cur = _roll(d, pos_c - src_start)
    odiv = jnp.where(o >= 0, o // sl_c, 0)
    for k in range(max(1, (L - 1).bit_length())):
        bitk = (odiv >> k) & 1
        cur = jnp.where(bitk == 1, _roll(cur, sl_c << k), cur)
    # repeated literal: offset modulo lit_len (reps==0 -> 1, pre-r5 rule)
    ll_c = jnp.maximum(lit_len, 1)
    omod = jnp.where(o >= 0, o % ll_c, -1)
    lit_at = jnp.zeros((1, L), jnp.uint8)
    for k in range(SCRATCH):
        lit_at = jnp.where(omod == k, lit[k], lit_at)
    repl = jnp.where(src == SRC_LIT, lit_at, cur)
    tail = _roll(d, rlen - drop_c)
    n_sp = jnp.clip(n - drop_c + rlen, 0, L)
    sp = jnp.where(i < pos_c, d, jnp.where(i < pos_c + rlen, repl, tail))
    sp = jnp.where(i < n_sp, sp, jnp.uint8(0))

    sw = jnp.where(
        (i >= a1) & (i < a1 + l2),
        _roll(d, -l1),
        jnp.where(
            (i >= a1 + l2) & (i < a1 + l2 + l1), _roll(d, l2), d
        ),
    )

    occ_n = (vb[_VB_MASK0 : _VB_MASK0 + 1] % 100).astype(jnp.int32)
    occurs = jnp.where(mask_prob == 1, occ_n != 0, occ_n < mask_prob)
    mbit = (vb[_VB_MASK1 : _VB_MASK1 + 1] % 8).astype(jnp.uint8)
    mrnd = (vb[_VB_MASK2 : _VB_MASK2 + 1] & 0xFF).astype(jnp.uint8)
    one = jnp.left_shift(jnp.uint8(1), mbit)
    masked = jnp.where(
        mask_op == 0, d & ~one,
        jnp.where(mask_op == 1, d | one,
                  jnp.where(mask_op == 2, d ^ one, mrnd)),
    )
    mk = jnp.where((i >= ps) & (i < ps + plen) & occurs, masked, d)

    lp_out = _perm_lines(d, i, n, start_mask, rank, nlines, ps, plen, vb,
                         line_span)

    out = jnp.where(
        kind == K_SPLICE, sp,
        jnp.where(kind == K_SWAP, sw,
                  jnp.where(kind == K_MASK, mk,
                            jnp.where(kind == K_PERM_LINES, lp_out, d))),
    )
    n1 = jnp.where(kind == K_SPLICE, n_sp, n)
    sref[...] = out

    # PERM_BYTES: Fisher-Yates over [ps, ps+span) in VECTOR form — the
    # window rides a [Wp] register tile and swaps are one-hot selects, so
    # the historical dynamic scalar VMEM reads/writes (the docstring's
    # named Mosaic risk) are gone. Same vb draws, same swap sequence,
    # same values: interpret-mode streams are unchanged. Gated by pl.when
    # and bounded by the traced span, so non-sp rounds (30 of 31
    # mutators) pay nothing. The sp setp guarantees ps + span <= n, so
    # the circular rolls never wrap inside the permuted region.
    @pl.when(kind == K_PERM_BYTES)
    def _fy():
        Wp = min(PERM_WINDOW, L)
        wi = _arange1d(Wp)
        span = jnp.clip(plen, 0, Wp)
        win0 = _roll(d, -ps)[0, :Wp]  # win0[k] = d[ps + k]
        vrow = vb[_VB_FY][:Wp].astype(jnp.uint32)

        def _fy_body(t, win):
            j = span - 1 - t
            rr = (
                jnp.sum(jnp.where(wi == j, vrow, 0)).astype(jnp.uint32)
                % jnp.maximum(j + 1, 1).astype(jnp.uint32)
            ).astype(jnp.int32)
            vj = jnp.sum(jnp.where(wi == j, win, 0)).astype(jnp.uint8)
            vr = jnp.sum(jnp.where(wi == rr, win, 0)).astype(jnp.uint8)
            swapped = jnp.where(wi == j, vr, jnp.where(wi == rr, vj, win))
            return jnp.where(j > 0, swapped, win)

        win_f = jax.lax.fori_loop(
            0, jnp.maximum(span - 1, 0), _fy_body, win0
        )
        win_l = jnp.concatenate([win_f, jnp.zeros(L - Wp, jnp.uint8)]) \
            if L > Wp else win_f
        fy_back = _roll(win_l.reshape(1, L), ps)
        sref[...] = jnp.where((i >= ps) & (i < ps + span), fy_back, d)

    # ---- score update (scheduler.adjust_scores) ----
    bin2 = _binarish(sref, n1)
    delta_f = jnp.where(
        applied == _IDX["num"], jnp.where(bin2, -1, 2), delta_sel
    )
    deltas = jnp.where(tried_before, -1, 0) + jnp.where(
        (midx == applied) & any_app, delta_f, 0
    )
    scores1 = jnp.clip(
        scores + deltas, int(MIN_SCORE), int(MAX_SCORE)
    ).astype(jnp.int32)

    # row-select store (not a dynamic scalar VMEM write): R_MAX is 16
    r_iota = jax.lax.broadcasted_iota(jnp.int32, (1, log_ref.shape[-1]), 1)
    log_ref[...] = jnp.where(
        r_iota == r, jnp.where(any_app, applied, -1), log_ref[...]
    )
    return n1, scores1


def _perm_lines(d, i, n, start_mask, rank, nlines, f, cnt, vb, line_span):
    """Permute up to 64 whole lines via static conditional rolls (no
    vector gather): output line w's bytes are source line order[w] rolled
    to the destination offset."""
    L = d.shape[-1]
    Wl = _PERM_LINES_W
    f = jnp.clip(f, 0, jnp.maximum(nlines - 1, 0))
    cnt = jnp.clip(cnt, 0, jnp.clip(nlines - f, 0, Wl))
    w = _arange1d(Wl)
    w1 = _arange1d(Wl + 1)
    # window line starts: [Wl+1, L] rank-match reduction (the +1 row gives
    # the start of the line just past the window, for the last line's len)
    wmask = start_mask[0][None, :] & (
        rank[0][None, :] == (f + w1)[:, None]
    )  # [Wl+1, L]
    ii = i[0]  # 1D view of the 2D lane iota
    starts_ext = jnp.max(
        jnp.where(wmask, ii[None, :], 0), axis=1
    ).astype(jnp.int32)
    starts_w = starts_ext[:Wl]
    has_w = jnp.any(wmask, axis=1)[:Wl]
    nxt = starts_ext[1:]
    is_last_global = (f + w) == nlines - 1
    lens_w = jnp.where(
        w < cnt,
        jnp.where(is_last_global, n - starts_w, nxt - starts_w),
        0,
    )
    lens_w = jnp.where(has_w, jnp.maximum(lens_w, 0), 0)

    # uniform permutation of the first cnt window lines: iterative
    # first-max pick over uint32 keys with an explicit used mask (the
    # int64 -1-sentinel form does not lower on 32-bit Mosaic)
    lrow = vb[_VB_LPERM]
    if L < Wl:  # tiny capacities: pad the key row statically
        lrow = jnp.concatenate([lrow, jnp.zeros(Wl - L, lrow.dtype)])
    keys = lrow[:Wl].astype(jnp.uint32)
    active = w < cnt
    order = w
    for j in range(Wl):
        mx = jnp.max(jnp.where(active, keys, jnp.uint32(0)))
        hit = active & (keys == mx)
        # first active max == the int64 argmax-with-sentinel pick; when
        # nothing is active the pick is unused (oj keeps j)
        pick = jnp.min(jnp.where(hit, w, Wl)).astype(jnp.int32)
        pick = jnp.where(jnp.any(hit), pick, 0)
        oj = jnp.where(j < cnt, pick, j)
        order = jnp.where(w == j, oj, order)
        active = active & (w != pick)

    onehot = order[:, None] == w[None, :]  # [Wl, Wl]
    plens = jnp.sum(jnp.where(onehot, lens_w[None, :], 0), axis=1)
    pstarts = jnp.sum(jnp.where(onehot, starts_w[None, :], 0), axis=1)
    cum = jnp.cumsum(plens)
    prev_cum = cum - plens
    win_start, _ = line_span(f)
    total = jnp.sum(jnp.where(w == cnt - 1, cum, 0))

    out = d
    rel = i - win_start
    for j in range(Wl):  # static rolls, one per window line
        dst0 = win_start + prev_cum[j]
        src0 = pstarts[j]
        rolled = _roll(d, dst0 - src0)
        in_seg = (i >= dst0) & (i < dst0 + plens[j]) & (j < cnt)
        out = jnp.where(in_seg, rolled, out)
    in_win = (rel >= 0) & (rel < total) & (cnt > 0)
    return jnp.where(in_win, out, d)


# --- int64 number mutate/render on raw bits -------------------------------


def _tbl_at64(hi_row, lo_row, idx):
    """Pair-valued table lookup from split int32 hi/lo rows [1, T] via
    one-hot sums (no dynamic slice, no int64 anywhere)."""
    t_iota = jax.lax.broadcasted_iota(jnp.int32, hi_row.shape, 1)
    m = t_iota == idx
    hi = jnp.sum(jnp.where(m, hi_row, 0)).astype(jnp.int32)
    lo = jnp.sum(jnp.where(m, lo_row, 0)).astype(jnp.int32)
    return (hi, lo)


def _mutate_num_bits(sb, v, itbl_hi, itbl_lo):
    """num_mutators._mutate_num on kernel bits (12 strategies,
    erlamsa_mutations.erl:95-112), in int32-pair math. v: (hi, lo) pair.
    itbl_hi/lo: the interesting-numbers table split into int32 halves."""
    t = _krand(sb[_SB_NUM], 12)
    i1 = _krand(sb[_SB_NUM + 1], itbl_hi.shape[-1])
    i2 = _krand(sb[_SB_NUM + 2], itbl_hi.shape[-1])
    interesting = _tbl_at64(itbl_hi, itbl_lo, i1)
    interesting2 = _tbl_at64(itbl_hi, itbl_lo, i2)
    one = _p_mk(0, 1)
    zero = _p_mk(0, 0)
    half_max = _p_const(INT64_MAX // 2)

    absv = _p_abs(v)
    absv_cap = _p_sel(_p_lt(half_max, absv), half_max, absv)
    absv2 = _p_shl1(absv_cap)
    u = _p_mk(sb[_SB_NUM + 3].astype(jnp.int32),
              sb[_SB_NUM + 4].astype(jnp.int32))
    rnd_abs = _p_umod(u, _p_sel(_p_eq0(absv2), one, absv2))
    v_neg = _p_is_neg(v)
    # v - rnd_abs * sign(v): toward zero for positive v, away for negative
    strat9 = _p_sel(v_neg, _p_add(v, rnd_abs), _p_sub(v, rnd_abs))

    n129 = _krand(sb[_SB_NUM + 5], 128) + 1  # rand_range(1, 129)
    bits = jnp.minimum(_krand(sb[_SB_NUM + 6], n129), 62)
    hi_p = _p_shl(one, jnp.maximum(bits - 1, 0))
    u2 = _p_mk(sb[_SB_NUM + 7].astype(jnp.int32),
               sb[_SB_NUM + 8].astype(jnp.int32))
    lo_p = _p_umod(u2, hi_p)  # hi_p >= 1 always
    lg = _p_sel(bits <= 0, zero, _p_or(hi_p, lo_p))
    s3 = _krand(sb[_SB_NUM + 9], 3)
    catch_all = _p_sel(s3 == 0, _p_sub(v, lg), _p_add(v, lg))

    out = catch_all
    out = _p_sel(t == 10, _p_neg(v), out)
    out = _p_sel(t == 9, strat9, out)
    out = _p_sel(t == 8, _p_sub(v, interesting2), out)
    out = _p_sel(t == 7, _p_add(v, interesting2), out)
    out = _p_sel((t == 4) | (t == 5), interesting, out)
    out = _p_sel(t == 3, one, out)
    out = _p_sel(t == 2, zero, out)
    out = _p_sel(t == 1, _p_sub(v, one), out)
    out = _p_sel(t == 0, _p_add(v, one), out)
    return out


def _render_scalars(v):
    """num_mutators._render_decimal as pure scalar pair math: (hi, lo) ->
    _SCRATCH (24) literal-byte SCALARS + length. (The shared vector version uses 1D
    scatters, flip/argmax, a vector gather and int64 — none of which
    lower on Mosaic; digits here are a python list of traced scalars.)"""
    neg = _p_is_neg(v)
    neg_i = neg.astype(jnp.int32)
    neg_max = _p_neg(_p_const(INT64_MAX))
    floored = _p_sel(_p_lt(v, neg_max), neg_max, v)
    mag = _p_sel(neg, _p_neg(floored), v)

    rev = []  # digit chars, least-significant first ('0'-padded to 20)
    mag_k = mag
    for _ in range(20):
        mag_k, dig = _p_divmod10(mag_k)
        rev.append(dig.astype(jnp.uint8) + jnp.uint8(48))
    idx_max = jnp.int32(-1)  # last significant-digit index
    for k in range(20):
        idx_max = jnp.where(rev[k] != jnp.uint8(48), jnp.int32(k), idx_max)
    ndig = jnp.maximum(idx_max + 1, 1)
    ndig = jnp.where(_p_eq0(mag), 1, ndig)
    total = (ndig + neg_i).astype(jnp.int32)

    out = []
    for k in range(_SCRATCH):
        digit_idx = jnp.clip(ndig - 1 - (k - neg_i), 0, 19)
        dk = jnp.uint8(48)
        for t in range(20):
            dk = jnp.where(digit_idx == t, rev[t], dk)
        dk = jnp.where((k == 0) & neg, jnp.uint8(45), dk)
        out.append(jnp.where(k < total, dk, jnp.uint8(0)))
    return out, total


# --- kernels + wrapper ----------------------------------------------------


def _run(meta_ref, pri_ref, sc_ref, funny_ref, flens_ref, itblh_ref,
         itbll_ref, payt_ref, payl_ref, data_ref, out_ref, nout_ref,
         scout_ref, log_ref, sref, get_bits):
    tables = (funny_ref[...], flens_ref[...], itblh_ref[...], itbll_ref[...],
              payt_ref[...], payl_ref[...])
    sref[...] = data_ref[...]
    log_ref[...] = jnp.full((1, R_MAX), -1, jnp.int32)
    n0 = meta_ref[0, 0]
    rounds = jnp.clip(meta_ref[0, 1], 0, R_MAX)
    pri_vec = pri_ref[0]
    scores0 = sc_ref[0]

    def body(r, carry):
        n, scores = carry
        sb, vb = get_bits(r)
        return _round(sref, log_ref, tables, r, n, scores, pri_vec, sb, vb)

    # DYNAMIC trip count: this sample pays exactly its own rounds draw
    n_f, sc_f = jax.lax.fori_loop(0, rounds, body, (n0, scores0))
    out_ref[...] = sref[...]
    nout_ref[0, 0] = n_f
    scout_ref[...] = sc_f.reshape(1, M)


def _kernel_portable(meta_ref, pri_ref, sc_ref, funny_ref, flens_ref,
                     itblh_ref, itbll_ref, payt_ref, payl_ref, sbits_ref,
                     vbits_ref, data_ref, out_ref, nout_ref, scout_ref,
                     log_ref, sref):
    _run(meta_ref, pri_ref, sc_ref, funny_ref, flens_ref, itblh_ref,
         itbll_ref, payt_ref, payl_ref, data_ref, out_ref, nout_ref,
         scout_ref, log_ref, sref,
         get_bits=lambda r: (sbits_ref[r], vbits_ref[r]))


def _kernel_hw(seed_ref, meta_ref, pri_ref, sc_ref, funny_ref, flens_ref,
               itblh_ref, itbll_ref, payt_ref, payl_ref, data_ref, out_ref,
               nout_ref, scout_ref, log_ref, sref):  # pragma: no cover - TPU
    pltpu.prng_seed(seed_ref[0, 0], seed_ref[0, 1])
    L = data_ref.shape[-1]

    def get_bits(r):
        sb = pltpu.prng_random_bits((1, _SB_ROW_LEN)).astype(jnp.uint32)[0]
        vb = pltpu.prng_random_bits((6, L)).astype(jnp.uint32)
        return sb, vb

    _run(meta_ref, pri_ref, sc_ref, funny_ref, flens_ref, itblh_ref,
         itbll_ref, payt_ref, payl_ref, data_ref, out_ref, nout_ref,
         scout_ref, log_ref, sref, get_bits)


def case_rounds_single(key, data_row, n, scores, pri, rounds):
    """All mutation rounds for ONE sample in one pallas_call (vmapped by
    the pipeline; vmap prepends a grid dimension).

    Args: key (threefry key), data_row uint8[L], n int32, scores int32[M],
    pri int32[M], rounds int32. Returns (out[L], n', scores'[M],
    log[R_MAX]) — log holds applied registry indices, -1 for empty rounds.
    """
    L = data_row.shape[0]
    meta = jnp.stack(
        [jnp.asarray(n, jnp.int32), jnp.asarray(rounds, jnp.int32)]
    ).reshape(1, 2)
    pri2 = jnp.asarray(pri, jnp.int32).reshape(1, M)
    sc2 = jnp.asarray(scores, jnp.int32).reshape(1, M)
    data2 = data_row.reshape(1, L)
    funny_t, _funny_lens = funny_tables()
    funny_l = _funny_lens.astype(jnp.int32).reshape(1, -1)
    # interesting numbers as int32 halves: int64 VECTORS never enter the
    # kernel (32-bit Mosaic); scalars are reassembled in _tbl_at64
    _itbl64 = np.asarray(_INTERESTING_NP, np.int64)
    int_hi = jnp.asarray((_itbl64 >> 32).astype(np.int32)).reshape(1, -1)
    int_lo = jnp.asarray(
        (_itbl64 & 0xFFFFFFFF).astype(np.uint32).astype(np.int32)
    ).reshape(1, -1)
    pay_t, _pay_lens = payload_tables()
    pay_l = _pay_lens.astype(jnp.int32).reshape(1, -1)
    out_shape = (
        jax.ShapeDtypeStruct((1, L), jnp.uint8),
        jax.ShapeDtypeStruct((1, 1), jnp.int32),
        jax.ShapeDtypeStruct((1, M), jnp.int32),
        jax.ShapeDtypeStruct((1, R_MAX), jnp.int32),
    )
    if pltpu is None:  # pragma: no cover - jax always ships pallas.tpu
        raise RuntimeError("ERLAMSA_PALLAS=2 requires pallas.tpu")
    scratch = [pltpu.VMEM((1, L), jnp.uint8)]
    if not _interpret():  # pragma: no cover - needs a chip
        # full 64 key bits -> 2 seed words (a single int32 seed would
        # cap the per-sample stream space at 2^31 and invite collisions)
        seed = jax.lax.bitcast_convert_type(
            jax.random.key_data(key), jnp.int32
        ).reshape(1, 2)
        out, nout, sc, log = pl.pallas_call(
            _kernel_hw, out_shape=out_shape, scratch_shapes=scratch
        )(seed, meta, pri2, sc2, funny_t, funny_l, int_hi, int_lo,
          pay_t, pay_l, data2)
    else:
        sbits = jax.random.bits(
            prng.sub(key, prng.TAG_SITE), (R_MAX, _SB_ROW_LEN), jnp.uint32
        )
        vbits = jax.random.bits(
            prng.sub(key, prng.TAG_PERM), (R_MAX, 6, L), jnp.uint32
        )
        out, nout, sc, log = pl.pallas_call(
            _kernel_portable, out_shape=out_shape, scratch_shapes=scratch,
            interpret=True,
        )(meta, pri2, sc2, funny_t, funny_l, int_hi, int_lo, pay_t, pay_l,
          sbits, vbits, data2)
    return out[0], nout[0, 0], sc[0], log[0]
