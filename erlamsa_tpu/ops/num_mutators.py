"""Textual-number mutator kernel (num).

Reference: sed_num walks the bytes, collects ASCII integer runs (optionally
'-'-signed), mutates one uniformly-chosen run with 12 strategies including
"interesting numbers" 2^k±1, and splices the decimal rendering back
(src/erlamsa_mutations.erl:63-169).

TPU re-expression: digit-run detection is a couple of shifted compares plus
a cumulative sum (one VPU pass), run selection is a masked argmax, value
parse/render are short fori_loops over at most 18/20 digit slots, and the
splice is the shared masked gather. No scanning loop over the buffer.

Documented divergences from the oracle (erlamsa_tpu/oracle): values are
int64-clamped (reference: bignum), runs longer than 18 digits parse their
first 18 digits, and a lone '-' chain collapses to one sign.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from . import prng
from .byte_mutators import _guard_empty, _positions
from .utf8_mutators import splice

_MAX_PARSE_DIGITS = 18
_SCRATCH = 24  # renders up to 20 chars (sign + 19 digits)

# Python ints / numpy here on purpose: module import must not touch the JAX
# backend (conversion happens at trace time inside the kernels).
INT64_MAX = 2**63 - 1


def _interesting_numbers() -> "np.ndarray":
    """2^k-1, 2^k, 2^k+1 for k in the reference list, int64-clamped
    (erlamsa_mutations.erl:67-75)."""
    vals = []
    for k in [1, 7, 8, 15, 16, 31, 32, 63, 64, 127, 128]:
        x = 1 << k
        for v in (x - 1, x, x + 1):
            vals.append(min(v, INT64_MAX))
    return np.asarray(vals, np.int64)


_INTERESTING_NP = _interesting_numbers()


@functools.lru_cache(maxsize=None)
def _interesting_dev():
    """The interesting-numbers table as a device constant, built once per
    process instead of per call/trace. Concrete even under an active
    trace — see utf8_mutators.funny_tables."""
    with jax.ensure_compile_time_eval():
        return jnp.asarray(_INTERESTING_NP)


def _rand_log_i64(key, n) -> jax.Array:
    """rand_log with the result clamped into int64 (reference draws up to
    2^127 bignums; we cap the bit width at 62)."""
    bits = prng.rand(prng.sub(key, 1), n)
    bits = jnp.minimum(bits, 62)
    hi = jnp.left_shift(jnp.int64(1), jnp.maximum(bits - 1, 0).astype(jnp.int64))
    lo_bits = jax.random.randint(
        prng.sub(key, 2), (), 0, jnp.maximum(hi, 1), dtype=jnp.int64
    )
    return jnp.where(bits <= 0, jnp.int64(0), hi | lo_bits)


def _mutate_num(key, v: jax.Array) -> jax.Array:
    """The 12 strategies of mutate_num (erlamsa_mutations.erl:95-112).
    Strategy ids 6 and 11 both take the +/- rand_log(rand_range(1,129))
    catch-all, as in the reference's clause order."""
    t = prng.rand(prng.sub(key, prng.TAG_VAL), 12)
    ki = prng.sub(key, prng.TAG_AUX)
    interesting_tbl = _interesting_dev()
    interesting = interesting_tbl[
        prng.rand(prng.sub(ki, 1), interesting_tbl.shape[0])
    ]
    interesting2 = interesting_tbl[
        prng.rand(prng.sub(ki, 2), interesting_tbl.shape[0])
    ]
    absv2 = jnp.minimum(jnp.abs(v), INT64_MAX // 2) * 2
    rnd_abs = jax.random.randint(
        prng.sub(ki, 3), (), 0, jnp.maximum(absv2, 1), dtype=jnp.int64
    )
    sign = jnp.where(v >= 0, jnp.int64(1), jnp.int64(-1))
    n129 = prng.rand_range(prng.sub(ki, 4), 1, 129)
    lg = _rand_log_i64(prng.sub(ki, 5), n129)
    s3 = prng.rand(prng.sub(ki, 6), 3)
    catch_all = jnp.where(s3 == 0, v - lg, v + lg)

    return jnp.select(
        [t == 0, t == 1, t == 2, t == 3, (t == 4) | (t == 5),
         t == 7, t == 8, t == 9, t == 10],
        [v + 1, v - 1, jnp.int64(0), jnp.int64(1), interesting,
         v + interesting2, v - interesting2, v - rnd_abs * sign, -v],
        catch_all,
    )


def _render_decimal(v: jax.Array):
    """int64 -> ASCII scratch row [SCRATCH] + length."""
    neg = v < 0
    mag = jnp.where(neg, -jnp.maximum(v, -INT64_MAX), v).astype(jnp.int64)

    def digit_body(k, carry):
        mag_k, digits = carry
        digits = digits.at[k].set((mag_k % 10).astype(jnp.uint8) + jnp.uint8(48))
        return mag_k // 10, digits

    mag_end, rev_digits = jax.lax.fori_loop(
        0, 20, digit_body, (mag, jnp.zeros(20, jnp.uint8))
    )
    ndig = jnp.maximum(
        20 - jnp.argmax(jnp.flip(rev_digits) != jnp.uint8(48)), 1
    ).astype(jnp.int32)
    ndig = jnp.where(mag == 0, 1, ndig)
    total = ndig + neg.astype(jnp.int32)

    i = jnp.arange(_SCRATCH, dtype=jnp.int32)
    # scratch[0] = '-' if neg; digits follow most-significant first
    digit_idx = jnp.clip(ndig - 1 - (i - neg.astype(jnp.int32)), 0, 19)
    out = jnp.where(
        (i == 0) & neg, jnp.uint8(45), rev_digits[digit_idx]
    )
    out = jnp.where(i < total, out, jnp.uint8(0))
    return out, total


def _device_binarish(data, n):
    """Device analogue of erlamsa_utils:binarish: NUL or high bit within the
    first 8 bytes means binary, unless a UTF BOM *starts at or before* the
    first bad byte — the reference retries its BOM clauses at every scan
    offset (erlamsa_utils.erl:241-247)."""
    b = data[:10].astype(jnp.int32)  # 8 scan offsets + 2 lookahead for BOM
    i = jnp.arange(8, dtype=jnp.int32)
    valid = i < jnp.minimum(n, 8)
    bad = ((b[:8] == 0) | (b[:8] >= 128)) & valid
    bom = (
        ((b[:8] == 0xEF) & (b[1:9] == 0xBB) & (b[2:10] == 0xBF))
        | ((b[:8] == 0xFE) & (b[1:9] == 0x0F))
    ) & valid
    first_bad = jnp.where(jnp.any(bad), jnp.argmax(bad), 8)
    first_bom = jnp.where(jnp.any(bom), jnp.argmax(bom), 8)
    return (first_bad < 8) & (first_bad < first_bom)


def sed_num(key, data, n):
    """num: mutate one textual number (erlamsa_mutations.erl:153-169)."""
    L = data.shape[0]
    i = _positions(L)
    valid = i < n
    is_digit = (data >= 48) & (data <= 57) & valid
    prev_digit = jnp.concatenate([jnp.zeros(1, bool), is_digit[:-1]])
    starts = is_digit & ~prev_digit
    run_count = jnp.sum(starts).astype(jnp.int32)

    which = prng.rand(prng.sub(key, prng.TAG_POS), run_count)
    # the reference's leftover-Which indexes numbers from the END
    target = run_count - 1 - which
    cs = jnp.cumsum(starts).astype(jnp.int32)
    a = jnp.argmax(starts & (cs == target + 1)).astype(jnp.int32)
    # end of run: first non-digit at or after a
    break_mask = (i >= a) & ~is_digit
    b_end = jnp.where(jnp.any(break_mask), jnp.argmax(break_mask), n).astype(
        jnp.int32
    )
    # count consecutive '-' immediately before a (reference get_num consumes
    # leading dashes as sign); i plays the role of distance-1 here
    is_dash_before = jnp.where(
        (i < a) & (a - 1 - i >= 0), data[jnp.clip(a - 1 - i, 0, L - 1)] == 45, False
    )
    # consecutive prefix of True in is_dash_before ordered by distance
    dash_count = jnp.argmin(
        jnp.concatenate([is_dash_before, jnp.zeros(1, bool)])
    ).astype(jnp.int32)
    neg = dash_count > 0
    a_ext = a - dash_count

    # parse value (first _MAX_PARSE_DIGITS digits)
    def parse_body(k, v):
        idx = jnp.clip(a + k, 0, L - 1)
        take = a + k < b_end
        d = (data[idx] - 48).astype(jnp.int64)
        return jnp.where(take & (k < _MAX_PARSE_DIGITS), v * 10 + d, v)

    mag = jax.lax.fori_loop(0, _MAX_PARSE_DIGITS, parse_body, jnp.int64(0))
    value = jnp.where(neg, -mag, mag)

    new_value = _mutate_num(key, value)
    repl, repl_len = _render_decimal(new_value)
    out, n_out = splice(data, n, a_ext, repl, repl_len, b_end - a_ext)

    mutated = run_count > 0
    out = jnp.where(mutated, out, data)
    n_out = jnp.where(mutated, n_out, n)

    # delta accounting (erlamsa_mutations.erl:158-169)
    r10 = prng.rand(prng.sub(key, prng.TAG_DELTA), 10)
    delta_nonum = jnp.where(r10 == 0, -1, 0)
    isbin = _device_binarish(out, n_out)
    delta_num = jnp.where(isbin, -1, 2)
    delta = jnp.where(mutated, delta_num, delta_nonum).astype(jnp.int32)
    return _guard_empty(data, n, out, n_out, delta)
