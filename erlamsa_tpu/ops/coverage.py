"""Device edge-coverage tensors: uint8 bitmap OR-fold and popcount.

AFL-style edge bitmaps arrive from the monitor plane as per-sample
uint8 maps (1 bit per edge).  Every feedback decision — "did this
sample light a genuinely-new edge?" — reduces to bitmap OR plus
popcount, natural uint8 element-wise kernels that live beside the
mutators.  The kernels are expressed in the DrJAX map_reduce shape
(PAPERS.md, arxiv 2403.07128): vmap the per-map popcount (the map
leg), OR-reduce along the sample axis (the reduce leg), so the fold
later rides the single-program fleet reduce unchanged.

The `*_np` twins are the numpy oracles and the byte-identity ground
truth: the device kernels must match them bit-for-bit (pinned in
tests/test_coverage.py), and degraded campaigns — device lost, or
coverage folded on a host-only path — run the oracles directly.

Gain semantics are SEQUENTIAL within a batch: map i's genuinely-new
edges are counted against the accumulated map OR'd with every earlier
map in the batch, so a slot that merely repeats the edges a lower slot
just lit scores zero.  That makes the per-slot adoption gate
order-stable and independent of how many maps share one batch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..constants import COVERAGE_MAP_BYTES

#: default edge-bitmap width in bytes (8 edges per byte); shared with
#: the jax-free monitor plane through constants.py
MAP_BYTES = COVERAGE_MAP_BYTES


# ---------------------------------------------------------------- numpy

def popcount_np(maps: np.ndarray) -> np.ndarray:
    """int32[...]: set-bit count over the trailing byte axis."""
    m = np.ascontiguousarray(maps, dtype=np.uint8)
    return np.unpackbits(m, axis=-1).sum(axis=-1, dtype=np.int32)


def fold_maps_np(acc: np.ndarray, maps: np.ndarray) -> np.ndarray:
    """uint8[B]: acc OR'd with every row of maps[N, B]."""
    out = np.asarray(acc, np.uint8).copy()
    for row in np.asarray(maps, np.uint8):
        out |= row
    return out


def batch_gains_np(acc: np.ndarray,
                   maps: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(gains int32[N], new_acc uint8[B]) — sequential new-edge counts.

    gains[i] = popcount(maps[i] & ~(acc | maps[0] | .. | maps[i-1])).
    """
    cur = np.asarray(acc, np.uint8).copy()
    gains = np.empty(len(maps), np.int32)
    for i, row in enumerate(np.asarray(maps, np.uint8)):
        gains[i] = popcount_np((row & ~cur)[None])[0]
        cur |= row
    return gains, cur


# --------------------------------------------------------------- device

def popcount(maps):
    """int32[...]: per-map popcount — SWAR bit-twiddling on uint8 lanes,
    no lookup table to stage per trace."""
    x = maps.astype(jnp.uint8)
    x = x - ((x >> 1) & jnp.uint8(0x55))
    x = (x & jnp.uint8(0x33)) + ((x >> 2) & jnp.uint8(0x33))
    x = (x + (x >> 4)) & jnp.uint8(0x0F)
    return jnp.sum(x.astype(jnp.int32), axis=-1)


@jax.jit
def fold_maps(acc, maps):
    """uint8[B]: acc OR every row of maps[N, B] (the reduce leg)."""
    folded = jax.lax.reduce(maps, np.uint8(0), jax.lax.bitwise_or, (0,))
    return acc | folded


@jax.jit
def batch_gains(acc, maps):
    """(gains int32[N], new_acc uint8[B]) — device twin of
    `batch_gains_np`: an inclusive OR-scan gives each map the union of
    its predecessors, the vmapped popcount scores what is left."""
    pref = jax.lax.associative_scan(jnp.bitwise_or, maps, axis=0)
    before = jnp.concatenate(
        [jnp.zeros_like(acc)[None, :], pref[:-1]], axis=0) | acc[None, :]
    gains = jax.vmap(popcount)(maps & ~before)
    return gains, pref[-1] | acc
