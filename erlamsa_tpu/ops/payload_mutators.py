"""Device ab/ad: ASCII payload injection as a table-row splice.

Reference: the ascii_bad / ascii_delimeter mutators
(src/erlamsa_mutations.erl:430-651) lex the input into string chunks and
splice badness payloads (format strings, traversal runs, 'a' floods,
NULs, delimiters, shell-inject wrappers) into a text chunk. The oracle
(oracle/textmutas.py) keeps that chunk-accurate path for host-routed and
parity work.

The DEVICE re-expression drops the lexer: for a sample the applicability
predicate already classifies as text (registry P_TEXT — the same
samples the hybrid used to route hostward for ab/ad), the payload lands
at a uniform byte position. The payload itself is one row of the packed
table in ops/payloads.py repeated ``reps`` times — exactly the splice
engine's literal-with-reps form, so ab/ad cost the same one gather as
every other splice mutator.

Documented deviations from the oracle (divergence class: device engines,
see ops/pipeline.py fuzz_sample NOTE): insert_badness repeats ONE silly
string rand(20)+1 times where the reference concatenates rand(20)+1
independent draws; traversal runs are period-3 ("/../../..") where the
reference appends a trailing separator; payloads land at byte (not
chunk-local) positions; ad's delimiter-drop arm (drop_delimeter) stays
host-side.

Draw layout (all scalar, shared verbatim by the fused param-gen and the
standalone switch kernel so both engines emit the same streams):
  ab: variant = rand(5) over {insert_badness, replace_badness,
      insert_aaas, insert_traversal, insert_null}
  ad: variant = rand(4): 3x delimiter insert, 1x shell-inject
      (erlamsa_mutations.erl:625-644's 3/4-1/4 split)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import payloads, prng

# interesting 'aaaa...' lengths (erlamsa_mutations.erl:486-501)
_AAA_COUNTS = (127, 128, 255, 256, 16383, 16384, 32767, 32768, 65535, 65536)


@functools.lru_cache(maxsize=None)
def payload_tables():
    """Device-resident (table, lens) for the packed payload table, built
    once per process instead of per call/trace (also used by the pallas
    rounds engine). Concrete even under an active trace — see
    utf8_mutators.funny_tables."""
    with jax.ensure_compile_time_eval():
        return jnp.asarray(payloads.TABLE), jnp.asarray(payloads.LENS)


@functools.lru_cache(maxsize=None)
def _aaa_counts():
    with jax.ensure_compile_time_eval():
        return jnp.asarray(_AAA_COUNTS, jnp.int32)


def draw_ab(key, n):
    """-> (pos, drop, row, lit_len, reps, delta): the ab edit program."""
    _tab, lens = payload_tables()
    kt = prng.sub(key, prng.TAG_TABLE)
    v = prng.rand(prng.sub(key, prng.TAG_MASK), 5)
    pos_ins = prng.rand(prng.sub(key, prng.TAG_POS), jnp.maximum(n, 1))

    silly_row = payloads.SILLY0 + prng.rand(prng.sub(kt, 1), payloads.N_SILLY)
    silly_reps = prng.rand(prng.sub(key, prng.TAG_LEN), 20) + 1

    t = prng.rand(prng.sub(kt, 2), 11)
    aaa_reps = jnp.where(
        t < 10,
        _aaa_counts()[jnp.clip(t, 0, 9)],
        prng.rand(prng.sub(kt, 3), 1024),
    )

    # row/aux subkeys shared across variants: exactly one variant is used
    trav_row = payloads.TRAV0 + prng.rand(prng.sub(kt, 1), 2)
    trav_reps = prng.erand(prng.sub(kt, 3), 10)

    row = jnp.select(
        [v <= 1, v == 2, v == 3],
        [silly_row, jnp.int32(payloads.AAA_ROW), trav_row],
        jnp.int32(payloads.NULL_ROW),
    ).astype(jnp.int32)
    reps = jnp.select(
        [v <= 1, v == 2, v == 3],
        [silly_reps, aaa_reps, trav_reps],
        jnp.int32(1),
    ).astype(jnp.int32)
    lit_len = lens[row]
    pos = jnp.where(v == 4, n, pos_ins).astype(jnp.int32)  # NUL appends
    # replace_badness overwrites in place; everything else inserts
    drop = jnp.where(v == 1, lit_len * reps, 0).astype(jnp.int32)
    return pos, drop, row, lit_len, reps, prng.rand_delta(key)


def draw_ad(key, n):
    """-> (pos, drop, row, lit_len, reps, delta): the ad edit program."""
    _tab, lens = payload_tables()
    kt = prng.sub(key, prng.TAG_TABLE)
    v = prng.rand(prng.sub(key, prng.TAG_MASK), 4)
    delim_row = payloads.DELIM0 + prng.rand(prng.sub(kt, 1), payloads.N_DELIM)
    shell_row = payloads.SHELL0 + prng.rand(prng.sub(kt, 2), payloads.N_SHELL)
    row = jnp.where(v < 3, delim_row, shell_row).astype(jnp.int32)
    pos = prng.rand(prng.sub(key, prng.TAG_POS), jnp.maximum(n, 1))
    return pos, jnp.int32(0), row, lens[row], jnp.int32(1), prng.rand_delta(key)


def lit_splice(data, n, pos, drop, lit, lit_len, reps):
    """out = data[:pos] ++ lit-repeated ++ data[pos+drop:] (the fused
    engine's SRC_LIT-with-reps splice, standalone for the switch engine).
    lit is a [W] row; the replacement is lit[:lit_len] tiled reps times."""
    L = data.shape[0]
    i = jnp.arange(L, dtype=jnp.int32)
    pos = jnp.clip(pos, 0, n)
    drop = jnp.clip(drop, 0, n - pos)
    rlen = jnp.clip(lit_len * jnp.maximum(reps, 1), 0, L)
    end_ins = pos + rlen
    lit_idx = jnp.clip(
        jnp.mod(i - pos, jnp.maximum(lit_len, 1)), 0, lit.shape[0] - 1
    )
    tail_src = jnp.clip(i - rlen + drop, 0, L - 1)
    out = jnp.where(
        i < pos,
        data,
        jnp.where(i < end_ins, lit[lit_idx], data[tail_src]),
    )
    n_out = jnp.clip(n - drop + rlen, 0, L)
    out = jnp.where(i < n_out, out, jnp.uint8(0))
    return out, n_out


def _payload_kernel(draw):
    def kernel(key, data, n):
        tab, _lens = payload_tables()
        pos, drop, row, lit_len, reps, delta = draw(key, n)
        out, n_out = lit_splice(data, n, pos, drop, tab[row], lit_len, reps)
        return out, n_out, delta

    return kernel


ascii_bad = _payload_kernel(draw_ab)
ascii_delim = _payload_kernel(draw_ad)
