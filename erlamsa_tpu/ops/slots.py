"""Slot-step kernels for the serving engines (services/serving.py).

Continuous batching applied to fuzzing (PAPERS.md, Gemma-on-TPU serving
comparison, arxiv 2605.25645): instead of flushing fixed batches, the
device holds a SLOT ARRAY — a paged arena (ops/paged.py) where slot ``s``
owns a fixed run of ``row_pages`` pages — and every device step mutates
all occupied slots at once. Free slots are masked by an int32 occupancy
vector, so the compiled shape never changes while requests join and
leave at step granularity.

PRNG contract (the determinism pin the serving tests enforce): a
request's byte stream is a pure function of ``(seed, request_id)``,
derived exactly like the flush batcher derives a sample's stream —

    key_r    = fold_in(case_key(base, 0), rid)
    scores_r = init_scores(fold_in(fold_in(base, 999), rid), 1)[0]

The case index is pinned at 0 and the sample index is the request id, so
the SAME request id yields the SAME bytes whether it rides a flush batch
(make_request_step), a slot step (make_slot_step), or a single-shot
oracle call — batch composition and slot placement cannot leak in.

STEP_CACHE is the compiled-step cache keyed by (capacity class, batch
geometry, engine, mutator-registry version): servers warm it at start so
a cold tenant or a post-reload first request never pays XLA compilation
on the request path, and a registry change can never reuse a stale
program.
"""

from __future__ import annotations

import threading

import jax
import jax.numpy as jnp
import numpy as np

from . import prng
from .paged import (PAGE, RESERVED_PAGES, TRASH_PAGE, gather_rows, new_arena,
                    upload_pages)
from .pipeline import (DEFAULT_SLICES, fuzz_batch, resolve_donate,
                       resolve_priorities)
from .registry import registry_version
from .scheduler import init_scores


def request_keys(base, rids):
    """Per-request PRNG keys: ``fold_in(case_key(base, 0), rid)`` —
    the flush batcher's derivation with the case counter pinned at 0 and
    the request id as the sample index."""
    ckey = prng.case_key(base, 0)
    return jax.vmap(lambda r: jax.random.fold_in(ckey, r))(rids)


def request_scores(base, rids):
    """Per-request scheduler rows. Each request re-derives its OWN
    init_scores row from (seed, rid): init_scores draws are a function of
    the batch shape, so slicing rows out of one batch-sized init would
    make a request's stream depend on who shared its batch — deriving
    per request keeps it batch-size independent (pinned by tests)."""
    k999 = jax.random.fold_in(base, 999)
    return jax.vmap(lambda r: init_scores(jax.random.fold_in(k999, r), 1)[0])(
        rids
    )


def _request_fuzz(base, rids, data, lens, pri, pat_pri, engine, flags,
                  slices, scan_len):
    keys = request_keys(base, rids)
    scores = request_scores(base, rids)
    out, n_out, _scores, _meta = fuzz_batch(
        keys, data, lens, scores, jnp.asarray(pri), jnp.asarray(pat_pri),
        engine=engine, slices=slices, scan_len=scan_len, **flags,
    )
    return out, n_out


def make_request_step(capacity: int, batch: int, mutator_pri=None,
                      pattern_pri=None, engine: str = "fused",
                      slices=DEFAULT_SLICES, scan_len: int | None = None,
                      donate=False):
    """Flush-mode step over a packed panel (the reworked TpuBatcher):

    step(base, rids, data, lens) -> (data', lens')

    rids: int32[batch] request ids (pad rows carry 0 — their outputs are
    never read). Scores are derived per request inside the program, so
    nothing chains between flushes and a device error costs no state."""
    pri, pat_pri, flags = resolve_priorities(mutator_pri, pattern_pri, engine)

    def step(base, rids, data, lens):
        if data.shape != (batch, capacity):
            raise ValueError(
                f"batch shape {data.shape} != ({batch}, {capacity})"
            )
        return _request_fuzz(base, rids, data, lens, pri, pat_pri,
                             engine, flags, slices, scan_len)

    donate_argnums = (2,) if resolve_donate(donate) else ()
    return jax.jit(step, donate_argnums=donate_argnums)


def make_slot_step(slots: int, row_pages: int, page: int = PAGE,
                   mutator_pri=None, pattern_pri=None,
                   engine: str = "fused", slices=DEFAULT_SLICES):
    """Continuous-mode step over a slot arena:

    step(arena, table, base, rids, lens, occ) -> (data[S, W], lens'[S])

    Gathers every slot's row out of the paged arena (ops/paged.py), runs
    the mutation kernel over ALL slots at the fixed working width
    ``W = row_pages * page``, and masks free slots back to their gathered
    bytes via the int32 occupancy vector ``occ`` — one compiled shape no
    matter which slots are live. The arena is NOT consumed (requests
    upload into it between steps)."""
    pri, pat_pri, flags = resolve_priorities(mutator_pri, pattern_pri, engine)
    width = row_pages * page

    def step(arena, table, base, rids, lens, occ):
        rows = gather_rows(arena, table)
        if rows.shape != (slots, width):
            raise ValueError(
                f"slot panel shape {rows.shape} != ({slots}, {width})"
            )
        out, n_out = _request_fuzz(base, rids, rows, lens, pri, pat_pri,
                                   engine, flags, slices, None)
        keep = occ > 0
        out = jnp.where(keep[:, None], out, rows)
        n_out = jnp.where(keep, n_out, lens)
        return out, n_out

    return jax.jit(step)


def slot_table(slots: int, row_pages: int) -> np.ndarray:
    """The constant int32[slots, row_pages] page table: slot ``s`` owns
    pages ``RESERVED_PAGES + s*row_pages .. + row_pages`` — a fixed
    mapping, so the table uploads once and never changes."""
    base = RESERVED_PAGES + np.arange(slots, dtype=np.int32)[:, None] * row_pages
    return base + np.arange(row_pages, dtype=np.int32)[None, :]


def arena_pages(slots: int, row_pages: int) -> int:
    return RESERVED_PAGES + slots * row_pages


def _next_pow2(n: int) -> int:
    return 1 if n <= 1 else 1 << (n - 1).bit_length()


def upload_slots(arena, table_np: np.ndarray, assignments, page: int = PAGE):
    """Scatter request payloads into their slots' page runs and return
    the updated arena. ``assignments`` is ``[(slot, payload bytes)]``;
    the index vector is pow2-padded with TRASH_PAGE (the corpus arena's
    admission idiom) so upload traffic compiles O(log) scatter shapes.
    Not donating: a previous step may still be reading the old arena
    version from the device queue (inflight > 1)."""
    row_pages = table_np.shape[1]
    kp = _next_pow2(len(assignments) * row_pages)
    idx = np.full(kp, TRASH_PAGE, np.int32)
    pages = np.zeros((kp, page), np.uint8)
    pos = 0
    for slot, payload in assignments:
        buf = np.frombuffer(payload, np.uint8)
        pages[pos:pos + row_pages].reshape(-1)[:buf.size] = buf
        idx[pos:pos + row_pages] = table_np[slot]
        pos += row_pages
    return upload_pages(arena, jnp.asarray(idx), jnp.asarray(pages),
                        donate=False)


class StepCache:
    """Compiled-step cache: one entry per (kind, capacity class, batch
    geometry, engine, registry version). Entries are warmed on build —
    the throwaway call right here pays the XLA compile so no request
    ever does — and shared across engine instances (the cache is a
    module-level singleton), so a second tenant's server or a reloaded
    engine at the same geometry hits the cache instead of recompiling.
    ``compiles`` counts cache misses; tests assert it stays flat across
    the request path post-warmup."""

    _GUARDED_BY = {"_lock": ("_steps", "compiles", "hits")}

    def __init__(self):
        self._lock = threading.Lock()
        self._steps: dict[tuple, object] = {}
        self.compiles = 0
        self.hits = 0

    def _get(self, key, build, warm):
        with self._lock:
            fn = self._steps.get(key)
            if fn is not None:
                self.hits += 1
                return fn
            fn = build()
            if warm:
                warm(fn)
            self._steps[key] = fn
            self.compiles += 1
            return fn

    def request_step(self, capacity: int, batch: int, engine: str = "fused",
                     slices=DEFAULT_SLICES, scan_len: int | None = None,
                     donate=False):
        # registry_version() also fingerprints the host/device routing
        # split (r13): a --struct-kernels flip between serving sessions
        # can never alias a compiled step built under the other split
        key = ("request", capacity, batch, engine, str(slices), scan_len,
               resolve_donate(donate), registry_version())

        def build():
            return make_request_step(capacity, batch, engine=engine,
                                     slices=slices, scan_len=scan_len,
                                     donate=donate)

        def warm(step):
            # host-side arrays, like a real flush's packed panel — see
            # the slot-step warm below for why the arg kinds must match
            base = prng.base_key(0)
            rids = np.zeros(batch, np.int32)
            data = np.zeros((batch, capacity), np.uint8)
            lens = np.zeros(batch, np.int32)
            jax.block_until_ready(step(base, rids, data, lens))

        return self._get(key, build, warm)

    def slot_step(self, slots: int, row_pages: int, page: int = PAGE,
                  engine: str = "fused", slices=DEFAULT_SLICES):
        key = ("slot", slots, row_pages, page, engine, str(slices),
               registry_version())

        def build():
            return make_slot_step(slots, row_pages, page=page, engine=engine,
                                  slices=slices)

        def warm(step):
            arena = new_arena(arena_pages(slots, row_pages), page)
            table = jnp.asarray(slot_table(slots, row_pages))
            base = prng.base_key(0)
            # warm every pow2 upload-chunk shape FIRST (admission
            # scatters must not compile on the request path either; all
            # entries target TRASH_PAGE, so live pages stay untouched),
            # THEN step on the uploaded arena with host-side int vectors
            # — the exact call sequence a request takes, so the jit fast
            # path's cache keys (committed-ness included) match and the
            # first real step is a perfect hit, not a near miss
            kp = 1
            while kp <= slots * row_pages:
                idx = jnp.full((kp,), TRASH_PAGE, jnp.int32)
                pages = jnp.zeros((kp, page), jnp.uint8)
                arena = upload_pages(arena, idx, pages, donate=False)
                kp *= 2
            zero = np.zeros(slots, np.int32)
            jax.block_until_ready(
                step(arena, table, base, zero, zero, zero)
            )

        return self._get(key, build, warm)

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._steps), "compiles": self.compiles,
                    "hits": self.hits}


#: process-wide cache instance — the point is sharing compiled programs
#: across servers/engines, so there is exactly one
STEP_CACHE = StepCache()
