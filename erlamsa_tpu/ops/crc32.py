"""Device crc32: trailer-checksum detection and recompute for the cs
pattern.

The reference brute-forces preamble offsets, recomputing crc32 of each
suffix with erlang:crc32 (src/erlamsa_field_predict.erl:129-161) — O(n*k)
sequential work. The TPU-native trick is GF(2) linearity: for a message
ending at byte e, the pure (init-free) CRC is the XOR of per-byte
contributions G[d, bit] that depend only on the byte's distance d from
the end — so

  crc32(data[a:e)) = Z[e-a]  ^  XOR_{j=a..e-1} G[e-1-j, bits(data[j])]

where Z[m] = crc32 of m zero bytes carries the init/final-xor affine
part. One reversed associative XOR-scan over the per-byte contributions
yields the crc of EVERY suffix at once; the tables are host-precomputed
per capacity (static at trace time) and addressed with a single roll by
the scalar e — no gathers.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..constants import PREAMBLE_MAX_BYTES
from . import prng

_POLY = 0xEDB88320  # reflected crc32 polynomial


@functools.lru_cache(maxsize=None)
def _byte_table() -> np.ndarray:
    """Standard reflected per-byte step table T[x] (linear in x)."""
    t = np.empty(256, np.uint32)
    for x in range(256):
        c = x
        for _ in range(8):
            c = (c >> 1) ^ (_POLY if c & 1 else 0)
        t[x] = c
    return t


@functools.lru_cache(maxsize=None)
def _tables(L: int) -> tuple[np.ndarray, np.ndarray]:
    """(G, Z) for buffers of capacity L.

    G: uint32[L, 8] — G[d, k] is the pure-linear crc contribution of bit k
    of a byte d positions before the message end. Z: uint32[L + 1] — Z[m]
    = crc32 of m zero bytes (the affine init/final part).
    """
    t = _byte_table()
    G = np.empty((L, 8), np.uint32)
    # d = 0: the byte is last; its pure contribution is T-step from zero
    # state, which for value v is t[v]; bits are linear so G[0, k] = t[1<<k]
    state = np.array([t[1 << k] for k in range(8)], np.uint32)
    for d in range(L):
        G[d] = state
        # append one zero byte: s' = (s >> 8) ^ T[s & 0xff] (linear in s)
        state = (state >> 8) ^ t[state & 0xFF]
    Z = np.empty(L + 1, np.uint32)
    z = 0xFFFFFFFF
    Z[0] = z ^ 0xFFFFFFFF
    for m in range(1, L + 1):
        z = (z >> 8) ^ t[z & 0xFF]
        Z[m] = z ^ 0xFFFFFFFF
    return G, Z


def _per_byte_contrib(data, e):
    """uint32[L]: pure-linear contribution of each byte toward the crc of
    a message ending at e (zeros at and beyond e)."""
    L = data.shape[0]
    i = jnp.arange(L, dtype=jnp.int32)
    G_np, _ = _tables(L)
    # Gr_static[i] = G[L-1-i]; rolling by (e - L) lands G[e-1-j] at row j
    Gr = jnp.roll(jnp.asarray(G_np[::-1]), e - L, axis=0)
    bits = (data[:, None] >> jnp.arange(8, dtype=jnp.uint8)[None, :]) & 1
    contrib = jnp.where((bits == 1) & (i < e)[:, None], Gr, jnp.uint32(0))
    out = jnp.zeros(L, jnp.uint32)
    for k in range(8):
        out = out ^ contrib[:, k]
    return out


def _z_at(L, m):
    """Z[m] for a traced scalar m (gather on the tiny static Z table)."""
    _, Z_np = _tables(L)
    return jnp.asarray(Z_np)[jnp.clip(m, 0, L)]


def crc32_of_range(data, a, b):
    """uint32 scalar: crc32(data[a:b)), matching zlib.crc32."""
    L = data.shape[0]
    i = jnp.arange(L, dtype=jnp.int32)
    c = _per_byte_contrib(data, b)
    c = jnp.where(i >= a, c, jnp.uint32(0))
    acc = jax.lax.associative_scan(jnp.bitwise_xor, c)[L - 1]
    return acc ^ _z_at(L, jnp.maximum(b - a, 0))


def crc32_suffixes(data, e):
    """uint32[L]: out[a] = crc32(data[a:e)) for every preamble a <= e —
    one reversed XOR-scan instead of the reference's per-offset rescans."""
    L = data.shape[0]
    i = jnp.arange(L, dtype=jnp.int32)
    c = _per_byte_contrib(data, e)
    sfx = jnp.flip(jax.lax.associative_scan(jnp.bitwise_xor, jnp.flip(c)))
    Zr = jnp.roll(jnp.asarray(_tables(L)[1][::-1]), e - L)[
        jnp.clip(i, 0, L - 1)
    ]
    # Zr[a] = Z[e - a] (Z reversed, rolled by the scalar e)
    return sfx ^ Zr


def crc32_candidates(data, n):
    """bool[L]: preambles a where the last 4 bytes (big-endian, matching
    the oracle's fieldpred) equal crc32(data[a:n-4))."""
    L = data.shape[0]
    i = jnp.arange(L, dtype=jnp.int32)
    e = jnp.maximum(n - 4, 0)
    stored = (
        data[jnp.clip(n - 4, 0, L - 1)].astype(jnp.uint32) << 24
        | data[jnp.clip(n - 3, 0, L - 1)].astype(jnp.uint32) << 16
        | data[jnp.clip(n - 2, 0, L - 1)].astype(jnp.uint32) << 8
        | data[jnp.clip(n - 1, 0, L - 1)].astype(jnp.uint32)
    )
    crcs = crc32_suffixes(jnp.where(i < n, data, jnp.uint8(0)), e)
    limit = jnp.minimum(2 * n // 3, 30 * PREAMBLE_MAX_BYTES)
    return (crcs == stored) & (i <= limit) & (n - i >= 4) & (n >= 4)


def detect_csum(key, data, n):
    """ONE uniform draw over the union of xor8 and crc32 trailer
    candidates — the same index order as the oracle's single rand_elem
    over get_possible_csum_locations (xor8 locations ascending, then
    crc32 locations ascending; models/fieldpred.py:134-155), closing the
    former pick-per-kind-then-kind divergence.

    Returns (found, a, is_crc).
    """
    from .sizer import xor8_candidates

    L = data.shape[0]
    cand = jnp.concatenate([xor8_candidates(data, n), crc32_candidates(data, n)])
    total = jnp.sum(cand).astype(jnp.int32)
    found = total > 0
    r = prng.rand(prng.sub(key, prng.TAG_MASK), total)
    cum = jnp.cumsum(cand).astype(jnp.int32)
    flat = jnp.argmax(cand & (cum == r + 1)).astype(jnp.int32)
    return found, flat % L, flat >= L


def write_crc32_be(data, pos, crc):
    """Write the 4 big-endian crc bytes at [pos, pos+4)."""
    L = data.shape[0]
    i = jnp.arange(L, dtype=jnp.int32)
    k = i - pos
    byte = (crc >> jnp.clip((3 - k) * 8, 0, 31)).astype(jnp.uint32) & 0xFF
    return jnp.where((k >= 0) & (k < 4), byte.astype(jnp.uint8), data)
