"""Device len: length-field mutation over the sizer detector.

Reference: the length-predict mutator (src/erlamsa_mutations.erl:1107-1143
via erlamsa_field_predict) finds a plausible length field and then draws
one of 7 edits: zero the field, saturate it, expand the enclosed blob
with random data, drop the blob (rewriting the field), or write a random
length. The oracle keeps the reference's randomized rescan
(models/fieldpred.py); the DEVICE path reuses ops/sizer.detect_sizer —
the vectorized one-pass field scan already built for the sz pattern —
and expresses every variant as ONE splice:

  t=0  field <- 0         splice [a, a+w) with zero literal
  t=1  field <- all-ones  splice [a, a+w) with 0xFF literal
  t=2  expand blob        insert random literal bytes at the blob end
  t=3  drop blob          splice [a, end) with the new-length field bytes
  t>3  field <- random    splice [a, a+w) with the new-length field bytes

Deviations (device divergence class): the random new length draws 31
uniform bits doubled (the reference draws size-of-field bits then
doubles, capped at ABSMAX_BINARY_BLOCK — same cap here); blob expansion
inserts an 8-byte random literal tiled 1 + rand_log(8) times (the
reference splices an uncapped random block; device capacity clips both).

The draw is shared verbatim by the fused param-gen and the standalone
switch kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..constants import ABSMAX_BINARY_BLOCK
from . import prng
from .sizer import KIND_U16LE, KIND_U32LE, detect_sizer

LIT_W = 8  # expand-fill literal: 8 random bytes, tiled via the reps field


def field_bytes(value, width, kind):
    """[4] uint8: the length field's byte image (endianness per kind)."""
    k = jnp.arange(4, dtype=jnp.int32)
    is_le = (kind == KIND_U16LE) | (kind == KIND_U32LE)
    shift = jnp.where(is_le, k * 8, (width - 1 - k) * 8)
    return (
        jnp.right_shift(value.astype(jnp.int32), jnp.clip(shift, 0, 31)) & 0xFF
    ).astype(jnp.uint8)


def draw_len(key, n, sizer):
    """-> (pos, drop, lit[LIT_W], lit_len, reps, delta). sizer is
    detect_sizer's (found, a, width, kind, end). Blob expansion tiles an
    8-byte random literal via reps (period-8 randomness — documented
    device deviation, the reference splices an uncapped random block)."""
    found, a, width, kind, end = sizer
    t = prng.rand(prng.sub(key, prng.TAG_MASK), 7)

    raw = jax.random.bits(prng.sub(key, prng.TAG_VAL), (), jnp.uint32)
    new_len = jnp.minimum(
        ((raw >> 2).astype(jnp.int32) * 2) & 0x7FFFFFFF,
        ABSMAX_BINARY_BLOCK,
    )
    fb = jnp.select(
        [t == 0, t == 1],
        [jnp.zeros(4, jnp.uint8), jnp.full(4, 0xFF, jnp.uint8)],
        field_bytes(new_len, width, kind),
    )
    # 8 fill bytes from 2 raw words, tiled via reps (period-8 randomness)
    fill_words = jax.random.bits(prng.sub(key, prng.TAG_AUX), (2,), jnp.uint32)
    shifts = jnp.arange(0, 32, 8, dtype=jnp.uint32)
    rand_fill = jnp.concatenate([
        ((fill_words[0] >> shifts) & 0xFF).astype(jnp.uint8),
        ((fill_words[1] >> shifts) & 0xFF).astype(jnp.uint8),
    ])

    expand = t == 2
    lit = jnp.where(expand, rand_fill, jnp.zeros(LIT_W, jnp.uint8).at[:4].set(fb))
    pos = jnp.where(expand, end, a).astype(jnp.int32)
    drop = jnp.select(
        [expand, t == 3], [jnp.int32(0), end - a], width
    ).astype(jnp.int32)
    lit_len = jnp.where(expand, LIT_W, width).astype(jnp.int32)
    reps = jnp.where(
        expand, 1 + prng.rand_log(prng.sub(key, prng.TAG_LEN), 8), 1
    ).astype(jnp.int32)

    # no detected field: emit a no-op program, report a failed try
    pos = jnp.where(found, pos, 0)
    drop = jnp.where(found, drop, 0)
    lit_len = jnp.where(found, lit_len, 0)
    reps = jnp.where(found, reps, 0)
    delta = jnp.where(found, 1, -1).astype(jnp.int32)  # reference: 1 / -2
    return pos, drop, lit, lit_len, reps, delta


def length_mutate(key, data, n):
    """Switch-engine kernel."""
    from .payload_mutators import lit_splice

    sizer = detect_sizer(key, data, n)
    pos, drop, lit, lit_len, reps, delta = draw_len(key, n, sizer)
    out, n_out = lit_splice(data, n, pos, drop, lit, lit_len, reps)
    return out, n_out, delta
