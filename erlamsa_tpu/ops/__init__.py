"""Device compute path: vmapped uint8 mutation kernels, scheduler, patterns.

Everything here is shape-static and jit/vmap/shard_map-safe. The unit of work
is one padded sample ``(data: uint8[L], n: int32)``; the pipeline vmaps over
the batch dimension and pjit-shards it over the device mesh.

x64 is enabled package-wide: the textual-number mutator needs int64 value
arithmetic (the reference uses bignums, src/erlamsa_mutations.erl:92-112).
Hot-path kernels pin int32/uint8 dtypes explicitly so index math stays cheap.
"""

import jax

jax.config.update("jax_enable_x64", True)
