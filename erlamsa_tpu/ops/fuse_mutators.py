"""Device ft/fn/fo: suffix-fusion as a context-matched span splice.

Reference: the fuse mutators (src/erlamsa_mutations.erl:384-427 over
src/erlamsa_fuse.erl) walk a generalized suffix structure of two buffers
and jump from a random source suffix to a target suffix sharing a prefix
— radamsa's "fuse". The oracle (models/fuse.py) keeps the exact
suffix-walk and its AS183 draw order for parity work.

The DEVICE re-expression replaces the structure walk with a vectorized
context match: draw a jump-out point p and a context depth k (the walk
deepens its shared prefix with prob 7/8 per round — a log-distributed
depth draw mirrors that), then match every position j whose forward
bytes agree with data[p:p+k] in one batch of shifted compares, and pick
the jump-in point q uniformly among matches. One O(L) scan per round
instead of a pointer structure — and the result is exactly a span splice
the fused engine already pays for.

In the batch pipeline each sample is its own block list, so all three
variants fuse the sample with itself (the oracle's fn/fo reach
neighbouring blocks; single-block ll reduces them to self-fusion too —
oracle/mutations.py sed_fuse_next). Shapes:

  ft  out = data[:p] ++ data[q:n]            (fuse_this: tail jump)
  fn  out = data[:p] ++ data[q:q+l] ++ data[p:n]   (splice a matched span in)
  fo  out = data[:p] ++ data[q:q+l] ++ data[p+d:n] (jump in AND skip ahead)

Draws are shared verbatim by the fused param-gens (via Tables) and the
standalone switch kernels.
"""

from __future__ import annotations

import jax.numpy as jnp

from . import prng

# static compare window == the max drawable depth: k = 1 + rand_log(3)
# reaches at most 4, so deeper compare passes would always be masked
MATCH_DEPTH = 4


def fuse_scan(key, data, n):
    """-> (p, q, ok): jump-out p, context-matched jump-in q.

    k = 1 + rand_log(3) (log-distributed like the walk's geometric
    deepening, capped at MATCH_DEPTH); q uniform over positions whose
    k forward bytes equal data[p:p+k], excluding p itself (a p->p jump
    is the identity). ok=False (no other occurrence) falls back to a
    uniform q — the walk's terminal single-suffix node analogue."""
    L = data.shape[0]
    i = jnp.arange(L, dtype=jnp.int32)
    kf = prng.sub(key, prng.TAG_FUSE)
    p = prng.rand(prng.sub(kf, 1), jnp.maximum(n, 1))
    k = jnp.minimum(
        1 + prng.rand_log(prng.sub(kf, 2), 3), MATCH_DEPTH
    ).astype(jnp.int32)

    match = jnp.ones(L, bool)
    for d in range(MATCH_DEPTH):
        if d == 0:
            a = data
        else:
            # static shift (== data[clip(i+d)]: bytes >= n are zero by the
            # buffer invariant, so zero-pad equals the clip-gather) — a
            # fusable slice where a gather would not fuse
            a = jnp.concatenate([data[d:], jnp.zeros(d, data.dtype)])
        probe = data[jnp.clip(p + d, 0, L - 1)]
        match = match & ((d >= k) | (a == probe))
    match = match & (i < n) & (i != p)

    total = jnp.sum(match).astype(jnp.int32)
    ok = total > 0
    r = prng.rand(prng.sub(kf, 3), total)
    cum = jnp.cumsum(match).astype(jnp.int32)
    q_hit = jnp.argmax(match & (cum == r + 1)).astype(jnp.int32)
    # fallback draw over [0, n) \ {p}: draw n-1 values and shift past p,
    # so a no-match round still jumps somewhere else
    q_rnd = prng.rand(prng.sub(kf, 4), jnp.maximum(n - 1, 1))
    q_rnd = q_rnd + (q_rnd >= p).astype(jnp.int32)
    return p, jnp.where(ok, q_hit, q_rnd), ok


def draw_ft(key, n, p, q):
    """-> (pos, drop, src_start, src_len, reps, delta)."""
    return (
        p, n - p, q, jnp.maximum(n - q, 1), jnp.int32(1),
        prng.rand_delta(key),
    )


def draw_fn(key, n, p, q):
    l = 1 + prng.rand(prng.sub(key, prng.TAG_LEN), jnp.maximum(n - q, 1))
    return p, jnp.int32(0), q, l, jnp.int32(1), prng.rand_delta(key)


def draw_fo(key, n, p, q):
    l = 1 + prng.rand(prng.sub(key, prng.TAG_LEN), jnp.maximum(n - q, 1))
    d = prng.erand(prng.sub(key, prng.TAG_AUX), jnp.maximum(n - p, 1))
    return p, d, q, l, jnp.int32(1), prng.rand_delta(key)


def span_splice(data, n, pos, drop, src_start, src_len, reps):
    """out = data[:pos] ++ span-repeated ++ data[pos+drop:] (the fused
    engine's SRC_SPAN splice, standalone for the switch engine)."""
    L = data.shape[0]
    i = jnp.arange(L, dtype=jnp.int32)
    pos = jnp.clip(pos, 0, n)
    drop = jnp.clip(drop, 0, n - pos)
    rlen = jnp.clip(src_len * jnp.maximum(reps, 1), 0, L)
    end_ins = pos + rlen
    span_src = jnp.clip(
        src_start + jnp.mod(i - pos, jnp.maximum(src_len, 1)), 0, L - 1
    )
    tail_src = jnp.clip(i - rlen + drop, 0, L - 1)
    out = jnp.where(
        i < pos,
        data,
        jnp.where(i < end_ins, data[span_src], data[tail_src]),
    )
    n_out = jnp.clip(n - drop + rlen, 0, L)
    out = jnp.where(i < n_out, out, jnp.uint8(0))
    return out, n_out


def _fuse_kernel(draw):
    def kernel(key, data, n):
        p, q, _ok = fuse_scan(key, data, n)
        pos, drop, s, sl, reps, delta = draw(key, n, p, q)
        out, n_out = span_splice(data, n, pos, drop, s, sl, reps)
        return out, n_out, delta

    return kernel


fuse_this = _fuse_kernel(draw_ft)
fuse_next = _fuse_kernel(draw_fn)
fuse_old = _fuse_kernel(draw_fo)
