"""Mutator scheduler: the batch re-expression of mux_fuzzers.

Reference semantics (src/erlamsa_mutations.erl:1244-1280): every mutator
carries a self-adjusting score (2..10) times a user priority; per mutation
event each mutator draws rand(score*pri), the draws are sorted descending,
and mutators are tried in that order until one changes the data; every
tried mutator's score is adjusted by the delta its attempt returned.

Device re-expression, one fused pass per sample (vmapped over the batch):

1. draw the weighted keys for all M mutators at once,
2. argsort once for the try order,
3. pick the first *applicable* mutator (predicate table, O(L) vector ops)
   instead of physically running and re-comparing candidates,
4. apply exactly one kernel via lax.switch,
5. adjust scores: every earlier (tried-and-failed) mutator gets -1 — which
   is precisely the delta our kernels return when inapplicable — and the
   applied mutator gets its own delta; clamp into [MIN_SCORE, MAX_SCORE].

Score state is per *sample* (int32[M]), initialized like mutators_mutator's
randomized scores (src/erlamsa_mutations.erl:1385-1395), carried across
cases by the caller. The reference shares one evolving score vector across
the whole sequential run; per-sample state keeps batch samples independent
(documented divergence — parity mode uses the oracle).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

import functools

from ..constants import MAX_SCORE, MIN_SCORE
from . import prng
from .registry import DEVICE_MUTATORS, NUM_DEVICE_MUTATORS, PRED_INDEX_NP, predicates


@functools.lru_cache(maxsize=None)
def _pred_index():
    """registry.PRED_INDEX_NP as a device constant, built once per
    process instead of per call/trace. Concrete even under an active
    trace — see utf8_mutators.funny_tables."""
    with jax.ensure_compile_time_eval():
        return jnp.asarray(PRED_INDEX_NP)

_KERNELS = tuple(m.kernel for m in DEVICE_MUTATORS)


def init_scores(key: jax.Array, batch: int) -> jax.Array:
    """Randomized initial scores max(2, rand(10)) per mutator per sample
    (erlamsa_mutations.erl:1393-1395)."""
    r = jax.random.randint(
        key, (batch, NUM_DEVICE_MUTATORS), 0, int(MAX_SCORE), dtype=jnp.int32
    )
    return jnp.maximum(r, int(MIN_SCORE))


def weighted_pick(key, data, n, scores, pri, preds=None):
    """The mux selection: applicability table, weighted-permutation draw,
    first applicable in descending order. Shared by both engines.

    preds: optional precomputed registry.predicates table (the fused
    engine shares scan work with its per-round Tables).

    Returns (applied, any_app, pos, pos_of): chosen registry index, whether
    anything was applicable, its position in the try order, and the inverse
    permutation (for tried-before score accounting)."""
    M = NUM_DEVICE_MUTATORS
    if preds is None:
        preds = predicates(data, n)  # bool[NUM_PREDS]
    applicable = preds[_pred_index()] & (pri > 0)

    # weighted permutation: r_m = rand(score_m * pri_m), sorted desc.
    # One threefry call for all M draws (bits % bound, bias < 1e-7 at
    # bound <= 100) instead of M key-splits + M randints — the split
    # chain dominated the pick at M=31 (ENGINE VERSION NOTE r5 in
    # ops/pipeline.py: selection streams changed).
    bits = jax.random.bits(prng.sub(key, prng.TAG_PERM), (M,), jnp.uint32)
    bounds = jnp.maximum(scores * pri, 1).astype(jnp.uint32)
    draws = (bits % bounds).astype(jnp.int32)
    order = jnp.argsort(-draws, stable=True).astype(jnp.int32)

    app_in_order = applicable[order]
    any_app = jnp.any(app_in_order)
    pos = jnp.argmax(app_in_order).astype(jnp.int32)  # first applicable
    applied = order[pos]
    pos_of = jnp.argsort(order).astype(jnp.int32)  # inverse permutation
    return applied, any_app, pos, pos_of


def adjust_scores(scores, applied, any_app, pos, pos_of, delta):
    """Score update for every tried mutator: -1 for tried-and-failed, the
    applied mutator's own delta, clamped (erlamsa_mutations.erl:1238-1242)."""
    M = NUM_DEVICE_MUTATORS
    tried_before = pos_of < pos
    deltas = jnp.where(tried_before, -1, 0)
    deltas = jnp.where((jnp.arange(M) == applied) & any_app, delta, deltas)
    return jnp.clip(scores + deltas, int(MIN_SCORE), int(MAX_SCORE)).astype(
        jnp.int32
    )


def mutate_step(key, data, n, scores, pri, preds=None):
    """One mutation event on one sample (the per-kernel "switch" engine).

    Args:
      key: per-event PRNG key.
      data: uint8[L]; n: int32 length.
      scores: int32[M] self-adjusting scores.
      pri: int32[M] user priorities (0 disables a mutator).
      preds: optional precomputed registry.predicates table.

    Returns: (data', n', scores', applied int32) — applied is the registry
    index, or -1 when nothing was applicable.
    """
    applied, any_app, pos, pos_of = weighted_pick(
        key, data, n, scores, pri, preds=preds
    )

    new_data, new_n, delta = jax.lax.switch(
        applied, _KERNELS, prng.sub(key, prng.TAG_SITE), data, n
    )
    new_data = jnp.where(any_app, new_data, data)
    new_n = jnp.where(any_app, new_n, n)

    new_scores = adjust_scores(scores, applied, any_app, pos, pos_of, delta)
    applied_out = jnp.where(any_app, applied, -1).astype(jnp.int32)
    return new_data, new_n, new_scores, applied_out
