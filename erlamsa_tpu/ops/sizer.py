"""Device sizer detection + rebuild: the sz pattern's vectorized core.

Reference: the sizer pattern finds a plausible length field, mutates the
enclosed blob, and rewrites the field with the blob's new length
(src/erlamsa_patterns.erl:81-111 over erlamsa_field_predict's randomized
O(n*k) rescan). On device the scan is a handful of shifted compares: every
offset is tested simultaneously for u8/u16/u32 big/little fields whose
value equals the distance to the end of the buffer — one vector pass
instead of hundreds of per-offset re-reads.

Scope vs the oracle: the device detects tail sizers (blob ends at n — the
overwhelmingly common layout), the reference's near-tail delta probes
(ends n-1, n-2, n-4, n-8, and for u8 fields every n-x down to n-8,
erlamsa_field_predict.erl simple_len/simple_u8len), AND sampled interior
ends like the oracle's random var_b draws — the key identity being that a
candidate's end offset is DERIVED from its field value (end = value +
offset + width), so interior support is a membership test on the same
[5, L] masks, not a mask explosion. Documented divergences: the device
draws a fixed 4 interior probes per sample (the oracle draws sublen+1,
scaling with n) and restricts only interior candidates to the reference's
a <= sublen window (tail/near-tail candidates keep the device's historic
any-offset scope). The checksum-preserving (cs) pattern runs on device
too — ops/crc32.py decomposes crc32 as a GF(2)-linear suffix scan (and
xor8 trivially), wired into the pipeline's cs branch.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..constants import PREAMBLE_MAX_BYTES, SIZER_MAX_FIRST_BYTES
from . import prng

# field kinds: (width_bytes, endianness) — index into these tables
KIND_U8, KIND_U16BE, KIND_U16LE, KIND_U32BE, KIND_U32LE = range(5)
_WIDTHS = (1, 2, 4)

N_INTERIOR_PROBES = 4  # keyed interior-end draws per sample (fixed; see top)


def sizer_candidates(data, n):
    """The STATIC (un-keyed) candidate scan, shared between detect_sizer
    and the len-mutator applicability predicate (registry P_SIZERQ) so one
    computation serves both per round.

    Returns (near [5, L] bool tail/near-tail candidates, vals [5] list of
    int32[L] field values, ends [5] list of implied end offsets).
    Byte shifts are STATIC zero-padded slices — equal to the historical
    clip-gather reads for every candidate the masks admit (bytes >= n are
    zero by the buffer invariant) and fusable where a gather is not."""
    L = data.shape[0]
    i = jnp.arange(L, dtype=jnp.int32)
    d = data.astype(jnp.int32)

    def at(off):
        if off == 0:
            return d
        # == d[clip(i + off, 0, L-1)] without the gather: bytes >= n are
        # zero by the buffer invariant, so the zero pad matches the
        # historical clip-gather reads for every candidate with e <= n
        return jnp.concatenate([d[off:], jnp.zeros(off, jnp.int32)])

    b0, b1, b2, b3 = at(0), at(1), at(2), at(3)
    v_u8 = b0
    v_u16be = b0 * 256 + b1
    v_u16le = b1 * 256 + b0
    v_u32be = v_u16be * 65536 + (b2 * 256 + b3)
    v_u32le = (b3 * 256 + b2) * 65536 + v_u16le

    kinds = ((v_u8, 1), (v_u16be, 2), (v_u16le, 2), (v_u32be, 4), (v_u32le, 4))
    nears, vals, ends = [], [], []
    for v, w in kinds:
        e = v + i + w  # the end offset this field value implies
        dlt = n - e
        if w == 1:
            # u8 probes every end from n down to n-8 (simple_u8len)
            near = (dlt >= 0) & (dlt <= 8)
        else:
            near = (dlt == 0) | (dlt == 1) | (dlt == 2) | (dlt == 4) | (dlt == 8)
        nears.append((v > 2) & (e <= n) & near)
        vals.append(v)
        ends.append(e)
    return jnp.stack(nears), vals, ends


def detect_sizer(key, data, n, candidates=None):
    """Find a random plausible length field (tail, near-tail, or sampled
    interior end).

    Returns (found, a, width_bytes, kind, end): field at [a, a+width)
    whose value v > 2 satisfies a + width + v == end, where end is n, a
    near-tail delta (reference simple_len/simple_u8len probes), or one of
    N_INTERIOR_PROBES keyed draws from [sublen, n) (the oracle's var_b
    sampling, erlamsa_field_predict.erl:90-105). One uniform pick among
    all candidates via keyed cumsum order.

    candidates: optional precomputed sizer_candidates(data, n) result
    (the fused engine computes it once per round for the predicate too).
    """
    L = data.shape[0]
    i = jnp.arange(L, dtype=jnp.int32)
    near_cand, vals, ends = (
        candidates if candidates is not None else sizer_candidates(data, n)
    )

    # interior end probes: uniform in [sublen, n) like the oracle's
    # rand_range(SubLen, Len); a candidate may only sit in the reference's
    # first-bytes window for these
    sublen = jnp.minimum(n // 5, SIZER_MAX_FIRST_BYTES)
    kp = prng.sub(key, prng.TAG_LEN)
    probes = [
        sublen + prng.rand(prng.sub(kp, j + 1),
                           jnp.maximum(n - sublen, 1)).astype(jnp.int32)
        for j in range(N_INTERIOR_PROBES)
    ]

    cands = []
    for kind, (v, e) in enumerate(zip(vals, ends)):
        interior = jnp.zeros(L, bool)
        for p in probes:
            interior = interior | (e == p)
        interior = interior & (i <= sublen) & (v > 2) & (e <= n)
        cands.append(near_cand[kind] | interior)
    cand = jnp.stack(cands)  # [5, L]

    # uniform pick with ONE scalar draw: the r-th candidate in flat
    # (kind-major) order — hierarchical form (this runs per ROUND in the
    # fused engine's Tables since r5): cheap per-kind COUNT reductions
    # pick the kind, then a single cumsum+argmax runs on the selected
    # kind's [L] row. Identical candidate to the historical flat [5L]
    # cumsum at ~1/4 the serial-scan cost.
    counts = jnp.sum(cand, axis=1).astype(jnp.int32)  # [5]
    cumcnt = jnp.cumsum(counts)
    total = cumcnt[4]
    any_found = total > 0
    r = prng.rand(prng.sub(key, prng.TAG_AUX), total)
    kind = jnp.sum((cumcnt <= r).astype(jnp.int32)).astype(jnp.int32)
    prev = jnp.where(kind > 0, cumcnt[jnp.clip(kind - 1, 0, 4)], 0)
    r_local = r - prev
    mask_k = cand[jnp.clip(kind, 0, 4)]  # [L] row select
    cum_k = jnp.cumsum(mask_k).astype(jnp.int32)
    a = jnp.argmax(mask_k & (cum_k == r_local + 1)).astype(jnp.int32)
    width = jnp.asarray((1, 2, 2, 4, 4), jnp.int32)[kind]
    # five scalar reads, not a [5, L] stack-then-gather
    val = jnp.stack([v[a] for v in vals])[kind]
    end = jnp.minimum(val + a + width, n)
    return any_found, a, width, kind, end


def xor8_candidates(data, n):
    """bool[L]: preambles a with a plausible xor8 trailer —
    xor(data[a:n-1]) == data[n-1], i.e. the suffix-xor at a is zero —
    one reversed cumulative-xor pass instead of the reference's
    O(n*k) per-preamble rescan (erlamsa_field_predict.erl:129-161)."""
    L = data.shape[0]
    i = jnp.arange(L, dtype=jnp.int32)
    x = jnp.where(i < n, data, jnp.uint8(0))
    sfx = jnp.flip(
        jax.lax.associative_scan(jnp.bitwise_xor, jnp.flip(x))
    )  # sfx[i] = xor of data[i:n]
    # inclusive preamble envelope, same as the oracle's range(0, limit + 1)
    # (models/fieldpred.py get_possible_csum_locations)
    limit = jnp.minimum(2 * n // 3, 30 * PREAMBLE_MAX_BYTES)
    # i < n - 1 == the oracle's non-empty-body guard (n - a - 1 > 0)
    return (sfx == 0) & (i <= limit) & (i < n - 1)


def xor8_of_range(data, start, end):
    """xor of data[start:end] via prefix-xor difference."""
    L = data.shape[0]
    i = jnp.arange(L, dtype=jnp.int32)
    x = jnp.where((i >= start) & (i < end), data, jnp.uint8(0))
    return jax.lax.associative_scan(jnp.bitwise_xor, x)[L - 1]


def rebuild_sizer(data, n, a, width, kind, blob_len):
    """Rewrite the length field at [a, a+width) with blob_len."""
    L = data.shape[0]
    i = jnp.arange(L, dtype=jnp.int32)
    v = blob_len.astype(jnp.int32)
    # byte k of the field (k = i - a in [0, width))
    k = i - a
    be_shift = (width - 1 - k) * 8
    le_shift = k * 8
    is_le = (kind == KIND_U16LE) | (kind == KIND_U32LE)
    shift = jnp.where(is_le, le_shift, be_shift)
    field_byte = jnp.right_shift(v, jnp.clip(shift, 0, 31)) & 0xFF
    in_field = (k >= 0) & (k < width)
    return jnp.where(in_field, field_byte.astype(jnp.uint8), data)
