"""Single-byte mutator kernels: bd bei bed br bf bi ber.

TPU re-expression of the reference's edit_byte_vector family
(src/erlamsa_mutations.erl:54-61, 175-223): instead of splitting a binary at
a random position, every kernel computes a per-output-position source index
and gathers — one fused vector op over the padded sample, identical cost for
any position, no dynamic shapes.

Kernel contract (single sample; the pipeline vmaps over the batch):

    kernel(key, data: uint8[L], n: int32) -> (uint8[L], int32 n', int32 delta)

On empty input (n == 0) kernels return the input unchanged with delta -1,
which makes the scheduler treat them as failed and move on — the batch
analogue of mux_fuzzers retrying (src/erlamsa_mutations.erl:1267-1280).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import prng


def _positions(L: int) -> jax.Array:
    return jnp.arange(L, dtype=jnp.int32)


def _guard_empty(data, n, out, n_out, delta):
    """n == 0 -> unchanged/failed."""
    empty = n <= 0
    return (
        jnp.where(empty, data, out),
        jnp.where(empty, n, n_out),
        jnp.where(empty, -1, delta),
    )


def byte_drop(key, data, n):
    """bd: drop the byte at a random position (erlamsa_mutations.erl:183-185)."""
    L = data.shape[0]
    p = prng.rand(prng.sub(key, prng.TAG_POS), n)
    i = _positions(L)
    src = jnp.where(i >= p, jnp.minimum(i + 1, L - 1), i)
    out = data[src]
    n_out = n - 1
    out = jnp.where(i < n_out, out, jnp.uint8(0))
    return _guard_empty(data, n, out, n_out, prng.rand_delta(key))


def _edit_at(key, data, n, new_byte_fn):
    """Replace data[p] with new_byte_fn(old_byte, key)."""
    p = prng.rand(prng.sub(key, prng.TAG_POS), n)
    old = data[p]
    new = new_byte_fn(old, key)
    out = data.at[p].set(new)
    return _guard_empty(data, n, out, n, prng.rand_delta(key))


def byte_inc(key, data, n):
    """bei: increment a byte mod 256 (erlamsa_mutations.erl:187-189)."""
    return _edit_at(key, data, n, lambda b, k: b + jnp.uint8(1))


def byte_dec(key, data, n):
    """bed: decrement a byte mod 256 (erlamsa_mutations.erl:191-193)."""
    return _edit_at(key, data, n, lambda b, k: b - jnp.uint8(1))


def byte_flip(key, data, n):
    """bf: flip one random bit (erlamsa_mutations.erl:199-207)."""

    def flip(b, k):
        bit = prng.rand(prng.sub(k, prng.TAG_VAL), 8)
        return b ^ jnp.left_shift(jnp.uint8(1), bit.astype(jnp.uint8))

    return _edit_at(key, data, n, flip)


def byte_random(key, data, n):
    """ber: replace a byte with a random one (erlamsa_mutations.erl:217-223)."""
    return _edit_at(
        key, data, n, lambda b, k: prng.rand_byte(prng.sub(k, prng.TAG_VAL))
    )


def _insert_at(key, data, n, inserted_fn):
    """Insert inserted_fn(data[p]) before position p; clips at capacity."""
    L = data.shape[0]
    p = prng.rand(prng.sub(key, prng.TAG_POS), n)
    i = _positions(L)
    src = jnp.where(i > p, i - 1, i)
    out = data[src]
    out = jnp.where(i == p, inserted_fn(data[p], key), out)
    n_out = jnp.minimum(n + 1, L)
    out = jnp.where(i < n_out, out, jnp.uint8(0))
    return _guard_empty(data, n, out, n_out, prng.rand_delta(key))


def byte_insert(key, data, n):
    """bi: insert a random byte (erlamsa_mutations.erl:209-215)."""
    return _insert_at(
        key, data, n, lambda b, k: prng.rand_byte(prng.sub(k, prng.TAG_VAL))
    )


def byte_repeat(key, data, n):
    """br: duplicate the byte at a random position (erlamsa_mutations.erl:195-197)."""
    return _insert_at(key, data, n, lambda b, k: b)
