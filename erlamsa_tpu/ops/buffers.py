"""Padded batch buffers: the device-side corpus representation.

A corpus batch lives on device as ``data: uint8[B, L]`` plus ``lens:
int32[B]`` — the TPU-native replacement for the reference's lazy lists of
variable-sized binaries (src/erlamsa_gen.erl:59-88). L is drawn from
CAPACITY_CLASSES so XLA compiles one program per class, and mutations that
grow data get real slack instead of dynamic shapes.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..constants import CAPACITY_CLASSES


class Batch(NamedTuple):
    """A batch of byte samples. NamedTuple => automatically a pytree."""

    data: jax.Array  # uint8[B, L]
    lens: jax.Array  # int32[B]

    @property
    def capacity(self) -> int:
        return self.data.shape[-1]

    @property
    def batch_size(self) -> int:
        return self.data.shape[0]


def capacity_for(max_len: int, slack: float = 2.0) -> int:
    """Smallest capacity class holding max_len * slack."""
    want = max(1, int(max_len * slack))
    for c in CAPACITY_CLASSES:
        if c >= want:
            return c
    return CAPACITY_CLASSES[-1]


def scan_bound(max_len: int, capacity: int) -> int:
    """Static detection-scan bound for a batch whose longest sample is
    max_len (fuzz_batch scan_len): lane-friendly multiple of 256, floored
    at 256 so a degenerate all-empty batch never yields a width-0 view,
    capped at the capacity."""
    return max(256, min(capacity, -(-max_len // 256) * 256))


def pack(seeds: Sequence[bytes], capacity: int | None = None) -> Batch:
    """Host -> device: pad/pack a list of byte strings."""
    if not seeds:
        raise ValueError("empty corpus")
    max_len = max(len(s) for s in seeds)
    cap = capacity or capacity_for(max_len)
    if max_len > cap:
        raise ValueError(f"seed of {max_len}B exceeds capacity {cap}")
    arr = np.zeros((len(seeds), cap), dtype=np.uint8)
    lens = np.empty(len(seeds), dtype=np.int32)
    for i, s in enumerate(seeds):
        arr[i, : len(s)] = np.frombuffer(s, dtype=np.uint8)
        lens[i] = len(s)
    return Batch(jnp.asarray(arr), jnp.asarray(lens))


def unpack(batch: Batch) -> list[bytes]:
    """Device -> host: strip padding."""
    data = np.asarray(batch.data)
    lens = np.asarray(batch.lens)
    return [data[i, : lens[i]].tobytes() for i in range(data.shape[0])]


def mask_tail(data: jax.Array, n: jax.Array) -> jax.Array:
    """Zero bytes at and beyond n (keeps padding canonical for comparisons)."""
    idx = jnp.arange(data.shape[-1], dtype=jnp.int32)
    return jnp.where(idx < n, data, jnp.uint8(0))
