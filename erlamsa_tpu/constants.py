"""Shared engine constants.

Values mirror the reference's tunables (reference: src/erlamsa.hrl:44-66) so
the mutation-site distribution and block envelopes match; TPU-side batch
capacities are new.
"""

# Basic patterns trigger a mutation on a block with probability 1/rand(INITIAL_IP)
# (reference: src/erlamsa.hrl:44, src/erlamsa_patterns.erl:271).
INITIAL_IP = 24

# Probability that a "many" pattern keeps mutating (reference: src/erlamsa.hrl:45).
REMUTATE_PROBABILITY = (4, 5)

# Upper bound on burst/many rounds on the device path. The reference's
# geometric chain is unbounded; on TPU we truncate (P(chain > 16) = (4/5)^16
# ~ 2.8%, folded into the final round) (reference: src/erlamsa.hrl:46).
MAX_BURST_MUTATIONS = 16

# Generator block envelope (reference: src/erlamsa.hrl:47-50).
MIN_BLOCK_SIZE = 256
AVG_BLOCK_SIZE = 2048
MAX_BLOCK_SIZE = 2 * AVG_BLOCK_SIZE

# Hard cap on a single mutable block (reference: src/erlamsa.hrl:51-52).
ABSMAXHALF_BINARY_BLOCK = 500_000
ABSMAX_BINARY_BLOCK = 2 * ABSMAXHALF_BINARY_BLOCK

# Mutator self-adjusting score range (reference: src/erlamsa_mutations.erl:42-43).
MIN_SCORE = 2.0
MAX_SCORE = 10.0

# Sizer / checksum field search limits (reference: src/erlamsa.hrl:57-58).
SIZER_MAX_FIRST_BYTES = 512
PREAMBLE_MAX_BYTES = 32

# Service-side timeouts, in seconds (reference: src/erlamsa_cmdparse.erl:109-111,
# src/erlamsa_fsupervisor.erl:83-86).
DEFAULT_MAX_RUNNING_TIME = 30.0
FAAS_REQUEST_TIMEOUT = 90.0

# Output failure tolerance (reference: src/erlamsa.hrl:55, src/erlamsa_main.erl:170-175).
TOO_MANY_FAILED_ATTEMPTS = 10

# Logging payload cap (reference: src/erlamsa.hrl:56).
MAX_LOG_DATA = 10_000_000

# Distributed nodes keepalive/eviction, seconds (reference: src/erlamsa.hrl:64-66).
NODE_ALIVE_DELTA = 17.0
NODE_KEEPALIVE = 15.0
NODES_CHECKTIMER = 5.0

# Connect-monitor default port, advertised to SSRF/shell-inject payload builders
# (reference: src/erlamsa_mon_connect.erl:27-29, src/erlamsa_mutations.erl:703).
DEFAULT_CM_PORT = 51234

# Edge-coverage bitmap width in bytes (8 edges/byte): 8192 edges, the
# classic AFL map scaled to loopback-smoke friendliness. Lives here (not
# ops/coverage.py) so the jax-free monitor plane can share it; the hub
# and checkpoints still carry the actual width explicitly.
COVERAGE_MAP_BYTES = 1024

# Default TPU batch capacity classes: sample buffers are padded to the
# smallest class >= seed length * growth slack.  TPU-native choice: lane
# dimension multiples of 128 keep layouts tight. The 2048/8192 rungs
# matter: common 1KB/4KB corpora at the default 2x slack land exactly
# there — without them capacity_for jumps 4x and every O(L) pass pays
# double (measured 1.7x e2e at 1KB seeds, PROFILE.md).
CAPACITY_CLASSES = (256, 1024, 2048, 4096, 8192, 16384, 65536, 262144,
                    ABSMAX_BINARY_BLOCK)
