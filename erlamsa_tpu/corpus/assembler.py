"""Bucketed batch assembly: scheduled seeds -> padded device batches.

The round-5 bench recorded the full-set slide 872 -> 550 samples/s
(BENCH_r05.json): one oversized capacity class drags every sample in the
batch to its padded width. This module groups a scheduled seed list into
power-of-two LENGTH buckets so each sample pays only the padding of its
own size class, and pads each bucket's row count up to a power of two so
the jitted step sees a bounded set of (B, L) shapes — recompiles stay
O(log^2) over the whole run instead of O(cases).

Emits plain numpy uint8[B, L] + int32[B] length vectors — exactly what
ops/buffers.Batch holds and services/batchrunner.py's step consumes —
without importing jax, so assembly can run on publisher threads and in
tests with no accelerator backend.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import numpy as np

from ..constants import CAPACITY_CLASSES

#: smallest bucket: below this, padding waste is noise and smaller
#: shapes would only multiply compiled programs (lane width, ops/buffers
#: scan_bound floor)
MIN_BUCKET = 256

#: smallest padded row count per bucket — thinner batches pay more
#: per-dispatch overhead than the padding costs
MIN_ROWS = 8

#: mutation growth slack, matching ops/buffers.capacity_for
GROWTH_SLACK = 2.0


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1)).bit_length()


def bucket_capacity(length: int, slack: float = GROWTH_SLACK,
                    device_max: int | None = None) -> int:
    """Power-of-two capacity for a seed of `length` bytes with mutation
    growth slack, floored at MIN_BUCKET and capped at the largest device
    capacity class (bigger seeds overflow to the host oracle, like the
    batch runner's capacity classes)."""
    cap_max = device_max or CAPACITY_CLASSES[-1]
    want = max(1, int(length * slack))
    return min(max(MIN_BUCKET, _next_pow2(want)), cap_max)


class Bucket(NamedTuple):
    """One padded device batch of same-size-class samples."""

    capacity: int  # L: power-of-two byte width
    slots: np.ndarray  # int32[rows]: positions in the scheduled list
    data: np.ndarray  # uint8[rows_padded, capacity]
    lens: np.ndarray  # int32[rows_padded]
    rows: int  # real sample count (<= rows_padded)
    padded_bytes_wasted: int  # sum(capacity - len) over REAL rows

    @property
    def rows_padded(self) -> int:
        return self.data.shape[0]

    @property
    def pad_rows(self) -> int:
        return self.rows_padded - self.rows


class BucketPlan(NamedTuple):
    """A bucket's membership before the padded panel is built — the cheap
    half of assembly. plan_buckets + materialize split the work so a
    pipelined runner can build bucket N+1's panel while bucket N computes
    on device; assemble() composes them for the one-shot callers."""

    capacity: int  # L: power-of-two byte width
    slots: np.ndarray  # int32[rows]: positions in the scheduled list
    rows_padded: int


def plan_buckets(samples: Sequence[bytes], slack: float = GROWTH_SLACK,
                 device_max: int | None = None,
                 pad_rows_pow2: bool = True) -> list[BucketPlan]:
    """Group sample positions into capacity buckets (no data copied).

    Every input position lands in exactly one bucket (slots); within a
    bucket, schedule order is preserved. Plans come back sorted by
    capacity (smallest first) for a stable compile order.
    """
    groups: dict[int, list[int]] = {}
    for pos, s in enumerate(samples):
        cap = bucket_capacity(len(s), slack, device_max)
        groups.setdefault(cap, []).append(pos)
    return [
        BucketPlan(
            capacity=cap,
            slots=np.asarray(positions, np.int32),
            rows_padded=(max(MIN_ROWS, _next_pow2(len(positions)))
                         if pad_rows_pow2 else len(positions)),
        )
        for cap, positions in sorted(groups.items())
    ]


def materialize(plan: BucketPlan, samples: Sequence[bytes]) -> Bucket:
    """Build one plan's padded device panel (the expensive half).

    Vectorized: one flat join of the row payloads and one masked scatter
    into the zero panel, instead of a per-row np.frombuffer loop — the
    row-major order of a boolean-mask assignment matches the join order
    exactly, so the panel is byte-identical to the loop it replaced.
    """
    cap = plan.capacity
    rows = len(plan.slots)
    # oversized samples (beyond the device cap) are truncated to
    # capacity rather than dropped — the scheduler picked them, and a
    # truncated mutation beats an empty slot; the runner counts them
    # into metrics.Counters (erlamsa_truncated_rows_total)
    src = [samples[plan.slots[r % rows]] for r in range(plan.rows_padded)]
    lens = np.fromiter((min(len(s), cap) for s in src), np.int32,
                       count=plan.rows_padded)
    flat = np.frombuffer(
        b"".join(s[:n] for s, n in zip(src, lens.tolist())), np.uint8
    )
    data = np.zeros((plan.rows_padded, cap), np.uint8)
    if flat.size:
        data[np.arange(cap) < lens[:, None]] = flat
    wasted = int(cap * rows - int(lens[:rows].sum()))
    return Bucket(
        capacity=cap,
        slots=plan.slots,
        data=data,
        lens=lens,
        rows=rows,
        padded_bytes_wasted=wasted,
    )


def assemble(samples: Sequence[bytes], slack: float = GROWTH_SLACK,
             device_max: int | None = None,
             pad_rows_pow2: bool = True) -> list[Bucket]:
    """Group a scheduled sample list into padded capacity buckets.

    Row padding repeats real rows cyclically — pad outputs are discarded
    by the consumer, so their content only has to be shape-valid.
    """
    return [
        materialize(p, samples)
        for p in plan_buckets(samples, slack, device_max, pad_rows_pow2)
    ]
