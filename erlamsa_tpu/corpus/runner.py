"""Feedback-driven batch loop: store -> energy schedule -> buckets ->
device mutation -> feedback, the closed-loop counterpart of
services/batchrunner.py's open-loop path.

Per case:
  1. the energy scheduler draws `batch` seeds (weighted, counter-keyed)
  2. the assembler groups them into power-of-two length buckets
  3. each bucket rides one jitted fuzz_batch call (device mutator set)
  4. outputs are hashed: a never-seen output hash bumps the source
     seed's energy (the cheap novelty signal standing in for coverage)
  5. the feedback bus is drained; monitor/proxy events promote the
     seeds that were in flight
  6. energies are checkpointed alongside the scheduler scores so a
     resumed run schedules identically

Determinism contract (the -s replay guarantee): every schedule draw is
keyed on (seed, case, TAG_SCHED), device keys on (seed, case, slot), and
energies evolve only from deterministic inputs applied at case
boundaries — so at a fixed seed, two runs produce byte-identical
schedules and outputs. External bus events are inherently timing-
dependent; they are folded in at the same case boundary, so replay
holds whenever the event stream is (e.g. absent, or injected at fixed
cases as the tests do).
"""

from __future__ import annotations

import hashlib
import sys
import time

import numpy as np

from ..services import logger, metrics, out
from . import feedback as fb
from .assembler import assemble
from .energy import EnergyScheduler
from .store import CorpusStore


def _out_hash(data: bytes) -> bytes:
    return hashlib.sha1(data).digest()[:12]


def run_corpus_batch(opts: dict, batch: int = 1024) -> int:
    """The --corpus DIR --feedback entry point."""
    import jax

    from ..constants import CAPACITY_CLASSES
    from ..oracle.mutations import default_mutations
    from ..ops import prng
    from ..ops.buffers import Batch, scan_bound, unpack
    from ..ops.pipeline import make_class_fuzzer
    from ..ops.registry import DEVICE_CODES
    from ..ops.scheduler import init_scores
    from ..services.checkpoint import (load_corpus_energies, load_state,
                                       save_state)

    store = CorpusStore(opts["corpus_dir"])
    direct = opts.get("corpus")
    if direct is not None:
        # in-process callers (bench corpus stage, tests) hand seeds over
        # directly instead of staging files
        for s in direct:
            store.add(s, origin="direct")
    else:
        paths = opts.get("paths") or []
        paths = [p for p in paths if p != "-"]
        if paths:
            from ..oracle.gen import _expand_paths

            expanded = (_expand_paths(paths) if opts.get("recursive")
                        else paths)
            new, dup, skipped = store.add_paths(expanded)
            print(f"# corpus: {new} new, {dup} duplicate, "
                  f"{skipped} skipped -> {len(store)} seeds in store",
                  file=sys.stderr)
    if len(store) == 0:
        print("no corpus (store empty and no readable seeds)",
              file=sys.stderr)
        return 1

    selected = dict(opts.get("mutations") or default_mutations())
    pri = [max(selected.get(code, 0), 0) for code in DEVICE_CODES]
    if not any(pri):
        print("none of the selected mutations runs on the TPU backend; "
              f"device set: {','.join(DEVICE_CODES)}", file=sys.stderr)
        return 1

    device_max = int(opts.get("device_capacity_max", CAPACITY_CLASSES[-1]))
    sched = EnergyScheduler(store, opts["seed"])
    step = make_class_fuzzer(mutator_pri=pri)
    base = prng.base_key(opts["seed"])
    scores = init_scores(jax.random.fold_in(base, 999), batch)
    bus = opts.get("feedback_bus", fb.GLOBAL)
    consume_feedback = bool(opts.get("feedback"))

    n_cases = opts.get("n", 1)
    start_case = 0
    ckpt_every = max(1, int(opts.get("checkpoint_every", 1)))
    state_path = opts.get("state_path")
    if state_path:
        import os as _os

        from ..ops.registry import NUM_DEVICE_MUTATORS

        if _os.path.exists(state_path):
            st = load_state(state_path)
            if st is None:
                print("# checkpoint unreadable, starting fresh",
                      file=sys.stderr)
            else:
                ck_seed, ck_case, ck_scores, _hs, _hsp = st
                if (ck_seed != tuple(opts["seed"])
                        or ck_scores.shape != (batch, NUM_DEVICE_MUTATORS)):
                    print("# checkpoint mismatch (seed/shape), starting "
                          "fresh", file=sys.stderr)
                else:
                    import jax.numpy as jnp

                    start_case = ck_case
                    scores = jnp.asarray(ck_scores)
                    energies = load_corpus_energies(state_path)
                    if energies:
                        store.restore_energies(energies)
                    print(f"# resumed at case {start_case} "
                          f"({len(energies or {})} seed energies restored)",
                          file=sys.stderr)
        if start_case >= n_cases:
            print(f"# run already complete ({start_case}/{n_cases} cases)",
                  file=sys.stderr)
            return 0

    writer, _mt = out.string_outputs(opts.get("output", "-"))
    stats = opts.get("_stats")  # caller-owned dict for measured numbers
    seen_hashes: set[bytes] = set()
    bucket_stats: dict[int, dict] = {}
    truncated = 0
    total = 0
    new_hashes = 0
    t0 = time.perf_counter()

    for case in range(start_case, n_cases):
        ids = sched.schedule(case, batch)
        samples = [store.get(sid) for sid in ids]
        truncated += sum(len(s) > device_max for s in samples)
        buckets = assemble(samples, device_max=device_max)

        results: dict[int, bytes] = {}
        # np.array (copy): jax gives back read-only views, and the
        # per-bucket scatter below writes in place
        scores_np = np.array(scores)
        case_bytes = 0
        t_dev = time.perf_counter()
        for b in buckets:
            # keys derive from the SLOT position (0..batch-1) so a
            # sample's stream is a pure function of (seed, case, slot)
            # no matter how the buckets partition the batch; pad rows get
            # out-of-range indices — their outputs are discarded
            idx = np.concatenate([
                b.slots, batch + np.arange(b.pad_rows, dtype=np.int32)
            ]).astype(np.int32)
            sc_in = scores_np[b.slots[np.arange(b.rows_padded) % b.rows]]
            new_data, new_lens, new_sc, meta = step(
                base, case, idx, b.data, b.lens, sc_in,
                scan_len=scan_bound(int(b.lens[:b.rows].max()), b.capacity),
            )
            outs = unpack(Batch(new_data[:b.rows], new_lens[:b.rows]))
            scores_np[b.slots] = np.asarray(new_sc)[:b.rows]
            for j, slot in enumerate(b.slots):
                results[int(slot)] = outs[j]
            # per-mutator applied counters (registry rows, device side)
            applied = np.asarray(meta.applied)[:b.rows].ravel()
            applied = applied[applied >= 0]
            if applied.size:
                counts = np.bincount(applied, minlength=len(DEVICE_CODES))
                for mi in np.nonzero(counts)[0]:
                    metrics.GLOBAL.record_mutator(
                        DEVICE_CODES[mi], applied=True, n=int(counts[mi])
                    )
            bs = bucket_stats.setdefault(
                b.capacity,
                {"batches": 0, "rows": 0, "pad_rows": 0,
                 "padded_bytes_wasted": 0},
            )
            bs["batches"] += 1
            bs["rows"] += b.rows
            bs["pad_rows"] += b.pad_rows
            bs["padded_bytes_wasted"] += b.padded_bytes_wasted
            metrics.GLOBAL.record_bucket(
                b.capacity, b.rows, b.pad_rows, b.padded_bytes_wasted
            )
        dev_s = time.perf_counter() - t_dev
        scores = scores_np

        # novelty feedback: a never-seen output hash is the cheap
        # stand-in for new coverage — the source seed earns energy
        for slot in range(batch):
            payload = results.get(slot, b"")
            case_bytes += len(payload)
            h = _out_hash(payload)
            if h not in seen_hashes:
                seen_hashes.add(h)
                new_hashes += 1
                store.apply_event(fb.Event("new_hash", ids[slot]))
            if writer is not None:
                writer(case * batch + slot, payload, [])
            else:
                sys.stdout.buffer.write(payload)
        total += len(results)
        metrics.GLOBAL.record_batch(len(results), case_bytes, dev_s)

        # external feedback (monitors/proxy/faas) folds in at the case
        # boundary; anonymous events credit this case's seeds
        if consume_feedback:
            credit = sorted(set(ids))
            for ev in bus.drain():
                store.apply_event(ev, credit=credit)
                logger.log("decision", "corpus: %s event from %s -> "
                           "energy feedback", ev.kind, ev.source or "?")

        if stats is not None:
            stats.setdefault("finish_times", []).append(time.perf_counter())
            stats.setdefault("schedules", []).append(list(ids))
        if state_path and ((case + 1 - start_case) % ckpt_every == 0
                           or case + 1 == n_cases):
            save_state(state_path, opts["seed"], case + 1, scores,
                       corpus_energies=store.energies())
            store.save()

    store.save()
    dt = time.perf_counter() - t0
    if truncated:
        print(f"# {truncated} scheduled samples exceeded the device "
              f"budget ({device_max}B) and were truncated", file=sys.stderr)
    if stats is not None:
        stats.update(total=total, dt=dt, batch=batch,
                     buckets=bucket_stats, new_hashes=new_hashes,
                     store_stats=store.stats())
    logger.log("info", "corpus backend: %d samples in %.2fs "
               "(%.0f samples/s), %d novel output hashes",
               total, dt, total / max(dt, 1e-9), new_hashes)
    waste = sum(b["padded_bytes_wasted"] for b in bucket_stats.values())
    rows = sum(b["rows"] for b in bucket_stats.values())
    print(
        f"# {total} samples, {dt:.2f}s, {total / max(dt, 1e-9):.0f} "
        f"samples/s, {new_hashes} novel hashes, "
        f"{len(bucket_stats)} buckets, "
        f"{waste / max(rows, 1):.0f} padded bytes wasted/sample",
        file=sys.stderr,
    )
    return 0
