"""Feedback-driven batch loop: store -> energy schedule -> buckets ->
device mutation -> feedback, the closed-loop counterpart of
services/batchrunner.py's open-loop path.

Per case:
  1. the energy scheduler draws `batch` seeds (weighted, counter-keyed)
  2. the assembler groups them into power-of-two length buckets
  3. each bucket rides one jitted fuzz_batch call (device mutator set)
  4. outputs are hashed: a never-seen output hash bumps the source
     seed's energy (the cheap novelty signal standing in for coverage)
  5. the feedback bus is drained; monitor/proxy events promote the
     seeds that were in flight
  6. energies are checkpointed alongside the scheduler scores so a
     resumed run schedules identically

Execution pipelines (--pipeline, default async):

  sync   the serialized baseline: every bucket's outputs are forced to
         host before the next bucket dispatches, and hashing/writing
         happen inline between cases.
  async  double-buffered: bucket steps dispatch without blocking (JAX
         async dispatch), the score table stays DEVICE-resident across
         buckets (gather/scatter on device — no host round-trip per
         bucket), bucket N+1's panel is assembled on the host while
         bucket N computes, and a drain worker thread forces completed
         futures, hashes outputs and writes results while the main
         thread dispatches the next case.

Determinism contract (the -s replay guarantee): every schedule draw is
keyed on (seed, case, TAG_SCHED), device keys on (seed, case, slot), and
energies evolve only from deterministic inputs applied at case
boundaries — so at a fixed seed, two runs produce byte-identical
schedules and outputs, and sync/async produce byte-identical streams
(the pipeline moves WHEN work happens, never WHAT is computed: hash
events apply in the same bucket-dispatch + slot order, and the drain
worker signals "events applied" before the next schedule draws).
External bus events are inherently timing-dependent; they are folded in
at the same case boundary, so replay holds whenever the event stream is
(e.g. absent, or injected at fixed cases as the tests do).

Device-loss degradation (services/resilience.py story): an XLA runtime
error anywhere in the pipeline — a real device abort or an injected
``device.step`` fault (services/chaos.py) — used to kill the whole run.
Now it flips the runner into a flagged DEGRADED mode: in-flight futures
are abandoned, un-finished cases are re-served by the host oracle engine
(deterministic per (seed, case, slot), though not byte-identical to the
device stream — degraded mode trades the device's exact output for
availability), and every DEVICE_PROBE_EVERY cases the runner probes the
device; a successful probe resumes the device pipeline. The transition
is visible as metrics events (device_lost / device_recovered) and the
``degraded`` flag in metrics snapshots and the faas stats op.

Coverage feedback (--coverage, r16): when a CoverageHub
(services/monitors.py) is wired in through opts["coverage_hub"], the
runner records every scheduled case in a SampleLedger, pulls the case's
buffered edge bitmaps off the hub at the case boundary, and folds them
through corpus/distill.CoverageIndex (ops/coverage.py kernels, numpy
oracles when degraded). A slot WITH a map gates adoption and energy on
genuinely-new edges (``new_cov`` events) instead of output-hash
novelty; a slot WITHOUT one keeps the exact baseline hash path. Hub
death — monitor killed, listener lost, or an injected monitor.ingest
fault tripping its breaker — degrades the run STICKILY to pure
hash-novelty (coverage_lost event, coverage-degraded flag): flickering
coverage would make adoption depend on reconnect timing, which the -s
replay contract forbids. A degraded or coverage-off run is
byte-identical to the r15 hash-novelty stream.
"""

from __future__ import annotations

import hashlib
import queue
import sys
import threading
import time

import numpy as np

from ..obs import trace
from ..services import chaos, logger, metrics, out
from . import feedback as fb
from .assembler import Bucket, bucket_capacity, materialize, plan_buckets
from .energy import EnergyScheduler
from .store import CorpusStore

PIPELINES = ("sync", "async")

# corpus memory layouts (--layout): buckets re-assembles and re-uploads
# pow2-padded panels per case (the default until arena parity is proven
# on real hardware); arena keeps seed bytes device-resident in fixed-size
# pages (corpus/arena.py) and addresses each case through a page table —
# one compiled step shape, ~zero padded waste, seeds cross PCIe once
LAYOUTS = ("buckets", "arena")

# degraded mode probes the device for recovery every N cases
DEVICE_PROBE_EVERY = 4


def _out_hash(data: bytes) -> bytes:
    return hashlib.sha1(data).digest()[:12]


class _DrainWorker:
    """Orders completed cases behind the device: one thread consuming
    submitted cases FIFO, so hashing/writing of case N overlaps the main
    thread's schedule/assemble/dispatch of case N+1.

    The first exception raised by the process callback is captured and
    re-raised in the MAIN thread (from wait_done/close) — a dead drain
    must fail the run, not silently stop consuming.

    Shared with the fleet coordinator (corpus/fleet.py), whose
    overlapped reduce runs the whole per-case merge as the process
    callback and rebuilds the worker at a rewind — the FIFO + in-order
    mark_done contract is what keeps N-shard == 1-shard byte-identity
    intact there."""

    def __init__(self, process, start_case: int, discard=None):
        self._process = process
        self._discard = discard  # best-effort cleanup for abandoned items
        self._q: queue.Queue = queue.Queue()
        self._cv = threading.Condition()
        self._done_case = start_case - 1
        self._abandoned = False
        self.error: BaseException | None = None
        #: the in-flight item whose processing raised `error` — the
        #: fleet's slice-granular rewind re-serves only its dead slices
        #: instead of replaying the whole window (one case is in flight
        #: at a time, so this is the only outstanding work at an error)
        self.failed_item = None
        self._t = threading.Thread(target=self._run, name="corpus-drain",
                                   daemon=True)
        self._t.start()

    @property
    def done_case(self) -> int:
        """Highest case whose events/writes have fully landed."""
        with self._cv:
            return self._done_case

    def submit(self, item):
        metrics.GLOBAL.record_drain_backlog(self._q.qsize() + 1)
        self._q.put(item)

    def mark_done(self, case: int):
        """Called by the process callback once the case's energy events
        are applied — the point after which the next schedule may draw."""
        with self._cv:
            self._done_case = case
            self._cv.notify_all()

    def wait_done(self, case: int):
        """Block until `case`'s events are applied (or the worker died)."""
        with self._cv:
            while (self._done_case < case and self.error is None
                   and not self._abandoned):
                self._cv.wait()
        if self.error is not None:
            raise self.error

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            if self._abandoned:
                # flush the queue: its futures are poisoned, but settle
                # them best-effort so no async work trails the fallback
                if self._discard is not None:
                    self._discard(item)
                continue
            try:
                self._process(item)
            except BaseException as e:  # lint: broad-except-ok surfaced to main via _cv
                with self._cv:
                    self.failed_item = item
                    self.error = e
                    self._cv.notify_all()
                return

    def abandon(self):
        """Detach on device loss: stop at the next queue item, swallow the
        (already-diagnosed) error, wake any waiter. The un-processed
        cases are the caller's to re-serve (done_case marks the last one
        whose effects landed)."""
        with self._cv:
            self._abandoned = True
            self._cv.notify_all()
        self._q.put(None)

    def close(self, join: bool = True):
        self._q.put(None)
        if join:
            self._t.join()
        if self.error is not None:
            raise self.error


def run_corpus_batch(opts: dict, batch: int = 1024) -> int:
    """The --corpus DIR --feedback entry point."""
    import jax
    import jax.numpy as jnp

    from ..constants import CAPACITY_CLASSES
    from ..oracle.mutations import default_mutations
    from ..ops import prng
    from ..ops.buffers import Batch, scan_bound, unpack
    from ..ops.pipeline import (drain_futures, is_device_error,
                                make_class_fuzzer, step_async)

    shards = opts.get("shards")
    if shards is not None or opts.get("fleet_nodes") or opts.get("spmd"):
        # --shards N / --fleet-nodes / --spmd routes the whole run
        # through the elastic fleet coordinator (corpus/fleet.py):
        # per-shard arenas (or remote workers over dist), breaker-aware
        # placement, live redistribution on shard loss; --spmd fuses
        # the local shards into one shard_map program per class
        from .fleet import run_corpus_fleet

        return run_corpus_fleet(opts, batch=batch)
    from ..ops.registry import DEVICE_CODES
    from ..ops.scheduler import init_scores
    from ..services.checkpoint import (load_corpus_energies,
                                       load_coverage_maps, load_state,
                                       quarantine_mismatch, save_state)

    pipeline = str(opts.get("pipeline") or "async")
    if pipeline not in PIPELINES:
        raise ValueError(f"pipeline must be one of {PIPELINES}, "
                         f"got {pipeline!r}")
    use_async = pipeline == "async"
    layout = str(opts.get("layout") or "buckets")
    if layout not in LAYOUTS:
        raise ValueError(f"layout must be one of {LAYOUTS}, "
                         f"got {layout!r}")
    use_arena = layout == "arena"
    # --adopt: novel outputs join the corpus as first-class seeds (capped
    # per case). The DECISION is layout-independent — first never-seen
    # hash wins, in slot order — so buckets and arena grow identical
    # stores at a fixed -s; the arena layout additionally adopts the
    # bytes device-side (DeviceArena.adopt_pending) so only hashes and
    # lengths cross PCIe for adopted offspring.
    adopt_on = bool(opts.get("adopt"))
    adopt_cap = int(opts.get("adopt_cap") or 64)

    store = CorpusStore(opts["corpus_dir"])
    # recovery fsck: a previous crash can leave corpus.json and seeds/
    # disagreeing (entries without files, orphaned/corrupt files) — heal
    # the store before the scheduler indexes into it
    fsck = store.fsck()
    if fsck["missing"] or fsck["corrupt"] or fsck["orphans"]:
        print(f"# corpus fsck: {fsck['ok']} ok, {fsck['missing']} missing, "
              f"{fsck['corrupt']} corrupt, {fsck['orphans']} orphaned",
              file=sys.stderr)
    direct = opts.get("corpus")
    if direct is not None:
        # in-process callers (bench corpus stage, tests) hand seeds over
        # directly instead of staging files
        for s in direct:
            store.add(s, origin="direct")
    else:
        paths = opts.get("paths") or []
        paths = [p for p in paths if p != "-"]
        if paths:
            from ..oracle.gen import _expand_paths

            expanded = (_expand_paths(paths) if opts.get("recursive")
                        else paths)
            new, dup, skipped = store.add_paths(expanded)
            print(f"# corpus: {new} new, {dup} duplicate, "
                  f"{skipped} skipped -> {len(store)} seeds in store",
                  file=sys.stderr)
    gen_opts = opts.get("gen")
    gen_engine = None
    if gen_opts:
        # r17 generate-then-mutate: seed the campaign from ONE batched
        # device expansion of the compiled grammar. Generated rows enter
        # the store like any other seed and the existing gather→mutate→
        # score loop takes over — zero per-case host expansion on the
        # hot path. Device loss (or an injected gen.expand fault)
        # degrades the expansion to the keyed host oracle per
        # (case, slot), byte-identically, so the campaign is the same
        # either way.
        from ..gen import GenEngine, compile_grammar

        cg = gen_opts.get("compiled")
        if cg is None:
            cg = compile_grammar(gen_opts["grammar"],
                                 source=gen_opts.get("label", "--gen"))
        gen_engine = GenEngine(cg, opts["seed"],
                               fuzz=bool(gen_opts.get("fuzz")))
        gen_n = int(gen_opts.get("n") or 64)
        payloads, gen_trunc = gen_engine.expand(case_idx=0, n=gen_n)
        gen_added = 0
        for p in payloads:
            if p:
                _sid, fresh = store.add(p, origin="gen")
                gen_added += int(fresh)
        print(f"# gen: {len(payloads)} samples from grammar "
              f"{cg.source} -> {gen_added} new seeds"
              f" ({gen_trunc} truncated)"
              f"{', host-degraded' if gen_engine.degraded else ''}",
              file=sys.stderr)
    if len(store) == 0:
        print("no corpus (store empty and no readable seeds)",
              file=sys.stderr)
        return 1

    selected = dict(opts.get("mutations") or default_mutations())
    pri = [max(selected.get(code, 0), 0) for code in DEVICE_CODES]
    if not any(pri):
        print("none of the selected mutations runs on the TPU backend; "
              f"device set: {','.join(DEVICE_CODES)}", file=sys.stderr)
        return 1

    device_max = int(opts.get("device_capacity_max", CAPACITY_CLASSES[-1]))
    sched = EnergyScheduler(store, opts["seed"])
    # async: donate the bucket panel + gathered score rows (fresh buffers
    # every step) so the compiled program writes outputs in place
    step = make_class_fuzzer(mutator_pri=pri,
                             donate="auto" if use_async else False)
    base = prng.base_key(opts["seed"])
    scores = init_scores(jax.random.fold_in(base, 999), batch)
    bus = opts.get("feedback_bus", fb.GLOBAL)
    consume_feedback = bool(opts.get("feedback"))

    # r16 coverage plane: the hub buffers connect-back edge bitmaps off
    # the wire; the runner folds them at case boundaries (never from
    # monitor threads — the determinism contract) and gates per-slot
    # adoption/energy on genuinely-new edges. The ledger maps
    # (case, slot) back to the scheduled seed for the fold and for any
    # monitor that can name the sample that provoked a signal.
    hub = opts.get("coverage_hub")
    coverage_on = bool(opts.get("coverage")) and hub is not None
    distill_on = bool(opts.get("distill"))
    ledger = fb.SampleLedger()
    cov = None
    cov_live = False
    if coverage_on:
        from .distill import CoverageIndex

        cov = CoverageIndex(map_bytes=hub.map_bytes, use_device=True)
        cov_live = True

    arena = None
    trunc_cap = device_max  # truncation threshold (both layouts)

    def _seed_arena(tick):
        """Upload every stored seed once — after this, scheduling a seed
        costs a page-table row, not a PCIe copy."""
        with trace.span("corpus.arena.seed", seeds=len(store), tick=tick):
            for sid in store.ids():
                arena.ensure(sid, store.get(sid), tick)
            arena.flush()

    if use_arena:
        from ..ops import paged
        from .arena import (RESERVED_PAGES, DeviceArena, fit_page_classes,
                            resolve_classes)

        # RAGGED rows over one physical page size: a small ascending set
        # of capacity classes, each with its own page table and compiled
        # step shape (--arena-classes; "auto" derives the exact bucket
        # capacities of the stored seeds). The fused engine's streams
        # are a function of the static row width (ops/pipeline.py ENGINE
        # VERSION NOTES), so arena==buckets byte-identity holds exactly
        # when every seed's class equals its bucket capacity — the auto
        # configuration, which the tests pin and README documents.
        sizes = [len(store.get(sid)) for sid in store.ids()]
        if not sizes:
            print("no corpus seeds to page into the arena",
                  file=sys.stderr)
            return 1
        classes = resolve_classes(opts.get("arena_classes"), sizes,
                                  device_max)
        trunc_cap = classes[-1]
        page_opt = int(opts.get("arena_page") or paged.PAGE)
        # the page must divide every class width exactly — otherwise a
        # class's rows come up narrower than its capacity (shape
        # mismatch on any spill overlay)
        page = fit_page_classes(page_opt, classes)
        if page != page_opt:
            print(f"# arena: page size {page_opt} does not fit the "
                  f"capacity classes {classes}, using {page}",
                  file=sys.stderr)
        # max(1, ...) matches PageAllocator.pages_for: a zero-length
        # seed still occupies one page
        need = sum(max(1, -(-min(n, trunc_cap) // page)) for n in sizes)
        num_pages = int(opts.get("arena_pages")
                        or RESERVED_PAGES + max(64, 2 * need))
        num_pages = max(num_pages, RESERVED_PAGES + classes[0] // page)
        # class routing mirrors the bucket assembler's slack exactly: a
        # seed WANTS its bucket capacity and lands in the smallest class
        # that satisfies it (longer routes UP, never silently down)
        arena = DeviceArena(
            num_pages, page=page, donate="auto" if use_async else False,
            classes=classes,
            classify=lambda n: bucket_capacity(n, device_max=device_max),
        )
        _seed_arena(tick=-1)
        # store-admission hook: seeds added mid-run (faas/monitors,
        # adopted offspring) queue here and upload at the next case
        # boundary — unless device-side adoption already landed them
        store.listener = arena.enqueue

    n_cases = opts.get("n", 1)
    start_case = 0
    ckpt_every = max(1, int(opts.get("checkpoint_every", 1)))
    state_path = opts.get("state_path")
    if state_path:
        import os as _os

        from ..ops.registry import NUM_DEVICE_MUTATORS

        if _os.path.exists(state_path):
            st = load_state(state_path)
            if st is None:
                print("# checkpoint unreadable, starting fresh",
                      file=sys.stderr)
            else:
                ck_seed, ck_case, ck_scores, _hs, _hsp = st
                cov_verdict, cov_snap = "absent", None
                if cov is not None:
                    # kind-stamped coverage fields: "absent" (pre-r16
                    # checkpoint) resumes with fresh empty coverage,
                    # "mismatch" (wrong kind/version/width) means the
                    # file belongs to a different configuration
                    cov_verdict, cov_snap = load_coverage_maps(
                        state_path, cov.map_bytes)
                if (ck_seed != tuple(opts["seed"])
                        or ck_scores.shape != (batch, NUM_DEVICE_MUTATORS)
                        or cov_verdict == "mismatch"):
                    # the mismatched file belongs to a DIFFERENT run:
                    # park it at .bak so that run can still resume from
                    # it, instead of burying it under this run's first
                    # save (tests pin the quarantine)
                    quarantine_mismatch(state_path)
                    print("# checkpoint mismatch (seed/shape/coverage), "
                          "starting fresh (original kept as .bak)",
                          file=sys.stderr)
                else:
                    start_case = ck_case
                    scores = jnp.asarray(ck_scores)
                    energies = load_corpus_energies(state_path)
                    if energies:
                        store.restore_energies(energies)
                    if cov_snap is not None:
                        cov.restore(cov_snap)
                    print(f"# resumed at case {start_case} "
                          f"({len(energies or {})} seed energies restored)",
                          file=sys.stderr)
        if start_case >= n_cases:
            print(f"# run already complete ({start_case}/{n_cases} cases)",
                  file=sys.stderr)
            return 0

    # r13 struct engine (--struct): the closed-loop runner gains the
    # span-splice mutators as a routed per-case overlay. A one-pass
    # tokenizer runs at store ADMISSION (seeds now, offspring when
    # store.add fires the chained listener below — adoption re-tokenizes
    # for free), the StructRouter picks a deterministic routed subset per
    # case (neutral device mass — the live score table stays
    # device-resident, and forcing it per case would add a sync), and the
    # routed rows ride one extra vmapped step ('device') or the numpy
    # span-oracle ('host', the parity path). Outputs stay sync==async
    # byte-identical: routing is a pure function of (seed, case,
    # scheduled samples) and overlay order is slot order.
    struct_mode = str(opts.get("struct") or "off")
    if struct_mode not in ("off", "host", "device"):
        raise ValueError(f"struct must be one of off/host/device, "
                         f"got {struct_mode!r}")
    struct_router = None
    struct_step = None
    span_cache = None
    from ..ops import registry as _registry
    from ..ops import structure as stm

    _struct_flag_before = _registry.struct_kernels_enabled()
    if struct_mode != "off":
        _registry.set_struct_kernels(True)
        span_cache = stm.SpanCache()
        struct_router = stm.StructRouter(opts["seed"], selected)
        if struct_mode == "device":
            from ..ops.tree_mutators import make_struct_step

            struct_step = make_struct_step()
        # chain the admission listener (after the arena installed its
        # own): every seed that enters the store — initial corpus,
        # monitors, adopted offspring — gets its span table the moment
        # its bytes are known
        _prev_listener = store.listener

        def _struct_admit(sid, _prev=_prev_listener):
            span_cache.note(sid, store.get(sid))
            if _prev is not None:
                _prev(sid)

        store.listener = _struct_admit
        for sid in store.ids():
            span_cache.note(sid, store.get(sid))

    writer, _mt = out.string_outputs(opts.get("output", "-"))
    stats = opts.get("_stats")  # caller-owned dict for measured numbers
    seen_hashes: set[bytes] = set()
    bucket_stats: dict[int, dict] = {}
    # tallies the drain worker owns in async mode (main reads after join)
    tallies = {"truncated": 0, "total": 0, "new_hashes": 0,
               "bytes_uploaded": 0, "offspring": 0, "struct_routed": 0,
               "cov_maps": 0, "cov_new_edges": 0}
    # distinct (rows, capacity, scan_len) triples the jitted step saw —
    # the compiled-program count the arena drives to O(1)
    step_shapes: set[tuple] = set()

    # sync mode keeps the score table host-resident. One conversion for
    # the whole run — per bucket only that bucket's ROWS are gathered and
    # scattered, never the full [batch, M] table (the pre-r6 path copied
    # the entire table every case).
    if not use_async:
        scores = np.array(scores)

    def _dispatch_arena(case, ids, samples, scores_in):
        """Arena layout's dispatch: adopt queued offspring and admit
        queued seeds, then build one page table PER CAPACITY CLASS (the
        cheap host half riding the async pipeline's assemble slot) and
        run one ragged step per class — each gather reads only its rows'
        live pages, no padding to the widest resident seed, no per-case
        seed re-upload. Spilled rows (arena full / injected arena.spill
        fault) are overlaid from host bytes, which costs an upload but
        never changes output bytes. Slot keying, row padding, score
        gather/scatter and scan bounds mirror the bucket path row for
        row, so arena==buckets byte-identity holds whenever class caps
        equal bucket caps."""
        t_a = time.perf_counter()
        with trace.span("corpus.assemble", case=case):
            if adopt_on:
                arena.adopt_pending(tick=case)
            arena.drain_pending(store.get, tick=case)
            arena.maybe_defrag()
            groups = arena.tables_for(ids, samples, tick=case)
        t_d = time.perf_counter()
        launched = []
        scores_out = scores_in
        dispatch_s = 0.0
        try:
            for g in groups:
                t_g = time.perf_counter()
                chaos.fault_point("device.step")
                k = int(g.rows.shape[0])
                kp = max(8, 1 << (k - 1).bit_length())
                # cyclic row padding, exactly like materialize(): pad
                # rows repeat real rows (shape-valid, outputs discarded)
                pad = np.arange(kp, dtype=np.int32) % k
                table_p = g.table[pad]
                lens_p = g.lens[pad]
                data = arena.gather(table_p)
                if g.spilled:
                    # pow2-padded overlay rows keep the compiled set
                    # bounded; padding repeats the first spilled row —
                    # idempotent, the same bytes land twice
                    ks = len(g.spilled)
                    ksp = max(8, 1 << (ks - 1).bit_length())
                    rows_idx = np.asarray(
                        (g.spilled + [g.spilled[0]] * (ksp - ks))[:ksp],
                        np.int32)
                    panel = np.zeros((ksp, g.capacity), np.uint8)
                    for j, r in enumerate(g.spilled):
                        s = samples[int(g.rows[r])][:g.capacity]
                        panel[j, :len(s)] = np.frombuffer(s, np.uint8)
                    panel[ks:] = panel[0]
                    data = data.at[rows_idx].set(panel)
                    tallies["bytes_uploaded"] += (panel.nbytes
                                                  + rows_idx.nbytes)
                # keys derive from the SLOT position (0..batch-1), pad
                # rows get out-of-range indices — identical to the
                # bucket path's contract
                idx = np.concatenate([
                    g.rows, batch + np.arange(kp - k, dtype=np.int32)
                ]).astype(np.int32)
                gather = g.rows[pad]
                sc_in = (jnp.take(scores_out, jnp.asarray(gather), axis=0)
                         if use_async else scores_out[gather])
                sl = scan_bound(int(g.lens.max()), g.capacity)
                tallies["bytes_uploaded"] += (table_p.nbytes
                                              + lens_p.nbytes + idx.nbytes)
                step_shapes.add((kp, g.capacity, sl))
                with trace.span("corpus.dispatch", case=case,
                                capacity=g.capacity, rows=k):
                    fut = step_async(step, base, case, idx, data, lens_p,
                                     sc_in, scan_len=sl)
                if use_async:
                    scores_out = scores_out.at[jnp.asarray(g.rows)].set(
                        fut.scores[:k]
                    )
                else:
                    scores_out[g.rows] = np.asarray(fut.scores)[:k]
                # shape-only placeholder panel: process_case never reads
                # bucket data (outputs come from the future), and
                # holding the donated working buffer in the work item
                # would pin device memory
                b = Bucket(capacity=g.capacity, slots=g.rows,
                           data=np.zeros((k, 0), np.uint8), lens=g.lens,
                           rows=k, padded_bytes_wasted=0)
                launched.append((b, fut))
                dispatch_s += time.perf_counter() - t_g
        except BaseException:  # lint: broad-except-ok re-raised after settling in-flight futures
            # a fault on class K's dispatch must not strand the earlier
            # classes' in-flight futures (mirrors the bucket path)
            drain_futures(fut for _b, fut in launched)
            raise
        metrics.GLOBAL.record_stage("assemble", t_d - t_a)
        metrics.GLOBAL.record_stage("dispatch", dispatch_s)
        return ids, launched, scores_out, dispatch_s

    def _dispatch_struct(case, ids, samples):
        """Route and dispatch this case's struct overlay: returns
        ([(slot, code_idx)], work) where work is the in-flight device
        (out, lens, applied) triple ('device', JAX async dispatch) or an
        already-computed {slot: bytes} dict ('host'). Oversized samples
        (> trunc_cap) never struct-route — the bucket path truncates
        them and the span table describes the UNtruncated bytes."""
        if struct_router is None:
            return [], None
        struct_router.prepare(samples, span_cache, keys=ids)
        excl = np.asarray([len(s) > trunc_cap for s in samples], bool)
        codes = struct_router.route(case, excluded=excl)
        routed = [(slot, int(c)) for slot, c in enumerate(codes) if c >= 0]
        if not routed:
            return [], None
        tallies["struct_routed"] += len(routed)
        caps = np.asarray(
            [bucket_capacity(len(samples[slot]), device_max=trunc_cap)
             for slot, _ in routed], np.int32)
        if struct_step is None:
            res = {}
            for (slot, ci), cap in zip(routed, caps):
                nd, cnt = span_cache.get(ids[slot], samples[slot])
                key = stm.struct_sample_key(base, case, slot)
                res[slot] = stm.host_struct_fuzz(key, samples[slot], nd,
                                                 int(cnt), ci, int(cap))
            return routed, res
        # pow2-padded panel of just the routed rows (the scheduled set
        # changes every case, so unlike the batchrunner's resident panel
        # the routed BYTES ride along — still a ~8%-of-batch upload, not
        # a per-sample host round-trip); pad rows carry code -1
        k = len(routed)
        kp = max(8, 1 << (k - 1).bit_length())
        width = int(caps.max())
        panel = np.zeros((kp, width), np.uint8)
        lens = np.zeros(kp, np.int32)
        nds = np.zeros((kp, stm.SPAN_NODES, 4), np.int32)
        cnts = np.zeros(kp, np.int32)
        caps_p = np.full(kp, width, np.int32)
        caps_p[:k] = caps
        slots_arr = np.concatenate([
            np.asarray([slot for slot, _ in routed], np.int32),
            batch + np.arange(kp - k, dtype=np.int32),
        ])
        cds = np.concatenate([
            np.asarray([c for _, c in routed], np.int32),
            np.full(kp - k, -1, np.int32),
        ])
        for p, (slot, _c) in enumerate(routed):
            raw = samples[slot]
            panel[p, :len(raw)] = np.frombuffer(raw, np.uint8)
            lens[p] = len(raw)
            nds[p], cnts[p] = span_cache.get(ids[slot], raw)
        tallies["bytes_uploaded"] += (panel.nbytes + lens.nbytes
                                      + nds.nbytes + cnts.nbytes
                                      + caps_p.nbytes + slots_arr.nbytes
                                      + cds.nbytes)
        with trace.span("corpus.struct_dispatch", case=case, rows=k):
            work = struct_step(base, case, slots_arr, panel, lens, nds,
                               cnts, caps_p, cds)
        return routed, work

    def dispatch_case(case, scores_in):
        """Schedule, assemble and dispatch every bucket of one case.

        async: steps dispatch without blocking, scores gather/scatter on
        device, and each bucket's panel is materialized WHILE the
        previous bucket's step runs (JAX async dispatch returns before
        the compute finishes). sync: each bucket is forced to host
        before the next dispatch — the serialized baseline.
        Returns (ids, launched, scores_out)."""
        t_s = time.perf_counter()
        with trace.span("corpus.schedule", case=case):
            ids = sched.schedule(case, batch)
            # attribution ledger BEFORE launch: monitors and the
            # coverage fold resolve (case, slot) -> seed through it
            ledger.record(case, ids)
            samples = [store.get(sid) for sid in ids]
            plans = (None if use_arena
                     else plan_buckets(samples, device_max=device_max))
        metrics.GLOBAL.record_stage("schedule", time.perf_counter() - t_s)
        trunc = sum(len(s) > trunc_cap for s in samples)
        if trunc:
            tallies["truncated"] += trunc
            metrics.GLOBAL.record_truncated(trunc)
        # struct overlay dispatches FIRST so its device work overlaps the
        # bucket/arena assembly below (JAX async dispatch)
        struct_rows, struct_work = _dispatch_struct(case, ids, samples)
        if use_arena:
            ids, launched, scores_out, dispatch_s = _dispatch_arena(
                case, ids, samples, scores_in)
            return (ids, launched, scores_out, dispatch_s, struct_rows,
                    struct_work)

        launched = []
        scores_out = scores_in
        assemble_s = dispatch_s = 0.0
        try:
            for plan in plans:
                t_a = time.perf_counter()
                with trace.span("corpus.assemble", case=case,
                                capacity=plan.capacity):
                    b = materialize(plan, samples)
                t_d = time.perf_counter()
                chaos.fault_point("device.step")
                # keys derive from the SLOT position (0..batch-1) so a
                # sample's stream is a pure function of (seed, case, slot)
                # no matter how the buckets partition the batch; pad rows get
                # out-of-range indices — their outputs are discarded
                idx = np.concatenate([
                    b.slots, batch + np.arange(b.pad_rows, dtype=np.int32)
                ]).astype(np.int32)
                gather = b.slots[np.arange(b.rows_padded) % b.rows]
                sc_in = (jnp.take(scores_out, gather, axis=0) if use_async
                         else scores_out[gather])
                sl = scan_bound(int(b.lens[:b.rows].max()), b.capacity)
                tallies["bytes_uploaded"] += (b.data.nbytes + b.lens.nbytes
                                              + idx.nbytes)
                step_shapes.add((b.rows_padded, b.capacity, sl))
                with trace.span("corpus.dispatch", case=case,
                                capacity=b.capacity, rows=b.rows):
                    fut = step_async(
                        step, base, case, idx, b.data, b.lens, sc_in,
                        scan_len=sl,
                    )
                if use_async:
                    scores_out = scores_out.at[jnp.asarray(b.slots)].set(
                        fut.scores[:b.rows]
                    )
                else:
                    scores_out[b.slots] = np.asarray(fut.scores)[:b.rows]
                launched.append((b, fut))
                t_e = time.perf_counter()
                assemble_s += t_d - t_a
                dispatch_s += t_e - t_d
        except BaseException:  # lint: broad-except-ok re-raised after settling in-flight futures
            # a fault on bucket K's dispatch must not strand buckets
            # 1..K-1's in-flight futures: settle them before the
            # device-loss path (or the caller) touches device state
            drain_futures(fut for _b, fut in launched)
            raise
        metrics.GLOBAL.record_stage("assemble", assemble_s)
        metrics.GLOBAL.record_stage("dispatch", dispatch_s)
        return ids, launched, scores_out, dispatch_s, struct_rows, struct_work

    class _CaseWork:
        __slots__ = ("case", "ids", "launched", "scores", "dispatch_s",
                     "struct_rows", "struct_work")

        def __init__(self, case, ids, launched, scores, dispatch_s,
                     struct_rows=(), struct_work=None):
            self.case = case
            self.ids = ids
            self.launched = launched
            self.scores = scores
            self.dispatch_s = dispatch_s
            self.struct_rows = struct_rows
            self.struct_work = struct_work

    drain: _DrainWorker | None = None

    def finish_case(case, ids, results, ckpt_scores, device_seconds,
                    devsrc=None):
        """The order-dependent tail every case runs — hashing (slot walk
        0..batch-1, identical in sync/async/degraded), offspring
        adoption, energy events, bus drain, writes and checkpointing —
        shared by the device drain path and the degraded oracle path.

        `devsrc` maps slot -> (device output buffer, row) when the
        outputs are still device-resident (arena layout): an adopted
        offspring then queues for DeviceArena.adopt_pending and its
        payload bytes never cross back over PCIe."""
        nonlocal cov_live
        # coverage pre-pass: pull this case's buffered bitmaps off the
        # hub and fold them (runner/drain thread, case boundary). Hub
        # death is STICKY — once lost, the rest of the run is pure
        # hash-novelty, so adoption never depends on reconnect timing.
        slot_gain: dict[int, int] = {}
        if cov is not None and cov_live:
            if not hub.alive():
                cov_live = False
                logger.log("warning", "corpus: coverage hub lost at case "
                           "%d — degrading to hash-novelty", case)
                metrics.GLOBAL.record_event("coverage_lost")
                metrics.GLOBAL.set_coverage_degraded(True)
            else:
                frames = hub.take(case)
                covered = [s for s in sorted(frames) if s < batch]
                pairs = [(ledger.resolve(case, s) or ids[s], frames[s])
                         for s in covered]
                t_f = time.perf_counter()
                try:
                    with trace.span("coverage.fold", case=case,
                                    maps=len(pairs)):
                        gains = cov.fold_case(pairs)
                except OSError as e:
                    # injected coverage.fold fault: the whole case is
                    # treated as uncovered — observable, never diverging
                    # from the hash-novelty baseline
                    logger.log("warning", "corpus: coverage fold failed "
                               "at case %d (%s) — case uncovered", case, e)
                    metrics.GLOBAL.record_coverage_frame("faulted")
                else:
                    if covered:
                        slot_gain = dict(zip(covered, gains))
                        new_edges = int(sum(gains))
                        metrics.GLOBAL.record_coverage_fold(
                            len(pairs), new_edges, cov.edges())
                        tallies["cov_maps"] += len(pairs)
                        tallies["cov_new_edges"] += new_edges
                finally:
                    metrics.GLOBAL.record_stage(
                        "coverage", time.perf_counter() - t_f)

        # novelty feedback: a slot WITH a coverage map admits on
        # genuinely-new edges (new_cov energy); a slot without one keeps
        # the hash-novelty stand-in byte-for-byte. seen_hashes is still
        # recorded for covered slots so a later degradation cannot
        # re-count their outputs as novel.
        t_h = time.perf_counter()
        case_bytes = 0
        case_adopted = 0
        with trace.span("corpus.hash", case=case):
            for slot in range(batch):
                payload = results.get(slot, b"")
                case_bytes += len(payload)
                h = _out_hash(payload)
                novel_hash = h not in seen_hashes
                if novel_hash:
                    seen_hashes.add(h)
                    tallies["new_hashes"] += 1
                if slot in slot_gain:
                    admit = slot_gain[slot] > 0
                    if admit:
                        store.apply_event(fb.Event("new_cov", ids[slot]))
                else:
                    admit = novel_hash
                    if admit:
                        store.apply_event(fb.Event("new_hash", ids[slot]))
                if admit and adopt_on and payload \
                        and case_adopted < adopt_cap:
                    # the store decides (dedup by content hash);
                    # store.add fires the arena's listener, and the
                    # device path below turns that host upload into
                    # a no-op when the scatter wins
                    sid_new, added = store.add(payload,
                                               origin="offspring")
                    if added:
                        case_adopted += 1
                        tallies["offspring"] += 1
                        if devsrc is not None and slot in devsrc:
                            src, row = devsrc[slot]
                            arena.enqueue_adopt(sid_new, len(payload),
                                                src, row)
        tallies["total"] += len(results)
        metrics.GLOBAL.record_stage("hash", time.perf_counter() - t_h)
        metrics.GLOBAL.record_batch(len(results), case_bytes,
                                    device_seconds)
        metrics.GLOBAL.record_routed_total(len(results))

        # external feedback (monitors/proxy/faas) folds in at the case
        # boundary; anonymous events credit this case's seeds
        if consume_feedback:
            credit = sorted(set(ids))
            for ev in bus.drain():
                store.apply_event(ev, credit=credit)
                logger.log("decision", "corpus: %s event from %s -> "
                           "energy feedback", ev.kind, ev.source or "?")

        ckpt = state_path and ((case + 1 - start_case) % ckpt_every == 0
                               or case + 1 == n_cases)
        if not ckpt and drain is not None:
            # energies are final for this case and no checkpoint pins
            # this case's store state: unblock the next schedule NOW so
            # writes below overlap the next case's dispatch
            drain.mark_done(case)

        t_o = time.perf_counter()
        with trace.span("corpus.write", case=case):
            for slot in range(batch):
                payload = results.get(slot, b"")
                if writer is not None:
                    writer(case * batch + slot, payload, [])
                else:
                    sys.stdout.buffer.write(payload)
        metrics.GLOBAL.record_stage("write", time.perf_counter() - t_o)
        if stats is not None:
            stats.setdefault("finish_times", []).append(time.perf_counter())
        if ckpt:
            # writes land BEFORE the checkpoint marks the case done (a
            # resumed run must not skip a case whose outputs never hit
            # disk), and the checkpoint lands before the next schedule
            # records its hits (else resume would double-count them)
            save_state(state_path, opts["seed"], case + 1,
                       np.asarray(ckpt_scores),
                       corpus_energies=store.energies(),
                       coverage=(cov.snapshot()
                                 if cov is not None else None))
            store.save()
            if drain is not None:
                drain.mark_done(case)

    def process_case(work: _CaseWork):
        """Force one case's futures to host, then finish_case's
        order-dependent tail (bucket dispatch order is fixed — identical
        in sync and async). Runs inline in sync mode, on the drain worker
        in async mode."""
        case, ids, launched = work.case, work.ids, work.launched
        results: dict[int, bytes] = {}
        # slot -> (device output buffer, row): the adoption source map.
        # Holding new_data here keeps the output buffers alive until the
        # next case's adopt_pending() scatter — they are never donated.
        devsrc: dict[int, tuple] | None = (
            {} if (adopt_on and use_arena) else None)
        t_w = time.perf_counter()
        for b, fut in launched:
            with trace.span("corpus.drain", case=case, capacity=b.capacity):
                new_data, new_lens, _new_sc, meta = fut.result()
                outs = unpack(Batch(new_data[:b.rows], new_lens[:b.rows]))
            for j, slot in enumerate(b.slots):
                results[int(slot)] = outs[j]
                if devsrc is not None:
                    devsrc[int(slot)] = (new_data, j)
            # per-mutator applied counters (registry rows, device side)
            applied = meta.applied[:b.rows].ravel()
            applied = applied[applied >= 0]
            if applied.size:
                counts = np.bincount(applied, minlength=len(DEVICE_CODES))
                for mi in np.nonzero(counts)[0]:
                    metrics.GLOBAL.record_mutator(
                        DEVICE_CODES[mi], applied=True, n=int(counts[mi])
                    )
            bs = bucket_stats.setdefault(
                b.capacity,
                {"batches": 0, "rows": 0, "pad_rows": 0,
                 "padded_bytes_wasted": 0},
            )
            bs["batches"] += 1
            bs["rows"] += b.rows
            bs["pad_rows"] += b.pad_rows
            bs["padded_bytes_wasted"] += b.padded_bytes_wasted
            metrics.GLOBAL.record_bucket(
                b.capacity, b.rows, b.pad_rows, b.padded_bytes_wasted
            )
        # struct overlay lands AFTER the device-set outputs (routed rows
        # rode the bucket step too; their device-set output is replaced,
        # mirroring the batchrunner's host-overwrite contract). Overlaid
        # slots leave devsrc: their adopted offspring go through the
        # store listener's host upload, not the device-set output buffer
        # (which holds the WRONG bytes for them).
        if work.struct_rows:
            if struct_step is not None:
                s_out, s_lens, s_app = work.struct_work
                out_np = np.asarray(s_out)
                lens_np = np.asarray(s_lens)
                app_np = np.asarray(s_app)
                for p, (slot, ci) in enumerate(work.struct_rows):
                    results[slot] = bytes(out_np[p, :int(lens_np[p])])
                    if devsrc is not None:
                        devsrc.pop(slot, None)
                    metrics.GLOBAL.record_mutator(
                        stm.STRUCT_CODES[ci],
                        applied=int(app_np[p]) >= 0)
            else:
                for slot, ci in work.struct_rows:
                    payload = work.struct_work[slot]
                    results[slot] = payload
                    if devsrc is not None:
                        devsrc.pop(slot, None)
                    metrics.GLOBAL.record_mutator(
                        stm.STRUCT_CODES[ci],
                        applied=payload != store.get(ids[slot]))
        drain_wait_s = time.perf_counter() - t_w
        metrics.GLOBAL.record_stage("drain_wait", drain_wait_s)
        # dispatch + drain_wait bounds the device-batch turnaround
        metrics.GLOBAL.observe("batch_latency",
                               work.dispatch_s + drain_wait_s)
        finish_case(case, ids, results, work.scores,
                    work.dispatch_s + drain_wait_s, devsrc=devsrc)

    def _scores_to_host(sc):
        """Pull the score table off a possibly-dead device; if even the
        copy-out fails, degraded cases keep scheduling from a fresh
        zero table (energies, the feedback state that matters, live on
        the host store and survive regardless)."""
        try:
            return np.asarray(sc)
        except Exception:  # lint: broad-except-ok device-lost coercion: zero scores are safe
            return np.zeros((batch, len(DEVICE_CODES)), np.int32)

    def _oracle_case(case, ids):
        """Host-oracle re-serve of one case: deterministic per
        (seed, case, slot) — availability at the cost of device-stream
        byte-identity (the degraded-mode trade documented in README)."""
        from ..oracle.engine import fuzz as oracle_fuzz

        a1, a2, a3 = opts["seed"]
        muta = opts.get("mutations") or default_mutations()
        results: dict[int, bytes] = {}
        t_w = time.perf_counter()
        with trace.span("corpus.oracle_fallback", case=case):
            for slot, sid in enumerate(ids):
                data = store.get(sid)[:device_max]
                results[slot] = oracle_fuzz(
                    data, seed=(a1 + case, a2 + slot, a3), mutations=muta,
                )
        metrics.GLOBAL.record_stage("oracle_fallback",
                                    time.perf_counter() - t_w)
        # the whole case host-routed (struct overlay included — degraded
        # mode trades the device stream for availability)
        metrics.GLOBAL.record_host_routed("degraded", len(ids))
        return results

    def _probe_device():
        """One tiny forced device op. The chaos fault point runs first so
        a still-armed persistent device.step spec keeps probes failing —
        recovery happens exactly when the (real or injected) fault
        clears."""
        chaos.fault_point("device.step")
        jnp.zeros(8).block_until_ready()

    def _discard_work(work):
        drain_futures(fut for _b, fut in work.launched)

    if use_async:
        drain = _DrainWorker(process_case, start_case,
                             discard=_discard_work)
    drain_floor = start_case  # first case the current drain may wait on
    device_mode = True
    probe_at = 0

    t0 = time.perf_counter()
    try:
        case = start_case
        while case < n_cases:
            if device_mode:
                try:
                    if drain is not None and case > drain_floor:
                        # the -s contract's one serialization point: case
                        # N's energy events must land before schedule N+1
                        # draws
                        drain.wait_done(case - 1)
                    (ids, launched, scores, dispatch_s, s_rows,
                     s_work) = dispatch_case(case, scores)
                    if stats is not None:
                        stats.setdefault("schedules", []).append(list(ids))
                    work = _CaseWork(case, ids, launched, scores, dispatch_s,
                                     struct_rows=s_rows, struct_work=s_work)
                    if drain is not None:
                        drain.submit(work)
                    else:
                        process_case(work)
                    case += 1
                    if case == n_cases and drain is not None:
                        # inside the try: a device error surfacing only at
                        # the final drain still degrades and re-serves the
                        # tail instead of killing the run
                        drain.close()
                        drain = None
                except Exception as e:  # lint: broad-except-ok re-raised below unless is_device_error
                    if not is_device_error(e):
                        raise
                    # device lost: flag degraded, abandon in-flight work,
                    # rewind to the first case whose effects never landed
                    # (done_case tracks the drain's progress; its writes
                    # are host-side and complete per case)
                    redo_from = case
                    if drain is not None:
                        redo_from = min(case, drain.done_case + 1)
                        drain.abandon()
                        drain = None
                    logger.log("warning", "corpus: device lost at case %d "
                               "(%s) — host oracle serves from case %d",
                               case, e, redo_from)
                    metrics.GLOBAL.record_event("device_lost")
                    metrics.GLOBAL.set_degraded(True)
                    if cov is not None:
                        # fold on the numpy oracle while the device is
                        # out (bit-identical by the parity tests)
                        cov.use_device = False
                    scores = _scores_to_host(scores)
                    case = redo_from
                    device_mode = False
                    probe_at = case + DEVICE_PROBE_EVERY
            else:
                if case >= probe_at:
                    probe_at = case + DEVICE_PROBE_EVERY
                    try:
                        _probe_device()
                    except Exception:  # lint: broad-except-ok probe failure = device still down
                        pass  # still down; keep serving from the oracle
                    else:
                        logger.log("warning", "corpus: device recovered at "
                                   "case %d — resuming device pipeline",
                                   case)
                        metrics.GLOBAL.record_event("device_recovered")
                        metrics.GLOBAL.set_degraded(False)
                        if cov is not None:
                            cov.use_device = True
                        device_mode = True
                        if use_arena:
                            # the old arena tensor died with the device:
                            # rebuild empty and pay the seed upload once
                            arena.reset()
                            _seed_arena(tick=case)
                        if use_async:
                            scores = jnp.asarray(scores)
                            drain = _DrainWorker(process_case, case,
                                                 discard=_discard_work)
                            drain_floor = case
                        continue
                t_s = time.perf_counter()
                ids = sched.schedule(case, batch)
                ledger.record(case, ids)
                metrics.GLOBAL.record_stage("schedule",
                                            time.perf_counter() - t_s)
                if stats is not None:
                    stats.setdefault("schedules", []).append(list(ids))
                finish_case(case, ids, _oracle_case(case, ids), scores, 0.0)
                case += 1
    finally:
        if drain is not None:
            # abandon, not close: close re-raises the drain error and
            # would mask the exception already unwinding through here
            drain.abandon()
        # process-global flag: later runs in this process (tests, bench
        # stages) must see their own routing split
        _registry.set_struct_kernels(_struct_flag_before)

    # --distill: greedy set-cover over the per-seed coverage tensor —
    # retire every seed whose edge set is provably subsumed by the kept
    # set (afl-cmin analogue; corpus/distill.py pins the determinism and
    # the never-retire-uncovered rule)
    distilled = 0
    if cov is not None and distill_on:
        from .distill import greedy_minimize

        snap = cov.snapshot()
        keep, retired = greedy_minimize(snap["ids"], snap["maps"])
        for sid in retired:
            if store.retire(sid):
                distilled += 1
        if distilled:
            metrics.GLOBAL.record_distilled(distilled)
        print(f"# distill: {len(keep)} covering seeds keep "
              f"{cov.edges()} edges, {distilled} subsumed seeds retired",
              file=sys.stderr)
    store.save()
    dt = time.perf_counter() - t0
    metrics.GLOBAL.record_pipeline_wall(dt)
    total = tallies["total"]
    new_hashes = tallies["new_hashes"]
    if tallies["truncated"]:
        print(f"# {tallies['truncated']} scheduled samples exceeded the "
              f"device budget ({trunc_cap}B) and were truncated",
              file=sys.stderr)
    bytes_up = tallies["bytes_uploaded"] + (arena.bytes_uploaded
                                            if arena is not None else 0)
    if arena is not None:
        metrics.GLOBAL.record_arena(arena.stats())
    if stats is not None:
        stats.update(total=total, dt=dt, batch=batch,
                     buckets=bucket_stats, new_hashes=new_hashes,
                     pipeline=pipeline, layout=layout,
                     bytes_uploaded=bytes_up,
                     offspring=tallies["offspring"],
                     step_shapes=sorted(step_shapes),
                     struct=struct_mode,
                     struct_routed=tallies["struct_routed"],
                     store_stats=store.stats())
        if arena is not None:
            stats["arena"] = arena.stats()
        if gen_engine is not None:
            stats["gen"] = {
                "grammar": gen_engine.cg.source,
                "grammar_id": gen_engine.cg.grammar_id,
                "generated": gen_engine.expansions,
                "host_fallback": gen_engine.host_fallbacks,
                "degraded": gen_engine.degraded,
            }
        if cov is not None:
            stats["coverage"] = {
                "edges": cov.edges(), "folds": cov.folds,
                "maps": tallies["cov_maps"],
                "new_edges": tallies["cov_new_edges"],
                "degraded": not cov_live, "distilled": distilled,
                "hub": hub.stats(),
            }
    logger.log("info", "corpus backend (%s pipeline, %s layout): %d "
               "samples in %.2fs (%.0f samples/s), %d novel output hashes",
               pipeline, layout, total, dt, total / max(dt, 1e-9),
               new_hashes)
    waste = sum(b["padded_bytes_wasted"] for b in bucket_stats.values())
    rows = sum(b["rows"] for b in bucket_stats.values())
    adopt_note = ""
    if adopt_on:
        dev_adopted = arena.stats()["adopted"] if arena is not None else 0
        adopt_note = (f", {tallies['offspring']} offspring adopted "
                      f"({dev_adopted} device-side)")
    print(
        f"# {total} samples, {dt:.2f}s, {total / max(dt, 1e-9):.0f} "
        f"samples/s ({pipeline} pipeline, {layout} layout), "
        f"{new_hashes} novel hashes, {len(bucket_stats)} buckets, "
        f"{waste / max(rows, 1):.0f} padded bytes wasted/sample, "
        f"{bytes_up / max(total, 1):.0f} bytes uploaded/sample"
        f"{adopt_note}",
        file=sys.stderr,
    )
    return 0
