"""Elastic sharded corpus fleet: map seeds across per-shard arenas,
reduce novelty/energy at a coordinator, survive shard loss by
redistribution instead of host fallback.

``--shards N`` routes run_corpus_batch here. The closed loop becomes a
DrJAX-style map/reduce (PAPERS.md, arxiv 2403.07128) per case:

  map     the coordinator draws ONE global schedule (the same
          counter-keyed EnergyScheduler draw as the single-device
          runner), partitions the batch's slots by each seed's stable
          content-hash partition (parallel/shards.py), and every live
          shard mutates+scores its slice against its OWN paged arena
          (corpus/arena.py — one DeviceArena per shard, so corpus
          capacity scales linearly with the fleet).
  reduce  the coordinator forces every shard's future, merges results
          by global slot, walks slots 0..batch-1 hashing outputs into
          one global seen-set (hash-equal offspring arriving from two
          shards credit energy ONCE), drains the feedback bus, writes
          outputs and scatters score rows — exactly the single-device
          finish path, so the scheduler state evolves identically.

Determinism (the headline guarantee): device PRNG streams key on the
GLOBAL slot index via make_class_fuzzer's ``indices`` argument — a
sample's bytes are a pure function of (seed, case, slot) no matter which
shard serves it — and placement is a pure function of the live-shard
set. So an N-shard run is byte-identical to the 1-shard run at a fixed
seed, a faulted run is byte-identical to the unfaulted run (migration
moves WHERE work happens, never WHAT is computed), and replaying the
recorded chaos spec reproduces the same failures, migrations and bytes.
tests/test_fleet.py pins all three.

Failure semantics (vs the single-device runner's all-or-nothing host
fallback): a device error on one shard — real, or an injected
``shard.step`` fault (services/chaos.py) — revokes that shard's lease
(breaker records the failure), redistributes its partitions across
survivors (pure recompute, migration logged), and re-dispatches the
failed slice on its new owners WITHIN the same case. Losing 1 of N
shards costs ~1/N capacity, not the device stream. Every
DEVICE_PROBE_EVERY cases the coordinator probes dead shards; a probe
success re-admits the shard (its arena is rebuilt lazily — seeds
re-upload on first dispatch). Only a fleet with ZERO live shards falls
back to the host oracle, per case, until a probe brings a shard back.

Cross-host fleet (r14): ``--fleet-nodes host:port,...`` makes the FIRST
len(nodes) shard ids remote — each one's per-case dispatch runs on a
WorkerNode over the services/dist.py shard protocol (lease / step /
revoke / probe, each lease carrying a fencing epoch from
FleetPlacement.lease_epoch_of). The worker is stateless: the lease ships
the step config (seed, mutator pri, capacity classes, device_max,
batch), every step ships the slice's bytes, and ``run_remote_slice``
reproduces the coordinator's local recipe — same class grouping, same
pow2 cyclic padding, same GLOBAL slot keys — so remote-N == local-N ==
1-shard byte-identity holds by construction. Remote failures
(RemoteShardError: connect/timeout/protocol/fenced) flow through the
SAME revoke/redispatch/readmit path as a local device loss; a stale
(fenced) reply is rejected by validate_shard_reply and never merged.

``--state`` (r14): the coordinator checkpoints per-case — scores, the
global seen-hash set, corpus energies, the placement fencing epoch and
the resolved capacity classes (services/checkpoint.save_fleet_state:
crc32, fsync-before-rename, .bak fallback) — after the case's outputs
are written and before the next schedule, mirroring the single-device
finish_case order. A killed coordinator resumes mid-campaign
byte-identically; resuming bumps the placement epoch past the saved one
so every pre-crash lease is fenced. A checkpoint from a different run
(seed/shape/shard-count mismatch) is quarantined to ``.bak``, never
silently overwritten.

Fleet phase 3 (r15) — the data path:

  transport  remote shards speak length-prefixed binary frames over ONE
             persistent stream per shard (services/dist.ShardStream):
             step frames are fire-and-forget (every remote shard
             computes its slice in parallel; r14 blocked serially per
             shard), raw byte panels ride the frame blob (no base64),
             and the only awaited steady-state exchange is a window
             sync every ``--fleet-window W`` steps per shard — round
             trips amortize W x.
  reduce     the host-side merge runs on the runner's drain worker
             (corpus/runner._DrainWorker), sequenced strictly in case
             order: the schedule for case N+1 waits for case N's
             energy/score/seen merge (the scheduler draw depends on
             it), then case N's output writes overlap case N+1's
             schedule/assemble/dispatch. Byte-identity is untouched —
             the drain moves WHERE the merge runs, never its order.
             ``--fleet-reduce boundary`` restores the case-boundary
             wait (the identity pin's reference ordering).
  warm start on lease (and re-admission), a shard restores its
             partition from a versioned arena snapshot (page payloads +
             crc32 + fencing epoch, corpus/arena.build_arena_snapshot)
             instead of lazy per-case re-upload: remote leases ship the
             image over a shard_snapshot frame (steps then send seed
             ids only), local readmits replay it into the rebuilt arena
             in ONE flush. The ``fleet.snapshot`` fault site skips the
             warm start — every seed ships/uploads lazily instead,
             byte-identically (tests pin this).

A reply lost mid-window (stream death, fenced zombie, injected
``dist.shard.recv`` fault) surfaces as FleetShardLost from the drain:
the coordinator rewinds to the first un-merged case, revokes the lost
shard, closes every stream, and replays — the replayed schedule draws
identically (energies unchanged since the last merged case), so the
rewound run stays byte-identical to the clean one.

Elastic membership (r20): the worker set is a RUNTIME variable, not a
launch constant. The logical shard count stays fixed (that is what the
PRNG streams and partition_of key on), but which physical worker
tenants each remote slot changes mid-campaign:

  hot-join   ``--fleet-accept PORT`` opens a membership listener; a
             worker started with ``--fleet-join COORD:PORT`` announces
             itself and is ADMITTED AT THE NEXT WINDOW FENCE (the only
             point with zero steps in flight) into the lowest vacant
             slot (``--fleet-expect K`` reserves K remote slots, the
             un-named ones starting VACANT) or a dead slot. Admission
             bumps every fencing epoch and warm-starts the new tenant
             via the r15 snapshot path — and because placement is pure
             and streams are counter-keyed, the campaign is
             byte-identical to a static fleet of the same shard count
             no matter WHEN the join lands.
  drain      a worker SIGTERM'd under ``--fleet-worker`` stamps
             ``draining: true`` on its replies; the coordinator hands
             its partitions back at the next fence with a
             ``fleet_drain`` op (lease dropped, fence floor raised so a
             re-join must lease strictly above it) — a PLANNED
             departure: no FleetShardLost, no rewind, survivor streams
             stay up. ``fleet.join``/``fleet.drain`` chaos sites
             degrade a faulted handshake to the existing paths
             (join aborted / crash-revoke), byte-identically.
  ledger     every join/drain/evict/readmit/vacate bumps a monotonic
             generation (parallel/shards.MembershipLedger), rides
             ``--state`` checkpoints (with the per-slot backend map,
             so a resume mid-churn rebinds the same tenants), and is
             exported as erlamsa_fleet_membership_* plus flight
             breadcrumbs. A deterministic churn schedule
             (opts["churn_schedule"], parallel/shards.
             make_churn_schedule) replays join/drain/kill storms
             case-keyed for the soak tests and the bench churn stage.

Still single-device only: the --struct overlay (a hard error here, not
a silent ignore).
"""

from __future__ import annotations

import os
import sys
import threading
import time
from types import SimpleNamespace

import numpy as np

from ..obs import flight, trace
from ..parallel.shards import (FleetPlacement, MembershipLedger,
                               partition_of)
from ..services import chaos, logger, metrics, out
from . import feedback as fb
from .assembler import bucket_capacity
from .energy import EnergyScheduler
from .runner import DEVICE_PROBE_EVERY, _DrainWorker, _out_hash
from .store import CorpusStore


def merge_shard_results(parts) -> dict[int, bytes]:
    """Reduce-side merge: shard sub-results (each a {global slot: bytes}
    dict over disjoint slots) into one case-wide results dict. Raises on
    overlap — two shards claiming one slot is a placement bug, and
    silently letting the later shard win would make output bytes depend
    on merge order."""
    merged: dict[int, bytes] = {}
    for part in parts:
        for slot, payload in part.items():
            if slot in merged:
                raise RuntimeError(f"fleet reduce: slot {slot} produced "
                                   f"by two shards")
            merged[slot] = payload
    return merged


def apply_novelty(store, ids, results, seen_hashes, batch,
                  tallies=None, on_novel=None, slot_gain=None,
                  dup_of=None) -> int:
    """The reduce step's novelty walk, shared with tests: slots
    0..batch-1 in order, one GLOBAL seen-set — a hash first seen this
    case credits energy exactly once no matter how many shards produced
    hash-equal offspring. `on_novel(slot, payload)` fires per admitted
    slot in the same slot order (the fleet's offspring-adoption hook).

    slot_gain (r19 fleet coverage): {slot: new-edge count} for slots
    the coverage fold covered this case — those admit on genuinely-new
    edges (``new_cov`` energy) while uncovered slots keep the
    hash-novelty stand-in, exactly the single-device runner's
    semantics. seen_hashes is still recorded for covered slots so a
    later degradation cannot re-count their outputs as novel.

    dup_of (r19 --spmd): {slot: earlier slot} duplicate HINTS from the
    on-device ppermute hash exchange. Every hint is memcmp-verified
    here before it short-circuits the sha1: equal bytes at a lower slot
    mean that slot's walk already interned this exact hash (induction
    over slot order), so skipping is bit-equivalent — and a weak-hash
    collision simply fails the memcmp and takes the normal path.
    Returns the number of new hashes."""
    new = 0
    for slot in range(batch):
        payload = results.get(slot, b"")
        if tallies is not None:
            tallies["bytes_out"] += len(payload)
        d = dup_of.get(slot) if dup_of else None
        if d is not None and payload and results.get(d) == payload:
            novel_hash = False
        else:
            h = _out_hash(payload)
            novel_hash = h not in seen_hashes
            if novel_hash:
                seen_hashes.add(h)
        if novel_hash:
            new += 1
        if slot_gain is not None and slot in slot_gain:
            admit = slot_gain[slot] > 0
            kind = "new_cov"
        else:
            admit = novel_hash
            kind = "new_hash"
        if admit:
            store.apply_event(fb.Event(kind, ids[slot]))
            if on_novel is not None:
                on_novel(slot, payload)
    return new


# worker-side compiled-step cache: one make_class_fuzzer per mutator-pri
# tuple, shared across leases/steps (compiling per step would dominate)
_REMOTE_STEPS: dict[tuple, object] = {}
_REMOTE_LOCK = threading.Lock()


def _remote_step_for(pri: tuple):
    from ..ops.pipeline import make_class_fuzzer

    with _REMOTE_LOCK:
        step = _REMOTE_STEPS.get(pri)
        if step is None:
            step = make_class_fuzzer(mutator_pri=list(pri), donate=False)
            _REMOTE_STEPS[pri] = step
        return step


class _DoneStep:
    """An already-materialized step result dressed in the StepFuture
    protocol (block/ready/result) — run_remote_slice's spmd leg returns
    host arrays, not a future, and the per-class force loop should not
    care which path produced them."""

    def __init__(self, res):
        self._res = res

    def block(self):
        return self

    def ready(self) -> bool:
        return True

    def result(self):
        return self._res


def _panel_future(base, case: int, idx, panel, lens, sc_in, pri,
                  scan_len: int):
    """Remote-SPMD leg (r19): split one class panel row-wise across the
    worker's local devices via parallel/spmd.run_panel — the mesh recipe
    the coordinator's --spmd mode compiles, re-derived worker-side so
    remote-SPMD == local-SPMD == 1-shard stays byte-identical (rows are
    independent and keyed on GLOBAL slots). Returns None when the board
    has one device or the split fails — the caller's single-device step
    serves the panel byte-identically."""
    import jax

    from ..parallel import spmd as spmd_mod

    devs = jax.devices()
    n = len(devs)
    while n > 1 and panel.shape[0] % n:
        n //= 2
    if n < 2:
        return None
    try:
        out, n_out, sc, applied = spmd_mod.run_panel(
            devs[:n], base, int(case), idx, panel, lens, sc_in,
            pri, None, int(scan_len))
    except Exception:  # lint: broad-except-ok mesh failure degrades to the byte-identical single-device step
        metrics.GLOBAL.record_event("spmd_panel_fallback")
        return None
    return _DoneStep((out, n_out, sc, SimpleNamespace(applied=applied)))


def run_remote_slice(seed, case: int, batch: int, slots, payloads,
                     score_rows, pri, classes, device_max: int,
                     spmd: bool = False):
    """Worker-side executor for one remote shard's per-case slice
    (called by services/dist.ShardHost under a validated lease).

    Mirrors the coordinator's local dispatch recipe byte-exactly, minus
    the arena: rows group by capacity class (smallest class holding
    bucket_capacity(len, device_max), longer samples truncate at the top
    class), each group pads to a pow2 row count cyclically, panels are
    zero-padded seed bytes (identical to a gathered arena row), and the
    PRNG keys on the GLOBAL slot indices shipped in the request — so the
    bytes are a pure function of (seed, case, slot), whatever host
    serves them. Returns (outs, score_rows, applied_rows, shapes), all
    aligned with `slots` order except `shapes` (one (kp, capacity,
    scan_len) per dispatched class group)."""
    from ..ops import prng
    from ..ops.buffers import Batch, scan_bound, unpack
    from ..ops.pipeline import drain_futures, step_async
    from .arena import _next_pow2

    classes = tuple(int(c) for c in classes)
    base = prng.base_key(tuple(int(x) for x in seed))
    step = _remote_step_for(tuple(int(x) for x in pri))
    groups: dict[int, list[int]] = {}
    for r, p in enumerate(payloads):
        want = bucket_capacity(len(p), device_max=int(device_max))
        cls = next((i for i, cap in enumerate(classes) if cap >= want),
                   len(classes) - 1)
        groups.setdefault(cls, []).append(r)
    launched: list[tuple] = []
    try:
        for cls in sorted(groups):
            rows = groups[cls]
            cap = classes[cls]
            k = len(rows)
            kp = max(8, _next_pow2(k))
            panel = np.zeros((kp, cap), np.uint8)
            lens = np.zeros(kp, np.int32)
            for j in range(kp):
                p = payloads[rows[j % k]][:cap]
                panel[j, :len(p)] = np.frombuffer(p, np.uint8)
                lens[j] = len(p)
            g_slots = [int(slots[r]) for r in rows]
            idx = np.concatenate([
                np.asarray(g_slots, np.int32),
                int(batch) + np.arange(kp - k, dtype=np.int32),
            ]).astype(np.int32)
            sc_in = np.asarray(
                [score_rows[rows[j % k]] for j in range(kp)], np.int32)
            sl = scan_bound(int(lens[:k].max()), cap)
            fut = (_panel_future(base, case, idx, panel, lens, sc_in,
                                 pri, sl) if spmd else None)
            if fut is None:
                fut = step_async(step, base, int(case), idx, panel, lens,
                                 sc_in, scan_len=sl)
            launched.append((rows, k, cap, sl, kp, fut))
    except BaseException:  # lint: broad-except-ok re-raised after settling in-flight futures
        drain_futures(f for *_g, f in launched)
        raise
    outs: list[bytes] = [b""] * len(slots)
    sc_out = [[int(x) for x in row] for row in score_rows]
    applied: list[list[int]] = [[] for _ in range(len(slots))]
    shapes: list[tuple] = []
    for rows, k, cap, sl, kp, fut in launched:
        new_data, new_lens, new_sc, meta = fut.result()
        group_outs = unpack(Batch(new_data[:k], new_lens[:k]))
        for j, r in enumerate(rows):
            outs[r] = group_outs[j]
            sc_out[r] = [int(x) for x in new_sc[j]]
            applied[r] = [int(x) for x in meta.applied[j]]
        shapes.append((kp, cap, sl))
    return outs, sc_out, applied, shapes


class _RemoteResult:
    """A completed remote step dressed in the StepFuture protocol
    (ops/pipeline.py: block/ready/result) so the reduce forces local and
    remote entries through ONE code path. data+lens are rebuilt so
    buffers.unpack reproduces the worker's bytes exactly; applied rows
    pad with -1 (the 'inactive round' convention the mutator-metrics
    walk already filters)."""

    def __init__(self, outs, sc_rows, applied_rows):
        k = len(outs)
        data = np.zeros((k, max([len(o) for o in outs] + [1])), np.uint8)
        lens = np.zeros(k, np.int32)
        for j, o in enumerate(outs):
            data[j, :len(o)] = np.frombuffer(o, np.uint8)
            lens[j] = len(o)
        width = max([len(a) for a in applied_rows] + [1])
        app = np.full((k, width), -1, np.int32)
        for j, a in enumerate(applied_rows):
            app[j, :len(a)] = a
        self._res = (data, lens, np.asarray(sc_rows, np.int32),
                     SimpleNamespace(applied=app))

    def block(self):
        return self

    def ready(self) -> bool:
        return True

    def result(self):
        return self._res


class FleetShardLost(RuntimeError):
    """A shard's already-dispatched work was lost AFTER the case left
    the dispatch loop — a step reply that never arrived (stream death,
    fenced zombie, injected dist.shard.recv fault) or a local future
    that died at force time. Raised by the drain's merge, caught by the
    coordinator's rewind: revoke the shard, close the streams, replay
    from the first un-merged case. Distinct from a dispatch-time
    failure, which redistributes WITHIN the case."""

    def __init__(self, shard: int, case: int, cause: BaseException):
        super().__init__(f"shard {shard} lost at case {case}: {cause}")
        self.shard = int(shard)
        self.case = int(case)
        self.cause = cause


class _PendingRemote:
    """A fire-and-forget framed step awaiting its FIFO reply (r15).
    The dispatch thread writes the step (and, when the window fills, a
    shard_sync barrier) and moves on; the drain thread calls force() to
    consume the result frame — and the sync ack behind it — off the
    same stream. force() is idempotent (the settle paths may force an
    entry the merge later reads), and the decoded reply is dressed as a
    _RemoteResult so the reduce treats local and remote entries
    identically."""

    def __init__(self, stream, epoch: int, case: int, n_slots: int,
                 sync: bool, shapes_acc: set, tele: bool = False):
        self.stream = stream
        self.epoch = int(epoch)
        self.case = int(case)
        self.n_slots = int(n_slots)
        self.sync = bool(sync)
        #: a shard_telemetry request rode this window's fence — its
        #: reply is owed on the FIFO stream right after the sync ack
        self.tele = bool(tele)
        self._shapes = shapes_acc
        self.done = False
        self._result = None

    def force(self) -> _RemoteResult:
        if self.done:
            return self._result
        # lint: span-coverage-ok forced under the drain worker's fleet.drain span (process_case)
        header, blob = self.stream.read_reply("shard_result", self.epoch,
                                              case=self.case)
        lens = [int(x) for x in header.get("lens", [])]
        if len(lens) != self.n_slots or sum(lens) != len(blob):
            from ..services.dist import RemoteShardError

            raise RemoteShardError(
                f"shard {self.stream.id}: reply geometry mismatch "
                f"({len(lens)} lens / {sum(lens)}B declared for "
                f"{self.n_slots} slots / {len(blob)}B blob)")
        outs = []
        off = 0
        for ln in lens:
            outs.append(blob[off:off + ln])
            off += ln
        for sh in header.get("shapes", []):
            self._shapes.add(tuple(int(x) for x in sh))
        if self.sync:
            # the window barrier: the ONLY awaited steady-state
            # exchange — consuming the ack re-opens the shard's window
            self.stream.read_reply("shard_synced", self.epoch,  # lint: span-coverage-ok same fleet.drain span as the result frame above
                                   case=self.case)
            if self.stream.tally is not None:
                self.stream.tally.add(round_trips=1)
            self.stream.unsynced = 0
            if self.tele:
                from ..services.dist import consume_telemetry

                # out-of-band: a lost/garbled telemetry reply counts
                # telemetry_lost and the merge proceeds untouched
                consume_telemetry(self.stream, self.epoch, self.case)
        self._result = _RemoteResult(outs, header.get("scores", []),
                                     header.get("applied", []))
        self.done = True
        return self._result


def run_corpus_fleet(opts: dict, batch: int = 1024) -> int:
    """The --corpus DIR --shards N entry point (see module docstring)."""
    import jax
    import jax.numpy as jnp

    from ..constants import CAPACITY_CLASSES
    from ..oracle.mutations import default_mutations
    from ..ops import paged, prng
    from ..ops.buffers import Batch, scan_bound, unpack
    from ..ops.pipeline import (drain_futures, is_device_error,
                                make_class_fuzzer, step_async)
    from ..ops.registry import DEVICE_CODES
    from ..ops.scheduler import init_scores
    from .arena import RESERVED_PAGES, DeviceArena, _next_pow2, \
        build_arena_snapshot, fit_page_classes, resolve_classes

    from ..parallel import spmd as spmd_mod
    from ..services.checkpoint import (load_coverage_maps,
                                       load_fleet_state,
                                       quarantine_mismatch,
                                       save_fleet_state)
    from ..services.dist import (MembershipListener, RemoteShardError,
                                 ShardStream, TransportTally,
                                 new_campaign_token, request_telemetry)

    raw_shards = opts.get("shards")
    # --fleet-window W: steps in flight per shard between sync barriers
    # (W=1 degenerates to one awaited exchange per step, the r14 cadence)
    fleet_window = max(1, int(opts.get("fleet_window") or 1))
    # --fleet-reduce: 'overlap' (default) runs the merge on the drain
    # worker; 'boundary' waits at the case boundary (the identity pin's
    # reference ordering — processing is identical either way)
    reduce_mode = str(opts.get("fleet_reduce") or "overlap")
    if reduce_mode not in ("overlap", "boundary"):
        raise ValueError(f"--fleet-reduce must be overlap|boundary, "
                         f"got {reduce_mode!r}")
    # --spmd (r19): fuse the LOCAL shards' per-case class steps into ONE
    # shard_map-compiled program per capacity class over the device mesh
    # (parallel/spmd.py) — one dispatch per (case, class) for the whole
    # board, with the score merge and a duplicate-hash exchange running
    # as on-device collectives. Remote shards keep the framed-stream
    # tier; their leases carry the flag so workers mesh their own boards.
    use_spmd = bool(opts.get("spmd"))
    # --fleet-rewind: 'slice' (default) replays only the lost shard's
    # partition slice of the un-merged case after a FleetShardLost;
    # 'full' restores the r15 whole-window rewind (the identity pin's
    # reference path — tests pin slice == full bytes)
    rewind_mode = str(opts.get("fleet_rewind") or "slice")
    if rewind_mode not in ("slice", "full"):
        raise ValueError(f"--fleet-rewind must be slice|full, "
                         f"got {rewind_mode!r}")
    fleet_nodes: list[tuple[str, int]] = []
    for spec in (opts.get("fleet_nodes") or []):
        host, _, port = str(spec).rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(
                f"--fleet-nodes entry {spec!r} is not host:port")
        fleet_nodes.append((host, int(port)))
    # --fleet-expect K (r20): reserve K REMOTE shard slots. The first
    # len(fleet_nodes) bind at start; the rest start VACANT and await a
    # hot-join. The LOGICAL shard count (what partition_of and the PRNG
    # streams key on) is fixed at launch either way — elasticity changes
    # tenancy, never the stream keying, which is the byte-identity
    # contract.
    fleet_expect = int(opts.get("fleet_expect") or 0)
    if fleet_expect < 0:
        raise ValueError(f"--fleet-expect must be >= 0, "
                         f"got {fleet_expect}")
    remote_slots = max(len(fleet_nodes), fleet_expect)
    # --fleet-nodes alone sizes the fleet to the worker list (plus any
    # vacant --fleet-expect slots); --shards N with M <= N remote slots
    # runs a mixed fleet (M remote + N-M local shards); --spmd alone
    # sizes the fleet to the local board (one mesh slot per device — the
    # single-program multi-device configuration)
    if raw_shards is not None:
        n_shards = int(raw_shards)
    elif remote_slots:
        n_shards = remote_slots
    elif use_spmd:
        n_shards = len(jax.devices())
    else:
        n_shards = 1
    if n_shards < 1:
        raise ValueError(f"--shards must be >= 1, got {n_shards}")
    if remote_slots > n_shards:
        raise ValueError(
            f"--fleet-nodes/--fleet-expect name {remote_slots} remote "
            f"slots but --shards is {n_shards}; drop --shards to size "
            f"the fleet from the remote slots, or raise it to at least "
            f"the slot count")
    # deterministic churn schedule (tests/bench): case-keyed
    # join/drain/kill events consumed at the window fence — sorted so
    # consumption order is a pure function of the schedule, never of
    # arrival timing
    churn_schedule = sorted(
        (dict(ev) for ev in (opts.get("churn_schedule") or [])),
        key=lambda ev: int(ev.get("case", 0)))
    for ev in churn_schedule:
        if ev.get("kind") not in ("join", "drain", "kill"):
            raise ValueError(
                f"churn_schedule kind must be join|drain|kill, "
                f"got {ev.get('kind')!r}")
    if str(opts.get("struct") or "off") != "off":
        # the struct overlay (ops/structure.py) is routed per scheduled
        # case against one arena; sharding it means per-shard span panels
        # and a merged routing draw — not built. A hard error beats the
        # old printed notice: nobody should believe struct kernels ran
        # fleet-wide when they didn't.
        raise ValueError(
            "--struct is single-device only: the span-splice overlay "
            "routes against one arena. Drop --shards/--fleet-nodes to "
            "run the struct overlay, or drop --struct to run the fleet.")

    store = CorpusStore(opts["corpus_dir"])
    fsck = store.fsck()
    if fsck["missing"] or fsck["corrupt"] or fsck["orphans"]:
        print(f"# corpus fsck: {fsck['ok']} ok, {fsck['missing']} missing, "
              f"{fsck['corrupt']} corrupt, {fsck['orphans']} orphaned",
              file=sys.stderr)
    direct = opts.get("corpus")
    if direct is not None:
        for s in direct:
            store.add(s, origin="direct")
    else:
        paths = [p for p in (opts.get("paths") or []) if p != "-"]
        if paths:
            from ..oracle.gen import _expand_paths

            expanded = (_expand_paths(paths) if opts.get("recursive")
                        else paths)
            new, dup, skipped = store.add_paths(expanded)
            print(f"# corpus: {new} new, {dup} duplicate, "
                  f"{skipped} skipped -> {len(store)} seeds in store",
                  file=sys.stderr)
    if len(store) == 0:
        print("no corpus (store empty and no readable seeds)",
              file=sys.stderr)
        return 1

    selected = dict(opts.get("mutations") or default_mutations())
    pri = [max(selected.get(code, 0), 0) for code in DEVICE_CODES]
    if not any(pri):
        print("none of the selected mutations runs on the TPU backend; "
              f"device set: {','.join(DEVICE_CODES)}", file=sys.stderr)
        return 1

    device_max = int(opts.get("device_capacity_max", CAPACITY_CLASSES[-1]))
    sched = EnergyScheduler(store, opts["seed"])
    # no donation: shard futures from one case coexist until the reduce
    # forces them, and a donated buffer consumed by shard A's step must
    # not alias anything shard B still reads
    step = make_class_fuzzer(mutator_pri=pri, donate=False)
    base = prng.base_key(opts["seed"])
    # host-resident score table: gathered per shard slice at dispatch,
    # scattered back at the reduce — slices are disjoint by slot, so the
    # evolution matches the single-device table exactly
    scores = np.array(init_scores(jax.random.fold_in(base, 999), batch))
    bus = opts.get("feedback_bus", fb.GLOBAL)
    consume_feedback = bool(opts.get("feedback"))

    # -- fleet coverage (r19, satellite of the spmd PR): ONE gating
    # CoverageIndex at the coordinator (the same admission authority as
    # the single-device runner — adoption must not depend on placement)
    # plus one attribution-only ledger per shard: a seed's per-seed map
    # accrues on its HOME shard's ledger, and the window fence
    # OR-reduces the ledger globals against the gating map. Hub death is
    # sticky hash-novelty degradation, byte-identical per PR 16.
    cov_hub = opts.get("coverage_hub")
    coverage_on = bool(opts.get("coverage")) and cov_hub is not None
    cov = None
    cov_ledgers: list = []
    cov_live = [coverage_on]
    ledger = fb.SampleLedger()
    if coverage_on:
        from .distill import CoverageIndex

        cov = CoverageIndex(map_bytes=cov_hub.map_bytes, use_device=True)
        cov_ledgers = [CoverageIndex(map_bytes=cov_hub.map_bytes)
                       for _ in range(n_shards)]

    # -- fleet checkpoint (--state): resume or start fresh -------------
    n_cases = int(opts.get("n", 1))
    state_path = opts.get("state_path")
    ckpt_every = max(1, int(opts.get("checkpoint_every", 1)))
    start_case = 0
    resume_seen: set[bytes] = set()
    resume_epoch = None
    resume_membership = None
    classes_override = None
    if state_path and os.path.exists(state_path):
        st = load_fleet_state(state_path)
        cov_verdict, cov_snap = "absent", None
        if st is not None and cov is not None:
            # kind-stamped coverage fields: "absent" (pre-coverage
            # checkpoint) resumes with fresh empty coverage; "mismatch"
            # (width/version/kind) joins the quarantine path below —
            # folding into maps written under another scheme would
            # corrupt every later adoption decision
            cov_verdict, cov_snap = load_coverage_maps(state_path,
                                                       cov.map_bytes)
        if st is None:
            print("# fleet checkpoint unreadable (or not a fleet "
                  "checkpoint), starting fresh", file=sys.stderr)
        elif (st["seed"] != tuple(opts["seed"])
                or st["scores"].shape != scores.shape
                or st["n_shards"] != n_shards
                or cov_verdict == "mismatch"):
            # a checkpoint from a DIFFERENT run is evidence, not trash:
            # quarantine it to .bak instead of burying it under this
            # run's first save (tests pin both paths)
            quarantine_mismatch(state_path)
            print("# fleet checkpoint mismatch (seed/shape/shards/"
                  "coverage), starting fresh (original kept as .bak)",
                  file=sys.stderr)
        else:
            start_case = st["case_idx"]
            scores[:] = st["scores"]
            resume_seen = st["seen"]
            if st["energies"]:
                store.restore_energies(st["energies"])
            resume_epoch = st["epoch"]
            resume_membership = st.get("membership")
            classes_override = st["classes"]
            # event counters (fence_rejected, telemetry_lost, ...) are
            # monotone across a resume: max-merge the checkpointed
            # floors so no counter ever reads lower after a restore
            for kind, floor in (st.get("events") or {}).items():
                metrics.GLOBAL.restore_event_floor(kind, floor)
            if cov_snap is not None:
                cov.restore(cov_snap)
                # rebuild the per-shard attribution ledgers from the
                # restored per-seed maps: attribution is a pure function
                # of (sid, n_shards), so the fence invariant (ledger
                # union == gating map) holds across the resume
                for sid, row in cov.per_seed.items():
                    cov_ledgers[partition_of(sid, n_shards)].fold_map(
                        sid, row.tobytes())
            print(f"# fleet resumed at case {start_case} "
                  f"({len(st['seen'])} seen hashes, "
                  f"{len(st['energies'])} seed energies, "
                  f"placement epoch > {resume_epoch})", file=sys.stderr)
    if start_case >= n_cases:
        print(f"# run already complete ({start_case}/{n_cases} cases)",
              file=sys.stderr)
        return 0

    # ONE capacity-class SET over the WHOLE store (never per shard): the
    # fused engine's streams are a function of the static row width, so
    # shard-count byte-identity requires every shard to mutate a seed at
    # the same class width the 1-shard run would use — each shard then
    # runs one ragged step per class present in its slice. A RESUMED run
    # restores the checkpointed set: the reloaded store already holds
    # adopted offspring, so re-deriving from it would change row widths —
    # and therefore bytes — relative to the uninterrupted run.
    sizes = [len(store.get(sid)) for sid in store.ids()]
    classes = (classes_override if classes_override is not None
               else resolve_classes(opts.get("arena_classes"), sizes,
                                    device_max))
    trunc_cap = classes[-1]
    page_opt = int(opts.get("arena_page") or paged.PAGE)
    page = fit_page_classes(page_opt, classes)
    if page != page_opt:
        print(f"# fleet: page size {page_opt} does not fit the capacity "
              f"classes {classes}, using {page}", file=sys.stderr)
    # offspring adoption (--adopt): the reduce's novelty walk adds novel
    # outputs to the store (layout-independent decision, capped per
    # case); when the producing shard still owns the new seed's home
    # partition, the bytes adopt device-side out of that shard's output
    # buffer — other placements upload lazily at first schedule
    adopt_on = bool(opts.get("adopt"))
    adopt_cap = int(opts.get("adopt_cap") or 64)

    devices = jax.devices()
    placement = FleetPlacement(n_shards, failure_threshold=1)
    if resume_epoch is not None:
        # continue the fencing sequence PAST the checkpointed epoch:
        # every lease the dead coordinator granted is now stale, so a
        # pre-crash zombie worker's reply can never pass validation
        placement.restore(resume_epoch)

    def _shard_page_need(shard_id: int) -> int:
        """Arena page count for one shard: sized for its home partition
        (fleet capacity scales linearly) with 2x slack for migrated
        partitions; overflow rides the host-overlay spill path."""
        home = [sid for sid in store.ids()
                if partition_of(sid, n_shards) == shard_id]
        need = sum(max(1, -(-min(len(store.get(sid)), trunc_cap)
                           // page)) for sid in home)
        per_opt = opts.get("arena_pages")  # per-shard when given
        num_pages = int(per_opt or RESERVED_PAGES + max(64, 2 * need))
        return max(num_pages, RESERVED_PAGES + classes[0] // page)

    # --spmd needs every LOCAL arena tensor the same shape: the fused
    # program's [N, pages, page] view is a zero-copy assembly of the
    # per-device tensors. Sizing every member at the fleet max only
    # moves spill boundaries, which the spill path keeps byte-neutral.
    local_shard_ids = list(range(remote_slots, n_shards))
    uniform_pages = (max(map(_shard_page_need, local_shard_ids))
                     if use_spmd and local_shard_ids else None)

    class _Shard:
        """One lease-holder: a device slot plus its own paged arena (see
        _shard_page_need; --spmd sizes all local arenas uniformly)."""

        def __init__(self, shard_id: int):
            self.id = shard_id
            self.device = devices[shard_id % len(devices)]
            num_pages = (uniform_pages if uniform_pages is not None
                         else _shard_page_need(shard_id))
            with jax.default_device(self.device):
                self.arena = DeviceArena(
                    num_pages, page=page, donate=False, classes=classes,
                    classify=lambda n: bucket_capacity(
                        n, device_max=device_max),
                )
            # COMMIT the pages tensor to this shard's slot: arrays born
            # under default_device are uncommitted, so the first
            # functional update outside this context (upload/adopt on
            # the main thread) would silently migrate the arena to
            # device 0 — fatal for the spmd assembly, which requires
            # one resident arena per distinct mesh device. A committed
            # input keeps every downstream jit output on this device.
            self.arena._arena = jax.device_put(self.arena._arena,
                                               self.device)

    # one token per coordinator campaign: worker-side fence floors are
    # scoped by it, so a fresh campaign's epoch-0 leases are not fenced
    # by floors a previous campaign left on a long-lived worker, while
    # zombies of past campaigns (old token) stay rejected. Transport
    # metadata only — sample bytes stay f(seed, case, slot).
    fleet_token = str(opts.get("fleet_token") or new_campaign_token())
    # one transport ledger for the whole campaign, shared by every
    # shard stream: frame bytes by direction + awaited round trips
    transport = TransportTally()
    fleet_timeout = float(opts.get("fleet_timeout") or 90.0)

    def _classify(n: int) -> int:
        return bucket_capacity(n, device_max=device_max)

    class _Remote:
        """One cross-host lease-holder (r15): a persistent framed
        stream to its worker. The worker stays stateless between leases
        — but WITHIN a lease it caches the warm-start snapshot this
        class ships right after the grant, so steady-state steps send
        seed ids instead of payloads for every snapshot-resident seed.
        A worker restart costs a re-lease plus a snapshot re-ship,
        nothing else. Offspring produced here adopt host-side only (no
        local device buffer to splice from); they ship inline at their
        first schedule like any post-snapshot seed."""

        def __init__(self, shard_id: int, host: str, port: int):
            self.id = shard_id
            self.host = host
            self.port = int(port)
            self.stream = ShardStream(shard_id, host, port,
                                      timeout=fleet_timeout,
                                      token=fleet_token, tally=transport)
            self._leased: int | None = None
            self.snap_sids: frozenset = frozenset()
            self.cfg = {
                "seed": [int(x) for x in opts["seed"]],
                "pri": [int(x) for x in pri],
                "classes": [int(c) for c in classes],
                "device_max": int(device_max),
                "batch": int(batch),
                # r19: a leased worker meshes its OWN local board when
                # the coordinator runs --spmd (run_remote_slice re-
                # derives the panel split; bytes are placement-free)
                "spmd": bool(use_spmd),
            }

        def ensure_lease(self, epoch: int):
            """(Re-)grant the lease when the placement epoch moved —
            initial grant, post-readmit, and post-resume all land here
            lazily at the next dispatch that needs the shard — then
            ship the arena warm-start snapshot for the shard's current
            partitions. The fleet.snapshot fault site skips the ship:
            every seed rides the inline path instead, byte-identically
            (the snapshot moves bytes earlier, never changes them)."""
            if self._leased == epoch:
                return
            msg = {"op": "shard_lease", "shard": self.id,
                   "epoch": int(epoch)}
            msg.update(self.cfg)
            with trace.span("fleet.lease", shard=self.id, epoch=epoch):
                self.stream.request(msg, expect="shard_leased")
            self._leased = epoch
            self.snap_sids = frozenset()
            try:
                chaos.fault_point("fleet.snapshot")
            except OSError:
                metrics.GLOBAL.record_event("fleet_snapshot_skipped")
                return
            part = [sid for sid in store.ids()
                    if placement.owner_of(partition_of(sid, n_shards))
                    == self.id]
            if not part:
                return
            snap = build_arena_snapshot(store.get, part, classes, page,
                                        classify=_classify,
                                        epoch=int(epoch),
                                        token=fleet_token)
            header = {"op": "shard_snapshot", "shard": self.id,
                      "epoch": int(epoch), "sids": list(snap.sids),
                      "lens": [int(x) for x in snap.lens],
                      "page": int(snap.page), "crc": int(snap.crc)}
            with trace.span("fleet.snapshot", shard=self.id,
                            seeds=len(snap.sids),
                            pages=int(snap.pages.shape[0])):
                self.stream.request(header, snap.pages.tobytes(),
                                    expect="shard_snapshotted")
            self.snap_sids = frozenset(snap.sids)
            metrics.GLOBAL.record_event("fleet_snapshot_shipped")
            flight.GLOBAL.note("fleet_warm_start", shard=self.id,
                               epoch=int(epoch), seeds=len(snap.sids),
                               bytes=int(snap.pages.nbytes))

    # the FIRST remote_slots shard ids are remote (the trailing ones
    # possibly VACANT, awaiting a hot-join), the rest local —
    # partition_of is shard-count-keyed only, so the mix never changes
    # WHAT any slot computes, only where. A checkpoint's membership
    # record wins over --fleet-nodes: the backend each slot held at the
    # kill is the one the resume re-binds (r20), so a campaign resumed
    # mid-churn re-derives the same placement the dead coordinator held.
    members = MembershipLedger()

    def _backend_for(s: int):
        if resume_membership is not None:
            backends = resume_membership.get("backends") or []
            if s < len(backends):
                b = backends[s]
                if b == "local":
                    return _Shard(s)
                if not b:
                    return None
                host, _, port = b.rpartition(":")
                return _Remote(s, host, int(port))
        if s < len(fleet_nodes):
            return _Remote(s, *fleet_nodes[s])
        if s < remote_slots:
            return None  # vacant: reserved for a hot-join
        return _Shard(s)

    shards: dict[int, object] = {s: _backend_for(s)
                                 for s in range(n_shards)}
    if resume_membership is not None:
        members.restore(resume_membership.get("generation", 0),
                        resume_membership.get("events") or [])
        # vacancies restore through placement silently — their history
        # is already in the restored ledger events
        for s, sh in shards.items():
            if sh is None:
                placement.vacate(s, start_case)
    else:
        for s, sh in shards.items():
            if sh is None:
                entry = placement.vacate(s, start_case)
                members.record("vacant", s, start_case,
                               entry["epoch"])

    # hot-join intake (r20): --fleet-accept opens a listener; announced
    # candidates are admitted ONLY at the window fence (never mid-case).
    # Tests may pass a pre-built listener via opts["membership_listener"].
    listener = opts.get("membership_listener")
    if listener is None and opts.get("fleet_accept") is not None:
        listener = MembershipListener(int(opts["fleet_accept"]))

    def membership_state() -> dict:
        """Checkpointable membership record: ledger snapshot plus the
        per-slot backend binding ("host:port" | "local" | "" vacant) and
        liveness — enough for a resume to re-bind exactly the tenancy
        the dead coordinator held (r20)."""
        snap = members.snapshot()
        snap["backends"] = [
            ("" if shards[s] is None
             else f"{shards[s].host}:{shards[s].port}"
             if isinstance(shards[s], _Remote) else "local")
            for s in range(n_shards)]
        snap["live"] = [placement.is_live(s) for s in range(n_shards)]
        return snap

    def record_membership():
        """Publish the ledger to /metrics (erlamsa_fleet_membership_*)."""
        metrics.GLOBAL.record_membership({
            "generation": members.generation,
            "events": members.counts(),
            "vacant": sum(1 for s in range(n_shards)
                          if shards[s] is None),
        })

    record_membership()

    # -- SPMD engine (r19, --spmd): one mesh over the local members ----
    spmd_engine = None
    spmd_members: dict[int, int] = {}   # shard id -> mesh position
    local_member_ids: list[int] = []    # mesh position -> shard id
    if use_spmd:
        local_member_ids = [s for s in sorted(shards)
                            if isinstance(shards[s], _Shard)]
        devs = [shards[s].device for s in local_member_ids]
        if local_member_ids and len({d.id for d in devs}) == len(devs):
            spmd_engine = spmd_mod.SpmdEngine(devs, batch,
                                              mutator_pri=pri, page=page)
            spmd_members = {s: i for i, s in enumerate(local_member_ids)}
        else:
            # more local shards than devices (or none): two mesh slots
            # cannot share a device, so the classic per-shard dispatch
            # serves the run byte-identically
            print("# --spmd: local shards do not map 1:1 onto distinct "
                  "devices — classic per-shard dispatch", file=sys.stderr)

    writer, _mt = out.string_outputs(opts.get("output", "-"))
    stats = opts.get("_stats")
    seen_hashes: set[bytes] = resume_seen
    tallies = {"truncated": 0, "total": 0, "new_hashes": 0, "bytes_out": 0,
               "oracle_cases": 0, "redispatches": 0, "offspring": 0,
               "rewinds": 0, "slice_rewinds": 0, "cov_maps": 0,
               "cov_new_edges": 0}
    step_shapes: set[tuple] = set()

    class _SpmdSlice:
        """One member's view of a fused class launch, dressed in the
        StepFuture protocol (block/ready/result) so process_case forces
        spmd and classic entries through ONE code path. Holds its
        case's plan state directly — a slice kept across a slice-rewind
        begin_case still resolves against the launch that produced it."""

        def __init__(self, case_state, cap, member, off, k, slots):
            self._state = case_state
            self._cap = cap
            self._member = member
            self._off = off
            self._k = k
            self._slots = slots

        def result(self):
            res = self._state["results"].get(self._cap)
            if res is None:
                raise RuntimeError(
                    f"spmd class {self._cap} was never launched")
            if isinstance(res, BaseException):
                raise res
            if isinstance(res, dict):   # classic per-member fallback
                data, lens, sc, meta = res[self._member].result()
                sl = slice(self._off, self._off + self._k)
                return (data[sl], lens[sl], sc[sl],
                        SimpleNamespace(applied=meta.applied[sl]))
            data, lens, sc, applied = res.member_view(
                self._member, self._off, self._k)
            return data, lens, sc, SimpleNamespace(applied=applied)

        def block(self):
            try:
                self.result()
            except Exception:  # lint: broad-except-ok settle-only; result() re-raises at the merge
                pass
            return self

        def ready(self) -> bool:
            return True

        def hints(self) -> dict[int, int]:
            res = self._state["results"].get(self._cap)
            if isinstance(res, spmd_mod.SpmdClassResult):
                return res.dup_hints(self._member, self._off, self._k,
                                     self._slots)
            return {}

    class _SpmdPlan:
        """Per-case staging for the fused dispatch: shard_dispatch banks
        each local member's class groups here instead of launching one
        step per (shard, class); launch() then fires ONE compiled
        program per capacity class across every staged member. In-case
        redispatch rounds (a member revoked at dispatch time) merge
        their groups into the same launch, so the one-dispatch-per-
        (case, class) invariant holds through requeues. A fused-launch
        failure degrades that class to the classic per-member path,
        byte-identically (pad rows and scan_len are bit-neutral)."""

        def __init__(self):
            self.cur = None

        def begin_case(self):
            self.cur = {"staged": {}, "results": {}, "max_len": {}}

        def stage(self, shard_id: int, cap: int, group: dict,
                  max_len: int):
            st = self.cur
            member = spmd_members[shard_id]
            key = (cap, member)
            g0 = st["staged"].get(key)
            if g0 is None:
                off = 0
                st["staged"][key] = group
            else:
                off = len(g0["slots"])
                st["staged"][key] = {
                    "table": np.concatenate([g0["table"],
                                             group["table"]]),
                    "lens": np.concatenate([g0["lens"], group["lens"]]),
                    "slots": list(g0["slots"]) + list(group["slots"]),
                    "sc": np.concatenate([g0["sc"], group["sc"]]),
                    # spill rows index the member's LOCAL row order:
                    # the appended group's rows sit after g0's
                    "spill_rows": np.concatenate(
                        [g0["spill_rows"], group["spill_rows"] + off]),
                    "spill_panel": np.concatenate(
                        [g0["spill_panel"], group["spill_panel"]]),
                }
            st["max_len"][cap] = max(st["max_len"].get(cap, 0),
                                     int(max_len))
            return _SpmdSlice(st, cap, member, off,
                              len(group["slots"]), group["slots"])

        def launch(self, case: int):
            st = self.cur
            arenas = [shards[s].arena._arena for s in local_member_ids]
            for cap in sorted({c for c, _m in st["staged"]}):
                groups = [st["staged"].get((cap, m))
                          for m in range(spmd_engine.n)]
                sl = scan_bound(st["max_len"][cap], cap)
                try:
                    with trace.span("fleet.spmd_dispatch", case=case,
                                    capacity=cap,
                                    members=sum(g is not None
                                                for g in groups)):
                        res = spmd_engine.run_class(arenas, groups, base,
                                                    case, cap, sl)
                    step_shapes.add((res.kp, cap, sl))
                    st["results"][cap] = res
                except Exception as e:  # lint: broad-except-ok fused failure degrades to the classic per-member path
                    spmd_mod.STATS["fallbacks"] += 1
                    metrics.GLOBAL.record_event("spmd_fallback")
                    logger.log("warning", "fleet: fused spmd launch "
                               "failed for class %d at case %d (%s) — "
                               "classic per-member dispatch", cap,
                               case, e)
                    try:
                        st["results"][cap] = self._classic(cap, groups,
                                                           case, sl)
                    except Exception as e2:  # lint: broad-except-ok stored; slices re-raise it into the FleetShardLost path
                        st["results"][cap] = e2

        def _classic(self, cap: int, groups, case: int, sl: int) -> dict:
            """Per-member fallback over the staged arrays: the same
            gather + overlay + step_async recipe as the non-spmd
            dispatch (uniform scan_len, which is bit-neutral)."""
            futs: dict[int, object] = {}
            try:
                for m, g in enumerate(groups):
                    if g is None:
                        continue
                    sh = shards[local_member_ids[m]]
                    k = len(g["slots"])
                    kp = max(8, _next_pow2(k))
                    pad = np.arange(kp, dtype=np.int32) % k
                    with jax.default_device(sh.device):
                        data_dev = sh.arena.gather(g["table"][pad])
                        if g["spill_rows"].shape[0]:
                            data_dev = data_dev.at[g["spill_rows"]].set(
                                g["spill_panel"])
                        idx = np.concatenate([
                            np.asarray(g["slots"], np.int32),
                            batch + np.arange(kp - k, dtype=np.int32),
                        ]).astype(np.int32)
                        futs[m] = step_async(step, base, case, idx,
                                             data_dev, g["lens"][pad],
                                             g["sc"][pad], scan_len=sl)
                    step_shapes.add((kp, cap, sl))
            except BaseException:  # lint: broad-except-ok re-raised after settling in-flight futures
                drain_futures(futs.values())
                raise
            return futs

    spmd_plan = _SpmdPlan()

    def remote_dispatch(shard: _Remote, case: int, slots: list[int],
                        ids, samples):
        """Map step for one REMOTE shard's slice, fire-and-forget: the
        step frame carries (global slots, seed ids, score rows) in the
        header and ONLY non-snapshot payloads in the blob, then returns
        a _PendingRemote immediately — the shard computes while the
        coordinator dispatches the other shards (r14 blocked here,
        serializing the fleet). When the shard's window fills, a
        shard_sync barrier frame follows; its ack is consumed with the
        step reply at the reduce. RemoteShardError (incl. injected
        dist.shard.* faults) flows into the same revoke/redispatch
        path as a local device loss."""
        epoch = placement.lease_epoch_of(shard.id)
        t_a = time.perf_counter()
        shard.ensure_lease(epoch)
        sub_sids = [ids[s] for s in slots]
        inline_sids: list[str] = []
        inline_lens: list[int] = []
        blobs: list[bytes] = []
        for sid, slot in zip(sub_sids, slots):
            if sid not in shard.snap_sids:
                inline_sids.append(sid)
                inline_lens.append(len(samples[slot]))
                blobs.append(samples[slot])
        header = {
            "op": "shard_step", "shard": shard.id, "epoch": int(epoch),
            "case": int(case), "slots": [int(s) for s in slots],
            "sids": sub_sids, "inline_sids": inline_sids,
            "inline_lens": inline_lens,
            "scores": [[int(x) for x in scores[s]] for s in slots],
        }
        # propagate the per-case trace context so the worker's
        # shard.step span parents onto this coordinator's fleet.case
        # span; keys are omitted entirely with tracing off, keeping
        # the wire bytes identical
        ctx_tid, ctx_span = trace.current_context()
        if ctx_tid:
            header["trace"] = ctx_tid
            header["span"] = ctx_span
        with trace.span("fleet.remote_dispatch", case=case,
                        shard=shard.id, rows=len(slots),
                        inline=len(inline_sids)):
            shard.stream.send(header, b"".join(blobs))
        shard.stream.unsynced += 1
        sync = shard.stream.unsynced >= fleet_window
        tele = False
        if sync:
            shard.stream.send({"op": "shard_sync", "shard": shard.id,
                               "epoch": int(epoch), "case": int(case)})
            # piggyback one out-of-band telemetry exchange on the window
            # fence; a chaos obs.telemetry firing drops it (counted as
            # telemetry_lost) and nothing downstream changes
            tele = request_telemetry(shard.stream, int(epoch),
                                     int(case))
        metrics.GLOBAL.record_stage("remote_step",
                                    time.perf_counter() - t_a)
        return [(list(slots), len(slots),
                 _PendingRemote(shard.stream, epoch, case, len(slots),
                                sync, step_shapes, tele=tele))]

    def shard_dispatch(shard, case: int, slots: list[int],
                       ids, samples):
        """Map step for one shard's slice: adopt queued offspring,
        ensure residency in the shard's arena (idempotent — migrated
        seeds upload on first touch), build one page table PER CAPACITY
        CLASS, and dispatch one ragged step per class keyed on the
        GLOBAL slot indices. Returns a list of (global slots, rows, fut)
        entries, one per class present in the slice. Raises on device
        error (incl. injected shard.step faults). Remote shards route to
        remote_dispatch — behind the SAME shard.step fault point, so a
        shard.step chaos spec kills local and remote shards alike."""
        chaos.fault_point("shard.step")
        if isinstance(shard, _Remote):
            return remote_dispatch(shard, case, slots, ids, samples)
        arena = shard.arena
        sub_ids = [ids[s] for s in slots]
        sub_samples = [samples[s] for s in slots]
        t_a = time.perf_counter()
        launched_here: list[tuple[list[int], int, object]] = []
        with jax.default_device(shard.device):
            with trace.span("fleet.assemble", case=case, shard=shard.id,
                            rows=len(slots)):
                if adopt_on:
                    arena.adopt_pending(tick=case)
                for sid, data in zip(sub_ids, sub_samples):
                    arena.ensure(sid, data, case)
                arena.flush()
                arena.maybe_defrag()
                groups = arena.tables_for(sub_ids, sub_samples, tick=case)
            t_d = time.perf_counter()
            if spmd_engine is not None and shard.id in spmd_members:
                # r19 --spmd: bank this member's class groups on the
                # per-case plan — ONE fused program per class launches
                # for the whole board after the map loop (plan.launch).
                # Slot keys, cyclic padding and spill panels match the
                # per-shard dispatch below, so bytes do too.
                for g in groups:
                    k = int(g.rows.shape[0])
                    g_slots = [slots[int(r)] for r in g.rows]
                    panel = np.zeros((len(g.spilled), g.capacity),
                                     np.uint8)
                    for j, r in enumerate(g.spilled):
                        s = sub_samples[int(g.rows[r])][:g.capacity]
                        panel[j, :len(s)] = np.frombuffer(s, np.uint8)
                    fut = spmd_plan.stage(
                        shard.id, int(g.capacity),
                        {"table": np.asarray(g.table, np.int32),
                         "lens": np.asarray(g.lens, np.int32),
                         "slots": g_slots,
                         "sc": scores[np.asarray(g_slots, np.int32)],
                         "spill_rows": np.asarray(g.spilled, np.int32),
                         "spill_panel": panel},
                        int(g.lens.max()))
                    launched_here.append((g_slots, k, fut))
                metrics.GLOBAL.record_stage("assemble", t_d - t_a)
                metrics.GLOBAL.record_stage(
                    "dispatch", time.perf_counter() - t_d)
                return launched_here
            try:
                for g in groups:
                    k = int(g.rows.shape[0])
                    # pow2 cyclic row padding bounds the compiled-shape
                    # set exactly like the bucket assembler: pad rows
                    # repeat real rows, get out-of-range slot indices,
                    # and their outputs are discarded
                    kp = max(8, _next_pow2(k))
                    pad = np.arange(kp, dtype=np.int32) % k
                    table_p = g.table[pad]
                    lens_p = g.lens[pad]
                    data_dev = arena.gather(table_p)
                    if g.spilled:
                        ks = len(g.spilled)
                        ksp = max(8, _next_pow2(ks))
                        rows_idx = np.asarray(
                            (g.spilled + [g.spilled[0]] * (ksp - ks))[:ksp],
                            np.int32)
                        panel = np.zeros((ksp, g.capacity), np.uint8)
                        for j, r in enumerate(g.spilled):
                            s = sub_samples[int(g.rows[r])][:g.capacity]
                            panel[j, :len(s)] = np.frombuffer(s, np.uint8)
                        panel[ks:] = panel[0]
                        data_dev = data_dev.at[rows_idx].set(panel)
                    g_slots = [slots[int(r)] for r in g.rows]
                    idx = np.concatenate([
                        np.asarray(g_slots, np.int32),
                        batch + np.arange(kp - k, dtype=np.int32),
                    ]).astype(np.int32)
                    gather = np.asarray(
                        [g_slots[j % k] for j in range(kp)], np.int32)
                    sc_in = scores[gather]
                    sl = scan_bound(int(g.lens.max()), g.capacity)
                    step_shapes.add((kp, g.capacity, sl))
                    with trace.span("fleet.dispatch", case=case,
                                    shard=shard.id, rows=k,
                                    capacity=g.capacity):
                        fut = step_async(step, base, case, idx, data_dev,
                                         lens_p, sc_in, scan_len=sl)
                    launched_here.append((g_slots, k, fut))
            except BaseException:  # lint: broad-except-ok re-raised after settling in-flight futures
                # a fault on class K's dispatch must not strand this
                # shard's earlier class futures: settle them before the
                # revoke/redispatch path (or the caller) unwinds
                drain_futures(f for _sl, _r, f in launched_here)
                raise
        t_e = time.perf_counter()
        metrics.GLOBAL.record_stage("assemble", t_d - t_a)
        metrics.GLOBAL.record_stage("dispatch", t_e - t_d)
        return launched_here

    def probe_shard(shard):
        """One tiny forced op on the shard's device — or, for a remote
        shard, a shard_probe round-trip to its worker. The shard.step
        fault point runs first so a still-armed persistent spec keeps
        probes failing — re-admission happens exactly when the fault
        clears (same discipline as the single-device runner's probe)."""
        chaos.fault_point("shard.step")
        if isinstance(shard, _Remote):
            with trace.span("fleet.probe", shard=shard.id):
                shard.stream.request(
                    {"op": "shard_probe", "shard": shard.id},
                    expect="shard_alive",
                    timeout=min(fleet_timeout, 10.0))
            return
        with jax.default_device(shard.device):
            jnp.zeros(8).block_until_ready()

    def oracle_slots(case: int, ids, slots: list[int]) -> dict[int, bytes]:
        """Last-resort host serve (fleet fully down): deterministic per
        (seed, case, slot) — same stream as the single-device runner's
        degraded mode, so even total-loss runs replay."""
        from ..oracle.engine import fuzz as oracle_fuzz

        a1, a2, a3 = opts["seed"]
        muta = opts.get("mutations") or default_mutations()
        results: dict[int, bytes] = {}
        t_w = time.perf_counter()
        with trace.span("fleet.oracle_fallback", case=case):
            for slot in slots:
                data = store.get(ids[slot])[:device_max]
                results[slot] = oracle_fuzz(
                    data, seed=(a1 + case, a2 + slot, a3), mutations=muta)
        metrics.GLOBAL.record_stage("oracle_fallback",
                                    time.perf_counter() - t_w)
        return results

    def revoke_shard(shard_id: int, case: int, err) -> dict:
        """Lease revocation + redistribution. The shard.migrate fault
        point guards the migration apply: an injected fault here forces
        one idempotent re-apply (the assignment recompute is pure), so
        the path is injectable without ever leaving partitions
        unowned — outputs must not change (tests pin this)."""
        logger.log("warning", "fleet: shard %d lost at case %d (%s) — "
                   "redistributing its partitions", shard_id, case, err)
        metrics.GLOBAL.record_event("shard_lost")
        entry = placement.revoke(shard_id, case)
        sh = shards[shard_id]
        if isinstance(sh, _Remote):
            # best-effort fence: raise the worker's floor so anything
            # still in flight from this lease is rejected worker-side
            # too. An unreachable worker is fenced anyway — its readmit
            # lease will carry a strictly higher epoch. The old stream
            # is closed FIRST so stale in-flight replies die with the
            # connection instead of desynchronizing a fresh request.
            sh._leased = None
            sh.snap_sids = frozenset()
            sh.stream.close()
            try:
                with trace.span("fleet.revoke", shard=shard_id,
                                case=case):
                    sh.stream.request(
                        {"op": "shard_revoke", "shard": shard_id,
                         "epoch": entry["epoch"]},
                        expect="shard_revoked")
            except (OSError, RemoteShardError):
                pass
            sh.stream.close()
        try:
            chaos.fault_point("shard.migrate")
        except OSError:
            metrics.GLOBAL.record_event("shard_migrate_retry")
            entry = {**entry, "retried": True}
            placement.migrations[-1] = entry
        flight.GLOBAL.note("shard_migration", migration="revoke",
                           shard=shard_id, case=case, epoch=entry["epoch"],
                           moved={str(k): v
                                  for k, v in entry["moved"].items()})
        members.record("evict", shard_id, case, entry["epoch"])
        metrics.GLOBAL.record_fleet(placement.snapshot())
        record_membership()
        return entry

    def try_readmit(shard_id: int, case: int) -> bool:
        """Probe a dead shard; on success re-grant its lease. The
        shard.migrate fault point guards the re-grant — an injected
        fault cancels re-admission (the shard stays dead until the next
        probe window), exercising the probe-again path."""
        try:
            probe_shard(shards[shard_id])
        except Exception:  # lint: broad-except-ok probe failure = shard still down
            return False
        try:
            chaos.fault_point("shard.migrate")
        except OSError:
            metrics.GLOBAL.record_event("shard_readmit_aborted")
            return False
        if isinstance(shards[shard_id], _Shard):
            # the old arena tensor died with the device: rebuild empty,
            # then warm-start it from a store-built snapshot of the
            # shard's HOME partition so the readmitted device serves
            # its first case without a lazy per-seed re-upload storm.
            # (A remote shard has no local arena — its re-grant AND
            # snapshot ship lazily via ensure_lease at the readmit
            # epoch.) The fleet.snapshot fault point degrades this to
            # the r14 lazy path — identity tests pin that bytes match.
            with jax.default_device(shards[shard_id].device):
                shards[shard_id].arena.reset()
            warm = True
            try:
                chaos.fault_point("fleet.snapshot")
            except OSError:
                metrics.GLOBAL.record_event("fleet_snapshot_skipped")
                warm = False
            if warm:
                home = [sid for sid in store.ids()
                        if partition_of(sid, n_shards) == shard_id]
                if home:
                    snap = build_arena_snapshot(
                        store.get, home, classes, page,
                        classify=_classify,
                        epoch=placement.epoch + 1, token=fleet_token)
                    with jax.default_device(shards[shard_id].device):
                        restored = shards[shard_id].arena.restore_snapshot(
                            snap, tick=case)
                    metrics.GLOBAL.record_event("fleet_snapshot_restored")
                    flight.GLOBAL.note(
                        "fleet_warm_start", shard=shard_id, case=case,
                        seeds=restored, bytes=int(snap.pages.size),
                        crc=snap.crc)
        entry = placement.readmit(shard_id, case)
        logger.log("warning", "fleet: shard %d re-admitted at case %d — "
                   "taking its partitions back", shard_id, case)
        metrics.GLOBAL.record_event("shard_readmitted")
        flight.GLOBAL.note("shard_migration", migration="readmit",
                           shard=shard_id, case=case, epoch=entry["epoch"],
                           moved={str(k): v
                                  for k, v in entry["moved"].items()})
        members.record("readmit", shard_id, case, entry["epoch"])
        metrics.GLOBAL.record_fleet(placement.snapshot())
        record_membership()
        return True

    def graceful_drain(shard_id: int, case: int) -> bool:
        """Planned departure (r20): take the shard out of the live set
        WITHOUT the crash machinery — no breaker trip, no slice rewind
        (the fence runs on quiescent streams, so nothing is in flight
        to lose). The worker gets a fleet_drain handshake (best-effort:
        it is already out of the live set when the request leaves, so a
        worker that dies mid-goodbye degrades to a log line, never a
        double migration), and the slot becomes VACANT — joinable by
        the next hot-join candidate. The fleet.drain fault site
        abandons the polite handoff and falls back to the revoke path:
        a drain dying half-way is exactly a shard loss, outputs
        unchanged."""
        sh = shards.get(shard_id)
        if sh is None or not placement.is_live(shard_id):
            return False
        if spmd_engine is not None and shard_id in spmd_members:
            # a mesh member's arena is part of the fused program's
            # zero-copy assembly — elastic departure of mesh slots is
            # future work (ROADMAP item 1 carried notes)
            logger.log("warning", "fleet: shard %d is an SPMD mesh "
                       "member — drain refused", shard_id)
            return False
        try:
            chaos.fault_point("fleet.drain")
        except OSError:
            metrics.GLOBAL.record_event("fleet_drain_faulted")
            revoke_shard(shard_id, case, "drain handoff faulted")
            return True
        entry = placement.drain(shard_id, case)
        if isinstance(sh, _Remote):
            try:
                with trace.span("fleet.drain", shard=shard_id,
                                case=case):
                    sh.stream.request(
                        {"op": "fleet_drain", "shard": shard_id,
                         "epoch": entry["epoch"]},
                        expect="fleet_drained",
                        timeout=min(fleet_timeout, 10.0))
            except (OSError, RemoteShardError) as e:
                logger.log("warning", "fleet: drain handshake with "
                           "shard %d failed (%s) — it is already out "
                           "of the live set", shard_id, e)
            sh.stream.close()
        shards[shard_id] = None
        logger.log("warning", "fleet: shard %d drained at case %d "
                   "(planned departure — partitions handed back, no "
                   "rewind)", shard_id, case)
        metrics.GLOBAL.record_event("shard_drained")
        flight.GLOBAL.note("shard_membership", change="drain",
                           shard=shard_id, case=case,
                           epoch=entry["epoch"])
        members.record("drain", shard_id, case, entry["epoch"])
        metrics.GLOBAL.record_fleet(placement.snapshot())
        record_membership()
        return True

    def admit_join(ev: dict, case: int) -> bool:
        """Hot-join admission (r20): bind an announced worker to the
        lowest vacant slot (else replace the lowest provably-dead
        remote backend), bump the fencing epoch via placement.join, and
        let ensure_lease warm-start it lazily at its first dispatch.
        Campaign byte-identity holds because the LOGICAL shard count is
        fixed — admission changes tenancy, never stream keying. The
        fleet.join fault site aborts the admit before any state moves:
        the candidate stays out (it may re-announce), placement and
        outputs are byte-identical to a run it never contacted."""
        host = str(ev.get("host") or "127.0.0.1")
        port = int(ev.get("port") or 0)
        who = f"{host}:{port}"
        slot = next((s for s in range(n_shards)
                     if shards[s] is None), None)
        if slot is None:
            slot = next((s for s in range(remote_slots)
                         if not placement.is_live(s)
                         and isinstance(shards[s], _Remote)), None)

        def reject(reason: str) -> bool:
            logger.log("warning", "fleet: hot-join from %s rejected "
                       "(%s)", who, reason)
            metrics.GLOBAL.record_event("fleet_join_rejected")
            members.record("join_rejected",
                           -1 if slot is None else slot, case,
                           placement.epoch)
            record_membership()
            return False

        try:
            chaos.fault_point("fleet.join")
        except OSError:
            return reject("injected fault")
        tok = str(ev.get("token") or "")
        if tok and tok != fleet_token:
            return reject("campaign token mismatch")
        if ev.get("classes") is not None and (
                [int(c) for c in ev["classes"]]
                != [int(c) for c in classes]):
            return reject("capacity-class mismatch")
        if not 0 < port < 65536:
            return reject(f"bad announce port {port}")
        if slot is None:
            return reject("no vacant or replaceable shard slot")
        old = shards[slot]
        if isinstance(old, _Remote):
            # replacing a dead backend: kill its stream first so a
            # zombie reply can never land on the fresh tenant's slot
            old.stream.close()
        shards[slot] = _Remote(slot, host, port)
        entry = placement.join(slot, case)
        logger.log("warning", "fleet: worker %s hot-joined as shard "
                   "%d at case %d (epoch %d)", who, slot, case,
                   entry["epoch"])
        metrics.GLOBAL.record_event("fleet_joined")
        flight.GLOBAL.note("shard_membership", change="join",
                           shard=slot, case=case, epoch=entry["epoch"],
                           worker=who)
        members.record("join", slot, case, entry["epoch"])
        metrics.GLOBAL.record_fleet(placement.snapshot())
        record_membership()
        return True

    def membership_fence(case: int) -> None:
        """The single admission point for ALL membership change (r20):
        runs at the top of the case loop strictly AFTER
        wait_done(case-1), when every step reply has been consumed —
        streams are quiescent, so a drain can never strand an in-flight
        reply (zero slice rewinds on planned departure, by
        construction). Processing order is deterministic: scheduled
        churn events first (schedule order), then reply-header drain
        requests (shard-id order), then listener announcements (arrival
        order). Placement is a pure function of the live SET, so none
        of this ordering can change campaign bytes — only tenancy."""
        while (churn_schedule
               and int(churn_schedule[0].get("case", 0)) <= case):
            ev = churn_schedule.pop(0)
            kind = ev["kind"]
            if kind == "kill":
                s = int(ev["shard"])
                if placement.is_live(s) and shards.get(s) is not None:
                    revoke_shard(s, case, "churn-schedule kill")
            elif kind == "drain":
                graceful_drain(int(ev["shard"]), case)
            else:
                admit_join(ev, case)
        for s in sorted(shards):
            sh = shards[s]
            if (isinstance(sh, _Remote) and sh.stream.draining
                    and placement.is_live(s)):
                # the worker stamped "draining" on a reply header
                # (SIGTERM): honor it now that its window is merged
                graceful_drain(s, case)
        if listener is not None:
            for ev in listener.take():
                admit_join(ev, case)

    def process_case(work):
        """Reduce for one case — runs ON THE DRAIN WORKER, strictly in
        case order (r15 overlapped reduce): force the shard replies,
        merge by slot, fold novelty / energy / feedback in, then write
        the outputs. The merge of case N overlaps the map of case N+1
        on the main thread; ordering keeps N-shard == 1-shard
        byte-identity intact. Writes happen AFTER mark_done (the main
        thread only needs the merged state, not the files) — except on
        a checkpoint case, where the single-device ordering contract
        (outputs before checkpoint before done) still holds. A reply
        that never arrives surfaces as FleetShardLost into the
        coordinator's rewind."""
        case_i, ids = work.case, work.ids
        # cross-thread parenting: the map thread stamped its fleet.case
        # span id into the work item, so this thread's reduce spans join
        # the same case tree in the merged trace
        case_parent = int(getattr(work, "span", 0) or 0)
        try:
            chaos.fault_point("fleet.reduce")
        except OSError:
            # the merge below is pure over replies the coordinator
            # already owns: an injected reduce fault costs one logged
            # re-apply, never data loss — outputs must not change
            metrics.GLOBAL.record_event("fleet_reduce_retry")
        t_r = time.perf_counter()
        parts: list[dict[int, bytes]] = []
        # slot -> (producing shard, device output buffer, row): adoption
        # sources for the novelty walk below (arena output buffers are
        # never donated in the fleet, so holding them here is safe)
        devsrc: dict[int, tuple] = {}
        # score scatters DEFER until every entry forced cleanly: a
        # FleetShardLost mid-loop must leave the table exactly as the
        # case's dispatch read it, or the replayed slice (and a full
        # rewind's re-dispatch) would gather partially-merged rows
        score_writes: list[tuple] = []
        dup_of: dict[int, int] = {}
        shard_id = -1
        try:
            for shard_id, slots, rows, fut in work.launched:
                with trace.span_remote("fleet.drain", parent=case_parent,
                                       case=case_i, rows=rows):
                    if isinstance(fut, _PendingRemote):
                        fut = fut.force()
                    new_data, new_lens, new_sc, meta = fut.result()
                    outs = unpack(Batch(new_data[:rows], new_lens[:rows]))
                parts.append({slot: outs[j]
                              for j, slot in enumerate(slots)})
                if isinstance(fut, _SpmdSlice):
                    dup_of.update(fut.hints())
                if adopt_on and isinstance(shards[shard_id], _Shard):
                    # remote shards never register adoption sources:
                    # there is no local device buffer to splice from, so
                    # their offspring take the lazy-upload path
                    for j, slot in enumerate(slots):
                        devsrc[slot] = (shard_id, new_data, j)
                score_writes.append((np.asarray(slots, np.int32),
                                     np.asarray(new_sc[:rows])))
                applied = meta.applied[:rows].ravel()
                applied = applied[applied >= 0]
                if applied.size:
                    counts = np.bincount(applied,
                                         minlength=len(DEVICE_CODES))
                    for mi in np.nonzero(counts)[0]:
                        metrics.GLOBAL.record_mutator(
                            DEVICE_CODES[mi], applied=True,
                            n=int(counts[mi]))
        except BaseException as e:  # lint: broad-except-ok shard losses become FleetShardLost for the rewind; the rest re-raise
            # settle local futures the merge will never read; remote
            # pendings die with their streams at the rewind
            drain_futures(
                f for _sh, _sl, _r, f in work.launched
                if not isinstance(f, (_PendingRemote, _RemoteResult)))
            if isinstance(e, RemoteShardError) or is_device_error(e):
                raise FleetShardLost(shard_id, case_i, e) from e
            raise
        for w_slots, w_sc in score_writes:
            scores[w_slots] = w_sc
        if work.host_slots:
            tallies["oracle_cases"] += 1
            parts.append(oracle_slots(case_i, ids, work.host_slots))
        # schedule-hit bookkeeping lands HERE, not at the draw: a case's
        # counts commit exactly when its merge does, so an attempt
        # abandoned by a rewind leaves the weights untouched and the
        # replayed draw reproduces the reference schedule. Ordering vs
        # the single-device runner is unchanged — case N's counts are
        # still applied before case N+1's draw (which waits on this
        # merge), and before the checkpoint's store.save below.
        sched_counts: dict[str, int] = {}
        for sid in ids:
            sched_counts[sid] = sched_counts.get(sid, 0) + 1
        store.record_scheduled(sched_counts)
        results = merge_shard_results(parts)
        drain_s = time.perf_counter() - t_r
        metrics.GLOBAL.record_stage("remote_wait", drain_s)
        device_s = drain_s + (t_r - work.t_map)
        metrics.GLOBAL.observe("batch_latency", device_s)

        # coverage pre-pass (r19 fleet coverage): pull this case's
        # buffered bitmaps off the hub and fold them into the GATING
        # index; each frame also ORs onto its seed's HOME shard's
        # attribution ledger. Runs strictly AFTER the force loop — an
        # aborted case never consumes its frames, so a rewound replay
        # folds them identically. Hub death is STICKY (PR 16): the rest
        # of the run is pure hash-novelty, byte-identically.
        slot_gain = None
        if cov is not None and cov_live[0]:
            if not cov_hub.alive():
                cov_live[0] = False
                logger.log("warning", "fleet: coverage hub lost at case "
                           "%d — degrading to hash-novelty", case_i)
                metrics.GLOBAL.record_event("coverage_lost")
                metrics.GLOBAL.set_coverage_degraded(True)
            else:
                frames = cov_hub.take(case_i)
                covered = [s for s in sorted(frames) if s < batch]
                pairs = [(ledger.resolve(case_i, s) or ids[s], frames[s])
                         for s in covered]
                t_f = time.perf_counter()
                try:
                    with trace.span_remote("coverage.fold",
                                           parent=case_parent,
                                           case=case_i, maps=len(pairs)):
                        gains = cov.fold_case(pairs)
                except OSError as e:
                    # injected coverage.fold fault: the whole case is
                    # treated as uncovered — observable, never diverging
                    # from the hash-novelty baseline
                    logger.log("warning", "fleet: coverage fold failed "
                               "at case %d (%s) — case uncovered",
                               case_i, e)
                    metrics.GLOBAL.record_coverage_frame("faulted")
                    slot_gain = {}
                else:
                    slot_gain = dict(zip(covered, gains))
                    for sid, frame in pairs:
                        cov_ledgers[partition_of(sid, n_shards)] \
                            .fold_map(sid, frame)
                    if covered:
                        new_edges = int(sum(gains))
                        metrics.GLOBAL.record_coverage_fold(
                            len(pairs), new_edges, cov.edges())
                        tallies["cov_maps"] += len(pairs)
                        tallies["cov_new_edges"] += new_edges
                finally:
                    metrics.GLOBAL.record_stage(
                        "coverage", time.perf_counter() - t_f)
            if cov_live[0] and (case_i + 1) % fleet_window == 0:
                # window fence: the shard ledgers' globals must
                # OR-reduce back to the gating map (attribution is a
                # partition of the folded frames) — a mismatch means an
                # attribution bug, surfaced as an event, never silently
                fused_map = np.zeros(cov.map_bytes, np.uint8)
                for cl in cov_ledgers:
                    fused_map |= cl.global_map
                if np.array_equal(fused_map, cov.global_map):
                    metrics.GLOBAL.record_event("coverage_fence_ok")
                else:
                    metrics.GLOBAL.record_event("coverage_fence_mismatch")
                    logger.log("warning", "fleet: coverage fence "
                               "mismatch at case %d — shard ledgers do "
                               "not reassemble the gating map", case_i)

        t_h = time.perf_counter()
        before = tallies["bytes_out"]
        case_adopted = [0]

        def on_novel(slot, payload):
            """Offspring adoption at the reduce: the store decides
            (dedup by content hash, capped per case); the bytes adopt
            device-side only when the producing shard owns the new
            seed's home partition — any other placement uploads lazily
            at its first schedule."""
            if not payload or case_adopted[0] >= adopt_cap:
                return
            sid_new, added = store.add(payload, origin="offspring")
            if not added:
                return
            case_adopted[0] += 1
            tallies["offspring"] += 1
            ent = devsrc.get(slot)
            if ent is None:
                return
            src_shard, src, row = ent
            if (placement.owner_of(partition_of(sid_new, n_shards))
                    == src_shard):
                shards[src_shard].arena.enqueue_adopt(
                    sid_new, len(payload), src, row)

        with trace.span_remote("fleet.hash", parent=case_parent,
                               case=case_i):
            tallies["new_hashes"] += apply_novelty(
                store, ids, results, seen_hashes, batch, tallies,
                on_novel=on_novel if adopt_on else None,
                slot_gain=slot_gain, dup_of=dup_of or None)
        tallies["total"] += len(results)
        metrics.GLOBAL.record_stage("hash", time.perf_counter() - t_h)
        metrics.GLOBAL.record_batch(len(results),
                                    tallies["bytes_out"] - before,
                                    device_s)
        if consume_feedback:
            credit = sorted(set(ids))
            for ev in bus.drain():
                store.apply_event(ev, credit=credit)
                logger.log("decision", "fleet: %s event from %s -> "
                           "energy feedback", ev.kind, ev.source or "?")

        def write_outputs():
            t_o = time.perf_counter()
            with trace.span_remote("fleet.write", parent=case_parent,
                                   case=case_i):
                for slot in range(batch):
                    payload = results.get(slot, b"")
                    if writer is not None:
                        writer(case_i * batch + slot, payload, [])
                    else:
                        sys.stdout.buffer.write(payload)
            metrics.GLOBAL.record_stage("write",
                                        time.perf_counter() - t_o)

        if state_path and ((case_i + 1) % ckpt_every == 0
                           or case_i + 1 == n_cases):
            # mirror the single-device finish_case ordering: this case's
            # outputs are written BEFORE the checkpoint marks it done (a
            # resumed run must not skip a case whose outputs never hit
            # disk), and the store snapshot follows so it contains this
            # case's adoptions when the checkpoint says they exist
            write_outputs()
            t_c = time.perf_counter()
            with trace.span_remote("fleet.checkpoint", parent=case_parent,
                                   case=case_i):
                save_fleet_state(state_path, opts["seed"], case_i + 1,
                                 scores, seen_hashes, store.energies(),
                                 placement.epoch, n_shards, classes,
                                 events=metrics.GLOBAL.event_counts(),
                                 coverage=(cov.snapshot()
                                           if cov is not None else None),
                                 membership=membership_state())
                store.save()
            metrics.GLOBAL.record_stage("checkpoint",
                                        time.perf_counter() - t_c)
            metrics.GLOBAL.record_event("fleet_checkpoint")
            drain.mark_done(case_i)
        else:
            # merged state is final: release the main thread BEFORE the
            # writes — file output of case N overlaps the schedule and
            # dispatch of case N+1 (the r15 overlapped reduce)
            drain.mark_done(case_i)
            write_outputs()
        reduce_busy[0] += time.perf_counter() - t_r
        metrics.GLOBAL.record_stage("reduce", time.perf_counter() - t_r)
        if stats is not None:
            stats["finish_times"].append(time.perf_counter())

    def discard_work(work):
        """Abandoned-queue hook at a rewind: settle local futures so no
        device work is stranded; remote pendings die with the streams
        the rewind closes."""
        drain_futures(
            f for _sh, _sl, _r, f in work.launched
            if not isinstance(f, (_PendingRemote, _RemoteResult)))

    def patch_case_slices(work, lost_shard: int):
        """Slice-granular rewind (r19): rebuild ONE case's work item
        after a shard loss — keep every entry whose reply survived,
        re-dispatch only the dead slices on the post-revoke placement.
        Recompute is pure (same GLOBAL slot keys, scores untouched by
        the aborted merge thanks to the deferred scatter), so the
        patched case merges byte-identically to a full rewind (tests
        pin slice == full). Surviving remote streams stay OPEN — their
        FIFO replies force in kept-entry order. Returns None when
        nothing is provably dead (the full rewind is always correct)."""
        dead_slots: list[int] = []
        kept: list[tuple] = []
        for ent in work.launched:
            sh_id, slots_e, _rows_e, f = ent
            dead = sh_id == lost_shard
            if isinstance(f, _PendingRemote):
                dead = dead or (not f.done and not f.stream.connected)
            elif isinstance(f, _SpmdSlice):
                # one fused launch serves every member: a lost member
                # poisons the whole class's program, so every spmd
                # slice of the case replays (pure recompute)
                dead = True
            if dead:
                dead_slots.extend(slots_e)
            else:
                kept.append(ent)
        if not dead_slots:
            return None
        # drain surviving remote replies BEFORE re-dispatching: the
        # requeue below re-leases surviving shards at the bumped epoch,
        # and a lease request must not race the undrained step replies
        # queued ahead of it on the FIFO stream. force() is idempotent
        # — the drain worker re-reads the cached result at merge time.
        # A failure here raises into the caller's full-rewind fallback.
        for _sh, _sl, _r, f in kept:
            if isinstance(f, _PendingRemote) and not f.done:
                f.force()
        ids = work.ids
        samples = [store.get(sid) for sid in ids]
        requeue: dict[int, list[int]] = {}
        host_extra: list[int] = []
        for slot in dead_slots:
            owner = placement.owner_of(partition_of(ids[slot], n_shards))
            if owner is None:
                host_extra.append(slot)
            else:
                requeue.setdefault(owner, []).append(slot)
        if spmd_engine is not None:
            spmd_plan.begin_case()
        new_entries: list[tuple] = []
        try:
            for owner, sl in sorted(requeue.items()):
                new_entries.extend(
                    (owner, *entry)
                    for entry in shard_dispatch(shards[owner], work.case,
                                                sorted(sl), ids, samples))
            if spmd_engine is not None:
                spmd_plan.launch(work.case)
        except BaseException:  # lint: broad-except-ok re-raised after settling; caller falls back to the full rewind
            drain_futures(
                f for _sh, _sl, _r, f in new_entries
                if not isinstance(f, (_PendingRemote, _RemoteResult)))
            raise
        work.launched = kept + new_entries
        work.host_slots = list(work.host_slots) + host_extra
        return work

    metrics.GLOBAL.record_fleet(placement.snapshot())
    if stats is not None:
        stats.setdefault("schedules", [])
        stats.setdefault("finish_times", [])
    counted: set[int] = set()   # cases whose run-once tallies already ran
    reduce_busy = [0.0]         # drain-thread seconds inside the merge
    waited = [0.0]              # main-thread seconds blocked on the drain
    t0 = time.perf_counter()
    probe_at = start_case
    case = start_case
    case_span = None
    drain = _DrainWorker(process_case, start_case, discard=discard_work)
    try:
        while True:
            try:
                while case < n_cases:
                    # -- re-admission probes (case-counter gated) ------
                    if placement.dead() and case >= probe_at:
                        probe_at = case + DEVICE_PROBE_EVERY
                        for s in placement.dead():
                            if shards.get(s) is None:
                                # vacant slot: fills by hot-join at the
                                # membership fence, not by probing
                                continue
                            try_readmit(s, case)

                    # the schedule is energy-weighted: case N+1 cannot
                    # draw until case N's merge lands, so the pipeline
                    # holds ONE case in flight — the window bounds sync
                    # frequency, not speculation depth
                    t_w = time.perf_counter()
                    drain.wait_done(case - 1)
                    w = time.perf_counter() - t_w
                    waited[0] += w
                    metrics.GLOBAL.record_stage("drain_wait", w)
                    if w > 0.05:
                        flight.GLOBAL.note("fleet_window_stall",
                                           case=case, waited=round(w, 4))

                    # -- membership fence (r20): the ONLY place the
                    # fleet composition changes. Case `case - 1` is
                    # fully merged and every reply consumed, so
                    # joins/drains land on quiescent streams.
                    membership_fence(case)

                    # per-case umbrella span: remote shard.step spans
                    # and the drain worker's reduce-side spans parent
                    # onto it, so the merged trace shows one case tree
                    # across threads and hosts. Managed manually — the
                    # map section has several exits (rewind included)
                    # and a `with` block can't straddle them
                    case_span = trace.span("fleet.case", case=case)
                    case_span.__enter__()
                    t_s = time.perf_counter()
                    with trace.span("fleet.schedule", case=case):
                        # record=False: schedule-hit counts decay future
                        # draw weights, so they must land exactly once
                        # per MERGED case — the drain's process_case
                        # applies them. Recording here would let an
                        # aborted attempt (rewind) inflate hits and skew
                        # the replayed draw off the reference bytes.
                        ids = sched.schedule(case, batch, record=False)
                        samples = [store.get(sid) for sid in ids]
                    # attribution ledger BEFORE launch: the coverage
                    # fold resolves (case, slot) -> seed through it
                    ledger.record(case, ids)
                    metrics.GLOBAL.record_stage(
                        "schedule", time.perf_counter() - t_s)
                    if case not in counted:
                        # a rewind replays cases: run-once tallies and
                        # the schedule log count each case exactly once
                        counted.add(case)
                        if stats is not None:
                            stats["schedules"].append(list(ids))
                        trunc = sum(len(s) > trunc_cap for s in samples)
                        if trunc:
                            tallies["truncated"] += trunc
                            metrics.GLOBAL.record_truncated(trunc)

                    # -- map: partition slots by lease, dispatch -------
                    by_shard: dict[int, list[int]] = {}
                    host_slots: list[int] = []
                    for slot, sid in enumerate(ids):
                        owner = placement.owner_of(
                            partition_of(sid, n_shards))
                        if owner is None:
                            host_slots.append(slot)
                        else:
                            by_shard.setdefault(owner, []).append(slot)
                    pending = sorted(by_shard.items())
                    # (shard_id, global slots, rows, fut) per entry
                    launched: list[tuple[int, list[int], int,
                                         object]] = []
                    t_map = time.perf_counter()
                    if spmd_engine is not None:
                        spmd_plan.begin_case()
                    try:
                        while pending:
                            shard_id, slots = pending.pop(0)
                            try:
                                launched.extend(
                                    (shard_id, *entry)
                                    for entry in shard_dispatch(
                                        shards[shard_id], case,
                                        slots, ids, samples))
                            except Exception as e:  # lint: broad-except-ok re-raised below unless a shard loss
                                # a remote shard loss (timeout, protocol
                                # error, or a FENCED stale reply) is the
                                # cross-host spelling of a device error:
                                # same revoke + in-case redispatch
                                if not (is_device_error(e)
                                        or isinstance(e,
                                                      RemoteShardError)):
                                    raise
                                revoke_shard(shard_id, case, e)
                                # the failed slice re-partitions onto
                                # its new owners and re-dispatches
                                # WITHIN this case — same global slot
                                # indices, so the re-served bytes are
                                # identical. Steps already fired at the
                                # dead stream will never be answered:
                                # sweep them into the requeue too
                                tallies["redispatches"] += 1
                                slots = list(slots)
                                kept = []
                                for ent in launched:
                                    f = ent[3]
                                    if (isinstance(f, _PendingRemote)
                                            and not f.done
                                            and not f.stream.connected):
                                        slots.extend(ent[1])
                                    else:
                                        kept.append(ent)
                                launched = kept
                                requeue: dict[int, list[int]] = {}
                                for slot in slots:
                                    owner = placement.owner_of(
                                        partition_of(ids[slot],
                                                     n_shards))
                                    if owner is None:
                                        host_slots.append(slot)
                                    else:
                                        requeue.setdefault(
                                            owner, []).append(slot)
                                merged = dict(pending)
                                for owner, sl in requeue.items():
                                    merged[owner] = sorted(
                                        merged.get(owner, []) + sl)
                                pending = sorted(merged.items())
                    except BaseException:  # lint: broad-except-ok re-raised after settling in-flight futures
                        # a non-device error mid-map must not strand the
                        # survivors' in-flight futures: settle the local
                        # ones before unwinding (remote pendings die
                        # with their streams)
                        drain_futures(
                            f for _sh, _sl, _r, f in launched
                            if not isinstance(f, (_PendingRemote,
                                                  _RemoteResult)))
                        raise
                    if spmd_engine is not None:
                        # requeue rounds merged their groups into the
                        # plan above — this is the case's ONE fused
                        # launch per staged capacity class
                        spmd_plan.launch(case)
                    if host_slots:
                        logger.log("warning", "fleet: no live shards at "
                                   "case %d — host oracle serves %d "
                                   "slot(s)", case, len(host_slots))

                    # -- reduce: hand the case to the drain worker -----
                    drain.submit(SimpleNamespace(
                        case=case, ids=ids, launched=launched,
                        host_slots=host_slots, t_map=t_map,
                        span=case_span.span_id))  # lint: no-wallclock-nondeterminism-ok span id only parents reduce-side spans, never feeds replay values
                    case_span.__exit__(None, None, None)
                    case_span = None
                    if reduce_mode == "boundary":
                        # --fleet-reduce boundary: the r14 lockstep —
                        # every case fully merges before the next maps
                        drain.wait_done(case)
                    case += 1
                drain.close()
                break
            except FleetShardLost as e:
                if case_span is not None:  # lint: no-wallclock-nondeterminism-ok stack hygiene on the abandoned span, no replay value involved
                    # the abandoned case's umbrella span must come off
                    # this thread's stack or every later span would
                    # parent onto it
                    case_span.__exit__(None, None, None)
                    case_span = None
                # a dispatched reply was lost after its case left the
                # map: the merged prefix is intact (merges run in case
                # order), so revoke the shard, drop every stream, and
                # replay from the first un-merged case. The replayed
                # schedule draws identically — energies and scores only
                # mutate at merges, and none landed past the rewind
                # point — so the rewound run stays byte-identical.
                redo = drain.done_case + 1
                failed = drain.failed_item
                drain.abandon()
                if placement.is_live(e.shard):
                    revoke_shard(e.shard, e.case, e.cause)
                patched = None
                if (rewind_mode == "slice" and failed is not None
                        and failed.case == redo):
                    try:
                        patched = patch_case_slices(failed, e.shard)
                    except Exception as pe:  # lint: broad-except-ok slice patch is best-effort; the full rewind below is always correct
                        logger.log("warning", "fleet: slice patch "
                                   "failed at case %d (%s) — full "
                                   "rewind", redo, pe)
                        patched = None
                if patched is not None:
                    # slice-granular rewind: only the dead slices
                    # recompute; surviving shard replies (and their
                    # streams) are kept, so the fleet never replays
                    # work whose results it already holds
                    tallies["slice_rewinds"] += 1
                    metrics.GLOBAL.record_event("fleet_slice_rewind")
                    flight.GLOBAL.note("fleet_slice_rewind",
                                       shard=e.shard, case=e.case,
                                       redo=redo)
                    logger.log("warning", "fleet: shard %d reply lost "
                               "at case %d — replaying only its slice",
                               e.shard, redo)
                    drain = _DrainWorker(process_case, redo,
                                         discard=discard_work)
                    drain.submit(patched)
                    case = redo + 1
                    continue
                for sh in shards.values():
                    if isinstance(sh, _Remote):
                        sh.stream.close()
                tallies["rewinds"] += 1
                metrics.GLOBAL.record_event("fleet_rewind")
                flight.GLOBAL.note("fleet_rewind", shard=e.shard,
                                   case=e.case, redo=redo)
                logger.log("warning", "fleet: shard %d reply lost at "
                           "case %d — rewinding pipeline to case %d",
                           e.shard, e.case, redo)
                drain = _DrainWorker(process_case, redo,
                                     discard=discard_work)
                case = redo
    finally:
        for sh in shards.values():
            if isinstance(sh, _Remote):
                sh.stream.close()
        if listener is not None and not opts.get("membership_listener"):
            listener.close()

    store.save()
    record_membership()
    dt = time.perf_counter() - t0
    metrics.GLOBAL.record_pipeline_wall(dt)
    metrics.GLOBAL.record_fleet(placement.snapshot())
    # overlap ratio: fraction of the drain worker's merge time the main
    # thread did NOT spend blocked waiting for it (1.0 = fully hidden)
    reduce_overlap = (max(0.0, min(1.0, (reduce_busy[0] - waited[0])
                                   / reduce_busy[0]))
                      if reduce_busy[0] > 0 else 0.0)
    metrics.GLOBAL.set_reduce_overlap(reduce_overlap)
    for shard in shards.values():
        if isinstance(shard, _Shard):
            metrics.GLOBAL.record_arena(shard.arena.stats())
    total, new_hashes = tallies["total"], tallies["new_hashes"]
    if tallies["truncated"]:
        print(f"# {tallies['truncated']} scheduled samples exceeded the "
              f"fleet capacity class ({trunc_cap}B) and were truncated",
              file=sys.stderr)
    if stats is not None:
        stats.update(total=total, dt=dt, batch=batch,
                     new_hashes=new_hashes, pipeline="fleet",
                     layout="fleet", shards=n_shards,
                     remote_shards=len(fleet_nodes),
                     start_case=start_case,
                     fleet=placement.snapshot(),
                     migrations=list(placement.migrations),
                     oracle_cases=tallies["oracle_cases"],
                     redispatches=tallies["redispatches"],
                     offspring=tallies["offspring"],
                     rewinds=tallies["rewinds"],
                     slice_rewinds=tallies["slice_rewinds"],
                     rewind_mode=rewind_mode,
                     membership=membership_state(),
                     vacant=sum(1 for sh in shards.values()
                                if sh is None),
                     spmd=(spmd_mod.stats_snapshot()
                           if spmd_engine is not None else None),
                     coverage_edges=(cov.edges() if cov is not None
                                     else None),
                     cov_maps=tallies["cov_maps"],
                     cov_new_edges=tallies["cov_new_edges"],
                     transport=transport.snapshot(),
                     fleet_window=fleet_window,
                     reduce_mode=reduce_mode,
                     reduce_overlap=round(reduce_overlap, 3),
                     step_shapes=sorted(step_shapes),
                     arenas={s: sh.arena.stats()
                             for s, sh in shards.items()
                             if isinstance(sh, _Shard)},
                     store_stats=store.stats())
    logger.log("info", "corpus fleet (%d shards, %d live): %d samples in "
               "%.2fs (%.0f samples/s), %d novel hashes, %d migration(s)",
               n_shards, len(placement.live()), total, dt,
               total / max(dt, 1e-9), new_hashes,
               len(placement.migrations))
    print(f"# {total} samples, {dt:.2f}s, {total / max(dt, 1e-9):.0f} "
          f"samples/s (fleet, {n_shards} shards, {len(placement.live())} "
          f"live), {new_hashes} novel hashes, "
          f"{len(placement.migrations)} migration(s), "
          f"{tallies['oracle_cases']} oracle case(s)", file=sys.stderr)
    return 0
