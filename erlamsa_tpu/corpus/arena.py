"""Device-resident paged seed arena: upload seeds once, mutate forever.

The bucket assembler (assembler.py) re-builds and re-uploads a padded
panel for every scheduled case — the same seed bytes cross the host→
device link every time the scheduler picks them, and a mixed corpus
compiles O(log²) (B, L) bucket shapes. This module keeps seed bytes ON
the device in an arena of fixed-size pages (ops/paged.py) addressed
through an int32 page table, the Ragged Paged Attention layout
(PAPERS.md, arxiv 2604.15464) applied to the corpus:

  * `PageAllocator` — pure-host bookkeeping: a free list of page ids,
    per-seed page runs tagged with a CAPACITY CLASS, pin counts (a
    pinned run is referenced by the case being assembled and must not
    be evicted), class-aware LRU eviction by last-scheduled case, and
    defrag compaction that renumbers live pages toward the front of the
    arena grouped by class for gather locality.
  * `DeviceArena` — the allocator plus the device tensor, with RAGGED
    rows over ONE physical page size: a small ascending set of capacity
    classes (``classes=(256, 4096, 65536)``-style), each with its own
    page-table width, so a case's gather reads only a row's live pages
    instead of padding every seed to the widest resident one. `ensure()`
    admits a seed's bytes into the smallest class that fits (ONE upload
    per seed, pow2-chunked), `tables_for()` builds one page table PER
    CLASS for a scheduled batch, `gather()` pulls a class's working
    buffer, and `adopt_pending()` scatters interesting offspring
    straight from a step's device-resident OUTPUT buffer into free
    pages of the right class (ops/paged.adopt_rows) — only content
    hashes and lengths ever cross PCIe for adopted seeds.

Spill-to-host: when the arena cannot hold a scheduled seed (pages
exhausted even after eviction, or an injected ``arena.spill`` chaos
fault), the seed stays host-resident for that case — its table row
points at the zero page and the runner overlays the row from host
bytes. Spills cost one extra upload but never change output bytes; the
chaos test pins that transparency. Device-side adoption has the same
contract behind the ``arena.adopt`` site: a faulted adoption batch
falls back to the host-upload path (the store listener already queued
the seed), byte-identically.

Determinism: page ids depend only on the deterministic call sequence
(alloc order, eviction order by (class preference, last_used, seed id),
LIFO free-list reuse) — no clocks, no thread timing. The `tick` every
call takes is the case counter, so at a fixed -s two runs allocate
identically.

Threading: the allocator and the device tensor are owned by the main
dispatch thread. Only the admission queue (`enqueue`, fed by the store
listener from service threads) and the adoption queue (`enqueue_adopt`,
fed by the drain worker) are shared, and they are lock-guarded.
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Sequence

import math
import threading
import zlib

import numpy as np

from ..obs import trace
from ..services import chaos
from .assembler import bucket_capacity

#: re-exported reserved-page convention (ops/paged.py is jax-importing;
#: the allocator half of this module must stay importable without it)
ZERO_PAGE = 0
TRASH_PAGE = 1
RESERVED_PAGES = 2


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1)).bit_length()


def fit_page(page: int, cap: int) -> int:
    """Largest power of two <= `page` that divides the row capacity
    `cap`. A page that does not divide the capacity would make resident
    rows narrower than their class cap — lengths past the row width,
    spill overlays with mismatched shapes — so the runner rounds the
    requested page through this before building the arena. Always >= 1
    (1 divides everything)."""
    if page <= 0:
        raise ValueError(f"page size must be positive, got {page}")
    if cap <= 0:
        raise ValueError(f"row capacity must be positive, got {cap}")
    page = min(int(page), int(cap))
    # pow2 floor of the request, then the largest pow2 dividing cap
    return min(1 << (page.bit_length() - 1), cap & -cap)


def fit_page_classes(page: int, classes: Sequence[int]) -> int:
    """fit_page against a whole class set: the page must divide EVERY
    class width, so fit against their gcd."""
    g = 0
    for c in classes:
        g = math.gcd(g, int(c))
    return fit_page(page, g)


def resolve_classes(spec, sizes: Sequence[int],
                    device_max: int) -> tuple[int, ...]:
    """Resolve an ``--arena-classes`` spec into the run's ascending
    capacity-class tuple.

    None/"auto" derives the exact set of bucket capacities the stored
    seeds occupy — every seed then mutates at the same width the bucket
    assembler would give it, so arena==buckets byte-identity holds by
    construction. An explicit spec ("256,4096,65536" or a sequence of
    ints) is honored as given, clamped to the device cap; seeds whose
    bucket capacity falls between two classes route UP to the next
    class (a wider row changes that seed's stream vs buckets — the
    documented trade for a bounded compiled-shape set)."""
    if spec in (None, "", "auto"):
        caps = {bucket_capacity(n, device_max=device_max) for n in sizes}
        if not caps:
            caps = {bucket_capacity(0, device_max=device_max)}
        return tuple(sorted(caps))
    if isinstance(spec, str):
        parts = [p for p in spec.replace(",", " ").split() if p]
        spec = [int(p) for p in parts]
    caps = sorted({min(int(c), int(device_max)) for c in spec})
    if not caps or caps[0] <= 0:
        raise ValueError(f"arena classes must be positive, got {spec!r}")
    return tuple(caps)


class ArenaSnapshot(NamedTuple):
    """Versioned warm-start image of one shard partition (r15): the
    page-padded payloads plus everything needed to re-admit them without
    touching the store — built host-side by build_arena_snapshot, shipped
    over a shard_snapshot frame or replayed into a local arena after
    readmission.

    Fencing: `epoch` and `token` stamp the snapshot with the lease it
    was built FOR. A worker only installs a snapshot whose stamp matches
    its current lease, so a zombie coordinator's stale image — or a
    zombie worker restoring after its lease was re-granted elsewhere —
    can never serve a stale partition. `crc` (crc32 over the flat page
    bytes) rejects corruption independently of fencing."""

    sids: tuple  # seed ids, in admission order
    lens: tuple  # true (class-truncated) payload lengths
    cls_map: tuple  # class index per sid (routing at build time)
    pages: np.ndarray  # uint8[n_pages, page] page-padded payloads
    page: int  # physical page size the image was cut with
    crc: int  # crc32 over pages.tobytes()
    epoch: int  # fencing epoch the snapshot is valid at
    token: str  # campaign token scoping the epoch


def build_arena_snapshot(get: Callable[[str], bytes],
                         sids: Sequence[str],
                         classes: Sequence[int], page: int,
                         classify: Callable[[int], int] | None = None,
                         epoch: int = 0,
                         token: str = "") -> ArenaSnapshot:
    """Cut a warm-start snapshot for a partition's seeds, pure-host (no
    jax): each payload is truncated at the TOP class (the same clamp
    ensure() applies at admission, so a restore reproduces admission
    byte-for-byte), class-routed exactly like DeviceArena.class_for, and
    laid out as consecutive zero-padded page chunks in sid order — the
    wire layout shard_snapshot frames and restore_snapshot() both walk."""
    classes = tuple(sorted({int(c) for c in classes}))
    if not classes or classes[0] <= 0:
        raise ValueError(f"capacity classes must be positive, got {classes}")
    page = int(page)
    if page <= 0:
        raise ValueError(f"page size must be positive, got {page}")
    sids = [str(s) for s in sids]
    lens: list[int] = []
    cls_map: list[int] = []
    chunks: list[np.ndarray] = []
    for sid in sids:
        data = bytes(get(sid))[:classes[-1]]
        want = classify(len(data)) if classify else len(data)
        cls = len(classes) - 1
        for i, cap in enumerate(classes):
            if cap >= want:
                cls = i
                break
        npages = max(1, -(-len(data) // page))
        buf = np.zeros(npages * page, np.uint8)
        buf[:len(data)] = np.frombuffer(data, np.uint8)
        lens.append(len(data))
        cls_map.append(cls)
        chunks.append(buf.reshape(npages, page))
    pages = (np.vstack(chunks) if chunks
             else np.zeros((0, page), np.uint8))
    return ArenaSnapshot(
        sids=tuple(sids), lens=tuple(lens), cls_map=tuple(cls_map),
        pages=pages, page=page,
        crc=zlib.crc32(pages.tobytes()) & 0xFFFFFFFF,
        epoch=int(epoch), token=str(token),
    )


class ClassTable(NamedTuple):
    """One capacity class's slice of a scheduled batch: the per-class
    page table tables_for() builds. `rows` are positions in the
    scheduled list (schedule order preserved); `spilled` are indices
    INTO `rows` (local) whose seeds ride the host-overlay path."""

    cls: int  # class index into DeviceArena.classes
    capacity: int  # class width in bytes
    rows: np.ndarray  # int32[k] positions in the scheduled batch
    table: np.ndarray  # int32[k, capacity // page]
    lens: np.ndarray  # int32[k] true lengths
    spilled: list  # local indices into rows


class PageAllocator:
    """Host-side page bookkeeping for one arena. No jax anywhere: the
    allocator is property-testable on any box (tests/test_arena.py).

    Owned by the main dispatch thread — see the module docstring."""

    def __init__(self, num_pages: int, page: int):
        if num_pages <= RESERVED_PAGES:
            raise ValueError(f"arena needs > {RESERVED_PAGES} pages, "
                             f"got {num_pages}")
        if page <= 0:
            raise ValueError(f"page size must be positive, got {page}")
        self.num_pages = int(num_pages)
        self.page = int(page)
        # descending so pop() hands out ascending ids first; freed runs
        # go back LIFO — both deterministic given the call sequence
        self._free = list(range(self.num_pages - 1, RESERVED_PAGES - 1, -1))
        self._runs: dict[str, list[int]] = {}
        self._lens: dict[str, int] = {}
        self._pins: dict[str, int] = {}
        self._last_used: dict[str, int] = {}
        self._cls: dict[str, int] = {}
        self.evictions = 0
        self.defrags = 0
        self.frees_since_defrag = 0
        # per-class counters (class index -> count), carried across
        # device-loss resets like evictions/defrags
        self.class_evictions: dict[int, int] = {}
        self.class_defrag_moves: dict[int, int] = {}

    # -- queries ---------------------------------------------------------

    def pages_for(self, nbytes: int) -> int:
        return max(1, -(-int(nbytes) // self.page))

    def free_pages(self) -> int:
        return len(self._free)

    def resident(self, sid: str) -> bool:
        return sid in self._runs

    def run(self, sid: str) -> list[int]:
        return self._runs[sid]

    def length(self, sid: str) -> int:
        return self._lens[sid]

    def cls_of(self, sid: str) -> int:
        return self._cls[sid]

    def occupancy(self) -> float:
        usable = self.num_pages - RESERVED_PAGES
        return 1.0 - len(self._free) / usable if usable else 0.0

    def class_usage(self) -> dict[int, tuple[int, int]]:
        """class index -> (resident seeds, pages held)."""
        usage: dict[int, tuple[int, int]] = {}
        for sid, cls in self._cls.items():
            seeds, pages = usage.get(cls, (0, 0))
            usage[cls] = (seeds + 1, pages + len(self._runs[sid]))
        return usage

    # -- alloc/free/pin --------------------------------------------------

    def alloc(self, sid: str, nbytes: int, tick: int,
              cls: int = 0) -> list[int] | None:
        """Reserve a page run for `sid` (None if the free list is too
        short — the caller evicts or spills). nbytes is the TRUE length;
        the run covers ceil(nbytes/page) pages. `cls` tags the run's
        capacity class for class-aware eviction/defrag accounting."""
        if sid in self._runs:
            raise ValueError(f"seed {sid} already resident")
        need = self.pages_for(nbytes)
        if need > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(need)]
        self._runs[sid] = pages
        self._lens[sid] = int(nbytes)
        self._pins[sid] = 0
        self._last_used[sid] = int(tick)
        self._cls[sid] = int(cls)
        return pages

    def free(self, sid: str) -> int:
        """Release a run back to the free list; returns pages freed."""
        if self._pins.get(sid, 0):
            raise ValueError(f"seed {sid} is pinned ({self._pins[sid]})")
        pages = self._runs.pop(sid)
        del self._lens[sid], self._pins[sid], self._last_used[sid]
        del self._cls[sid]
        self._free.extend(pages)
        self.frees_since_defrag += len(pages)
        return len(pages)

    def pin(self, sid: str):
        """Ref-count a run the current case's table points at — pinned
        runs survive eviction until the matching unpin."""
        self._pins[sid] += 1

    def unpin(self, sid: str):
        if self._pins[sid] <= 0:
            raise ValueError(f"seed {sid} is not pinned")
        self._pins[sid] -= 1

    def touch(self, sid: str, tick: int):
        self._last_used[sid] = int(tick)

    # -- eviction / defrag -----------------------------------------------

    def evict_for(self, need: int, prefer_cls: int | None = None) -> list[str]:
        """Free least-recently-scheduled unpinned runs until `need`
        pages are available (or no candidates remain). With
        `prefer_cls`, same-class victims go first — big-class churn then
        cannibalizes its own class before destroying a hot small-class
        working set. Ties break on seed id so eviction order is
        replayable. Returns evicted sids."""
        evicted: list[str] = []
        while len(self._free) < need:
            victims = sorted(
                (sid for sid, p in self._pins.items() if p == 0),
                key=lambda sid: (
                    0 if prefer_cls is None or self._cls[sid] == prefer_cls
                    else 1,
                    self._last_used[sid], sid,
                ),
            )
            if not victims:
                break
            cls = self._cls[victims[0]]
            self.free(victims[0])
            evicted.append(victims[0])
            self.class_evictions[cls] = self.class_evictions.get(cls, 0) + 1
        self.evictions += len(evicted)
        return evicted

    def defrag(self) -> np.ndarray:
        """Compact live runs toward the front of the arena and return
        the int32[num_pages] source map for ops/paged.permute_pages
        (new_arena[i] = old_arena[src[i]]). Runs are renumbered grouped
        by class, then in ascending order of their current first page —
        each class's gathers walk one contiguous region after the move,
        and the order is deterministic."""
        src = np.arange(self.num_pages, dtype=np.int32)
        nxt = RESERVED_PAGES
        for sid in sorted(self._runs,
                          key=lambda s: (self._cls[s], self._runs[s][0])):
            old = self._runs[sid]
            new = list(range(nxt, nxt + len(old)))
            src[new] = old
            moved = sum(1 for o, n in zip(old, new) if o != n)
            if moved:
                cls = self._cls[sid]
                self.class_defrag_moves[cls] = (
                    self.class_defrag_moves.get(cls, 0) + moved)
            self._runs[sid] = new
            nxt += len(old)
        self._free = list(range(self.num_pages - 1, nxt - 1, -1))
        self.defrags += 1
        self.frees_since_defrag = 0
        return src

    def stats(self) -> dict:
        return {
            "pages": self.num_pages,
            "page_size": self.page,
            "pages_free": len(self._free),
            "occupancy": round(self.occupancy(), 4),
            "resident_seeds": len(self._runs),
            "evictions": self.evictions,
            "defrags": self.defrags,
        }


class DeviceArena:
    """The allocator married to the device tensor. All methods except
    `enqueue` and `enqueue_adopt` are main-thread-only (module
    docstring)."""

    _GUARDED_BY = {"_lock": ("_pending", "_adopt_q")}

    def __init__(self, num_pages: int, page: int | None = None,
                 row_pages: int = 1, donate="auto",
                 classes: Sequence[int] | None = None,
                 classify: Callable[[int], int] | None = None):
        from ..ops import paged

        self._paged = paged
        self.alloc = PageAllocator(num_pages, page or paged.PAGE)
        self.page = self.alloc.page
        # capacity classes: ascending row widths over the ONE physical
        # page size. The legacy single-width constructor (row_pages=N)
        # is the degenerate one-class arena; `classify` maps a sample
        # length to the capacity it WANTS (default: the raw length — the
        # corpus runner passes bucket_capacity so class routing matches
        # the bucket assembler's slack exactly), and class_for() picks
        # the smallest class that satisfies it
        if classes is None:
            classes = (self.page * int(row_pages),)
        classes = tuple(sorted({int(c) for c in classes}))
        if not classes or classes[0] <= 0:
            raise ValueError(f"capacity classes must be positive, "
                             f"got {classes}")
        for c in classes:
            if c % self.page:
                raise ValueError(f"class width {c} is not a multiple of "
                                 f"the {self.page}B page")
        self.classes = classes
        self.class_pages = tuple(c // self.page for c in classes)
        self.row_pages = self.class_pages[-1]
        self.width = classes[-1]
        self._classify = classify
        self._arena = paged.new_arena(num_pages, self.page)
        self._donate = donate
        self._staged_idx: list[int] = []
        self._staged_pages: list[np.ndarray] = []
        self._lock = threading.Lock()
        self._pending: list[str] = []
        self._adopt_q: list[tuple] = []
        self.spills = 0
        self.uploads = 0
        self.bytes_uploaded = 0
        self.bytes_gathered = 0
        self.truncated = 0
        self.adopted = 0
        self.adopt_skips = 0
        self.adopt_faults = 0
        self.class_adopted: dict[int, int] = {}

    # -- class routing ---------------------------------------------------

    def class_for(self, nbytes: int) -> int:
        """Smallest class whose width satisfies the sample's wanted
        capacity (classify(nbytes), default the raw length). A sample
        wanting more than the top class routes there and is truncated at
        admission — the ONLY case the truncated counter fires."""
        want = self._classify(nbytes) if self._classify else int(nbytes)
        for i, cap in enumerate(self.classes):
            if cap >= want:
                return i
        return len(self.classes) - 1

    # -- admission -------------------------------------------------------

    def enqueue(self, sid: str):
        """Store-admission hook (CorpusStore.listener): note a new seed
        for upload at the next case boundary. Thread-safe; the upload
        itself happens on the main thread in drain_pending()."""
        with self._lock:
            self._pending.append(sid)

    def drain_pending(self, get: Callable[[str], bytes], tick: int):
        """Admit every seed queued by enqueue() since the last case."""
        with self._lock:
            pending, self._pending = self._pending, []
        for sid in pending:
            self.ensure(sid, get(sid), tick)
        if pending:
            self.flush()

    def _spill_forced(self) -> bool:
        try:
            chaos.fault_point("arena.spill")
        except OSError:
            # an injected arena.spill fault: treat this seed as if the
            # arena were full — it must ride the host-overlay path and
            # the output stream must not change (tests pin this)
            return True
        return False

    def ensure(self, sid: str, data: bytes, tick: int) -> bool:
        """Make `sid` resident (True) or report a spill (False). Bytes
        land in the smallest class that fits (longer samples route UP a
        class, never silently down) and are paged out zero-padded, so a
        gathered row matches a packed panel row exactly. Only samples
        beyond the TOP class are clamped, and counted."""
        if self.alloc.resident(sid):
            self.alloc.touch(sid, tick)
            return True
        if self._spill_forced():
            self.spills += 1
            return False
        cls = self.class_for(len(data))
        cap = self.classes[cls]
        if len(data) > cap:
            # only possible at the top class: class_for routes anything
            # smaller up to a class that holds it whole
            self.truncated += 1
        data = data[:cap]
        need = self.alloc.pages_for(len(data))
        pages = self.alloc.alloc(sid, len(data), tick, cls=cls)
        if pages is None:
            # close the staging window BEFORE evicting: a seed staged
            # earlier in this window (bulk admission is unpinned) may be
            # the eviction victim, and recycling its pages while its
            # payload still sits in _staged_pages would put duplicate
            # indices with different payloads into one upload scatter —
            # nondeterministic on TPU/GPU (silent seed-byte corruption)
            self.flush()
            with trace.span("corpus.arena.evict", need=need):
                self.alloc.evict_for(need, prefer_cls=cls)
            pages = self.alloc.alloc(sid, len(data), tick, cls=cls)
        if pages is None:
            self.spills += 1
            return False
        buf = np.zeros(len(pages) * self.page, np.uint8)
        buf[:len(data)] = np.frombuffer(data, np.uint8)
        self._staged_idx.extend(pages)
        self._staged_pages.append(buf.reshape(len(pages), self.page))
        return True

    def flush(self):
        """Upload staged pages in one pow2-padded chunk (padding rows
        target the trash page), so admission compiles O(log) shapes over
        a run, not one per seed count."""
        if not self._staged_idx:
            return
        k = len(self._staged_idx)
        if len(set(self._staged_idx)) != k:
            # duplicate indices in one scatter are nondeterministic on
            # TPU/GPU — fail loudly rather than corrupt seed bytes
            raise RuntimeError("staged page ids alias (a staged page was "
                               "freed and reallocated before flush)")
        kp = _next_pow2(k)
        idx = np.full(kp, TRASH_PAGE, np.int32)
        idx[:k] = self._staged_idx
        pages = np.zeros((kp, self.page), np.uint8)
        pages[:k] = np.vstack(self._staged_pages)
        self._staged_idx, self._staged_pages = [], []
        with trace.span("corpus.arena.upload", pages=k, padded=kp):
            self._arena = self._paged.upload_pages(
                self._arena, idx, pages, donate=self._donate
            )
        self.uploads += 1
        self.bytes_uploaded += int(pages.nbytes + idx.nbytes)

    # -- batch addressing ------------------------------------------------

    def tables_for(self, sids: Sequence[str], samples: Sequence[bytes],
                   tick: int) -> list[ClassTable]:
        """Build one case's page tables, one per capacity class in
        ascending width order — the ragged view: each class's gather
        reads only its rows' live pages. Every resident run is pinned
        while the tables are built so a later row's eviction cannot
        steal its pages, then unpinned — the gather dispatch order makes
        the tables safe to use after unpinning (uploads queue behind the
        gathers)."""
        groups: dict[int, dict] = {}
        pinned: list[str] = []
        try:
            with trace.span("corpus.arena.alloc", rows=len(sids),
                            tick=tick):
                for r, (sid, data) in enumerate(zip(sids, samples)):
                    if self.ensure(sid, data, tick):
                        # the allocator's recorded class/length are
                        # authoritative: for store seeds they match the
                        # routed sample, and adopted seeds (device-only
                        # bytes) have no host sample at all
                        cls = self.alloc.cls_of(sid)
                        n = self.alloc.length(sid)
                        run = self.alloc.run(sid)
                        self.alloc.pin(sid)
                        pinned.append(sid)
                    else:
                        cls = self.class_for(len(data))
                        n = min(len(data), self.classes[cls])
                        run = None
                    g = groups.setdefault(cls, {"rows": [], "lens": [],
                                                "runs": [], "spilled": []})
                    if run is None:
                        g["spilled"].append(len(g["rows"]))
                    g["rows"].append(r)
                    g["lens"].append(n)
                    g["runs"].append(run)
                self.flush()
        finally:
            # unconditional unpin: an ensure()/flush() escape (e.g. an
            # XLA error mid-upload) must not leave runs unevictable for
            # the rest of the run
            for sid in pinned:
                self.alloc.unpin(sid)
        out = []
        for cls in sorted(groups):
            g = groups[cls]
            k = len(g["rows"])
            table = np.full((k, self.class_pages[cls]), ZERO_PAGE, np.int32)
            for j, run in enumerate(g["runs"]):
                if run is not None:
                    table[j, :len(run)] = run
            out.append(ClassTable(
                cls=cls, capacity=self.classes[cls],
                rows=np.asarray(g["rows"], np.int32), table=table,
                lens=np.asarray(g["lens"], np.int32),
                spilled=g["spilled"],
            ))
        return out

    def table_for(self, sids: Sequence[str], samples: Sequence[bytes],
                  tick: int):
        """Single-table view over tables_for(): one int32[B, row_pages]
        table at the arena's WIDEST class (short rows end in ZERO_PAGE
        entries), lens int32[B], and spilled row positions — the legacy
        r9 addressing, still used by callers that mutate every row at
        one width (slot pools, tests)."""
        groups = self.tables_for(sids, samples, tick)
        rows = len(sids)
        table = np.full((rows, self.row_pages), ZERO_PAGE, np.int32)
        lens = np.zeros(rows, np.int32)
        spilled: list[int] = []
        for g in groups:
            for j, r in enumerate(g.rows):
                table[r, :g.table.shape[1]] = g.table[j]
                lens[r] = g.lens[j]
            spilled.extend(int(g.rows[j]) for j in g.spilled)
        return table, lens, sorted(spilled)

    def gather(self, table):
        """Device gather: uint8[B, P*page] working buffer for an
        int32[B, P] page table (a ClassTable's, possibly row-padded, or
        the legacy full-width table)."""
        table = np.asarray(table, np.int32)
        self.bytes_gathered += int(table.shape[0] * table.shape[1]
                                   * self.page)
        with trace.span("corpus.arena.gather", rows=int(table.shape[0])):
            return self._paged.gather_rows(self._arena, table)

    # -- offspring adoption ----------------------------------------------

    def enqueue_adopt(self, sid: str, length: int, src, row: int):
        """Queue an interesting offspring for device-side adoption:
        `src` is the step's device-resident OUTPUT buffer uint8[B, W]
        (any class width), `row` the offspring's row in it. Thread-safe
        (the drain worker calls this as it hashes); the scatter itself
        happens on the main thread in adopt_pending(). The host-upload
        fallback (the store listener's enqueue) stays armed: a
        successful adoption turns that upload into a no-op — ensure()
        sees the sid resident — a failed or chaos-faulted one lets the
        upload proceed, so output bytes never depend on which path won."""
        with self._lock:
            self._adopt_q.append((sid, int(length), src, int(row)))

    def adopt_pending(self, tick: int) -> int:
        """Scatter every queued offspring into free pages of its class —
        the admission path where only hashes and lengths crossed PCIe.
        Returns the number adopted; seeds the allocator cannot place
        (even after class-preferring eviction) are skipped and ride the
        host path instead."""
        with self._lock:
            q, self._adopt_q = self._adopt_q, []
        if not q:
            return 0
        try:
            chaos.fault_point("arena.adopt")
        except OSError:
            # injected adoption fault: drop the device path for this
            # batch — the seeds stay queued for the host-upload fallback
            # and the output stream must not change (tests pin this)
            self.adopt_faults += len(q)
            return 0
        groups: dict[int, tuple[object, list]] = {}
        adopted = 0
        pinned: list[str] = []
        try:
            for sid, length, src, row in q:
                if self.alloc.resident(sid):
                    continue
                width = int(src.shape[1])
                if width % self.page:
                    raise ValueError(f"adopt source rows are {width}B, "
                                     f"not a multiple of the "
                                     f"{self.page}B page")
                cls = self.class_for(length)
                n = min(length, self.classes[cls], width)
                need = self.alloc.pages_for(n)
                pages = self.alloc.alloc(sid, n, tick, cls=cls)
                if pages is None:
                    # same alias discipline as ensure(): close the
                    # staging window before eviction can recycle a
                    # staged page
                    self.flush()
                    with trace.span("corpus.arena.evict", need=need):
                        self.alloc.evict_for(need, prefer_cls=cls)
                    pages = self.alloc.alloc(sid, n, tick, cls=cls)
                if pages is None:
                    self.adopt_skips += 1
                    continue
                # pinned until the scatter lands: a later entry's
                # eviction re-using these pages in the SAME scatter
                # would alias indices (nondeterministic on TPU/GPU)
                self.alloc.pin(sid)
                pinned.append(sid)
                _src, entries = groups.setdefault(id(src), (src, []))
                entries.append((row, pages, n))
                self.class_adopted[cls] = self.class_adopted.get(cls, 0) + 1
                adopted += 1
            for src, entries in groups.values():
                k = len(entries)
                kp = _next_pow2(k)
                run_pages = int(src.shape[1]) // self.page
                rows = np.zeros(kp, np.int32)
                lens = np.zeros(kp, np.int32)
                table = np.full((kp, run_pages), TRASH_PAGE, np.int32)
                for j, (row, pages, n) in enumerate(entries):
                    rows[j] = row
                    lens[j] = n
                    table[j, :len(pages)] = pages
                with trace.span("corpus.arena.adopt", rows=k, padded=kp):
                    self._arena = self._paged.adopt_rows(
                        self._arena, src, rows, table, lens,
                        donate=self._donate,
                    )
        finally:
            for sid in pinned:
                self.alloc.unpin(sid)
        self.adopted += adopted
        return adopted

    def adopt(self, sids: Sequence[str], data, lens: Sequence[int],
              tick: int) -> list[str]:
        """Host-driven adoption of a full output panel (uint8[B, width]
        at the TOP class width): scatter rows back in as new runs.
        Rows whose run cannot be allocated are skipped and returned
        (the caller may fall back to host-side ensure()). The hot paths
        use enqueue_adopt()/adopt_pending() instead — this remains for
        direct callers that already hold a panel."""
        rows, width = data.shape
        if width != self.width:
            raise ValueError(f"adopt rows are {width}B, arena rows "
                             f"are {self.width}B")
        table = np.full((rows, self.row_pages), TRASH_PAGE, np.int32)
        skipped: list[str] = []
        for r, sid in enumerate(sids):
            if self.alloc.resident(sid):
                continue
            n = min(int(lens[r]), self.width)
            pages = self.alloc.alloc(sid, n, tick,
                                     cls=self.class_for(n))
            if pages is None:
                skipped.append(sid)
                continue
            table[r, :len(pages)] = pages
        with trace.span("corpus.arena.scatter", rows=rows):
            self._arena = self._paged.scatter_rows(
                self._arena, table, data, donate=self._donate
            )
        return skipped

    # -- maintenance -----------------------------------------------------

    def maybe_defrag(self) -> bool:
        """Compact once enough pages have churned through the free list
        (a quarter of the arena) — cheap insurance that long runs stay
        front-packed for gather locality after heavy eviction."""
        usable = self.alloc.num_pages - RESERVED_PAGES
        if self.alloc.frees_since_defrag < max(16, usable // 4):
            return False
        self.defrag()
        return True

    def defrag(self):
        src = self.alloc.defrag()
        with trace.span("corpus.arena.defrag"):
            self._arena = self._paged.permute_pages(
                self._arena, src, donate=self._donate
            )

    def reset(self):
        """Device-loss recovery: drop every run and rebuild an empty
        arena tensor (the old one died with the device). Cumulative
        counters survive — evictions/defrags and the per-class tallies
        carry into the fresh allocator so the Prometheus counters
        (type: counter) never go backwards; the runner re-seeds from the
        store. Queued adoptions die with the device (their source
        buffers are gone) — those seeds re-upload via the host path."""
        old = self.alloc
        self.alloc = PageAllocator(old.num_pages, self.page)
        self.alloc.evictions = old.evictions
        self.alloc.defrags = old.defrags
        self.alloc.class_evictions = dict(old.class_evictions)
        self.alloc.class_defrag_moves = dict(old.class_defrag_moves)
        self._staged_idx, self._staged_pages = [], []
        with self._lock:
            self._adopt_q = []
        self._arena = self._paged.new_arena(self.alloc.num_pages, self.page)

    def restore_snapshot(self, snap: ArenaSnapshot, tick: int) -> int:
        """Warm-start this arena from a snapshot (r15): bulk re-admit
        every payload through the normal ensure() path and close the
        staging window with ONE flush — a readmitted shard repopulates
        its partition in one upload instead of lazy per-case re-uploads.
        Returns the number of seeds made resident (spilled seeds stay
        host-resident, same transparency contract as ensure). The
        caller checks the snapshot's epoch/token stamp against its lease
        BEFORE calling; this method only verifies physical integrity
        (page geometry + crc) and raises ValueError on a mismatch."""
        if int(snap.page) != self.page:
            raise ValueError(f"snapshot page size {snap.page} != arena "
                             f"page size {self.page}")
        if zlib.crc32(snap.pages.tobytes()) & 0xFFFFFFFF != snap.crc:
            raise ValueError("snapshot crc mismatch — corrupt image "
                             "rejected")
        restored = 0
        off = 0
        with trace.span("corpus.arena.restore", seeds=len(snap.sids)):
            for sid, ln in zip(snap.sids, snap.lens):
                npages = max(1, -(-int(ln) // self.page))
                data = snap.pages[off:off + npages].tobytes()[:int(ln)]
                off += npages
                if self.ensure(sid, data, tick):
                    restored += 1
            self.flush()
        return restored

    def stats(self) -> dict:
        s = self.alloc.stats()
        usable = self.alloc.num_pages - RESERVED_PAGES
        usage = self.alloc.class_usage()
        classes = {}
        for i, cap in enumerate(self.classes):
            seeds, pages = usage.get(i, (0, 0))
            classes[str(cap)] = {
                "pages": pages,
                "resident_seeds": seeds,
                "occupancy": round(pages / usable, 4) if usable else 0.0,
                "evictions": self.alloc.class_evictions.get(i, 0),
                "defrag_moves": self.alloc.class_defrag_moves.get(i, 0),
                "adopted": self.class_adopted.get(i, 0),
            }
        s.update(spills=self.spills, uploads=self.uploads,
                 bytes_uploaded=self.bytes_uploaded,
                 bytes_gathered=self.bytes_gathered,
                 truncated=self.truncated, adopted=self.adopted,
                 adopt_skips=self.adopt_skips,
                 adopt_faults=self.adopt_faults, classes=classes)
        return s
