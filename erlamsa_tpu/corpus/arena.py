"""Device-resident paged seed arena: upload seeds once, mutate forever.

The bucket assembler (assembler.py) re-builds and re-uploads a padded
panel for every scheduled case — the same seed bytes cross the host→
device link every time the scheduler picks them, and a mixed corpus
compiles O(log²) (B, L) bucket shapes. This module keeps seed bytes ON
the device in an arena of fixed-size pages (ops/paged.py) addressed
through an int32 page table, the Ragged Paged Attention layout
(PAPERS.md, arxiv 2604.15464) applied to the corpus:

  * `PageAllocator` — pure-host bookkeeping: a free list of page ids,
    per-seed page runs, pin counts (a pinned run is referenced by the
    case being assembled and must not be evicted), LRU eviction by
    last-scheduled case, and defrag compaction that renumbers live
    pages toward the front of the arena for gather locality.
  * `DeviceArena` — the allocator plus the device tensor: `ensure()`
    admits a seed's bytes as zero-padded pages (ONE upload per seed,
    pow2-chunked so admission compiles O(log) programs), `table_for()`
    builds a batch's page table + true-length vector, `gather()` pulls
    the working buffer for the mutation step, `adopt()` scatters
    device-resident output rows back in as new runs without a host
    round trip, and `reset()` rebuilds after device loss.

Spill-to-host: when the arena cannot hold a scheduled seed (pages
exhausted even after eviction, or an injected ``arena.spill`` chaos
fault), the seed stays host-resident for that case — its table row
points at the zero page and the runner overlays the row from host
bytes. Spills cost one extra upload but never change output bytes; the
chaos test pins that transparency.

Determinism: page ids depend only on the deterministic call sequence
(alloc order, eviction order by (last_used, seed id), LIFO free-list
reuse) — no clocks, no thread timing. The `tick` every call takes is
the case counter, so at a fixed -s two runs allocate identically.

Threading: the allocator and the device tensor are owned by the main
dispatch thread. Only the admission queue (`enqueue`, fed by the store
listener from service threads) is shared, and it is lock-guarded.
"""

from __future__ import annotations

from typing import Callable, Sequence

import threading

import numpy as np

from ..obs import trace
from ..services import chaos

#: re-exported reserved-page convention (ops/paged.py is jax-importing;
#: the allocator half of this module must stay importable without it)
ZERO_PAGE = 0
TRASH_PAGE = 1
RESERVED_PAGES = 2


def _next_pow2(n: int) -> int:
    return 1 << max(0, (int(n) - 1)).bit_length()


def fit_page(page: int, cap: int) -> int:
    """Largest power of two <= `page` that divides the row capacity
    `cap`. A page that does not divide the capacity would make resident
    rows (row_pages * page wide) narrower than the truncation cap —
    lengths past the row width, spill overlays with mismatched shapes —
    so the runner rounds the requested page through this before
    building the arena. Always >= 1 (1 divides everything)."""
    if page <= 0:
        raise ValueError(f"page size must be positive, got {page}")
    if cap <= 0:
        raise ValueError(f"row capacity must be positive, got {cap}")
    page = min(int(page), int(cap))
    # pow2 floor of the request, then the largest pow2 dividing cap
    return min(1 << (page.bit_length() - 1), cap & -cap)


class PageAllocator:
    """Host-side page bookkeeping for one arena. No jax anywhere: the
    allocator is property-testable on any box (tests/test_arena.py).

    Owned by the main dispatch thread — see the module docstring."""

    def __init__(self, num_pages: int, page: int):
        if num_pages <= RESERVED_PAGES:
            raise ValueError(f"arena needs > {RESERVED_PAGES} pages, "
                             f"got {num_pages}")
        if page <= 0:
            raise ValueError(f"page size must be positive, got {page}")
        self.num_pages = int(num_pages)
        self.page = int(page)
        # descending so pop() hands out ascending ids first; freed runs
        # go back LIFO — both deterministic given the call sequence
        self._free = list(range(self.num_pages - 1, RESERVED_PAGES - 1, -1))
        self._runs: dict[str, list[int]] = {}
        self._lens: dict[str, int] = {}
        self._pins: dict[str, int] = {}
        self._last_used: dict[str, int] = {}
        self.evictions = 0
        self.defrags = 0
        self.frees_since_defrag = 0

    # -- queries ---------------------------------------------------------

    def pages_for(self, nbytes: int) -> int:
        return max(1, -(-int(nbytes) // self.page))

    def free_pages(self) -> int:
        return len(self._free)

    def resident(self, sid: str) -> bool:
        return sid in self._runs

    def run(self, sid: str) -> list[int]:
        return self._runs[sid]

    def length(self, sid: str) -> int:
        return self._lens[sid]

    def occupancy(self) -> float:
        usable = self.num_pages - RESERVED_PAGES
        return 1.0 - len(self._free) / usable if usable else 0.0

    # -- alloc/free/pin --------------------------------------------------

    def alloc(self, sid: str, nbytes: int, tick: int) -> list[int] | None:
        """Reserve a page run for `sid` (None if the free list is too
        short — the caller evicts or spills). nbytes is the TRUE length;
        the run covers ceil(nbytes/page) pages."""
        if sid in self._runs:
            raise ValueError(f"seed {sid} already resident")
        need = self.pages_for(nbytes)
        if need > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(need)]
        self._runs[sid] = pages
        self._lens[sid] = int(nbytes)
        self._pins[sid] = 0
        self._last_used[sid] = int(tick)
        return pages

    def free(self, sid: str) -> int:
        """Release a run back to the free list; returns pages freed."""
        if self._pins.get(sid, 0):
            raise ValueError(f"seed {sid} is pinned ({self._pins[sid]})")
        pages = self._runs.pop(sid)
        del self._lens[sid], self._pins[sid], self._last_used[sid]
        self._free.extend(pages)
        self.frees_since_defrag += len(pages)
        return len(pages)

    def pin(self, sid: str):
        """Ref-count a run the current case's table points at — pinned
        runs survive eviction until the matching unpin."""
        self._pins[sid] += 1

    def unpin(self, sid: str):
        if self._pins[sid] <= 0:
            raise ValueError(f"seed {sid} is not pinned")
        self._pins[sid] -= 1

    def touch(self, sid: str, tick: int):
        self._last_used[sid] = int(tick)

    # -- eviction / defrag -----------------------------------------------

    def evict_for(self, need: int) -> list[str]:
        """Free least-recently-scheduled unpinned runs until `need`
        pages are available (or no candidates remain). Ties break on
        seed id so eviction order is replayable. Returns evicted sids."""
        evicted: list[str] = []
        while len(self._free) < need:
            victims = sorted(
                (sid for sid, p in self._pins.items() if p == 0),
                key=lambda sid: (self._last_used[sid], sid),
            )
            if not victims:
                break
            self.free(victims[0])
            evicted.append(victims[0])
        self.evictions += len(evicted)
        return evicted

    def defrag(self) -> np.ndarray:
        """Compact live runs toward the front of the arena and return
        the int32[num_pages] source map for ops/paged.permute_pages
        (new_arena[i] = old_arena[src[i]]). Runs are renumbered in
        ascending order of their current first page, so relative layout
        is preserved and the move is deterministic."""
        src = np.arange(self.num_pages, dtype=np.int32)
        nxt = RESERVED_PAGES
        for sid in sorted(self._runs, key=lambda s: self._runs[s][0]):
            old = self._runs[sid]
            new = list(range(nxt, nxt + len(old)))
            src[new] = old
            self._runs[sid] = new
            nxt += len(old)
        self._free = list(range(self.num_pages - 1, nxt - 1, -1))
        self.defrags += 1
        self.frees_since_defrag = 0
        return src

    def stats(self) -> dict:
        return {
            "pages": self.num_pages,
            "page_size": self.page,
            "pages_free": len(self._free),
            "occupancy": round(self.occupancy(), 4),
            "resident_seeds": len(self._runs),
            "evictions": self.evictions,
            "defrags": self.defrags,
        }


class DeviceArena:
    """The allocator married to the device tensor. All methods except
    `enqueue` are main-thread-only (module docstring)."""

    _GUARDED_BY = {"_lock": ("_pending",)}

    def __init__(self, num_pages: int, page: int | None = None,
                 row_pages: int = 1, donate="auto"):
        from ..ops import paged

        self._paged = paged
        self.alloc = PageAllocator(num_pages, page or paged.PAGE)
        self.page = self.alloc.page
        # every gathered row spans row_pages pages: the run's ONE
        # working-buffer width. Seeds longer than this are truncated at
        # admission (the same clamp the bucket path applies at its
        # device cap; metrics.record_truncated counts them)
        self.row_pages = int(row_pages)
        self.width = self.page * self.row_pages
        self._arena = paged.new_arena(num_pages, self.page)
        self._donate = donate
        self._staged_idx: list[int] = []
        self._staged_pages: list[np.ndarray] = []
        self._lock = threading.Lock()
        self._pending: list[str] = []
        self.spills = 0
        self.uploads = 0
        self.bytes_uploaded = 0

    # -- admission -------------------------------------------------------

    def enqueue(self, sid: str):
        """Store-admission hook (CorpusStore.listener): note a new seed
        for upload at the next case boundary. Thread-safe; the upload
        itself happens on the main thread in drain_pending()."""
        with self._lock:
            self._pending.append(sid)

    def drain_pending(self, get: Callable[[str], bytes], tick: int):
        """Admit every seed queued by enqueue() since the last case."""
        with self._lock:
            pending, self._pending = self._pending, []
        for sid in pending:
            self.ensure(sid, get(sid), tick)
        if pending:
            self.flush()

    def _spill_forced(self) -> bool:
        try:
            chaos.fault_point("arena.spill")
        except OSError:
            # an injected arena.spill fault: treat this seed as if the
            # arena were full — it must ride the host-overlay path and
            # the output stream must not change (tests pin this)
            return True
        return False

    def ensure(self, sid: str, data: bytes, tick: int) -> bool:
        """Make `sid` resident (True) or report a spill (False). Bytes
        are clamped to the row width and paged out zero-padded, so a
        gathered row matches a packed panel row exactly."""
        if self.alloc.resident(sid):
            self.alloc.touch(sid, tick)
            return True
        if self._spill_forced():
            self.spills += 1
            return False
        data = data[:self.width]
        need = self.alloc.pages_for(len(data))
        pages = self.alloc.alloc(sid, len(data), tick)
        if pages is None:
            # close the staging window BEFORE evicting: a seed staged
            # earlier in this window (bulk admission is unpinned) may be
            # the eviction victim, and recycling its pages while its
            # payload still sits in _staged_pages would put duplicate
            # indices with different payloads into one upload scatter —
            # nondeterministic on TPU/GPU (silent seed-byte corruption)
            self.flush()
            with trace.span("corpus.arena.evict", need=need):
                self.alloc.evict_for(need)
            pages = self.alloc.alloc(sid, len(data), tick)
        if pages is None:
            self.spills += 1
            return False
        buf = np.zeros(len(pages) * self.page, np.uint8)
        buf[:len(data)] = np.frombuffer(data, np.uint8)
        self._staged_idx.extend(pages)
        self._staged_pages.append(buf.reshape(len(pages), self.page))
        return True

    def flush(self):
        """Upload staged pages in one pow2-padded chunk (padding rows
        target the trash page), so admission compiles O(log) shapes over
        a run, not one per seed count."""
        if not self._staged_idx:
            return
        k = len(self._staged_idx)
        if len(set(self._staged_idx)) != k:
            # duplicate indices in one scatter are nondeterministic on
            # TPU/GPU — fail loudly rather than corrupt seed bytes
            raise RuntimeError("staged page ids alias (a staged page was "
                               "freed and reallocated before flush)")
        kp = _next_pow2(k)
        idx = np.full(kp, TRASH_PAGE, np.int32)
        idx[:k] = self._staged_idx
        pages = np.zeros((kp, self.page), np.uint8)
        pages[:k] = np.vstack(self._staged_pages)
        self._staged_idx, self._staged_pages = [], []
        with trace.span("corpus.arena.upload", pages=k, padded=kp):
            self._arena = self._paged.upload_pages(
                self._arena, idx, pages, donate=self._donate
            )
        self.uploads += 1
        self.bytes_uploaded += int(pages.nbytes + idx.nbytes)

    # -- batch addressing ------------------------------------------------

    def table_for(self, sids: Sequence[str], samples: Sequence[bytes],
                  tick: int):
        """Build one case's page table. Returns (table int32[B, P],
        lens int32[B], spilled rows). Every resident run is pinned while
        the table is built so a later row's eviction cannot steal its
        pages, then unpinned — the gather dispatch order makes the table
        safe to use after unpinning (uploads queue behind the gather)."""
        rows = len(sids)
        table = np.full((rows, self.row_pages), ZERO_PAGE, np.int32)
        lens = np.zeros(rows, np.int32)
        spilled: list[int] = []
        pinned: list[str] = []
        try:
            with trace.span("corpus.arena.alloc", rows=rows, tick=tick):
                for r, (sid, data) in enumerate(zip(sids, samples)):
                    if self.ensure(sid, data, tick):
                        # the allocator's recorded length is
                        # authoritative: for store seeds it equals the
                        # clamped sample length, and adopted seeds
                        # (device-only bytes) have no host sample at all
                        lens[r] = self.alloc.length(sid)
                        run = self.alloc.run(sid)
                        table[r, :len(run)] = run
                        self.alloc.pin(sid)
                        pinned.append(sid)
                    else:
                        lens[r] = min(len(data), self.width)
                        spilled.append(r)
                self.flush()
        finally:
            # unconditional unpin: an ensure()/flush() escape (e.g. an
            # XLA error mid-upload) must not leave runs unevictable for
            # the rest of the run
            for sid in pinned:
                self.alloc.unpin(sid)
        return table, lens, spilled

    def gather(self, table: np.ndarray):
        """Device gather: uint8[B, row_pages*page] working buffer."""
        with trace.span("corpus.arena.gather", rows=int(table.shape[0])):
            return self._paged.gather_rows(self._arena, table)

    def adopt(self, sids: Sequence[str], data, lens: Sequence[int],
              tick: int) -> list[str]:
        """Scatter device-resident output rows (uint8[B, row_pages*page])
        back into the arena as new runs — the admission path that never
        crosses PCIe. Rows whose run cannot be allocated are skipped and
        returned (the caller may fall back to host-side ensure())."""
        rows, width = data.shape
        if width != self.width:
            raise ValueError(f"adopt rows are {width}B, arena rows "
                             f"are {self.width}B")
        table = np.full((rows, self.row_pages), TRASH_PAGE, np.int32)
        skipped: list[str] = []
        for r, sid in enumerate(sids):
            if self.alloc.resident(sid):
                continue
            pages = self.alloc.alloc(sid, min(int(lens[r]), self.width),
                                     tick)
            if pages is None:
                skipped.append(sid)
                continue
            table[r, :len(pages)] = pages
        with trace.span("corpus.arena.scatter", rows=rows):
            self._arena = self._paged.scatter_rows(
                self._arena, table, data, donate=self._donate
            )
        return skipped

    # -- maintenance -----------------------------------------------------

    def maybe_defrag(self) -> bool:
        """Compact once enough pages have churned through the free list
        (a quarter of the arena) — cheap insurance that long runs stay
        front-packed for gather locality after heavy eviction."""
        usable = self.alloc.num_pages - RESERVED_PAGES
        if self.alloc.frees_since_defrag < max(16, usable // 4):
            return False
        self.defrag()
        return True

    def defrag(self):
        src = self.alloc.defrag()
        with trace.span("corpus.arena.defrag"):
            self._arena = self._paged.permute_pages(
                self._arena, src, donate=self._donate
            )

    def reset(self):
        """Device-loss recovery: drop every run and rebuild an empty
        arena tensor (the old one died with the device). Cumulative
        counters survive — evictions/defrags carry into the fresh
        allocator so the Prometheus counters (type: counter) never go
        backwards; the runner re-seeds from the store."""
        old = self.alloc
        self.alloc = PageAllocator(old.num_pages, self.page)
        self.alloc.evictions = old.evictions
        self.alloc.defrags = old.defrags
        self._staged_idx, self._staged_pages = [], []
        self._arena = self._paged.new_arena(self.alloc.num_pages, self.page)

    def stats(self) -> dict:
        s = self.alloc.stats()
        s.update(spills=self.spills, uploads=self.uploads,
                 bytes_uploaded=self.bytes_uploaded)
        return s
