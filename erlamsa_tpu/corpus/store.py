"""Persistent seed store: content-hash-deduped corpus with metadata.

Layout under the --corpus directory:

    <root>/corpus.json      metadata: per-seed origin, energy, hit
                            counts, discovered-by, event tallies
    <root>/seeds/<sha256>   one file per unique seed, named by its
                            content hash — dedup is the filename

JSON-backed like services/cmanager.py's mnesia stand-in: a thread lock
guards the in-memory state and every save is an atomic tmp+rename, so
concurrent writers (monitor threads publishing through apply_event,
the runner's case loop) never corrupt the store and a crash mid-save
leaves the previous snapshot intact. Seed files are immutable once
written (content-addressed), so cross-process sharing of a corpus
directory is safe too: the worst race is two writers creating the same
file with identical bytes.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading

from ..services import chaos, logger
from ..services.resilience import RetryExhausted, RetryPolicy
from .feedback import EVENT_GAIN, Event

STORE_VERSION = 1

# metadata saves are frequent and cheap; one quick retry absorbs a
# transient disk error (or an injected store.save fault) so persistence
# actually happens instead of silently best-efforting into the void
SAVE_RETRY = RetryPolicy(attempts=2, base=0.01, max_delay=0.1,
                         retry_on=(OSError,))

INIT_ENERGY = 1.0
MIN_ENERGY = 0.25
MAX_ENERGY = 64.0


def seed_id_for(data: bytes) -> str:
    """Content hash = identity; the store's dedup key and filename."""
    return hashlib.sha256(data).hexdigest()


class CorpusStore:
    """Deduped seed corpus with per-seed scheduling metadata."""

    # lock discipline (analysis/rules_threads.py enforces this declaration):
    # every touch of these fields happens with _lock held
    _GUARDED_BY = {"_lock": ("_meta", "_next_idx", "_cache")}

    def __init__(self, root: str, create: bool = True):
        self.root = root
        self.seeds_dir = os.path.join(root, "seeds")
        self.meta_path = os.path.join(root, "corpus.json")
        if create:
            os.makedirs(self.seeds_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._meta: dict[str, dict] = {}
        self._next_idx = 0
        self._cache: dict[str, bytes] = {}
        # admission hook: called with the seed id of every NEWLY added
        # seed, outside the store lock (callers may be service threads).
        # The arena layout uses it to stage device uploads at store
        # admission — a seed crosses PCIe once, here, then mutates from
        # device pages (corpus/arena.py)
        self.listener = None
        with self._lock:
            self._load_locked()

    # --- persistence (cmanager.py idiom: atomic, best-effort) ------------

    def _load_locked(self):
        """Caller holds self._lock (only __init__, before any thread can
        see the store — locked anyway so the guarded-field discipline
        holds by inspection, not by timing argument)."""
        for candidate in (self.meta_path, self.meta_path + ".bak"):
            if not os.path.exists(candidate):
                continue
            try:
                with open(candidate) as f:
                    st = json.load(f)
                self._meta = dict(st.get("seeds", {}))
                self._next_idx = max(
                    (m.get("idx", 0) + 1 for m in self._meta.values()),
                    default=0,
                )
                if candidate != self.meta_path:
                    logger.log("warning", "corpus store %s unusable; "
                               "recovered from backup %s", self.meta_path,
                               candidate)
                return
            except (OSError, ValueError) as e:
                logger.log("warning", "corpus store %s unreadable (%s)",
                           candidate, e)
        if os.path.exists(self.meta_path):
            logger.log("warning", "corpus store %s: no usable snapshot; "
                       "starting empty", self.meta_path)

    def _save_locked(self):
        """Caller holds self._lock. Atomic AND durable: tmp is fsynced
        before the rename publishes it (a power loss after os.replace
        must not leave a truncated corpus.json — "atomic" against process
        kills alone is not durability), the previous snapshot survives as
        .bak, and the directory entry is fsynced so the rename itself
        sticks. Transient write errors get one retry; a persistently
        failing disk degrades to best-effort (the live store stays
        valid)."""
        tmp = self.meta_path + ".tmp"
        blob = json.dumps({"version": STORE_VERSION, "seeds": self._meta})

        def _write():
            chaos.fault_point("store.save")
            with open(tmp, "w") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            if os.path.exists(self.meta_path):
                try:
                    os.replace(self.meta_path, self.meta_path + ".bak")
                except OSError:
                    pass
            os.replace(tmp, self.meta_path)
            from ..services.checkpoint import fsync_dir

            fsync_dir(self.meta_path)

        try:
            SAVE_RETRY.call(_write, site="store.save")
        except (RetryExhausted, OSError):
            pass  # persistence is best-effort; the live store stays valid

    def save(self):
        with self._lock:
            self._save_locked()

    # --- seed CRUD -------------------------------------------------------

    def add(self, data: bytes, origin: str = "import",
            discovered_by: str | None = None) -> tuple[str | None, bool]:
        """Dedup-add one seed. Returns (seed_id, newly_added); empty data
        is rejected with (None, False) — a zero-byte seed can never be
        mutated into anything and would poison batch assembly."""
        if not data:
            return None, False
        sid = seed_id_for(data)
        with self._lock:
            if sid in self._meta:
                return sid, False
            path = os.path.join(self.seeds_dir, sid)
            if not os.path.exists(path):
                def _write_seed():
                    chaos.fault_point("store.seed")
                    tmp = path + ".tmp"
                    with open(tmp, "wb") as f:
                        f.write(data)
                        f.flush()
                        os.fsync(f.fileno())
                    os.replace(tmp, path)

                try:
                    SAVE_RETRY.call(_write_seed, site="store.seed")
                except (RetryExhausted, OSError):
                    # best-effort like _save_locked: the seed keeps being
                    # served from the in-memory cache this run; fsck drops
                    # the metadata entry if the file never landed
                    pass
            self._meta[sid] = {
                "idx": self._next_idx,
                "len": len(data),
                "origin": origin,
                "discovered_by": discovered_by,
                "energy": INIT_ENERGY,
                "hits": 0,
                "events": {},
            }
            self._next_idx += 1
            self._cache[sid] = data
            self._save_locked()
        if self.listener is not None:
            # outside self._lock: the listener (arena admission queue)
            # has its own lock and must not nest under the store's
            self.listener(sid)
        return sid, True

    def add_paths(self, paths: list[str]) -> tuple[int, int, int]:
        """Import seed files; unreadable/empty files are skipped with a
        logged warning instead of aborting the run (the _load_corpus
        contract). Returns (new, dup, skipped)."""
        new = dup = skipped = 0
        for p in paths:
            try:
                with open(p, "rb") as f:
                    data = f.read()
            except OSError as e:
                logger.log("warning", "corpus: skipping unreadable seed "
                           "%s: %s", p, e)
                skipped += 1
                continue
            if not data:
                logger.log("warning", "corpus: skipping empty seed %s", p)
                skipped += 1
                continue
            _sid, added = self.add(data, origin=os.path.basename(p))
            if added:
                new += 1
            else:
                dup += 1
        return new, dup, skipped

    def fsck(self, adopt_orphans: bool = True) -> dict:
        """Recovery pass: reconcile corpus.json against seeds/.

        - metadata entries whose seed file is missing are dropped (a
          schedule would crash reading them);
        - seed files whose content no longer matches their content-hash
          name are CORRUPT: quarantined to <root>/quarantine/ and dropped
          from the metadata;
        - seed files with no metadata entry (orphans — e.g. a crash
          between the file write and the corpus.json save) are adopted
          back into the store, or quarantined with adopt_orphans=False.

        Returns {"missing": n, "corrupt": n, "orphans": n, "ok": n} and
        persists the reconciled metadata when anything changed. Leftover
        .tmp files from torn writes are removed."""
        qdir = os.path.join(self.root, "quarantine")
        missing = corrupt = orphans = 0
        orphan_data: list[bytes] = []
        with self._lock:
            try:
                on_disk = set(os.listdir(self.seeds_dir))
            except OSError:
                on_disk = set()
            for name in sorted(on_disk):
                path = os.path.join(self.seeds_dir, name)
                if name.endswith(".tmp"):
                    # torn write: the content it was renaming to is either
                    # published under its hash or lost — never both
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
                    on_disk.discard(name)
                    continue
                try:
                    with open(path, "rb") as f:
                        data = f.read()
                except OSError:
                    continue
                if seed_id_for(data) != name:
                    corrupt += 1
                    os.makedirs(qdir, exist_ok=True)
                    try:
                        # lint: chaos-site-coverage-ok quarantine move on the recovery path
                        os.replace(path, os.path.join(qdir, name))
                    except OSError:
                        pass
                    self._meta.pop(name, None)
                    self._cache.pop(name, None)
                    on_disk.discard(name)
                    logger.log("warning", "corpus fsck: %s corrupt "
                               "(content/hash mismatch), quarantined", name)
                elif name not in self._meta:
                    orphans += 1
                    if adopt_orphans:
                        orphan_data.append(data)
                    else:
                        os.makedirs(qdir, exist_ok=True)
                        try:
                            # lint: chaos-site-coverage-ok quarantine move on the recovery path
                            os.replace(path, os.path.join(qdir, name))
                        except OSError:
                            pass
            for sid in [s for s in self._meta if s not in on_disk]:
                missing += 1
                del self._meta[sid]
                self._cache.pop(sid, None)
                logger.log("warning", "corpus fsck: %s in corpus.json but "
                           "its seed file is gone; entry dropped", sid)
            changed = bool(missing or corrupt
                           or (orphans and not adopt_orphans))
            if changed:
                self._save_locked()
        # adoption re-enters through add() (it takes the lock itself)
        for data in orphan_data:
            self.add(data, origin="fsck-orphan")
        ok = len(self)
        summary = {"missing": missing, "corrupt": corrupt,
                   "orphans": orphans, "ok": ok}
        if missing or corrupt or orphans:
            logger.log("info", "corpus fsck: %d ok, %d missing, %d corrupt "
                       "quarantined, %d orphan(s) %s", ok, missing, corrupt,
                       orphans, "adopted" if adopt_orphans else "quarantined")
        return summary

    def get(self, seed_id: str) -> bytes:
        with self._lock:
            data = self._cache.get(seed_id)
        if data is None:
            with open(os.path.join(self.seeds_dir, seed_id), "rb") as f:
                data = f.read()
            with self._lock:
                self._cache[seed_id] = data
        return data

    def ids(self) -> list[str]:
        """Seed ids in insertion order — THE deterministic ordering every
        scheduler draw indexes into (energy.EnergyScheduler)."""
        with self._lock:
            items = sorted(self._meta.items(), key=lambda kv: kv[1]["idx"])
        return [sid for sid, _ in items]

    def __len__(self) -> int:
        with self._lock:
            return len(self._meta)

    def __contains__(self, seed_id: str) -> bool:
        with self._lock:
            return seed_id in self._meta

    def meta(self, seed_id: str) -> dict:
        with self._lock:
            return dict(self._meta[seed_id])

    def seed_paths(self) -> list[str]:
        """Seed file paths in insertion order (the oracle engine path
        reads files; the store IS files)."""
        return [os.path.join(self.seeds_dir, s) for s in self.ids()]

    # --- energy bookkeeping ---------------------------------------------

    def bump(self, seed_id: str, delta: float, kind: str | None = None):
        with self._lock:
            m = self._meta.get(seed_id)
            if m is None:
                return
            m["energy"] = min(MAX_ENERGY,
                              max(MIN_ENERGY, m["energy"] + delta))
            if kind:
                m["events"][kind] = m["events"].get(kind, 0) + 1

    def apply_event(self, ev: Event, credit: list[str] | None = None):
        """Fold one feedback event into seed energies. Events naming a
        seed bump it directly; anonymous events (a monitor can rarely say
        WHICH input crashed the target) split the gain evenly over the
        `credit` set — the seeds scheduled in the case that was in flight,
        the same attribution AFL makes."""
        gain = EVENT_GAIN.get(ev.kind, 1.0)
        if ev.seed_id is not None and ev.seed_id in self:
            self.bump(ev.seed_id, gain, ev.kind)
        elif credit:
            share = gain / len(credit)
            for sid in credit:
                self.bump(sid, share, ev.kind)

    def retire(self, seed_id: str) -> bool:
        """Remove a seed the distillation pass proved subsumed
        (corpus/distill.py). The seed file moves to <root>/retired/ —
        evidence is preserved but fsck will not re-adopt it as an
        orphan. Returns False for unknown ids. If the move itself fails
        (or an injected store.seed fault fires) the metadata removal
        still sticks; the stranded file is adopted back by a later fsck,
        which is the safe direction — a retired seed resurfacing costs
        schedule weight, a lost seed costs coverage."""
        with self._lock:
            m = self._meta.pop(seed_id, None)
            if m is None:
                return False
            self._cache.pop(seed_id, None)
            rdir = os.path.join(self.root, "retired")
            try:
                chaos.fault_point("store.seed")
                os.makedirs(rdir, exist_ok=True)
                os.replace(os.path.join(self.seeds_dir, seed_id),
                           os.path.join(rdir, seed_id))
            except OSError as e:
                logger.log("warning", "corpus: retiring %s: move failed "
                           "(%s); file left for fsck", seed_id, e)
            self._save_locked()
        return True

    def record_scheduled(self, counts: dict[str, int]):
        """hits += n per seed: the scheduler's energy-spend record that
        decays a seed's effective weight over time (energy.seed_weights)."""
        with self._lock:
            for sid, n in counts.items():
                m = self._meta.get(sid)
                if m is not None:
                    m["hits"] += n

    def energies(self) -> dict[str, tuple[float, int]]:
        """{seed_id: (energy, hits)} — the checkpointable schedule state."""
        with self._lock:
            return {s: (m["energy"], m["hits"])
                    for s, m in self._meta.items()}

    def restore_energies(self, mapping: dict[str, tuple[float, int]]):
        """Resume path (services/checkpoint.py): restored energies make a
        resumed run schedule exactly like the uninterrupted one."""
        with self._lock:
            for sid, (energy, hits) in mapping.items():
                m = self._meta.get(sid)
                if m is not None:
                    m["energy"] = float(energy)
                    m["hits"] = int(hits)

    def stats(self) -> dict:
        with self._lock:
            events: dict[str, int] = {}
            for m in self._meta.values():
                for k, n in m["events"].items():
                    events[k] = events.get(k, 0) + n
            return {
                "seeds": len(self._meta),
                "bytes": sum(m["len"] for m in self._meta.values()),
                "total_hits": sum(m["hits"] for m in self._meta.values()),
                "events": events,
            }
