"""Feedback bus: the channel from detectors to the seed store.

Monitors (services/monitors.py), the fuzzing proxy (services/proxy.py)
and the FaaS /manage endpoint (services/faas.py) publish events here;
the corpus runner drains the bus at case boundaries and folds the events
into seed energies (store.apply_event). Publishing is always cheap and
never blocks — when nothing consumes the bus (stateless runs, the
default) events age out of a bounded deque.

Deliberately jax-free: publishers include spawned host-pool workers and
monitor threads that must never trigger an accelerator backend import
(see services/hostpool.py).
"""

from __future__ import annotations

import threading
from collections import deque
from typing import NamedTuple


class Event(NamedTuple):
    """One observed outcome.

    kind: what happened (see EVENT_GAIN for the known kinds).
    seed_id: the store id of the seed that provoked it, when the
        publisher knows it; None means "whatever was in flight" and the
        consumer credits the seeds scheduled in the current case.
    source: publisher tag, e.g. "monitor:exec" or "proxy:c->s".
    detail: free-form context for logs/stats.
    """

    kind: str
    seed_id: str | None = None
    source: str = ""
    detail: str = ""


#: energy delta per event kind (store.apply_event). Crashes dominate,
#: protocol desyncs and connect-backs rank above plain liveness drops,
#: and novel output hashes give the small per-case exploration signal.
EVENT_GAIN = {
    "crash": 8.0,
    "connback": 4.0,
    "desync": 4.0,
    "drop": 2.0,
    "finding": 2.0,
    "new_hash": 0.5,
    # a genuinely-new coverage edge outranks a merely-novel output hash:
    # hashes churn forever, the edge frontier is finite and is the
    # ground-truth exploration signal when --coverage is live
    "new_cov": 2.0,
}


class FeedbackBus:
    """Bounded thread-safe publish/drain queue."""

    # lock discipline (analysis/rules_threads.py enforces this declaration)
    _GUARDED_BY = {"_lock": ("_events", "published", "dropped")}

    def __init__(self, maxlen: int = 4096):
        self._events: deque[Event] = deque(maxlen=maxlen)
        self._lock = threading.Lock()
        self.published = 0
        self.dropped = 0  # aged out of the bounded deque before a drain

    def publish(self, kind: str, seed_id: str | None = None,
                source: str = "", detail: str = "") -> None:
        ev = Event(kind, seed_id, source, detail)
        with self._lock:
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(ev)
            self.published += 1

    def drain(self) -> list[Event]:
        """All pending events, oldest first; the bus is left empty."""
        with self._lock:
            evs = list(self._events)
            self._events.clear()
        return evs

    def pending(self) -> int:
        with self._lock:
            return len(self._events)


class SampleLedger:
    """(case, slot) -> seed-id attribution for externally-observed
    signals.

    The runner records every scheduled case here BEFORE launching it;
    the coverage fold and any monitor that can name a (case, slot) —
    e.g. an instrumented target echoing the ids the harness passed it —
    resolve through the ledger instead of guessing. Bounded: only the
    most recent `keep` cases are held, which comfortably covers the
    in-flight window (drain depth) plus monitor reporting latency.
    Thread-safe for the same reason the bus is: resolvers may be
    monitor threads.
    """

    _GUARDED_BY = {"_lock": ("_cases",)}

    def __init__(self, keep: int = 64):
        self.keep = int(keep)
        self._lock = threading.Lock()
        self._cases: dict[int, tuple[str, ...]] = {}

    def record(self, case: int, ids: list[str]) -> None:
        with self._lock:
            self._cases[case] = tuple(ids)
            while len(self._cases) > self.keep:
                self._cases.pop(next(iter(self._cases)))

    def resolve(self, case: int, slot: int) -> str | None:
        with self._lock:
            ids = self._cases.get(case)
        if ids is None or not 0 <= slot < len(ids):
            return None
        return ids[slot]

    def ids(self, case: int) -> tuple[str, ...]:
        with self._lock:
            return self._cases.get(case, ())


#: process-global bus: detectors publish here without any wiring; only a
#: feedback-mode run ever drains it
GLOBAL = FeedbackBus()


def publish(kind: str, seed_id: str | None = None,
            source: str = "", detail: str = "") -> None:
    GLOBAL.publish(kind, seed_id, source, detail)
