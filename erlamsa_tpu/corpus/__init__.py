"""Feedback-driven corpus engine.

The reference erlamsa is a pure open-loop mutator: monitors and the proxy
*detect* interesting outcomes but nothing feeds them back into seed
selection, so every batch re-mutates a static corpus with uniform
probability. This package closes the loop (SURVEY.md §7 / ROADMAP north
star) with four pieces:

  store.py      content-hash-deduped persistent seed corpus with
                per-seed metadata (origin, energy, hit counts,
                discovered-by), JSON-backed like services/cmanager.py
  energy.py     AFL-style per-seed energy scheduling with deterministic
                weighted sampling (counter-keyed like ops/prng.py, so a
                fixed -s seed replays bit-identically)
  assembler.py  power-of-two length-bucketed batch assembly bounding
                padding waste and jit recompiles; emits the uint8[B, L]
                + length vectors the device engine consumes
  feedback.py   thread-safe event bus monitors/proxy/faas publish onto
                and the store consumes to promote/demote seeds
  runner.py     the feedback-driven batch loop riding the TPU engine
                (the only module here that imports jax)

Everything except runner.py is deliberately jax-free so monitors, the
proxy and spawned host-pool workers can publish events without touching
an accelerator backend (see services/hostpool.py for why that matters in
this image).
"""

from .feedback import Event, FeedbackBus

__all__ = ["Event", "FeedbackBus"]
