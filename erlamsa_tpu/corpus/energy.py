"""Energy scheduling: deterministic weighted seed selection.

AFL-style semantics: each seed carries an energy raised by feedback
events (new output hash, monitor-reported crash, proxy desync — see
feedback.EVENT_GAIN) and its effective weight decays with the number of
times it has already been scheduled, so fresh high-signal seeds get
fuzzed hard and exhausted ones fade without ever reaching zero.

Selection is counter-keyed like the device PRNG (ops/prng.py): the draw
for case c seeds a fresh generator from (run seed, c, TAG_SCHED), never
an evolving stream — so schedules replay bit-identically at a fixed -s
seed, resume at any case without replaying earlier draws, and shard
cleanly across workers. TAG_SCHED lives in the ops/prng.py tag registry;
the copy here keeps this module jax-free (tests pin the two equal).
"""

from __future__ import annotations

import numpy as np

from .store import CorpusStore

#: mirrors ops.prng.TAG_SCHED — jax-free copy, equality test-pinned
#: (tests/test_corpus.py::test_sched_tag_matches_prng_registry)
TAG_SCHED = 0x0D


def seed_weights(energies: list[float], hits: list[int]) -> np.ndarray:
    """float64[N] sampling weights: energy decayed by sqrt of prior
    schedule count. Strictly positive — every seed stays reachable."""
    e = np.asarray(energies, np.float64)
    h = np.asarray(hits, np.float64)
    return np.maximum(e, 1e-9) / np.sqrt(1.0 + h)


class EnergyScheduler:
    """Per-case weighted seed selection over a CorpusStore."""

    def __init__(self, store: CorpusStore, seed):
        self.store = store
        self.seed_ints = (
            [int(x) for x in seed] if isinstance(seed, (tuple, list))
            else [int(seed)]
        )

    def _rng(self, case_idx: int) -> np.random.Generator:
        # counter-keyed, same construction as HybridDispatcher.split: the
        # integer seed values, NOT Python's salted hash, so the schedule
        # reproduces across processes and after resume
        return np.random.default_rng([*self.seed_ints, case_idx, TAG_SCHED])

    def schedule(self, case_idx: int, batch: int,
                 record: bool = True) -> list[str]:
        """Draw `batch` seed ids (with replacement) for one case,
        weighted by current energy state. Deterministic in
        (run seed, case_idx, energy state at call time)."""
        ids = self.store.ids()
        if not ids:
            raise ValueError("empty corpus store")
        en = self.store.energies()
        w = seed_weights(*zip(*[en[s] for s in ids]))
        picks = self._rng(case_idx).choice(len(ids), size=batch, p=w / w.sum())
        chosen = [ids[i] for i in picks]
        if record:
            counts: dict[str, int] = {}
            for sid in chosen:
                counts[sid] = counts.get(sid, 0) + 1
            self.store.record_scheduled(counts)
        return chosen
