"""Corpus distillation: per-seed coverage tensor + greedy set-cover.

The coverage hub (services/monitors.py) delivers per-sample edge
bitmaps; this module owns what the campaign LEARNS from them:

- ``CoverageIndex`` folds each sample's bitmap into a per-seed coverage
  tensor and the global accumulated map, answering the per-slot gating
  question "did this sample light a genuinely-new edge?" with the
  ops/coverage.py kernels (device) or their numpy oracles (host /
  degraded) — both bit-identical by the parity tests.
- ``greedy_minimize`` is the afl-cmin analogue: a greedy set-cover over
  the per-seed tensor keeps the smallest seed set whose union still
  covers every observed edge; everything else is provably subsumed and
  can be retired so store/arena stay lean at large corpus sizes.

Determinism: candidate rows are scanned in insertion (idx) order and
ties on gain break toward the earliest-inserted seed (np.argmax picks
the first maximum), so the same tensor always distills to the same
keep set. Seeds with EMPTY bitmaps are never retired — no coverage
evidence ever arrived for them, which is absence of signal, not proof
of subsumption.
"""

from __future__ import annotations

import numpy as np

from ..ops import coverage as covops
from ..services import chaos


class CoverageIndex:
    """Global + per-seed edge-coverage state for one campaign.

    Single-threaded by design: folds happen only at case boundaries on
    the runner thread (the determinism contract), never from monitor
    threads — the hub buffers raw frames, the runner folds them.
    """

    def __init__(self, map_bytes: int = covops.MAP_BYTES,
                 use_device: bool = False):
        self.map_bytes = int(map_bytes)
        self.use_device = bool(use_device)
        self.global_map = np.zeros(self.map_bytes, np.uint8)
        # sid -> uint8[map_bytes], insertion-ordered (dict preserves it)
        self.per_seed: dict[str, np.ndarray] = {}
        self.folds = 0

    def fold_case(self, pairs: list[tuple[str, bytes]]) -> list[int]:
        """OR one case's maps into the tensor, slot order; returns the
        per-map genuinely-new edge counts (sequential semantics: a map
        that only repeats a lower slot's edges gains 0).

        Raises OSError under an injected ``coverage.fold`` fault — the
        runner treats the whole case as uncovered (hash-novelty
        fallback) so the fault is observable but never diverging.
        """
        chaos.fault_point("coverage.fold")
        if not pairs:
            return []
        maps = np.stack([np.frombuffer(m, np.uint8) for _, m in pairs])
        if maps.shape[1] != self.map_bytes:
            raise ValueError(
                f"coverage map width {maps.shape[1]} != {self.map_bytes}")
        if self.use_device:
            gains_dev, acc_dev = covops.batch_gains(self.global_map, maps)
            gains = np.asarray(gains_dev, np.int32)
            self.global_map = np.asarray(acc_dev, np.uint8)
        else:
            gains, self.global_map = covops.batch_gains_np(
                self.global_map, maps)
        for (sid, _), row in zip(pairs, maps):
            cur = self.per_seed.get(sid)
            self.per_seed[sid] = row.copy() if cur is None else cur | row
        self.folds += 1
        return [int(g) for g in gains]

    def fold_map(self, sid: str, frame: bytes) -> None:
        """Attribution-only OR of one raw frame (no gain computation,
        no fault point): the fleet's per-shard ledgers accrue each
        seed's map on its HOME shard through this, and the window fence
        OR-reduces the ledger globals against the gating index
        (corpus/fleet.py). Gains and admission stay with fold_case."""
        row = np.frombuffer(frame, np.uint8)
        if row.shape[0] != self.map_bytes:
            raise ValueError(
                f"coverage map width {row.shape[0]} != {self.map_bytes}")
        cur = self.per_seed.get(sid)
        self.per_seed[sid] = row.copy() if cur is None else cur | row
        self.global_map |= row

    def edges(self) -> int:
        """Total distinct edges observed so far."""
        return int(covops.popcount_np(self.global_map[None])[0])

    # --- checkpoint round-trip (services/checkpoint.py) -----------------

    def snapshot(self) -> dict:
        ids = list(self.per_seed)
        maps = (np.stack([self.per_seed[s] for s in ids])
                if ids else np.zeros((0, self.map_bytes), np.uint8))
        return {"ids": ids, "maps": maps, "global": self.global_map.copy()}

    def restore(self, snap: dict):
        self.per_seed = {
            sid: np.asarray(row, np.uint8).copy()
            for sid, row in zip(snap["ids"], snap["maps"])
        }
        self.global_map = np.asarray(snap["global"], np.uint8).copy()


def greedy_minimize(ids: list[str],
                    maps: np.ndarray) -> tuple[list[str], list[str]]:
    """Greedy set-cover over per-seed coverage rows.

    Returns (keep, retired). Every retired seed's edge set is fully
    subsumed by the union of the kept set (asserted row by row, not
    just implied by the greedy loop); empty rows are always kept.
    Deterministic at fixed input: rows scanned in given order, gain
    ties break toward the earliest row.
    """
    if len(ids) != len(maps):
        raise ValueError("ids/maps length mismatch")
    if not ids:
        return [], []
    maps = np.asarray(maps, np.uint8)
    counts = covops.popcount_np(maps)
    target = np.zeros(maps.shape[1], np.uint8)
    for row in maps:
        target |= row
    covered = np.zeros_like(target)
    chosen: list[int] = []
    candidates = [i for i in range(len(ids)) if counts[i] > 0]
    while np.any(covered != target) and candidates:
        gains = covops.popcount_np(maps[candidates] & ~covered)
        best = int(np.argmax(gains))  # first max: earliest-row tie-break
        if gains[best] == 0:
            break
        pick = candidates.pop(best)
        chosen.append(pick)
        covered |= maps[pick]
    keep_idx = set(chosen) | {i for i in range(len(ids)) if counts[i] == 0}
    retired = [
        ids[i] for i in range(len(ids))
        if i not in keep_idx and not np.any(maps[i] & ~covered)
    ]
    keep = [ids[i] for i in range(len(ids)) if ids[i] not in set(retired)]
    return keep, retired
