"""Generic list mutations with the reference's exact draw order.

Reference: src/erlamsa_generic.erl:52-162. Operates on Python lists (of
lines, bytes, or arbitrary elements); every random draw maps 1:1 onto an
erlamsa_rnd call so the AS183 stream stays aligned.
"""

from __future__ import annotations

from ..utils.erlrand import ErlRand

STORED_ELEMS = 10


def list_del(r: ErlRand, l: list) -> list:
    """Delete one random element (erlamsa_generic.erl:52-57)."""
    if not l:
        return l
    p = r.erand(len(l))
    return l[: p - 1] + l[p:]


def list_del_seq(r: ErlRand, l: list) -> list:
    """Delete a run: keep first start-1 elements, then resume from offset n
    within the tail (erlamsa_generic.erl:59-66: applynth + lists:sublist)."""
    if not l:
        return l
    ln = len(l)
    start = r.erand(ln)
    n = r.erand(ln - start + 1)
    rest = l[start:]  # after dropping element at `start`
    return l[: start - 1] + rest[n - 1 : n - 1 + ln]


def list_dup(r: ErlRand, l: list) -> list:
    """Duplicate one element (erlamsa_generic.erl:68-73)."""
    if not l:
        return l
    p = r.erand(len(l))
    return l[: p - 1] + [l[p - 1], l[p - 1]] + l[p:]


def list_repeat(r: ErlRand, l: list) -> list:
    """Replace one element with max(2, rand_log(10)) copies
    (erlamsa_generic.erl:75-82)."""
    if not l:
        return l
    p = r.erand(len(l))
    n = max(2, r.rand_log(10))
    return l[: p - 1] + [l[p - 1]] * n + l[p:]


def list_clone(r: ErlRand, l: list) -> list:
    """Overwrite element To with a copy of element From
    (erlamsa_generic.erl:84-91)."""
    if not l:
        return l
    frm = r.erand(len(l))
    to = r.erand(len(l))
    elem = l[frm - 1]
    return l[: to - 1] + [elem] + l[to:]


def list_swap(r: ErlRand, l: list) -> list:
    """Swap two adjacent elements (erlamsa_generic.erl:93-99)."""
    if len(l) < 2:
        return l
    p = r.erand(len(l) - 1)
    out = list(l)
    out[p - 1], out[p] = out[p], out[p - 1]
    return out


def list_perm(r: ErlRand, l: list) -> list:
    """Permute a run of N = max(2, min(A, B)) elements from a random start
    (erlamsa_generic.erl:101-116)."""
    ln = len(l)
    if ln < 3:
        return l
    frm = r.erand(ln - 1)
    a = r.rand_range(2, ln - frm)
    b = r.rand_log(10)
    n = max(2, min(a, b))
    head = l[: frm - 1]
    seg = l[frm - 1 : frm - 1 + n]
    tail = l[frm - 1 + n :]
    return head + r.random_permutation(seg) + tail


# --- stateful ops: 10-slot reservoir carried across calls ----------------
# state = [count, elem1, elem2, ...] (erlamsa_generic.erl:118-143)


def _step_state(r: ErlRand, st: list, l: list) -> list:
    ln = len(l)
    st = list(st)
    while st[0] < STORED_ELEMS:
        p = r.erand(ln)
        st = [st[0] + 1, l[p - 1]] + st[1:]
    up = r.erand(STORED_ELEMS << 1)  # [1, 20]; updates fire for up in [1, 9]
    if up < STORED_ELEMS:
        ep = r.erand(ln)
        new = l[ep - 1]
        old = st[up]
        # the reference's applynth fun keeps the slot as the nested term
        # [New | tl(Old)] (erlamsa_generic.erl:135): the first update's tail
        # is the original line minus its head byte; a SECOND update drops
        # the whole previous New and keeps that same tail. Model the slot
        # as (new_line, tail_bytes).
        if isinstance(old, tuple):
            tail = old[1]
        else:
            tail = old[1:]
        st[up] = (new, tail)
    return st


def _pick_state(r: ErlRand, st: list):
    p = r.erand(st[0])
    return st[p]


def st_list_ins(r: ErlRand, st: list, l: list) -> tuple[list, list]:
    """Insert a reservoir element at a random position
    (erlamsa_generic.erl:155-157)."""
    stp = _step_state(r, st, l)
    x = _pick_state(r, stp)
    p = r.erand(len(l))
    return stp, l[: p - 1] + [x] + l[p - 1 :]


def st_list_replace(r: ErlRand, st: list, l: list) -> tuple[list, list]:
    """Overwrite a random position with a reservoir element
    (erlamsa_generic.erl:160-162)."""
    stp = _step_state(r, st, l)
    x = _pick_state(r, stp)
    p = r.erand(len(l))
    return stp, l[: p - 1] + [x] + l[p:]
