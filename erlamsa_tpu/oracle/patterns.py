"""Oracle mutation patterns over block streams.

Sequential re-implementation of src/erlamsa_patterns.erl with the
reference's draw order: od (once), nd (geometric many), bu (burst), sk
(skip prefix), sz (sizer-aware), cs (checksum-preserving), ar (ZIP
archive), cp (gzip/deflate), nu (none), co (coin flip).

A pattern call takes (ll, rows, meta) where ll is a list of byte blocks
(possibly with thunks) and rows is the mux mutator table; it returns
(blocks_out, rows', meta') with blocks fully forced — the forcing order
matches the reference's lazy-stream consumption, so AS183 draws align.
"""

from __future__ import annotations

import gzip as gzipmod
import zlib

from ..constants import ABSMAX_BINARY_BLOCK, ABSMAXHALF_BINARY_BLOCK, INITIAL_IP, REMUTATE_PROBABILITY
from ..models import fieldpred, zipops
from ..utils.erlrand import ErlRand
from .mutations import Ctx, apply_mux


def _force(x):
    while callable(x):
        x = x()
    return x


def _uncons(ll):
    ll = _force(ll)
    if isinstance(ll, (bytes, bytearray)):
        return bytes(ll), []
    if not ll:
        return None, []
    return _force(ll[0]), ll[1:]


def _split_maxblocks(r: ErlRand, this: bytes, acc: list) -> list:
    """Giant blocks split below the 1MB bitstring cap with a random cut
    (src/erlamsa_patterns.erl:45-51)."""
    while len(this) > ABSMAX_BINARY_BLOCK:
        s = ABSMAXHALF_BINARY_BLOCK
        cut = s + r.rand(s) - 1
        acc = [this[:cut]] + acc
        this = this[cut:]
    return [this] + acc


def _split(r: ErlRand, this, rest):
    """(src/erlamsa_patterns.erl:53-60)."""
    if this is None:
        return None, rest
    if isinstance(this, bytes) and len(this) > ABSMAX_BINARY_BLOCK:
        lst = _split_maxblocks(r, this, [])
        lst = lst[::-1] + list(rest)  # cons_revlst
        return lst[0], lst[1:]
    return this, rest


def _mutate_once_loop(ctx: Ctx, rows, meta, cont, ip, this, ll):
    """Walk blocks, 1/rand(Ip) trigger per block
    (src/erlamsa_patterns.erl:281-296)."""
    out_blocks: list[bytes] = []
    while True:
        ll = _force(ll)
        n = ctx.r.rand(ip)
        if n == 0 or ll == []:
            nrows, nll, nmeta = apply_mux(ctx, rows, [this] + list(ll), meta)
            blocks, frows, fmeta = cont(nll, nrows, nmeta)
            return out_blocks + blocks, frows, fmeta
        out_blocks.append(this)
        this, ll = _force(ll[0]), ll[1:]


def _mutate_once(ctx: Ctx, ll, rows, meta, cont):
    """(src/erlamsa_patterns.erl:266-278)."""
    if ll == [b""]:
        return [], rows, [("mutate_once", "empty_stopped")] + meta
    ip = ctx.r.rand(INITIAL_IP)
    this, rest = _uncons(ll)
    this, rest = _split(ctx.r, this, rest)
    if this is not None:
        return _mutate_once_loop(ctx, rows, meta, cont, ip, this, rest)
    return cont([], rows, meta)


def _final(ll, rows, meta):
    return [b for b in map(_force, ll) if isinstance(b, (bytes, bytearray))], rows, meta


def pat_once_dec(ctx: Ctx, ll, rows, meta):
    """od (src/erlamsa_patterns.erl:307-309)."""
    return _mutate_once(ctx, ll, rows, [("pattern", "once_dec")] + meta, _final)


def pat_many_dec(ctx: Ctx, ll, rows, meta):
    """nd: remutate with 4/5 probability (src/erlamsa_patterns.erl:314-326)."""

    def cont(l, rw, mt):
        if ctx.r.rand_occurs(REMUTATE_PROBABILITY):
            return pat_many_dec(ctx, l, rw, mt)
        return _final(l, rw, mt)

    return _mutate_once(ctx, ll, rows, [("pattern", "many_dec")] + meta, cont)


def pat_burst(ctx: Ctx, ll, rows, meta):
    """bu: >= 2 consecutive mutations at the same stream point
    (src/erlamsa_patterns.erl:330-349)."""

    def cont(l, rw, mt, n=1):
        while True:
            p = ctx.r.rand_occurs(REMUTATE_PROBABILITY)
            if p or n < 2:
                rw, l, mt = apply_mux(ctx, rw, l, mt)
                n += 1
                continue
            return _final(l, rw, mt)

    return _mutate_once(ctx, ll, rows, [("pattern", "burst")] + meta, cont)


def _rand_cont_pattern(ctx: Ctx):
    """make_complex_pat picks a continuation pattern from the FULL table
    each call (src/erlamsa_patterns.erl:352-357)."""
    table = patterns_table()
    _pri, fn, _name, _desc = ctx.r.rand_elem(table)
    return fn


def pat_skip(ctx: Ctx, ll, rows, meta):
    """sk: protect a random prefix (src/erlamsa_patterns.erl:147-161)."""
    next_pat = _rand_cont_pattern(ctx)
    meta = [("pattern", "skipper")] + meta
    ip = ctx.r.rand(INITIAL_IP)
    bin_, rest = _uncons(ll)
    if bin_ is None:
        return [], rows, meta
    ln = ctx.r.rand(len(bin_) // 2)
    head, tail = bin_[:ln], bin_[ln:]
    this, rest = _split(ctx.r, tail, rest)
    meta2 = [("skipped", ln)] + meta
    if this is not None:
        blocks, frows, fmeta = _mutate_once_loop(
            ctx, rows, meta2, lambda l, rw, mt: next_pat(ctx, l, rw, mt), ip, this, rest
        )
    else:
        blocks, frows, fmeta = [], rows, meta2
    return [head] + blocks, frows, fmeta


def _prepare4sizer(blocks):
    """Join leading binaries (src/erlamsa_patterns.erl:64-78)."""
    return b"".join(blocks)


def pat_sizer(ctx: Ctx, ll, rows, meta):
    """sz: find a length field and mutate the enclosed blob
    (src/erlamsa_patterns.erl:81-111)."""
    next_pat = _rand_cont_pattern(ctx)
    meta = [("pattern", "sizer")] + meta
    ip = ctx.r.rand(INITIAL_IP)
    bin_, rest = _uncons(ll)
    if bin_ is None:
        return [], rows, meta
    elem = ctx.r.rand_elem(fieldpred.get_possible_simple_lens(ctx.r, bin_))
    if not elem:
        this, rest2 = _split(ctx.r, bin_, rest)
        return _mutate_once_loop(
            ctx, rows, [("sizer", "failed")] + meta,
            lambda l, rw, mt: next_pat(ctx, l, rw, mt), ip, this, rest2,
        )
    size, endian, _lval, _a, _b = elem
    head, _lv, blob, tailbin = fieldpred.extract_blob(bin_, elem)
    this, rest2 = _split(ctx.r, blob, rest)
    blocks, frows, fmeta = _mutate_once_loop(
        ctx, rows, [("sizer", elem)] + meta,
        lambda l, rw, mt: next_pat(ctx, l, rw, mt), ip, this, rest2,
    )
    new_blob = _prepare4sizer(blocks)
    new_bin = fieldpred.rebuild_blob(endian, head, len(new_blob), size, b"", new_blob)
    return [new_bin, tailbin], frows, fmeta


def pat_csum(ctx: Ctx, ll, rows, meta):
    """cs: mutate a checksummed body and fix the trailer
    (src/erlamsa_patterns.erl:115-144)."""
    next_pat = _rand_cont_pattern(ctx)
    meta = [("pattern", "csum")] + meta
    ip = ctx.r.rand(INITIAL_IP)
    bin_, rest = _uncons(ll)
    if bin_ is None:
        return [], rows, meta
    elem = ctx.r.rand_elem(fieldpred.get_possible_csum_locations(bin_))
    if not elem:
        this, rest2 = _split(ctx.r, bin_, rest)
        return _mutate_once_loop(
            ctx, rows, [("csum", "failed")] + meta,
            lambda l, rw, mt: next_pat(ctx, l, rw, mt), ip, this, rest2,
        )
    kind, size, plen, blen = elem
    pre, blob = bin_[:plen], bin_[plen : plen + blen]
    this, rest2 = _split(ctx.r, blob, rest)
    blocks, frows, fmeta = _mutate_once_loop(
        ctx, rows, [("csum", elem)] + meta,
        lambda l, rw, mt: next_pat(ctx, l, rw, mt), ip, this, rest2,
    )
    new_blob = _prepare4sizer(blocks)
    c = fieldpred.recalc_csum(kind, new_blob)
    return [pre + new_blob + c.to_bytes(size // 8, "big")], frows, fmeta


def pat_archiver(ctx: Ctx, ll, rows, meta):
    """ar: mutate ~25% of ZIP members (src/erlamsa_patterns.erl:165-214)."""
    next_pat = _rand_cont_pattern(ctx)
    meta = [("pattern", "archiver")] + meta
    ip = ctx.r.rand(INITIAL_IP)
    bin_, rest = _uncons(ll)
    if bin_ is None:
        return [], rows, meta
    joined = bin_
    if rest and all(isinstance(x, (bytes, bytearray)) for x in rest):
        joined = bin_ + b"".join(rest)
        rest = []
    members = zipops.list_members(joined)
    if members is None:
        this, rest2 = _split(ctx.r, joined, rest)
        return _mutate_once_loop(
            ctx, rows, [("archiver", "failed")] + meta,
            lambda l, rw, mt: next_pat(ctx, l, rw, mt), ip, this, rest2,
        )
    new_members = []
    frows = rows
    for name, content in members:
        if ctx.r.rand(1000) > 750:  # 25%-ish per member
            blocks, frows, _m = _mutate_once_loop(
                ctx, frows, [], lambda l, rw, mt: next_pat(ctx, l, rw, mt),
                ip, content, [],
            )
            new_members.append((name, _prepare4sizer(blocks)))
        else:
            new_members.append((name, content))
    try:
        return [zipops.rebuild(new_members)], frows, [("archiver", "ok")] + meta
    except Exception:  # lint: broad-except-ok rebuild failure degrades to joined bytes
        return [joined], frows, [("archiver", "failed")] + meta


def pat_compressed(ctx: Ctx, ll, rows, meta):
    """cp: decompress (gzip, then raw zlib), mutate, recompress
    (src/erlamsa_patterns.erl:216-260)."""
    next_pat = _rand_cont_pattern(ctx)
    meta = [("pattern", "compressed")] + meta
    ip = ctx.r.rand(INITIAL_IP)
    bin_, rest = _uncons(ll)
    if bin_ is None:
        return [], rows, meta
    new_bin, frows, ok = None, rows, False
    for kind in ("gzip", "deflate"):
        try:
            data = gzipmod.decompress(bin_) if kind == "gzip" else zlib.decompress(bin_)
            blocks, frows, _m = _mutate_once_loop(
                ctx, rows, [], lambda l, rw, mt: next_pat(ctx, l, rw, mt),
                ip, data, [],
            )
            payload = _prepare4sizer(blocks)
            # mtime=0 keeps recompression deterministic: gzip's header
            # otherwise embeds wall-clock seconds and identical seeds
            # produce different bytes across calls
            new_bin = (
                gzipmod.compress(payload, mtime=0)
                if kind == "gzip"
                else zlib.compress(payload)
            )
            meta = [("compressed", kind)] + meta
            ok = True
            break
        except Exception:  # lint: broad-except-ok codec probe: try the next kind
            continue
    if not ok or new_bin == bin_:
        this, rest2 = _split(ctx.r, bin_, rest)
        return _mutate_once_loop(
            ctx, frows, [("compressed", "failed")] + meta,
            lambda l, rw, mt: next_pat(ctx, l, rw, mt), ip, this, rest2,
        )
    return [new_bin] + [b for b in rest if isinstance(b, bytes)], frows, meta


def pat_nomuta(ctx: Ctx, ll, rows, meta):
    """nu (src/erlamsa_patterns.erl:387-390)."""
    this, rest = _uncons(ll)
    this, rest = _split(ctx.r, this, rest)
    blocks = [] if this is None else [this]
    for b in rest:
        b = _force(b)
        if isinstance(b, (bytes, bytearray)):
            blocks.append(bytes(b))
    return blocks, rows, [("pattern", "no_muta")] + meta


def pat_50_muta(ctx: Ctx, ll, rows, meta):
    """co (src/erlamsa_patterns.erl:379-384)."""
    if ctx.r.erand(2) == 1:
        return pat_nomuta(ctx, ll, rows, meta)
    return pat_once_dec(ctx, ll, rows, meta)


_TABLE = None


def patterns_table():
    """(pri, fn, name, desc) rows (src/erlamsa_patterns.erl:394-405)."""
    global _TABLE
    if _TABLE is None:
        _TABLE = [
            (1, pat_once_dec, "od", "Mutate once pattern"),
            (2, pat_many_dec, "nd", "Mutate possibly many times"),
            (1, pat_burst, "bu", "Make several mutations closeby once"),
            (2, pat_skip, "sk", "Skip random sized block and mutate rest"),
            (2, pat_sizer, "sz", "Try to find sizer and mutate enclosed data"),
            (1, pat_csum, "cs", "Try to find control sum field and mutate enclosed data"),
            (1, pat_archiver, "ar", "Check whether data is an container (ZIP) and mutate enclosed files"),
            (1, pat_compressed, "cp", "Check whether data compressed, decompress and mutate"),
            (0, pat_50_muta, "co", "Coin-flip pattern"),
            (0, pat_nomuta, "nu", "Pattern that calls no mutations"),
        ]
    return _TABLE


def default_patterns() -> list[tuple[str, int]]:
    return [(name, pri) for pri, _fn, name, _d in patterns_table()]


def make_pattern(selected: list[tuple[str, int]]):
    """Priority-muxed pattern chooser (src/erlamsa_patterns.erl:416-443)."""
    sel = dict(selected)
    pats = [
        (sel[name], fn)
        for pri, fn, name, _d in patterns_table()
        if name in sel
    ]
    pats.sort(key=lambda x: -x[0])
    total = sum(p for p, _ in pats)

    def pattern(ctx: Ctx, ll, rows, meta):
        n = ctx.r.rand(total)
        for pri, fn in pats:
            if n < pri or pri == 0 and n == 0:
                return fn(ctx, ll, rows, meta)
            n -= pri
        return pats[-1][1](ctx, ll, rows, meta)

    return pattern
