"""The oracle's 38-mutator registry and mux scheduler.

Sequential, AS183-driven re-implementation of src/erlamsa_mutations.erl.
Each mutator is fn(ll, meta) -> (next_fn, ll', meta', delta) over a list
whose head is the current bytes block (tail may hold further blocks or
thunks). mux semantics (weighted permutation, retry-until-changed,
self-adjusting scores, list reordering) follow mux_fuzzers
(src/erlamsa_mutations.erl:1244-1280) draw-for-draw.

Byte-exact parity notes: closed-form mutators (byte/seq/num/line/utf8/
lines/fuse/len) follow the reference's draw order exactly; the JSON/SGML
engines are behavioral re-implementations with their own draw sequences
(documented in erlamsa_tpu/models/)."""

from __future__ import annotations

import base64 as b64mod
import math
from typing import Callable

import numpy as np

from ..constants import ABSMAX_BINARY_BLOCK, MAX_SCORE, MIN_SCORE
from ..models import fieldpred, fuse as fusemod, jsonfmt, sgmlfmt, strlex, treeops, zipops
from ..utils.bytehelpers import binarish, flush_bvecs, halve
from ..utils.erlrand import ErlRand
from ..utils.tables import funny_unicode, interesting_numbers
from . import generic, textmutas


class Ctx:
    """Shared oracle context: the PRNG and host-side config (the reference
    keeps the latter in the global_config ets table,
    src/erlamsa_app.erl:129).

    The PRNG slot is THREAD-LOCAL (with the constructor's rand as the
    shared default): a case abandoned by the per-case watchdog
    (utils/watchdog.py) keeps running in its own thread, and it must keep
    drawing from its own worker stream rather than racing the live case's
    — the reference gets this isolation from per-case Erlang processes
    (src/erlamsa_main.erl:180-221)."""

    def __init__(self, r: ErlRand, ssrf_host="localhost", ssrf_port=51234):
        import threading

        self._r_default = r
        self._r_local = threading.local()
        self.ssrf_host = ssrf_host
        self.ssrf_port = ssrf_port

    @property
    def r(self) -> ErlRand:
        return getattr(self._r_local, "value", None) or self._r_default

    @r.setter
    def r(self, rand: ErlRand) -> None:
        self._r_local.value = rand

    @property
    def ssrf_ep(self):
        return (self.ssrf_host, self.ssrf_port)

    def ssrf_uri(self) -> str:
        return f"://{self.ssrf_host}:{self.ssrf_port}/"


# --- byte-level helpers ---------------------------------------------------


def _edit_byte(data: bytes, pos: int, repl: bytes) -> bytes:
    """Clone-and-edit at position (edit_byte_vector,
    src/erlamsa_mutations.erl:54-61); empty input unchanged."""
    if not data:
        return data
    return data[:pos] + repl + data[pos + 1 :]


def _mk_byte_muta(ctx: Ctx, edit: Callable[[Ctx, int], bytes], name: str):
    """construct_sed_byte_muta: draws P, then D, then the edit's own draws
    (src/erlamsa_mutations.erl:175-181)."""

    def fn(ll, meta):
        h = ll[0]
        p = ctx.r.rand(len(h))
        d = ctx.r.rand_delta()
        new = _edit_byte(h, p, edit(ctx, h[p]) if h else b"")
        return fn, [new] + ll[1:], [(name, d)] + meta, d

    return fn


def _mk_bytes_muta(ctx: Ctx, op: Callable, name: str):
    """construct_sed_bytes_muta: S, L, op draws, then D
    (src/erlamsa_mutations.erl:230-249)."""

    def fn(ll, meta):
        h = ll[0]
        if not h:
            return fn, ll, [(name, -1)] + meta, -1
        bsize = len(h)
        s = ctx.r.rand(bsize)
        l = ctx.r.rand_range(1, bsize - s + 1)
        head, span, tail = h[:s], h[s : s + l], h[s + l :]
        new_ll = op(ctx, head, span, tail, ll[1:])
        d = ctx.r.rand_delta()
        return fn, new_ll, [(name, bsize)] + meta, d

    return fn


# --- textual number (src/erlamsa_mutations.erl:63-169) --------------------


def mutate_float(r: ErlRand, num: float) -> float:
    t = r.rand(7)
    if t == 0:
        return -num
    if t == 1:
        return 0.0
    if t == 2:
        return 1.0
    if t == 3:
        return 1.0e-323
    if t == 4:
        return 1.0e308
    return r.rand_float() * math.exp(100 * r.rand_float())


def mutate_num(r: ErlRand, num: int) -> int:
    """12 strategies; ids 6 and 11 hit the catch-all via clause order
    (src/erlamsa_mutations.erl:92-112)."""
    t = r.rand(12)
    if t == 0:
        return num + 1
    if t == 1:
        return num - 1
    if t == 2:
        return 0
    if t == 3:
        return 1
    if t in (4, 5):
        return r.rand_elem(interesting_numbers())
    if t == 7:
        return num + r.rand_elem(interesting_numbers())
    if t == 8:
        return num - r.rand_elem(interesting_numbers())
    if t == 9:
        sign = 1 if num >= 0 else -1
        return num - r.rand(abs(num) * 2) * sign
    if t == 10:
        return -num
    n = r.rand_range(1, 129)
    l = r.rand_log(n)
    s = r.rand(3)
    return num - l if s == 0 else num + l


def _find_numbers(data: bytes) -> list[tuple[int, int, int]]:
    """Non-overlapping (start, end, value) runs, matching get_num's
    left-to-right walk with leading-dash sign consumption
    (src/erlamsa_mutations.erl:114-151)."""
    out = []
    i, n = 0, len(data)
    # walk only the digit/dash EVENTS (one vector pass) — binary data is
    # mostly neither, and the per-byte outer walk was measurable at 4KB
    # inputs; the run parser is untouched, and events already consumed by
    # a previous run skip monotonically (same pattern as treeops)
    arr = np.frombuffer(data, dtype=np.uint8)
    events = np.flatnonzero(((arr >= 48) & (arr <= 57)) | (arr == 45)).tolist()
    for p in events:
        if p < i:
            continue  # inside the run a previous event already parsed
        i = p  # data[p] is a digit or dash by construction
        j = i
        sign = 1
        digits = 0
        val = 0
        while j < n:
            c = data[j]
            if 48 <= c <= 57:
                val = val * 10 + (c - 48)
                digits += 1
                j += 1
            elif c == 45 and digits == 0:
                sign = -1
                j += 1
            else:
                break
        if digits:
            out.append((i, j, sign * val))
            i = j
        else:
            i = j if j > i else i + 1
    return out


def sed_num(ctx: Ctx):
    """num (src/erlamsa_mutations.erl:153-169)."""

    def fn(ll, meta):
        r = ctx.r
        h = ll[0]
        nums = _find_numbers(h)
        which = r.rand(len(nums))
        if not nums:
            # no numbers: Which stays 0 at top; the data is still re-flushed
            # (so a >2KB head re-splits and mux counts the try as "used",
            # matching the reference's hd comparison)
            d = -1 if r.rand(10) == 0 else 0
            return fn, flush_bvecs(h, ll[1:]), [("muta_num", 0)] + meta, d
        # the leftover-Which counts numbers from the END
        a, b, val = nums[len(nums) - 1 - which]
        new_val = mutate_num(r, val)
        new = h[:a] + str(new_val).encode() + h[b:]
        isbin = binarish(new)
        new_ll = flush_bvecs(new, ll[1:])
        d = -1 if isbin else 2
        return fn, new_ll, [("muta_num", 1)] + meta, d

    return fn


# --- mutator constructors -------------------------------------------------


def build_mutators(ctx: Ctx, custom=()) -> list[list]:
    """The mutations() table: [score, pri, fn, name] rows in reference
    order (src/erlamsa_mutations.erl:1283-1332). Construction-time draws
    (the randmask mask picks) happen here, in row order, like the
    reference's list evaluation."""
    r = ctx.r

    def sed_byte_drop(c, b):
        return b""

    def sed_byte_inc(c, b):
        return bytes([(b + 1) & 255])

    def sed_byte_dec(c, b):
        return bytes([(b - 1) & 255])

    def sed_byte_repeat(c, b):
        return bytes([b, b])

    def sed_byte_flip(c, b):
        return bytes([b ^ (1 << c.r.rand(8))])

    def sed_byte_insert(c, b):
        return bytes([c.r.rand(256), b])

    def sed_byte_random(c, b):
        return bytes([c.r.rand(256)])

    def op_perm(c, head, span, tail, rest):
        permed = bytes(c.r.random_permutation(list(span)))
        return [head + permed + tail] + rest

    def op_repeat(c, head, span, tail, rest):
        n = max(2, c.r.rand_log(10))
        return [head + span * n + tail] + rest

    def op_drop(c, head, span, tail, rest):
        return [head + tail] + rest

    def mask_nand(c, b):
        return b & ~(1 << c.r.rand(8))

    def mask_or(c, b):
        return b | (1 << c.r.rand(8))

    def mask_xor(c, b):
        return b ^ (1 << c.r.rand(8))

    def mask_replace(c, b):
        return c.r.rand(256)

    def mk_randmask(mask_funs):
        # mask fun drawn once at construction (src/erlamsa_mutations.erl:309-312)
        mask_fun = r.rand_elem(mask_funs)

        def op(c, head, span, tail, rest):
            # randmask: prob erand(100)/100 per byte with the nom==1 quirk.
            # The reference draws the NEXT byte's occurrence flag before the
            # current byte's mask draw and discards the final one at [] —
            # N+1 flag draws total (src/erlamsa_mutations.erl:279-291).
            prob = c.r.erand(100)
            flag = c.r.rand_occurs_fixed(prob, 100)
            out = bytearray()
            for byte in span:
                cur = flag
                flag = c.r.rand_occurs_fixed(prob, 100)
                if cur:
                    out.append(mask_fun(c, byte) & 0xFF)
                else:
                    out.append(byte)
            return [head + bytes(out) + tail] + rest

        return op

    rows = [
        [MAX_SCORE, 10, sgml_mutator(ctx), "sgm"],
        [MAX_SCORE, 3, json_mutator(ctx), "js"],
        [MAX_SCORE, 1, sed_utf8_widen(ctx), "uw"],
        [MAX_SCORE, 2, sed_utf8_insert(ctx), "ui"],
        [MAX_SCORE, 1, ascii_bad_mutator(ctx), "ab"],
        [MAX_SCORE, 1, ascii_delimeter_mutator(ctx), "ad"],
        [MAX_SCORE, 1, tree_op(ctx, treeops.sed_tree_dup, "tree_dup"), "tr2"],
        [MAX_SCORE, 1, tree_op(ctx, treeops.sed_tree_del, "tree_del"), "td"],
        [MAX_SCORE, 3, sed_num(ctx), "num"],
        [MAX_SCORE, 2, tree_swap(ctx, treeops.sed_tree_swap_one, "tree_swap_one"), "ts1"],
        [MAX_SCORE, 2, tree_stutter(ctx), "tr"],
        [MAX_SCORE, 2, tree_swap(ctx, treeops.sed_tree_swap_two, "tree_swap_two"), "ts2"],
        [MAX_SCORE, 1, _mk_byte_muta(ctx, sed_byte_drop, "byte_drop"), "bd"],
        [MAX_SCORE, 1, _mk_byte_muta(ctx, sed_byte_inc, "byte_inc"), "bei"],
        [MAX_SCORE, 1, _mk_byte_muta(ctx, sed_byte_dec, "byte_dec"), "bed"],
        [MAX_SCORE, 1, _mk_byte_muta(ctx, sed_byte_flip, "byte_flip"), "bf"],
        [MAX_SCORE, 1, _mk_byte_muta(ctx, sed_byte_insert, "byte_insert"), "bi"],
        [MAX_SCORE, 1, _mk_byte_muta(ctx, sed_byte_random, "byte_swap_random"), "ber"],
        [MAX_SCORE, 1, _mk_byte_muta(ctx, sed_byte_repeat, "byte_repeat"), "br"],
        [MAX_SCORE, 1, _mk_bytes_muta(ctx, op_perm, "seq_perm"), "sp"],
        [MAX_SCORE, 1, _mk_bytes_muta(ctx, op_repeat, "seq_repeat"), "sr"],
        [MAX_SCORE, 1, _mk_bytes_muta(ctx, op_drop, "seq_drop"), "sd"],
        [MAX_SCORE, 1, _mk_bytes_muta(
            ctx, mk_randmask([mask_nand, mask_or, mask_xor]), "seq_randmask"), "snand"],
        [MAX_SCORE, 1, _mk_bytes_muta(ctx, mk_randmask([mask_replace]), "seq_randmask"), "srnd"],
        [MAX_SCORE, 1, line_muta(ctx, generic.list_del, "line_del"), "ld"],
        [MAX_SCORE, 1, line_muta(ctx, generic.list_del_seq, "line_del_seq"), "lds"],
        [MAX_SCORE, 1, line_muta(ctx, generic.list_dup, "line_dup"), "lr2"],
        [MAX_SCORE, 1, line_muta(ctx, generic.list_clone, "line_clone"), "lri"],
        [MAX_SCORE, 1, line_muta(ctx, generic.list_repeat, "line_repeat"), "lr"],
        [MAX_SCORE, 1, line_muta(ctx, generic.list_swap, "line_swap"), "ls"],
        [MAX_SCORE, 1, line_muta(ctx, generic.list_perm, "line_perm"), "lp"],
        [MAX_SCORE, 1, st_line_muta(ctx, generic.st_list_ins, "list_ins"), "lis"],
        [MAX_SCORE, 1, st_line_muta(ctx, generic.st_list_replace, "list_replace"), "lrs"],
        [MAX_SCORE, 2, sed_fuse_this(ctx), "ft"],
        [MAX_SCORE, 1, sed_fuse_next(ctx), "fn"],
        [MAX_SCORE, 2, sed_fuse_old(ctx), "fo"],
        [MAX_SCORE, 2, length_predict(ctx), "len"],
        [MAX_SCORE, 7, base64_mutator(ctx), "b64"],
        [MAX_SCORE, 1, uri_mutator(ctx), "uri"],
        [MAX_SCORE, 1, zip_path_traversal(ctx), "zip"],
        [MAX_SCORE, 0, nomutation(), "nil"],
    ]
    return rows + [list(row) for row in custom]


# --- lines (src/erlamsa_mutations.erl:320-378) ----------------------------


def _lines(data: bytes) -> list[bytes]:
    out = []
    cur = bytearray()
    for b in data:
        cur.append(b)
        if b == 10:
            out.append(bytes(cur))
            cur = bytearray()
    if cur:
        out.append(bytes(cur))
    return out


def _try_lines(data: bytes):
    ls = _lines(data)
    if not ls or binarish(data):
        return None
    return ls


def line_muta(ctx: Ctx, op, name: str):
    def fn(ll, meta):
        ls = _try_lines(ll[0])
        if ls is None:
            return fn, ll, meta, -1
        mls = op(ctx.r, ls)
        return fn, [b"".join(mls)] + ll[1:], [(name, 1)] + meta, 1

    return fn


def st_line_muta(ctx: Ctx, op, name: str, initial_state=None):
    state = initial_state if initial_state is not None else [0]

    def make(state):
        def fn(ll, meta):
            ls = _try_lines(ll[0])
            if ls is None:
                return make(state), ll, meta, -1
            stp, new_ls = op(ctx.r, state, ls)
            return make(stp), [b"".join(_as_bytes(x) for x in new_ls)] + ll[1:], [
                (name, 1)
            ] + meta, 1

        return fn

    return make(state)


def _as_bytes(x) -> bytes:
    if isinstance(x, (bytes, bytearray)):
        return bytes(x)
    if isinstance(x, int):
        return bytes([x & 0xFF])
    if isinstance(x, tuple):  # nested reservoir slot (new_line, tail_bytes)
        return _as_bytes(x[0]) + _as_bytes(x[1])
    return b"".join(_as_bytes(e) for e in x)


# --- utf8 (src/erlamsa_mutations.erl:1025-1099) ---------------------------


def sed_utf8_widen(ctx: Ctx):
    def fn(ll, meta):
        h = ll[0]
        p = ctx.r.rand(len(h))
        d = ctx.r.rand_delta()
        if h and (h[p] & 0x3F) == h[p]:
            new = _edit_byte(h, p, bytes([0xC0, h[p] | 0x80]))
        else:
            new = h
        return fn, [new] + ll[1:], [("sed_utf8_widen", d)] + meta, d

    return fn


def sed_utf8_insert(ctx: Ctx):
    def fn(ll, meta):
        h = ll[0]
        p = ctx.r.rand(len(h))
        d = ctx.r.rand_delta()
        seq = bytes(ctx.r.rand_elem(funny_unicode()))
        new = _edit_byte(h, p, bytes([h[p]]) + seq) if h else h
        return fn, [new] + ll[1:], [("sed_utf8_insert", d)] + meta, d

    return fn


# --- ascii (src/erlamsa_mutations.erl:585-651) ----------------------------


def _ascii_mutator(ctx: Ctx, mutate_chunks, name: str):
    def fn(ll, meta):
        h = ll[0]
        cs = strlex.lex(h)
        if not textmutas.stringy(cs):
            return fn, ll, meta, -1
        ms = mutate_chunks(ctx, cs)
        d = ctx.r.rand_delta()
        return fn, [strlex.unlex(ms)] + ll[1:], [(name, d)] + meta, d

    return fn


def ascii_bad_mutator(ctx: Ctx):
    return _ascii_mutator(
        ctx,
        lambda c, cs: textmutas.string_generic_mutate(
            c.r, cs,
            ["insert_badness", "replace_badness", "insert_traversal",
             "insert_aaas", "insert_null"],
            c.ssrf_ep,
        ),
        "ascii_bad",
    )


def ascii_delimeter_mutator(ctx: Ctx):
    return _ascii_mutator(
        ctx,
        lambda c, cs: textmutas.string_delimeter_mutate(c.r, cs, c.ssrf_ep),
        "ascii_delimeter",
    )


# --- fuse (src/erlamsa_mutations.erl:384-427) -----------------------------


def sed_fuse_this(ctx: Ctx):
    def fn(ll, meta):
        h = ll[0]
        b = fusemod.fuse(ctx.r, h, h)
        d = ctx.r.rand_delta()
        return fn, [b] + ll[1:], [("fuse_this", d)] + meta, d

    return fn


def sed_fuse_next(ctx: Ctx):
    def fn(ll, meta):
        h = ll[0]
        al1, al2 = halve(h)
        tail = ll[1:]
        if tail:
            b, rest = tail[0], tail[1:]
        else:
            b, rest = h, []
        abl = fusemod.fuse(ctx.r, al1, b)
        abal = fusemod.fuse(ctx.r, abl, al2)
        d = ctx.r.rand_delta()
        return fn, flush_bvecs(abal, rest), [("fuse_next", d)] + meta, d

    return fn


def sed_fuse_old(ctx: Ctx, block: bytes | None = None):
    def fn(ll, meta):
        h = ll[0]
        blk = h if block is None else block
        al1, al2 = halve(h)
        ol1, ol2 = halve(blk)
        a = fusemod.fuse(ctx.r, al1, ol1)
        b = fusemod.fuse(ctx.r, ol2, al2)
        swap = ctx.r.rand(3)
        d = ctx.r.rand_delta()
        new_block = h if swap == 0 else blk
        out = flush_bvecs(a, flush_bvecs(b, ll[1:]))
        return sed_fuse_old(ctx, new_block), out, [("fuse_old", d)] + meta, d

    return fn


# --- tree (src/erlamsa_mutations.erl:786-1023) ----------------------------


def tree_op(ctx: Ctx, op, name: str):
    def fn(ll, meta):
        h = ll[0]
        if binarish(h):
            return fn, ll, meta, -1
        tree = treeops.partial_parse(h)
        new = op(ctx.r, tree)
        flat = treeops.flatten_tree(new, limit=ABSMAX_BINARY_BLOCK)
        if flat is None:  # oversized result: failed try
            return fn, ll, meta, -1
        return fn, [flat] + ll[1:], [(name, 1)] + meta, 1

    return fn


def tree_swap(ctx: Ctx, op, name: str):
    def fn(ll, meta):
        h = ll[0]
        if binarish(h):
            return fn, ll, meta, -1
        tree = treeops.partial_parse(h)
        new = op(ctx.r, tree)
        if new is None:
            return fn, ll, meta, -1
        flat = treeops.flatten_tree(new, limit=ABSMAX_BINARY_BLOCK)
        if flat is None:
            return fn, ll, meta, -1
        return fn, [flat] + ll[1:], [(name, 1)] + meta, 1

    return fn


def tree_stutter(ctx: Ctx):
    def fn(ll, meta):
        h = ll[0]
        if binarish(h):
            return fn, ll, meta, -1
        tree = treeops.partial_parse(h)
        new = treeops.sed_tree_stutter(ctx.r, tree)
        if new is None:
            return fn, ll, meta, -1
        flat = treeops.flatten_tree(new, limit=ABSMAX_BINARY_BLOCK)
        if flat is None:
            return fn, ll, meta, -1
        return fn, [flat] + ll[1:], [("tree_stutter", 1)] + meta, 1

    return fn


# --- length predict (src/erlamsa_mutations.erl:1107-1143) -----------------


def length_predict(ctx: Ctx):
    def fn(ll, meta):
        r = ctx.r
        h = ll[0]
        lens = fieldpred.get_possible_simple_lens(r, h)
        elem = r.rand_elem(lens)
        if not elem:
            return fn, ll, [("muta_len", -2)] + meta, -2
        size, endian, lval, a, _bb = elem
        head, _lv, blob, rest = fieldpred.extract_blob(h, elem)
        tmp = int.from_bytes(r.random_block(size // 8), "big")
        new_len = min(ABSMAX_BINARY_BLOCK, tmp * 2)
        t = r.rand(7)
        if t == 0:  # len = 0
            new = fieldpred.rebuild_blob(endian, head, 0, size, blob, rest)
        elif t == 1:  # len = -1 (all ones)
            new = fieldpred.rebuild_blob(endian, head, (1 << size) - 1, size, blob, rest)
        elif t == 2:  # expand blob with random data
            rnd = r.fast_pseudorandom_block(new_len)
            new = fieldpred.rebuild_blob(endian, head, lval, size, blob, rnd) + rest
        elif t == 3:  # drop blob
            new = fieldpred.rebuild_blob(endian, head, new_len, size, b"", rest)
        else:  # random len field
            new = fieldpred.rebuild_blob(endian, head, new_len, size, blob, rest)
        return fn, [new] + ll[1:], [("muta_len", 1)] + meta, 1

    return fn


# --- base64 (src/erlamsa_mutations.erl:653-690) ---------------------------


def base64_mutator(ctx: Ctx):
    def fn(ll, meta):
        r = ctx.r
        h = ll[0]
        cs = strlex.lex(h)
        mutas = build_mutators(ctx)
        new_cs = []
        total_d = -1
        new_meta = list(meta)
        for chunk in cs:
            if chunk[0] == "text" and len(chunk[1]) > 6:
                try:
                    raw = bytes(chunk[1]) if not isinstance(chunk[1], bytes) else chunk[1]
                    decoded = b64mod.b64decode(raw, validate=True)
                    d = r.rand_delta()
                    muta = mutators_mutator(ctx, [row[:] for row in mutas])
                    _m, new_ll, mm = apply_mux(ctx, muta, [decoded], [])
                    new_bin = b"".join(x for x in new_ll if isinstance(x, bytes))
                    enc = b64mod.b64encode(new_bin)
                    new_cs.append(("text", list(enc)))
                    total_d += d
                    new_meta = [mm, ("base64_mutator", d)] + new_meta
                    continue
                except Exception:  # lint: broad-except-ok not base64: keep chunk unchanged
                    pass
            new_cs.append(chunk)
        return fn, [strlex.unlex(new_cs)] + ll[1:], new_meta, total_d

    return fn


# --- URI (src/erlamsa_mutations.erl:693-784) ------------------------------


def _change_scheme(acc_rev: list[int]) -> list[int]:
    """Trailing 'file' becomes 'http' IN PLACE: the reference reverses
    [$p,$t,$t,$h | T], i.e. prefix-text ++ "http"
    (src/erlamsa_mutations.erl:734-736)."""
    if acc_rev[:4] == [ord("e"), ord("l"), ord("i"), ord("f")]:
        return acc_rev[4:][::-1] + [ord("h"), ord("t"), ord("t"), ord("p")]
    return acc_rev[::-1]


def uri_mutator(ctx: Ctx):
    def fn(ll, meta):
        r = ctx.r
        h = ll[0]
        cs = strlex.lex(h)
        new_cs = []
        total_d = -1
        new_meta = list(meta)
        for chunk in cs:
            if chunk[0] == "text" and len(chunk[1]) > 5:
                s = "".join(chr(c) for c in chunk[1])
                idx = s.find("://")
                if idx >= 0:
                    acc_rev = [ord(c) for c in s[:idx]][::-1]
                    tail = s[idx + 3 :]
                    mutated = _rand_uri_mutate(ctx, tail, acc_rev, r.erand(3))
                    new_cs.append(("text", [ord(c) & 0xFF for c in mutated]))
                    total_d += 1
                    new_meta = [("uri", "success")] + new_meta
                    continue
            new_cs.append(chunk)
        # the reference returns fun base64_mutator/2 as the continuation
        # (erlamsa_mutations.erl:784) — after its first run the mux row
        # labelled 'uri' executes the base64 mutator; quirk preserved
        return base64_mutator(ctx), [strlex.unlex(new_cs)] + ll[1:], new_meta, total_d

    return fn


def _rand_uri_mutate(ctx: Ctx, tail: str, acc_rev: list[int], t: int) -> str:
    """(src/erlamsa_mutations.erl:738-758)."""
    r = ctx.r
    host, port = ctx.ssrf_ep
    scheme = "".join(chr(c) for c in _change_scheme(acc_rev))
    if t == 1:
        return scheme + ctx.ssrf_uri() + tail
    parts = [p for p in tail.split("/") if p != ""]
    domain = parts[0] if parts else ""
    query = parts[1:]
    if t == 2:
        at = r.rand_elem([" @{}:{}", "@{}:{}"]).format(host, port)
        return f"{scheme}://{domain}{at}/" + "/".join(query)
    traversals = "/" + "".join("../" for _ in range(r.erand(10)))
    which = r.erand(4)
    target = ["/".join(query), "Windows/win.ini", "etc/shadow", "etc/passwd"][which - 1]
    return "".join(chr(c) for c in acc_rev[::-1]) + "://" + domain + traversals + target


# --- zip (src/erlamsa_mutations.erl:1146-1163) ----------------------------


def zip_path_traversal(ctx: Ctx):
    def fn(ll, meta):
        h = ll[0]
        new = zipops.path_traversal(ctx.r, h)
        if new is None:
            return fn, ll, [("muta_zippath", -1)] + meta, -1
        return fn, [new] + ll[1:], [("muta_zippath", 1)] + meta, 1

    return fn


# --- JSON / SGML ----------------------------------------------------------


def _inner_bytes_mutator(ctx: Ctx, kind: str):
    """Mutate a leaf's raw bytes with the inner mutator subset
    (inner_mutations, src/erlamsa_mutations.erl:1341-1356)."""

    def run(raw: bytes) -> bytes:
        rows = [
            row for row in build_mutators(ctx)
            if row[3] in _INNER_SETS.get(kind, _INNER_SETS["default"])
        ]
        muta = mutators_mutator(ctx, rows)
        _m, new_ll, _meta = apply_mux(ctx, muta, [bytes(raw)], [])
        return b"".join(x for x in new_ll if isinstance(x, bytes))

    return run


_INNER_SETS = {
    # the reference's sgml list names the atom `json`, which matches no
    # registry entry (registry name is `js`) — so the JSON mutator is
    # effectively absent from the sgml inner set; quirk preserved
    # (erlamsa_mutations.erl:1342)
    "sgml": {"ab", "ad", "bd", "b64", "ld", "lp", "lri", "lr", "num", "sd", "uri"},
    "json": {"ab", "ad", "b64", "num", "sd", "sp", "sr", "uri", "sgm"},
    "default": {"ab", "ad", "ber", "b64", "ld", "lp", "lri", "lr", "num", "sd",
                "srnd", "uri", "zip"},
}


def json_mutator(ctx: Ctx):
    def fn(ll, meta):
        new, op, d = jsonfmt.json_mutate(
            ctx.r, ll[0], _inner_bytes_mutator(ctx, "json")
        )
        return fn, [new] + ll[1:], [(op, d)] + meta, d

    return fn


def sgml_mutator(ctx: Ctx):
    def fn(ll, meta):
        new, op, d = sgmlfmt.sgml_mutate(
            ctx.r, ll[0], _inner_bytes_mutator(ctx, "sgml"),
            ctx.ssrf_uri().encode(),
        )
        return fn, [new] + ll[1:], [(op, d)] + meta, d

    return fn


def nomutation():
    def fn(ll, meta):
        return fn, ll, [("nomutation", -1)] + meta, -1

    return fn


# --- mux (src/erlamsa_mutations.erl:1238-1280, 1370-1395) -----------------


def adjust_priority(pri: float, delta: int) -> float:
    if delta == 0:
        return pri
    return max(MIN_SCORE, min(MAX_SCORE, pri + delta))


def weighted_permutations(r: ErlRand, rows: list[list]) -> list[list]:
    """rand(score*pri) keys, sorted descending (stable)
    (src/erlamsa_mutations.erl:1244-1250)."""
    keyed = [(r.rand(int(row[0] * row[1])), row) for row in rows]
    keyed.sort(key=lambda kv: -kv[0])
    return [row for _k, row in keyed]


def mutators_mutator(ctx: Ctx, rows: list[list]) -> list[list]:
    """Randomize initial scores max(2, rand(10)); the reference folds the
    input list prepending, so scores are drawn in reversed row order
    (src/erlamsa_mutations.erl:1385-1395 over the make_mutator fold output)."""
    out = []
    for row in rows:
        n = ctx.r.rand(int(MAX_SCORE))
        out.insert(0, [max(2, n), row[1], row[2], row[3]])
    return out


def apply_mux(ctx: Ctx, rows: list[list], ll: list, meta: list):
    """One mux_fuzzers event: returns (rows', ll', meta')
    (src/erlamsa_mutations.erl:1256-1280)."""
    if ll == [b""] or not ll:
        return rows, ll, meta
    perm = weighted_permutations(ctx.r, rows)
    out: list[list] = []
    idx = 0
    while idx < len(perm):
        row = perm[idx]
        h = ll[0] if ll else b""
        if isinstance(h, bytes) and len(h) > ABSMAX_BINARY_BLOCK:
            return out + perm[idx + 1 :], ll, [("skipped_big", len(h))] + meta
        score, pri, fn, name = row
        nfn, nll, nmeta, delta = fn(ll, meta)
        nrow = [adjust_priority(score, delta), pri, nfn, name]
        out = [nrow] + out
        changed = not (isinstance(nll, list) and nll and ll and nll[0] == ll[0])
        if changed:
            return out + perm[idx + 1 :], nll, [("used", name)] + nmeta
        meta = [("failed", name)] + nmeta
        idx += 1
    return out, ll, meta


def make_mutator(ctx: Ctx, selected: list[tuple[str, int]], custom=()) -> list[list]:
    """CLI entry: filter the registry by selected (name, pri) pairs and
    randomize scores (make_mutator, src/erlamsa_mutations.erl:1370-1383)."""
    sel = dict(selected)
    rows = []
    for row in build_mutators(ctx, custom):
        if row[3] in sel:
            rows.insert(0, [row[0], sel[row[3]], row[2], row[3]])
    return mutators_mutator(ctx, rows)


def default_mutations() -> list[tuple[str, int]]:
    """(name, pri) defaults (src/erlamsa_mutations.erl:1358-1359)."""
    return [
        ("sgm", 10), ("js", 3), ("uw", 1), ("ui", 2), ("ab", 1), ("ad", 1),
        ("tr2", 1), ("td", 1), ("num", 3), ("ts1", 2), ("tr", 2), ("ts2", 2),
        ("bd", 1), ("bei", 1), ("bed", 1), ("bf", 1), ("bi", 1), ("ber", 1),
        ("br", 1), ("sp", 1), ("sr", 1), ("sd", 1), ("snand", 1), ("srnd", 1),
        ("ld", 1), ("lds", 1), ("lr2", 1), ("lri", 1), ("lr", 1), ("ls", 1),
        ("lp", 1), ("lis", 1), ("lrs", 1), ("ft", 2), ("fn", 1), ("fo", 2),
        ("len", 2), ("b64", 7), ("uri", 1), ("zip", 1), ("nil", 0),
    ]
