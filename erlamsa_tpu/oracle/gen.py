"""Oracle input generators: priority-muxed block-stream sources.

Reference: src/erlamsa_gen.erl. A generator call returns (blocks, meta)
where blocks is a list of byte blocks with generator-chosen random sizes
(256*bs .. 4096*bs) and an occasional random padding tail.
"""

from __future__ import annotations

import os
import sys

from ..constants import MAX_BLOCK_SIZE, MIN_BLOCK_SIZE
from ..utils.erlrand import ErlRand
from .mutations import Ctx


def _finish(r: ErlRand, total_len: int) -> list[bytes]:
    """1/(len+1) chance of a random padding tail (erlamsa_gen.erl:42-51)."""
    n = r.rand(total_len + 1)
    if n == total_len:
        bits = r.rand_range(1, 16)
        nlen = r.rand(1 << bits)
        block = bytes(r.random_numbers(256, nlen))
        return [] if block == b"" else [block]
    return []


def rand_block_size(r: ErlRand, block_scale: float) -> int:
    """(erlamsa_gen.erl:54-56)."""
    return max(r.rand(round(MAX_BLOCK_SIZE * block_scale)),
               round(MIN_BLOCK_SIZE * block_scale))


def _lazy_stream(ctx: Ctx, data: bytes, block_scale: float):
    """One-shot lazy stream mirroring port_stream (erlamsa_gen.erl:59-88):
    the returned thunk, when FORCED (by the pattern's uncons, i.e. after
    the pattern-choice and Ip draws), materializes the whole block list —
    the reference's stream_port recursion is eager after the first force.
    The next block size is drawn before each read, so exact-boundary data
    consumes one trailing size draw before EOF; draws land on whatever
    stream ctx.r is bound to at forcing time (the per-case worker stream)."""

    def force() -> list[bytes]:
        r = ctx.r
        blocks: list[bytes] = []
        i = 0
        while True:
            want = rand_block_size(r, block_scale)
            chunk = data[i : i + want]
            i += len(chunk)
            if len(chunk) == want:
                blocks.append(chunk)
                continue
            if chunk:
                blocks.append(chunk)
            return blocks + _finish(r, len(data))

    return force


def _force_all(ll) -> list[bytes]:
    """forcell (erlamsa_utils.erl:108-111): materialize a lazy chain."""
    out = []
    while callable(ll):
        ll = ll()
    for x in ll:
        while callable(x):
            x = x()
        if isinstance(x, (bytes, bytearray)):
            out.append(bytes(x))
        else:
            out.extend(_force_all(x))
    return out


def stdin_generator(ctx: Ctx, online: bool, block_scale: float):
    """stdin source (erlamsa_gen.erl:91-102): single-case runs keep the
    stream lazy (block draws land on the worker stream); multi-case runs
    force it ONCE at construction on the parent stream and reuse it."""
    data = sys.stdin.buffer.read()
    if online:
        def gen():
            return _lazy_stream(ctx, data, block_scale), ("generator", "stdin")
        return gen
    blocks = _force_all(_lazy_stream(ctx, data, block_scale))

    def gen():
        return list(blocks), ("generator", "stdin")

    return gen


def file_generator(ctx: Ctx, paths: list[str], block_scale: float):
    """Pick a random path per case; blocks stay lazy
    (erlamsa_gen.erl:105-121)."""

    def gen():
        p = ctx.r.erand(len(paths))
        with open(paths[p - 1], "rb") as f:
            data = f.read()
        return _lazy_stream(ctx, data, block_scale), [
            ("generator", "file"), ("source", "path")
        ]

    return gen


def jump_generator(ctx: Ctx, paths: list[str], block_scale: float):
    """Splice random spans of two random files; the splice itself is a
    thunk forced under the pattern walk (erlamsa_gen.erl:123-150)."""

    def gen():
        r = ctx.r
        p1 = r.rand_elem(paths)
        p2 = r.rand_elem(paths)
        with open(p1, "rb") as f:
            d1r = f.read()
        with open(p2, "rb") as f:
            d2r = f.read()
        ll1 = _lazy_stream(ctx, d1r, block_scale)
        ll2 = _lazy_stream(ctx, d2r, block_scale)

        def thunk():
            # interleaved like jump_somewhere (erlamsa_gen.erl:123-132):
            # force stream 1, pick from it, THEN force stream 2
            b1 = _force_all(ll1)
            data1 = r.rand_elem(b1) if b1 else b""
            b2 = _force_all(ll2)
            data2 = r.rand_elem(b2) if b2 else b""
            s1 = r.rand(len(data1))
            s2 = r.rand(len(data2))
            l1 = r.erand(len(data1) - s1)
            l2 = r.erand(len(data2) - s2)
            return [data1[s1 : s1 + l1] + data2[s2 : s2 + l2]]

        return thunk, [("generator", "jump"), ("source", "path")]

    return gen


def direct_generator(ctx: Ctx, data: bytes, block_scale: float):
    """Library-call input. The reference's split_binary guard compares
    byte_size(Bin) against byte_size(Wanted-integer), which always fails, so
    direct input is never block-split — kept for parity
    (erlamsa_gen.erl:152-164)."""

    def gen():
        _ = rand_block_size(ctx.r, block_scale)  # drawn then unused, as in ref
        return [data] + _finish(ctx.r, len(data)), ("generator", "direct")

    return gen


def random_generator(ctx: Ctx, block_scale: float):
    """Endless-ish random blocks (erlamsa_gen.erl:167-183)."""

    def gen():
        r = ctx.r
        blocks = []
        while True:
            n = r.rand_range(32, round(MAX_BLOCK_SIZE * block_scale))
            blocks.append(r.random_block(n))
            ip = r.rand_range(1, 100)
            if r.rand(ip) == 0:
                return blocks, ("generator", "random")

    return gen


GENERATOR_INFO = [
    ("random", 1, "generate random data"),
    ("jump", 100, "jump between multiple files"),
    ("direct", 500, "read data directly from function call arguments"),
    ("file", 1000, "read data from given files"),
    ("genfuz", 10000, "generational-based fuzzer using supplied grammar"),
    ("stdin", 100000, "read data from standard input"),
]


def default_generators() -> list[tuple[str, int]]:
    return [(name, pri) for name, pri, _d in GENERATOR_INFO]


def make_generator(ctx: Ctx, pris: list[tuple[str, int]], paths, opts, n_cases: int):
    """Filter applicable sources, then one priority draw selects the
    generator for the whole run (erlamsa_gen.erl:193-247)."""
    inp = opts.get("input")
    block_scale = opts.get("blockscale", 1.0)
    external = opts.get("external_generator")
    candidates = []
    for name, pri in pris:
        if name == "stdin" and paths and paths[0] == "-" and external is None:
            candidates.append(
                (pri, name, stdin_generator(ctx, n_cases == 1, block_scale))
            )
        elif name == "file" and paths and paths != ["-"] and paths != ["direct"]:
            fpaths = _expand_paths(paths) if opts.get("recursive") else list(paths)
            candidates.append((pri, name, file_generator(ctx, fpaths, block_scale)))
        elif name == "jump" and len(paths) > 1:
            fpaths = _expand_paths(paths) if opts.get("recursive") else list(paths)
            candidates.append((pri, name, jump_generator(ctx, fpaths, block_scale)))
        elif name == "direct" and inp is not None:
            candidates.append((pri, name, direct_generator(ctx, inp, block_scale)))
        elif name == "random":
            candidates.append((pri, name, random_generator(ctx, block_scale)))
        elif name == "genfuz" and external is not None:
            candidates.append((pri, name, external))
        elif name == "genfuz" and opts.get("gen_grammar") is not None:
            # --gen without an external module: the parsed grammar fills
            # the reference's genfuz slot through the sequential ErlRand
            # path (models/genfuzz.make_external_generator); the batched
            # counter-keyed path lives in gen/ + ops/grammar.py
            from ..models.genfuzz import make_external_generator

            candidates.append((pri, name, make_external_generator(
                opts["gen_grammar"], seed=opts.get("seed"))))
    if not candidates:
        raise ValueError("No generators!")
    if len(candidates) == 1:
        return candidates[0][1], candidates[0][2]
    srt = sorted(candidates, key=lambda c: -c[0])
    total = sum(c[0] for c in srt)
    n = ctx.r.rand(total)
    for pri, name, gen in srt:
        if n < pri or (pri == 0 and n == 0):
            return name, gen
        n -= pri
    return srt[-1][1], srt[-1][2]


def _expand_paths(paths: list[str]) -> list[str]:
    """Recursive directory walk (erlamsa_utils:build_recursive_paths)."""
    out = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, files in os.walk(p):
                out.extend(os.path.join(root, f) for f in files)
        else:
            out.append(p)
    return out
