"""ASCII/text badness mutations used by the ab / ad mutators.

Reference: src/erlamsa_mutations.erl:430-651. Operates on strlex chunk
lists; text payloads draw from the silly-strings / delimiter / shell-inject
tables with the reference's draw order.
"""

from __future__ import annotations

from ..utils.erlrand import ErlRand
from ..utils.tables import DELIMETERS, REV_CONNECTS, SHELL_INJECTS, SILLY_STRINGS


def stringy(chunks: list[tuple]) -> bool:
    """Any non-byte chunk present (erlamsa_mutations.erl:440-443)."""
    return any(c[0] != "byte" for c in chunks)


def random_badness(r: ErlRand) -> list[int]:
    """rand(20)+1 silly strings, accumulated by prepending
    (erlamsa_mutations.erl:469-477)."""
    n = r.rand(20) + 1
    out: list[int] = []
    for _ in range(n):
        x = r.rand_elem(SILLY_STRINGS)
        out = [ord(c) for c in x] + out
    return out


def rand_as_count(r: ErlRand) -> int:
    """Interesting 'aaaa...' lengths (erlamsa_mutations.erl:486-501)."""
    t = r.rand(11)
    table = (127, 128, 255, 256, 16383, 16384, 32767, 32768, 65535, 65536)
    if t < 10:
        return table[t]
    return r.rand(1024)


def insert_traversal(r: ErlRand, symb: str) -> list[int]:
    """'/../../..' runs (erlamsa_mutations.erl:509-511)."""
    n = r.erand(10)
    s = symb + "".join(".." + symb for _ in range(n))
    return [ord(c) for c in s]


def build_revconnect(r: ErlRand, ssrf_ep) -> list[int]:
    """Shell-inject wrapping a reverse-connect payload
    (erlamsa_mutations.erl:517-522)."""
    inj = r.rand_elem(SHELL_INJECTS)
    rev = r.rand_elem(REV_CONNECTS)
    host, port = ssrf_ep
    payload = inj.format(rev.format(host=host, port=port))
    return [ord(c) & 0xFF for c in payload]


def overwrite(new: list, old: list) -> list:
    """Overlay new onto old, keeping old's tail (erlamsa_mutations.erl:479-484)."""
    return new + old[len(new) :]


def mutate_text(r: ErlRand, which: str, lst: list[int], ssrf_ep) -> list[int]:
    """One text mutation (erlamsa_mutations.erl:524-563)."""
    if which == "insert_badness":
        if not lst:
            return random_badness(r)
        p = r.erand(len(lst))
        bad = random_badness(r)
        return lst[: p - 1] + bad + lst[p - 1 :]
    if which == "replace_badness":
        if not lst:
            return random_badness(r)
        p = r.erand(len(lst))
        bad = random_badness(r)
        # the reference calls overwrite(Tail, Bad): the TAIL overlays onto
        # the badness, keeping bad's tail beyond len(tail)
        # (erlamsa_mutations.erl:533-536)
        return lst[: p - 1] + overwrite(lst[p:], bad)
    if which == "insert_aaas":
        n = rand_as_count(r)
        if not lst:
            return [97] * n
        p = r.erand(len(lst))
        return lst[: p - 1] + [97] * n + lst[p - 1 :]
    if which == "insert_traversal":
        if not lst:
            return insert_traversal(r, "/")
        p = r.erand(len(lst))
        symb = r.rand_elem(["\\", "/"])
        return lst[: p - 1] + insert_traversal(r, symb) + lst[p - 1 :]
    if which == "insert_null":
        return lst + [0]
    if which == "insert_delimeter":
        if not lst:
            return [ord(c) for c in r.rand_elem(DELIMETERS)]
        p = r.erand(len(lst))
        bad = [ord(c) for c in r.rand_elem(DELIMETERS)]
        return lst[: p - 1] + bad + lst[p - 1 :]
    if which == "insert_shellinj":
        if not lst:
            return [ord(c) for c in r.rand_elem(DELIMETERS)]
        p = r.erand(len(lst))
        inj = build_revconnect(r, ssrf_ep)
        return lst[: p - 1] + inj + lst[p - 1 :]
    return lst


def mutate_text_data(r: ErlRand, lst, txt_mutators: list[str], ssrf_ep) -> list[int]:
    """rand_elem over the mutator-name list then apply
    (erlamsa_mutations.erl:513-515)."""
    which = r.rand_elem(txt_mutators)
    return mutate_text(r, which, list(lst), ssrf_ep)


def string_generic_mutate(r: ErlRand, chunks, txt_mutators, ssrf_ep) -> list:
    """Pick chunks until a mutable one is hit, <= len/4 byte-chunk retries
    (erlamsa_mutations.erl:570-583)."""
    cs = list(chunks)
    ln = len(cs)
    retries = 0
    while retries <= ln / 4:
        p = r.erand(ln)
        el = cs[p - 1]
        if el[0] == "text":
            data = mutate_text_data(r, el[1], txt_mutators, ssrf_ep)
            return cs[: p - 1] + [("text", data)] + cs[p:]
        if el[0] == "byte":
            retries += 1
            continue
        # delimited
        data = mutate_text_data(r, el[2], txt_mutators, ssrf_ep)
        return cs[: p - 1] + [("delimited", el[1], data, el[3])] + cs[p:]
    return cs


def drop_delimeter(n: int, el: tuple) -> tuple:
    """Drop right/left/both/none delimiters (erlamsa_mutations.erl:613-622)."""
    if el[0] != "delimited":
        return el
    _, left, body, right = el
    if n == 0:
        return ("text", [left] + list(body))
    if n == 1:
        return ("text", list(body) + [right])
    if n == 2:
        return ("text", list(body))
    return el


def string_delimeter_mutate(r: ErlRand, chunks, ssrf_ep) -> list:
    """Delimiter-focused chunk mutation (erlamsa_mutations.erl:625-644)."""
    cs = list(chunks)
    ln = len(cs)
    retries = 0
    while retries <= ln / 4:
        p = r.erand(ln)
        el = cs[p - 1]
        if el[0] == "text":
            which = r.rand_elem(
                ["insert_delimeter", "insert_delimeter", "insert_delimeter",
                 "insert_shellinj"]
            )
            data = mutate_text_data(r, el[1], [which], ssrf_ep)
            return cs[: p - 1] + [("text", data)] + cs[p:]
        if el[0] == "byte":
            retries += 1
            continue
        drop = drop_delimeter(r.rand(4), el)
        return cs[: p - 1] + [drop] + cs[p:]
    return cs
