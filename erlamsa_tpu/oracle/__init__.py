"""Sequential parity engine ("oracle").

A pure-Python re-implementation of the reference's exact mutation pipeline,
driven by the AS183 PRNG (erlamsa_tpu.utils.erlrand) in the reference's
draw order, so a fixed seed reproduces the reference's decision stream.
This is the `-m default`-equivalent path and the parity baseline the TPU
throughput path is measured against; it also hosts the structured mutators
(tree/JSON/SGML/fuse/zip) that the batch path routes to the host.

Public surface:
    fuzz(data, seed=..., **opts) -> bytes       one-shot library call
    Engine(opts).run_case(idx) -> bytes         the CLI's per-case driver
"""

from .engine import fuzz  # noqa: F401
