"""The oracle fuzzing engine: seed -> generator -> pattern -> mutators ->
output, case by case.

Sequential re-implementation of erlamsa_main:fuzzer (src/erlamsa_main.erl:
124-247) with the reference's seeding discipline: the parent stream draws a
3-tuple ThreadSeed per case (gen_predictable_seed) and each case runs on a
fresh AS183 stream seeded with it; resume therefore needs only
(seed, case index) — the reference's last_seed.txt + --skip contract.
"""

from __future__ import annotations

import sys
import time
from typing import Callable


def _ensure_deep_stack():
    """Deep parse trees (pump mutations nest nodes across nd/bu rounds)
    exceed CPython's default 1000-frame limit in the recursive serializers;
    the reference runs on BEAM with no such ceiling. Applied at Engine
    construction (not import) so merely importing the package doesn't
    mutate global interpreter state. CPython 3.12's C-stack guard turns
    overshoot into a catchable RecursionError rather than a crash, and the
    pump size caps (models/jsonfmt.py, models/sgmlfmt.py) bound realistic
    depth well below this."""
    if sys.getrecursionlimit() < 20000:
        sys.setrecursionlimit(20000)

from ..constants import TOO_MANY_FAILED_ATTEMPTS
from ..obs import trace
from ..utils.erlrand import ErlRand, gen_urandom_seed
from . import gen as genmod
from . import patterns as patmod
from .mutations import Ctx, default_mutations, make_mutator


class Engine:
    def __init__(self, opts: dict):
        _ensure_deep_stack()
        self.opts = dict(opts)
        self.seed = opts.get("seed") or gen_urandom_seed()
        self.n_cases = opts.get("n", 1)
        self.parent = ErlRand(self.seed)
        self.ctx = Ctx(
            self.parent,
            ssrf_host=opts.get("ssrf_host", "localhost"),
            ssrf_port=opts.get("ssrf_port", 51234),
        )
        # construction order matches fuzzer/1: mutator table first (its
        # construction draws), then the generator choice draw
        selected = opts.get("mutations") or default_mutations()
        custom = list(opts.get("custom_mutas", ()))
        ext = opts.get("external_module")
        if ext is not None:
            custom += ext.custom_mutations(self.ctx)
            selected = list(selected) + [(row[3], row[1]) for row in custom]
        self.base_rows = make_mutator(self.ctx, selected, custom)
        paths = opts.get("paths", ["-"])
        self.gen_name, self.generator = genmod.make_generator(
            self.ctx,
            opts.get("generators") or genmod.default_generators(),
            paths,
            self.opts,
            self.n_cases,
        )
        self.pattern = patmod.make_pattern(
            opts.get("patterns") or patmod.default_patterns()
        )
        self.sequence_muta = opts.get("sequence_muta", False)
        self.skip = opts.get("skip", 0)
        self.sleep = opts.get("sleep", 0)
        self.maxfails = opts.get("maxfails", TOO_MANY_FAILED_ATTEMPTS)
        # per-case wall-clock budget in seconds (reference MaxRunningTime,
        # src/erlamsa_main.erl:211-220); 0/None = unlimited
        self.max_running_time = opts.get("maxrunningtime") or 0
        self.post = opts.get("post") or (lambda d: d)
        self._rows = self.base_rows
        self._case_gen = 0

    def run_case(self, case_idx: int) -> tuple[bytes, list]:
        """One fuzzing case: returns (mutated bytes, meta). The worker
        stream is seeded from the parent stream (erlamsa_main.erl:179-184)."""
        thread_seed = (
            self.parent.erand(99999),
            self.parent.erand(99999),
            self.parent.erand(99999),
        )
        worker = ErlRand(thread_seed)
        saved = self.ctx.r
        self._case_gen += 1
        gen = self._case_gen
        self.ctx.r = worker
        try:
            blocks, gen_meta = self.generator()
            rows = self._rows
            ll = blocks if callable(blocks) else list(blocks)
            out_blocks, new_rows, meta = self.pattern(
                self.ctx, ll, rows, [("nth", case_idx)]
            )
            if self.sequence_muta and self._case_gen == gen:
                # a case the watchdog abandoned must not clobber the live
                # case's sequence state when its thread wakes up late
                self._rows = new_rows
            data = self.post(b"".join(out_blocks))
            return data, meta
        finally:
            # ctx.r is thread-local (see Ctx), so an abandoned case thread
            # only ever touches its own slot here
            self.ctx.r = saved

    def run(self, writer: Callable[[int, bytes, list], None] | None = None) -> list[bytes]:
        """The fuzzing loop (erlamsa_main.erl:165-243). Returns collected
        outputs when no writer is given (return/direct mode)."""
        from ..utils.watchdog import CaseTimeout, run_with_timeout

        acc: list[bytes] = []
        fails = 0
        i = 1
        while i <= self.n_cases:
            if fails > self.maxfails:
                break
            try:
                with trace.span("oracle.case", case=i):
                    data, meta = run_with_timeout(
                        self.run_case, self.max_running_time, i
                    )
            except CaseTimeout:
                # reference kills the case worker and moves on
                # (src/erlamsa_main.erl:211-220)
                i += 1
                continue
            if i > self.skip:
                if writer is not None:
                    try:
                        run_with_timeout(
                            writer, self.max_running_time, i, data, meta
                        )
                        fails = 0
                    except (ConnectionError, CaseTimeout):
                        # a hung writer is an output failure: back off and
                        # let maxfails break the loop
                        # (src/erlamsa_main.erl:170-175,203-207)
                        fails += 1
                        time.sleep((10 * fails) / 1000.0)
                        i += 1
                        continue
                else:
                    if data != b"":
                        acc.append(data)
            if self.sleep:
                time.sleep(self.sleep / 1000.0)
            i += 1
        return acc


def fuzz(data: bytes, seed=None, **opts) -> bytes:
    """Direct library call, like erlamsa_app:fuzz/2
    (src/erlamsa_app.erl:255-263): paths=[direct], output=return."""
    o = {"paths": ["direct"], "input": data, "n": 1}
    if seed is not None:
        o["seed"] = seed
    o.update(opts)
    eng = Engine(o)
    results = eng.run()
    return results[0] if results else b""
