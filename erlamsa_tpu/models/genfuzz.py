"""Generation-based fuzzing combinators (genfuzz).

Reference: src/erlamsa_gf.erl — a small grammar DSL (static / range /
rbyte..rddword / rbinary / pick / pick_pref / loop / sizer / block /
session_get) whose tree is flattened once to estimate depth and then
generated with a fuzzing probability scaled by that depth
(erlamsa_gf:fuzz/3, :173-181).

A grammar is a list of nodes; each node is a tuple ("kind", ...):

    ("static", bytes)            literal bytes
    ("range", lo, hi)            one byte in [lo, hi]
    ("rbyte",) ("rword",) ("rdword",) ("rddword",)   random 1/2/4/8 bytes
    ("rbinary", n)               n random bytes
    ("pick", [grammar, ...])     uniform choice of a sub-grammar
    ("pick_pref", [(w, grammar), ...])   weighted choice
    ("loop", grammar, max_n)     1..max_n repetitions
    ("sizer", fmt, grammar)      length field over the generated block;
                                 fmt in {u8, u16be, u16le, u32be, u32le}
    ("block", [grammar...])      grouping (sizer target)
    ("session_get", key, default)   replay session state (gfcomms)

generate() is the pure expansion; fuzz_grammar() expands while mutating
leaves with probability ~ 1/depth, like the reference's scaled fuzzing.
"""

from __future__ import annotations

import struct

from ..utils.erlrand import ErlRand

_SIZER_FMT = {
    "u8": ("B", 1, "big"),
    "u16be": (">H", 2, "big"),
    "u16le": ("<H", 2, "little"),
    "u32be": (">I", 4, "big"),
    "u32le": ("<I", 4, "little"),
}


def _flatten_depth(node, depth=1) -> int:
    """Estimate grammar depth (the reference flattens twice,
    erlamsa_gf:173-181)."""
    if isinstance(node, list):
        return max((_flatten_depth(x, depth + 1) for x in node), default=depth)
    if not isinstance(node, tuple):
        return depth
    kind = node[0]
    if kind in ("pick",):
        return max(
            (_flatten_depth(g, depth + 1) for g in node[1]), default=depth
        )
    if kind == "pick_pref":
        return max(
            (_flatten_depth(g, depth + 1) for _w, g in node[1]), default=depth
        )
    if kind in ("loop", "sizer"):
        return _flatten_depth(node[-1] if kind == "loop" else node[2], depth + 1)
    if kind == "block":
        return max(
            (_flatten_depth(g, depth + 1) for g in node[1]), default=depth
        )
    return depth


def generate(r: ErlRand, grammar, session: dict | None = None,
             fuzz_prob: float = 0.0) -> bytes:
    """Expand a grammar to bytes; leaves mutate with fuzz_prob."""
    session = session if session is not None else {}

    def emit(node) -> bytes:
        if isinstance(node, list):
            return b"".join(emit(x) for x in node)
        if isinstance(node, (bytes, bytearray)):
            node = ("static", bytes(node))
        kind = node[0]
        if kind == "static":
            out = node[1]
            if fuzz_prob and r.rand_float() < fuzz_prob and out:
                # flip one byte of the literal
                p = r.rand(len(out))
                out = out[:p] + bytes([r.rand(256)]) + out[p + 1 :]
            return out
        if kind == "range":
            lo, hi = node[1], node[2]
            if fuzz_prob and r.rand_float() < fuzz_prob:
                return bytes([r.rand(256)])  # out-of-range byte
            return bytes([r.rand_span(lo, hi)])
        if kind == "rbyte":
            return r.rbyte()
        if kind == "rword":
            return r.rword()
        if kind == "rdword":
            return r.rdword()
        if kind == "rddword":
            return r.rddword()
        if kind == "rbinary":
            return r.random_block(node[1])
        if kind == "pick":
            return emit(r.rand_elem(node[1]))
        if kind == "pick_pref":
            total = sum(w for w, _g in node[1])
            n = r.rand(total)
            for w, g in node[1]:
                if n < w:
                    return emit(g)
                n -= w
            return emit(node[1][-1][1])
        if kind == "loop":
            times = r.erand(node[2])
            if fuzz_prob and r.rand_float() < fuzz_prob:
                times = times * (1 + r.rand_log(6))  # loop blowup
            return b"".join(emit(node[1]) for _ in range(times))
        if kind == "sizer":
            fmt, _width, _endian = _SIZER_FMT[node[1]]
            body = emit(node[2])
            size = len(body)
            if fuzz_prob and r.rand_float() < fuzz_prob:
                size = r.rand(1 << (8 * _width))  # lie about the length
            mask = (1 << (8 * _width)) - 1
            return struct.pack(fmt, size & mask) + body
        if kind == "block":
            return b"".join(emit(g) for g in node[1])
        if kind == "session_get":
            return bytes(session.get(node[1], node[2]))
        raise ValueError(f"unknown grammar node {node!r}")

    return emit(grammar)


def fuzz_grammar(r: ErlRand, grammar, session: dict | None = None) -> bytes:
    """Generate with depth-scaled fuzzing probability
    (erlamsa_gf:fuzz/3)."""
    depth = _flatten_depth(grammar)
    prob = 1.0 / max(depth * 2, 2)
    return generate(r, grammar, session, fuzz_prob=prob)


def make_external_generator(grammar, seed=None):
    """Adapter: a grammar becomes a generator for the engine's genfuz slot
    (the reference's external module `generator` capability)."""
    from ..utils.erlrand import gen_urandom_seed

    r = ErlRand(seed or gen_urandom_seed())

    def gen():
        return [fuzz_grammar(r, grammar)], ("generator", "genfuz")

    return gen
