"""Generation-based fuzzing combinators (genfuzz).

Reference: src/erlamsa_gf.erl — a small grammar DSL (static / range /
rbyte..rddword / rbinary / pick / pick_pref / loop / sizer / block /
session_get) whose tree is flattened once to estimate depth and then
generated with a fuzzing probability scaled by that depth
(erlamsa_gf:fuzz/3, :173-181).

A grammar is a list of nodes; each node is a tuple ("kind", ...):

    ("static", bytes)            literal bytes
    ("range", lo, hi)            one byte in [lo, hi]
    ("rbyte",) ("rword",) ("rdword",) ("rddword",)   random 1/2/4/8 bytes
    ("rbinary", n)               n random bytes
    ("pick", [grammar, ...])     uniform choice of a sub-grammar
    ("pick_pref", [(w, grammar), ...])   weighted choice
    ("loop", grammar, max_n)     1..max_n repetitions
    ("sizer", fmt, grammar)      length field over the generated block;
                                 fmt in {u8, u16be, u16le, u32be, u32le}
    ("block", [grammar...])      grouping (sizer target)
    ("session_get", key, default)   replay session state (gfcomms)

generate() is the pure expansion; fuzz_grammar() expands while mutating
leaves with probability ~ 1/depth, like the reference's scaled fuzzing.
"""

from __future__ import annotations

import struct

from ..utils.erlrand import ErlRand

_SIZER_FMT = {
    "u8": ("B", 1, "big"),
    "u16be": (">H", 2, "big"),
    "u16le": ("<H", 2, "little"),
    "u32be": (">I", 4, "big"),
    "u32le": ("<I", 4, "little"),
}


def _flatten_depth(node, depth=1) -> int:
    """Estimate grammar depth (the reference flattens twice,
    erlamsa_gf:173-181)."""
    if isinstance(node, list):
        return max((_flatten_depth(x, depth + 1) for x in node), default=depth)
    if not isinstance(node, tuple):
        return depth
    kind = node[0]
    if kind in ("pick",):
        return max(
            (_flatten_depth(g, depth + 1) for g in node[1]), default=depth
        )
    if kind == "pick_pref":
        return max(
            (_flatten_depth(g, depth + 1) for _w, g in node[1]), default=depth
        )
    if kind in ("loop", "sizer"):
        return _flatten_depth(node[-1] if kind == "loop" else node[2], depth + 1)
    if kind == "block":
        return max(
            (_flatten_depth(g, depth + 1) for g in node[1]), default=depth
        )
    return depth


def generate(r: ErlRand, grammar, session: dict | None = None,
             fuzz_prob: float = 0.0) -> bytes:
    """Expand a grammar to bytes; leaves mutate with fuzz_prob."""
    session = session if session is not None else {}

    def emit(node) -> bytes:
        if isinstance(node, list):
            return b"".join(emit(x) for x in node)
        if isinstance(node, (bytes, bytearray)):
            node = ("static", bytes(node))
        kind = node[0]
        if kind == "static":
            out = node[1]
            if fuzz_prob and r.rand_float() < fuzz_prob and out:
                # flip one byte of the literal
                p = r.rand(len(out))
                out = out[:p] + bytes([r.rand(256)]) + out[p + 1 :]
            return out
        if kind == "range":
            lo, hi = node[1], node[2]
            if fuzz_prob and r.rand_float() < fuzz_prob:
                return bytes([r.rand(256)])  # out-of-range byte
            return bytes([r.rand_span(lo, hi)])
        if kind == "rbyte":
            return r.rbyte()
        if kind == "rword":
            return r.rword()
        if kind == "rdword":
            return r.rdword()
        if kind == "rddword":
            return r.rddword()
        if kind == "rbinary":
            return r.random_block(node[1])
        if kind == "pick":
            return emit(r.rand_elem(node[1]))
        if kind == "pick_pref":
            total = sum(w for w, _g in node[1])
            n = r.rand(total)
            for w, g in node[1]:
                if n < w:
                    return emit(g)
                n -= w
            return emit(node[1][-1][1])
        if kind == "loop":
            times = r.erand(node[2])
            if fuzz_prob and r.rand_float() < fuzz_prob:
                times = times * (1 + r.rand_log(6))  # loop blowup
            return b"".join(emit(node[1]) for _ in range(times))
        if kind == "sizer":
            fmt, _width, _endian = _SIZER_FMT[node[1]]
            body = emit(node[2])
            size = len(body)
            if fuzz_prob and r.rand_float() < fuzz_prob:
                size = r.rand(1 << (8 * _width))  # lie about the length
            mask = (1 << (8 * _width)) - 1
            return struct.pack(fmt, size & mask) + body
        if kind == "block":
            return b"".join(emit(g) for g in node[1])
        if kind == "session_get":
            return bytes(session.get(node[1], node[2]))
        raise ValueError(f"unknown grammar node {node!r}")

    return emit(grammar)


def fuzz_grammar(r: ErlRand, grammar, session: dict | None = None) -> bytes:
    """Generate with depth-scaled fuzzing probability
    (erlamsa_gf:fuzz/3)."""
    depth = _flatten_depth(grammar)
    prob = 1.0 / max(depth * 2, 2)
    return generate(r, grammar, session, fuzz_prob=prob)


def generate_keyed(cg, skey, fuzz: bool = False):
    """Counter-keyed expansion of a COMPILED grammar — the host twin of
    ops/grammar.py's device stack machine.

    ``generate()`` above follows the reference's sequential ErlRand
    stream and stays the gfcomms/per-sample path. This walk instead
    consumes draw j of a per-sample threefry key, exactly the (j, n)
    sequence the device kernel consumes (threefry is backend-
    deterministic, so a draw computed host-side equals the same draw
    inside a jitted kernel) — which makes this function both the byte-
    identity test oracle and the degraded path when the device is lost
    (gen/engine.py, chaos site ``gen.expand``). Truncation, sizer-record
    budgets and step budgets mirror the kernel's static bounds.

    Returns (row bytes[width], length, truncated) — the full padded
    panel row, so tests can compare entire rows against the device.
    """
    import jax
    import numpy as np

    from ..gen.compile import (ENDIAN_LITTLE, K_LOOP, K_PICK, K_PICKP,
                               K_RANGE, K_RBYTES, K_SEQ, K_SIZER, K_STATIC,
                               K_SZEND, K_VERB)
    from ..ops import prng

    prod = cg.prod
    children = cg.children
    cweights = cg.cweights
    pool = bytes(cg.pool)
    W = int(cg.width)
    R = int(cg.max_recs)
    prob = np.float32(cg.fuzz_prob) if fuzz else None

    def dk(j):
        return jax.random.fold_in(skey, j)

    def draw(j, n):
        return int(prng.rand(dk(j), int(n)))

    def ufire(j):
        return bool(np.float32(prng.uniform_f32(dk(j))) < prob)

    out = bytearray(W + max(int(cg.emit), 4))
    stack: list[tuple[int, int]] = [(int(cg.root), 1)]
    recs: list[list[int]] = []
    pos = j = steps = 0
    truncated = False

    def emit(data: bytes, n: int):
        nonlocal pos
        wp = min(pos, W)
        out[wp : wp + n] = data[:n]
        pos += n

    while stack and steps < int(cg.max_steps):
        steps += 1
        node, aux = stack[-1]
        kind, a, b, off, cnt = (int(x) for x in prod[node])
        if kind != K_SZEND and aux > 1:
            stack[-1] = (node, aux - 1)
        else:
            stack.pop()
        if kind in (K_STATIC, K_VERB):
            lit = pool[a : a + b]
            if prob is not None and kind == K_STATIC:
                fire = ufire(j) and b > 0
                if fire:
                    p = draw(j + 1, b)
                    v = draw(j + 2, 256)
                    lit = lit[:p] + bytes([v]) + lit[p + 1 :]
                j += 1 + (2 if fire else 0)
            emit(lit, b)
        elif kind == K_RANGE:
            if prob is not None:
                v = draw(j + 1, 256) if ufire(j) else a + draw(
                    j + 1, b - a + 1
                )
                j += 2
            else:
                v = a + draw(j, b - a + 1)
                j += 1
            emit(bytes([v]), 1)
        elif kind == K_RBYTES:
            emit(bytes(draw(j + t, 256) for t in range(a)), a)
            j += a
        elif kind == K_PICK:
            c = draw(j, cnt)
            j += 1
            stack.append((int(children[off + c]), 1))
        elif kind == K_PICKP:
            n = draw(j, b)
            j += 1
            sel = next(
                i for i in range(cnt) if n < int(cweights[off + i])
            )
            stack.append((int(children[off + sel]), 1))
        elif kind == K_LOOP:
            times = draw(j, a) + 1
            j += 1
            if prob is not None:
                fire = ufire(j)
                if fire:
                    times *= 1 + int(prng.rand_log(dk(j + 1), 6))
                j += 1 + (1 if fire else 0)
            stack.append((int(children[off]), times))
        elif kind == K_SIZER:
            avail = len(recs) < R
            field_pos = pos
            emit(b"\x00" * a, a)
            if avail:
                recs.append([field_pos, pos, 0, a, b])
                stack.append((int(children[off + 1]), len(recs) - 1))
            else:
                truncated = True
            stack.append((int(children[off]), 1))
        elif kind == K_SZEND:
            width = recs[aux][3]
            blen = pos - recs[aux][1]
            lo, hi = blen & 0xFFFF, blen >> 16
            if prob is not None:
                fire = ufire(j)
                wide = width == 4
                if fire:
                    d1 = draw(j + 1, 256 if width == 1 else 65536)
                    lo, hi = (draw(j + 2, 65536), d1) if wide else (d1, 0)
                j += 1 + (2 if wide else 1) * int(fire)
            recs[aux][1], recs[aux][2] = lo, hi
        elif kind == K_SEQ:
            for i in reversed(range(cnt)):
                stack.append((int(children[off + i]), 1))
        else:
            raise ValueError(f"bad compiled node kind {kind}")

    truncated = truncated or bool(stack) or pos > W
    for fp, lo, hi, width, endian in recs:
        le = (lo & 0xFF, (lo >> 8) & 0xFF, hi & 0xFF, (hi >> 8) & 0xFF)
        wp = min(fp, W)
        for k in range(width):
            out[wp + k] = le[k if endian == ENDIAN_LITTLE else width - 1 - k]
    return bytes(out[:W]), min(pos, W), truncated


def make_external_generator(grammar, seed=None):
    """Adapter: a grammar becomes a generator for the engine's genfuz slot
    (the reference's external module `generator` capability)."""
    from ..utils.erlrand import gen_urandom_seed

    r = ErlRand(seed or gen_urandom_seed())

    def gen():
        return [fuzz_grammar(r, grammar)], ("generator", "genfuz")

    return gen
