"""HPACK (RFC 7541) header compression — decoder/encoder.

Reference vendors cowlib's cow_hpack (src/cow_hpack.erl +
src/cow_hpack_dec_huffman_lookup.hrl) for its HTTP/2 proxy path. This
implementation covers integer/string primitives, Huffman string coding
(models/huffman.py), the full static table, and a size-managed dynamic
table — so http2 header fuzzing sees real decoded strings. Invalid
Huffman payloads fall back to an opaque ``?huff:`` marker rather than
failing the whole block; re-encoding uses non-huffman literals (always
legal per the RFC).
"""

from __future__ import annotations

from .huffman import huffman_decode

STATIC_TABLE = [
    (b":authority", b""), (b":method", b"GET"), (b":method", b"POST"),
    (b":path", b"/"), (b":path", b"/index.html"), (b":scheme", b"http"),
    (b":scheme", b"https"), (b":status", b"200"), (b":status", b"204"),
    (b":status", b"206"), (b":status", b"304"), (b":status", b"400"),
    (b":status", b"404"), (b":status", b"500"), (b"accept-charset", b""),
    (b"accept-encoding", b"gzip, deflate"), (b"accept-language", b""),
    (b"accept-ranges", b""), (b"accept", b""), (b"access-control-allow-origin", b""),
    (b"age", b""), (b"allow", b""), (b"authorization", b""),
    (b"cache-control", b""), (b"content-disposition", b""),
    (b"content-encoding", b""), (b"content-language", b""),
    (b"content-length", b""), (b"content-location", b""),
    (b"content-range", b""), (b"content-type", b""), (b"cookie", b""),
    (b"date", b""), (b"etag", b""), (b"expect", b""), (b"expires", b""),
    (b"from", b""), (b"host", b""), (b"if-match", b""),
    (b"if-modified-since", b""), (b"if-none-match", b""), (b"if-range", b""),
    (b"if-unmodified-since", b""), (b"last-modified", b""), (b"link", b""),
    (b"location", b""), (b"max-forwards", b""), (b"proxy-authenticate", b""),
    (b"proxy-authorization", b""), (b"range", b""), (b"referer", b""),
    (b"refresh", b""), (b"retry-after", b""), (b"server", b""),
    (b"set-cookie", b""), (b"strict-transport-security", b""),
    (b"transfer-encoding", b""), (b"user-agent", b""), (b"vary", b""),
    (b"via", b""), (b"www-authenticate", b""),
]


def encode_integer(value: int, prefix_bits: int, flags: int = 0) -> bytes:
    """RFC 7541 §5.1 prefix-coded integer."""
    limit = (1 << prefix_bits) - 1
    if value < limit:
        return bytes([flags | value])
    out = bytearray([flags | limit])
    value -= limit
    while value >= 128:
        out.append((value % 128) + 128)
        value //= 128
    out.append(value)
    return bytes(out)


def decode_integer(data: bytes, pos: int, prefix_bits: int) -> tuple[int, int]:
    """Returns (value, next_pos)."""
    limit = (1 << prefix_bits) - 1
    value = data[pos] & limit
    pos += 1
    if value < limit:
        return value, pos
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        value += (b & 0x7F) << shift
        shift += 7
        if not b & 0x80:
            return value, pos


def decode_string(data: bytes, pos: int) -> tuple[bytes, bool, int]:
    """Returns (string, is_opaque, next_pos). Huffman payloads are decoded
    to their real octets; is_opaque is True only when a huffman payload is
    invalid and must be carried raw (caller marks it)."""
    huff = bool(data[pos] & 0x80)
    length, pos = decode_integer(data, pos, 7)
    raw = data[pos : pos + length]
    if huff:
        try:
            return huffman_decode(raw), False, pos + length
        except ValueError:
            return raw, True, pos + length
    return raw, False, pos + length


def encode_string(s: bytes) -> bytes:
    """Non-huffman literal (always legal)."""
    return encode_integer(len(s), 7) + s


class HpackContext:
    """One direction's decoding context (dynamic table)."""

    def __init__(self, max_size: int = 4096):
        self.max_size = max_size
        # (name, value, rfc_size): size is tracked OUT OF BAND, computed
        # from the wire-decoded octet lengths at insert time, so a decoded
        # value that happens to start with the '?huff:' fallback marker
        # can't skew the accounting
        self.dynamic: list[tuple[bytes, bytes, int]] = []

    def _size(self) -> int:
        return sum(sz for _n, _v, sz in self.dynamic)

    def _evict(self):
        while self.dynamic and self._size() > self.max_size:
            self.dynamic.pop()

    def add(self, name: bytes, value: bytes, entry_size: int | None = None):
        """entry_size: RFC 7541 §4.1 decoded-octets size (len(name) +
        len(value) + 32); derived from the stored strings when omitted."""
        if entry_size is None:
            entry_size = len(name) + len(value) + 32
        self.dynamic.insert(0, (name, value, entry_size))
        self._evict()

    def lookup(self, index: int) -> tuple[bytes, bytes]:
        if 1 <= index <= len(STATIC_TABLE):
            return STATIC_TABLE[index - 1]
        dyn = index - len(STATIC_TABLE) - 1
        if 0 <= dyn < len(self.dynamic):
            return self.dynamic[dyn][:2]
        raise IndexError(f"hpack index {index} out of range")

    def decode(self, block: bytes) -> list[tuple[bytes, bytes]]:
        """Header block -> [(name, value)] with huffman strings decoded;
        invalid huffman payloads come back marked b'?huff:'+raw."""
        headers = []
        pos = 0
        while pos < len(block):
            b = block[pos]
            if b & 0x80:  # indexed
                idx, pos = decode_integer(block, pos, 7)
                headers.append(self.lookup(idx))
            elif b & 0x40:  # literal with incremental indexing
                idx, pos = decode_integer(block, pos, 6)
                if idx:
                    name = self.lookup(idx)[0]
                    name_sz = len(name)
                else:
                    raw, hf, pos = decode_string(block, pos)
                    name = b"?huff:" + raw if hf else raw
                    name_sz = len(raw)
                raw, hf, pos = decode_string(block, pos)
                value = b"?huff:" + raw if hf else raw
                self.add(name, value, name_sz + len(raw) + 32)
                headers.append((name, value))
            elif b & 0x20:  # dynamic table size update
                size, pos = decode_integer(block, pos, 5)
                self.max_size = size
                self._evict()
            else:  # literal without indexing / never indexed (4-bit prefix)
                idx, pos = decode_integer(block, pos, 4)
                name = self.lookup(idx)[0] if idx else None
                if name is None:
                    raw, hf, pos = decode_string(block, pos)
                    name = b"?huff:" + raw if hf else raw
                raw, hf, pos = decode_string(block, pos)
                value = b"?huff:" + raw if hf else raw
                headers.append((name, value))
        return headers

    def encode(self, headers: list[tuple[bytes, bytes]]) -> bytes:
        """Simple encoder: indexed where a full static match exists, else
        literal-without-indexing with plain strings."""
        out = bytearray()
        for name, value in headers:
            try:
                idx = STATIC_TABLE.index((name, value)) + 1
                out += encode_integer(idx, 7, 0x80)
                continue
            except ValueError:
                pass
            name_idx = 0
            for i, (n, _v) in enumerate(STATIC_TABLE):
                if n == name:
                    name_idx = i + 1
                    break
            out += encode_integer(name_idx, 4, 0x00)
            if not name_idx:
                out += encode_string(name)
            out += encode_string(value)
        return bytes(out)
