"""ZIP archive helpers: path-traversal member renaming and member-wise
mutation support.

Reference: zip_path_traversal (src/erlamsa_mutations.erl:1146-1163) and the
archiver pattern (src/erlamsa_patterns.erl:165-214), which use OTP's zip
module; here Python's zipfile over in-memory buffers.
"""

from __future__ import annotations

import io
import zipfile

from ..utils.erlrand import ErlRand


def list_members(data: bytes) -> list[tuple[str, bytes]] | None:
    """[(name, content)] or None when not a readable ZIP."""
    try:
        with zipfile.ZipFile(io.BytesIO(data)) as z:
            return [(i.filename, z.read(i.filename)) for i in z.infolist()]
    except Exception:  # lint: broad-except-ok any parse failure means not-a-ZIP
        return None


def rebuild(members: list[tuple[str, bytes]]) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
        for name, content in members:
            # fixed timestamp: writestr(name, ...) would embed the current
            # wall clock and break fixed-seed reproducibility
            info = zipfile.ZipInfo(name, date_time=(1980, 1, 1, 0, 0, 0))
            info.compress_type = zipfile.ZIP_DEFLATED
            # writestr(str, ...) would set this itself; a bare ZipInfo
            # leaves mode 000 and attrs-honoring extractors create
            # unreadable files
            info.external_attr = 0o600 << 16
            z.writestr(info, content)
    return buf.getvalue()


def path_traversal(r: ErlRand, data: bytes) -> bytes | None:
    """Prefix every member with rand(20) '../' segments
    (src/erlamsa_mutations.erl:1149-1163)."""
    members = list_members(data)
    if members is None:
        return None
    out = []
    for name, content in members:
        n = r.rand(20)
        out.append(("../" * n + name, content))
    return rebuild(out)
