"""HTTP/2 framing and the DATA-only fuzz path.

Reference: src/erlamsa_http2.erl — parses the frame stream, HPACK-tracks
header state per direction, fuzzes ONLY DATA payloads, and repacks
(fuzz_http2, :609-665). Same policy here: HEADERS/SETTINGS/etc. pass
through byte-identical (which also keeps both endpoints' HPACK contexts
consistent), DATA payloads go through the fuzzer and the frame length is
recomputed; padding is stripped on fuzzed frames.
"""

from __future__ import annotations

from typing import Callable

from .hpack import HpackContext

PREFACE = b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"

T_DATA = 0x0
T_HEADERS = 0x1
T_PRIORITY = 0x2
T_RST_STREAM = 0x3
T_SETTINGS = 0x4
T_PUSH_PROMISE = 0x5
T_PING = 0x6
T_GOAWAY = 0x7
T_WINDOW_UPDATE = 0x8
T_CONTINUATION = 0x9

F_PADDED = 0x8
F_END_HEADERS = 0x4
F_PRIORITY = 0x20


def parse_frames(data: bytes) -> tuple[list[tuple[int, int, int, bytes]], bytes]:
    """-> ([(type, flags, stream_id, payload)], remainder). The remainder is
    an incomplete trailing frame (stream reassembly buffer)."""
    frames = []
    pos = 0
    if data.startswith(PREFACE):
        frames.append((-1, 0, 0, PREFACE))  # pseudo-frame for the preface
        pos = len(PREFACE)
    while pos + 9 <= len(data):
        length = int.from_bytes(data[pos : pos + 3], "big")
        ftype = data[pos + 3]
        flags = data[pos + 4]
        stream = int.from_bytes(data[pos + 5 : pos + 9], "big") & 0x7FFFFFFF
        if pos + 9 + length > len(data):
            break
        frames.append((ftype, flags, stream, data[pos + 9 : pos + 9 + length]))
        pos += 9 + length
    return frames, data[pos:]


def build_frame(ftype: int, flags: int, stream: int, payload: bytes) -> bytes:
    if ftype == -1:
        return payload  # preface pseudo-frame
    return (
        len(payload).to_bytes(3, "big")
        + bytes([ftype & 0xFF, flags & 0xFF])
        + (stream & 0x7FFFFFFF).to_bytes(4, "big")
        + payload
    )


class Http2FuzzState:
    """Per-direction stream state: HPACK context + reassembly remainder
    (the reference keeps this in the process dictionary,
    src/erlamsa_http2.erl:623-624)."""

    def __init__(self):
        self.hpack = HpackContext()
        self.remainder = b""
        self.seen_headers: list = []


def fuzz_http2(
    fuzzer: Callable[[bytes], bytes], data: bytes, state: Http2FuzzState
) -> bytes:
    """Fuzz DATA payloads in a captured HTTP/2 byte stream; everything else
    passes through unchanged."""
    frames, rem = parse_frames(state.remainder + data)
    state.remainder = rem
    out = bytearray()
    for ftype, flags, stream, payload in frames:
        if ftype == T_HEADERS:
            # decode purely to track state/observability; frame unchanged
            try:
                block = payload
                if flags & F_PADDED and block:
                    pad = block[0]
                    block = block[1 : len(block) - pad]
                if flags & F_PRIORITY and len(block) >= 5:
                    block = block[5:]  # stream dep (4) + weight (1)
                state.seen_headers.append(state.hpack.decode(block))
            except (IndexError, ValueError):
                pass  # desync-tolerant, like the reference's kill-on-desync
            out += build_frame(ftype, flags, stream, payload)
        elif ftype == T_DATA and payload:
            body = payload
            new_flags = flags
            if flags & F_PADDED and body:
                pad = body[0]
                body = body[1 : len(body) - pad] if pad < len(body) else b""
                new_flags = flags & ~F_PADDED
            fuzzed = fuzzer(body)
            out += build_frame(ftype, new_flags, stream, fuzzed)
        else:
            out += build_frame(ftype, flags, stream, payload)
    return bytes(out)
