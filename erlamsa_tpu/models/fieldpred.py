"""Length-field ("sizer") and checksum-field prediction.

Reference: src/erlamsa_field_predict.erl. Finds plausible u8/u16/u32/u64
big/little length fields whose value equals the distance to a candidate end
offset, and xor8/crc32 trailer checksums by brute force over preamble
offsets. Draw order matters for the sizer scan (it samples random end
offsets); kept 1:1.

The numpy variants (suffix _np) are the batch path's vectorized versions:
one pass computes every candidate offset simultaneously instead of the
reference's O(n*k) rescan.
"""

from __future__ import annotations

import zlib

import numpy as np

from ..constants import PREAMBLE_MAX_BYTES, SIZER_MAX_FIRST_BYTES
from ..utils.erlrand import ErlRand

# sizer_location: (size_bits, "big"|"little", length_value, A, B)


def _read_uint(data: bytes, off: int, size_bits: int, endian: str) -> int | None:
    nbytes = size_bits // 8
    if off + nbytes > len(data):
        return None
    chunk = data[off : off + nbytes]
    return int.from_bytes(chunk, "big" if endian == "big" else "little")


def _basic_u8len(a: int, b: int, data: bytes) -> list[tuple]:
    """u8 length at offset a whose value == b - a - 1 > 2
    (erlamsa_field_predict.erl:50-58)."""
    if not (a < b and b > 0 and a < len(data)):
        return []
    v = _read_uint(data, a, 8, "big")
    if v is not None and v == b - a - 1 and v > 2:
        return [(8, "big", v, a, b)]
    return []


def _simple_u8len(a: int, data: bytes) -> list[tuple]:
    return [
        loc
        for x in range(0, 9)
        for loc in _basic_u8len(a, len(data) - x, data)
    ]


def _basic_len(a: int, b: int, data: bytes) -> list[tuple]:
    """u16/u32/u64 BE then LE, first match wins (the reference's binary
    pattern match tries clauses in order, erlamsa_field_predict.erl:66-78)."""
    if not (a < b and b > 0 and a < len(data)):
        return []
    for size, endian in ((16, "big"), (32, "big"), (64, "big"),
                         (16, "little"), (32, "little"), (64, "little")):
        v = _read_uint(data, a, size, endian)
        if v is not None and v == b - a - size // 8 and v > 2:
            return [(size, endian, v, a, b)]
    return []


def _simple_len(a: int, b: int, data: bytes) -> list[tuple]:
    out = []
    for d in (0, 1, 2, 4, 8):
        out.extend(_basic_len(a, b - d, data))
    return out


_COMBOS = ((16, "big"), (32, "big"), (64, "big"),
           (16, "little"), (32, "little"), (64, "little"))


def _field_targets(data: bytes, amax: int):
    """For each scan offset a in [0, amax] and each (size, endian) clause,
    the UNIQUE end offset b that would match: a field matches iff
    v == b - a - nb (and v > 2), i.e. iff b == v + a + nb. Returns
    (targets[6, A] int64, vals[6, A] int64) with -1 where no match is
    possible (value <= 2, overflow, or field past the end)."""
    n = len(data)
    arr = np.frombuffer(data, dtype=np.uint8).astype(np.uint64)
    a_idx = np.arange(amax + 1, dtype=np.int64)
    targets = np.full((len(_COMBOS), amax + 1), -1, dtype=np.int64)
    vals = np.full((len(_COMBOS), amax + 1), -1, dtype=np.int64)
    for k, (size, endian) in enumerate(_COMBOS):
        nb = size // 8
        if n < nb:
            continue
        v = np.zeros(amax + 1, dtype=np.uint64)
        for j in range(nb):
            shift = (nb - 1 - j) if endian == "big" else j
            idx = np.minimum(a_idx + j, n - 1)
            v |= arr[idx] << np.uint64(8 * shift)
        ok = (a_idx + nb <= n) & (v > 2) & (v < np.uint64(1 << 62))
        vi = v.astype(np.int64)
        vals[k] = np.where(ok, vi, -1)
        targets[k] = np.where(ok, vi + a_idx + nb, -1)
    return targets, vals


def get_possible_simple_lens(r: ErlRand, data: bytes) -> list[tuple]:
    """All sizer candidates; for >10B inputs the end offsets are randomly
    sampled (erlamsa_field_predict.erl:90-105).

    Vectorized: the reference rescans every (a, b) range per clause —
    O(A^2 * 30) byte reads (the oracle's dominant cost on 4KB inputs).
    Since a clause matches iff b == value(a) + a + nb, precomputing that
    unique target end offset per (a, clause) turns the scan into array
    compares. Output (order included) and draw order are identical to
    the reference shape; tests lock this against the scalar scan.
    """
    n = len(data)
    if n <= 10:
        out = []
        for x in range(0, 4):
            out.extend(_simple_len(x, n, data))
            out.extend(_simple_u8len(x, data))
        return out

    sublen = min(n // 5, SIZER_MAX_FIRST_BYTES)
    first_seq = np.arange(0, sublen + 1, dtype=np.int64)
    # sublen+1 consecutive rand_range(sublen, n) draws in one block:
    # rand_range(l, r) with r > l is trunc(uniform()*(r-l)) + l
    var_b = (
        (r.uniform_block(sublen + 1) * (n - sublen)).astype(np.int64)
        + sublen
    ).tolist()
    targets, vals = _field_targets(data, sublen)
    deltas = (0, 1, 2, 4, 8)
    nvb = len(var_b)

    # invert the scan: a clause matches range (a, b) at delta d iff
    # b == target[k, a] + d, so look the required b value up instead of
    # comparing every (range, delta, clause) triple. Matches are keyed
    # (range_index, d) -> FIRST clause k (min over k), computed with
    # vectorized membership tests — no per-(k, a, d) Python loop.
    K = len(_COMBOS)
    A = sublen + 1
    D = len(deltas)
    T = targets  # [K, A]; -1 where impossible
    valid = (T > 0) & (first_seq[None, :] < T)
    k_col = np.arange(K, dtype=np.int64)[:, None]  # broadcast over a

    # the (a, n) block occupies range indices 0..sublen
    h_tail = np.full((A, D), K, np.int64)
    # the (x, y) block: index sublen+1 + x*nvb + j
    h_var = np.full((A * nvb, D), K, np.int64)
    vb_arr = np.asarray(var_b, np.int64)
    order = np.argsort(vb_arr, kind="stable")
    sv = vb_arr[order]
    for di, d in enumerate(deltas):
        m = valid & (T == n - d)
        if m.any():
            ks, as_ = np.nonzero(m)  # k-ascending (row-major)
            np.minimum.at(h_tail[:, di], as_, ks)
        want = T + d
        lo = np.searchsorted(sv, want.ravel()).reshape(K, A)
        hi = np.searchsorted(sv, want.ravel(), side="right").reshape(K, A)
        cnt = np.where(valid, hi - lo, 0).ravel()
        total = int(cnt.sum())
        if total == 0:
            continue
        ks = np.repeat(np.broadcast_to(k_col, (K, A)).ravel(), cnt)
        as_ = np.repeat(np.broadcast_to(first_seq, (K, A)).ravel(), cnt)
        starts = np.repeat(lo.ravel(), cnt)
        offs = np.arange(total) - np.repeat(np.cumsum(cnt) - cnt, cnt)
        js = order[starts + offs]
        np.minimum.at(h_var[:, di], as_ * nvb + js, ks)

    def a_of(ridx: int) -> int:
        return ridx if ridx <= sublen else (ridx - sublen - 1) // nvb

    # hit enumeration in ascending (range_index, d): tail indices
    # (0..sublen) precede every var index, and argwhere is row-major
    hit_items = [
        (int(a_), int(di), int(h_tail[a_, di]))
        for a_, di in np.argwhere(h_tail < K)
    ] + [
        (sublen + 1 + int(rid), int(di), int(h_var[rid, di]))
        for rid, di in np.argwhere(h_var < K)
    ]

    big_parts: dict[int, list[tuple]] = {}
    for ridx, _di, k in hit_items:
        size, endian = _COMBOS[k]
        a = a_of(ridx)
        bb = int(targets[k, a])
        big_parts.setdefault(ridx, []).append(
            (size, endian, int(vals[k, a]), a, bb)
        )
    # the reference foldl-prepends per-range results, reversing range order
    big = [
        loc
        for ridx in sorted(big_parts, reverse=True)
        for loc in big_parts[ridx]
    ]

    # u8 scan: b in (n-0 .. n-8), match iff v8[a] == b - a - 1 > 2
    arr8 = np.frombuffer(data, dtype=np.uint8).astype(np.int64)
    v8 = arr8[np.minimum(first_seq, n - 1)]
    t8 = np.where((first_seq + 1 <= n) & (v8 > 2), v8 + first_seq + 1, -1)
    xs = n - np.arange(0, 9, dtype=np.int64)  # b candidates, x = 0..8
    m8 = (
        (t8[:, None] == xs[None, :])
        & (first_seq[:, None] < xs[None, :])
        & (xs[None, :] > 0)
        & (first_seq[:, None] < n)
    )
    small = [
        (8, "big", int(v8[a]), int(a), int(xs[x]))
        for a, x in np.argwhere(m8)
    ]
    return small + big


def extract_blob(data: bytes, loc: tuple) -> tuple[bytes, int, bytes, bytes]:
    """(head, len_value, blob, rest) around a sizer
    (erlamsa_field_predict.erl:111-117)."""
    size, endian, lval, a, _b = loc
    nb = size // 8
    head = data[:a]
    blob = data[a + nb : a + nb + lval]
    rest = data[a + nb + lval :]
    return head, lval, blob, rest


def rebuild_blob(loc_endian: str, head: bytes, new_len: int, size_bits: int,
                 blob: bytes, tail: bytes) -> bytes:
    """head ++ len-field ++ blob ++ tail (erlamsa_field_predict.erl:119-123)."""
    nb = size_bits // 8
    field = (new_len % (1 << size_bits)).to_bytes(
        nb, "big" if loc_endian == "big" else "little"
    )
    return head + field + blob + tail


# --- checksums ------------------------------------------------------------


def calc_xor8(data: bytes) -> int:
    v = 0
    for b in data:
        v ^= b
    return v


def recalc_csum(kind: str, data: bytes) -> int:
    """(erlamsa_field_predict.erl:163-167)."""
    if kind == "crc32":
        return zlib.crc32(data) & 0xFFFFFFFF
    return calc_xor8(data)


def get_possible_csum_locations(data: bytes) -> list[tuple]:
    """Trailer checksums over preamble offsets: (kind, size_bits,
    preamble_len, body_len) (erlamsa_field_predict.erl:154-161)."""
    n = len(data)
    if n == 0:
        return []
    out = []
    limit = min(2 * n // 3, 30 * PREAMBLE_MAX_BYTES)
    pre = np.frombuffer(data, dtype=np.uint8)
    # vectorized xor8: suffix xors via cumulative xor from the right
    sfx_xor = np.bitwise_xor.accumulate(pre[::-1])[::-1]
    for a in range(0, limit + 1):
        if n - a - 1 > 0:
            body_x = sfx_xor[a] ^ sfx_xor[n - 1]  # xor of data[a:n-1]
            if body_x == data[n - 1]:
                out.append(("xor8", 8, a, n - a - 1))
    for a in range(0, limit + 1):
        if n - a >= 4:
            c = int.from_bytes(data[n - 4 :], "big")
            if zlib.crc32(data[a : n - 4]) & 0xFFFFFFFF == c:
                out.append(("crc32", 32, a, n - a - 4))
    return out
