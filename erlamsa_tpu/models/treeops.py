"""Guessed parse-tree mutations: partial-parse bytes by bracket/quote pairs
and dup/del/swap/stutter subtrees.

Reference: src/erlamsa_mutations.erl:786-1023. The tree is a Python list of
ints (bytes) and nested lists (delimited nodes, first element = opening
delimiter byte, last = closing when complete).
"""

from __future__ import annotations

import sys

import numpy as np

from ..utils.erlrand import ErlRand

_DELIMS = {40: 41, 91: 93, 60: 62, 123: 125, 34: 34, 39: 39}

# every byte value that can be a parse event (any opener or closer); all
# other bytes are literals and can be bulk-copied between events
_EVENT = np.zeros(256, bool)
for _k, _v in _DELIMS.items():
    _EVENT[_k] = True
    _EVENT[_v] = True


def _ensure_stack():
    """The recursive walkers below (sublists/edit_sublist) descend to the
    parse depth, which MAX_PARSE_DEPTH allows up to 2000 — beyond CPython's
    default 1000-frame limit. Ensure headroom here so library callers are
    covered too, not only Engine-constructed flows."""
    if sys.getrecursionlimit() < 20000:
        sys.setrecursionlimit(20000)


MAX_PARSE_DEPTH = 2000


def partial_parse(data: bytes, max_depth: int = MAX_PARSE_DEPTH) -> list:
    """bytes -> tree (erlamsa_mutations.erl:886-905), iteratively.

    The reference's recursive grow() runs on BEAM with no stack ceiling;
    mutated data routinely contains thousands of consecutive openers (a
    seq-repeat of '<' alone does it), so this walker keeps an explicit
    stack. Nesting beyond max_depth treats further openers as literal
    bytes — a documented pragmatic cap that also bounds every downstream
    recursive tree walker.
    """
    _ensure_stack()
    root: list = []
    # frames: (close_byte, node_list); node[0] is the opener byte
    stack: list[tuple[int, list]] = []
    cur = root
    # walk only the delimiter EVENTS; literal runs between events bulk-
    # copy in one extend (this parser was the oracle's 4KB-input hotspot)
    arr = np.frombuffer(data, dtype=np.uint8)
    prev = 0
    for p in np.flatnonzero(_EVENT[arr]).tolist():
        if p > prev:
            cur.extend(data[prev:p])
        prev = p + 1
        h = data[p]
        if stack and h == stack[-1][0]:
            close, node = stack.pop()
            node.append(close)
            parent = stack[-1][1] if stack else root
            parent.append(node)
            cur = parent
            continue
        close = _DELIMS.get(h)
        if close is not None and len(stack) < max_depth:
            node = [h]
            stack.append((close, node))
            cur = node
            continue
        cur.append(h)
    if len(data) > prev:
        cur.extend(data[prev:])
    # EOF with unclosed frames: flatten each partial node into its parent
    # (the reference's failed grow() splices [H|This] into the enclosing
    # level, keeping completed sublists intact)
    while stack:
        _close, node = stack.pop()
        parent = stack[-1][1] if stack else root
        parent.extend(node)
    return root


def flatten_tree(node, limit: int | None = None) -> bytes | None:
    """Tree -> bytes. With `limit`, returns None as soon as the output
    would exceed it — stutter/dup results can reference large shared
    substructure at many positions, and materializing them unbounded is a
    multi-GB trap (the reference leans on BEAM heap guards; we cap at the
    caller's block limit instead)."""
    out = bytearray()
    stack = [node]
    while stack:
        x = stack.pop()
        if isinstance(x, int):
            out.append(x & 0xFF)
            if limit is not None and len(out) > limit:
                return None
        else:
            stack.extend(reversed(x))
    return bytes(out)


def sublists(lst: list) -> list[list]:
    """All nested list nodes, reference walk order
    (erlamsa_mutations.erl:836-845): prepend-on-descend."""
    # the reference accumulates [H|Found] then recurses into H with that
    # accumulator, scanning each list left to right
    def walk(node: list, found: list) -> list:
        for h in node:
            if isinstance(h, list):
                found = walk(h, [h] + found)
        return found

    return walk(lst, [])


def edit_sublist(lst: list, sub, op) -> list:
    """Replace nodes STRUCTURALLY equal to `sub` (the reference compares
    with =:= on list values, erlamsa_mutations.erl:857-869): at each list
    level, the first equal element swallows the rest of that list into
    op([sub | rest]); subtrees walked before the match are edited too.
    op returns the replacement slice."""
    if not isinstance(lst, list):
        return [lst]
    out = []
    i = 0
    while i < len(lst):
        h = lst[i]
        if h == sub:
            return out + op(lst[i:])
        if isinstance(h, list):
            out.append(edit_sublist(h, sub, op))
        else:
            out.append(h)
        i += 1
    return out


def sed_tree_dup(r: ErlRand, tree: list) -> list:
    """tr2: duplicate a node (erlamsa_mutations.erl:930-932)."""
    subs = sublists(tree)
    if not subs:
        return tree
    sub = r.rand_elem(subs)
    return edit_sublist(tree, sub, lambda s: [s[0]] + s)


def sed_tree_del(r: ErlRand, tree: list) -> list:
    """td: delete a node (erlamsa_mutations.erl:934-936)."""
    subs = sublists(tree)
    if not subs:
        return tree
    sub = r.rand_elem(subs)
    return edit_sublist(tree, sub, lambda s: s[1:])


def sed_tree_swap_one(r: ErlRand, tree: list) -> list | None:
    """ts1: overwrite one node with another (erlamsa_mutations.erl:938-943)."""
    subs = sublists(tree)
    if len(subs) < 2:
        return None
    to_swap = r.reservoir_sample(subs, 2)
    perm = r.random_permutation(to_swap)
    a, b = perm[0], perm[1]
    return edit_sublist(tree, a, lambda s: [b] + s[1:])


def sed_tree_swap_two(r: ErlRand, tree: list) -> list | None:
    """ts2: pairwise swap (erlamsa_mutations.erl:945-952). Structural
    matching like the reference's gb_trees mapping: ALL nodes equal to a
    become b and vice versa; replaced nodes are not descended into
    (edit_sublists, erlamsa_mutations.erl:872-884). Keeps the quirk that a
    parent can swap with its own child."""
    subs = sublists(tree)
    if len(subs) < 2:
        return None
    a, b = r.reservoir_sample(subs, 2)[:2]

    def walk(node):
        if not isinstance(node, list):
            return node
        out = []
        for h in node:
            if isinstance(h, list) and h == a:
                out.append(b)
            elif isinstance(h, list) and h == b:
                out.append(a)
            elif isinstance(h, list):
                out.append(walk(h))
            else:
                out.append(h)
        return out

    return walk(tree)


def sed_tree_stutter(r: ErlRand, tree: list) -> list | None:
    """tr: repeat a parent->child path 2^rand(10)-ish times
    (erlamsa_mutations.erl:973-1022), memory-capped like the reference's
    256MB guard."""
    subs = sublists(tree)
    rand_subs = r.random_permutation(subs)
    parent = child = None
    for h in rand_subs:
        csubs = sublists(h)
        if csubs:
            parent, child = h, r.rand_elem(csubs)
            break
    n_reps = r.rand_log(10)
    if parent is None:
        return None

    # repeat_path unrolled iteratively (the reference recurses and guards on
    # process heap; Python's stack can't take n_reps levels). A flattened-
    # bytes budget stands in for the reference's 256MB heap cap.
    budget = 4 * 1024 * 1024
    parent_size = len(flatten_tree(parent))
    acc = parent
    for _ in range(max(n_reps - 1, 0)):
        if budget <= 0:
            break
        budget -= parent_size
        prev = acc
        acc = edit_sublist(parent, child, lambda s: [prev] + s[1:])

    return edit_sublist(tree, child, lambda s: [acc] + s[1:])
