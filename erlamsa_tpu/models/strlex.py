"""String lexer: bytes -> chunks of text / byte / delimited runs.

Reference: src/erlamsa_strlex.erl. A run of >= 6 "texty" bytes opens a text
chunk; quote characters open delimited chunks with backslash-escape
handling; everything else accumulates into byte chunks. unlex is the exact
inverse used after chunk-level mutation.

Chunks are tuples:
    ("text", list[int]) | ("byte", list[int]) |
    ("delimited", quote:int, list[int], quote:int)
"""

from __future__ import annotations

MIN_TEXTY = 6


def texty(b: int) -> bool:
    """Printable ASCII or tab/newline/CR (erlamsa_strlex.erl:45-52)."""
    if b < 9 or b > 126:
        return False
    if b > 31:
        return True
    return b in (9, 10, 13)


def _texty_enough(data: bytes, pos: int) -> bool:
    """At least MIN_TEXTY texty bytes ahead (or texty until end)
    (erlamsa_strlex.erl:54-64)."""
    for k in range(MIN_TEXTY):
        if pos + k >= len(data):
            return True  # short trailing runs count
        if not texty(data[pos + k]):
            return False
    return True


def lex(data: bytes) -> list[tuple]:
    """bytes -> chunk list (erlamsa_strlex.erl:74-142)."""
    chunks: list[tuple] = []
    i = 0
    n = len(data)
    raw: list[int] = []

    def flush_raw():
        nonlocal raw
        if raw:
            chunks.append(("byte", raw))
            raw = []

    while i < n:
        if not _texty_enough(data, i):
            raw.append(data[i])
            i += 1
            continue
        flush_raw()
        # text mode
        seen: list[int] = []
        while i < n:
            b = data[i]
            if b in (0x22, 0x27):  # " or '
                # delimited run; the opening quote is provisionally part of
                # the text until the closing quote is found
                quote = b
                j = i + 1
                after: list[int] = []
                closed = False
                while j < n:
                    c = data[j]
                    if c == quote:
                        closed = True
                        j += 1
                        break
                    if c == 0x5C:  # backslash escape
                        if j + 1 >= n:
                            after.append(0x5C)
                            j += 1
                            continue
                        nxt = data[j + 1]
                        if texty(nxt):
                            after.extend((0x5C, nxt))
                            j += 2
                            continue
                        after.append(0x5C)
                        j += 1
                        continue
                    if texty(c):
                        after.append(c)
                        j += 1
                        continue
                    break  # non-texty inside quotes: abandon delimited run
                if closed:
                    if seen:
                        chunks.append(("text", seen))
                        seen = []
                    chunks.append(("delimited", quote, after, quote))
                    i = j
                    continue
                # unterminated: quote + contents become text, resume scan
                seen = seen + [quote] + after
                i = j
                if i < n and not texty(data[i]):
                    break
                continue
            if texty(b):
                seen.append(b)
                i += 1
                continue
            break
        if seen:
            chunks.append(("text", seen))
    flush_raw()
    return chunks


def unlex(chunks: list[tuple]) -> bytes:
    """Chunk list -> bytes (erlamsa_strlex.erl:145-156)."""
    out = bytearray()
    for c in chunks:
        if c[0] == "delimited":
            _, l, body, rr = c
            out.append(l)
            out.extend(_flatten(body))
            out.append(rr)
        else:
            out.extend(_flatten(c[1]))
    return bytes(out)


def _flatten(x) -> bytes:
    """Tolerate nested int/str/bytes lists produced by text mutators."""
    if isinstance(x, (bytes, bytearray)):
        return bytes(x)
    if isinstance(x, int):
        return bytes([x & 0xFF])
    if isinstance(x, str):
        return x.encode("latin-1", "replace")
    out = bytearray()
    for e in x:
        out.extend(_flatten(e))
    return bytes(out)
