"""String lexer: bytes -> chunks of text / byte / delimited runs.

Reference: src/erlamsa_strlex.erl. A run of >= 6 "texty" bytes opens a text
chunk; quote characters open delimited chunks with backslash-escape
handling; everything else accumulates into byte chunks. unlex is the exact
inverse used after chunk-level mutation.

Chunks are tuples:
    ("text", list[int]) | ("byte", list[int]) |
    ("delimited", quote:int, list[int], quote:int)

The scan is run-jumped rather than per-byte (this was an oracle-wide
hotspot: every host-routed case lexes its data at least once): texty and
"6-texty-ahead" predicates are numpy masks computed once, and the byte /
plain-text / inside-quote loops advance by whole runs via searchsorted
jumps into precomputed boundary-position arrays. Only quote handling
(rare) stays scalar. Chunk contents are unchanged (lists of ints), and
tests lock the chunking against the per-byte semantics.
"""

from __future__ import annotations

import numpy as np

MIN_TEXTY = 6


def texty(b: int) -> bool:
    """Printable ASCII or tab/newline/CR (erlamsa_strlex.erl:45-52)."""
    if b < 9 or b > 126:
        return False
    if b > 31:
        return True
    return b in (9, 10, 13)


def _texty_enough(data: bytes, pos: int) -> bool:
    """At least MIN_TEXTY texty bytes ahead (or texty until end)
    (erlamsa_strlex.erl:54-64)."""
    for k in range(MIN_TEXTY):
        if pos + k >= len(data):
            return True  # short trailing runs count
        if not texty(data[pos + k]):
            return False
    return True


def lex(data: bytes) -> list[tuple]:
    """bytes -> chunk list (erlamsa_strlex.erl:74-142)."""
    chunks: list[tuple] = []
    i = 0
    n = len(data)
    if n == 0:
        return chunks

    d = np.frombuffer(data, dtype=np.uint8)
    tm = ((d >= 32) & (d <= 126)) | (d == 9) | (d == 10) | (d == 13)
    # enough[i] == _texty_enough(data, i): a short all-texty tail counts,
    # so the window slides over a 6-True pad
    pad = np.concatenate([tm, np.ones(MIN_TEXTY, bool)])
    c = np.concatenate([[0], np.cumsum(pad)])
    enough = (c[MIN_TEXTY:] - c[:-MIN_TEXTY])[:n] == MIN_TEXTY
    enough_pos = np.flatnonzero(enough)
    isquote = (d == 0x22) | (d == 0x27)
    # plain-text runs stop at a non-texty byte or a quote
    textstop_pos = np.flatnonzero(~tm | isquote)
    # inside quotes, scalar handling is needed at quotes, backslashes and
    # non-texty bytes; everything between advances in one jump
    special_pos = np.flatnonzero(~tm | isquote | (d == 0x5C))

    def jump(positions: np.ndarray, frm: int) -> int:
        k = np.searchsorted(positions, frm, side="left")
        return int(positions[k]) if k < len(positions) else n

    raw: list[int] = []

    def flush_raw():
        nonlocal raw
        if raw:
            chunks.append(("byte", raw))
            raw = []

    while i < n:
        if not enough[i]:
            # whole run of not-enough positions becomes byte chunk content
            j = jump(enough_pos, i)
            raw.extend(data[i:j])
            i = j
            continue
        flush_raw()
        # text mode
        seen: list[int] = []
        while i < n:
            b = data[i]
            if b in (0x22, 0x27):  # " or '
                # delimited run; the opening quote is provisionally part of
                # the text until the closing quote is found
                quote = b
                j = i + 1
                after: list[int] = []
                closed = False
                while j < n:
                    c_ = data[j]
                    if c_ == quote:
                        closed = True
                        j += 1
                        break
                    if c_ == 0x5C:  # backslash escape
                        if j + 1 >= n:
                            after.append(0x5C)
                            j += 1
                            continue
                        nxt = data[j + 1]
                        if texty(nxt):
                            after.extend((0x5C, nxt))
                            j += 2
                            continue
                        after.append(0x5C)
                        j += 1
                        continue
                    if tm[j]:
                        # data[j] is texty and neither the delimiter nor a
                        # backslash — but it may be the OTHER quote char
                        # (itself in special_pos), which the scan simply
                        # consumes; so always take data[j] and jump from
                        # j+1 to the next special byte
                        k = jump(special_pos, j + 1)
                        after.extend(data[j:k])
                        j = k
                        continue
                    break  # non-texty inside quotes: abandon delimited run
                if closed:
                    if seen:
                        chunks.append(("text", seen))
                        seen = []
                    chunks.append(("delimited", quote, after, quote))
                    i = j
                    continue
                # unterminated: quote + contents become text, resume scan
                seen = seen + [quote] + after
                i = j
                if i < n and not tm[i]:
                    break
                continue
            if tm[i]:
                # run of plain texty bytes up to the next quote/non-texty;
                # i itself is texty and not a quote, so the stop is > i
                j = jump(textstop_pos, i)
                seen.extend(data[i:j])
                i = j
                continue
            break
        if seen:
            chunks.append(("text", seen))
    flush_raw()
    return chunks


def unlex(chunks: list[tuple]) -> bytes:
    """Chunk list -> bytes (erlamsa_strlex.erl:145-156)."""
    out = bytearray()
    for c in chunks:
        if c[0] == "delimited":
            _, l, body, rr = c
            out.append(l)
            _flatten_into(out, body)
            out.append(rr)
        else:
            _flatten_into(out, c[1])
    return bytes(out)


def _flatten_into(out: bytearray, x) -> None:
    """Flatten nested int/str/bytes lists into out — int elements (the
    overwhelming majority) append without a recursive call each."""
    if isinstance(x, (bytes, bytearray)):
        out.extend(x)
        return
    if isinstance(x, int):
        out.append(x & 0xFF)
        return
    if isinstance(x, str):
        out.extend(x.encode("latin-1", "replace"))
        return
    if isinstance(x, (list, tuple)):
        # C fast path: a flat list of in-range ints (the overwhelming
        # case) converts in one call. Guarded to sequences — a one-shot
        # iterator would be partially consumed by a failed bytes() and
        # the fallback loop below would drop its leading elements.
        try:
            out.extend(bytes(x))
            return
        except (TypeError, ValueError):
            pass
    for e in x:
        if isinstance(e, int):
            out.append(e & 0xFF)
        else:
            _flatten_into(out, e)


def _flatten(x) -> bytes:
    """Tolerate nested int/str/bytes lists produced by text mutators."""
    out = bytearray()
    _flatten_into(out, x)
    return bytes(out)
