"""Format-aware engines ("model families"): lexer, parse-tree, JSON, SGML,
fuse, URI, base64, length-field/checksum, ZIP, genfuzz grammar.

These run host-side in both modes (the reference also treats them as the
structured tail of the mutator distribution, SURVEY.md §7 phase 3); the
batch path routes samples to them via the hybrid dispatcher.
"""
