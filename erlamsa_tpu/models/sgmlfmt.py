"""SGML/XML/HTML format engine: fault-tolerant parse, tree mutations,
XML-feature injections, fold back to bytes.

Reference: src/erlamsa_sgml.erl — a binary-pattern tokenizer (tz/2), an AST
builder that tolerates unclosed and mismatched tags (build_ast2), and
mutators: swap/dup/pump/repeat/insert nodes, permute or mutate attributes,
break a tag, inject XXE / billion-laughs / xmlns-SSRF features
(sgml_xmlfeatures :627-665), and inner-text mutation of text nodes and
attribute values (try_mutate_innertext :683-693).

Tokens / nodes:
    ("text", bytes)
    ("decl", bytes)              <! ... > and <? ... ?> passthrough blobs
    ("tag", name, attrs, children, closed)   attrs = list[(bytes, bytes|None)]
unparsed close tags with no open partner become text, like the reference's
fault tolerance.
"""

from __future__ import annotations

from ..utils.erlrand import ErlRand

_NAME_END = frozenset(b" \t\r\n/>")


def _parse_attrs(chunk: bytes) -> list[tuple[bytes, bytes | None]]:
    attrs = []
    i, n = 0, len(chunk)
    while i < n:
        while i < n and chunk[i] in b" \t\r\n":
            i += 1
        if i >= n:
            break
        ks = i
        while i < n and chunk[i] not in b" \t\r\n=":
            i += 1
        key = chunk[ks:i]
        if not key:
            break
        while i < n and chunk[i] in b" \t\r\n":
            i += 1
        if i < n and chunk[i] == 0x3D:  # =
            i += 1
            while i < n and chunk[i] in b" \t\r\n":
                i += 1
            if i < n and chunk[i] in b"\"'":
                q = chunk[i]
                i += 1
                vs = i
                while i < n and chunk[i] != q:
                    i += 1
                attrs.append((key, chunk[vs:i]))
                i += 1
            else:
                vs = i
                while i < n and chunk[i] not in b" \t\r\n":
                    i += 1
                attrs.append((key, chunk[vs:i]))
        else:
            attrs.append((key, None))
    return attrs


def tokenize(data: bytes) -> list[tuple]:
    """bytes -> flat token stream (erlamsa_sgml.erl:100-164 behavior)."""
    toks: list[tuple] = []
    i, n = 0, len(data)
    text_start = 0
    while i < n:
        if data[i] != 0x3C:  # <
            # jump straight to the next tag opener (C-level find); the
            # skipped run is plain text emitted at the next boundary
            nxt = data.find(b"<", i)
            i = n if nxt < 0 else nxt
            continue
        if i > text_start:
            toks.append(("text", data[text_start:i]))
        if data[i + 1 : i + 4] == b"!--":
            end = data.find(b"-->", i + 4)
            end = n if end < 0 else end + 3
            toks.append(("decl", data[i:end]))
            i = text_start = end
            continue
        if i + 1 < n and data[i + 1] in b"!?":
            close = b"?>" if data[i + 1] == 0x3F else b">"
            end = data.find(close, i + 2)
            end = n if end < 0 else end + len(close)
            toks.append(("decl", data[i:end]))
            i = text_start = end
            continue
        end = data.find(b">", i + 1)
        if end < 0:
            # unterminated tag: trailing text, like the reference's tolerance
            toks.append(("text", data[i:]))
            i = text_start = n
            break
        inner = data[i + 1 : end]
        if inner.startswith(b"/"):
            toks.append(("close", inner[1:].strip()))
        else:
            selfclosed = inner.endswith(b"/")
            if selfclosed:
                inner = inner[:-1]
            # name = up to first whitespace
            j = 0
            while j < len(inner) and inner[j] not in _NAME_END:
                j += 1
            name = inner[:j]
            attrs = _parse_attrs(inner[j:])
            toks.append(("open", name, attrs, selfclosed))
        i = text_start = end + 1
    if i > text_start:
        toks.append(("text", data[text_start:i]))
    return toks


def build_ast(toks: list[tuple]) -> list:
    """Token stream -> forest, tolerant of mismatches
    (erlamsa_sgml.erl:204-279): a close tag pops up to its matching open if
    one exists anywhere on the stack; otherwise it becomes text."""
    root: list = []
    stack: list[tuple] = []  # (name, attrs, children_list)
    cur = root
    for t in toks:
        if t[0] in ("text", "decl"):
            cur.append(t)
        elif t[0] == "open":
            _, name, attrs, selfclosed = t
            node = ["tag", name, attrs, [], selfclosed]
            cur.append(node)
            if not selfclosed:
                stack.append(node)
                cur = node[3]
        else:  # close
            name = t[1]
            match = None
            for k in range(len(stack) - 1, -1, -1):
                if stack[k][1] == name:
                    match = k
                    break
            if match is None:
                cur.append(("text", b"</" + name + b">"))
                continue
            # everything above the match stays as (implicitly closed) children
            del stack[match:]
            cur = stack[-1][3] if stack else root
    return root


def serialize(forest: list) -> bytes:
    out = bytearray()
    _ser_forest(forest, out)
    return bytes(out)


def _ser_forest(forest: list, out: bytearray):
    for node in forest:
        if isinstance(node, tuple):
            out.extend(node[1])
        else:
            _, name, attrs, children, selfclosed = node
            out.append(0x3C)
            out.extend(name)
            for k, v in attrs:
                out.append(0x20)
                out.extend(k)
                if v is not None:
                    out.extend(b'="')
                    out.extend(v)
                    out.append(0x22)
            if selfclosed:
                out.extend(b"/>")
            else:
                out.append(0x3E)
                _ser_forest(children, out)
                out.extend(b"</")
                out.extend(name)
                out.append(0x3E)


def parse(data: bytes) -> list:
    return build_ast(tokenize(data))


def _tag_nodes(forest: list) -> list:
    out = []
    for node in forest:
        if isinstance(node, list):
            out.append(node)
            out.extend(_tag_nodes(node[3]))
    return out


def _clone(node):
    if isinstance(node, tuple):
        return node
    return [node[0], node[1], list(node[2]), [_clone(c) for c in node[3]], node[4]]


# --- XML feature injections (erlamsa_sgml.erl:627-665) --------------------


def _xxe_decl(ssrf_uri: bytes) -> bytes:
    return (
        b'<!DOCTYPE foo [ <!ENTITY xxe SYSTEM "file:///etc/passwd"> '
        b'<!ENTITY ssrf SYSTEM "http' + ssrf_uri + b'"> ]>'
    )


def _billion_laughs() -> bytes:
    ents = [b'<!ENTITY a0 "lol">']
    for k in range(1, 6):
        prev = b"&a%d;" % (k - 1)
        ents.append(b'<!ENTITY a%d "%s">' % (k, prev * 8))
    return b"<!DOCTYPE bomb [ " + b" ".join(ents) + b" ]>"


def sgml_xmlfeatures(r: ErlRand, forest: list, ssrf_uri: bytes) -> list:
    """Prepend a hostile prolog / inject xmlns SSRF."""
    choice = r.rand(3)
    if choice == 0:
        return [("decl", _xxe_decl(ssrf_uri)), ("text", b"&xxe;&ssrf;")] + forest
    if choice == 1:
        return [("decl", _billion_laughs()), ("text", b"&a5;")] + forest
    tags = _tag_nodes(forest)
    if tags:
        tag = r.rand_elem(tags)
        tag[2] = list(tag[2]) + [(b"xmlns:ssrf", b"http" + ssrf_uri)]
    return forest


# --- mutations ------------------------------------------------------------


def sgml_mutate(
    r: ErlRand, data: bytes, inner_bytes_mutator, ssrf_uri: bytes = b"://localhost:51234/"
) -> tuple[bytes, str, int]:
    """sgm: one random tree mutation (erlamsa_sgml.erl:739-766 behavior).
    Returns (mutated, op_name, delta); delta -1 when no tags parse."""
    forest = parse(data)
    tags = _tag_nodes(forest)
    if not tags:
        return data, "sgml_no_tags", -1

    op = r.rand(9)
    if op == 0 and len(tags) >= 2:  # swap two tags' payloads
        a, b = r.rand_elem(tags), r.rand_elem(tags)
        a[1], b[1] = b[1], a[1]
        a[2], b[2] = b[2], a[2]
        return serialize(forest), "sgml_swap", 1
    if op == 1:  # dup a node in place
        tag = r.rand_elem(tags)
        tag[3] = tag[3] + [_clone(c) for c in tag[3]]
        return serialize(forest), "sgml_dup", 1
    if op == 2:  # pump: nest a clone of a tag inside itself (size-capped —
        # repeated pumps across nd/bu rounds otherwise explode the tree,
        # cf. the reference's 256MB heap guard on tree stutter)
        tag = r.rand_elem(tags)
        if len(serialize([tag])) >= 1 << 20:
            # capped: report a failed try (unchanged data, noop delta) so
            # the mux doesn't reward a no-op
            return data, "sgml_pump_capped", -1
        tag[3] = tag[3] + [_clone(tag)]
        return serialize(forest), "sgml_pump", 1
    if op == 3:  # repeat a tag up to 100x at top level
        tag = r.rand_elem(tags)
        reps = r.erand(100)
        forest = forest + [_clone(tag) for _ in range(reps)]
        return serialize(forest), "sgml_repeat", 1
    if op == 4:  # permute attributes
        tag = r.rand_elem(tags)
        if len(tag[2]) >= 2:
            tag[2] = r.random_permutation(tag[2])
        return serialize(forest), "sgml_permparams", 1
    if op == 5:  # break a tag: drop its closing delimiter
        tag = r.rand_elem(tags)
        raw = serialize([tag])
        broken = raw.replace(b">", b"", 1)
        return serialize(forest).replace(raw, broken, 1), "sgml_breaktag", 1
    if op == 6:  # XML features: XXE / billion laughs / xmlns SSRF
        forest = sgml_xmlfeatures(r, forest, ssrf_uri)
        return serialize(forest), "sgml_xmlfeatures", 1
    if op == 7:  # mutate an attribute value byte-level
        cands = [t for t in tags if any(v is not None for _, v in t[2])]
        if cands:
            tag = r.rand_elem(cands)
            idxs = [i for i, (_, v) in enumerate(tag[2]) if v is not None]
            i = idxs[r.rand(len(idxs))]
            k, v = tag[2][i]
            tag[2][i] = (k, bytes(inner_bytes_mutator(v)))
            return serialize(forest), "sgml_attr_innertext", 1
    # default: inner-text mutation of a random text node
    texts = _text_refs(forest)
    if texts:
        holder, idx = texts[r.rand(len(texts))]
        holder[idx] = ("text", bytes(inner_bytes_mutator(holder[idx][1])))
        return serialize(forest), "sgml_innertext", 1
    return serialize(forest), "sgml_noop", 1


def _text_refs(forest: list) -> list[tuple[list, int]]:
    """(container, index) for every text node so it can be replaced in place."""
    out = []
    for i, node in enumerate(forest):
        if isinstance(node, tuple) and node[0] == "text":
            out.append((forest, i))
        elif isinstance(node, list):
            out.extend(_text_refs(node[3]))
    return out
