"""Fuse: jump between shared suffix positions of two byte sequences.

Reference: src/erlamsa_fuse.erl (radamsa's "fuse"). The algorithm walks a
lazily-built generalized suffix structure: nodes pair source-suffix sets
with target-suffix sets sharing a prefix; each round either stops (prob
1/8 or fuel exhausted) and picks a random (from, to) suffix pair, or
refines every node one shared character deeper.

The oracle keeps the reference's draw order (stop check, then element
picks) so AS183 streams align. Suffixes are represented as integer offsets
into the two buffers instead of linked lists — same walk, O(1) memory per
suffix.
"""

from __future__ import annotations

from ..utils.erlrand import ErlRand

SEARCH_FUEL = 100_000
SEARCH_STOP_IP = 8


def _char_suffixes(buf: bytes, sufs: list[int]) -> dict[int, list[int]]:
    """Group suffix offsets by first byte; each advances one position.
    Empty suffixes (offset == len) are skipped; a bucket holding only one
    exhausted suffix collapses to [] via the reference's fix_empty_list
    (erlamsa_fuse.erl:57-70). Buckets build by prepending, so they end up
    reversed relative to the input walk."""
    n = len(buf)
    subs: dict[int, list[int]] = {}
    for off in sufs:
        # empty suffixes: offset == n, or the [] marker from a degenerate
        # node — both hit the reference's ([], Subs) -> Subs skip clause
        if not isinstance(off, int) or off >= n:
            continue
        bucket = [off + 1] + subs.get(buf[off], [])
        if bucket == [n]:
            bucket = []  # fix_empty_list([[]]) -> []
        subs[buf[off]] = bucket
    return subs


def _any_position_pair(r: ErlRand, buf_a: bytes, buf_b: bytes, nodes) -> tuple[int, int]:
    """Pick a random node, then a random source and target suffix
    (erlamsa_fuse.erl:72-77). rand_elem([]) yields the empty suffix without
    a draw (erlamsa_rnd:rand_elem clause for [])."""
    froms, tos = r.rand_elem(nodes)
    frm = r.rand_elem(froms) if froms else []
    to = r.rand_elem(tos) if tos else []
    frm = frm if isinstance(frm, int) else len(buf_a)
    to = to if isinstance(to, int) else len(buf_b)
    return frm, to


def find_jump_points(r: ErlRand, a: bytes, b: bytes) -> tuple[int, int]:
    """Walk shared-prefix refinements until the stop draw fires
    (erlamsa_fuse.erl:102-128). Returns byte offsets (from_a, to_b)."""
    # suffixes(X) excludes the empty suffix (erlamsa_fuse.erl:52-55)
    nodes: list[tuple[list, list]] = [
        (list(range(len(a))), list(range(len(b))))
    ]
    fuel = SEARCH_FUEL
    while True:
        if fuel < 0:
            return _any_position_pair(r, a, b, nodes)
        if r.rand(SEARCH_STOP_IP) == 0:
            return _any_position_pair(r, a, b, nodes)
        refined: list[tuple[list, list]] = []
        for froms, tos in nodes:
            sas = _char_suffixes(a, froms)
            sbs = _char_suffixes(b, tos)
            # gb_trees:to_list iterates in ascending key order
            for ch in sorted(sas):
                asufs = sas[ch]
                if asufs == []:
                    # collapsed bucket: the reference pushes a degenerate
                    # node #([[]], []) unconditionally (erlamsa_fuse.erl:90-92)
                    refined.insert(0, ([[]], []))
                    continue
                bsufs = sbs.get(ch)
                if bsufs is not None:
                    refined.insert(0, (asufs, bsufs))
        if not refined:
            return _any_position_pair(r, a, b, nodes)
        nodes = refined
        fuel -= len(refined)


def fuse(r: ErlRand, a: bytes, b: bytes) -> bytes:
    """a[:from] ++ b[to:] via a shared-prefix jump (erlamsa_fuse.erl:130-135)."""
    if not a:
        return b
    if not b:
        return a
    frm, to = find_jump_points(r, a, b)
    return a[:frm] + b[to:]
