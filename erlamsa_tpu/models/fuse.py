"""Fuse: jump between shared suffix positions of two byte sequences.

Reference: src/erlamsa_fuse.erl (radamsa's "fuse"). The algorithm walks a
lazily-built generalized suffix structure: nodes pair source-suffix sets
with target-suffix sets sharing a prefix; each round either stops (prob
1/8 or fuel exhausted) and picks a random (from, to) suffix pair, or
refines every node one shared character deeper.

The oracle keeps the reference's draw order (stop check, then element
picks) so AS183 streams align. Suffixes are represented as integer offsets
into the two buffers instead of linked lists — same walk, O(1) memory per
suffix.
"""

from __future__ import annotations

import numpy as np

from ..utils.erlrand import ErlRand

SEARCH_FUEL = 100_000
SEARCH_STOP_IP = 8


def _char_suffixes(buf: bytes, sufs: list[int]) -> dict[int, list[int]]:
    """Group suffix offsets by first byte; each advances one position.
    Empty suffixes (offset == len) are skipped; a bucket holding only one
    exhausted suffix collapses to [] via the reference's fix_empty_list
    (erlamsa_fuse.erl:57-70). Buckets build by prepending, so they end up
    reversed relative to the input walk."""
    n = len(buf)
    subs: dict[int, list[int]] = {}
    for off in sufs:
        # empty suffixes: offset == n, or the [] marker from a degenerate
        # node — both hit the reference's ([], Subs) -> Subs skip clause
        if not isinstance(off, int) or off >= n:
            continue
        bucket = [off + 1] + subs.get(buf[off], [])
        if bucket == [n]:
            bucket = []  # fix_empty_list([[]]) -> []
        subs[buf[off]] = bucket
    return subs


def _any_position_pair(r: ErlRand, buf_a: bytes, buf_b: bytes, nodes) -> tuple[int, int]:
    """Pick a random node, then a random source and target suffix
    (erlamsa_fuse.erl:72-77). rand_elem([]) yields the empty suffix without
    a draw (erlamsa_rnd:rand_elem clause for []). Nodes hold offset arrays;
    the empty-suffix marker is the offset len(buf) itself (same value the
    marker mapped to), so tolist() keeps draw counts and results exact."""
    froms, tos = r.rand_elem(nodes)
    frm = r.rand_elem(list(map(int, froms))) if len(froms) else []
    to = r.rand_elem(list(map(int, tos))) if len(tos) else []
    frm = frm if isinstance(frm, int) else len(buf_a)
    to = to if isinstance(to, int) else len(buf_b)
    return frm, to


def _round_buckets_flat(buf_arr: np.ndarray, n: int, parts):
    """One round's bucketing for EVERY node at once, kept FLAT: returns
    (uk, so1, starts, bounds) where uk is the ascending unique
    node_id*256 + ch keys (the reference's per-node gb_trees ascending
    walk), so1 holds every advanced offset (+1) in key-sorted walk order,
    and bucket g is the view so1[starts[g]:bounds[g]][::-1] — the
    reference's prepend order — with the fix_empty_list marker adjustment
    already applied to starts. Returning views instead of a dict of
    per-bucket copies is the difference between ~3 numpy slices per
    bucket and a python build loop that dominated oracle profiles."""
    sizes = np.fromiter((p.size for p in parts), np.int64, len(parts))
    total = int(sizes.sum())
    empty = np.asarray([], np.int64)
    if total == 0:
        return empty, empty, empty, empty
    offs = np.concatenate(parts)
    ids = np.repeat(np.arange(len(parts), dtype=np.int64), sizes)
    m = offs < n
    offs, ids = offs[m], ids[m]
    if offs.size == 0:
        return empty, empty, empty, empty
    keys = ids * 256 + buf_arr[offs].astype(np.int64)
    order = np.argsort(keys, kind="stable")
    sk = keys[order]
    so = offs[order]
    new_grp = np.empty(len(sk), bool)
    new_grp[0] = True
    np.not_equal(sk[1:], sk[:-1], out=new_grp[1:])
    starts = np.flatnonzero(new_grp)
    uk = sk[starts]
    bounds = np.append(starts[1:], len(sk))
    # fix_empty_list fires AT INSERT time: the exhausted suffix
    # (offset n-1 -> marker n) is discarded iff it is the FIRST walked
    # element of its bucket ([n] collapses to [], and later inserts start
    # from the emptied bucket); a marker walked into a non-empty bucket
    # is kept (erlamsa_fuse.erl:57-70)
    starts = starts + (so[starts] == n - 1)
    return uk, so + 1, starts, bounds


def find_jump_points(r: ErlRand, a: bytes, b: bytes) -> tuple[int, int]:
    """Walk shared-prefix refinements until the stop draw fires
    (erlamsa_fuse.erl:102-128). Returns byte offsets (from_a, to_b).

    Vectorized over the reference walk (this was the oracle's #2 hotspot:
    per-suffix dict prepends over every node every round). Each round is
    ONE grouped argsort per side — node count no longer matters. Bucket
    contents and refinement order reproduce the scalar walk element-for-
    element; tests lock both the draw stream and the results."""
    na, nb = len(a), len(b)
    arr_a = np.frombuffer(a, dtype=np.uint8)
    arr_b = np.frombuffer(b, dtype=np.uint8)
    # suffixes(X) excludes the empty suffix (erlamsa_fuse.erl:52-55)
    nodes = [(np.arange(na, dtype=np.int64), np.arange(nb, dtype=np.int64))]
    sent_a = np.asarray([na], np.int64)  # the degenerate node's [[]]
    empty = np.asarray([], np.int64)
    fuel = SEARCH_FUEL
    while True:
        if fuel < 0:
            return _any_position_pair(r, a, b, nodes)
        if r.rand(SEARCH_STOP_IP) == 0:
            return _any_position_pair(r, a, b, nodes)
        uka, soa, sa_, ba_ = _round_buckets_flat(arr_a, na, [f for f, _ in nodes])
        ukb, sob, sb_, bb_ = _round_buckets_flat(arr_b, nb, [t for _, t in nodes])
        # b-side lookup by key: searchsorted over ascending uniques
        # replaces per-bucket dict inserts for the whole b side
        pos_b = np.searchsorted(ukb, uka)
        safe = np.minimum(pos_b, max(len(ukb) - 1, 0))
        has_b = (pos_b < len(ukb)) & (len(ukb) > 0)
        if len(ukb):
            has_b &= ukb[safe] == uka
        acc: list[tuple[np.ndarray, np.ndarray]] = []
        # python ints up front: indexing numpy scalars inside the loop
        # costs more than the loop body itself
        sal, bal = sa_.tolist(), ba_.tolist()
        sbl, bbl = sb_.tolist(), bb_.tolist()
        hbl, pbl = has_b.tolist(), pos_b.tolist()
        # uka ascending == the per-node gb_trees ascending (node, ch) walk
        for g in range(len(sal)):
            s0, e0 = sal[g], bal[g]
            if s0 == e0:
                # collapsed bucket: the reference pushes a degenerate
                # node #([[]], []) unconditionally (erlamsa_fuse.erl:90-92)
                acc.append((sent_a, empty))
                continue
            if not hbl[g]:
                continue
            gb_ = pbl[g]
            acc.append((soa[s0:e0][::-1], sob[sbl[gb_]:bbl[gb_]][::-1]))
        if not acc:
            return _any_position_pair(r, a, b, nodes)
        # the reference insert(0)s every node: final order is reversed
        nodes = acc[::-1]
        fuel -= len(acc)


def fuse(r: ErlRand, a: bytes, b: bytes) -> bytes:
    """a[:from] ++ b[to:] via a shared-prefix jump (erlamsa_fuse.erl:130-135)."""
    if not a:
        return b
    if not b:
        return a
    frm, to = find_jump_points(r, a, b)
    return a[:frm] + b[to:]
