"""Fuse: jump between shared suffix positions of two byte sequences.

Reference: src/erlamsa_fuse.erl (radamsa's "fuse"). The algorithm walks a
lazily-built generalized suffix structure: nodes pair source-suffix sets
with target-suffix sets sharing a prefix; each round either stops (prob
1/8 or fuel exhausted) and picks a random (from, to) suffix pair, or
refines every node one shared character deeper.

The oracle keeps the reference's draw order (stop check, then element
picks) so AS183 streams align. Suffixes are represented as integer offsets
into the two buffers instead of linked lists — same walk, O(1) memory per
suffix.
"""

from __future__ import annotations

import numpy as np

from ..utils.erlrand import ErlRand

SEARCH_FUEL = 100_000
SEARCH_STOP_IP = 8


def _char_suffixes(buf: bytes, sufs: list[int]) -> dict[int, list[int]]:
    """Group suffix offsets by first byte; each advances one position.
    Empty suffixes (offset == len) are skipped; a bucket holding only one
    exhausted suffix collapses to [] via the reference's fix_empty_list
    (erlamsa_fuse.erl:57-70). Buckets build by prepending, so they end up
    reversed relative to the input walk."""
    n = len(buf)
    subs: dict[int, list[int]] = {}
    for off in sufs:
        # empty suffixes: offset == n, or the [] marker from a degenerate
        # node — both hit the reference's ([], Subs) -> Subs skip clause
        if not isinstance(off, int) or off >= n:
            continue
        bucket = [off + 1] + subs.get(buf[off], [])
        if bucket == [n]:
            bucket = []  # fix_empty_list([[]]) -> []
        subs[buf[off]] = bucket
    return subs


# NOTE: the scalar _any_position_pair and the per-round dict/view bucket
# builders were removed in r4 when find_jump_points went fully flat; the
# scalar walk lives on as the pinned reference implementation inside
# tests/test_fuse_vectorized.py (which also exercises _char_suffixes).


def _round_groups(buf_arr: np.ndarray, n: int, offs: np.ndarray,
                  sizes: np.ndarray):
    """Flat bucketing over the flat node state: returns
    (uk, so1, starts, bounds, adj) in key-sorted coordinates, where
    starts/bounds delimit groups PRE marker adjustment and adj[g] flags a
    group whose first walked element is the exhausted-suffix marker (the
    reference's fix_empty_list drops it at insert time,
    erlamsa_fuse.erl:57-70)."""
    empty = np.empty(0, np.int64)
    if offs.size == 0:
        return empty, empty, empty, empty, empty.astype(bool)
    ids = np.repeat(np.arange(len(sizes), dtype=np.int64), sizes)
    m = offs < n
    offs2, ids = offs[m], ids[m]
    if offs2.size == 0:
        return empty, empty, empty, empty, empty.astype(bool)
    keys = ids * 256 + buf_arr[offs2].astype(np.int64)
    order = np.argsort(keys, kind="stable")
    sk = keys[order]
    so = offs2[order]
    new_grp = np.empty(len(sk), bool)
    new_grp[0] = True
    np.not_equal(sk[1:], sk[:-1], out=new_grp[1:])
    starts = np.flatnonzero(new_grp)
    uk = sk[starts]
    bounds = np.append(starts[1:], len(sk))
    adj = so[starts] == n - 1
    return uk, so + 1, starts, bounds, adj


def find_jump_points(r: ErlRand, a: bytes, b: bytes) -> tuple[int, int]:
    """Walk shared-prefix refinements until the stop draw fires
    (erlamsa_fuse.erl:102-128). Returns byte offsets (from_a, to_b).

    Fully flat over the reference walk (this was the oracle's #1 hotspot
    twice over): the node list never materializes — the state between
    rounds is four arrays (per-side concatenated offsets + per-node
    sizes, in node order), and a round is one grouped argsort per side,
    one searchsorted key intersection, and mask/reverse/insert array ops.
    Node order (the reference's insert(0) reversal), within-bucket
    prepend order, fix_empty_list marker drops, and the degenerate
    #([[]], []) sentinel nodes all reproduce the scalar walk element for
    element; tests lock both the draw stream and the results against a
    scalar reference implementation."""
    na, nb = len(a), len(b)
    arr_a = np.frombuffer(a, dtype=np.uint8)
    arr_b = np.frombuffer(b, dtype=np.uint8)
    # suffixes(X) excludes the empty suffix (erlamsa_fuse.erl:52-55);
    # node state: offsets concatenated in node order + per-node sizes
    fa = np.arange(na, dtype=np.int64)
    fa_sizes = np.asarray([na], np.int64)
    fb = np.arange(nb, dtype=np.int64)
    fb_sizes = np.asarray([nb], np.int64)
    fuel = SEARCH_FUEL
    while True:
        if fuel < 0 or r.rand(SEARCH_STOP_IP) == 0:
            return _pick_flat(r, a, b, fa, fa_sizes, fb, fb_sizes)
        uka, soa1, sta, bda, adja = _round_groups(arr_a, na, fa, fa_sizes)
        if uka.size == 0:
            return _pick_flat(r, a, b, fa, fa_sizes, fb, fb_sizes)
        ukb, sob1, stb, bdb, adjb = _round_groups(arr_b, nb, fb, fb_sizes)
        pos_b = np.searchsorted(ukb, uka)
        if ukb.size:
            safe = np.minimum(pos_b, ukb.size - 1)
            has_b = (pos_b < ukb.size) & (ukb[safe] == uka)
        else:
            has_b = np.zeros(len(uka), bool)
        size_a = (bda - sta) - adja
        # collapsed bucket: the reference pushes a degenerate node
        # #([[]], []) unconditionally (erlamsa_fuse.erl:90-92)
        collapsed = size_a == 0
        kept = has_b & ~collapsed
        live = kept | collapsed
        if not live.any():
            return _pick_flat(r, a, b, fa, fa_sizes, fb, fb_sizes)

        # a side: drop markers and dead groups, splice a sentinel (the
        # value na == the empty-suffix marker) where a group collapsed,
        # then reverse — groups are contiguous, so one reversal yields
        # both the insert(0) node order and the per-bucket prepend order
        keep_elem = np.ones(len(soa1), bool)
        keep_elem[sta[adja]] = False
        dead = ~live
        if dead.any():
            delta = np.zeros(len(soa1) + 1, np.int64)
            np.add.at(delta, sta[dead], 1)
            np.add.at(delta, bda[dead], -1)
            keep_elem &= np.cumsum(delta[:-1]) == 0
        ea = soa1[keep_elem]
        if collapsed.any():
            csum_keep = np.concatenate([[0], np.cumsum(keep_elem)])
            ea = np.insert(ea, csum_keep[sta[collapsed]], na)
        fa = ea[::-1]
        fa_sizes = np.where(collapsed, 1, size_a)[live][::-1]

        # b side: elements of the groups matched by kept a-groups (key
        # ascent is shared, so relative order already agrees), markers
        # dropped; collapsed nodes contribute size-0 parts
        bsel = pos_b[kept]
        keep_b = np.zeros(len(sob1), bool)
        if bsel.size:
            delta = np.zeros(len(sob1) + 1, np.int64)
            np.add.at(delta, stb[bsel] + adjb[bsel], 1)
            np.add.at(delta, bdb[bsel], -1)
            keep_b = np.cumsum(delta[:-1]) > 0
        fb = sob1[keep_b][::-1]
        szb = np.zeros(len(uka), np.int64)
        szb[kept] = ((bdb - stb) - adjb)[bsel]
        fb_sizes = szb[live][::-1]

        fuel -= int(live.sum())


def _pick_flat(r: ErlRand, buf_a: bytes, buf_b: bytes,
               fa, fa_sizes, fb, fb_sizes) -> tuple[int, int]:
    """_any_position_pair over the flat node state: same three draws
    (node, from-suffix, to-suffix) in the same order."""
    count = len(fa_sizes)
    idx = r.uniform_n(count) - 1  # rand_elem over the node list
    ba_ = np.concatenate([[0], np.cumsum(fa_sizes)])
    bb_ = np.concatenate([[0], np.cumsum(fb_sizes)])
    froms = fa[ba_[idx]:ba_[idx + 1]]
    tos = fb[bb_[idx]:bb_[idx + 1]]
    frm = r.rand_elem(list(map(int, froms))) if len(froms) else []
    to = r.rand_elem(list(map(int, tos))) if len(tos) else []
    frm = frm if isinstance(frm, int) else len(buf_a)
    to = to if isinstance(to, int) else len(buf_b)
    return frm, to


def fuse(r: ErlRand, a: bytes, b: bytes) -> bytes:
    """a[:from] ++ b[to:] via a shared-prefix jump (erlamsa_fuse.erl:130-135)."""
    if not a:
        return b
    if not b:
        return a
    frm, to = find_jump_points(r, a, b)
    return a[:frm] + b[to:]
