"""JSON format engine: tolerant parse, AST mutations, fold back to bytes.

Reference: src/erlamsa_json.erl — a hand-written RFC7159-ish tokenizer with
a context stack, AST walk/select helpers, and mutators: swap two nodes,
duplicate, pump (nest a node inside itself), repeat an element (<= 100x),
insert unserialization gadget payloads, and recurse a byte-level mutator
into string/number leaves (json_mutation, :646-708).

This implementation parses into span-preserving nodes so untouched regions
fold back byte-identically, which matters because fuzzing targets parse the
*raw* bytes.
"""

from __future__ import annotations

from ..utils.erlrand import ErlRand

WS = b" \t\r\n"


class JNode:
    """kind: obj | arr | str | num | lit; children only for obj/arr.
    raw holds the exact source bytes for leaves (and separators are
    reconstructed canonically on serialize)."""

    __slots__ = ("kind", "children", "raw", "key")

    def __init__(self, kind, children=None, raw=b"", key=None):
        self.kind = kind
        self.children = children if children is not None else []
        self.raw = raw
        self.key = key  # raw key bytes for object members

    def clone(self) -> "JNode":
        return JNode(
            self.kind,
            [c.clone() for c in self.children],
            self.raw,
            self.key,
        )


class _P:
    def __init__(self, data: bytes):
        self.d = data
        self.i = 0

    def ws(self):
        while self.i < len(self.d) and self.d[self.i] in WS:
            self.i += 1

    def peek(self) -> int:
        return self.d[self.i] if self.i < len(self.d) else -1


def _parse_string(p: _P) -> bytes | None:
    start = p.i
    if p.peek() != 0x22:
        return None
    p.i += 1
    while p.i < len(p.d):
        c = p.d[p.i]
        if c == 0x5C:
            p.i += 2
            continue
        p.i += 1
        if c == 0x22:
            return p.d[start : p.i]
    return None  # unterminated


_NUM_CHARS = frozenset(b"-+.eE0123456789")


def _parse_number(p: _P) -> bytes | None:
    start = p.i
    while p.i < len(p.d) and p.d[p.i] in _NUM_CHARS:
        p.i += 1
    return p.d[start : p.i] if p.i > start else None


def _parse_value(p: _P, depth: int = 0) -> JNode | None:
    if depth > 200:
        return None
    p.ws()
    c = p.peek()
    if c == 0x7B:  # {
        p.i += 1
        node = JNode("obj")
        p.ws()
        if p.peek() == 0x7D:
            p.i += 1
            return node
        while True:
            p.ws()
            key = _parse_string(p)
            if key is None:
                return None
            p.ws()
            if p.peek() != 0x3A:
                return None
            p.i += 1
            val = _parse_value(p, depth + 1)
            if val is None:
                return None
            val.key = key
            node.children.append(val)
            p.ws()
            if p.peek() == 0x2C:
                p.i += 1
                continue
            if p.peek() == 0x7D:
                p.i += 1
                return node
            return None
    if c == 0x5B:  # [
        p.i += 1
        node = JNode("arr")
        p.ws()
        if p.peek() == 0x5D:
            p.i += 1
            return node
        while True:
            val = _parse_value(p, depth + 1)
            if val is None:
                return None
            node.children.append(val)
            p.ws()
            if p.peek() == 0x2C:
                p.i += 1
                continue
            if p.peek() == 0x5D:
                p.i += 1
                return node
            return None
    if c == 0x22:
        raw = _parse_string(p)
        return JNode("str", raw=raw) if raw is not None else None
    for lit in (b"true", b"false", b"null"):
        if p.d[p.i : p.i + len(lit)] == lit:
            p.i += len(lit)
            return JNode("lit", raw=lit)
    raw = _parse_number(p)
    if raw is not None:
        return JNode("num", raw=raw)
    return None


def parse(data: bytes) -> JNode | None:
    """Tolerant top-level parse; None when the data isn't JSON-ish."""
    p = _P(data)
    node = _parse_value(p)
    if node is None:
        return None
    p.ws()
    if p.i != len(p.d):
        return None  # trailing garbage: not a clean JSON document
    return node


def serialize(node: JNode) -> bytes:
    out = bytearray()
    _ser(node, out, with_key=False)
    return bytes(out)


def _ser(node: JNode, out: bytearray, with_key: bool):
    if with_key and node.key is not None:
        out.extend(node.key)
        out.append(0x3A)
    if node.kind == "obj":
        out.append(0x7B)
        for i, c in enumerate(node.children):
            if i:
                out.append(0x2C)
            _ser(c, out, with_key=True)
        out.append(0x7D)
    elif node.kind == "arr":
        out.append(0x5B)
        for i, c in enumerate(node.children):
            if i:
                out.append(0x2C)
            _ser(c, out, with_key=False)
        out.append(0x5D)
    else:
        out.extend(node.raw)


def walk(node: JNode) -> list[JNode]:
    """All nodes, depth-first (erlamsa_json.erl:286-319)."""
    out = [node]
    for c in node.children:
        out.extend(walk(c))
    return out


# --- payloads (unserialize gadget probes, erlamsa_json.erl:617-625) -------

UNSERIALIZE_PAYLOADS = (
    # .NET ObjectDataProvider-style type-confusion probe
    b'{"$type":"System.Windows.Data.ObjectDataProvider, PresentationFramework",'
    b'"MethodName":"Start","ObjectInstance":{"$type":"System.Diagnostics.Process,'
    b' System"},"MethodParameters":{"$type":"System.Collections.ArrayList",'
    b'"$values":["calc.exe"]}}',
    # fastjson-style autotype probe
    b'{"@type":"com.sun.rowset.JdbcRowSetImpl","dataSourceName":'
    b'"ldap://localhost:51234/Exploit","autoCommit":true}',
    # generic prototype-pollution probe
    b'{"__proto__":{"polluted":"1"}}',
    b'{"$type":"System.IO.FileInfo, System.IO.FileSystem","fileName":"/etc/passwd"}',
)


# --- mutations ------------------------------------------------------------


def _mutate_tree(r: ErlRand, root: JNode, inner_bytes_mutator) -> tuple[JNode, str]:
    """One random tree mutation; returns (new_root, op_name).

    Op mix follows erlamsa_json:json_mutation (:646-708): node swap, dup,
    pump, repeat (<=100), payload insert, inner byte-level mutation of a
    leaf.
    """
    nodes = walk(root)
    op = r.rand(6)
    if op == 0 and len(nodes) >= 2:  # swap two nodes' contents
        a = r.rand_elem(nodes)
        b = r.rand_elem(nodes)
        a_copy = a.clone()
        b_copy = b.clone()
        _overwrite(a, b_copy)
        _overwrite(b, a_copy)
        return root, "json_swap"
    if op == 1:  # dup: duplicate a child inside its parent
        parents = [x for x in nodes if x.children]
        if parents:
            parent = r.rand_elem(parents)
            idx = r.rand(len(parent.children))
            parent.children.insert(idx, parent.children[idx].clone())
            return root, "json_dup"
    if op == 2:  # pump: nest a container inside itself (2x depth growth,
        # size-capped like the sgml pump so repeated rounds can't explode)
        conts = [x for x in nodes if x.kind in ("obj", "arr") and x.children]
        if conts:
            target = r.rand_elem(conts)
            if len(serialize(target)) >= 1 << 20:
                return root, "json_pump_capped"
            clone = target.clone()
            clone.key = None
            target.children.append(clone)
            return root, "json_pump"
    if op == 3:  # repeat an array element up to 100x
        arrs = [x for x in nodes if x.kind == "arr" and x.children]
        if arrs:
            arr = r.rand_elem(arrs)
            idx = r.rand(len(arr.children))
            reps = r.erand(100)
            elem = arr.children[idx]
            for _ in range(reps):
                arr.children.insert(idx, elem.clone())
            return root, "json_repeat"
    if op == 4:  # insert an unserialization payload as a value
        payload = parse(bytes(r.rand_elem(UNSERIALIZE_PAYLOADS)))
        if payload is not None and nodes:
            target = r.rand_elem(nodes)
            key = target.key
            _overwrite(target, payload)
            target.key = key
            return root, "json_unserialize"
    # inner byte-level mutation of a string/number leaf
    leaves = [x for x in nodes if x.kind in ("str", "num")]
    if leaves:
        leaf = r.rand_elem(leaves)
        leaf.raw = bytes(inner_bytes_mutator(leaf.raw))
        return root, "json_innertext"
    return root, "json_noop"


def _overwrite(dst: JNode, src: JNode):
    dst.kind = src.kind
    dst.children = src.children
    dst.raw = src.raw
    # key stays: object membership is positional


def json_mutate(r: ErlRand, data: bytes, inner_bytes_mutator) -> tuple[bytes, str, int]:
    """js: returns (mutated, op_name, delta). delta -1 when not JSON
    (erlamsa_json.erl:710-730)."""
    root = parse(data)
    if root is None:
        return data, "json_not_json", -1
    root, op = _mutate_tree(r, root, inner_bytes_mutator)
    if op.endswith("_capped"):
        # suppressed mutation: return the ORIGINAL bytes with a failure
        # delta so the mux retries instead of rewarding a no-op (serialize
        # could still normalize whitespace and read as a change)
        return data, op, -1
    return serialize(root), op, 1
