"""TPU batch runner: corpus -> padded device batches -> mutation -> outputs.

The throughput path (SURVEY.md §7 phase 1): pack seed files into
``uint8[B, L]`` buffers, run the jitted fuzz_batch per case with
counter-derived keys, and stream results to the output writer.

Pipelined: case c+1's device steps dispatch (async) BEFORE case c's
results are unpacked/written, so host IO and host-routed oracle work
overlap device compute. Determinism is preserved by construction: the
split for case c uses device scores through c-1 (a tiny forced sync) and
host outcome scores through c-2, and checkpoints record exactly those
states so a resumed run routes identically.
"""

from __future__ import annotations

import sys
import time

import numpy as np

from . import logger, out


def _load_corpus(paths: list[str], recursive: bool,
                 direct: list[bytes] | None = None,
                 store_dir: str | None = None) -> list[bytes]:
    from ..oracle.gen import _expand_paths

    if direct is not None and store_dir is None:
        # in-process callers (bench full-set stage, tests) hand the corpus
        # over directly instead of staging files
        return list(direct)
    if store_dir is not None:
        # --corpus: dedup everything through the persistent store and run
        # over the deduped seed set, in store insertion order
        from ..corpus.store import CorpusStore

        store = CorpusStore(store_dir)
        for s in direct or []:
            store.add(s, origin="direct")
        in_paths = [p for p in paths if p != "-"]
        if in_paths:
            new, dup, skipped = store.add_paths(
                _expand_paths(in_paths) if recursive else in_paths
            )
            print(f"# corpus: {new} new, {dup} duplicate, {skipped} "
                  f"skipped -> {len(store)} seeds in store", file=sys.stderr)
        return [store.get(sid) for sid in store.ids()]
    if paths in ([], ["-"]):
        data = sys.stdin.buffer.read()
        return [data]
    seeds = []
    for p in _expand_paths(paths) if recursive else paths:
        # a mid-run raise on one bad file would abandon the whole batch:
        # skip unreadable/empty seeds with a logged warning instead
        try:
            with open(p, "rb") as f:
                data = f.read()
        except OSError as e:
            logger.log("warning", "corpus: skipping unreadable seed %s: %s",
                       p, e)
            continue
        if not data:
            logger.log("warning", "corpus: skipping empty seed %s", p)
            continue
        seeds.append(data)
    return seeds


def run_tpu_batch(opts: dict, batch: int = 1024) -> int:
    import jax

    from ..constants import CAPACITY_CLASSES
    from ..ops import payloads, prng
    from ..ops.buffers import Batch, capacity_for, pack, unpack
    from ..ops.pipeline import make_class_fuzzer
    from ..ops.registry import DEVICE_CODES
    from ..ops.scheduler import init_scores

    # bake the reverse-connect endpoint into the device ab/ad payload
    # table BEFORE any fuzzer is built (jit captures it as a constant) —
    # same source of truth as the oracle Ctx (oracle/engine.py)
    payloads.configure(
        opts.get("ssrf_host", "localhost"), opts.get("ssrf_port", 51234)
    )

    seeds = _load_corpus(opts.get("paths", ["-"]), opts.get("recursive", False),
                         direct=opts.get("corpus"),
                         store_dir=opts.get("corpus_dir"))
    if not seeds:
        print("no corpus", file=sys.stderr)
        return 1

    # replicate seeds round-robin up to the batch size
    corpus = [seeds[i % len(seeds)] for i in range(batch)]

    # capacity classes (SURVEY.md §5.7/§7.3-2): group samples by the
    # smallest capacity class that fits them so a corpus with one huge
    # file doesn't pad every sample to the giant class — XLA compiles one
    # program per class and each runs at its natural width. Samples beyond
    # the device budget overflow to the host oracle entirely.
    device_max = int(opts.get("device_capacity_max", CAPACITY_CLASSES[-1]))
    class_indices: dict[int, list[int]] = {}
    overflow_idx: list[int] = []
    for i, s in enumerate(corpus):
        cls = capacity_for(len(s))
        if cls > device_max:
            overflow_idx.append(i)
        else:
            class_indices.setdefault(cls, []).append(i)
    # per-class static scan bound: the true max sample length lets
    # detection scans run at data width instead of capacity width
    # (fuzz_batch scan_len)
    from ..ops.buffers import scan_bound

    class_batches = {
        cls: (np.asarray(idx, np.int32),
              pack([corpus[i] for i in idx], capacity=cls),
              scan_bound(max(len(corpus[i]) for i in idx), cls))
        for cls, idx in sorted(class_indices.items())
    }
    overflow_set = set(overflow_idx)

    # device-capable subset of the selected mutators; host-capable rows go
    # to the hybrid dispatcher's oracle pool
    from ..oracle.mutations import default_mutations
    from .hybrid import HybridDispatcher

    selected = dict(opts.get("mutations") or default_mutations())
    pri = [selected.get(code, 0) for code in DEVICE_CODES]
    if not any(pri):
        print(
            "none of the selected mutations runs on the TPU backend; "
            f"device set: {','.join(DEVICE_CODES)}",
            file=sys.stderr,
        )
        return 1
    from .batcher import service_budget

    # r13 struct engine: --struct {off,host,device} (--struct-kernels =
    # device). host/device route IDENTICALLY and draw from the same
    # counter-keyed streams, so their outputs are byte-identical — host
    # is the parity/debug path, device the throughput path. Either way
    # the struct codes leave the hybrid's host set (zip stays), and the
    # registry fingerprint follows the split (registry_version()).
    from ..ops import registry as _registry
    from ..ops import structure as stm

    struct_mode = str(opts.get("struct") or "off")
    if struct_mode not in ("off", "host", "device"):
        raise ValueError(
            f"struct must be one of off/host/device, got {struct_mode!r}")
    _struct_flag_before = _registry.struct_kernels_enabled()
    _registry.set_struct_kernels(struct_mode != "off")
    hybrid_sel = (selected if struct_mode == "off" else
                  {c: p for c, p in selected.items()
                   if c not in stm.STRUCT_CODES})
    hybrid = HybridDispatcher(list(hybrid_sel.items()), opts["seed"],
                              max_running_time=service_budget(opts))

    # struct source panel: tokenize each DISTINCT seed once (SpanCache),
    # pack the struct-applicable, non-overflow rows into one fixed-width
    # buffer, and (device mode) upload it ONCE — per case only row
    # indices and code picks cross PCIe, the seed bytes and span tables
    # are already resident. Host mode keeps the same numpy arrays and
    # serves the routed rows from the span-oracle on the host pool.
    router = None
    struct_step = None
    src_dev = None
    struct_ids: list[int] = []
    pos_of: dict[int, int] = {}
    # host->device transfer ledger for the struct engine: the one-time
    # resident panel upload plus the per-case routing vectors
    struct_bytes = {"uploaded": 0}
    if struct_mode != "off":
        span_cache = stm.SpanCache()
        router = stm.StructRouter(opts["seed"], selected)
        router.prepare(corpus, span_cache,
                       keys=[i % len(seeds) for i in range(batch)])
        appl_any = router.applicable_any()
        struct_ids = [i for i in range(batch)
                      if appl_any[i] and i not in overflow_set]
        if struct_ids:
            s_caps = np.asarray(
                [capacity_for(len(corpus[i])) for i in struct_ids],
                np.int32)
            width = int(s_caps.max())
            src = np.zeros((len(struct_ids), width), np.uint8)
            s_lens = np.zeros(len(struct_ids), np.int32)
            s_nds = np.zeros((len(struct_ids), stm.SPAN_NODES, 4), np.int32)
            s_cnts = np.zeros(len(struct_ids), np.int32)
            for r, i in enumerate(struct_ids):
                raw = corpus[i]
                src[r, :len(raw)] = np.frombuffer(raw, np.uint8)
                s_lens[r] = len(raw)
                s_nds[r], s_cnts[r] = span_cache.get(i % len(seeds), raw)
            pos_of = {i: r for r, i in enumerate(struct_ids)}
            if struct_mode == "device":
                import jax.numpy as jnp

                from ..ops.tree_mutators import make_struct_step

                struct_step = make_struct_step()
                src_dev = jnp.asarray(src)
                s_lens_dev = jnp.asarray(s_lens)
                s_nds_dev = jnp.asarray(s_nds)
                s_cnts_dev = jnp.asarray(s_cnts)
                s_caps_dev = jnp.asarray(s_caps)
                struct_bytes["uploaded"] += (
                    src.nbytes + s_lens.nbytes + s_nds.nbytes
                    + s_cnts.nbytes + s_caps.nbytes)
        else:
            router = None  # nothing struct-applicable in this corpus

    # one jitted class step, retraced per (B_cls, capacity) shape; keys are
    # derived from the ORIGINAL corpus index, so per-sample streams don't
    # depend on how the classes partition the batch
    step = make_class_fuzzer(mutator_pri=pri)
    base = prng.base_key(opts["seed"])
    scores = init_scores(jax.random.fold_in(base, 999), batch)

    # resume: restore the scheduler scores + case counter (the rest of the
    # stream is a pure function of (seed, case, sample))
    from ..ops.registry import NUM_DEVICE_MUTATORS

    start_case = 0
    n_cases = opts.get("n", 1)
    state_path = opts.get("state_path")
    # post-outcome host scores to swap in AFTER the first resumed launch:
    # split(k) must see the pre state (one-case outcome lag), split(k+1)
    # the post state — exactly what an uninterrupted run's splits saw
    resume_host_post: dict | None = None
    if state_path:
        import os as _os

        from .checkpoint import load_state, save_state

        if _os.path.exists(state_path):
            st = load_state(state_path)
            if st is None:
                print("# checkpoint unreadable, starting fresh", file=sys.stderr)
            else:
                ck_seed, start_case, ck_scores, ck_host, ck_host_post = st
                if (ck_seed != tuple(opts["seed"])
                        or ck_scores.shape != (batch, NUM_DEVICE_MUTATORS)):
                    print("# checkpoint mismatch (seed/shape), starting fresh",
                          file=sys.stderr)
                    start_case = 0
                else:
                    import jax.numpy as jnp

                    scores = jnp.asarray(ck_scores)
                    # restore the hybrid routing state too, so the resumed
                    # run splits host/device exactly like an uninterrupted
                    # one
                    for code, val in ck_host.items():
                        if code in hybrid.host_scores:
                            hybrid.host_scores[code] = val
                    resume_host_post = ck_host_post
                    print(f"# resumed at case {start_case}", file=sys.stderr)
        if start_case >= n_cases:
            print(f"# run already complete ({start_case}/{n_cases} cases)",
                  file=sys.stderr)
            _registry.set_struct_kernels(_struct_flag_before)
            return 0

    if overflow_idx:
        print(f"# {len(overflow_idx)} samples exceed the device budget "
              f"({device_max}B class): oracle-routed", file=sys.stderr)

    from ..oracle.engine import fuzz as oracle_fuzz
    from ..utils.watchdog import CaseTimeout, run_with_timeout

    overflow_budget = service_budget(opts)

    def fuzz_overflow(case_idx: int) -> dict[int, bytes]:
        """Host escape for samples beyond the largest device class: the
        full oracle pipeline with the complete selected mutator set, under
        the same per-case budget as host-routed hybrid samples (overflow
        samples are the biggest files — the likeliest to be slow)."""
        res = {}
        for i in overflow_idx:
            seed3 = (opts["seed"][0], opts["seed"][1] ^ case_idx,
                     opts["seed"][2] ^ (i + 1))
            try:
                res[i] = run_with_timeout(
                    oracle_fuzz, overflow_budget, corpus[i], seed=seed3,
                    mutations=list(selected.items()),
                )
            except CaseTimeout:
                res[i] = b""  # abandoned; the slot still emits
        return res

    import concurrent.futures as cf
    from typing import NamedTuple

    class _Launched(NamedTuple):
        case: int
        class_outputs: list
        host_idx: list
        host_fut: object
        of_fut: object
        scores_after: object
        # struct overlay: [(slot, code_idx)] routed this case, plus the
        # in-flight work — device-mode (out, lens, applied) arrays (JAX
        # async dispatch) or the host-pool future of {slot: bytes}
        struct_rows: list
        struct_work: object

    def fuzz_struct_host(case_idx: int, routed: list) -> dict[int, bytes]:
        """--struct host: the span-oracle serves the routed rows with the
        same counter-keyed draws the device kernels compute — the parity
        baseline the --struct-smoke leg compares --struct-kernels to."""
        res = {}
        for i, ci in routed:
            r = pos_of[i]
            key = stm.struct_sample_key(base, case_idx, i)
            res[i] = stm.host_struct_fuzz(key, corpus[i], s_nds[r],
                                          int(s_cnts[r]), ci,
                                          int(s_caps[r]))
        return res

    writer, _mt = out.string_outputs(opts.get("output", "-"))
    total = 0
    host_total = 0
    stats = opts.get("_stats")  # caller-owned dict for measured numbers
    # checkpoint cadence: an fsync'd save per case throttles short cases;
    # a coarser interval re-runs at most (interval-1) deterministic cases
    # after a crash
    ckpt_every = max(1, int(opts.get("checkpoint_every", 1)))
    host_pool = cf.ThreadPoolExecutor(max_workers=2)
    t0 = time.perf_counter()

    def launch(case, scores_in):
        """Dispatch one case: split on the previous case's scores (a tiny
        forced sync), device steps async, host/overflow work on threads.
        Nothing here waits for the device data."""
        scores_np = np.asarray(scores_in)
        host_mask = hybrid.split(case, corpus, device_scores=scores_np)
        # struct routing sees the same live scores; hybrid-routed and
        # overflow rows are excluded so one sample never lands in two
        # host-side result sets (overlay order would otherwise matter)
        struct_rows: list = []
        struct_work = None
        if router is not None:
            excl = host_mask.copy()
            for i in overflow_idx:
                excl[i] = True
            codes_all = router.route(case, device_scores=scores_np,
                                     excluded=excl)
            struct_rows = [(i, int(codes_all[i])) for i in struct_ids
                           if codes_all[i] >= 0]
        class_outputs = []
        scores_out = scores_in
        for cls, (idx, packed, cls_scan) in class_batches.items():
            new_data, new_lens, new_cls_scores, _meta = step(
                base, case, idx, packed.data, packed.lens, scores_out[idx],
                scan_len=cls_scan,
            )
            class_outputs.append((idx, new_data, new_lens, new_cls_scores))
            scores_out = scores_out.at[idx].set(new_cls_scores)
        if struct_rows:
            if struct_step is not None:
                # pow2-padded row gather out of the RESIDENT panel: only
                # these int32 vectors cross PCIe per case. Pad rows carry
                # code -1 (kernel passthrough, output discarded).
                k = len(struct_rows)
                kp = max(8, 1 << (k - 1).bit_length())
                sel = np.asarray([pos_of[i] for i, _ in struct_rows]
                                 + [0] * (kp - k), np.int32)
                slots = np.asarray([i for i, _ in struct_rows]
                                   + [0] * (kp - k), np.int32)
                cds = np.asarray([c for _, c in struct_rows]
                                 + [-1] * (kp - k), np.int32)
                struct_work = struct_step(
                    base, case, slots, src_dev[sel], s_lens_dev[sel],
                    s_nds_dev[sel], s_cnts_dev[sel], s_caps_dev[sel], cds)
                struct_bytes["uploaded"] += (sel.nbytes + slots.nbytes
                                             + cds.nbytes)
            else:
                struct_work = host_pool.submit(fuzz_struct_host, case,
                                               struct_rows)
        host_idx = [(i, corpus[i]) for i in np.nonzero(host_mask)[0]
                    if i not in overflow_set]
        host_fut = (host_pool.submit(hybrid.fuzz_host, case, host_idx,
                                     defer_scores=True)
                    if host_idx else None)
        of_fut = (host_pool.submit(fuzz_overflow, case)
                  if overflow_idx else None)
        return _Launched(case, class_outputs, host_idx, host_fut, of_fut,
                         scores_out, struct_rows, struct_work)

    def finish(pend: "_Launched"):
        """Unpack + write one launched case (device of the NEXT case is
        already running — this is the overlap)."""
        nonlocal total, host_total
        (case, class_outputs, host_idx, host_fut, of_fut, scores_after,
         struct_rows, struct_work) = pend
        from . import metrics

        results: dict[int, bytes] = {}
        for idx, new_data, new_lens, _nsc in class_outputs:
            outs = unpack(Batch(new_data, new_lens))
            for j, i in enumerate(idx):
                results[int(i)] = outs[j]
        # per-case host-tail ledger: {code: samples the host served}
        routed_codes: dict[str, int] = {}
        if struct_rows:
            if struct_step is not None:
                s_out, s_lens_o, s_applied = struct_work
                out_np = np.asarray(s_out)
                lens_np = np.asarray(s_lens_o)
                app_np = np.asarray(s_applied)
                for p, (i, ci) in enumerate(struct_rows):
                    results[i] = bytes(out_np[p, :int(lens_np[p])])
                    metrics.GLOBAL.record_mutator(
                        stm.STRUCT_CODES[ci], applied=int(app_np[p]) >= 0)
            else:
                struct_results = struct_work.result()
                for i, ci in struct_rows:
                    payload = struct_results[i]
                    results[i] = payload
                    code = stm.STRUCT_CODES[ci]
                    metrics.GLOBAL.record_mutator(
                        code, applied=payload != corpus[i])
                    routed_codes[code] = routed_codes.get(code, 0) + 1
        # the overlapped next case's split already ran and saw host scores
        # through case-1; checkpoint that same pre-outcome state so a
        # resumed run's split(case+1) routes identically to this one
        host_scores_for_ckpt = dict(hybrid.host_scores)
        if host_fut is not None:
            host_results, host_metas = host_fut.result()
            results.update(host_results)
            # score outcomes apply HERE, in case order — the overlapped
            # next case's split must see a deterministic routing state
            hybrid.apply_outcomes(host_metas)
            for meta in host_metas:
                used = [v for t, v in (e for e in meta
                                       if isinstance(e, tuple) and len(e) == 2)
                        if t == "used"]
                code = used[0] if used else "none"
                routed_codes[code] = routed_codes.get(code, 0) + 1
        if of_fut is not None:
            results.update(of_fut.result())
            routed_codes["overflow"] = (routed_codes.get("overflow", 0)
                                        + len(overflow_idx))
        metrics.GLOBAL.record_routed_total(batch)
        for code, n in sorted(routed_codes.items()):
            metrics.GLOBAL.record_host_routed(code, n)
        for i in range(batch):
            payload = results.get(i, b"")
            if writer is not None:
                writer(case * batch + i, payload, [])
            else:
                sys.stdout.buffer.write(payload)
        total += len(results)
        host_total += len(host_idx) + len(overflow_idx)
        if struct_rows and struct_step is None:
            # --struct host serves the routed rows on the host pool — an
            # honest host-tail count for the parity path
            host_total += len(struct_rows)
        if stats is not None:
            # per-case completion timestamps: callers that measure warm
            # throughput (bench full-set stage) drop the first case's
            # compile+trace cost by differencing these
            stats.setdefault("finish_times", []).append(time.perf_counter())
        if state_path and ((case + 1 - start_case) % ckpt_every == 0
                           or case + 1 == n_cases):
            save_state(state_path, opts["seed"], case + 1, scores_after,
                       host_scores=host_scores_for_ckpt,
                       host_scores_post=dict(hybrid.host_scores))

    # -n is the TOTAL case target, like the reference: resume completes the
    # original run rather than adding n more cases
    pending = None
    try:
        for case in range(start_case, n_cases):
            cur = launch(case, scores)
            scores = cur.scores_after
            if resume_host_post is not None:
                # first resumed launch done: later splits build on the
                # post-outcome state, like the uninterrupted run's did
                for code, val in resume_host_post.items():
                    if code in hybrid.host_scores:
                        hybrid.host_scores[code] = val
                resume_host_post = None
            if pending is not None:
                finish(pending)
            pending = cur
        if pending is not None:
            finish(pending)
            pending = None
    finally:
        host_pool.shutdown(wait=False, cancel_futures=True)
        hybrid.close()
        # process-global flag: restore so later runs in this process (a
        # struct-off bench stage, tests) see their own routing split
        _registry.set_struct_kernels(_struct_flag_before)
    dt = time.perf_counter() - t0
    if stats is not None:
        stats.update(total=total, host_total=host_total, dt=dt, batch=batch,
                     struct=struct_mode,
                     struct_bytes_uploaded=struct_bytes["uploaded"])
    logger.log("info", "tpu backend: %d samples in %.2fs (%.0f samples/s)",
               total, dt, total / max(dt, 1e-9))
    struct_note = ""
    if struct_mode != "off":
        struct_note = (f", struct={struct_mode} "
                       f"({len(struct_ids)} rows resident)")
    print(
        f"# {total} samples ({host_total} host-routed), {dt:.2f}s, "
        f"{total / max(dt, 1e-9):.0f} samples/s{struct_note}",
        file=sys.stderr,
    )
    return 0
