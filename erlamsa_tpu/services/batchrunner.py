"""TPU batch runner: corpus -> padded device batches -> mutation -> outputs.

The throughput path (SURVEY.md §7 phase 1): pack seed files into
``uint8[B, L]`` buffers, run the jitted fuzz_batch per case with
counter-derived keys, and stream results to the output writer. The host
stays on IO while the device mutates the next batch (double-buffered via
jax's async dispatch).
"""

from __future__ import annotations

import sys
import time

import numpy as np

from . import logger, out


def _load_corpus(paths: list[str], recursive: bool) -> list[bytes]:
    from ..oracle.gen import _expand_paths

    if paths in ([], ["-"]):
        data = sys.stdin.buffer.read()
        return [data]
    seeds = []
    for p in _expand_paths(paths) if recursive else paths:
        with open(p, "rb") as f:
            seeds.append(f.read())
    return seeds


def run_tpu_batch(opts: dict, batch: int = 1024) -> int:
    import jax

    from ..ops import prng
    from ..ops.buffers import Batch, capacity_for, pack, unpack
    from ..ops.pipeline import make_fuzzer
    from ..ops.registry import DEVICE_CODES
    from ..ops.scheduler import init_scores

    seeds = _load_corpus(opts.get("paths", ["-"]), opts.get("recursive", False))
    if not seeds:
        print("no corpus", file=sys.stderr)
        return 1

    # replicate seeds round-robin up to the batch size
    corpus = [seeds[i % len(seeds)] for i in range(batch)]
    cap = capacity_for(max(len(s) for s in corpus))
    packed = pack(corpus, capacity=cap)

    # device-capable subset of the selected mutators; host-capable rows go
    # to the hybrid dispatcher's oracle pool
    from ..oracle.mutations import default_mutations
    from .hybrid import HybridDispatcher

    selected = dict(opts.get("mutations") or default_mutations())
    pri = [selected.get(code, 0) for code in DEVICE_CODES]
    if not any(pri):
        print(
            "none of the selected mutations runs on the TPU backend; "
            f"device set: {','.join(DEVICE_CODES)}",
            file=sys.stderr,
        )
        return 1
    from .batcher import service_budget

    hybrid = HybridDispatcher(list(selected.items()), opts["seed"],
                              max_running_time=service_budget(opts))

    step, _ = make_fuzzer(cap, batch, mutator_pri=pri)
    base = prng.base_key(opts["seed"])
    scores = init_scores(jax.random.fold_in(base, 999), batch)

    # resume: restore the scheduler scores + case counter (the rest of the
    # stream is a pure function of (seed, case, sample))
    from ..ops.registry import NUM_DEVICE_MUTATORS

    start_case = 0
    n_cases = opts.get("n", 1)
    state_path = opts.get("state_path")
    if state_path:
        import os as _os

        from .checkpoint import load_state, save_state

        if _os.path.exists(state_path):
            st = load_state(state_path)
            if st is None:
                print("# checkpoint unreadable, starting fresh", file=sys.stderr)
            else:
                ck_seed, start_case, ck_scores, ck_host = st
                if (ck_seed != tuple(opts["seed"])
                        or ck_scores.shape != (batch, NUM_DEVICE_MUTATORS)):
                    print("# checkpoint mismatch (seed/shape), starting fresh",
                          file=sys.stderr)
                    start_case = 0
                else:
                    import jax.numpy as jnp

                    scores = jnp.asarray(ck_scores)
                    # restore the hybrid routing state too, so the resumed
                    # run splits host/device exactly like an uninterrupted
                    # one
                    for code, val in ck_host.items():
                        if code in hybrid.host_scores:
                            hybrid.host_scores[code] = val
                    print(f"# resumed at case {start_case}", file=sys.stderr)
        if start_case >= n_cases:
            print(f"# run already complete ({start_case}/{n_cases} cases)",
                  file=sys.stderr)
            return 0

    writer, _mt = out.string_outputs(opts.get("output", "-"))
    total = 0
    host_total = 0
    t0 = time.perf_counter()
    data, lens = packed.data, packed.lens
    # -n is the TOTAL case target, like the reference: resume completes the
    # original run rather than adding n more cases
    for case in range(start_case, n_cases):
        # live scheduler scores weight the host/device split like the
        # reference's score*pri mux mass (erlamsa_mutations.erl:1244-1250)
        host_mask = hybrid.split(case, corpus,
                                 device_scores=np.asarray(scores))
        # device mutates the WHOLE batch (async); the host pool handles its
        # share in parallel, and host results override at merge time
        new_data, new_lens, scores, meta = step(base, case, data, lens, scores)
        host_results = {}
        host_idx = [(i, corpus[i]) for i in np.nonzero(host_mask)[0]]
        if host_idx:
            host_results = hybrid.fuzz_host(case, host_idx)
        results = unpack(Batch(new_data, new_lens))
        for i, rdata in enumerate(results):
            payload = host_results.get(i, rdata)
            if writer is not None:
                writer(case * batch + i, payload, [])
            else:
                sys.stdout.buffer.write(payload)
        total += len(results)
        host_total += len(host_idx)
        if state_path:
            save_state(state_path, opts["seed"], case + 1, scores,
                       host_scores=hybrid.host_scores)
    hybrid.close()
    dt = time.perf_counter() - t0
    logger.log("info", "tpu backend: %d samples in %.2fs (%.0f samples/s)",
               total, dt, total / max(dt, 1e-9))
    print(
        f"# {total} samples ({host_total} host-routed), {dt:.2f}s, "
        f"{total / max(dt, 1e-9):.0f} samples/s",
        file=sys.stderr,
    )
    return 0
