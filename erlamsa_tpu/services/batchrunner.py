"""TPU batch runner: corpus -> padded device batches -> mutation -> outputs.

The throughput path (SURVEY.md §7 phase 1): pack seed files into
``uint8[B, L]`` buffers, run the jitted fuzz_batch per case with
counter-derived keys, and stream results to the output writer. The host
stays on IO while the device mutates the next batch (double-buffered via
jax's async dispatch).
"""

from __future__ import annotations

import sys
import time

from . import logger, out


def _load_corpus(paths: list[str], recursive: bool) -> list[bytes]:
    from ..oracle.gen import _expand_paths

    if paths in ([], ["-"]):
        data = sys.stdin.buffer.read()
        return [data]
    seeds = []
    for p in _expand_paths(paths) if recursive else paths:
        with open(p, "rb") as f:
            seeds.append(f.read())
    return seeds


def run_tpu_batch(opts: dict, batch: int = 1024) -> int:
    import jax

    from ..ops import prng
    from ..ops.buffers import Batch, capacity_for, pack, unpack
    from ..ops.pipeline import make_fuzzer
    from ..ops.registry import DEVICE_CODES
    from ..ops.scheduler import init_scores

    seeds = _load_corpus(opts.get("paths", ["-"]), opts.get("recursive", False))
    if not seeds:
        print("no corpus", file=sys.stderr)
        return 1

    # replicate seeds round-robin up to the batch size
    corpus = [seeds[i % len(seeds)] for i in range(batch)]
    cap = capacity_for(max(len(s) for s in corpus))
    packed = pack(corpus, capacity=cap)

    # device-capable subset of the selected mutators
    selected = dict(opts.get("mutations") or [])
    pri = [selected.get(code, 0) for code in DEVICE_CODES]
    if not any(pri):
        print(
            "none of the selected mutations runs on the TPU backend; "
            f"device set: {','.join(DEVICE_CODES)}",
            file=sys.stderr,
        )
        return 1

    step, _ = make_fuzzer(cap, batch, mutator_pri=pri)
    base = prng.base_key(opts["seed"])
    scores = init_scores(jax.random.fold_in(base, 999), batch)

    writer, _mt = out.string_outputs(opts.get("output", "-"))
    n_cases = opts.get("n", 1)
    total = 0
    t0 = time.perf_counter()
    data, lens = packed.data, packed.lens
    for case in range(n_cases):
        new_data, new_lens, scores, meta = step(base, case, data, lens, scores)
        results = unpack(Batch(new_data, new_lens))
        for i, rdata in enumerate(results):
            if writer is not None:
                writer(case * batch + i, rdata, [])
            else:
                sys.stdout.buffer.write(rdata)
        total += len(results)
    dt = time.perf_counter() - t0
    logger.log("info", "tpu backend: %d samples in %.2fs (%.0f samples/s)",
               total, dt, total / max(dt, 1e-9))
    print(
        f"# {total} samples, {dt:.2f}s, {total / max(dt, 1e-9):.0f} samples/s",
        file=sys.stderr,
    )
    return 0
