"""The erlamsa-side bridge server: the north star's ``-m xla`` backend.

Speaks the length-prefixed frame protocol in bridge/PROTOCOL.md to an
Erlang `open_port({packet,4})` (stdio mode) or over TCP (daemon mode).
The Erlang counterpart is bridge/erlamsa_mutations_xla.erl, loaded into
the reference with ``-e erlamsa_mutations_xla`` (the external-module hook,
src/erlamsa_cmdparse.erl:456-470; module shape external_muta.erl:1-21).

Ops (see PROTOCOL.md):
- FUZZ_CASE: whole-case oracle run for byte-exact parity at fixed seed.
- MUX_EVENT: one mux_fuzzers event (src/erlamsa_mutations.erl:1256-1280)
  against the caller's live AS183 state; the advanced state rides back so
  the Erlang process's stream continues in lockstep.
- FUZZ_BATCH: many samples per call on the TPU batch engine (or the
  oracle, per-sample) — the throughput path.

The server holds no cross-frame state (state travels in the frames), so a
restart loses nothing.
"""

from __future__ import annotations

import json
import socket
import struct
import sys
import threading

MAX_FRAME = 64 * 1024 * 1024
VERSION = 1

OP_HELLO = 0x01
OP_FUZZ_CASE = 0x02
OP_MUX_EVENT = 0x03
OP_FUZZ_BATCH = 0x05
OP_PING = 0x7E
OP_ERROR = 0xFF
RESP = 0x80


class ProtocolError(Exception):
    pass


def encode_frame(opcode: int, header: dict, payload: bytes = b"") -> bytes:
    body = bytes([opcode]) + json.dumps(header).encode() + b"\x00" + payload
    if len(body) > MAX_FRAME:
        raise ProtocolError("frame too large")
    return struct.pack(">I", len(body)) + body


def decode_body(body: bytes) -> tuple[int, dict, bytes]:
    if not body:
        raise ProtocolError("empty frame")
    sep = body.find(b"\x00", 1)
    if sep < 0:
        raise ProtocolError("missing header separator")
    header = json.loads(body[1:sep] or b"{}")
    return body[0], header, body[sep + 1 :]


def _read_exact(read, n: int) -> bytes | None:
    buf = b""
    while len(buf) < n:
        chunk = read(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def read_frame(read) -> tuple[int, dict, bytes] | None:
    """read(n) -> bytes callable; returns None on clean EOF."""
    hdr = _read_exact(read, 4)
    if hdr is None:
        return None
    (ln,) = struct.unpack(">I", hdr)
    if ln > MAX_FRAME:
        raise ProtocolError("oversized frame")
    body = _read_exact(read, ln)
    if body is None:
        raise ProtocolError("truncated frame")
    return decode_body(body)


# ---- op handlers ----------------------------------------------------------


def _parse_mutations(spec):
    from ..oracle.mutations import default_mutations
    from .cli import _parse_actions

    if not spec or spec == "default":
        return None
    return _parse_actions(spec, default_mutations())


def _parse_patterns(spec):
    from ..oracle.patterns import default_patterns
    from .cli import _parse_actions

    if not spec or spec == "default":
        return None
    return _parse_actions(spec, default_patterns())


def _handle_fuzz_case(header: dict, payload: bytes):
    from ..oracle.engine import fuzz

    seed = tuple(int(x) for x in header["seed"])
    opts = {}
    muts = _parse_mutations(header.get("mutations"))
    if muts is not None:
        opts["mutations"] = muts
    pats = _parse_patterns(header.get("patterns"))
    if pats is not None:
        opts["patterns"] = pats
    out = fuzz(payload, seed=seed, **opts)
    return {"len": len(out)}, out


def _handle_mux_event(header: dict, payload: bytes):
    """make_mutator (init-score draws included) + one apply_mux on the
    caller's AS183 state; deterministic per (state, mutations, data)."""
    from ..oracle.mutations import (
        Ctx,
        apply_mux,
        default_mutations,
        make_mutator,
    )
    from ..utils.erlrand import ErlRand

    state = tuple(int(x) for x in header["state"])
    r = ErlRand()
    r.setstate(state)
    ctx = Ctx(r)
    muts = _parse_mutations(header.get("mutations")) or default_mutations()
    rows = make_mutator(ctx, muts)
    _rows, ll, meta = apply_mux(ctx, rows, [payload], [])
    out = b"".join(b for b in ll if isinstance(b, bytes))
    used = next((v for k, v in meta if k == "used"), None)
    return {"len": len(out), "state": list(r.getstate()), "used": used}, out


def _split_payload(payload: bytes, lens: list[int]) -> list[bytes]:
    if sum(lens) != len(payload):
        raise ProtocolError("lens do not sum to payload size")
    out, pos = [], 0
    for n in lens:
        out.append(payload[pos : pos + n])
        pos += n
    return out


def _fuzz_batch_tpu(seed, case_idx: int, samples: list[bytes]) -> list[bytes]:
    import jax

    from ..ops import prng
    from ..ops.buffers import Batch, capacity_for, pack, unpack
    from ..ops.pipeline import make_fuzzer
    from ..ops.scheduler import init_scores

    cap = capacity_for(max(1, max(len(s) for s in samples)))
    packed = pack(samples, capacity=cap)
    step, _ = make_fuzzer(cap, len(samples))
    base = prng.base_key(seed)
    scores = init_scores(jax.random.fold_in(base, 999), len(samples))
    data, lens, _scores, _meta = step(
        base, case_idx, packed.data, packed.lens, scores
    )
    return unpack(Batch(data, lens))


def _fuzz_batch_oracle(seed, case_idx: int, samples: list[bytes]) -> list[bytes]:
    """Per-sample oracle with the engine's ThreadSeed derivation: sample i
    of case c uses the parent stream's (case*B+i)-th derived seed."""
    from ..oracle.engine import fuzz
    from ..utils.erlrand import ErlRand

    parent = ErlRand(tuple(seed))
    for _ in range(3 * case_idx * len(samples)):
        parent.erand(99999)
    out = []
    for s in samples:
        ts = (parent.erand(99999), parent.erand(99999), parent.erand(99999))
        out.append(fuzz(s, seed=ts))
    return out


def _handle_fuzz_batch(header: dict, payload: bytes):
    seed = tuple(int(x) for x in header["seed"])
    case_idx = int(header.get("case", 0))
    samples = _split_payload(payload, [int(x) for x in header["lens"]])
    if not samples:
        return {"lens": []}, b""
    backend = header.get("backend", "tpu")
    if backend == "oracle":
        results = _fuzz_batch_oracle(seed, case_idx, samples)
    else:
        results = _fuzz_batch_tpu(seed, case_idx, samples)
    return {"lens": [len(r) for r in results]}, b"".join(results)


class BridgeServer:
    """One protocol session over a (read, write) byte-stream pair."""

    def __init__(self):
        self._hello_done = False

    def handle(self, opcode: int, header: dict, payload: bytes) -> bytes:
        try:
            if opcode == OP_HELLO:
                self._hello_done = True
                return encode_frame(
                    OP_HELLO | RESP,
                    {
                        "ok": True,
                        "server": "erlamsa_tpu",
                        "version": VERSION,
                        "backends": ["oracle", "tpu"],
                    },
                )
            if opcode == OP_PING:
                return encode_frame(OP_PING | RESP, {})
            if not self._hello_done:
                raise ProtocolError("HELLO required first")
            if opcode == OP_FUZZ_CASE:
                h, p = _handle_fuzz_case(header, payload)
                return encode_frame(OP_FUZZ_CASE | RESP, h, p)
            if opcode == OP_MUX_EVENT:
                h, p = _handle_mux_event(header, payload)
                return encode_frame(OP_MUX_EVENT | RESP, h, p)
            if opcode == OP_FUZZ_BATCH:
                h, p = _handle_fuzz_batch(header, payload)
                return encode_frame(OP_FUZZ_BATCH | RESP, h, p)
            raise ProtocolError(f"unknown opcode {opcode:#x}")
        except ProtocolError as e:
            return encode_frame(OP_ERROR, {"error": str(e)})
        except Exception as e:  # lint: broad-except-ok never kill the port on a bad sample
            return encode_frame(OP_ERROR, {"error": f"{type(e).__name__}: {e}"})

    def serve_stream(self, read, write) -> None:
        while True:
            try:
                frame = read_frame(read)
            except ProtocolError as e:
                write(encode_frame(OP_ERROR, {"error": str(e)}))
                return
            if frame is None:
                return
            write(self.handle(*frame))


def serve_stdio() -> int:
    """Erlang port mode: {packet,4} frames on stdin/stdout."""
    stdin = sys.stdin.buffer
    stdout = sys.stdout.buffer

    def write(b: bytes):
        stdout.write(b)
        stdout.flush()

    BridgeServer().serve_stream(stdin.read1 if hasattr(stdin, "read1") else stdin.read, write)
    return 0


def serve_tcp(port: int, host: str = "127.0.0.1", block: bool = True):
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, port))
    srv.listen(16)

    def client(conn):
        with conn:
            BridgeServer().serve_stream(conn.recv, conn.sendall)

    def loop():
        while True:
            try:
                conn, _ = srv.accept()
            except OSError:
                return
            threading.Thread(target=client, args=(conn,), daemon=True).start()

    if block:
        loop()
        return 0
    threading.Thread(target=loop, daemon=True).start()
    return srv


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="erlamsa bridge server (see bridge/PROTOCOL.md)"
    )
    ap.add_argument("--tcp", type=int, default=None, metavar="PORT",
                    help="serve over TCP instead of stdio")
    ap.add_argument("--host", default="127.0.0.1")
    args = ap.parse_args(argv)
    if args.tcp is not None:
        return serve_tcp(args.tcp, args.host)
    return serve_stdio()


if __name__ == "__main__":
    sys.exit(main())
