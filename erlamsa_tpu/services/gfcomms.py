"""Genfuzz TCP service: serve grammar-generated fuzzing data per connection.

Reference: src/erlamsa_gfcomms.erl — accept TCP, call the external module's
fuzzer per packet with a session dict. Here the handler generates from a
genfuzz grammar (models/genfuzz.py) or delegates to an external module's
``fuzzer(proto, data, session)``.

Two generation paths (r17):

* **sequential** (default): one shared ErlRand AS183 stream, one
  ``fuzz_grammar`` expansion per packet under a lock — the reference's
  shape. The stream seed is explicit and logged at startup, so a fixed
  ``--seed`` replays the service byte-identically (it used to default to
  urandom silently, which made "replay the session" impossible).
* **batched** (``engine=`` / --gfcomms-batched): responses come from the
  device grammar kernel via a GenEngine. A handler drains whatever
  packets are already pending on the connection and answers them with
  ONE kernel call. Response i of connection c is keyed on
  ``(grammar_id, c, i)`` — a pure function of the seed and the packet's
  position, independent of how packets were grouped into kernel calls —
  so the single-connection replay contract survives batching, and the
  engine's gen.expand chaos/degradation semantics apply.
"""

from __future__ import annotations

import socket
import threading

from ..models.genfuzz import fuzz_grammar
from ..obs import trace
from ..utils.erlrand import ErlRand, gen_urandom_seed
from . import logger

# batched mode: cap on packets answered by one kernel call
MAX_DRAIN = 64


def _fmt_seed(seed) -> str:
    if isinstance(seed, tuple):
        return ",".join(str(x) for x in seed)
    return str(seed)


class GfComms:
    def __init__(self, port: int, grammar=None, external_fuzzer=None,
                 seed=None, engine=None):
        self.port = port
        self.grammar = grammar
        self.external = external_fuzzer
        self.engine = engine  # gen.GenEngine -> batched keyed mode
        if seed is None:
            seed = gen_urandom_seed()
        self.seed = seed
        self.r = ErlRand(seed)
        # one AS183 stream shared by handler threads: serialize draws so a
        # fixed seed stays reproducible (single-connection replay contract)
        self._rlock = threading.Lock()
        self._stop = threading.Event()
        self._conn_seq = 0

    def _handle(self, conn: socket.socket, addr):
        session: dict = {}
        try:
            while not self._stop.is_set():
                data = conn.recv(65536)
                if not data:
                    break
                with trace.span("gfcomms.request", bytes=len(data)):
                    if self.external is not None:
                        out = self.external("tcp", data, session)
                    elif self.grammar is not None:
                        with self._rlock:
                            out = fuzz_grammar(self.r, self.grammar,
                                               session)
                    else:
                        out = data
                conn.sendall(out)
        except OSError:
            pass
        finally:
            conn.close()

    def _handle_batched(self, conn: socket.socket, addr, conn_id: int):
        """Drain pending packets, answer them with one kernel call.
        Response i of this connection is expand(case=conn_id, slot=i)
        whatever the grouping — replay-stable by construction."""
        seq = 0
        try:
            while not self._stop.is_set():
                data = conn.recv(65536)
                if not data:
                    break
                npkts = 1
                conn.setblocking(False)
                try:
                    while npkts < MAX_DRAIN:
                        more = conn.recv(65536)
                        if not more:
                            break
                        npkts += 1
                except OSError:
                    pass  # nothing else pending
                finally:
                    conn.setblocking(True)
                with trace.span("gen.expand", conn=conn_id, seq=seq,
                                pkts=npkts):
                    outs, _trunc = self.engine.expand(
                        conn_id, slots=range(seq, seq + npkts)
                    )
                seq += npkts
                for out in outs:
                    conn.sendall(out)
        except OSError:
            pass
        finally:
            conn.close()

    def serve(self, block: bool = True):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", self.port))
        srv.listen(16)
        self._srv = srv
        # the replay coordinate, stated up front: rerunning with this
        # seed (and the same per-connection packet sequence) reproduces
        # every response byte
        logger.log("info", "gfcomms listening on :%d (seed %s, %s mode)",
                   self.port, _fmt_seed(self.seed),
                   "batched" if self.engine is not None else "sequential")

        def loop():
            while not self._stop.is_set():
                try:
                    conn, addr = srv.accept()
                except OSError:
                    break
                conn_id = self._conn_seq
                self._conn_seq += 1
                if self.engine is not None:
                    target, args = self._handle_batched, (conn, addr, conn_id)
                else:
                    target, args = self._handle, (conn, addr)
                threading.Thread(target=target, args=args,
                                 daemon=True).start()

        if block:
            loop()
            return 0
        threading.Thread(target=loop, daemon=True).start()
        return self

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
