"""Genfuzz TCP service: serve grammar-generated fuzzing data per connection.

Reference: src/erlamsa_gfcomms.erl — accept TCP, call the external module's
fuzzer per packet with a session dict. Here the handler generates from a
genfuzz grammar (models/genfuzz.py) or delegates to an external module's
``fuzzer(proto, data, session)``.
"""

from __future__ import annotations

import socket
import threading

from ..models.genfuzz import fuzz_grammar
from ..utils.erlrand import ErlRand, gen_urandom_seed
from . import logger


class GfComms:
    def __init__(self, port: int, grammar=None, external_fuzzer=None, seed=None):
        self.port = port
        self.grammar = grammar
        self.external = external_fuzzer
        self.r = ErlRand(seed or gen_urandom_seed())
        # one AS183 stream shared by handler threads: serialize draws so a
        # fixed seed stays reproducible (single-connection replay contract)
        self._rlock = threading.Lock()
        self._stop = threading.Event()

    def _handle(self, conn: socket.socket, addr):
        session: dict = {}
        try:
            while not self._stop.is_set():
                data = conn.recv(65536)
                if not data:
                    break
                if self.external is not None:
                    out = self.external("tcp", data, session)
                elif self.grammar is not None:
                    with self._rlock:
                        out = fuzz_grammar(self.r, self.grammar, session)
                else:
                    out = data
                conn.sendall(out)
        except OSError:
            pass
        finally:
            conn.close()

    def serve(self, block: bool = True):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", self.port))
        srv.listen(16)
        self._srv = srv
        logger.log("info", "gfcomms listening on :%d", self.port)

        def loop():
            while not self._stop.is_set():
                try:
                    conn, addr = srv.accept()
                except OSError:
                    break
                threading.Thread(
                    target=self._handle, args=(conn, addr), daemon=True
                ).start()

        if block:
            loop()
            return 0
        threading.Thread(target=loop, daemon=True).start()
        return self

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
