"""Command-line interface, flag-compatible with the reference's erlamsa.

Reference: src/erlamsa_cmdparse.erl (getopt spec at :77-137, --list
introspection at :147-178, priority-list parsing at :232-257). Added here:
``--backend tpu`` selects the batched device engine and ``--batch`` its
batch size — the TPU analogue of ``-m xla`` in the north star.
"""

from __future__ import annotations

import argparse
import sys

from ..utils.erlrand import gen_urandom_seed, parse_seed
from . import logger, out


def _parse_actions(s: str, defaults: list[tuple[str, int]]) -> list[tuple[str, int]]:
    """'name=pri,name,...' -> [(name, pri)] on top of defaults
    (string_to_actions, src/erlamsa_cmdparse.erl:232-257)."""
    if s in ("default", "", None):
        return list(defaults)
    known = dict(defaults)
    out_list: list[tuple[str, int]] = []
    for item in s.split(","):
        if not item:
            continue
        if "=" in item:
            name, pri = item.split("=", 1)
            out_list.append((name, int(pri)))
        else:
            out_list.append((item, known.get(item, 1)))
    bad = [n for n, _ in out_list if n not in known]
    if bad:
        raise SystemExit(f"Unknown mutation/pattern/generator name(s): {bad}")
    return out_list


def _show_list() -> None:
    """--list introspection (show_list, src/erlamsa_cmdparse.erl:147-178)."""
    from ..oracle.gen import GENERATOR_INFO
    from ..oracle.mutations import default_mutations
    from ..oracle.patterns import patterns_table
    from ..ops.registry import DEVICE_CODES

    descs = {
        "sgm": "SGML tree mutations", "js": "JSON tree mutations",
        "uw": "try to make a code point too wide",
        "ui": "insert funny unicode",
        "ab": "enhance silly issues in ASCII string data handling",
        "ad": "play with delimeters in ASCII string data",
        "tr2": "duplicate a node", "td": "delete a node",
        "num": "try to modify a textual number",
        "ts1": "swap one node with another one",
        "tr": "repeat a path of the parse tree",
        "ts2": "swap two nodes pairwise",
        "bd": "drop a byte", "bei": "increment a byte by one",
        "bed": "decrement a byte by one", "bf": "flip one bit",
        "bi": "insert a byte", "ber": "swap a byte with random one",
        "br": "repeat a byte", "sp": "permute a sequence of bytes",
        "sr": "repeat a sequence of bytes", "sd": "delete a sequence of bytes",
        "snand": "NAND/OR/XOR random bytes from block",
        "srnd": "replace random bytes from block with random values",
        "ld": "delete a line", "lds": "delete many lines",
        "lr2": "duplicate a line", "lri": "copy a line closeby",
        "lr": "repeat a line", "ls": "swap two lines",
        "lp": "swap order of lines", "lis": "insert a line from elsewhere",
        "lrs": "replace a line with one from elsewhere",
        "ft": "jump to a similar position in block",
        "fn": "likely clone data between similar positions",
        "fo": "fuse previously seen data elsewhere",
        "len": "predicted length mutation",
        "b64": "try mutate base64-encoded block",
        "uri": "try mutate URI to cause SSRF", "zip": "ZIP path traversal",
        "nil": "no mutation will occur (debugging purposes)",
    }
    print("Mutations (-m)   [* = also runs on TPU backend]")
    for name, pri in default_mutations():
        star = "*" if name in DEVICE_CODES else " "
        print(f"  {star} {name:6s} pri={pri:<3d} {descs.get(name, '')}")
    print("\nPatterns (-p)")
    for pri, _fn, name, desc in patterns_table():
        print(f"    {name:6s} pri={pri:<3d} {desc}")
    print("\nGenerators (-g)")
    for name, pri, desc in GENERATOR_INFO:
        print(f"    {name:6s} pri={pri:<6d} {desc}")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="erlamsa-tpu",
        description="TPU-native general-purpose fuzzer "
        "(erlamsa-compatible CLI).",
    )
    p.add_argument("paths", nargs="*", default=[], help="input files, or - for stdin")
    p.add_argument("-n", "--count", default="1", help="number of cases, or 'inf'")
    p.add_argument("-s", "--seed", default=None, help="random seed a,b,c")
    p.add_argument("-m", "--mutations", default="default")
    p.add_argument("-p", "--patterns", default="default")
    p.add_argument("-g", "--generators", default="default")
    p.add_argument("-o", "--output", default="-")
    p.add_argument("-b", "--blockscale", type=float, default=1.0)
    p.add_argument("-w", "--workers", type=int, default=1)
    p.add_argument("--skip", type=int, default=0)
    p.add_argument("--sleep", type=int, default=0, help="ms between cases")
    p.add_argument("--maxfails", type=int, default=10)
    p.add_argument("-T", "--maxrunningtime", type=float, default=None,
                   help="per-case wall-clock budget in seconds (0 = "
                        "unlimited); hung cases/writers are abandoned "
                        "(reference MaxRunningTime; service modes default "
                        "to 30, CLI runs to unlimited)")
    p.add_argument("-S", "--sequence-muta", action="store_true")
    p.add_argument("-l", "--list", action="store_true", help="list engines")
    p.add_argument("-v", "--verbose", action="count", default=0)
    p.add_argument("-L", "--logger", default=None,
                   help="log spec: stdout|stderr|file=path|sqlite=path "
                        "(sqlite is the queryable findings store; see "
                        "--list-findings)")
    p.add_argument("--list-findings", default=None, metavar="DB",
                   help="print findings recorded in a '-L sqlite=DB' "
                        "store from any past run, then exit")
    p.add_argument("-M", "--meta", default=None, help="write metadata to path")
    p.add_argument("-r", "--recursive", action="store_true")
    p.add_argument("-H", "--httpsvc", default=None, help="run FaaS at host:port")
    p.add_argument("--serving", choices=["continuous", "flush"],
                   default="continuous",
                   help="FaaS device engine: continuous (default) admits "
                        "requests into a slot-based in-flight batch at "
                        "step granularity (services/serving.py); flush "
                        "keeps the deadline-flushed batcher. Single-"
                        "request bytes are identical between modes at a "
                        "fixed -s")
    p.add_argument("--serving-slots", type=int, default=None, metavar="N",
                   help="continuous-engine slot count (device rows per "
                        "step; default 64)")
    p.add_argument("--capacity", type=int, default=None, metavar="BYTES",
                   help="serving working width in bytes (default 16384); "
                        "longer requests overflow to the host oracle")
    p.add_argument("--queue-cap", type=int, default=1024, metavar="N",
                   help="FaaS admission backlog bound: requests beyond "
                        "this shed with HTTP 429 + Retry-After (0 = "
                        "unbounded)")
    p.add_argument("--tenant-rate", type=float, default=0.0, metavar="R",
                   help="per-tenant admission quota in requests/sec "
                        "(token bucket; 0 = no quotas)")
    p.add_argument("--tenant-burst", type=float, default=None, metavar="B",
                   help="per-tenant burst allowance (default 2x rate)")
    p.add_argument("--checkpoint-every", type=int, default=1, metavar="N",
                   help="--state save cadence in cases (fsync per save; "
                        "a crash re-runs at most N-1 deterministic cases)")
    p.add_argument("--device-capacity-max", type=int, default=None,
                   metavar="BYTES",
                   help="largest capacity class run on the device; bigger "
                        "samples overflow to the host oracle")
    p.add_argument("--cmanager-store", default=None, metavar="PATH",
                   help="persist FaaS tokens/sessions to a JSON file "
                        "(the reference keeps them in mnesia)")
    p.add_argument("-i", "--proxy", default=None,
                   help="fuzzing proxy spec proto://lport:rhost:rport")
    p.add_argument("-P", "--proxy-prob", default="0.1,0.1",
                   help="proxy fuzzing probabilities c->s,s->c")
    p.add_argument("-k", "--bypass", type=int, default=0,
                   help="pass through the first K proxy packets unfuzzed")
    p.add_argument("--ascent", type=float, default=0.0,
                   help="proxy probability ascent coefficient")
    p.add_argument("--certfile", default=None, help="TLS cert for tls:// proxy")
    p.add_argument("--keyfile", default=None, help="TLS key for tls:// proxy")
    p.add_argument("--workers-same-seed", action="store_true",
                   help="all workers use the run seed instead of derived seeds")
    p.add_argument("-D", "--detach", action="store_true",
                   help="daemonize (fork to background)")
    p.add_argument("--monitor", action="append", default=[],
                   help="+name:params / !name:off")
    p.add_argument("-e", "--external", default=None,
                   help="python module with capabilities()")
    p.add_argument("-d", "--debug", action="store_true",
                   help="start the periodic profiler")
    p.add_argument("--corpus", default=None, metavar="DIR",
                   help="persistent seed store directory: input seeds are "
                        "content-hash-deduped into DIR and runs draw from "
                        "the store (corpus/store.py)")
    p.add_argument("--feedback", action="store_true",
                   help="feedback-driven corpus engine (requires --corpus): "
                        "AFL-style energy scheduling over the store, "
                        "length-bucketed device batches, monitor/proxy "
                        "events promote seeds")
    p.add_argument("--backend", choices=["oracle", "tpu"], default="oracle",
                   help="oracle = sequential parity engine; tpu = batched device engine")
    p.add_argument("--batch", type=int, default=1024, help="TPU batch size")
    p.add_argument("--pipeline", choices=["sync", "async"], default="async",
                   help="corpus execution pipeline: async (default) "
                        "overlaps host assembly, device mutation and "
                        "output drain; sync is the serialized baseline. "
                        "Outputs are byte-identical at a fixed -s")
    p.add_argument("--layout", choices=["buckets", "arena"],
                   default="buckets",
                   help="corpus memory layout: buckets (default) "
                        "re-uploads pow2-padded panels per case; arena "
                        "keeps seeds device-resident in fixed-size pages "
                        "addressed through a page table — one compiled "
                        "step, ~zero padded waste, each seed crosses "
                        "PCIe once (corpus/arena.py)")
    p.add_argument("--shards", type=int, default=None, metavar="N",
                   help="elastic sharded corpus fleet (corpus/fleet.py): "
                        "partition seeds across N per-shard arenas by "
                        "content hash, merge novelty/energy at a "
                        "coordinator. Byte-identical to --shards 1 at a "
                        "fixed -s; a lost shard redistributes across "
                        "survivors instead of falling back to the host "
                        "(default: single-device runner)")
    p.add_argument("--spmd", action="store_true",
                   help="single-program fleet (parallel/spmd.py): run "
                        "every local shard's gather→mutate→score as ONE "
                        "shard_map-compiled program over the device mesh "
                        "with on-device novelty/score reduce — one "
                        "dispatch per (case, capacity class) instead of "
                        "one per shard. Without --shards the fleet is "
                        "sized to jax.devices(); byte-identical to "
                        "--shards N and to the single-device runner at "
                        "a fixed -s. Verify on any box with XLA_FLAGS="
                        "--xla_force_host_platform_device_count=8")
    p.add_argument("--arena-pages", type=int, default=None, metavar="N",
                   help="arena page count (default: 2x the pages the "
                        "store needs, min 64 — eviction/spill handle "
                        "overflow)")
    p.add_argument("--arena-page", type=int, default=None, metavar="BYTES",
                   help="arena page size in bytes (default 256, the "
                        "device lane width; must divide the run's "
                        "working width)")
    p.add_argument("--arena-classes", default=None, metavar="SPEC",
                   help="arena capacity classes: comma-separated byte "
                        "widths (e.g. '256,4096,65536') or 'auto' "
                        "(default) to derive them from the stored seed "
                        "sizes. Each seed rides the smallest class that "
                        "holds it whole — short seeds stop paying the "
                        "widest row's gather/compute (corpus/arena.py)")
    p.add_argument("--struct", choices=["off", "host", "device"],
                   default="off",
                   help="structured-format engine (ops/structure.py): "
                        "route the span-splice mutators (tr2 td ts1 tr "
                        "ts2 js sgm b64 uri) through the one-pass span "
                        "tokenizer instead of the host oracle tail. "
                        "'device' runs them as vmapped kernels "
                        "(ops/tree_mutators.py) — zip is then the only "
                        "host-routed code; 'host' is the byte-identical "
                        "numpy parity path; 'off' (default) keeps the "
                        "legacy hybrid routing")
    p.add_argument("--struct-kernels", action="store_true",
                   help="shorthand for --struct device")
    p.add_argument("--adopt", action="store_true",
                   help="device-resident offspring adoption: interesting "
                        "offspring scatter straight from the step's "
                        "output buffer into free arena pages of the "
                        "right class, so only content hashes and "
                        "lengths cross PCIe (requires --layout arena; "
                        "outputs stay byte-identical at a fixed -s)")
    p.add_argument("--coverage", action="store_true",
                   help="device edge-coverage feedback (requires "
                        "--feedback): listen for connect-back edge "
                        "bitmaps (services/monitors.py CoverageHub), "
                        "fold them into per-seed coverage tensors "
                        "(ops/coverage.py) and gate adoption/energy on "
                        "genuinely-new edges instead of output-hash "
                        "novelty; a dead monitor plane degrades the run "
                        "to hash-novelty, byte-identical to --coverage "
                        "off at a fixed -s")
    p.add_argument("--coverage-port", type=int, default=None, metavar="PORT",
                   help="coverage hub listen port (default: ephemeral, "
                        "printed at startup)")
    p.add_argument("--distill", action="store_true",
                   help="end-of-run corpus distillation (requires "
                        "--coverage): greedy set-cover keeps the "
                        "smallest seed set whose union covers every "
                        "observed edge and retires the provably-"
                        "subsumed rest (corpus/distill.py)")
    p.add_argument("--state", default=None,
                   help="checkpoint file (.npz) for stop/resume of batch "
                        "runs; with --shards/--fleet-nodes this is the "
                        "fleet coordinator checkpoint (per-case progress, "
                        "scores, seen hashes, energies, placement epoch) "
                        "— a killed coordinator resumes byte-identically")
    p.add_argument("--fleet-nodes", default=None, metavar="HOST:PORT,...",
                   help="cross-host fleet: serve the first shard ids on "
                        "these remote workers (each started with "
                        "--fleet-worker) over the dist shard protocol "
                        "with fenced leases; without --shards the fleet "
                        "is sized to this list, with --shards N the "
                        "remaining ids run locally (mixed fleet). "
                        "Byte-identical to the all-local run at a "
                        "fixed -s (corpus/fleet.py)")
    p.add_argument("--fleet-worker", type=int, default=None, metavar="PORT",
                   help="serve fleet shard leases on PORT (the worker "
                        "half of --fleet-nodes) and block; SIGTERM "
                        "requests a graceful drain: the worker finishes "
                        "its in-flight window, hands its partitions "
                        "back, and exits without a rewind")
    p.add_argument("--fleet-join", default=None, metavar="HOST:PORT",
                   help="hot-join (r20): announce this --fleet-worker "
                        "to the coordinator's --fleet-accept listener; "
                        "admission lands at the next window fence and "
                        "the campaign stays byte-identical to a static "
                        "fleet of the same logical shard count")
    p.add_argument("--fleet-accept", type=int, default=None,
                   metavar="PORT",
                   help="coordinator half of --fleet-join: listen for "
                        "worker announcements on PORT and admit them "
                        "into vacant shard slots at window fences")
    p.add_argument("--fleet-expect", type=int, default=0, metavar="K",
                   help="reserve K remote shard slots at launch; slots "
                        "beyond --fleet-nodes start VACANT (their "
                        "partitions serve from survivors) and fill via "
                        "--fleet-join. The logical shard count — and "
                        "therefore every campaign byte — is fixed "
                        "regardless of when workers arrive")
    p.add_argument("--fleet-window", type=int, default=1, metavar="W",
                   help="framed shard-stream window: steps in flight "
                        "per remote shard between sync barriers (default "
                        "1 = a barrier every case; 8 amortizes the "
                        "round trip 8x with identical output bytes)")
    p.add_argument("--fleet-reduce", choices=("overlap", "boundary"),
                   default="overlap",
                   help="where the fleet merge runs: 'overlap' (default) "
                        "folds case N's reduce into the drain worker "
                        "while case N+1 maps; 'boundary' is the lockstep "
                        "fallback — both are byte-identical")
    p.add_argument("--fleet-rewind", choices=("slice", "full"),
                   default="slice",
                   help="FleetShardLost replay granularity: 'slice' "
                        "(default) re-dispatches only the lost shard's "
                        "slice of the aborted case to the post-migration "
                        "owners (surviving streams stay open); 'full' "
                        "replays the whole case from scratch — both are "
                        "byte-identical at a fixed -s")
    p.add_argument("--node", default=None, help="join a parent node host:port")
    p.add_argument("--svcport", type=int, default=17771,
                   help="distribution/control port")
    p.add_argument("--chaos", default=None, metavar="SPEC",
                   help="deterministic fault injection spec, e.g. "
                        "'dist.send:x2,store.save:x1' or 'device.step:*' "
                        "(services/chaos.py; ERLAMSA_FAULTS is the env "
                        "equivalent, --chaos wins). Replayable: the same "
                        "spec + seed fires the same faults")
    gen = p.add_argument_group(
        "grammar generation (erlamsa_tpu/gen; r17 generate-then-mutate)")
    gen.add_argument("--gen", default=None, metavar="GRAMMAR[:N]",
                     help="compile GRAMMAR (a builtin name or an "
                          "s-expression DSL file; see README "
                          "'Generation-based fuzzing') for device "
                          "expansion. With --feedback, seed the campaign "
                          "with N generated samples (default 64) from one "
                          "batched kernel call — device loss degrades to "
                          "the keyed host oracle byte-identically (chaos "
                          "site gen.expand). Without --feedback the "
                          "grammar feeds the oracle engine's genfuz "
                          "generator slot. Spec errors are hard errors")
    gen.add_argument("--gfcomms", type=int, default=None, metavar="PORT",
                     help="serve grammar-generated data per TCP packet "
                          "(services/gfcomms.py; requires --gen). -s "
                          "seeds the stream and is logged at startup, so "
                          "a fixed seed replays byte-identically")
    gen.add_argument("--gfcomms-batched", action="store_true",
                     help="gfcomms drains a connection's pending packets "
                          "through ONE device kernel call; responses are "
                          "keyed by (connection, packet index), so the "
                          "single-connection replay contract holds "
                          "regardless of how packets were batched")
    obs = p.add_argument_group(
        "observability (erlamsa_tpu/obs; pure side channel — outputs at a "
        "fixed -s are byte-identical with tracing on or off)")
    obs.add_argument("--trace", default=None, metavar="FILE",
                     help="write a Chrome-trace-event JSON of pipeline "
                          "spans to FILE (load in Perfetto or "
                          "chrome://tracing)")
    obs.add_argument("--xprof", default=None, metavar="DIR",
                     help="also run jax.profiler into DIR and annotate "
                          "spans, lining host spans up with XLA device "
                          "timelines in XProf/TensorBoard")
    obs.add_argument("--metrics-port", type=int, default=None, metavar="PORT",
                     help="serve Prometheus text exposition on "
                          "PORT/metrics (the faas server also serves "
                          "GET /metrics without this flag)")
    obs.add_argument("--flight-dir", default=None, metavar="DIR",
                     help="flight recorder dump directory: the ring of "
                          "recent spans/events is written here as JSONL "
                          "on device loss, breaker-open, supervisor "
                          "give-up, or SIGUSR2")
    obs.add_argument("--log-format", choices=["text", "json"],
                     default="text",
                     help="json: one object per log line with "
                          "level/ts/component/span_id, correlating logs "
                          "with traces and flight dumps")
    obs.add_argument("--metrics-out", default=None, metavar="FILE",
                     help="write the final metrics snapshot as JSON to "
                          "FILE at exit — the artifact python -m "
                          "erlamsa_tpu.obs.report --metrics reads")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    fleet_mode = (args.shards is not None or args.fleet_nodes
                  or args.spmd or args.fleet_expect)
    if args.fleet_join and args.fleet_worker is None:
        raise SystemExit(
            "erlamsa-tpu: --fleet-join announces a worker, so it "
            "requires --fleet-worker PORT (0 picks an ephemeral port)")
    if args.fleet_expect < 0:
        raise SystemExit("erlamsa-tpu: --fleet-expect must be >= 0")
    if fleet_mode and (args.struct_kernels or args.struct != "off"):
        # hard error, not a printed notice: nobody should believe struct
        # kernels ran fleet-wide when the overlay is single-device only
        raise SystemExit(
            "erlamsa-tpu: --struct is single-device only (the span-splice "
            "overlay routes against one arena): drop --shards/"
            "--fleet-nodes/--spmd to run the struct overlay, or drop "
            "--struct to run the fleet")

    if args.distill and not args.coverage:
        raise SystemExit("erlamsa-tpu: --distill requires --coverage "
                         "(set-cover needs the per-seed coverage tensor)")
    if args.coverage and not args.feedback:
        raise SystemExit("erlamsa-tpu: --coverage requires --feedback "
                         "(coverage gates the feedback runner's adoption)")
    # r19: --coverage composes with the fleet (per-shard attribution
    # ledgers + window-fence OR-reduce, corpus/fleet.py); only the
    # end-of-run distillation still needs the single-device runner
    if args.distill and fleet_mode:
        raise SystemExit(
            "erlamsa-tpu: --distill is single-device only (set-cover "
            "runs over the runner's end-of-run tensor): drop --shards/"
            "--fleet-nodes/--spmd to distill, or drop --distill to run "
            "the fleet with coverage")

    gen_opts = None
    if args.gen:
        # hard errors by design: a typo'd grammar must abort the run with
        # a pointer at the DSL doc, never start an unseeded campaign
        spec, _, n_part = args.gen.partition(":")
        try:
            gen_count = int(n_part) if n_part else 64
        except ValueError:
            raise SystemExit(f"erlamsa-tpu: --gen {args.gen!r}: sample "
                             f"count {n_part!r} is not an integer")
        if gen_count < 1:
            raise SystemExit(f"erlamsa-tpu: --gen {args.gen!r}: sample "
                             f"count must be >= 1")
        from ..gen import GenSpecError, compile_grammar, load_grammar

        try:
            grammar, label = load_grammar(spec)
            compiled = compile_grammar(grammar, source=label)
        except GenSpecError as e:
            raise SystemExit(
                f"erlamsa-tpu: --gen: {e} (grammar DSL reference: "
                f"README.md, 'Generation-based fuzzing')")
        gen_opts = {"grammar": grammar, "compiled": compiled,
                    "label": label, "n": gen_count}
    if args.gen and fleet_mode:
        # hard error, not a silent ignore: generation is single-device
        # first (one panel seeds one store before the campaign starts)
        raise SystemExit(
            "erlamsa-tpu: --gen is single-device only for now: drop "
            "--shards/--fleet-nodes/--spmd to run generate-then-mutate, "
            "or drop --gen to run the fleet")
    if args.gfcomms is not None and not args.gen:
        raise SystemExit("erlamsa-tpu: --gfcomms requires --gen GRAMMAR "
                         "(the grammar to serve)")
    if args.gfcomms_batched and args.gfcomms is None:
        raise SystemExit("erlamsa-tpu: --gfcomms-batched requires "
                         "--gfcomms PORT")

    if args.list:
        _show_list()
        return 0

    if args.list_findings:
        try:
            rows = logger.query_log(args.list_findings, level="finding",
                                    limit=None)
        except (FileNotFoundError, ValueError) as e:
            raise SystemExit(f"erlamsa-tpu: {e}")
        for _id, ts, _level, message in rows:
            print(f"{ts}\t{message}")
        print(f"# {len(rows)} finding(s)", file=sys.stderr)
        return 0

    if args.log_format != "text":
        # before any sink logs a line, so every record is structured
        logger.GLOBAL.set_format(args.log_format)

    if args.logger:
        spec = {}
        for part in args.logger.split(","):
            if part in ("stdout", "stderr"):
                spec[part] = "debug" if args.verbose else "info"
            elif part.startswith("file="):
                spec["file"] = (part[5:], "debug")
            elif part.startswith("sqlite="):
                # findings-and-worse only: every row is an individually
                # fsync'd commit (durability by design), so routing info/
                # debug spam here would starve the drain thread and bloat
                # the store; stream sinks carry the verbose levels
                spec["sqlite"] = (part[7:], "finding")
        logger.GLOBAL.configure(spec)

    try:
        seed = (
            parse_seed(args.seed, allow_source=True)
            if args.seed
            else gen_urandom_seed()
        )
    except ValueError as e:
        raise SystemExit(f"erlamsa-tpu: {e}")
    with open("./last_seed.txt", "w") as f:  # erlamsa_main.erl:135
        f.write(repr(seed))

    # arm fault injection before any engine/service construction so every
    # fault_point in the process sees the spec; chaos firings are keyed on
    # the run seed's first component — replay = same spec + same -s
    from . import chaos

    try:
        if args.chaos:
            chaos.configure(args.chaos, seed=seed[0])
        else:
            chaos.configure_from_env(seed=seed[0])
    except ValueError as e:
        raise SystemExit(f"erlamsa-tpu: {e}")

    # observability arms before engines/services for the same reason as
    # chaos: every span/event from construction onward must be seen
    from ..obs import flight, trace

    if args.flight_dir:
        flight.configure(args.flight_dir)
    if args.trace or args.xprof:
        # the campaign trace id is seed-derived (no wall clock, no
        # entropy): a fleet coordinator hands the same id to every
        # worker frame, so the merged export is one logical trace
        trace.configure(path=args.trace, xprof=args.xprof,
                        trace_id="c%08x" % (seed[0] & 0xFFFFFFFF))
    if args.metrics_port:
        from ..obs import prom

        prom.serve_metrics(args.metrics_port)

    def _finish():
        # idempotent: trace.export() is a no-op without --trace, and the
        # atexit hook (armed in trace.configure) backstops service modes
        # that never reach these finallys
        trace.export()
        if args.metrics_out:
            import json

            from . import metrics

            try:
                with open(args.metrics_out, "w") as f:
                    json.dump(metrics.GLOBAL.snapshot(), f, indent=2,
                              default=str)
            except OSError as e:
                logger.log("warning", "cli: metrics snapshot to %s "
                           "failed: %s", args.metrics_out, e)
        logger.GLOBAL.flush()

    from ..oracle.gen import default_generators
    from ..oracle.mutations import default_mutations
    from ..oracle.patterns import default_patterns

    n = 2**62 if args.count == "inf" else int(args.count)
    opts = {
        "paths": args.paths or ["-"],
        "n": n,
        "seed": seed,
        "mutations": _parse_actions(args.mutations, default_mutations()),
        "patterns": _parse_actions(args.patterns, default_patterns()),
        "generators": _parse_actions(args.generators, default_generators()),
        "blockscale": args.blockscale,
        "skip": args.skip,
        "sleep": args.sleep,
        "maxfails": args.maxfails,
        # None = unset: engines treat it as unlimited, service modes as 30s
        "maxrunningtime": args.maxrunningtime,
        "sequence_muta": args.sequence_muta,
        "recursive": args.recursive,
        "checkpoint_every": args.checkpoint_every,
        **({"device_capacity_max": args.device_capacity_max}
           if args.device_capacity_max is not None else {}),
        "workers": args.workers,
        "workers_same_seed": args.workers_same_seed,
        "corpus_dir": args.corpus,
        "feedback": args.feedback,
        "pipeline": args.pipeline,
        "layout": args.layout,
        "shards": args.shards,
        "spmd": args.spmd,
        "fleet_nodes": ([s for s in args.fleet_nodes.split(",") if s]
                        if args.fleet_nodes else None),
        "fleet_window": args.fleet_window,
        "fleet_reduce": args.fleet_reduce,
        "fleet_rewind": args.fleet_rewind,
        "fleet_accept": args.fleet_accept,
        "fleet_expect": args.fleet_expect,
        "arena_pages": args.arena_pages,
        "arena_page": args.arena_page,
        "arena_classes": args.arena_classes,
        "adopt": args.adopt,
        "coverage": args.coverage,
        "distill": args.distill,
        "struct": "device" if args.struct_kernels else args.struct,
        "output": args.output,
        "verbose": args.verbose,
        "meta_path": args.meta,
        "certfile": args.certfile,
        "keyfile": args.keyfile,
        "state_path": args.state,
        # --gen: the runner seeds from the compiled grammar; the oracle
        # engine's genfuz slot picks up the raw grammar (sequential path)
        **({"gen": gen_opts, "gen_grammar": gen_opts["grammar"]}
           if gen_opts else {}),
    }

    if args.detach:
        import os as _os

        # classic double-fork detach (the reference re-execs a -detached
        # escript, src/erlamsa.erl:9-13 + erlamsa_daemon)
        if _os.fork() > 0:
            return 0
        _os.setsid()
        if _os.fork() > 0:
            _os._exit(0)

    # externals and the profiler load before service modes so -e/-d apply
    # to the proxy/FaaS/node paths too
    if args.external:
        from .external import load_external

        ext = load_external(args.external)
        if ext:
            opts["external_module"] = ext
            gen = ext.generator()
            if gen is not None:
                opts["external_generator"] = gen
            post = ext.post()
            if post is not None:
                opts["post"] = post

    if args.debug:
        from .metrics import Profiler

        Profiler().start()

    # service modes
    if args.httpsvc:
        from .faas import serve

        host, _, port = args.httpsvc.rpartition(":")
        opts["cmanager_store"] = args.cmanager_store
        opts["serving"] = args.serving
        if args.serving_slots is not None:
            opts["slots"] = args.serving_slots
        if args.capacity is not None:
            opts["capacity"] = args.capacity
        opts["queue_cap"] = args.queue_cap
        opts["tenant_rate"] = args.tenant_rate
        opts["tenant_burst"] = args.tenant_burst
        return serve(host or "0.0.0.0", int(port), opts, backend=args.backend,
                     batch=args.batch)
    if args.proxy:
        from .proxy import FuzzProxy

        return FuzzProxy(args.proxy, args.proxy_prob, opts,
                         backend=args.backend, bypass=args.bypass,
                         ascent=args.ascent).start(block=True)
    if args.gfcomms is not None:
        from .gfcomms import GfComms

        engine = None
        if args.gfcomms_batched:
            from ..gen import GenEngine

            # fuzz=True: the batched service replaces the sequential
            # fuzz_grammar path, so leaves mutate at the 1/depth rate
            engine = GenEngine(gen_opts["compiled"], seed, fuzz=True)
        try:
            return GfComms(args.gfcomms, grammar=gen_opts["grammar"],
                           seed=seed, engine=engine).serve(block=True)
        finally:
            _finish()

    if args.fleet_worker is not None:
        from .dist import run_shard_worker

        return run_shard_worker(args.fleet_worker, opts,
                                join=args.fleet_join)

    if args.node:
        from .dist import run_node

        host, _, port = args.node.rpartition(":")
        return run_node(host or "127.0.0.1", int(port), opts)

    if args.monitor:
        from .monitors import start_monitors

        start_monitors(args.monitor)

    if args.feedback:
        # the feedback loop IS the batched device engine: energy-scheduled
        # store draws, bucketed batches, bus events promoting seeds
        if not args.corpus:
            raise SystemExit("erlamsa-tpu: --feedback requires --corpus DIR")
        from ..corpus.runner import run_corpus_batch

        cov_hub = None
        if args.coverage:
            # the hub is jax-free and binds before the runner imports the
            # device stack, so instrumented targets can connect back the
            # moment the campaign starts
            from .monitors import CoverageHub

            cov_hub = CoverageHub(port=args.coverage_port or 0).start()
            opts["coverage_hub"] = cov_hub
        try:
            return run_corpus_batch(opts, batch=args.batch)
        finally:
            if cov_hub is not None:
                cov_hub.stop()
                cov_hub.join(timeout=5)
            _finish()

    if args.backend == "tpu":
        from .batchrunner import run_tpu_batch

        try:
            return run_tpu_batch(opts, batch=args.batch)
        finally:
            _finish()

    if args.corpus:
        # stateless oracle path with a store: dedup the inputs into DIR
        # and run over the store's seed files (the store IS files)
        from ..corpus.store import CorpusStore

        store = CorpusStore(args.corpus)
        in_paths = [p for p in opts["paths"] if p != "-"]
        if in_paths:
            from ..oracle.gen import _expand_paths

            expanded = (_expand_paths(in_paths) if args.recursive
                        else in_paths)
            new, dup, skipped = store.add_paths(expanded)
            print(f"# corpus: {new} new, {dup} duplicate, {skipped} "
                  f"skipped -> {len(store)} seeds", file=sys.stderr)
        if len(store) == 0:
            raise SystemExit("erlamsa-tpu: --corpus store is empty and no "
                             "readable seeds were given")
        opts["paths"] = store.seed_paths()
        opts["recursive"] = False

    try:
        return _run_oracle(opts)
    finally:
        # findings from the last cases must reach durable sinks (sqlite/
        # file), and the trace must land, before the process dies
        _finish()


def _run_oracle(opts: dict) -> int:
    from ..oracle.engine import Engine

    workers = opts.get("workers", 1)
    output = opts.get("output", "-")
    if workers > 1 and output not in ("-", "return", "stdout", "stderr"):
        # workers create their own writers — binding sockets here too would
        # clash with theirs (e.g. tcp:// listen mode)
        from .workerpool import run_workers

        return run_workers(opts, None)

    writer, _maxtime = out.string_outputs(output)
    meta_fd = open(opts["meta_path"], "w") if opts.get("meta_path") else None

    def writing(case_idx, data, meta):
        if writer is not None:
            writer(case_idx, data, meta)
        if meta_fd:
            meta_fd.write(f"{case_idx}\t{meta!r}\n")

    eng = Engine(opts)
    if writer is None:
        # return mode: Engine collects, CLI prints the collected results
        results = eng.run()
        for rdata in results:
            sys.stdout.buffer.write(rdata)
        sys.stdout.buffer.flush()
    else:
        eng.run(writing)
    if meta_fd:
        meta_fd.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
