"""Reusable resilience policies: retry, circuit breaker, node health.

The reference leans on OTP for all of this — crashed children restart
one_for_one (src/erlamsa_sup.erl:51-54), dead distribution nodes fall out
of the parent's table after 17 silent seconds (src/erlamsa_app.erl:
210-246), hung cases are reaped (src/erlamsa_fsupervisor.erl:96-105).
This module is the policy half of that story for the Python port, shared
by services/dist.py (multi-node failover), services/batcher.py (device
step retry) and corpus/store.py (durable-save retry):

- RetryPolicy: jittered exponential backoff with deadline propagation —
  a caller-supplied monotonic deadline caps total time spent retrying,
  so a 90s client budget is never blown inside a retry loop.
- CircuitBreaker: per-endpoint closed/open/half-open gate. A run of
  failures opens the breaker (calls are refused without touching the
  endpoint); after a cool-down one probe call is admitted and its
  outcome closes or re-opens the circuit.
- HealthTable: breaker-backed endpoint registry with an EWMA health
  score — the NodePool's brain: pick() prefers healthy endpoints,
  refuses open-breaker ones, and admits half-open probes so an evicted
  node that recovered is re-admitted automatically.

Determinism: retry jitter is drawn from a counter-keyed hash when the
policy is given a key (the chaos replay contract — see services/chaos.py)
and from os.urandom otherwise. Sleeps affect WHEN work happens, never
what is computed, so jitter never breaks the -s output contract.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time

from . import logger, metrics


class RetryExhausted(Exception):
    """Every attempt failed (or the deadline passed); the last underlying
    error is the __cause__."""


class RetryPolicy:
    """Jittered exponential retry with deadline propagation.

    attempts: total tries (1 = no retry). base/factor/max_delay: the
    backoff schedule base * factor**n clipped to max_delay. jitter: each
    delay is scaled by a uniform draw in [1-jitter, 1]. retry_on: the
    exception types worth retrying — anything else propagates
    immediately.
    """

    def __init__(self, attempts: int = 3, base: float = 0.05,
                 factor: float = 2.0, max_delay: float = 2.0,
                 jitter: float = 0.5,
                 retry_on: tuple = (OSError, ValueError)):
        self.attempts = max(1, int(attempts))
        self.base = base
        self.factor = factor
        self.max_delay = max_delay
        self.jitter = min(max(jitter, 0.0), 1.0)
        self.retry_on = retry_on

    def delay(self, attempt: int, key: str | None = None) -> float:
        """Backoff before retry number `attempt` (1-based). With a key the
        jitter draw is hash(key, attempt) — replayable; without, urandom."""
        d = min(self.base * (self.factor ** (attempt - 1)), self.max_delay)
        if self.jitter <= 0.0:
            return d
        if key is not None:
            h = hashlib.sha256(f"{key}:{attempt}".encode()).digest()
            frac = int.from_bytes(h[:8], "big") / float(1 << 64)
        else:
            frac = int.from_bytes(os.urandom(8), "big") / float(1 << 64)
        return d * (1.0 - self.jitter * frac)

    def call(self, fn, *args, site: str = "?", deadline: float | None = None,
             key: str | None = None, on_retry=None, **kwargs):
        """Run fn(*args, **kwargs) under this policy.

        deadline: absolute time.monotonic() bound — no retry sleep starts
        past it, and the sleep itself is clipped to the time remaining
        (deadline propagation: a caller's budget caps the whole loop).
        on_retry(attempt, exc): caller hook per failed attempt (e.g. mark
        an endpoint unhealthy before the next try). Raises RetryExhausted
        (with the last error as __cause__) when every attempt failed."""
        last: BaseException | None = None
        for attempt in range(1, self.attempts + 1):
            try:
                return fn(*args, **kwargs)
            except self.retry_on as e:
                last = e
                metrics.GLOBAL.record_event(f"retry:{site}")
                if on_retry is not None:
                    on_retry(attempt, e)
                if attempt >= self.attempts:
                    break
                d = self.delay(attempt, key=key)
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        logger.log("warning", "retry %s: deadline passed "
                                   "after attempt %d: %s", site, attempt, e)
                        break
                    d = min(d, remaining)
                logger.log("warning", "retry %s: attempt %d failed (%s), "
                           "retrying in %.3fs", site, attempt, e, d)
                if d > 0:
                    time.sleep(d)
        raise RetryExhausted(
            f"{site}: {self.attempts} attempt(s) failed"
        ) from last


# breaker states
CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"


class CircuitBreaker:
    """Per-endpoint failure gate (the dist parent's eviction, made
    re-admitting). failure_threshold consecutive failures open the
    circuit; while open, allow() refuses instantly; after reset_timeout
    one HALF_OPEN probe is admitted — success closes the circuit,
    failure re-opens it for another cool-down."""

    def __init__(self, failure_threshold: int = 3,
                 reset_timeout: float = 5.0, name: str = "?"):
        self.failure_threshold = max(1, int(failure_threshold))
        self.reset_timeout = reset_timeout
        self.name = name
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probing = False

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def _maybe_half_open(self):
        if (self._state == OPEN
                and time.monotonic() - self._opened_at >= self.reset_timeout):
            self._state = HALF_OPEN
            self._probing = False

    def allow(self) -> bool:
        """May a call proceed now? In HALF_OPEN exactly one caller gets a
        True (the probe) until its outcome is recorded."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == HALF_OPEN and not self._probing:
                self._probing = True
                return True
            return False

    def record_success(self):
        with self._lock:
            if self._state != CLOSED:
                metrics.GLOBAL.record_event("breaker_closed")
                logger.log("info", "breaker %s: probe ok, circuit closed",
                           self.name)
            self._state = CLOSED
            self._failures = 0
            self._probing = False

    def reset(self):
        """Forget everything: CLOSED, zero failures, no probe in flight.
        The eviction path (HealthTable.drop_stale) calls this so a
        dropped endpoint that later re-registers — or any caller still
        holding the NodeHealth — never resurrects a stale open breaker
        and sits out a cool-down it no longer owes."""
        with self._lock:
            self._state = CLOSED
            self._failures = 0
            self._probing = False

    def record_failure(self):
        with self._lock:
            self._maybe_half_open()
            self._failures += 1
            if self._state == HALF_OPEN or (
                    self._state == CLOSED
                    and self._failures >= self.failure_threshold):
                self._state = OPEN
                self._opened_at = time.monotonic()
                self._probing = False
                # name the breaker in the flight ring BEFORE the generic
                # breaker_open event trips the dump, so the dump says
                # WHICH circuit opened
                from ..obs import flight

                flight.GLOBAL.note("breaker_detail", name=self.name,
                                   failures=self._failures)
                metrics.GLOBAL.record_event("breaker_open")
                logger.log("warning", "breaker %s: circuit OPEN after %d "
                           "failure(s), cooling %.1fs", self.name,
                           self._failures, self.reset_timeout)


class NodeHealth:
    """One endpoint's health record: EWMA success score in [0, 1] plus
    its breaker. A fresh node starts optimistic (score 1.0)."""

    __slots__ = ("score", "breaker", "last_seen", "successes", "failures")

    EWMA = 0.3  # weight of the newest outcome

    def __init__(self, name: str = "?", failure_threshold: int = 3,
                 reset_timeout: float = 5.0):
        self.score = 1.0
        self.breaker = CircuitBreaker(failure_threshold, reset_timeout, name)
        self.last_seen = time.monotonic()
        self.successes = 0
        self.failures = 0

    def report(self, ok: bool):
        self.score = (1.0 - self.EWMA) * self.score + self.EWMA * (
            1.0 if ok else 0.0
        )
        if ok:
            self.successes += 1
            self.breaker.record_success()
        else:
            self.failures += 1
            self.breaker.record_failure()


class HealthTable:
    """Endpoint registry scored for routing. touch() registers/refreshes
    (the keepalive path), report() folds an outcome in, pick() returns a
    usable endpoint — healthy ones weighted by score, open breakers
    skipped, half-open probes admitted (that admission IS the
    re-admission path for a recovered node)."""

    def __init__(self, rng, failure_threshold: int = 3,
                 reset_timeout: float = 5.0):
        self._rng = rng
        self._lock = threading.Lock()
        self._nodes: dict = {}
        self._failure_threshold = failure_threshold
        self._reset_timeout = reset_timeout

    def touch(self, endpoint) -> bool:
        """Register/refresh an endpoint; True when it is new."""
        with self._lock:
            fresh = endpoint not in self._nodes
            if fresh:
                self._nodes[endpoint] = NodeHealth(
                    str(endpoint), self._failure_threshold,
                    self._reset_timeout,
                )
            self._nodes[endpoint].last_seen = time.monotonic()
        return fresh

    def drop(self, endpoint):
        with self._lock:
            self._nodes.pop(endpoint, None)

    def drop_stale(self, max_age: float) -> list:
        """Remove endpoints silent for more than max_age (the keepalive
        eviction); returns the dropped endpoints. Each dropped node's
        breaker is reset on the way out: staleness is an eviction, not a
        failure verdict, so a re-admitted endpoint starts CLOSED instead
        of inheriting an open circuit from its previous life."""
        now = time.monotonic()
        with self._lock:
            dead = [k for k, h in self._nodes.items()
                    if now - h.last_seen > max_age]
            for k in dead:
                # table lock -> breaker lock matches report()'s nesting
                self._nodes[k].breaker.reset()
                del self._nodes[k]
        for _ in dead:  # outside the lock: metrics/flight own their locks
            metrics.GLOBAL.record_event("dropped_stale")
        return dead

    def report(self, endpoint, ok: bool):
        with self._lock:
            h = self._nodes.get(endpoint)
            if h is not None:
                h.report(ok)

    def start_eviction(self, name: str, interval: float, max_age: float,
                       on_drop=None):
        """THE keepalive-eviction loop, shared by every HealthTable user
        (the dist NodePool's node table, a fleet coordinator's worker
        registry): a supervised daemon thread drop_stale()s this table
        every `interval` seconds, so `dropped_stale` accounting and
        breaker-reset-on-eviction behave identically wherever node
        health lives. `on_drop(endpoint)` fires per evicted endpoint
        (the caller's logging/metrics hook). Returns the supervised
        thread handle."""
        from .supervisor import supervise

        def loop():
            while True:
                time.sleep(interval)
                for ep in self.drop_stale(max_age):
                    if on_drop is not None:
                        on_drop(ep)

        return supervise(name, loop)

    def pick(self, exclude=()):
        """A usable endpoint or None. Closed-breaker endpoints are drawn
        score-weighted; when none qualify, a half-open breaker may admit
        one probe call (re-admission)."""
        with self._lock:
            usable = []
            half_open = []
            for ep, h in self._nodes.items():
                if ep in exclude:
                    continue
                st = h.breaker.state
                if st == CLOSED:
                    usable.append((ep, max(h.score, 0.05)))
                elif st == HALF_OPEN:
                    half_open.append(ep)
            if usable:
                total = sum(w for _, w in usable)
                r = self._rng.random() * total
                for ep, w in usable:
                    r -= w
                    if r <= 0:
                        return ep
                return usable[-1][0]
            for ep in half_open:
                if self._nodes[ep].breaker.allow():
                    metrics.GLOBAL.record_event("node_probe")
                    return ep
            return None

    def count(self) -> int:
        with self._lock:
            return len(self._nodes)

    def endpoints(self) -> list:
        with self._lock:
            return list(self._nodes)

    def stats(self) -> dict:
        with self._lock:
            return {
                str(ep): {
                    "score": round(h.score, 3),
                    "state": h.breaker.state,
                    "successes": h.successes,
                    "failures": h.failures,
                }
                for ep, h in self._nodes.items()
            }
