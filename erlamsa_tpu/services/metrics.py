"""Metrics and the debug profiler.

Reference: src/erlamsa_profiler.erl (-d mode: 5s loop logging process
count/memory) and the per-case metadata recorder (maybe_meta_logger,
src/erlamsa_main.erl:58-70). The TPU design makes per-batch device timing
and samples/sec first-class (the BASELINE metric, SURVEY.md §5.1).
"""

from __future__ import annotations

import threading
import time

from ..obs import flight, hist
from . import logger

#: latency histograms folded into the snapshot and the Prometheus
#: exposition (obs/prom.py): batch_latency is collect→drain for one
#: device batch, request_latency is enqueue→answer for one faas/batcher
#: request, device_step is the device-side step time alone
HIST_NAMES = ("batch_latency", "request_latency", "device_step")


class Ewma:
    """Windowed exponential moving average for gauge-style ratios (slot
    fill, occupancy): recent behaviour dominates, so bursty load reads
    as bursty instead of being flattened by a cumulative mean. Updates
    are single-float stores (GIL-atomic); callers serialize per engine
    thread, so no lock is carried."""

    __slots__ = ("alpha", "_v")

    def __init__(self, alpha: float = 0.2):
        self.alpha = float(alpha)
        self._v: float | None = None

    def update(self, x: float) -> float:
        v = self._v
        self._v = x if v is None else self.alpha * x + (1 - self.alpha) * v
        return self._v

    @property
    def value(self) -> float:
        return 0.0 if self._v is None else self._v


class Counters:
    """Throughput counters shared by batch runners and services."""

    def __init__(self):
        self._lock = threading.Lock()
        self.samples = 0
        self.bytes_out = 0
        self.batches = 0
        self.requests = 0
        self.device_time = 0.0
        # log2-bucketed latency histograms; each Hist carries its own
        # lock, so observe() calls stay OUTSIDE self._lock (no nesting)
        self.hists: dict[str, hist.Hist] = {n: hist.Hist() for n in HIST_NAMES}
        # per-mutator applied/failed tallies, keyed by registry code:
        # device counts come from FuzzMeta.applied (corpus/runner.py),
        # host counts from the oracle's used/failed metas
        # (hybrid.apply_outcomes)
        self.mutators: dict[str, list[int]] = {}
        # per-capacity-bucket assembly stats (corpus/assembler.py)
        self.buckets: dict[int, dict[str, int]] = {}
        # scheduled rows truncated to the device/arena capacity — the
        # overflow count the assembler comment promises is surfaced
        self.truncated = 0
        # latest paged-arena health snapshot (corpus/arena.py stats():
        # pages/pages_free/occupancy/resident_seeds/evictions/defrags/
        # spills/uploads/bytes_uploaded) — gauge-style, set not summed
        self.arena: dict | None = None
        # pipeline overlap accounting (corpus/runner.py, services/batcher):
        # per-stage wall seconds keyed by stage name; when stages run on
        # overlapping threads, sum(stages) > pipeline_wall_s measures the
        # overlap won (ratio 1.0 = fully serialized)
        self.stages: dict[str, float] = {}
        self.pipeline_wall = 0.0
        self.drain_backlog_peak = 0
        # resilience accounting (services/resilience.py, services/chaos.py):
        # injected-fault firings per site, retry/breaker/failover event
        # tallies, and the degraded flag — 1 while the corpus runner serves
        # from the host oracle because the device was lost
        self.faults: dict[str, int] = {}
        self.events: dict[str, int] = {}
        self.degraded = 0
        # latest fleet placement snapshot (parallel/shards.py
        # FleetPlacement.snapshot(): shards/live/epoch/migrations plus
        # per-shard lease + breaker state) — gauge-style, set not summed
        self.fleet: dict | None = None
        # latest membership-ledger snapshot (r20 elastic membership:
        # generation counter + join/drain/evict event totals + vacancy)
        # — gauge-style like the placement snapshot above
        self.membership: dict | None = None
        # latest serving-engine snapshot (services/serving.py stats() /
        # TpuBatcher.stats(): mode/slots/fill_efficiency/steps_per_request/
        # compiles) — gauge-style, set not summed
        self.serving: dict | None = None
        # host-tail accounting (r13): per-code samples that left the
        # device stream for the host oracle, and the routed-sample total
        # they are a fraction of. With --struct-kernels the host_routed
        # keys should collapse to {"zip"} (+"overflow" for samples past
        # the device budget) — the erlamsa_host_routed_total counter and
        # host_tail_pct gauge in /metrics make the tail observable.
        self.host_routed: dict[str, int] = {}
        self.host_samples = 0
        self.routed_samples = 0
        # fleet transport accounting (services/dist.TransportTally
        # mirrors in here): raw frame bytes by direction plus awaited
        # round trips — the erlamsa_fleet_transport_bytes_total{dir}
        # and erlamsa_fleet_round_trips_total counters in /metrics
        self.transport = {"bytes_sent": 0, "bytes_recv": 0,
                          "round_trips": 0, "frame_bytes_max": 0}
        # reduce-overlap ratio (corpus/fleet.py): fraction of the
        # host-side merge hidden behind remote shard compute —
        # gauge-style, set not summed
        self.reduce_overlap = 0.0
        # admission-control sheds by reason (queue_full/quota/chaos) —
        # the faas_rejected_total counter in /metrics
        self.rejected: dict[str, int] = {}
        # coverage-plane accounting (services/monitors.CoverageHub +
        # corpus runner fold): frame dispositions, fold totals, the
        # edges/degraded gauges — the erlamsa_coverage_* families
        self.coverage = {"frames": 0, "stale": 0, "torn": 0, "faulted": 0,
                         "folds": 0, "new_edges": 0, "edges": 0,
                         "degraded": 0, "distilled": 0}
        # grammar-generation accounting (gen/engine.py): device panel
        # expansions, generated bytes, truncated rows, per-sample host
        # fallbacks and the gen-degraded gauge — the erlamsa_gen_*
        # families
        self.gen = {"expansions": 0, "bytes": 0, "truncated": 0,
                    "host_fallback": 0, "degraded": 0}
        # monitor-plane event tallies by kind (crash/crash_dup/
        # hang_killed/spawn_failed/after_spawned, ...) — the
        # erlamsa_monitor_events_total counter
        self.monitor_events: dict[str, int] = {}
        # per-tenant served/rejected tallies (services/serving.TenantTable)
        self.tenants: dict[str, dict[str, int]] = {}
        self.t0 = time.perf_counter()

    def record_batch(self, n_samples: int, n_bytes: int, device_seconds: float):
        with self._lock:
            self.samples += n_samples
            self.bytes_out += n_bytes
            self.batches += 1
            self.device_time += device_seconds
        self.hists["device_step"].observe(device_seconds)

    def record_request(self, latency_seconds: float):
        """One client-visible request answered (faas/batcher), with its
        enqueue→answer latency."""
        with self._lock:
            self.requests += 1
        self.hists["request_latency"].observe(latency_seconds)

    def observe(self, name: str, seconds: float):
        """Feed one observation into a named latency histogram."""
        self.hists[name].observe(seconds)

    def record_mutator(self, code: str, applied: bool = True, n: int = 1):
        with self._lock:
            entry = self.mutators.setdefault(code, [0, 0])
            entry[0 if applied else 1] += n

    def record_host_routed(self, code: str, n: int = 1):
        """`n` samples left the device stream and were served by the
        host engine under mutator `code` ("overflow" = full-oracle escape
        for samples past the device budget). Breadcrumbed per call —
        callers aggregate per case, so the flight ring sees one note per
        (case, code), not one per sample."""
        with self._lock:
            self.host_routed[code] = self.host_routed.get(code, 0) + n
            self.host_samples += n
        # outside the lock: the flight ring has its own lock
        flight.GLOBAL.note("host_routed", code=code, count=n)

    def record_routed_total(self, n: int):
        """`n` samples were routed this case (device + host) — the
        denominator of host_tail_pct."""
        with self._lock:
            self.routed_samples += n

    def record_bucket(self, capacity: int, rows: int, pad_rows: int,
                      padded_bytes_wasted: int):
        with self._lock:
            b = self.buckets.setdefault(
                capacity,
                {"batches": 0, "rows": 0, "pad_rows": 0,
                 "padded_bytes_wasted": 0},
            )
            b["batches"] += 1
            b["rows"] += rows
            b["pad_rows"] += pad_rows
            b["padded_bytes_wasted"] += padded_bytes_wasted

    def record_truncated(self, n: int):
        """`n` scheduled rows exceeded the device/arena capacity this
        case and were truncated. Rare enough to breadcrumb every time —
        a run that silently truncates is a run fuzzing the wrong bytes."""
        with self._lock:
            self.truncated += n
        # outside the lock: the flight ring has its own lock
        flight.GLOBAL.note("truncated_rows", count=n)

    def record_arena(self, stats: dict):
        """Latest arena health snapshot (corpus/arena.py stats())."""
        with self._lock:
            self.arena = dict(stats)
        # outside the lock: the flight ring has its own lock. One
        # class-mix breadcrumb per snapshot so a post-mortem shows how
        # the ragged arena's capacity classes were actually populated.
        classes = stats.get("classes")
        if classes:
            flight.GLOBAL.note(
                "arena_class_mix",
                mix={cap: c["resident_seeds"]
                     for cap, c in classes.items()},
                adopted=stats.get("adopted", 0),
            )

    def record_fleet(self, stats: dict):
        """Latest fleet placement snapshot (corpus/fleet.py): leases,
        per-shard breaker state, migration epoch."""
        with self._lock:
            self.fleet = dict(stats)

    def record_membership(self, snap: dict):
        """Latest membership-ledger state (r20): ``generation``
        (monotonic), ``events`` totals by kind (join/drain/evict/...),
        and ``vacant`` (remote slots with no tenant). Renders as the
        erlamsa_fleet_membership_* family in /metrics."""
        with self._lock:
            self.membership = dict(snap)

    def record_serving(self, stats: dict):
        """Latest serving-engine snapshot (continuous or flush)."""
        with self._lock:
            self.serving = dict(stats)

    def record_rejected(self, reason: str):
        """One request shed by admission control (HTTP 429), by reason."""
        with self._lock:
            self.rejected[reason] = self.rejected.get(reason, 0) + 1

    def record_tenant(self, tenant: str, served: int = 0, rejected: int = 0):
        """Per-tenant request accounting (faas multi-tenancy)."""
        with self._lock:
            t = self.tenants.setdefault(tenant, {"served": 0, "rejected": 0})
            t["served"] += served
            t["rejected"] += rejected

    def record_transport(self, sent: int = 0, recv: int = 0,
                         round_trips: int = 0, frame_bytes: int = 0):
        """Fleet transport deltas (framed shard streams): raw wire bytes
        by direction, plus awaited round trips. ``frame_bytes`` is the
        largest physical frame of the call and max-merges (r19 chunked
        continuation frames keep it bounded by FRAME_CHUNK)."""
        with self._lock:
            self.transport["bytes_sent"] += int(sent)
            self.transport["bytes_recv"] += int(recv)
            self.transport["round_trips"] += int(round_trips)
            if int(frame_bytes) > self.transport["frame_bytes_max"]:
                self.transport["frame_bytes_max"] = int(frame_bytes)

    def set_reduce_overlap(self, ratio: float):
        """Fraction of the fleet's host-side merge hidden behind shard
        compute (0 = fully serialized, 1 = fully overlapped)."""
        with self._lock:
            self.reduce_overlap = float(ratio)

    def record_stage(self, name: str, seconds: float):
        """Accumulate wall time for one pipeline stage (schedule, assemble,
        dispatch, drain_wait, hash, write, ...)."""
        with self._lock:
            self.stages[name] = self.stages.get(name, 0.0) + seconds

    def record_pipeline_wall(self, seconds: float):
        """Wall time a pipelined segment actually took end to end — the
        denominator of the overlap ratio."""
        with self._lock:
            self.pipeline_wall += seconds

    def record_drain_backlog(self, depth: int):
        """High-water mark of cases queued behind the drain worker."""
        with self._lock:
            if depth > self.drain_backlog_peak:
                self.drain_backlog_peak = depth

    def record_fault(self, site: str):
        """One chaos-injected failure fired at `site`."""
        with self._lock:
            self.faults[site] = self.faults.get(site, 0) + 1
        # outside the lock: the flight ring has its own lock and a trip
        # may write a dump file — never under the counters lock
        flight.GLOBAL.note("fault", site=site)

    def record_event(self, kind: str):
        """One resilience event: retry:<site>, breaker_open/closed,
        failover, dist_local_fallback, node_evicted, device_lost,
        device_recovered, supervisor_give_up, ..."""
        with self._lock:
            self.events[kind] = self.events.get(kind, 0) + 1
        # trip kinds (device_lost, breaker_open, supervisor_give_up)
        # auto-dump the ring inside note()
        flight.GLOBAL.note(kind)

    def event_counts(self) -> dict[str, int]:
        """Copy of the resilience-event tallies (fence_rejected,
        telemetry_lost, ...) — the piece the fleet checkpoint persists
        so counters survive a coordinator resume."""
        with self._lock:
            return dict(self.events)

    def restore_event_floor(self, kind: str, floor: int) -> None:
        """Raise an event counter to at least `floor` (checkpoint
        restore). Max-merge, never assignment: events recorded between
        process start and restore must not be erased, and a counter can
        never go backwards across a resume."""
        floor = int(floor)
        with self._lock:
            if self.events.get(kind, 0) < floor:
                self.events[kind] = floor

    def federation_totals(self) -> dict:
        """Cumulative totals a fleet worker ships in its shard_telemetry
        reply (obs/federate.py re-exposes them node-labeled). Cumulative
        rather than delta on purpose: a lost or duplicated telemetry
        frame then means stale data, never corrupted counters."""
        with self._lock:
            counters = {
                "samples": self.samples,
                "batches": self.batches,
                "bytes_out": self.bytes_out,
                "device_s": round(self.device_time, 6),
                "transport_bytes_sent": self.transport["bytes_sent"],
                "transport_bytes_recv": self.transport["bytes_recv"],
                "round_trips": self.transport["round_trips"],
                "degraded": self.degraded,
            }
            events = dict(self.events)
            faults = dict(self.faults)
            stages = {k: round(v, 6) for k, v in self.stages.items()}
        # hists carry their own locks — snapshot outside self._lock
        hists = {
            name: {"counts": list(s["counts"]), "sum": s["sum"],
                   "count": s["count"]}
            for name, s in ((n, h.snapshot())
                            for n, h in self.hists.items())
        }
        return {"counters": counters, "events": events, "faults": faults,
                "stages": stages, "hists": hists}

    def record_monitor(self, kind: str):
        """One monitor-plane event (spawn/crash/hang bookkeeping)."""
        with self._lock:
            self.monitor_events[kind] = self.monitor_events.get(kind, 0) + 1

    def record_coverage_frame(self, result: str):
        """One coverage frame's disposition: ok/stale/torn/faulted."""
        key = "frames" if result == "ok" else result
        with self._lock:
            if key in self.coverage:
                self.coverage[key] += 1

    def record_coverage_fold(self, maps: int, new_edges: int, edges: int):
        """One case-boundary coverage fold: `maps` bitmaps folded,
        `new_edges` genuinely new, `edges` the global gauge after."""
        with self._lock:
            self.coverage["folds"] += 1
            self.coverage["new_edges"] += int(new_edges)
            self.coverage["edges"] = int(edges)

    def record_distilled(self, n: int):
        """`n` seeds retired by the set-cover distillation pass."""
        with self._lock:
            self.coverage["distilled"] += int(n)

    def set_coverage_degraded(self, on: bool):
        """Flip the coverage-degraded gauge: 1 while the campaign runs
        on hash-novelty because the monitor plane died (distinct from
        the device-loss `degraded` flag — the device may be fine)."""
        with self._lock:
            self.coverage["degraded"] = 1 if on else 0

    def record_gen_expand(self, samples: int, nbytes: int, truncated: int):
        """One grammar-panel expansion: `samples` rows generated,
        `nbytes` payload bytes, `truncated` rows that hit a static
        bound (panel width / step budget / sizer records)."""
        with self._lock:
            self.gen["expansions"] += int(samples)
            self.gen["bytes"] += int(nbytes)
            self.gen["truncated"] += int(truncated)

    def record_gen_fallback(self, samples: int):
        """`samples` rows expanded by the keyed host oracle because the
        device call failed (chaos gen.expand or a real device loss)."""
        with self._lock:
            self.gen["host_fallback"] += int(samples)

    def set_gen_degraded(self, on: bool):
        """Flip the gen-degraded gauge: 1 while grammar generation runs
        on the host oracle (distinct from the runner's device-loss flag
        — generation may degrade while mutation is healthy)."""
        with self._lock:
            self.gen["degraded"] = 1 if on else 0

    def set_degraded(self, on: bool):
        """Flip the degraded-mode flag (corpus runner fell back to the
        host oracle after device loss / recovered)."""
        with self._lock:
            self.degraded = 1 if on else 0

    def snapshot(self) -> dict:
        with self._lock:
            wall = time.perf_counter() - self.t0
            # overlap_ratio: sum of per-stage wall over true pipeline wall.
            # 1.0 = serialized; >1 = host stages ran while the device (or
            # another host stage) was busy. device_idle_frac: fraction of
            # the pipelined wall with no device step in flight (dispatch +
            # drain_wait bound device-busy time from above).
            stage_sum = sum(self.stages.values())
            dev_busy = (self.stages.get("dispatch", 0.0)
                        + self.stages.get("drain_wait", 0.0))
            pipeline = {
                "stages": {k: round(v, 3)
                           for k, v in sorted(self.stages.items())},
                "wall_s": round(self.pipeline_wall, 3),
                "overlap_ratio": round(stage_sum / self.pipeline_wall, 3)
                if self.pipeline_wall else 0.0,
                "device_idle_frac": round(
                    max(0.0, 1.0 - dev_busy / self.pipeline_wall), 3
                ) if self.pipeline_wall else 0.0,
                "drain_backlog_peak": self.drain_backlog_peak,
                "reduce_overlap": round(self.reduce_overlap, 3),
            }
            resilience = {
                "degraded": self.degraded,
                "faults": dict(self.faults),
                "events": dict(self.events),
            }
        # outside self._lock: supervisor owns its own registry lock, and
        # holding both here would order them against callers
        from .supervisor import thread_stats

        resilience["services"] = thread_stats()
        from . import chaos

        inj = chaos.active()
        if inj is not None:
            resilience["chaos"] = inj.stats()
        # hists have their own locks — summarize them outside self._lock
        hists = {name: h.summary() for name, h in self.hists.items()}
        with self._lock:
            # derived rates computed HERE, under the lock, from one
            # consistent read — consumers (faas stats, bench, README
            # examples) must not re-derive them from racy field reads
            return {
                "resilience": resilience,
                "pipeline": pipeline,
                "samples": self.samples,
                "batches": self.batches,
                "requests": self.requests,
                "bytes_out": self.bytes_out,
                "wall_s": round(wall, 3),
                "device_s": round(self.device_time, 3),
                "samples_per_sec": round(self.samples / wall, 1) if wall else 0.0,
                "requests_per_sec": round(self.requests / wall, 2) if wall else 0.0,
                "device_samples_per_sec": round(
                    self.samples / self.device_time, 1
                ) if self.device_time else 0.0,
                "hist": hists,
                "mutators": {
                    code: {"applied": a, "failed": f}
                    for code, (a, f) in sorted(self.mutators.items())
                },
                "host_routed": dict(sorted(self.host_routed.items())),
                "host_samples": self.host_samples,
                "routed_samples": self.routed_samples,
                "host_tail_pct": round(
                    100.0 * self.host_samples / self.routed_samples, 3
                ) if self.routed_samples else 0.0,
                "buckets": {cap: dict(b)
                            for cap, b in sorted(self.buckets.items())},
                "truncated": self.truncated,
                "arena": dict(self.arena) if self.arena else None,
                "fleet": dict(self.fleet) if self.fleet else None,
                "fleet_membership": (dict(self.membership)
                                     if self.membership else None),
                "fleet_transport": dict(self.transport),
                "serving": dict(self.serving) if self.serving else None,
                "rejected": dict(self.rejected),
                "tenants": {t: dict(v)
                            for t, v in sorted(self.tenants.items())},
                "coverage": dict(self.coverage),
                "gen": dict(self.gen),
                "monitors": dict(sorted(self.monitor_events.items())),
            }


GLOBAL = Counters()


class Profiler(threading.Thread):
    """-d mode: periodic process stats to the logger
    (erlamsa_profiler:profiler/1, 5s loop)."""

    def __init__(self, interval: float = 5.0):
        super().__init__(daemon=True)
        self.interval = interval
        self._stop_evt = threading.Event()

    def run(self):
        while not self._stop_evt.is_set():
            try:
                with open("/proc/self/status") as f:
                    status = f.read()
                rss = next(
                    (l.split()[1] for l in status.splitlines()
                     if l.startswith("VmRSS")), "?"
                )
                threads = next(
                    (l.split()[1] for l in status.splitlines()
                     if l.startswith("Threads")), "?"
                )
            except OSError:
                rss = threads = "?"
            snap = GLOBAL.snapshot()
            logger.log(
                "debug",
                "profiler: rss=%skB threads=%s samples=%d (%.1f/s)",
                rss, threads, snap["samples"], snap["samples_per_sec"],
            )
            self._stop_evt.wait(self.interval)

    def stop(self):
        self._stop_evt.set()
