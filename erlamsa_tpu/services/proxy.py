"""Fuzzing MitM proxy: TCP/UDP/HTTP pass-through with probabilistic
mutation of either direction.

Reference: src/erlamsa_fuzzproxy.erl — per-endpoint acceptor workers, c->s
and s->c fuzzing probabilities with an ascent coefficient (raise_prob),
first-K-packet bypass, HTTP header re-packing with Content-Length fixup,
and CONNECT-based TLS MitM. Spec forms (erlamsa_cmdparse proxy parsing):

    tcp://lport:rhost:rport
    udp://lport:rhost:rport
    http://lport:rhost:rport
    http2://lport:rhost:rport
    tls://lport:rhost:rport    (MitM: self-signed listener, TLS upstream;
                                cert/key via opts certfile/keyfile)
"""

from __future__ import annotations

import socket
import threading

from ..utils.erlrand import gen_urandom_seed
from . import logger
from .batcher import make_batcher


def parse_proxy_spec(spec: str):
    proto, _, rest = spec.partition("://")
    parts = rest.split(":")
    if len(parts) != 3:
        raise SystemExit(f"bad proxy spec {spec!r}; want proto://lport:rhost:rport")
    return proto, int(parts[0]), parts[1], int(parts[2])


def parse_probs(s: str) -> tuple[float, float]:
    a, _, b = s.partition(",")
    return float(a), float(b or a)


def raise_prob(prob: float, ascent: float) -> float:
    """Probability ascent per packet (erlamsa_fuzzproxy.erl:61-65)."""
    if ascent <= 0:
        return prob
    return min(1.0, prob + prob * ascent)


def _split_http(data: bytes):
    """(headers, body) or None when not HTTP-ish
    (erlamsa_netutils:extract_http, src/erlamsa_netutils.erl:154-174)."""
    sep = data.find(b"\r\n\r\n")
    if sep < 0:
        return None
    head = data[:sep]
    if b"HTTP/" not in head.split(b"\r\n", 1)[0]:
        return None
    return head, data[sep + 4 :]


def _pack_http(head: bytes, body: bytes) -> bytes:
    """Reassemble with Content-Length fixup
    (erlamsa_netutils:pack_http, src/erlamsa_netutils.erl:176-207)."""
    lines = head.split(b"\r\n")
    out = []
    had_cl = False
    for ln in lines:
        if ln.lower().startswith(b"content-length:"):
            out.append(b"Content-Length: %d" % len(body))
            had_cl = True
        else:
            out.append(ln)
    if not had_cl and body:
        out.append(b"Content-Length: %d" % len(body))
    return b"\r\n".join(out) + b"\r\n\r\n" + body


class FuzzProxy:
    def __init__(self, spec: str, probs: str = "0.1,0.1", opts: dict | None = None,
                 backend: str = "oracle", bypass: int = 0, ascent: float = 0.0):
        self.proto, self.lport, self.rhost, self.rport = parse_proxy_spec(spec)
        self.prob_cs, self.prob_sc = parse_probs(probs)
        self.opts = opts or {}
        self.bypass = bypass  # first K packets pass through (-k)
        self.ascent = ascent
        if self.proto == "tls" and not self.opts.get("certfile"):
            raise SystemExit(
                "tls:// proxy needs --certfile/--keyfile (generate with: "
                "openssl req -x509 -newkey rsa:2048 -nodes -keyout k.pem "
                "-out c.pem -days 30 -subj /CN=localhost)")
        self.batcher = make_batcher(backend, workers=self.opts.get("workers", 10),
                                    seed=self.opts.get("seed"))
        import random as _pyrandom

        self._coin = _pyrandom.Random(str(self.opts.get("seed") or gen_urandom_seed()))
        self._stop = threading.Event()

    def _fuzz_maybe(self, data: bytes, prob: float, npacket: int,
                    direction: str, conn_state: dict) -> bytes:
        """Probability gate + protocol-aware fuzz (fuzz_rnd,
        src/erlamsa_fuzzproxy.erl:309-324). HTTP/2 is special: EVERY packet
        must flow through the framer (its reassembly buffer owns partial
        frames), with the coin gating only whether DATA payloads mutate."""
        gate = npacket > self.bypass and self._coin.random() < prob
        if self.proto == "http2":
            from ..models.http2 import Http2FuzzState, fuzz_http2

            st = conn_state.setdefault(direction, Http2FuzzState())
            fuzzer = (
                (lambda b: self.batcher.fuzz(b, dict(self.opts)))
                if gate
                else (lambda b: b)
            )
            out = fuzz_http2(fuzzer, data, st)
            del st.seen_headers[:-32]  # bounded observability buffer
            if gate:
                logger.log_data("info", "proxy fuzzed packet %d (%s)",
                                (npacket, direction), out)
            return out
        if not gate:
            return data
        if self.proto == "http":
            parts = _split_http(data)
            if parts is not None:
                head, body = parts
                fuzzed = self.batcher.fuzz(body, dict(self.opts)) if body else body
                out = _pack_http(head, fuzzed)
            else:
                out = self.batcher.fuzz(data, dict(self.opts))
        else:
            out = self.batcher.fuzz(data, dict(self.opts))
        logger.log_data("info", "proxy fuzzed packet %d (%s)",
                        (npacket, direction), out)
        return out

    # --- TCP stream (loop_stream, erlamsa_fuzzproxy.erl:261-296) ----------

    def _pump(self, src: socket.socket, dst: socket.socket, prob: float,
              direction: str, conn_state: dict):
        n = 0
        pcs = prob
        try:
            while not self._stop.is_set():
                data = src.recv(65536)
                if not data:
                    break
                n += 1
                out = self._fuzz_maybe(data, pcs, n, direction, conn_state)
                pcs = raise_prob(pcs, self.ascent)
                dst.sendall(out)
        except OSError:
            pass
        finally:
            # propagate the half-close: stop writing to dst, but leave the
            # opposite pump (dst -> src) alive to deliver the response
            try:
                dst.shutdown(socket.SHUT_WR)
            except OSError:
                pass

    def _tls_wrap_client(self, client: socket.socket):
        import ssl

        certfile = self.opts.get("certfile")
        keyfile = self.opts.get("keyfile")
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        if certfile:
            ctx.load_cert_chain(certfile, keyfile)
        else:
            raise RuntimeError(
                "tls:// proxy needs certfile=/keyfile= in opts "
                "(generate: openssl req -x509 -newkey rsa:2048 -nodes ...)")
        return ctx.wrap_socket(client, server_side=True)

    def _tls_wrap_server(self, server: socket.socket):
        import ssl

        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        return ctx.wrap_socket(server, server_hostname=self.rhost)

    def _handle_tcp(self, client: socket.socket):
        server = None
        try:
            server = socket.create_connection((self.rhost, self.rport), timeout=10)
            if self.proto == "tls":
                client = self._tls_wrap_client(client)
                server = self._tls_wrap_server(server)
        except (OSError, RuntimeError) as e:
            logger.log("error", "proxy connection setup failed (%s:%d): %s",
                       self.rhost, self.rport, e)
            client.close()
            if server is not None:
                server.close()
            return
        conn_state: dict = {}  # per-connection HTTP/2 framing + HPACK state
        t1 = threading.Thread(
            target=self._pump,
            args=(client, server, self.prob_cs, "c->s", conn_state),
            daemon=True)
        t2 = threading.Thread(
            target=self._pump,
            args=(server, client, self.prob_sc, "s->c", conn_state),
            daemon=True)
        t1.start()
        t2.start()

    def _serve_tcp(self):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", self.lport))
        srv.listen(64)
        self._srv = srv
        logger.log("info", "fuzzproxy %s://%d -> %s:%d",
                   self.proto, self.lport, self.rhost, self.rport)
        while not self._stop.is_set():
            try:
                client, _addr = srv.accept()
            except OSError:
                break
            self._handle_tcp(client)

    # --- UDP (loop_udp, erlamsa_fuzzproxy.erl:226-259) --------------------

    def _serve_udp(self):
        srv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        srv.bind(("0.0.0.0", self.lport))
        self._srv = srv
        up = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        client_addr = None
        n = 0
        conn_state: dict = {}
        while not self._stop.is_set():
            try:
                data, addr = srv.recvfrom(65536)
            except OSError:
                break
            if addr[0] != self.rhost or addr[1] != self.rport:
                client_addr = addr
                n += 1
                out = self._fuzz_maybe(data, self.prob_cs, n, "c->s", conn_state)
                up.sendto(out, (self.rhost, self.rport))
            elif client_addr:
                out = self._fuzz_maybe(data, self.prob_sc, n, "s->c", conn_state)
                srv.sendto(out, client_addr)

    def start(self, block: bool = True):
        target = self._serve_udp if self.proto == "udp" else self._serve_tcp
        if block:
            target()
            return 0
        threading.Thread(target=target, daemon=True).start()
        return self

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except Exception:
            pass


def run_proxy(spec: str, probs: str, opts: dict) -> int:
    return FuzzProxy(spec, probs, opts).start(block=True)
