"""Fuzzing MitM proxy: TCP/UDP/HTTP pass-through with probabilistic
mutation of either direction.

Reference: src/erlamsa_fuzzproxy.erl — per-endpoint acceptor workers, c->s
and s->c fuzzing probabilities with an ascent coefficient (raise_prob),
first-K-packet bypass, HTTP header re-packing with Content-Length fixup,
and CONNECT-based TLS MitM. Spec forms (erlamsa_cmdparse proxy parsing):

    tcp://lport:rhost:rport
    udp://lport:rhost:rport
    http://lport:rhost:rport
    http2://lport:rhost:rport
    tls://lport:rhost:rport    (MitM: self-signed listener, TLS upstream;
                                cert/key via opts certfile/keyfile)
    connect://lport::          (standalone HTTP proxy: clients send
                                CONNECT host:port / absolute-URI requests;
                                the upstream target comes from the request,
                                like the reference's CONNECT MitM path,
                                src/erlamsa_fuzzproxy.erl:138-164)
    serial://dev1@baud:dev2@baud  (dual serial pass-through,
                                src/erlamsa_fuzzproxy.erl:202-224)
"""

from __future__ import annotations

import socket
import threading

from ..corpus import feedback
from ..utils.erlrand import gen_urandom_seed
from . import logger
from .batcher import make_batcher
from .supervisor import supervise


def parse_proxy_spec(spec: str):
    proto, _, rest = spec.partition("://")
    if proto == "serial":
        parts = rest.split(":")
        if len(parts) != 2 or "@" not in parts[0] or "@" not in parts[1]:
            raise SystemExit(
                f"bad serial proxy spec {spec!r}; want serial://dev1@baud:dev2@baud"
            )
        return proto, parts[0], parts[1], 0
    parts = rest.split(":")
    if len(parts) != 3:
        raise SystemExit(f"bad proxy spec {spec!r}; want proto://lport:rhost:rport")
    if proto == "connect":
        # the upstream comes from each CONNECT/Host request; rhost:rport in
        # the spec are meaningless and stay empty
        return proto, int(parts[0]), "", 0
    if not parts[2]:
        raise SystemExit(f"bad proxy spec {spec!r}; missing rport")
    return proto, int(parts[0]), parts[1], int(parts[2])


def parse_probs(s: str) -> tuple[float, float]:
    a, _, b = s.partition(",")
    return float(a), float(b or a)


def raise_prob(prob: float, ascent: float) -> float:
    """Probability ascent per packet (erlamsa_fuzzproxy.erl:61-65)."""
    if ascent <= 0:
        return prob
    return min(1.0, prob + prob * ascent)


def _split_http(data: bytes):
    """(headers, body) or None when not HTTP-ish
    (erlamsa_netutils:extract_http, src/erlamsa_netutils.erl:154-174)."""
    sep = data.find(b"\r\n\r\n")
    if sep < 0:
        return None
    head = data[:sep]
    if b"HTTP/" not in head.split(b"\r\n", 1)[0]:
        return None
    return head, data[sep + 4 :]


def _pack_http(head: bytes, body: bytes) -> bytes:
    """Reassemble with Content-Length fixup
    (erlamsa_netutils:pack_http, src/erlamsa_netutils.erl:176-207)."""
    lines = head.split(b"\r\n")
    out = []
    had_cl = False
    for ln in lines:
        if ln.lower().startswith(b"content-length:"):
            out.append(b"Content-Length: %d" % len(body))
            had_cl = True
        else:
            out.append(ln)
    if not had_cl and body:
        out.append(b"Content-Length: %d" % len(body))
    return b"\r\n".join(out) + b"\r\n\r\n" + body


class FuzzProxy:
    def __init__(self, spec: str, probs: str = "0.1,0.1", opts: dict | None = None,
                 backend: str = "oracle", bypass: int = 0, ascent: float = 0.0):
        self.proto, self.lport, self.rhost, self.rport = parse_proxy_spec(spec)
        self.prob_cs, self.prob_sc = parse_probs(probs)
        self.opts = opts or {}
        self.bypass = bypass  # first K packets pass through (-k)
        self.ascent = ascent
        if self.proto == "tls" and not self.opts.get("certfile"):
            raise SystemExit(
                "tls:// proxy needs --certfile/--keyfile (generate with: "
                "openssl req -x509 -newkey rsa:2048 -nodes -keyout k.pem "
                "-out c.pem -days 30 -subj /CN=localhost)")
        self.batcher = make_batcher(backend, workers=self.opts.get("workers", 10),
                                    seed=self.opts.get("seed"))
        import random as _pyrandom

        self._coin = _pyrandom.Random(str(self.opts.get("seed") or gen_urandom_seed()))
        self._stop = threading.Event()

    def _fuzz_maybe(self, data: bytes, prob: float, npacket: int,
                    direction: str, conn_state: dict) -> bytes:
        """Probability gate + protocol-aware fuzz (fuzz_rnd,
        src/erlamsa_fuzzproxy.erl:309-324). HTTP/2 is special: EVERY packet
        must flow through the framer (its reassembly buffer owns partial
        frames), with the coin gating only whether DATA payloads mutate."""
        gate = npacket > self.bypass and self._coin.random() < prob
        if gate:
            # per-connection fuzz tally: an abnormal close AFTER a fuzzed
            # packet reads as a desync, not a routine drop (_pump)
            conn_state["fuzzed"] = conn_state.get("fuzzed", 0) + 1
        if self.proto == "http2":
            from ..models.http2 import Http2FuzzState, fuzz_http2

            st = conn_state.setdefault(direction, Http2FuzzState())
            fuzzer = (
                (lambda b: self.batcher.fuzz(b, dict(self.opts)))
                if gate
                else (lambda b: b)
            )
            out = fuzz_http2(fuzzer, data, st)
            del st.seen_headers[:-32]  # bounded observability buffer
            if gate:
                logger.log_data("info", "proxy fuzzed packet %d (%s)",
                                (npacket, direction), out)
            return out
        if not gate:
            return data
        if self.proto == "http":
            parts = _split_http(data)
            if parts is not None:
                head, body = parts
                fuzzed = self.batcher.fuzz(body, dict(self.opts)) if body else body
                out = _pack_http(head, fuzzed)
            else:
                out = self.batcher.fuzz(data, dict(self.opts))
        else:
            out = self.batcher.fuzz(data, dict(self.opts))
        logger.log_data("info", "proxy fuzzed packet %d (%s)",
                        (npacket, direction), out)
        return out

    # --- TCP stream (loop_stream, erlamsa_fuzzproxy.erl:261-296) ----------

    def _pump(self, src: socket.socket, dst: socket.socket, prob: float,
              direction: str, conn_state: dict):
        n = 0
        pcs = prob
        try:
            while not self._stop.is_set():
                data = src.recv(65536)
                if not data:
                    break
                n += 1
                out = self._fuzz_maybe(data, pcs, n, direction, conn_state)
                pcs = raise_prob(pcs, self.ascent)
                dst.sendall(out)
        except OSError as e:
            # abnormal close (reset/refused mid-stream): a desync when
            # fuzzed traffic flowed on this connection, else a drop —
            # feedback-mode runs promote whatever seeds were in flight
            kind = "desync" if conn_state.get("fuzzed") else "drop"
            feedback.publish(kind, source=f"proxy:{direction}",
                             detail=str(e)[:100])
            logger.log("finding", "proxy %s (%s): %s", kind, direction, e)
        finally:
            # propagate the half-close: stop writing to dst, but leave the
            # opposite pump (dst -> src) alive to deliver the response
            try:
                dst.shutdown(socket.SHUT_WR)
            except OSError:
                pass

    def _tls_wrap_client(self, client: socket.socket):
        import ssl

        certfile = self.opts.get("certfile")
        keyfile = self.opts.get("keyfile")
        ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
        if certfile:
            ctx.load_cert_chain(certfile, keyfile)
        else:
            raise RuntimeError(
                "tls:// proxy needs certfile=/keyfile= in opts "
                "(generate: openssl req -x509 -newkey rsa:2048 -nodes ...)")
        return ctx.wrap_socket(client, server_side=True)

    def _tls_wrap_server(self, server: socket.socket):
        import ssl

        ctx = ssl.create_default_context()
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
        return ctx.wrap_socket(server, server_hostname=self.rhost)

    def _handle_tcp(self, client: socket.socket):
        server = None
        try:
            server = socket.create_connection((self.rhost, self.rport), timeout=10)
            if self.proto == "tls":
                client = self._tls_wrap_client(client)
                server = self._tls_wrap_server(server)
        except (OSError, RuntimeError) as e:
            logger.log("error", "proxy connection setup failed (%s:%d): %s",
                       self.rhost, self.rport, e)
            client.close()
            if server is not None:
                server.close()
            return
        conn_state: dict = {}  # per-connection HTTP/2 framing + HPACK state
        t1 = threading.Thread(
            target=self._pump,
            args=(client, server, self.prob_cs, "c->s", conn_state),
            daemon=True)
        t2 = threading.Thread(
            target=self._pump,
            args=(server, client, self.prob_sc, "s->c", conn_state),
            daemon=True)
        t1.start()
        t2.start()

    # --- CONNECT / absolute-URI HTTP proxy (erlamsa_fuzzproxy.erl:138-164) -

    def _handle_connect(self, client: socket.socket):
        """Standalone HTTP proxy: read the request head, derive the real
        upstream from CONNECT host:port or the request's Host header."""
        server = None
        try:
            client.settimeout(10)
            head = b""
            while b"\r\n\r\n" not in head and len(head) < 65536:
                chunk = client.recv(8192)
                if not chunk:
                    client.close()
                    return
                head += chunk
            first = head.split(b"\r\n", 1)[0]
            if first.startswith(b"CONNECT "):
                target = first.split()[1].decode()
                host, _, port = target.rpartition(":")
                server = socket.create_connection(
                    (host or target, int(port or 443)), timeout=10
                )
                client.sendall(b"HTTP/1.1 200 Connection Established\r\n\r\n")
                leftover = head.split(b"\r\n\r\n", 1)[1]
            else:
                # absolute-URI / Host-header plain proxying
                host_line = next(
                    (l for l in head.split(b"\r\n") if l.lower().startswith(b"host:")),
                    None,
                )
                if host_line is None:
                    client.close()
                    return
                hostport = host_line.split(b":", 1)[1].strip().decode()
                host, _, port = hostport.partition(":")
                server = socket.create_connection((host, int(port or 80)), timeout=10)
                leftover = head  # forward the full request
            client.settimeout(None)
            conn_state: dict = {}
            if leftover:
                out = self._fuzz_maybe(leftover, self.prob_cs, 1, "c->s", conn_state)
                server.sendall(out)
            t1 = threading.Thread(
                target=self._pump,
                args=(client, server, self.prob_cs, "c->s", conn_state),
                daemon=True)
            t2 = threading.Thread(
                target=self._pump,
                args=(server, client, self.prob_sc, "s->c", conn_state),
                daemon=True)
            t1.start()
            t2.start()
        except (OSError, ValueError, IndexError) as e:
            logger.log("error", "connect-proxy setup failed: %s", e)
            client.close()
            if server is not None:
                server.close()

    # --- dual serial (erlamsa_fuzzproxy.erl:202-224) -----------------------

    def _serve_serial(self):
        import os as _os
        import select

        from .out import open_serial_raw

        def open_dev(spec):
            dev, _, baud = spec.partition("@")
            return open_serial_raw(dev, int(baud or 115200))

        fd1 = open_dev(self.lport)  # lport/rhost carry the dev specs here
        fd2 = open_dev(self.rhost)
        conn_state: dict = {}
        counts = {"c->s": 0, "s->c": 0}  # per-direction like _pump's n
        try:
            while not self._stop.is_set():
                r, _w, _x = select.select([fd1, fd2], [], [], 1.0)
                for fd in r:
                    try:
                        data = _os.read(fd, 4096)
                    except OSError as e:
                        logger.log("error", "serial proxy read failed: %s", e)
                        return
                    if not data:
                        # EOF (pty peer closed): selecting again would spin
                        logger.log("info", "serial endpoint closed")
                        return
                    direction = "c->s" if fd == fd1 else "s->c"
                    counts[direction] += 1
                    prob = self.prob_cs if fd == fd1 else self.prob_sc
                    out = self._fuzz_maybe(
                        data, prob, counts[direction], direction, conn_state
                    )
                    try:
                        _os.write(fd2 if fd == fd1 else fd1, out)
                    except OSError as e:
                        logger.log("error", "serial proxy write failed: %s", e)
                        return
        finally:
            _os.close(fd1)
            _os.close(fd2)

    def _serve_tcp(self):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", self.lport))
        srv.listen(64)
        self._srv = srv
        logger.log("info", "fuzzproxy %s://%d -> %s:%d",
                   self.proto, self.lport, self.rhost, self.rport)
        try:
            while not self._stop.is_set():
                try:
                    client, _addr = srv.accept()
                except OSError:
                    break
                if self.proto == "connect":
                    threading.Thread(
                        target=self._handle_connect, args=(client,),
                        daemon=True,
                    ).start()
                else:
                    self._handle_tcp(client)
        finally:
            srv.close()  # a supervised restart must be able to re-bind

    # --- UDP (loop_udp, erlamsa_fuzzproxy.erl:226-259) --------------------

    def _serve_udp(self):
        import select

        # bound OUTSIDE the try: a bind failure must not look like a
        # recoverable crash to the supervisor
        srv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", self.lport))
        self._srv = srv
        # upstream-facing socket: client packets go out of it, so server
        # replies come back to ITS ephemeral port — select over both, like
        # the reference receiving on SrvSocket and ClSocket
        # (erlamsa_fuzzproxy.erl:226-259)
        up = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        up.bind(("0.0.0.0", 0))
        try:
            r_ip = socket.gethostbyname(self.rhost)
        except OSError:
            r_ip = self.rhost
        client_addr = None
        counts = {"c->s": 0, "s->c": 0}
        conn_state: dict = {}
        try:
            while not self._stop.is_set():
                try:
                    rd, _w, _x = select.select([srv, up], [], [], 1.0)
                except (OSError, ValueError):
                    # stop() closes srv from another thread; a closed
                    # socket's fileno() is -1 which select rejects
                    break
                for sock in rd:
                    try:
                        data, addr = sock.recvfrom(65536)
                    except OSError:
                        return
                    is_server = sock is up or (
                        addr[0] == r_ip and addr[1] == self.rport
                    )
                    if is_server:
                        if client_addr is None:
                            continue
                        counts["s->c"] += 1
                        out = self._fuzz_maybe(
                            data, self.prob_sc, counts["s->c"], "s->c",
                            conn_state,
                        )
                        try:
                            srv.sendto(out, client_addr)
                        except OSError:
                            # a vanished client answers with ICMP
                            # port-unreachable; drop, keep serving
                            pass
                    else:
                        client_addr = addr
                        counts["c->s"] += 1
                        out = self._fuzz_maybe(
                            data, self.prob_cs, counts["c->s"], "c->s",
                            conn_state,
                        )
                        try:
                            up.sendto(out, (r_ip, self.rport))
                        except OSError:
                            pass
        finally:
            # release the listen port too, so a supervised restart can
            # re-bind instead of dying on EADDRINUSE
            up.close()
            srv.close()

    def start(self, block: bool = True):
        if self.proto == "serial":
            target = self._serve_serial
        elif self.proto == "udp":
            target = self._serve_udp
        else:
            target = self._serve_tcp
        if block:
            target()
            return 0
        supervise(f"fuzzproxy-{self.proto}", target)
        return self

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass


def run_proxy(spec: str, probs: str, opts: dict) -> int:
    return FuzzProxy(spec, probs, opts).start(block=True)
