"""ctypes bindings for the C++ runtime ports (native/erlamsa_port.cpp).

Builds the shared library on first use when a compiler is available (the
reference ships its native deps pre-built; here g++ is part of the image).
Every caller has a pure-Python fallback, so a missing toolchain degrades
gracefully rather than breaking the CLI.
"""

from __future__ import annotations

import ctypes
import shutil
import subprocess
import threading
from pathlib import Path

from . import logger

_SRC = Path(__file__).resolve().parent.parent.parent / "native" / "erlamsa_port.cpp"
_LIB = _SRC.parent / "liberlamsa_port.so"
_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


class ExecResult(ctypes.Structure):
    _fields_ = [
        ("exit_code", ctypes.c_int32),
        ("term_signal", ctypes.c_int32),
        ("timed_out", ctypes.c_int32),
        ("user_usec", ctypes.c_int64),
        ("sys_usec", ctypes.c_int64),
        ("max_rss_kb", ctypes.c_int64),
        ("pid", ctypes.c_int32),
    ]


def build() -> bool:
    """Compile the library if needed; returns availability."""
    if _LIB.exists() and _LIB.stat().st_mtime >= _SRC.stat().st_mtime:
        return True
    gxx = shutil.which("g++")
    if gxx is None:
        return False
    try:
        subprocess.run(
            [gxx, "-O2", "-shared", "-fPIC", "-o", str(_LIB), str(_SRC)],
            check=True, capture_output=True, timeout=120,
        )
        return True
    except (subprocess.CalledProcessError, subprocess.TimeoutExpired) as e:
        logger.log("warning", "native port build failed: %s", e)
        return False


def get() -> ctypes.CDLL | None:
    """The loaded library, building it on demand; None when unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if not build():
            return None
        lib = ctypes.CDLL(str(_LIB))
        lib.erlamsa_exec_feed.argtypes = [
            ctypes.POINTER(ctypes.c_char_p), ctypes.c_char_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.POINTER(ExecResult),
        ]
        lib.erlamsa_exec_feed.restype = ctypes.c_int
        lib.erlamsa_rawsock_open.restype = ctypes.c_int
        lib.erlamsa_rawsock_send.argtypes = [
            ctypes.c_int, ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint32,
        ]
        lib.erlamsa_serial_open.argtypes = [ctypes.c_char_p, ctypes.c_int]
        lib.erlamsa_serial_open.restype = ctypes.c_int
        lib.erlamsa_fd_write.argtypes = [
            ctypes.c_int, ctypes.c_char_p, ctypes.c_int64,
        ]
        _lib = lib
        return _lib


def exec_feed(argv: list[str], data: bytes, timeout_ms: int = 30000):
    """Spawn a target, feed stdin, return an ExecResult — the erlexec-port
    path. Returns None when the native lib is unavailable (callers fall
    back to subprocess)."""
    lib = get()
    if lib is None:
        return None
    c_argv = (ctypes.c_char_p * (len(argv) + 1))(
        *[a.encode() for a in argv], None
    )
    res = ExecResult()
    rc = lib.erlamsa_exec_feed(c_argv, data, len(data), timeout_ms, res)
    if rc != 0:
        logger.log("warning", "native exec failed: errno %d", -rc)
        return None
    return res


def rawsock_send(packet: bytes, dst_ip: str) -> int | None:
    """Send a raw IPv4 packet (caller-built header); needs CAP_NET_RAW."""
    import socket as pysock
    import struct

    lib = get()
    if lib is None:
        return None
    fd = lib.erlamsa_rawsock_open()
    if fd < 0:
        return fd
    try:
        dst_be = struct.unpack("=I", pysock.inet_aton(dst_ip))[0]
        return lib.erlamsa_rawsock_send(fd, packet, len(packet), dst_be)
    finally:
        lib.erlamsa_fd_close(fd)


def serial_open(dev: str, baud: int) -> int | None:
    lib = get()
    if lib is None:
        return None
    fd = lib.erlamsa_serial_open(dev.encode(), baud)
    return fd if fd >= 0 else None


def fd_write(fd: int, data: bytes) -> int:
    lib = get()
    assert lib is not None
    return lib.erlamsa_fd_write(fd, data, len(data))
