"""Restart supervision for service threads.

Reference: erlamsa's OTP supervisor runs logger/fsupervisor/monitors/
proxy/httpsvc one_for_one with intensity 5 restarts per 1 second
(src/erlamsa_sup.erl:51-54) — a crashed service child is restarted, and a
crash loop terminates the tree instead of spinning. Python threads don't
restart themselves, so service loops here run under ``supervise``: the
target is re-invoked on an unhandled exception, with the reference's
intensity/period circuit breaker.
"""

from __future__ import annotations

import threading
import time

from . import logger

RESTART_INTENSITY = 5  # src/erlamsa_sup.erl:51-54
RESTART_PERIOD = 1.0


class SupervisedThread:
    """A daemon thread whose target is restarted on crash (one_for_one).

    After more than `intensity` crashes within `period` seconds the
    supervisor gives up (like OTP escalating a restart storm), logs at
    critical, and the thread exits. A target that RETURNS normally is
    considered finished — only exceptions restart it.
    """

    def __init__(self, name: str, target, args=(), kwargs=None,
                 intensity: int = RESTART_INTENSITY,
                 period: float = RESTART_PERIOD):
        self.name = name
        self.target = target
        self.args = args
        self.kwargs = kwargs or {}
        self.intensity = intensity
        self.period = period
        self.crashes: list[float] = []
        self.gave_up = False
        self._thread = threading.Thread(
            target=self._run, name=f"sup:{name}", daemon=True
        )

    def _run(self):
        while True:
            try:
                self.target(*self.args, **self.kwargs)
                return  # normal completion: don't resurrect
            except Exception as e:
                now = time.monotonic()
                self.crashes = [
                    t for t in self.crashes if now - t < self.period
                ] + [now]
                if len(self.crashes) > self.intensity:
                    self.gave_up = True
                    logger.log(
                        "critical",
                        "service %s crashed %d times in %.1fs, giving up: %s",
                        self.name, len(self.crashes), self.period, e,
                    )
                    return
                logger.log("error", "service %s crashed, restarting: %s",
                           self.name, e)

    def start(self) -> "SupervisedThread":
        self._thread.start()
        return self

    def join(self, timeout=None):
        self._thread.join(timeout)

    def is_alive(self) -> bool:
        return self._thread.is_alive()


def supervise(name: str, target, *args, **kwargs) -> SupervisedThread:
    """Start `target(*args)` in a supervised daemon thread."""
    return SupervisedThread(name, target, args, kwargs).start()
