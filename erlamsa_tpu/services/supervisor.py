"""Restart supervision for service threads.

Reference: erlamsa's OTP supervisor runs logger/fsupervisor/monitors/
proxy/httpsvc one_for_one with intensity 5 restarts per 1 second
(src/erlamsa_sup.erl:51-54) — a crashed service child is restarted, and a
crash loop terminates the tree instead of spinning. Python threads don't
restart themselves, so service loops here run under ``supervise``: the
target is re-invoked on an unhandled exception, with the reference's
intensity/period circuit breaker plus a capped exponential backoff
between restarts (a crash loop used to spin its 5 attempts in
milliseconds doing no useful work; now each restart waits a beat, and
the cap keeps a genuine storm inside the escalation window).

Every SupervisedThread registers itself so services/metrics.py can
surface per-thread crash counts and gave_up state (snapshot()["resilience"]
["services"], also served by the faas stats op) — the observability the
reference gets for free from OTP's sasl reports.
"""

from __future__ import annotations

import threading
import time

from . import logger

RESTART_INTENSITY = 5  # src/erlamsa_sup.erl:51-54
RESTART_PERIOD = 1.0
RESTART_BACKOFF = 0.02  # first restart delay; doubles per consecutive crash
RESTART_BACKOFF_MAX = 0.2  # capped below period/intensity so a persistent
#                            crasher still accumulates enough crashes inside
#                            one period to trip the give-up breaker

_registry_lock = threading.Lock()
_registry: dict[str, "SupervisedThread"] = {}


def thread_stats() -> dict:
    """{name: {crashes, gave_up, alive}} for every supervised thread this
    process ever started (same-named restarts overwrite — latest wins)."""
    with _registry_lock:
        return {
            name: {
                "crashes": t.total_crashes,
                "gave_up": t.gave_up,
                "alive": t.is_alive(),
            }
            for name, t in _registry.items()
        }


class SupervisedThread:
    """A daemon thread whose target is restarted on crash (one_for_one).

    After more than `intensity` crashes within `period` seconds the
    supervisor gives up (like OTP escalating a restart storm), logs at
    critical, and the thread exits. A target that RETURNS normally is
    considered finished — only exceptions restart it. Consecutive crashes
    back off exponentially (backoff * 2^n, capped at backoff_max) so a
    failing dependency gets breathing room instead of a hot spin.
    """

    def __init__(self, name: str, target, args=(), kwargs=None,
                 intensity: int = RESTART_INTENSITY,
                 period: float = RESTART_PERIOD,
                 backoff: float = RESTART_BACKOFF,
                 backoff_max: float = RESTART_BACKOFF_MAX):
        self.name = name
        self.target = target
        self.args = args
        self.kwargs = kwargs or {}
        self.intensity = intensity
        self.period = period
        self.backoff = backoff
        self.backoff_max = backoff_max
        self.crashes: list[float] = []
        self.total_crashes = 0
        self.gave_up = False
        self._thread = threading.Thread(
            target=self._run, name=f"sup:{name}", daemon=True
        )
        with _registry_lock:
            _registry[name] = self

    def _run(self):
        consecutive = 0
        while True:
            try:
                self.target(*self.args, **self.kwargs)
                return  # normal completion: don't resurrect
            except Exception as e:  # lint: broad-except-ok a crash IS the supervised event
                now = time.monotonic()
                self.total_crashes += 1
                self.crashes = [
                    t for t in self.crashes if now - t < self.period
                ] + [now]
                if len(self.crashes) > self.intensity:
                    self.gave_up = True
                    logger.log(
                        "critical",
                        "service %s crashed %d times in %.1fs, giving up: %s",
                        self.name, len(self.crashes), self.period, e,
                    )
                    # a give-up is a flight-recorder trip: dump the ring
                    # while the scrollback leading here is still in it
                    # (lazy import keeps supervisor import-light)
                    from . import metrics

                    metrics.GLOBAL.record_event("supervisor_give_up")
                    return
                delay = min(self.backoff * (2 ** consecutive),
                            self.backoff_max)
                consecutive += 1
                logger.log("error", "service %s crashed, restarting in "
                           "%.2fs: %s", self.name, delay, e)
                if delay > 0:
                    time.sleep(delay)

    def start(self) -> "SupervisedThread":
        self._thread.start()
        return self

    def join(self, timeout=None):
        self._thread.join(timeout)

    def is_alive(self) -> bool:
        return self._thread.is_alive()


def supervise(name: str, target, *args, **kwargs) -> SupervisedThread:
    """Start `target(*args)` in a supervised daemon thread."""
    return SupervisedThread(name, target, args, kwargs).start()
