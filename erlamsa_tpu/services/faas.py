"""Fuzzing-as-a-service HTTP endpoint.

Reference: src/erlamsa_httpsvc.erl + src/erlamsa_esi.erl — endpoints
/erlamsa/erlamsa_esi:fuzz (octet-stream in/out), :json (base64 JSON), and
:manage (token admin), with fuzzing options in erlamsa-* HTTP headers or
JSON fields and session auth via the cloud manager. Requests are served
from the continuous-batching engine (services/serving.py) by default;
``--serving flush`` keeps the adaptive flush batcher.

Multi-tenancy (r10): a request's tenant is its auth token (digested — a
secret must not become a metrics label), an explicit ``erlamsa-tenant``
header, or "public". Admission control runs BEFORE the device queue:
per-tenant token-bucket quotas and a bounded backlog shed load with
HTTP 429 + Retry-After instead of letting p99 collapse, behind the
``serving.admit`` chaos site so resilience tests can force the rejection
path. With a ``--corpus`` dir, each tenant's request payloads are
admitted into its own corpus namespace (``corpus_dir/<tenant>``).
"""

from __future__ import annotations

import base64
import binascii
import hashlib
import json
import math
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class _FaasServer(ThreadingHTTPServer):
    """HTTPServer's default accept backlog is 5 — concurrent load (the
    reference serves 10k simultaneous fsupervisor requests) overflows it
    and the kernel RESETS connections. A deep listen queue plus the
    batcher's own queueing is the capacity model here."""

    request_queue_size = 1024

from ..utils.erlrand import parse_seed
from . import chaos, logger, metrics
from .cmanager import CloudManager
from .serving import TenantTable, make_engine


def _parse_opts(get) -> dict:
    """Shared option parsing for both transports: `get(name)` returns the
    raw value for mutations/patterns/seed/blockscale or None. Values must
    be strings (numbers allowed for blockscale) — anything else raises
    ValueError so callers answer HTTP 400, never a connection abort."""
    opts: dict = {}

    def _want_str(name, v):
        if not isinstance(v, str):
            raise ValueError(f"{name} must be a string")
        return v

    m = get("mutations")
    if m:
        from ..oracle.mutations import default_mutations
        from .cli import _parse_actions

        opts["mutations"] = _parse_actions(
            _want_str("mutations", m), default_mutations()
        )
    p = get("patterns")
    if p:
        from ..oracle.patterns import default_patterns
        from .cli import _parse_actions

        opts["patterns"] = _parse_actions(
            _want_str("patterns", p), default_patterns()
        )
    s = get("seed")
    if s:
        opts["seed"] = parse_seed(_want_str("seed", s))
    b = get("blockscale")
    if b:
        if not isinstance(b, (str, int, float)) or isinstance(b, bool):
            raise ValueError("blockscale must be a number")
        opts["blockscale"] = float(b)
    return opts


def _parse_header_opts(headers) -> dict:
    """erlamsa-mutations/patterns/seed/blockscale headers
    (erlamsa_esi:parse_headers, src/erlamsa_esi.erl:34-56)."""
    return _parse_opts(lambda name: headers.get(f"erlamsa-{name}"))


class _Handler(BaseHTTPRequestHandler):
    server_version = "erlamsa-tpu"
    # keep-alive: every _reply carries Content-Length, so HTTP/1.1 is
    # safe and lets load-test harnesses and fuzzing loops reuse one
    # connection per client instead of paying a TCP handshake + server
    # thread spawn per request
    protocol_version = "HTTP/1.1"
    batcher = None
    cmanager: CloudManager | None = None
    tenants: TenantTable | None = None
    #: admission backlog bound: requests queued behind the engine beyond
    #: this are shed with 429 instead of growing queue.Queue unboundedly
    queue_cap: int = 1024

    def log_message(self, fmt, *args):
        logger.log("debug", "faas: " + fmt, *args)

    def _tenant(self, body_req: dict | None = None) -> str:
        """Tenant identity: the auth token (digested, never the secret
        itself), an explicit erlamsa-tenant header, or "public"."""
        body_req = body_req or {}
        tok = self.headers.get("erlamsa-token") or body_req.get("token")
        if isinstance(tok, str) and tok:
            return "tok:" + hashlib.sha256(tok.encode()).hexdigest()[:8]
        name = self.headers.get("erlamsa-tenant")
        if isinstance(name, str) and name.strip():
            return name.strip()[:48]
        return "public"

    def _admit(self, tenant: str):
        """Admission control, BEFORE the device queue. Returns None to
        admit, else ``(retry_after_s, reason)`` for a 429."""
        try:
            chaos.fault_point("serving.admit")
        except OSError:
            # an injected admission fault sheds exactly like real
            # pressure — clients must see a well-formed 429, never a
            # connection abort (tests force this path)
            return 1.0, "chaos"
        if self.tenants is not None:
            retry = self.tenants.admit(tenant)
            if retry > 0.0:
                return retry, "quota"
        backlog = getattr(self.batcher, "backlog", None)
        if self.queue_cap and backlog is not None \
                and backlog() >= self.queue_cap:
            return 1.0, "queue_full"
        return None

    def _reject(self, tenant: str, reason: str, retry_after: float,
                is_json: bool, session: str):
        metrics.GLOBAL.record_rejected(reason)
        if self.tenants is not None:
            self.tenants.record(tenant, served=False)
        headers = {"Retry-After": str(max(1, math.ceil(retry_after)))}
        if is_json:
            self._reply(429, json.dumps(
                {"error": "overloaded", "reason": reason}).encode(),
                session, ctype="application/json", headers=headers)
        else:
            self._reply(429, f"overloaded: {reason}".encode(), session,
                        headers=headers)

    def _record_served(self, tenant: str, data: bytes):
        if self.tenants is None:
            return
        self.tenants.record(tenant, served=True)
        store = self.tenants.corpus_for(tenant)
        if store is not None and data:
            try:
                store.add(data, origin=f"faas:{tenant}")
            except (OSError, ValueError) as e:
                logger.log("warn", "tenant corpus add failed: %s", e)

    def _auth(self, body_req: dict | None = None):
        """Token/session from erlamsa-* headers, or (JSON API) from the
        request body — the reference accepts both (erlamsa_esi.erl
        parse_headers:34-56 / parse_json:70-82)."""
        cm = self.cmanager
        body_req = body_req or {}

        def _str_or_none(v):
            # non-string JSON values (dict/list/number) must not reach the
            # token store — an unhashable value would crash pre-auth
            return v if isinstance(v, str) else None

        status, session = cm.get_client_context(
            self.headers.get("erlamsa-token")
            or _str_or_none(body_req.get("token")),
            self.headers.get("erlamsa-session")
            or _str_or_none(body_req.get("session")),
        )
        return status, session

    def _reply(self, code: int, body: bytes, session: str = "",
               ctype="application/octet-stream",
               headers: dict | None = None):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.send_header("erlamsa-status", "ok" if code == 200 else "error")
        for name, value in (headers or {}).items():
            self.send_header(name, value)
        if session:
            self.send_header("erlamsa-session", session)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        # Prometheus scrape endpoint: counters aren't secrets (same rule
        # as the stats op), so no auth gate — scrapers don't do sessions
        if self.path.split("?")[0] == "/metrics":
            from ..obs import prom

            self._reply(200, prom.render().encode(), ctype=prom.CONTENT_TYPE)
            return
        self._reply(404, b"not found")

    def do_POST(self):  # noqa: N802
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        path = self.path.rstrip("/")
        is_json = path.endswith(("erlamsa_esi:json", "/json"))
        body_req: dict = {}
        if is_json:
            try:
                body_req = json.loads(body)
                if not isinstance(body_req, dict):
                    raise ValueError("JSON body must be an object")
            except ValueError as e:
                self._reply(400, json.dumps({"error": f"bad json: {e}"})
                            .encode(), ctype="application/json")
                return
        status, session = self._auth(body_req)
        if status != "ok":
            if is_json:
                self._reply(401, json.dumps({"error": "unauthorized"})
                            .encode(), ctype="application/json")
            else:
                self._reply(401, b"unauthorized")
            return
        if path.endswith(("erlamsa_esi:fuzz", "/fuzz")):
            try:
                opts = _parse_header_opts(self.headers)
            except (ValueError, SystemExit) as e:
                self._reply(400, f"bad erlamsa-* header: {e}".encode())
                return
            tenant = self._tenant()
            shed = self._admit(tenant)
            if shed is not None:
                self._reject(tenant, shed[1], shed[0], False, session)
                return
            out = self.batcher.fuzz(body, opts)
            self._record_served(tenant, body)
            self._reply(200, out, session)
            return
        if is_json:
            try:
                data = base64.b64decode(body_req.get("data", ""))
                opts = _parse_opts(body_req.get)
                tenant = self._tenant(body_req)
                shed = self._admit(tenant)
                if shed is not None:
                    self._reject(tenant, shed[1], shed[0], True, session)
                    return
                out = self.batcher.fuzz(data, opts)
                self._record_served(tenant, data)
                self._reply(
                    200,
                    json.dumps({"data": base64.b64encode(out).decode()}).encode(),
                    session,
                    ctype="application/json",
                )
            except (ValueError, KeyError, TypeError, binascii.Error,
                    SystemExit) as e:
                # _parse_actions raises SystemExit for unknown names —
                # a bad request here, not a server exit
                self._reply(400, json.dumps({"error": f"bad request: {e}"})
                            .encode(), ctype="application/json")
            return
        if path.endswith(("erlamsa_esi:manage", "/manage")):
            try:
                req = json.loads(body)
                cm = self.cmanager
                admin = req.get("admin", "")
                op = req.get("op")
                if op == "addtoken":
                    t = cm.add_token(admin)
                    ok = t is not None
                    resp = {"status": "ok" if ok else "denied", "token": t or ""}
                elif op == "deltoken":
                    ok = cm.del_token(admin, req.get("token", ""))
                    resp = {"status": "ok" if ok else "denied"}
                elif op == "listtokens":
                    ts = cm.list_tokens(admin)
                    resp = {"status": "ok" if ts is not None else "denied",
                            "tokens": ts or []}
                elif op == "stats":
                    # throughput + per-mutator applied/failed + bucket
                    # stats; counters aren't secrets, so no admin gate
                    from . import metrics

                    resp = {"status": "ok", "stats": metrics.GLOBAL.snapshot()}
                elif op == "event":
                    # external harnesses report outcomes (crash observed,
                    # target hung) back through the HTTP API; a feedback-
                    # mode run folds them into seed energies
                    from ..corpus import feedback

                    kind = req.get("kind")
                    sid = req.get("seed_id")
                    if isinstance(kind, str) and kind:
                        feedback.publish(
                            kind,
                            seed_id=sid if isinstance(sid, str) else None,
                            source="faas",
                            detail=str(req.get("detail", ""))[:200],
                        )
                        resp = {"status": "ok"}
                    else:
                        resp = {"status": "badop"}
                else:
                    resp = {"status": "badop"}
                self._reply(200, json.dumps(resp).encode(), session,
                            ctype="application/json")
            except ValueError as e:
                self._reply(400, f"bad request: {e}".encode())
            return
        self._reply(404, b"not found")


def serve(host: str, port: int, opts: dict, backend: str = "oracle",
          batch: int = 256, auth_required: bool = False,
          block: bool = True):
    """Start the FaaS server; returns the server object when block=False.

    Serving mode comes from ``opts["serving"]`` ("continuous" | "flush",
    default continuous for the tpu backend) — the engine is built, and
    its compiled step warmed, HERE at server start, so no request pays
    an XLA compile."""
    from .batcher import service_budget

    serving = opts.get("serving") or "continuous"
    # a per-server handler subclass: batcher/cmanager must not be shared
    # class state, or starting a second service (e.g. one with auth)
    # would silently reconfigure every running server
    handler = type(
        "_BoundHandler",
        (_Handler,),
        {
            "batcher": make_engine(
                backend, serving=serving, batch=batch,
                workers=opts.get("workers", 10),
                seed=opts.get("seed"),
                max_running_time=service_budget(opts),
                warm=opts.get("warm", True),
                **{k: opts[k] for k in
                   ("capacity", "max_latency_ms", "inflight", "slots",
                    "classes")
                   if opts.get(k) is not None},
            ),
            "cmanager": CloudManager(
                auth_required=auth_required,
                store_path=opts.get("cmanager_store"),
            ),
            "tenants": TenantTable(
                rate=opts.get("tenant_rate", 0.0),
                burst=opts.get("tenant_burst"),
                corpus_dir=opts.get("corpus_dir"),
            ),
            "queue_cap": opts.get("queue_cap", 1024),
        },
    )
    srv = _FaasServer((host, port), handler)
    logger.log("info", "faas listening on %s:%d (backend=%s serving=%s)",
               host, port, backend, serving)
    print(f"# faas listening on {host}:{port} backend={backend} "
          f"serving={serving if backend == 'tpu' else 'oracle'} "
          f"admin-token={handler.cmanager.admin_token}", flush=True)
    if not block:
        import threading

        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0
