"""Fuzzing-as-a-service HTTP endpoint.

Reference: src/erlamsa_httpsvc.erl + src/erlamsa_esi.erl — endpoints
/erlamsa/erlamsa_esi:fuzz (octet-stream in/out), :json (base64 JSON), and
:manage (token admin), with fuzzing options in erlamsa-* HTTP headers or
JSON fields and session auth via the cloud manager. Requests are served
from the adaptive batcher instead of one process per request.
"""

from __future__ import annotations

import base64
import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils.erlrand import parse_seed
from . import logger
from .batcher import make_batcher
from .cmanager import CloudManager


def _parse_header_opts(headers) -> dict:
    """erlamsa-mutations/patterns/seed/blockscale headers
    (erlamsa_esi:parse_headers, src/erlamsa_esi.erl:34-56)."""
    opts: dict = {}
    m = headers.get("erlamsa-mutations")
    if m:
        from .cli import _parse_actions
        from ..oracle.mutations import default_mutations

        opts["mutations"] = _parse_actions(m, default_mutations())
    p = headers.get("erlamsa-patterns")
    if p:
        from .cli import _parse_actions
        from ..oracle.patterns import default_patterns

        opts["patterns"] = _parse_actions(p, default_patterns())
    s = headers.get("erlamsa-seed")
    if s:
        opts["seed"] = parse_seed(s)
    b = headers.get("erlamsa-blockscale")
    if b:
        opts["blockscale"] = float(b)
    return opts


class _Handler(BaseHTTPRequestHandler):
    server_version = "erlamsa-tpu"
    batcher = None
    cmanager: CloudManager | None = None

    def log_message(self, fmt, *args):
        logger.log("debug", "faas: " + fmt, *args)

    def _auth(self):
        cm = self.cmanager
        status, session = cm.get_client_context(
            self.headers.get("erlamsa-token"), self.headers.get("erlamsa-session")
        )
        return status, session

    def _reply(self, code: int, body: bytes, session: str = "",
               ctype="application/octet-stream"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.send_header("erlamsa-status", "ok" if code == 200 else "error")
        if session:
            self.send_header("erlamsa-session", session)
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):  # noqa: N802
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        path = self.path.rstrip("/")
        status, session = self._auth()
        if status != "ok":
            self._reply(401, b"unauthorized")
            return
        if path.endswith(("erlamsa_esi:fuzz", "/fuzz")):
            try:
                opts = _parse_header_opts(self.headers)
            except (ValueError, SystemExit) as e:
                self._reply(400, f"bad erlamsa-* header: {e}".encode())
                return
            out = self.batcher.fuzz(body, opts)
            self._reply(200, out, session)
            return
        if path.endswith(("erlamsa_esi:json", "/json")):
            try:
                req = json.loads(body)
                data = base64.b64decode(req.get("data", ""))
                opts: dict = {}
                if "seed" in req:
                    opts["seed"] = parse_seed(req["seed"])
                if "mutations" in req:
                    from .cli import _parse_actions
                    from ..oracle.mutations import default_mutations

                    opts["mutations"] = _parse_actions(
                        req["mutations"], default_mutations()
                    )
                out = self.batcher.fuzz(data, opts)
                self._reply(
                    200,
                    json.dumps({"data": base64.b64encode(out).decode()}).encode(),
                    session,
                    ctype="application/json",
                )
            except (ValueError, KeyError, SystemExit) as e:
                # _parse_actions raises SystemExit for unknown names —
                # a bad request here, not a server exit
                self._reply(400, f"bad request: {e}".encode())
            return
        if path.endswith(("erlamsa_esi:manage", "/manage")):
            try:
                req = json.loads(body)
                cm = self.cmanager
                admin = req.get("admin", "")
                op = req.get("op")
                if op == "addtoken":
                    t = cm.add_token(admin)
                    ok = t is not None
                    resp = {"status": "ok" if ok else "denied", "token": t or ""}
                elif op == "deltoken":
                    ok = cm.del_token(admin, req.get("token", ""))
                    resp = {"status": "ok" if ok else "denied"}
                elif op == "listtokens":
                    ts = cm.list_tokens(admin)
                    resp = {"status": "ok" if ts is not None else "denied",
                            "tokens": ts or []}
                else:
                    resp = {"status": "badop"}
                self._reply(200, json.dumps(resp).encode(), session,
                            ctype="application/json")
            except ValueError as e:
                self._reply(400, f"bad request: {e}".encode())
            return
        self._reply(404, b"not found")


def serve(host: str, port: int, opts: dict, backend: str = "oracle",
          batch: int = 256, auth_required: bool = False,
          block: bool = True):
    """Start the FaaS server; returns the server object when block=False."""
    from .batcher import service_budget

    _Handler.batcher = make_batcher(
        backend, batch=batch, workers=opts.get("workers", 10),
        seed=opts.get("seed"), max_running_time=service_budget(opts),
    )
    _Handler.cmanager = CloudManager(
        auth_required=auth_required,
        store_path=opts.get("cmanager_store"),
    )
    srv = ThreadingHTTPServer((host, port), _Handler)
    logger.log("info", "faas listening on %s:%d (backend=%s)", host, port, backend)
    print(f"# faas listening on {host}:{port} backend={backend} "
          f"admin-token={_Handler.cmanager.admin_token}", flush=True)
    if not block:
        import threading

        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0
