"""Fuzzing-as-a-service HTTP endpoint.

Reference: src/erlamsa_httpsvc.erl + src/erlamsa_esi.erl — endpoints
/erlamsa/erlamsa_esi:fuzz (octet-stream in/out), :json (base64 JSON), and
:manage (token admin), with fuzzing options in erlamsa-* HTTP headers or
JSON fields and session auth via the cloud manager. Requests are served
from the adaptive batcher instead of one process per request.
"""

from __future__ import annotations

import base64
import binascii
import json
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


class _FaasServer(ThreadingHTTPServer):
    """HTTPServer's default accept backlog is 5 — concurrent load (the
    reference serves 10k simultaneous fsupervisor requests) overflows it
    and the kernel RESETS connections. A deep listen queue plus the
    batcher's own queueing is the capacity model here."""

    request_queue_size = 1024

from ..utils.erlrand import parse_seed
from . import logger
from .batcher import make_batcher
from .cmanager import CloudManager


def _parse_opts(get) -> dict:
    """Shared option parsing for both transports: `get(name)` returns the
    raw value for mutations/patterns/seed/blockscale or None. Values must
    be strings (numbers allowed for blockscale) — anything else raises
    ValueError so callers answer HTTP 400, never a connection abort."""
    opts: dict = {}

    def _want_str(name, v):
        if not isinstance(v, str):
            raise ValueError(f"{name} must be a string")
        return v

    m = get("mutations")
    if m:
        from ..oracle.mutations import default_mutations
        from .cli import _parse_actions

        opts["mutations"] = _parse_actions(
            _want_str("mutations", m), default_mutations()
        )
    p = get("patterns")
    if p:
        from ..oracle.patterns import default_patterns
        from .cli import _parse_actions

        opts["patterns"] = _parse_actions(
            _want_str("patterns", p), default_patterns()
        )
    s = get("seed")
    if s:
        opts["seed"] = parse_seed(_want_str("seed", s))
    b = get("blockscale")
    if b:
        if not isinstance(b, (str, int, float)) or isinstance(b, bool):
            raise ValueError("blockscale must be a number")
        opts["blockscale"] = float(b)
    return opts


def _parse_header_opts(headers) -> dict:
    """erlamsa-mutations/patterns/seed/blockscale headers
    (erlamsa_esi:parse_headers, src/erlamsa_esi.erl:34-56)."""
    return _parse_opts(lambda name: headers.get(f"erlamsa-{name}"))


class _Handler(BaseHTTPRequestHandler):
    server_version = "erlamsa-tpu"
    batcher = None
    cmanager: CloudManager | None = None

    def log_message(self, fmt, *args):
        logger.log("debug", "faas: " + fmt, *args)

    def _auth(self, body_req: dict | None = None):
        """Token/session from erlamsa-* headers, or (JSON API) from the
        request body — the reference accepts both (erlamsa_esi.erl
        parse_headers:34-56 / parse_json:70-82)."""
        cm = self.cmanager
        body_req = body_req or {}

        def _str_or_none(v):
            # non-string JSON values (dict/list/number) must not reach the
            # token store — an unhashable value would crash pre-auth
            return v if isinstance(v, str) else None

        status, session = cm.get_client_context(
            self.headers.get("erlamsa-token")
            or _str_or_none(body_req.get("token")),
            self.headers.get("erlamsa-session")
            or _str_or_none(body_req.get("session")),
        )
        return status, session

    def _reply(self, code: int, body: bytes, session: str = "",
               ctype="application/octet-stream"):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.send_header("erlamsa-status", "ok" if code == 200 else "error")
        if session:
            self.send_header("erlamsa-session", session)
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802
        # Prometheus scrape endpoint: counters aren't secrets (same rule
        # as the stats op), so no auth gate — scrapers don't do sessions
        if self.path.split("?")[0] == "/metrics":
            from ..obs import prom

            self._reply(200, prom.render().encode(), ctype=prom.CONTENT_TYPE)
            return
        self._reply(404, b"not found")

    def do_POST(self):  # noqa: N802
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        path = self.path.rstrip("/")
        is_json = path.endswith(("erlamsa_esi:json", "/json"))
        body_req: dict = {}
        if is_json:
            try:
                body_req = json.loads(body)
                if not isinstance(body_req, dict):
                    raise ValueError("JSON body must be an object")
            except ValueError as e:
                self._reply(400, json.dumps({"error": f"bad json: {e}"})
                            .encode(), ctype="application/json")
                return
        status, session = self._auth(body_req)
        if status != "ok":
            if is_json:
                self._reply(401, json.dumps({"error": "unauthorized"})
                            .encode(), ctype="application/json")
            else:
                self._reply(401, b"unauthorized")
            return
        if path.endswith(("erlamsa_esi:fuzz", "/fuzz")):
            try:
                opts = _parse_header_opts(self.headers)
            except (ValueError, SystemExit) as e:
                self._reply(400, f"bad erlamsa-* header: {e}".encode())
                return
            out = self.batcher.fuzz(body, opts)
            self._reply(200, out, session)
            return
        if is_json:
            try:
                data = base64.b64decode(body_req.get("data", ""))
                opts = _parse_opts(body_req.get)
                out = self.batcher.fuzz(data, opts)
                self._reply(
                    200,
                    json.dumps({"data": base64.b64encode(out).decode()}).encode(),
                    session,
                    ctype="application/json",
                )
            except (ValueError, KeyError, TypeError, binascii.Error,
                    SystemExit) as e:
                # _parse_actions raises SystemExit for unknown names —
                # a bad request here, not a server exit
                self._reply(400, json.dumps({"error": f"bad request: {e}"})
                            .encode(), ctype="application/json")
            return
        if path.endswith(("erlamsa_esi:manage", "/manage")):
            try:
                req = json.loads(body)
                cm = self.cmanager
                admin = req.get("admin", "")
                op = req.get("op")
                if op == "addtoken":
                    t = cm.add_token(admin)
                    ok = t is not None
                    resp = {"status": "ok" if ok else "denied", "token": t or ""}
                elif op == "deltoken":
                    ok = cm.del_token(admin, req.get("token", ""))
                    resp = {"status": "ok" if ok else "denied"}
                elif op == "listtokens":
                    ts = cm.list_tokens(admin)
                    resp = {"status": "ok" if ts is not None else "denied",
                            "tokens": ts or []}
                elif op == "stats":
                    # throughput + per-mutator applied/failed + bucket
                    # stats; counters aren't secrets, so no admin gate
                    from . import metrics

                    resp = {"status": "ok", "stats": metrics.GLOBAL.snapshot()}
                elif op == "event":
                    # external harnesses report outcomes (crash observed,
                    # target hung) back through the HTTP API; a feedback-
                    # mode run folds them into seed energies
                    from ..corpus import feedback

                    kind = req.get("kind")
                    sid = req.get("seed_id")
                    if isinstance(kind, str) and kind:
                        feedback.publish(
                            kind,
                            seed_id=sid if isinstance(sid, str) else None,
                            source="faas",
                            detail=str(req.get("detail", ""))[:200],
                        )
                        resp = {"status": "ok"}
                    else:
                        resp = {"status": "badop"}
                else:
                    resp = {"status": "badop"}
                self._reply(200, json.dumps(resp).encode(), session,
                            ctype="application/json")
            except ValueError as e:
                self._reply(400, f"bad request: {e}".encode())
            return
        self._reply(404, b"not found")


def serve(host: str, port: int, opts: dict, backend: str = "oracle",
          batch: int = 256, auth_required: bool = False,
          block: bool = True):
    """Start the FaaS server; returns the server object when block=False."""
    from .batcher import service_budget

    # a per-server handler subclass: batcher/cmanager must not be shared
    # class state, or starting a second service (e.g. one with auth)
    # would silently reconfigure every running server
    handler = type(
        "_BoundHandler",
        (_Handler,),
        {
            "batcher": make_batcher(
                backend, batch=batch, workers=opts.get("workers", 10),
                seed=opts.get("seed"),
                max_running_time=service_budget(opts),
                **{k: opts[k] for k in
                   ("capacity", "max_latency_ms", "inflight")
                   if opts.get(k) is not None},
            ),
            "cmanager": CloudManager(
                auth_required=auth_required,
                store_path=opts.get("cmanager_store"),
            ),
        },
    )
    srv = _FaasServer((host, port), handler)
    logger.log("info", "faas listening on %s:%d (backend=%s)", host, port, backend)
    print(f"# faas listening on {host}:{port} backend={backend} "
          f"admin-token={handler.cmanager.admin_token}", flush=True)
    if not block:
        import threading

        threading.Thread(target=srv.serve_forever, daemon=True).start()
        return srv
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0
