"""Checkpoint/resume for batch runs.

The reference's checkpoint is (seed, case index): last_seed.txt plus
--skip reproduces any point of the stream because everything is a pure
function of the PRNG (SURVEY.md §5.4). The TPU path keeps that contract —
counter keys derive from (seed, case, sample) — plus one piece of real
state: the per-sample scheduler scores (and, in sequence mode, the case
counter). This module persists both as a .npz so a long corpus run can
stop and resume exactly.
"""

from __future__ import annotations

import os

import numpy as np


def save_state(path: str, seed, case_idx: int, scores) -> None:
    """Atomic write (tmp + rename): a kill mid-save — the very interruption
    checkpoints exist for — must never corrupt the previous checkpoint."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(
            f,
            seed=np.asarray(seed, np.int64),
            case_idx=np.asarray(case_idx, np.int64),
            scores=np.asarray(scores, np.int32),
        )
        # data must be durable BEFORE the rename publishes it, or a crash
        # right after os.replace leaves a truncated checkpoint and the run
        # silently restarts from case 0
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    try:
        dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


def load_state(path: str):
    """-> (seed tuple, case_idx, scores ndarray), or None when the file is
    unreadable/corrupt (callers start fresh)."""
    try:
        with np.load(path) as z:
            seed = tuple(int(x) for x in z["seed"])
            case_idx = int(z["case_idx"])
            scores = z["scores"].copy()
        return seed, case_idx, scores
    except Exception:
        return None
