"""Checkpoint/resume for batch runs.

The reference's checkpoint is (seed, case index): last_seed.txt plus
--skip reproduces any point of the stream because everything is a pure
function of the PRNG (SURVEY.md §5.4). The TPU path keeps that contract —
counter keys derive from (seed, case, sample) — plus one piece of real
state: the per-sample scheduler scores (and, in sequence mode, the case
counter). This module persists both as a .npz so a long corpus run can
stop and resume exactly.
"""

from __future__ import annotations

import os
import zipfile
import zlib

import numpy as np

from . import chaos, logger
from .resilience import RetryExhausted, RetryPolicy

# checkpoint saves ride the same quick-retry policy as the corpus store:
# one retry absorbs a transient disk error (or an injected checkpoint.save
# fault); a persistently failing disk degrades to best-effort — the run
# continues and resume restarts from the previous good checkpoint
SAVE_RETRY = RetryPolicy(attempts=2, base=0.01, max_delay=0.1,
                         retry_on=(OSError,))


def _engine_stamp(engine: str = "fused") -> np.ndarray:
    """(engine id string) the saved stream is only replayable under: the
    engine name, the ERLAMSA_PALLAS level, and the device-registry size
    (engines draw differently, and a registry growth like the r5
    ab/ad/len/ft/fn/fo move changes every weighted pick). engine comes
    from the caller — the batch runner always builds the fused engine
    today, so the default reflects the only shipping configuration."""
    from ..ops.registry import NUM_DEVICE_MUTATORS

    pallas = os.environ.get("ERLAMSA_PALLAS", "0")
    return np.asarray(f"{engine}/pallas{pallas}/M{NUM_DEVICE_MUTATORS}", "U32")


def _checksum(fields: dict) -> np.ndarray:
    """crc32 over every field's raw bytes in key order: cheap end-to-end
    integrity for the whole checkpoint (npz's per-member zlib CRCs don't
    catch a member silently missing or a short write of the directory)."""
    crc = 0
    for k in sorted(fields):
        if k == "checksum":
            continue
        crc = zlib.crc32(k.encode(), crc)
        crc = zlib.crc32(np.asarray(fields[k]).tobytes(), crc)
    return np.asarray(crc & 0xFFFFFFFF, np.uint32)


def fsync_dir(path: str) -> None:
    """fsync the directory holding `path` so the rename that published it
    is itself durable (shared with corpus/store.py)."""
    try:
        dfd = os.open(os.path.dirname(os.path.abspath(path)), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass


#: version stamp for the coverage-map checkpoint fields: bump on any
#: change to their layout/semantics so a resume can never silently
#: alias maps written under a different scheme
COVERAGE_STATE_VERSION = 1


def _coverage_fields(coverage: dict) -> dict:
    """Kind-stamped, versioned coverage-map fields shared by the
    single-device and fleet checkpoints: load_coverage_maps reads both
    (it keys on the cov_* names, not the checkpoint kind)."""
    width = len(coverage["global"])
    cov_ids = list(coverage["ids"])
    cov_maps = (np.asarray(coverage["maps"], np.uint8)
                if cov_ids else np.zeros((0, width), np.uint8))
    return dict(
        cov_kind=np.asarray("edges", "U8"),
        cov_version=np.asarray(COVERAGE_STATE_VERSION, np.int64),
        cov_map_bytes=np.asarray(width, np.int64),
        cov_ids=np.asarray(cov_ids, "U64"),
        cov_maps=cov_maps,
        cov_global=np.asarray(coverage["global"], np.uint8),
    )


def save_state(path: str, seed, case_idx: int, scores,
               host_scores: dict | None = None,
               host_scores_post: dict | None = None,
               engine: str = "fused",
               corpus_energies: dict | None = None,
               coverage: dict | None = None) -> None:
    """Atomic write (tmp + rename): a kill mid-save — the very interruption
    checkpoints exist for — must never corrupt the previous checkpoint.
    host_scores: the hybrid routing scores the resumed case's split must
    see (the pipelined loop gives split a one-case outcome lag);
    host_scores_post: the same scores WITH the just-finished case's
    outcomes folded in — the state every later split builds on. Saving
    both is what makes an interrupted run route identically to an
    uninterrupted one.
    corpus_energies: {seed_id: (energy, hits)} from the corpus store
    (corpus/store.py) — the feedback-mode schedule state; restoring it
    makes a resumed run draw identical schedules.
    coverage: CoverageIndex.snapshot() ({ids, maps, global}) from a
    --coverage run. The fields are kind-stamped ("edges") and versioned
    (COVERAGE_STATE_VERSION) with the map width recorded explicitly, so
    load_coverage_maps can refuse — never alias — maps written under a
    different scheme or width."""
    tmp = path + ".tmp"
    hs = host_scores or {}
    hsp = host_scores_post if host_scores_post is not None else hs
    fields = dict(
        seed=np.asarray(seed, np.int64),
        case_idx=np.asarray(case_idx, np.int64),
        engine=_engine_stamp(engine),
        scores=np.asarray(scores, np.int32),
        host_codes=np.asarray(sorted(hs), "U8"),
        host_values=np.asarray([hs[k] for k in sorted(hs)], np.float64),
        host_codes_post=np.asarray(sorted(hsp), "U8"),
        host_values_post=np.asarray(
            [hsp[k] for k in sorted(hsp)], np.float64
        ),
    )
    if corpus_energies is not None:
        # only feedback-mode runs carry corpus state; stateless
        # checkpoints stay field-free so load_corpus_energies can tell
        # "no corpus" (None) from "corpus with zero seeds" ({})
        ce_ids = sorted(corpus_energies)
        fields.update(
            corpus_ids=np.asarray(ce_ids, "U64"),
            corpus_energy=np.asarray(
                [float(corpus_energies[s][0]) for s in ce_ids], np.float64
            ),
            corpus_hits=np.asarray(
                [int(corpus_energies[s][1]) for s in ce_ids], np.int64
            ),
        )
    if coverage is not None:
        fields.update(_coverage_fields(coverage))
    fields["checksum"] = _checksum(fields)

    def _write():
        chaos.fault_point("checkpoint.save")
        with open(tmp, "wb") as f:
            np.savez(f, **fields)
            # data must be durable BEFORE the rename publishes it, or a
            # crash right after os.replace leaves a truncated checkpoint
            # and the run silently restarts from case 0
            f.flush()
            os.fsync(f.fileno())
        # keep the previous good checkpoint as .bak: the loaders fall back
        # to it when the primary turns out corrupt (torn disk, fs bug) — a
        # run then resumes a few cases earlier instead of restarting from 0
        if os.path.exists(path):
            try:
                os.replace(path, path + ".bak")
            except OSError:
                pass
        os.replace(tmp, path)
        fsync_dir(path)

    try:
        SAVE_RETRY.call(_write, site="checkpoint.save")
    except (RetryExhausted, OSError):
        logger.log("warning", "checkpoint %s: save failed; run continues, "
                   "resume falls back to the previous checkpoint", path)


def quarantine_mismatch(path: str) -> bool:
    """A checkpoint that LOADED fine but does not match the run it was
    handed to (different seed, different score shape) is evidence worth
    keeping, not a file to silently bury under the next save: move it
    aside to `.bak` so the operator can still resume the original run
    from it. Returns True when the quarantine landed. The next save then
    finds no primary and does not rotate, so the quarantined file
    survives at least one save cycle."""
    try:
        # the quarantine IS a durable publish on the checkpoint path, so
        # it shares the save fault site: an injected checkpoint.save
        # fault degrades it to "start fresh without quarantine" — the
        # same best-effort contract as the save itself
        chaos.fault_point("checkpoint.save")
        os.replace(path, path + ".bak")
        fsync_dir(path)
    except OSError:
        return False
    from . import metrics

    metrics.GLOBAL.record_event("checkpoint_quarantined")
    logger.log("warning", "checkpoint %s: mismatched state quarantined "
               "to %s.bak", path, path)
    return True


def save_fleet_state(path: str, seed, case_idx: int, scores, seen_hashes,
                     corpus_energies: dict, epoch: int, n_shards: int,
                     classes, engine: str = "fused",
                     events: dict | None = None,
                     coverage: dict | None = None,
                     membership: dict | None = None) -> None:
    """Fleet-coordinator checkpoint (corpus/fleet.py --shards --state):
    per-case progress plus everything the resumed coordinator needs to
    continue byte-identically — scheduler scores, the global seen-hash
    dedupe set (12-byte sha1 prefixes), corpus energies, the placement
    fencing epoch, and the capacity-class set (resolved from the store
    at case 0; a resumed store already holds adopted offspring, so
    re-deriving would change row widths and therefore bytes). Same
    durability contract as save_state: crc32 whole-file checksum,
    fsync-before-rename, previous checkpoint kept as .bak."""
    tmp = path + ".tmp"
    seen_sorted = sorted(seen_hashes)
    seen_arr = (np.frombuffer(b"".join(seen_sorted), np.uint8)
                .reshape(len(seen_sorted), 12)
                if seen_sorted else np.zeros((0, 12), np.uint8))
    ce_ids = sorted(corpus_energies or {})
    fields = dict(
        kind=np.asarray("fleet", "U8"),
        seed=np.asarray(seed, np.int64),
        case_idx=np.asarray(case_idx, np.int64),
        engine=_engine_stamp(engine),
        scores=np.asarray(scores, np.int32),
        seen=seen_arr,
        epoch=np.asarray(epoch, np.int64),
        n_shards=np.asarray(n_shards, np.int64),
        classes=np.asarray(list(classes), np.int64),
        corpus_ids=np.asarray(ce_ids, "U64"),
        corpus_energy=np.asarray(
            [float(corpus_energies[s][0]) for s in ce_ids], np.float64),
        corpus_hits=np.asarray(
            [int(corpus_energies[s][1]) for s in ce_ids], np.int64),
    )
    if events:
        # observability carry-over (r18): resilience-event counters
        # (fence_rejected, telemetry_lost, ...) survive a resume so
        # scraped counters never go backwards across a restore
        ev_kinds = sorted(events)
        fields["events_kinds"] = np.asarray(ev_kinds, "U64")
        fields["events_counts"] = np.asarray(
            [int(events[k]) for k in ev_kinds], np.int64)
    if coverage is not None:
        # r19 fleet coverage: same kind-stamped fields as save_state —
        # load_coverage_maps reads them off either checkpoint kind
        fields.update(_coverage_fields(coverage))
    if membership is not None:
        # r20 elastic membership: the ledger (generation + event
        # history) and the per-shard backend map ride the checkpoint so
        # a resume mid-churn reconstructs WHO was serving each slot —
        # "host:port" for a remote tenant, "local" for a device shard,
        # "" for a vacant slot — and continues the membership history
        # instead of forgetting every join/drain that already happened
        evs = membership.get("events") or []
        fields["membership_generation"] = np.asarray(
            int(membership.get("generation", 0)), np.int64)
        fields["membership_ev_kinds"] = np.asarray(
            [str(e["kind"]) for e in evs], "U16")
        fields["membership_ev_gens"] = np.asarray(
            [int(e["gen"]) for e in evs], np.int64)
        fields["membership_ev_shards"] = np.asarray(
            [int(e["shard"]) for e in evs], np.int64)
        fields["membership_ev_cases"] = np.asarray(
            [int(e["case"]) for e in evs], np.int64)
        fields["membership_ev_epochs"] = np.asarray(
            [int(e["epoch"]) for e in evs], np.int64)
        fields["membership_backends"] = np.asarray(
            [str(b) for b in membership.get("backends") or []], "U64")
        fields["membership_live"] = np.asarray(
            [1 if x else 0 for x in membership.get("live") or []],
            np.int64)
    fields["checksum"] = _checksum(fields)

    def _write():
        chaos.fault_point("fleet.checkpoint")
        with open(tmp, "wb") as f:
            np.savez(f, **fields)
            f.flush()
            os.fsync(f.fileno())
        if os.path.exists(path):
            try:
                os.replace(path, path + ".bak")
            except OSError:
                pass
        os.replace(tmp, path)
        fsync_dir(path)

    try:
        SAVE_RETRY.call(_write, site="fleet.checkpoint")
    except (RetryExhausted, OSError):
        logger.log("warning", "fleet checkpoint %s: save failed; run "
                   "continues, resume falls back to the previous "
                   "checkpoint", path)


def load_fleet_state(path: str, engine: str = "fused") -> dict | None:
    """-> {seed, case_idx, scores, seen, energies, epoch, n_shards,
    classes} from a fleet checkpoint, or None when the file (and its
    .bak) is unreadable/corrupt, stamped for a different engine, or is
    not a fleet checkpoint at all (a single-device save_state file
    handed to --shards must start fresh, not half-resume)."""
    try:
        z = _load_fields(path, engine)
        if z is None or str(z.get("kind", "")) != "fleet":
            return None
        return {
            "seed": tuple(int(x) for x in z["seed"]),
            "case_idx": int(z["case_idx"]),
            "scores": z["scores"].copy(),
            "seen": {bytes(row) for row in z["seen"]},
            "energies": {
                str(s): (float(e), int(h))
                for s, e, h in zip(z["corpus_ids"], z["corpus_energy"],
                                   z["corpus_hits"])
            },
            "epoch": int(z["epoch"]),
            "n_shards": int(z["n_shards"]),
            "classes": tuple(int(c) for c in z["classes"]),
            # optional (absent in pre-r18 checkpoints — membership
            # check, not indexing, or the broad except would reject
            # every old checkpoint via KeyError)
            "events": ({str(k): int(n)
                        for k, n in zip(z["events_kinds"],
                                        z["events_counts"])}
                       if "events_kinds" in z else {}),
            # optional (absent pre-r20): membership ledger + backend map
            "membership": ({
                "generation": int(z["membership_generation"]),
                "events": [
                    {"gen": int(g), "kind": str(k), "shard": int(s),
                     "case": int(c), "epoch": int(e)}
                    for g, k, s, c, e in zip(
                        z["membership_ev_gens"], z["membership_ev_kinds"],
                        z["membership_ev_shards"],
                        z["membership_ev_cases"],
                        z["membership_ev_epochs"])
                ],
                "backends": [str(b) for b in z["membership_backends"]],
                "live": [bool(x) for x in z["membership_live"]],
            } if "membership_generation" in z else None),
        }
    except (OSError, KeyError, ValueError, TypeError, zipfile.BadZipFile,
            zlib.error):
        return None


def _read_verified(path: str) -> dict | None:
    """Materialize one checkpoint file's fields, verifying the whole-file
    checksum when present (pre-checksum files pass — their contract was
    weaker but real). Raises on unreadable/corrupt input; the caller
    decides whether a fallback exists."""
    chaos.fault_point("checkpoint.load")
    with np.load(path) as z:
        fields = {k: z[k].copy() for k in z.files}
    if "checksum" in fields:
        want = int(fields["checksum"])
        got = int(_checksum(fields))
        if want != got:
            raise ValueError(
                f"checkpoint {path}: checksum mismatch "
                f"(stored {want:#010x}, computed {got:#010x})"
            )
    return fields


def _load_fields(path: str, engine: str) -> dict | None:
    """Load the primary checkpoint, falling back to .bak when the primary
    is unreadable or fails its checksum. None when neither is usable or
    the stamp names a different engine/pallas-level/registry (a stampless
    file is by definition pre-r5: its stream ran the 25-mutator registry
    and cannot resume bit-faithfully either)."""
    fields = None
    for candidate in (path, path + ".bak"):
        try:
            fields = _read_verified(candidate)
            if candidate != path:
                from . import metrics

                metrics.GLOBAL.record_event("checkpoint_bak_fallback")
                logger.log("warning", "checkpoint %s unusable, resumed "
                           "from backup %s", path, candidate)
            break
        except (OSError, KeyError, ValueError, zipfile.BadZipFile,
                zlib.error) as e:
            if candidate == path:
                logger.log("warning", "checkpoint %s unreadable (%s), "
                           "trying backup", path, e)
            fields = None
    if fields is None:
        return None
    if "engine" not in fields or str(fields["engine"]) != str(
        _engine_stamp(engine)
    ):
        return None
    return fields


def load_state(path: str, engine: str = "fused"):
    """-> (seed tuple, case_idx, scores ndarray, host_scores dict,
    host_scores_post dict), or None when the file (and its .bak) is
    unreadable/corrupt OR was written under a different engine/pallas-
    level/registry (the stream is only reproducible per-engine — callers
    start fresh). Older files without the post state fall back to the pre
    state."""
    try:
        z = _load_fields(path, engine)
        if z is None:
            return None
        seed = tuple(int(x) for x in z["seed"])
        case_idx = int(z["case_idx"])
        scores = z["scores"].copy()
        host_scores = {}
        if "host_codes" in z:
            host_scores = {
                str(c): float(v)
                for c, v in zip(z["host_codes"], z["host_values"])
            }
        host_post = dict(host_scores)
        if "host_codes_post" in z:
            host_post = {
                str(c): float(v)
                for c, v in zip(z["host_codes_post"],
                                z["host_values_post"])
            }
        return seed, case_idx, scores, host_scores, host_post
    except (OSError, KeyError, ValueError, TypeError, zipfile.BadZipFile,
            zlib.error):
        return None


def load_coverage_maps(path: str, map_bytes: int,
                       engine: str = "fused") -> tuple[str, dict | None]:
    """Coverage-map leg of a --coverage resume. Returns a verdict pair:

    - ("ok", {ids, maps, global}) — kind/version/width all match; feed
      it to CoverageIndex.restore().
    - ("absent", None) — the checkpoint carries no coverage fields at
      all (a pre-coverage or stateless checkpoint, or no usable file).
      Resuming with fresh, empty coverage cannot alias anything.
    - ("mismatch", None) — coverage fields exist but their kind,
      version, or map width disagrees with this run. The caller must
      quarantine the checkpoint (quarantine_mismatch) and start fresh:
      folding new bitmaps into maps written under another scheme would
      corrupt every subsequent adoption decision.
    """
    try:
        z = _load_fields(path, engine)
        if z is None or "cov_kind" not in z:
            return "absent", None
        if (str(z["cov_kind"]) != "edges"
                or int(z.get("cov_version", -1)) != COVERAGE_STATE_VERSION
                or int(z.get("cov_map_bytes", -1)) != int(map_bytes)
                or z["cov_maps"].ndim != 2
                or z["cov_maps"].shape[1] != int(map_bytes)
                or len(z["cov_global"]) != int(map_bytes)):
            return "mismatch", None
        return "ok", {
            "ids": [str(s) for s in z["cov_ids"]],
            "maps": z["cov_maps"].copy(),
            "global": z["cov_global"].copy(),
        }
    except (OSError, KeyError, ValueError, TypeError, zipfile.BadZipFile,
            zlib.error):
        return "absent", None


def load_corpus_energies(path: str, engine: str = "fused") -> dict | None:
    """-> {seed_id: (energy, hits)} from a feedback-mode checkpoint, or
    None when the file is unreadable, stamped for a different engine, or
    predates the corpus fields. Kept separate from load_state so its
    5-tuple contract (and every existing caller) stays untouched."""
    try:
        z = _load_fields(path, engine)
        if z is None or "corpus_ids" not in z:
            return None
        return {
            str(s): (float(e), int(h))
            for s, e, h in zip(z["corpus_ids"], z["corpus_energy"],
                               z["corpus_hits"])
        }
    except (OSError, KeyError, ValueError, TypeError, zipfile.BadZipFile,
            zlib.error):
        return None
