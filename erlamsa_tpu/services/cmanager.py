"""Cloud manager: API tokens and sessions for the FaaS service.

Reference: src/erlamsa_cmanager.erl — 160-bit base64 tokens and sessions
with 600s expiry kept in mnesia, token CRUD gated by an admin token. Here
an in-memory store with a lock (the FaaS server is threaded).
"""

from __future__ import annotations

import base64
import os
import threading
import time

from ..constants import NODE_ALIVE_DELTA  # noqa: F401  (re-exported constants live here)

SESSION_EXPIRETIME = 600.0  # src/erlamsa.hrl:71
TOKEN_BITS = 160  # src/erlamsa.hrl:69


def _new_token() -> str:
    return base64.b64encode(os.urandom(TOKEN_BITS // 8)).decode()


class CloudManager:
    def __init__(self, admin_token: str | None = None, auth_required: bool = False):
        self.admin_token = admin_token or _new_token()
        self.auth_required = auth_required
        self._tokens: dict[str, dict] = {}
        self._sessions: dict[str, dict] = {}
        self._lock = threading.Lock()

    # --- token CRUD (admin-gated, erlamsa_cmanager.erl:174-179) ----------

    def add_token(self, admin: str, kind: str = "user") -> str | None:
        if admin != self.admin_token:
            return None
        t = _new_token()
        with self._lock:
            self._tokens[t] = {"date": time.time(), "type": kind}
        return t

    def del_token(self, admin: str, token: str) -> bool:
        if admin != self.admin_token:
            return False
        with self._lock:
            return self._tokens.pop(token, None) is not None

    def list_tokens(self, admin: str) -> list[str] | None:
        if admin != self.admin_token:
            return None
        with self._lock:
            return list(self._tokens)

    # --- sessions (erlamsa_cmanager.erl:124-133, 225-242) ----------------

    def get_client_context(self, token: str | None, session: str | None):
        """Returns (status, session_id): 'ok' with a fresh/refreshed session,
        or 'unauthorized'."""
        self._cleanup()
        if not self.auth_required:
            return "ok", session or _new_token()[:27]
        with self._lock:
            if session and session in self._sessions:
                self._sessions[session]["lastaccess"] = time.time()
                return "ok", session
            if token and (token in self._tokens or token == self.admin_token):
                s = _new_token()[:27]
                self._sessions[s] = {"token": token, "lastaccess": time.time()}
                return "ok", s
        return "unauthorized", ""

    def _cleanup(self):
        now = time.time()
        with self._lock:
            dead = [
                s for s, v in self._sessions.items()
                if now - v["lastaccess"] > SESSION_EXPIRETIME
            ]
            for s in dead:
                del self._sessions[s]
