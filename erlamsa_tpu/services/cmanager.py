"""Cloud manager: API tokens and sessions for the FaaS service.

Reference: src/erlamsa_cmanager.erl — 160-bit base64 tokens and sessions
with 600s expiry kept in mnesia (records src/erlamsa.hrl:104-106), token
CRUD gated by an admin token. Here a locked in-memory store (the FaaS
server is threaded) with optional JSON persistence standing in for
mnesia: pass store_path and tokens/sessions survive a process restart.
"""

from __future__ import annotations

import base64
import json
import os
import threading
import time

# lint: unused-import-ok re-exported: cluster callers import it from here
from ..constants import NODE_ALIVE_DELTA  # noqa: F401

SESSION_EXPIRETIME = 600.0  # src/erlamsa.hrl:71
TOKEN_BITS = 160  # src/erlamsa.hrl:69


def _new_token() -> str:
    return base64.b64encode(os.urandom(TOKEN_BITS // 8)).decode()


class CloudManager:
    def __init__(self, admin_token: str | None = None,
                 auth_required: bool = False,
                 store_path: str | None = None):
        self._explicit_admin = admin_token is not None
        self.admin_token = admin_token or _new_token()
        self.auth_required = auth_required
        self.store_path = store_path
        self._tokens: dict[str, dict] = {}
        self._sessions: dict[str, dict] = {}
        self._lock = threading.Lock()
        self._load()

    # --- persistence (mnesia stand-in, erlamsa_cmanager.erl:124-133) -----

    def _load(self):
        if not self.store_path or not os.path.exists(self.store_path):
            return
        try:
            with open(self.store_path) as f:
                st = json.load(f)
            self._tokens = dict(st.get("tokens", {}))
            self._sessions = dict(st.get("sessions", {}))
            # lastaccess refreshes are in-memory only (persisting every
            # request would hammer the store); treat the restart itself as
            # activity so sessions that were live at save time stay usable
            now = time.time()
            for v in self._sessions.values():
                v["lastaccess"] = now
            if not self._explicit_admin and st.get("admin_token"):
                # a persisted admin token wins over a freshly generated one
                # (a restarted service must honor tokens it already issued)
                self.admin_token = st["admin_token"]
        except (OSError, ValueError):
            pass  # unreadable store: start empty, overwrite on next save

    def _save_locked(self):
        """Caller holds self._lock."""
        if not self.store_path:
            return
        tmp = self.store_path + ".tmp"
        try:
            with open(tmp, "w") as f:
                json.dump({"admin_token": self.admin_token,
                           "tokens": self._tokens,
                           "sessions": self._sessions}, f)
            os.replace(tmp, self.store_path)
        except OSError:
            pass  # persistence is best-effort; the live store stays valid

    # --- token CRUD (admin-gated, erlamsa_cmanager.erl:174-179) ----------

    def add_token(self, admin: str, kind: str = "user") -> str | None:
        if admin != self.admin_token:
            return None
        t = _new_token()
        with self._lock:
            self._tokens[t] = {"date": time.time(), "type": kind}
            self._save_locked()
        return t

    def del_token(self, admin: str, token: str) -> bool:
        if admin != self.admin_token:
            return False
        with self._lock:
            existed = self._tokens.pop(token, None) is not None
            if existed:
                self._save_locked()
            return existed

    def list_tokens(self, admin: str) -> list[str] | None:
        if admin != self.admin_token:
            return None
        with self._lock:
            return list(self._tokens)

    # --- sessions (erlamsa_cmanager.erl:124-133, 225-242) ----------------

    def get_client_context(self, token: str | None, session: str | None):
        """Returns (status, session_id): 'ok' with a fresh/refreshed session,
        or 'unauthorized'."""
        self._cleanup()
        if not self.auth_required:
            return "ok", session or _new_token()[:27]
        with self._lock:
            if session and session in self._sessions:
                self._sessions[session]["lastaccess"] = time.time()
                return "ok", session
            if token and (token in self._tokens or token == self.admin_token):
                s = _new_token()[:27]
                self._sessions[s] = {"token": token, "lastaccess": time.time()}
                self._save_locked()
                return "ok", s
        return "unauthorized", ""

    def _cleanup(self):
        now = time.time()
        with self._lock:
            dead = [
                s for s, v in self._sessions.items()
                if now - v["lastaccess"] > SESSION_EXPIRETIME
            ]
            for s in dead:
                del self._sessions[s]
