"""The hybrid dispatcher's host-pool worker, in a deliberately jax-free
module: process-pool workers (spawn) import the function's defining module
on unpickle, and in this image a bare ``import jax`` can BLOCK when the
axon TPU relay is wedged — so the worker lives here, where the transitive
imports are only the oracle engine and the watchdog (both pure Python).
tests/test_hybrid.py pins the no-jax property.
"""

from __future__ import annotations


def warmup(_i):
    """No-op used to force worker bootstrap while the parent holds a
    known-safe environment (see HybridDispatcher.__init__)."""
    return None


def host_worker(args):
    """One host-routed oracle case, a pure function of its args so results
    are identical across thread and process pools."""
    i, data, ts, host_rows, budget = args
    from ..oracle.engine import Engine
    from ..utils.watchdog import CaseTimeout, run_with_timeout

    def case():
        eng = Engine({"paths": ["direct"], "input": data, "seed": ts,
                      "n": 1, "mutations": host_rows})
        return eng.run_case(1)

    try:
        out, meta = run_with_timeout(case, budget)
    except CaseTimeout:
        return i, None, []
    return i, out, meta
