"""NHRP external module: the shipped real-protocol example for the -e hook.

Mirrors the reference's ``external_nhrp.erl`` (repo root of the
reference): a post-processor that re-fixes the NHRP packet checksum after
mutation, so fuzzed packets keep passing the target's integrity check and
the interesting payload bytes actually get parsed. Layout follows the
reference module exactly — a 4-byte fixed prefix, 12 header bytes, a
16-bit one's-complement checksum, then the body — and, like the
reference's ``fix_checksum``, the checksum is computed over the packet
WITHOUT the 4-byte prefix, checksum field zeroed (``packet:makesum``
semantics = the RFC 1071 internet checksum).

On top of the reference's post hook this module also provides the
``fuzzer`` capability used by gfcomms/proxy session fuzzing: a
protocol-shaped fuzz that preserves the 18-byte header structure, mutates
only the body through the full oracle engine, and re-fixes the checksum —
i.e. structure-aware fuzzing of a real protocol through the same -e seam
a user's own module would use.

Usage:  -e erlamsa_tpu.services.external_nhrp
"""

from __future__ import annotations

_PREFIX = 4          # the reference's HSRP:32 fixed prefix
_HDR = 12            # Hdr:96
_CKSUM_OFF = _PREFIX + _HDR  # 2-byte checksum right after the header
_MIN = _CKSUM_OFF + 2


def capabilities() -> set[str]:
    return {"post", "fuzzer"}


def inet_checksum(data: bytes) -> int:
    """RFC 1071 one's-complement 16-bit checksum (packet:makesum)."""
    if len(data) % 2:
        data += b"\x00"
    total = 0
    for i in range(0, len(data), 2):
        total += (data[i] << 8) | data[i + 1]
        total = (total & 0xFFFF) + (total >> 16)
    return (~total) & 0xFFFF


def fix_checksum(data: bytes) -> bytes:
    """Rewrite the checksum field so the packet verifies; packets too
    short to carry the header pass through untouched (the reference's
    catch-all clause)."""
    if len(data) < _MIN:
        return data
    stub = data[_PREFIX:_CKSUM_OFF] + b"\x00\x00" + data[_MIN:]
    ck = inet_checksum(stub)
    return data[:_CKSUM_OFF] + ck.to_bytes(2, "big") + data[_MIN:]


def post(data: bytes) -> bytes:
    return fix_checksum(data)


def fuzzer(proto: str, data: bytes, session: dict | None) -> bytes:
    """Protocol-shaped session fuzz: keep the 18-byte NHRP header intact,
    oracle-fuzz the body, re-fix the checksum. Non-NHRP-sized payloads
    fall back to whole-packet fuzz (still checksum-fixed on the way out
    if they grew past the header)."""
    from ..oracle.engine import fuzz as oracle_fuzz
    from ..utils.erlrand import gen_urandom_seed

    session = session if isinstance(session, dict) else {}
    # deterministic within a session: successive calls advance a counter
    seed = session.get("nhrp_seed") or gen_urandom_seed()
    count = session["nhrp_count"] = session.get("nhrp_count", 0) + 1
    session["nhrp_seed"] = seed
    seed3 = (seed[0], seed[1] ^ count, seed[2])

    if len(data) <= _MIN:
        # whole-packet fuzz; if the result grew past the header it now has
        # a checksum field, which must verify (fix_checksum passes
        # still-short packets through untouched)
        return fix_checksum(oracle_fuzz(data, seed=seed3))
    head, body = data[:_MIN], data[_MIN:]
    fuzzed = oracle_fuzz(body, seed=seed3)
    return fix_checksum(head + fuzzed)
