"""Deterministic fault injection: named fault sites, replayable firings.

The reference never needed a fault injector — OTP supervision was
exercised daily by real crashes. This port's resilience paths (retry,
failover, degraded mode; services/resilience.py) would otherwise only run
in production, so every guarded operation passes through a *named fault
site* and an injector decides, deterministically, whether that invocation
fails. The derivation mirrors ops/prng.py's counter philosophy: a firing
is a pure function of (chaos seed, site, invocation counter) — never of
wall clock or thread timing — so the same spec + seed replays the same
failure sequence, and a failure found under chaos is a unit test, not a
flake.

Spec grammar (``ERLAMSA_FAULTS`` env var or ``--chaos``)::

    spec     := clause ("," clause)*
    clause   := site ":" mode
    mode     := "x" N          fail invocations 1..N of the site, then heal
              | "s" K "x" N    skip the first K invocations, fail the next N
              | "p" F          each invocation fails with probability F,
                               drawn from hash(seed, site, counter)
              | "*"            every invocation fails (persistent fault)

    e.g.  ERLAMSA_FAULTS="dist.send:x2,store.save:x1"
          ERLAMSA_FAULTS="device.step:*"
          ERLAMSA_FAULTS="dist.recv:p0.25"

Known sites (grep `fault_point(` for the authoritative list):

    dist.send        parent->node request transmission (services/dist.py)
    dist.recv        node response parse (services/dist.py)
    batcher.step     TpuBatcher's jitted device call (services/batcher.py)
    store.save       corpus.json snapshot write (corpus/store.py)
    store.seed       seed-file publish in CorpusStore.add (corpus/store.py)
    device.step      corpus runner's bucket dispatch (corpus/runner.py)
    arena.spill      paged-arena admission (corpus/arena.py): an injected
                     fault forces the seed onto the host-overlay spill
                     path — outputs must not change (tests pin this)
    arena.adopt      device-resident offspring adoption (corpus/arena.py):
                     an injected fault drops the pending adoption batch,
                     so the offspring upload lazily from the host store
                     instead — outputs must not change (tests pin this)
    checkpoint.load  --state checkpoint read (services/checkpoint.py)
    checkpoint.save  --state checkpoint write (services/checkpoint.py)
    serving.admit    faas admission control (services/faas.py): an
                     injected fault sheds the request with a well-formed
                     HTTP 429 + Retry-After, never a connection abort
    serving.step     continuous engine's jitted slot step
                     (services/serving.py)
    shard.step       one fleet shard's dispatch or re-admission probe
                     (corpus/fleet.py): an injected fault revokes the
                     shard's lease and redistributes its partitions
                     across survivors — outputs must not change
    shard.migrate    lease migration apply (corpus/fleet.py): on the
                     revoke path an injected fault forces one idempotent
                     re-apply (outputs unchanged); on the re-admission
                     path it cancels the re-grant — the shard stays dead
                     until the next probe window
    fleet.reduce     the fleet coordinator's per-case merge
                     (corpus/fleet.py): an injected fault costs one
                     logged re-apply of the pure merge, never data loss
    dist.shard.send  coordinator->fleet-worker shard-protocol
                     transmission (services/dist.py): an injected fault
                     reads as a remote shard loss — revoke, in-case
                     redispatch on survivors, outputs unchanged
    dist.shard.recv  fleet-worker shard-protocol reply read
                     (services/dist.py): same revoke/redispatch
                     contract as dist.shard.send — on a framed stream
                     a lost reply after dispatch rewinds the pipeline
                     to the first un-merged case instead
    dist.shard.frame shard frame encode/decode on the framed stream
                     (services/dist.py): an injected fault poisons the
                     codec before any bytes hit the wire — same remote
                     shard-loss contract as dist.shard.send
    fleet.snapshot   arena warm-start snapshot build/ship at lease or
                     re-admission (corpus/fleet.py): an injected fault
                     skips the warm start — the shard degrades to lazy
                     per-case seed upload, outputs unchanged
    fleet.checkpoint the fleet coordinator's --state checkpoint write
                     (services/checkpoint.py save_fleet_state): an
                     injected fault degrades to a warning — the run
                     continues, resume falls back to the previous
                     checkpoint (or its .bak)
    monitor.spawn    monitor-plane subprocess creation
                     (services/monitors.py _spawn): an injected fault
                     reads as a failed target launch — logged, counted,
                     never a crashed monitor thread
    monitor.ingest   all monitor-plane socket I/O (services/monitors.py):
                     connect-back reads, probe/lxi sends, and the
                     CoverageHub's frame ingest; a persistent fault
                     trips the hub's breaker and the campaign degrades
                     to hash-novelty — outputs byte-identical to the
                     coverage-off baseline (tests pin this)
    coverage.fold    per-case edge-bitmap fold (corpus/distill.py
                     CoverageIndex.fold_case): an injected fault leaves
                     the whole case uncovered — the runner falls back
                     to hash-novelty for those slots, outputs unchanged
    gen.expand       device grammar-expansion call (gen/engine.py
                     GenEngine.expand): an injected fault degrades
                     generation to the counter-keyed host oracle,
                     byte-identical panels, erlamsa_gen_degraded=1
    obs.telemetry    the out-of-band shard_telemetry exchange riding a
                     window fence (services/dist.py request_telemetry):
                     an injected fault drops the whole exchange before
                     any frame hits the wire — counted telemetry_lost,
                     federation data goes stale for one window, and the
                     campaign output is byte-identical (telemetry is a
                     pure side channel; tests pin this)
    fleet.join       hot-join admission at the window fence
                     (corpus/fleet.py): an injected fault aborts the
                     admit — the candidate stays out (join_rejected,
                     it may re-announce), placement and outputs are
                     byte-identical to a run it never contacted
    fleet.drain      graceful-drain handoff at the window fence
                     (corpus/fleet.py): an injected fault abandons the
                     polite handoff and falls back to the crash path
                     (revoke + redistribute) — a drain dying half-way
                     degrades to exactly the PR 11 loss semantics,
                     outputs unchanged

Injected failures raise ``InjectedFault``, an OSError subclass, so they
flow through exactly the except-clauses that catch real socket/disk
errors — the resilience paths cannot special-case them. ``device.step``
faults are additionally recognized by ops/pipeline.is_device_error so the
runner's device-loss degradation treats them like an XLA abort.
"""

from __future__ import annotations

import hashlib
import os
import threading


class InjectedFault(OSError):
    """A chaos-injected failure. OSError subclass by design: real fault
    handlers (socket retries, best-effort saves) must catch it without
    knowing chaos exists."""

    def __init__(self, site: str, invocation: int):
        super().__init__(f"chaos: injected fault at {site} "
                         f"(invocation {invocation})")
        self.site = site
        self.invocation = invocation


class _Clause:
    __slots__ = ("site", "mode", "skip", "count", "prob")

    def __init__(self, site: str, mode: str, skip: int = 0,
                 count: int = 0, prob: float = 0.0):
        self.site = site
        self.mode = mode  # "count" | "prob" | "always"
        self.skip = skip
        self.count = count
        self.prob = prob

    def fires(self, seed: int, invocation: int) -> bool:
        if self.mode == "always":
            return True
        if self.mode == "count":
            return self.skip < invocation <= self.skip + self.count
        # prob: counter-keyed draw — sha256(seed:site:counter) as a
        # fraction in [0, 1); same invocation always draws the same bit
        h = hashlib.sha256(
            f"{seed}:{self.site}:{invocation}".encode()
        ).digest()
        frac = int.from_bytes(h[:8], "big") / float(1 << 64)
        return frac < self.prob


def parse_spec(spec: str) -> dict[str, _Clause]:
    """Parse the fault spec grammar; raises ValueError on a bad spec
    (a typo'd chaos spec must abort the run, not silently inject
    nothing)."""
    clauses: dict[str, _Clause] = {}
    for raw in spec.split(","):
        raw = raw.strip()
        if not raw:
            continue
        site, sep, mode = raw.partition(":")
        site = site.strip()
        mode = mode.strip()
        if not sep or not site or not mode:
            raise ValueError(f"chaos clause {raw!r}: want site:mode")
        if mode == "*":
            clauses[site] = _Clause(site, "always")
        elif mode.startswith("p"):
            p = float(mode[1:])
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"chaos clause {raw!r}: probability "
                                 f"must be in [0, 1]")
            clauses[site] = _Clause(site, "prob", prob=p)
        elif mode.startswith("s"):
            k, x, n = mode[1:].partition("x")
            if not x:
                raise ValueError(f"chaos clause {raw!r}: want sKxN")
            clauses[site] = _Clause(site, "count", skip=int(k),
                                    count=int(n))
        elif mode.startswith("x"):
            clauses[site] = _Clause(site, "count", count=int(mode[1:]))
        else:
            raise ValueError(f"chaos clause {raw!r}: unknown mode "
                             f"{mode!r} (want xN, sKxN, pF or *)")
    return clauses


class ChaosInjector:
    """One armed fault spec. Per-site invocation counters advance on
    every check (fired or not), so a firing is addressable as
    (seed, site, invocation) — the replay coordinate."""

    def __init__(self, spec: str, seed: int = 0):
        self.spec = spec
        self.seed = int(seed)
        self._clauses = parse_spec(spec)
        self._lock = threading.Lock()
        self._invocations: dict[str, int] = {}
        self.fired: dict[str, int] = {}

    def check(self, site: str) -> None:
        """Count one invocation of `site`; raise InjectedFault when the
        spec says this invocation fails."""
        clause = self._clauses.get(site)
        if clause is None:
            return
        with self._lock:
            n = self._invocations.get(site, 0) + 1
            self._invocations[site] = n
            fire = clause.fires(self.seed, n)
            if fire:
                self.fired[site] = self.fired.get(site, 0) + 1
        if fire:
            from . import logger, metrics

            metrics.GLOBAL.record_fault(site)
            logger.log("warning", "chaos: injected fault at %s "
                       "(invocation %d)", site, n)
            raise InjectedFault(site, n)

    def stats(self) -> dict:
        with self._lock:
            return {"spec": self.spec, "seed": self.seed,
                    "invocations": dict(self._invocations),
                    "fired": dict(self.fired)}


_ACTIVE: ChaosInjector | None = None


def configure(spec: str | None, seed: int = 0) -> ChaosInjector | None:
    """Arm (or, with a falsy spec, disarm) the process-wide injector.
    Returns the armed injector."""
    global _ACTIVE
    _ACTIVE = ChaosInjector(spec, seed) if spec else None
    return _ACTIVE


def configure_from_env(seed: int = 0) -> ChaosInjector | None:
    """Arm from ERLAMSA_FAULTS when set; leaves an already-armed injector
    alone so --chaos wins over the environment."""
    if _ACTIVE is None:
        spec = os.environ.get("ERLAMSA_FAULTS")
        if spec:
            return configure(spec, seed)
    return _ACTIVE


def active() -> ChaosInjector | None:
    return _ACTIVE


def fault_point(site: str) -> None:
    """THE hook guarded code calls. Free when no injector is armed."""
    inj = _ACTIVE
    if inj is not None:
        inj.check(site)
