"""Host shell: CLI, output writers, logging, monitors, proxy, FaaS,
distributed nodes — the reference's L5/L4/L2/L1 layers (SURVEY.md §1)
re-implemented around the TPU batch engine and the oracle."""
