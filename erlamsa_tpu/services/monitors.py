"""Crash/event monitors and the coverage ingest hub.

Reference: src/erlamsa_monitor.erl and mon_* modules — a registry of
monitors started from ``--monitor +name:params`` / ``!name:off`` CLI specs,
each reporting findings through the logger and optionally running an
``after=exec`` recovery action:

  cm      connect-back listener catching SSRF/XXE/reverse-shell payloads
          (src/erlamsa_mon_connect.erl); its host:port is advertised to the
          payload builders via the shared config
  probe   periodic TCP/UDP liveness probe; refused/timeout -> finding
          (src/erlamsa_mon_network.erl)
  exec    spawn-and-watch a target process; nonzero/signal exit -> finding
          (the cdb/r2 equivalent for environments without a debugger)
  r2      radare2-driven crash triage (src/erlamsa_mon_r2.erl); gated on
          an available `r2` binary
  lc      adb logcat crash extraction (src/erlamsa_mon_logcat.erl); gated
          on an available `adb` binary
  lxi     SCPI measurement-range monitor over TCP
          (src/erlamsa_mon_lxi.erl)
  cdb     Windows CDB console-debugger driver: on a debugger break-in log
          backtrace/registers, write a minidump, restart
          (src/erlamsa_mon_cdb.erl); gated on an available `cdb` binary

Unlike the original fire-and-forget daemon threads, every monitor loop
now runs under services/supervisor.py (per-monitor restart backoff,
give-up breaker on crash storms), monitor subprocesses spawn through
one chaos-faultable funnel with a per-execution hang watchdog
(deadline + process-group kill), and crash reports are deduped by
(signal, top-frames stack hash) before they reach the feedback bus —
the energy scheduler sees each distinct crash once, not a log line per
re-trigger.

``CoverageHub`` is the monitor plane's device-feedback half: a framed
connect-back listener (the r15 frame codec from services/dist.py)
accepting per-sample edge bitmaps that the corpus runner folds into
per-seed coverage tensors at case boundaries. This module stays
jax-free on purpose (like corpus/feedback.py): monitor threads must
never trigger an accelerator backend import.
"""

from __future__ import annotations

import hashlib
import os
import re
import shlex
import shutil
import signal
import socket
import subprocess
import threading
import time
import zlib

from ..constants import COVERAGE_MAP_BYTES, DEFAULT_CM_PORT
from ..corpus import feedback
from ..obs import trace
from . import chaos, logger, metrics
from .dist import _read_frame
from .resilience import OPEN, CircuitBreaker
from .supervisor import SupervisedThread

# shared monitor config, the reference's global_config ets analogue
CONFIG: dict = {"cm_port": DEFAULT_CM_PORT, "cm_host": None}

#: per-execution watchdog default: a watched target (or after-action)
#: that produces no exit within this many seconds is group-killed
EXEC_DEADLINE = 30.0


# --- subprocess funnel: one spawn site, one hang watchdog ----------------

def _spawn(argv: list[str], **popen_kw) -> subprocess.Popen:
    """Every monitor subprocess comes to life here: one chaos site
    (monitor.spawn) so fault specs can starve the whole recovery/triage
    plane, and its own session/process group so the hang watchdog can
    kill the target together with anything it forked."""
    chaos.fault_point("monitor.spawn")
    return subprocess.Popen(argv, start_new_session=True, **popen_kw)


def _kill_group(proc: subprocess.Popen):
    """Process-group kill with reaping; falls back to killing the lone
    process when the group is already gone."""
    try:
        os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
    except OSError:
        try:
            proc.kill()
        except OSError:
            pass
    try:
        proc.wait(timeout=5)
    except (subprocess.TimeoutExpired, OSError):
        pass


def _watch(proc: subprocess.Popen,
           deadline: float) -> tuple[bytes | None, int | None]:
    """Per-execution hang watchdog: wait for exit within `deadline`
    seconds; a target still running past it is process-group-killed.
    Returns (output, returncode); returncode None means the watchdog
    fired (a hang, not an exit)."""
    try:
        out, _ = proc.communicate(timeout=deadline if deadline > 0 else None)
        return out, proc.returncode
    except subprocess.TimeoutExpired:
        _kill_group(proc)
        try:
            out, _ = proc.communicate(timeout=5)
        except (subprocess.TimeoutExpired, OSError):
            out = b""
        return out, None


def _run_after(params: dict):
    """after=exec recovery hook (erlamsa_monitor:do_after,
    src/erlamsa_monitor.erl:98-104). Spawns through the monitor.spawn
    funnel — failures are LOGGED, never swallowed — and a reaper thread
    waits on the action under the hang watchdog so a stuck recovery
    command is group-killed instead of leaking a zombie."""
    cmd = params.get("after")
    if not cmd:
        return
    budget = float(params.get("after_timeout", EXEC_DEADLINE))
    try:
        proc = _spawn(shlex.split(cmd))
    except (OSError, ValueError) as e:
        metrics.GLOBAL.record_monitor("spawn_failed")
        logger.log("error", "monitor after-action %r failed to spawn: %s",
                   cmd, e)
        return
    metrics.GLOBAL.record_monitor("after_spawned")

    def _reap():
        _out, rc = _watch(proc, budget)
        if rc is None:
            metrics.GLOBAL.record_monitor("hang_killed")
            logger.log("warning", "monitor after-action %r hung past "
                       "%.1fs, killed", cmd, budget)

    threading.Thread(target=_reap, name="mon:after-reap",
                     daemon=True).start()


# --- network helpers: monitor-plane socket I/O behind one fault site -----

def _net_read(sock: socket.socket, n: int) -> bytes:
    """Monitor-plane socket read (chaos site monitor.ingest)."""
    chaos.fault_point("monitor.ingest")
    return sock.recv(n)


def _net_write(sock: socket.socket, payload: bytes, addr=None):
    """Monitor-plane socket write (probe hellos, SCPI queries) behind
    the same monitor.ingest site — one spec kills the whole plane's
    I/O."""
    chaos.fault_point("monitor.ingest")
    if addr is not None:
        sock.sendto(payload, addr)
    else:
        sock.sendall(payload)


# --- crash dedup/triage --------------------------------------------------

_FRAME_PAT = re.compile(rb"(?:#\d+\s|\+0x[0-9a-fA-F]+|\bat\s+\S|\bin\s+\S+\s*\()")


class CrashTriage:
    """Dedup crashes by (signal, top-frames stack hash).

    The triage key hashes the first `frames` backtrace-looking lines of
    the target's output (falling back to the first non-empty lines when
    no frame pattern matches) together with the signal number — the
    classic "same signal, same top of stack => same bug" bucketing. The
    first observation of a bucket is a finding for the feedback bus;
    re-triggers only count.
    """

    def __init__(self, frames: int = 3):
        self.frames = int(frames)
        self._seen: set[str] = set()
        self.dups = 0

    def key(self, sig: int, output: bytes | None) -> str:
        lines = [ln.strip() for ln in (output or b"").splitlines()
                 if ln.strip()]
        top = [ln for ln in lines if _FRAME_PAT.search(ln)][:self.frames]
        if not top:
            top = lines[:self.frames]
        h = hashlib.sha1(b"|".join([b"sig%d" % sig, *top])).hexdigest()[:12]
        return f"sig{sig}:{h}"

    def observe(self, sig: int, output: bytes | None) -> tuple[str, bool]:
        """(triage key, first time seen?)"""
        k = self.key(sig, output)
        if k in self._seen:
            self.dups += 1
            return k, False
        self._seen.add(k)
        return k, True


# --- monitor base: supervised loops --------------------------------------

class Monitor:
    """One monitor = one supervised loop (services/supervisor.py): an
    unhandled crash in run() restarts it with backoff, and a crash
    storm trips the supervisor's give-up breaker instead of spinning.
    The public surface (start/stop/join/is_alive) matches the old
    threading.Thread subclass so CLI wiring and tests are unchanged."""

    name_code = "base"

    def __init__(self, params: dict):
        self.params = params
        self._stop_evt = threading.Event()
        self._thread = SupervisedThread(f"monitor:{self.name_code}",
                                        self._supervised_run)

    def _supervised_run(self):
        if not self._stop_evt.is_set():
            self.run()

    def run(self):
        raise NotImplementedError

    def start(self) -> "Monitor":
        self._thread.start()
        return self

    def stop(self):
        self._stop_evt.set()

    def join(self, timeout=None):
        self._thread.join(timeout)

    def is_alive(self) -> bool:
        return self._thread.is_alive()


class ConnectMonitor(Monitor):
    """cm: TCP listener catching connect-backs; '{event}'-prefixed payloads
    log as findings (src/erlamsa_mon_connect.erl:47-54)."""

    name_code = "cm"

    def run(self):
        port = int(self.params.get("port", DEFAULT_CM_PORT))
        CONFIG["cm_port"] = port
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            srv.bind(("0.0.0.0", port))
        except OSError as e:
            logger.log("error", "cm monitor cannot bind :%d: %s", port, e)
            return
        srv.listen(16)
        srv.settimeout(1.0)
        logger.log("info", "connect monitor listening on :%d", port)
        while not self._stop_evt.is_set():
            try:
                conn, addr = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                conn.settimeout(2.0)
                data = _net_read(conn, 4096)
            except OSError:
                data = b""
            finally:
                conn.close()
            if data.startswith(b"{event}"):
                logger.log("finding", "cm event from %s: %r", addr[0], data[7:200])
            else:
                logger.log("finding", "connect-back from %s:%d (%d bytes)",
                           addr[0], addr[1], len(data))
            feedback.publish("connback", source="monitor:cm",
                             detail=f"from {addr[0]}")
            _run_after(self.params)


class NetworkProbeMonitor(Monitor):
    """probe: periodic hello; timeout/refusal is a finding
    (src/erlamsa_mon_network.erl:48-57)."""

    name_code = "probe"

    def run(self):
        host = self.params.get("host", "127.0.0.1")
        port = int(self.params.get("port", 80))
        proto = self.params.get("proto", "tcp")
        interval = float(self.params.get("interval", 5.0))
        hello = self.params.get("hello", "hello").encode()
        while not self._stop_evt.is_set():
            ok = False
            try:
                if proto == "udp":
                    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                    s.settimeout(3.0)
                    _net_write(s, hello, (host, port))
                    ok = True
                else:
                    with socket.create_connection((host, port), timeout=3.0) as s:
                        _net_write(s, hello)
                        ok = True
            except OSError as e:
                logger.log("finding", "probe: %s:%d unreachable (%s)", host, port, e)
                feedback.publish("drop", source="monitor:probe",
                                 detail=f"{host}:{port}")
                _run_after(self.params)
            if ok:
                logger.log("debug", "probe: %s:%d alive", host, port)
            self._stop_evt.wait(interval)


class ExecMonitor(Monitor):
    """exec: keep a target app running; abnormal exits are findings and the
    app is restarted — the cross-platform stand-in for the cdb/r2 debugger
    monitors (src/erlamsa_mon_cdb.erl behavior).

    Every execution runs under the hang watchdog (``timeout=`` param,
    default EXEC_DEADLINE): a wedged target is process-group-killed and
    reported as a hang finding. Spawn failures feed a CircuitBreaker so
    a broken cmdline cools down instead of hot-spinning, and crashes
    are triage-deduped before they reach the bus."""

    name_code = "exec"

    def __init__(self, params: dict):
        super().__init__(params)
        self.triage = CrashTriage()
        self.breaker = CircuitBreaker(failure_threshold=3,
                                      reset_timeout=10.0,
                                      name="monitor:exec")

    def run(self):
        cmd = self.params.get("app")
        if not cmd:
            logger.log("error", "exec monitor needs app=<cmdline>")
            return
        deadline = float(self.params.get("timeout", EXEC_DEADLINE))
        delay = float(self.params.get("delay", 5.0))
        while not self._stop_evt.is_set():
            if not self.breaker.allow():
                self._stop_evt.wait(delay)
                continue
            try:
                proc = _spawn(shlex.split(cmd), stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT)
            except (OSError, ValueError) as e:
                self.breaker.record_failure()
                metrics.GLOBAL.record_monitor("spawn_failed")
                logger.log("error", "exec monitor: cannot spawn %r: %s",
                           cmd, e)
                self._stop_evt.wait(delay)
                continue
            self.breaker.record_success()
            out, rc = _watch(proc, deadline)
            if rc is None:
                metrics.GLOBAL.record_monitor("hang_killed")
                logger.log("finding", "exec target hung past %.1fs, "
                           "killed; tail: %r", deadline,
                           out[-500:] if out else b"")
                feedback.publish("finding", source="monitor:exec",
                                 detail="hang")
                _run_after(self.params)
            elif rc and not self._stop_evt.is_set():
                if rc < 0:
                    key, first = self.triage.observe(-rc, out)
                    if first:
                        metrics.GLOBAL.record_monitor("crash")
                        logger.log("finding", "exec target crashed sig=%d "
                                   "triage=%s; tail: %r", -rc, key,
                                   out[-500:] if out else b"")
                        feedback.publish("crash", source="monitor:exec",
                                         detail=key)
                    else:
                        metrics.GLOBAL.record_monitor("crash_dup")
                        logger.log("debug", "exec target crash (dup "
                                   "triage=%s)", key)
                else:
                    logger.log("warning", "exec target exited rc=%d; "
                               "tail: %r", rc, out[-500:] if out else b"")
                    feedback.publish("finding", source="monitor:exec",
                                     detail=f"rc={rc}")
                _run_after(self.params)
            self._stop_evt.wait(delay)


class R2Monitor(Monitor):
    """r2: drive radare2 over r2pipe for crash triage; registers/backtrace
    dumps on crash (src/erlamsa_mon_r2.erl:43-58). Requires `r2`."""

    name_code = "r2"

    def __init__(self, params: dict):
        super().__init__(params)
        self.triage = CrashTriage()

    def run(self):
        if shutil.which("r2") is None:
            logger.log("error", "r2 monitor: radare2 not found in PATH")
            return
        app = self.params.get("app")
        while not self._stop_evt.is_set():
            try:
                proc = _spawn(["r2", "-q0", "-d", *shlex.split(app)],
                              stdin=subprocess.PIPE, stdout=subprocess.PIPE)
            except (OSError, ValueError) as e:
                metrics.GLOBAL.record_monitor("spawn_failed")
                logger.log("error", "r2 monitor: cannot spawn: %s", e)
                self._stop_evt.wait(float(self.params.get("delay", 2.0)))
                continue
            try:
                proc.stdin.write(b"dc\n")
                proc.stdin.flush()
                out = proc.stdout.read()
                if b"SIGSEGV" in out or b"signal" in out:
                    proc.stdin.write(b"drj\nij\ndbt\n")
                    proc.stdin.flush()
                    dump = proc.stdout.read()
                    key, first = self.triage.observe(signal.SIGSEGV, dump)
                    if first:
                        metrics.GLOBAL.record_monitor("crash")
                        logger.log("finding", "r2 crash dump triage=%s: %r",
                                   key, dump[:1000])
                        feedback.publish("crash", source="monitor:r2",
                                         detail=key)
                    else:
                        metrics.GLOBAL.record_monitor("crash_dup")
                    _run_after(self.params)
            except (OSError, ValueError):
                pass
            finally:
                _kill_group(proc)
            self._stop_evt.wait(float(self.params.get("delay", 2.0)))


class LogcatMonitor(Monitor):
    """lc: adb logcat crash extraction for Android targets
    (src/erlamsa_mon_logcat.erl:31-51). Requires `adb`."""

    name_code = "lc"

    def run(self):
        if shutil.which("adb") is None:
            logger.log("error", "logcat monitor: adb not found in PATH")
            return
        app = self.params.get("app", "")
        if app:
            subprocess.run(["adb", "shell", "am", "start", "-n", app], check=False)
        try:
            proc = _spawn(["adb", "logcat", "*:E"], stdout=subprocess.PIPE)
        except OSError as e:
            metrics.GLOBAL.record_monitor("spawn_failed")
            logger.log("error", "logcat monitor: cannot spawn adb: %s", e)
            return
        crash_lines: list[bytes] = []
        for line in proc.stdout:
            if self._stop_evt.is_set():
                break
            if b"FATAL EXCEPTION" in line or b"SIGSEGV" in line:
                crash_lines = [line]
            elif crash_lines:
                crash_lines.append(line)
                if len(crash_lines) > 20:
                    logger.log("finding", "logcat crash: %r",
                               b"".join(crash_lines)[:2000])
                    feedback.publish("crash", source="monitor:lc")
                    _run_after(self.params)
                    crash_lines = []
        _kill_group(proc)


class LxiMonitor(Monitor):
    """lxi: SCPI MEAS:CURR? over TCP; out-of-range measurement -> finding
    (hardware fuzzing, src/erlamsa_mon_lxi.erl:75-93)."""

    name_code = "lxi"

    def run(self):
        host = self.params.get("host", "127.0.0.1")
        port = int(self.params.get("port", 5025))
        lo = float(self.params.get("lvalue", 0.0))
        hi = float(self.params.get("uvalue", 1.0))
        interval = float(self.params.get("interval", 2.0))
        while not self._stop_evt.is_set():
            try:
                with socket.create_connection((host, port), timeout=3.0) as s:
                    _net_write(s, b"MEAS:CURR?\n")
                    v = float(_net_read(s, 256).strip())
                    if not (lo <= v <= hi):
                        logger.log("finding",
                                   "lxi measurement %g outside [%g, %g]", v, lo, hi)
                        feedback.publish("finding", source="monitor:lxi",
                                         detail=f"{v}")
                        _run_after(self.params)
            except (OSError, ValueError) as e:
                logger.log("warning", "lxi probe failed: %s", e)
            self._stop_evt.wait(interval)


class CdbMonitor(Monitor):
    """cdb: drive the Windows CDB console debugger over stdio
    (src/erlamsa_mon_cdb.erl:72-94). Attach with ``pid=N`` (-p),
    ``attach=name`` (-pn) or launch with ``app=<cmdline>``; `g` resumes the
    target, and when the debugger breaks back in (crash/exception) the
    monitor logs the event, a `k` backtrace and `r` registers as findings,
    saves a timestamped minidump via ``.dump /m``, runs the after actions
    and re-attaches. ``cdb=<binary>`` overrides the debugger path (used by
    tests to substitute an emulator; real use needs cdb.exe in PATH).

    The stdio protocol matches the reference's port loop: every command's
    reply is read until the "> " debugger prompt (read_cdb_data,
    src/erlamsa_mon_cdb.erl:131-141).
    """

    name_code = "cdb"
    ATTEMPTS = 5  # ?START_MONITOR_ATTEMPTS

    def __init__(self, params: dict):
        super().__init__(params)
        self._proc: subprocess.Popen | None = None
        self.triage = CrashTriage()

    def stop(self):
        super().stop()
        proc = self._proc
        if proc is not None and proc.poll() is None:
            proc.kill()

    def _target_args(self):
        if "pid" in self.params:
            return ["-p", str(self.params["pid"])]
        if "attach" in self.params:
            return ["-pn", str(self.params["attach"])]
        if "app" in self.params:
            return shlex.split(self.params["app"])
        return None

    def _read_to_prompt(self) -> bytes | None:
        """Accumulate debugger output until the trailing '> ' prompt; None
        when the debugger exits first (closed/process_exit in the ref)."""
        buf = b""
        while True:
            chunk = self._proc.stdout.read(1)
            if not chunk:
                return None
            buf += chunk
            if buf.endswith(b"> "):
                return buf

    def _call(self, cmd: bytes) -> bytes | None:
        try:
            self._proc.stdin.write(cmd)
            self._proc.stdin.flush()
        except OSError:
            return None
        return self._read_to_prompt()

    def run(self):
        cdb = self.params.get("cdb", "cdb")
        if shutil.which(cdb) is None:
            logger.log("error", "cdb monitor: %s not found in PATH", cdb)
            return
        args = self._target_args()
        if args is None:
            logger.log("error", "cdb monitor needs pid=/attach=/app=")
            return
        attempts = self.ATTEMPTS
        while not self._stop_evt.is_set():
            if attempts <= 0:
                logger.log("error",
                           "cdb monitor: too many failures (%d), giving up",
                           self.ATTEMPTS)
                return
            try:
                self._proc = _spawn(
                    [cdb, *args], stdin=subprocess.PIPE,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                )
            except OSError as e:
                metrics.GLOBAL.record_monitor("spawn_failed")
                logger.log("warning", "cdb monitor spawn failed: %s", e)
                attempts -= 1
                self._stop_evt.wait(1.0)
                continue
            if self._stop_evt.is_set():  # stop() may have raced the spawn
                self._kill()
                return
            banner = self._read_to_prompt()
            if banner is None:
                logger.log("warning", "cdb monitor: debugger exited at start")
                attempts -= 1
                self._stop_evt.wait(1.0)
                continue
            logger.log("info", "cdb monitor attached: %r", banner[-200:])
            # `g` blocks until the debugger breaks back in — that IS the event
            crash = self._call(b"g\r\n")
            if crash is None or self._stop_evt.is_set():
                if not self._stop_evt.is_set():
                    logger.log("warning",
                               "cdb monitor: debugger exited while running")
                    attempts -= 1
                    self._stop_evt.wait(1.0)
                self._kill()
                continue
            # a full cycle reached the break-in: reset the give-up budget
            # (cdb_start(..., ?START_MONITOR_ATTEMPTS) after each cycle)
            attempts = self.ATTEMPTS
            logger.log("finding", "cdb monitor detected event (crash?): %r",
                       crash[:1000])
            bt = self._call(b"k\r\n")
            logger.log("finding", "cdb monitor backtrace: %r",
                       (bt or b"")[:2000])
            key, first = self.triage.observe(0, bt or crash)
            if first:
                metrics.GLOBAL.record_monitor("crash")
                feedback.publish("crash", source="monitor:cdb", detail=key)
            else:
                metrics.GLOBAL.record_monitor("crash_dup")
            regs = self._call(b"r\r\n")
            logger.log("finding", "cdb monitor registers: %r",
                       (regs or b"")[:2000])
            name = re.sub(r"[^A-Za-z0-9._-]", "_",
                          self.params.get("app", "cdb_target"))
            dump = name + time.strftime("_%Y_%m_%d_%H_%M_%S.minidump")
            res = self._call(f".dump /m {dump} \r\n".encode())
            logger.log("finding", "cdb monitor minidump saved to %s: %r",
                       dump, (res or b"")[:500])
            try:
                self._proc.stdin.write(b"q\r\n")
                self._proc.stdin.flush()
            except OSError:
                pass
            self._kill()
            _run_after(self.params)

    def _kill(self):
        proc = self._proc
        if proc is None:
            return
        try:
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=5)
        except OSError:
            pass


# --- coverage ingest hub --------------------------------------------------

class CoverageHub:
    """Framed connect-back coverage ingest (the r15 frame codec of
    services/dist.py on a loopback-friendly listener).

    Instrumented targets (or the tier-1 stub) connect back and stream
    frames whose header is ``{"op": "cov", "case": C, "slot": S,
    "epoch": E, "crc": crc32(blob)}`` with the raw edge bitmap as the
    blob. Frames are crc32-checked against the blob and epoch-stamped;
    stale-epoch and torn (bad magic/width/crc) frames are rejected AND
    counted. Accepted maps buffer per case until the runner folds them
    at the case boundary (corpus/runner.py), where the sample ledger
    maps them back to (seed, case, slot).

    Robustness contract: the accept loop runs under the supervisor;
    every ingest failure — including an injected ``monitor.ingest``
    chaos fault — feeds a CircuitBreaker, and an OPEN circuit or a dead
    listener thread marks the hub dead. Death is sticky and one-way:
    the campaign degrades to hash-novelty and STAYS degraded, because a
    coverage signal that flickers would make adoption decisions depend
    on reconnect timing.
    """

    _GUARDED_BY = {"_lock": ("_pending", "counts")}

    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 map_bytes: int = COVERAGE_MAP_BYTES, epoch: int = 0):
        self.map_bytes = int(map_bytes)
        self.epoch = int(epoch)
        self._lock = threading.Lock()
        self._stop_evt = threading.Event()
        self._dead = False
        self._pending: dict[int, dict[int, bytes]] = {}
        self.counts = {"frames": 0, "stale": 0, "torn": 0, "faulted": 0,
                       "late": 0}
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, int(port)))
        srv.listen(8)
        srv.settimeout(0.5)
        self._srv = srv
        self.host, self.port = srv.getsockname()[:2]
        self.breaker = CircuitBreaker(failure_threshold=4,
                                      reset_timeout=3600.0,
                                      name="monitor:ingest")
        self._thread = SupervisedThread("monitor:coverage", self._serve)

    def start(self) -> "CoverageHub":
        self._thread.start()
        logger.log("info", "coverage hub listening on %s:%d (map=%dB "
                   "epoch=%d)", self.host, self.port, self.map_bytes,
                   self.epoch)
        return self

    def _serve(self):
        while not self._stop_evt.is_set():
            try:
                conn, addr = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break  # listener socket gone: alive() flips false
            threading.Thread(target=self._client, args=(conn, addr),
                             name="mon:cov-conn", daemon=True).start()
        try:
            self._srv.close()
        except OSError:
            pass

    def _client(self, conn: socket.socket, addr):
        f = conn.makefile("rb")
        try:
            while not self._stop_evt.is_set():
                try:
                    fr = _read_frame(f)
                except ValueError as e:
                    with self._lock:
                        self.counts["torn"] += 1
                    metrics.GLOBAL.record_coverage_frame("torn")
                    logger.log("warning", "coverage hub: torn stream from "
                               "%s: %s", addr[0], e)
                    break
                if fr is None:
                    break
                self._ingest(fr[0], fr[1], addr)
        except OSError:
            pass  # peer vanished mid-frame; buffered maps stay valid
        finally:
            try:
                f.close()
                conn.close()
            except OSError:
                pass

    def _ingest(self, header: dict, blob: bytes, addr):
        try:
            chaos.fault_point("monitor.ingest")
        except OSError as e:
            with self._lock:
                self.counts["faulted"] += 1
            self.breaker.record_failure()
            if self.breaker.state == OPEN:
                self._dead = True
            logger.log("warning", "coverage hub: ingest fault from %s: %s",
                       addr[0], e)
            return
        try:
            op = header.get("op")
            case = int(header["case"])
            slot = int(header["slot"])
            epoch = int(header.get("epoch", -1))
            crc = int(header.get("crc", -1))
        except (KeyError, TypeError, ValueError):
            op = None
            case = slot = epoch = crc = -1
        if op != "cov":
            with self._lock:
                self.counts["torn"] += 1
            metrics.GLOBAL.record_coverage_frame("torn")
            return
        if epoch != self.epoch:
            with self._lock:
                self.counts["stale"] += 1
            metrics.GLOBAL.record_coverage_frame("stale")
            return
        if len(blob) != self.map_bytes or zlib.crc32(blob) != crc & 0xFFFFFFFF:
            with self._lock:
                self.counts["torn"] += 1
            metrics.GLOBAL.record_coverage_frame("torn")
            return
        # accepted frames adopt any sender-carried trace context so a
        # remote target's coverage delivery lands parented under the
        # coordinator's case span in the merged fleet trace
        with trace.span_remote("coverage.ingest",
                               trace_id=str(header.get("trace", "")),
                               parent=int(header.get("span", 0) or 0),
                               case=case, slot=slot):
            with self._lock:
                self.counts["frames"] += 1
                self._pending.setdefault(case, {})[slot] = blob
        metrics.GLOBAL.record_coverage_frame("ok")
        self.breaker.record_success()

    def take(self, case: int) -> dict[int, bytes]:
        """Pop this case's buffered maps {slot: bitmap}. Frames for
        cases the runner already folded are dropped and counted late —
        re-folding them would make energy depend on arrival timing."""
        with self._lock:
            out = self._pending.pop(case, {})
            n_late = sum(len(self._pending.pop(c))
                         for c in [c for c in self._pending if c < case])
            if n_late:
                self.counts["late"] += n_late
        return out

    def pending_frames(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._pending.values())

    def stats(self) -> dict:
        with self._lock:
            return dict(self.counts)

    def alive(self) -> bool:
        return not self._dead and self._thread.is_alive()

    def stop(self):
        self._stop_evt.set()
        try:
            self._srv.close()
        except OSError:
            pass

    def join(self, timeout=None):
        self._thread.join(timeout)


MONITORS = {
    m.name_code: m
    for m in (ConnectMonitor, NetworkProbeMonitor, ExecMonitor, R2Monitor,
              LogcatMonitor, LxiMonitor, CdbMonitor)
}


def parse_monitor_spec(spec: str):
    """'+name:k=v,k=v' enables, '!name:off' disables
    (erlamsa_cmdparse monitor parsing, src/erlamsa_cmdparse.erl:436-451)."""
    if spec.startswith("!"):
        return None
    spec = spec.lstrip("+")
    name, _, rest = spec.partition(":")
    params = {}
    for kv in rest.split(","):
        if "=" in kv:
            k, v = kv.split("=", 1)
            params[k] = v
    return name, params


def start_monitors(specs: list[str], default_cm: bool = False) -> list[Monitor]:
    """Start requested monitors; with default_cm the connect monitor starts
    unless disabled (erlamsa_monitor:default/0, src/erlamsa_monitor.erl:33)."""
    started = []
    disabled = {s.lstrip("!").partition(":")[0] for s in specs if s.startswith("!")}
    wanted = [parse_monitor_spec(s) for s in specs if not s.startswith("!")]
    wanted = [w for w in wanted if w]
    if default_cm and "cm" not in disabled and not any(n == "cm" for n, _ in wanted):
        wanted.append(("cm", {}))
    for name, params in wanted:
        cls = MONITORS.get(name)
        if cls is None:
            logger.log("error", "unknown monitor %s", name)
            continue
        mon = cls(params)
        mon.start()
        started.append(mon)
    return started
