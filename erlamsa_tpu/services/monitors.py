"""Crash/event monitors.

Reference: src/erlamsa_monitor.erl and mon_* modules — a registry of
monitors started from ``--monitor +name:params`` / ``!name:off`` CLI specs,
each reporting findings through the logger and optionally running an
``after=exec`` recovery action:

  cm      connect-back listener catching SSRF/XXE/reverse-shell payloads
          (src/erlamsa_mon_connect.erl); its host:port is advertised to the
          payload builders via the shared config
  probe   periodic TCP/UDP liveness probe; refused/timeout -> finding
          (src/erlamsa_mon_network.erl)
  exec    spawn-and-watch a target process; nonzero/signal exit -> finding
          (the cdb/r2 equivalent for environments without a debugger)
  r2      radare2-driven crash triage (src/erlamsa_mon_r2.erl); gated on
          an available `r2` binary
  lc      adb logcat crash extraction (src/erlamsa_mon_logcat.erl); gated
          on an available `adb` binary
  lxi     SCPI measurement-range monitor over TCP
          (src/erlamsa_mon_lxi.erl)
  cdb     Windows CDB console-debugger driver: on a debugger break-in log
          backtrace/registers, write a minidump, restart
          (src/erlamsa_mon_cdb.erl); gated on an available `cdb` binary
"""

from __future__ import annotations

import re
import shlex
import shutil
import socket
import subprocess
import threading
import time

from ..constants import DEFAULT_CM_PORT
from ..corpus import feedback
from . import logger

# shared monitor config, the reference's global_config ets analogue
CONFIG: dict = {"cm_port": DEFAULT_CM_PORT, "cm_host": None}


def _run_after(params: dict):
    """after=exec recovery hook (erlamsa_monitor:do_after,
    src/erlamsa_monitor.erl:98-104)."""
    cmd = params.get("after")
    if cmd:
        subprocess.Popen(shlex.split(cmd))


class Monitor(threading.Thread):
    name_code = "base"

    def __init__(self, params: dict):
        super().__init__(daemon=True)
        self.params = params
        self._stop_evt = threading.Event()

    def stop(self):
        self._stop_evt.set()


class ConnectMonitor(Monitor):
    """cm: TCP listener catching connect-backs; '{event}'-prefixed payloads
    log as findings (src/erlamsa_mon_connect.erl:47-54)."""

    name_code = "cm"

    def run(self):
        port = int(self.params.get("port", DEFAULT_CM_PORT))
        CONFIG["cm_port"] = port
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            srv.bind(("0.0.0.0", port))
        except OSError as e:
            logger.log("error", "cm monitor cannot bind :%d: %s", port, e)
            return
        srv.listen(16)
        srv.settimeout(1.0)
        logger.log("info", "connect monitor listening on :%d", port)
        while not self._stop_evt.is_set():
            try:
                conn, addr = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                conn.settimeout(2.0)
                data = conn.recv(4096)
            except OSError:
                data = b""
            finally:
                conn.close()
            if data.startswith(b"{event}"):
                logger.log("finding", "cm event from %s: %r", addr[0], data[7:200])
            else:
                logger.log("finding", "connect-back from %s:%d (%d bytes)",
                           addr[0], addr[1], len(data))
            feedback.publish("connback", source="monitor:cm",
                             detail=f"from {addr[0]}")
            _run_after(self.params)


class NetworkProbeMonitor(Monitor):
    """probe: periodic hello; timeout/refusal is a finding
    (src/erlamsa_mon_network.erl:48-57)."""

    name_code = "probe"

    def run(self):
        host = self.params.get("host", "127.0.0.1")
        port = int(self.params.get("port", 80))
        proto = self.params.get("proto", "tcp")
        interval = float(self.params.get("interval", 5.0))
        hello = self.params.get("hello", "hello").encode()
        while not self._stop_evt.is_set():
            ok = False
            try:
                if proto == "udp":
                    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                    s.settimeout(3.0)
                    s.sendto(hello, (host, port))
                    ok = True
                else:
                    with socket.create_connection((host, port), timeout=3.0) as s:
                        s.sendall(hello)
                        ok = True
            except OSError as e:
                logger.log("finding", "probe: %s:%d unreachable (%s)", host, port, e)
                feedback.publish("drop", source="monitor:probe",
                                 detail=f"{host}:{port}")
                _run_after(self.params)
            if ok:
                logger.log("debug", "probe: %s:%d alive", host, port)
            self._stop_evt.wait(interval)


class ExecMonitor(Monitor):
    """exec: keep a target app running; abnormal exits are findings and the
    app is restarted — the cross-platform stand-in for the cdb/r2 debugger
    monitors (src/erlamsa_mon_cdb.erl behavior)."""

    name_code = "exec"

    def run(self):
        cmd = self.params.get("app")
        if not cmd:
            logger.log("error", "exec monitor needs app=<cmdline>")
            return
        while not self._stop_evt.is_set():
            proc = subprocess.Popen(
                shlex.split(cmd), stdout=subprocess.PIPE, stderr=subprocess.STDOUT
            )
            out, _ = proc.communicate()
            rc = proc.returncode
            if rc and not self._stop_evt.is_set():
                level = "finding" if rc < 0 else "warning"
                logger.log(level, "exec target exited rc=%d; tail: %r",
                           rc, out[-500:] if out else b"")
                # signal exits are crashes; plain nonzero rc a finding
                feedback.publish("crash" if rc < 0 else "finding",
                                 source="monitor:exec", detail=f"rc={rc}")
                _run_after(self.params)
            time.sleep(float(self.params.get("delay", 5.0)))


class R2Monitor(Monitor):
    """r2: drive radare2 over r2pipe for crash triage; registers/backtrace
    dumps on crash (src/erlamsa_mon_r2.erl:43-58). Requires `r2`."""

    name_code = "r2"

    def run(self):
        if shutil.which("r2") is None:
            logger.log("error", "r2 monitor: radare2 not found in PATH")
            return
        app = self.params.get("app")
        while not self._stop_evt.is_set():
            proc = subprocess.Popen(
                ["r2", "-q0", "-d", *shlex.split(app)],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            )
            try:
                proc.stdin.write(b"dc\n")
                proc.stdin.flush()
                out = proc.stdout.read()
                if b"SIGSEGV" in out or b"signal" in out:
                    proc.stdin.write(b"drj\nij\ndbt\n")
                    proc.stdin.flush()
                    dump = proc.stdout.read()
                    logger.log("finding", "r2 crash dump: %r", dump[:1000])
                    feedback.publish("crash", source="monitor:r2")
                    _run_after(self.params)
            except (OSError, ValueError):
                pass
            finally:
                proc.kill()
            time.sleep(float(self.params.get("delay", 2.0)))


class LogcatMonitor(Monitor):
    """lc: adb logcat crash extraction for Android targets
    (src/erlamsa_mon_logcat.erl:31-51). Requires `adb`."""

    name_code = "lc"

    def run(self):
        if shutil.which("adb") is None:
            logger.log("error", "logcat monitor: adb not found in PATH")
            return
        app = self.params.get("app", "")
        if app:
            subprocess.run(["adb", "shell", "am", "start", "-n", app], check=False)
        proc = subprocess.Popen(
            ["adb", "logcat", "*:E"], stdout=subprocess.PIPE
        )
        crash_lines: list[bytes] = []
        for line in proc.stdout:
            if self._stop_evt.is_set():
                break
            if b"FATAL EXCEPTION" in line or b"SIGSEGV" in line:
                crash_lines = [line]
            elif crash_lines:
                crash_lines.append(line)
                if len(crash_lines) > 20:
                    logger.log("finding", "logcat crash: %r",
                               b"".join(crash_lines)[:2000])
                    feedback.publish("crash", source="monitor:lc")
                    _run_after(self.params)
                    crash_lines = []
        proc.kill()


class LxiMonitor(Monitor):
    """lxi: SCPI MEAS:CURR? over TCP; out-of-range measurement -> finding
    (hardware fuzzing, src/erlamsa_mon_lxi.erl:75-93)."""

    name_code = "lxi"

    def run(self):
        host = self.params.get("host", "127.0.0.1")
        port = int(self.params.get("port", 5025))
        lo = float(self.params.get("lvalue", 0.0))
        hi = float(self.params.get("uvalue", 1.0))
        interval = float(self.params.get("interval", 2.0))
        while not self._stop_evt.is_set():
            try:
                with socket.create_connection((host, port), timeout=3.0) as s:
                    s.sendall(b"MEAS:CURR?\n")
                    v = float(s.recv(256).strip())
                    if not (lo <= v <= hi):
                        logger.log("finding",
                                   "lxi measurement %g outside [%g, %g]", v, lo, hi)
                        feedback.publish("finding", source="monitor:lxi",
                                         detail=f"{v}")
                        _run_after(self.params)
            except (OSError, ValueError) as e:
                logger.log("warning", "lxi probe failed: %s", e)
            self._stop_evt.wait(interval)


class CdbMonitor(Monitor):
    """cdb: drive the Windows CDB console debugger over stdio
    (src/erlamsa_mon_cdb.erl:72-94). Attach with ``pid=N`` (-p),
    ``attach=name`` (-pn) or launch with ``app=<cmdline>``; `g` resumes the
    target, and when the debugger breaks back in (crash/exception) the
    monitor logs the event, a `k` backtrace and `r` registers as findings,
    saves a timestamped minidump via ``.dump /m``, runs the after actions
    and re-attaches. ``cdb=<binary>`` overrides the debugger path (used by
    tests to substitute an emulator; real use needs cdb.exe in PATH).

    The stdio protocol matches the reference's port loop: every command's
    reply is read until the "> " debugger prompt (read_cdb_data,
    src/erlamsa_mon_cdb.erl:131-141).
    """

    name_code = "cdb"
    ATTEMPTS = 5  # ?START_MONITOR_ATTEMPTS

    def __init__(self, params: dict):
        super().__init__(params)
        self._proc: subprocess.Popen | None = None

    def stop(self):
        super().stop()
        proc = self._proc
        if proc is not None and proc.poll() is None:
            proc.kill()

    def _target_args(self):
        if "pid" in self.params:
            return ["-p", str(self.params["pid"])]
        if "attach" in self.params:
            return ["-pn", str(self.params["attach"])]
        if "app" in self.params:
            return shlex.split(self.params["app"])
        return None

    def _read_to_prompt(self) -> bytes | None:
        """Accumulate debugger output until the trailing '> ' prompt; None
        when the debugger exits first (closed/process_exit in the ref)."""
        buf = b""
        while True:
            chunk = self._proc.stdout.read(1)
            if not chunk:
                return None
            buf += chunk
            if buf.endswith(b"> "):
                return buf

    def _call(self, cmd: bytes) -> bytes | None:
        try:
            self._proc.stdin.write(cmd)
            self._proc.stdin.flush()
        except OSError:
            return None
        return self._read_to_prompt()

    def run(self):
        cdb = self.params.get("cdb", "cdb")
        if shutil.which(cdb) is None:
            logger.log("error", "cdb monitor: %s not found in PATH", cdb)
            return
        args = self._target_args()
        if args is None:
            logger.log("error", "cdb monitor needs pid=/attach=/app=")
            return
        attempts = self.ATTEMPTS
        while not self._stop_evt.is_set():
            if attempts <= 0:
                logger.log("error",
                           "cdb monitor: too many failures (%d), giving up",
                           self.ATTEMPTS)
                return
            try:
                self._proc = subprocess.Popen(
                    [cdb, *args], stdin=subprocess.PIPE,
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                )
            except OSError as e:
                logger.log("warning", "cdb monitor spawn failed: %s", e)
                attempts -= 1
                self._stop_evt.wait(1.0)
                continue
            if self._stop_evt.is_set():  # stop() may have raced the spawn
                self._kill()
                return
            banner = self._read_to_prompt()
            if banner is None:
                logger.log("warning", "cdb monitor: debugger exited at start")
                attempts -= 1
                self._stop_evt.wait(1.0)
                continue
            logger.log("info", "cdb monitor attached: %r", banner[-200:])
            # `g` blocks until the debugger breaks back in — that IS the event
            crash = self._call(b"g\r\n")
            if crash is None or self._stop_evt.is_set():
                if not self._stop_evt.is_set():
                    logger.log("warning",
                               "cdb monitor: debugger exited while running")
                    attempts -= 1
                    self._stop_evt.wait(1.0)
                self._kill()
                continue
            # a full cycle reached the break-in: reset the give-up budget
            # (cdb_start(..., ?START_MONITOR_ATTEMPTS) after each cycle)
            attempts = self.ATTEMPTS
            logger.log("finding", "cdb monitor detected event (crash?): %r",
                       crash[:1000])
            feedback.publish("crash", source="monitor:cdb")
            bt = self._call(b"k\r\n")
            logger.log("finding", "cdb monitor backtrace: %r",
                       (bt or b"")[:2000])
            regs = self._call(b"r\r\n")
            logger.log("finding", "cdb monitor registers: %r",
                       (regs or b"")[:2000])
            name = re.sub(r"[^A-Za-z0-9._-]", "_",
                          self.params.get("app", "cdb_target"))
            dump = name + time.strftime("_%Y_%m_%d_%H_%M_%S.minidump")
            res = self._call(f".dump /m {dump} \r\n".encode())
            logger.log("finding", "cdb monitor minidump saved to %s: %r",
                       dump, (res or b"")[:500])
            try:
                self._proc.stdin.write(b"q\r\n")
                self._proc.stdin.flush()
            except OSError:
                pass
            self._kill()
            _run_after(self.params)

    def _kill(self):
        proc = self._proc
        if proc is None:
            return
        try:
            if proc.poll() is None:
                proc.kill()
            proc.wait(timeout=5)
        except OSError:
            pass


MONITORS = {
    m.name_code: m
    for m in (ConnectMonitor, NetworkProbeMonitor, ExecMonitor, R2Monitor,
              LogcatMonitor, LxiMonitor, CdbMonitor)
}


def parse_monitor_spec(spec: str):
    """'+name:k=v,k=v' enables, '!name:off' disables
    (erlamsa_cmdparse monitor parsing, src/erlamsa_cmdparse.erl:436-451)."""
    if spec.startswith("!"):
        return None
    spec = spec.lstrip("+")
    name, _, rest = spec.partition(":")
    params = {}
    for kv in rest.split(","):
        if "=" in kv:
            k, v = kv.split("=", 1)
            params[k] = v
    return name, params


def start_monitors(specs: list[str], default_cm: bool = False) -> list[Monitor]:
    """Start requested monitors; with default_cm the connect monitor starts
    unless disabled (erlamsa_monitor:default/0, src/erlamsa_monitor.erl:33)."""
    started = []
    disabled = {s.lstrip("!").partition(":")[0] for s in specs if s.startswith("!")}
    wanted = [parse_monitor_spec(s) for s in specs if not s.startswith("!")]
    wanted = [w for w in wanted if w]
    if default_cm and "cm" not in disabled and not any(n == "cm" for n, _ in wanted):
        wanted.append(("cm", {}))
    for name, params in wanted:
        cls = MONITORS.get(name)
        if cls is None:
            logger.log("error", "unknown monitor %s", name)
            continue
        mon = cls(params)
        mon.start()
        started.append(mon)
    return started
