"""Crash/event monitors.

Reference: src/erlamsa_monitor.erl and mon_* modules — a registry of
monitors started from ``--monitor +name:params`` / ``!name:off`` CLI specs,
each reporting findings through the logger and optionally running an
``after=exec`` recovery action:

  cm      connect-back listener catching SSRF/XXE/reverse-shell payloads
          (src/erlamsa_mon_connect.erl); its host:port is advertised to the
          payload builders via the shared config
  probe   periodic TCP/UDP liveness probe; refused/timeout -> finding
          (src/erlamsa_mon_network.erl)
  exec    spawn-and-watch a target process; nonzero/signal exit -> finding
          (the cdb/r2 equivalent for environments without a debugger)
  r2      radare2-driven crash triage (src/erlamsa_mon_r2.erl); gated on
          an available `r2` binary
  lc      adb logcat crash extraction (src/erlamsa_mon_logcat.erl); gated
          on an available `adb` binary
  lxi     SCPI measurement-range monitor over TCP
          (src/erlamsa_mon_lxi.erl)

Deliberately absent: the reference's Windows CDB monitor
(src/erlamsa_mon_cdb.erl — cdb.exe backtrace/minidump/restart). This
framework targets Linux hosts; `exec` covers exit-status triage and `r2`
covers debugger-grade backtraces there. Port a cdb driver in the same
ExecMonitor shape if Windows targets ever matter.
"""

from __future__ import annotations

import shlex
import shutil
import socket
import subprocess
import threading
import time

from ..constants import DEFAULT_CM_PORT
from . import logger

# shared monitor config, the reference's global_config ets analogue
CONFIG: dict = {"cm_port": DEFAULT_CM_PORT, "cm_host": None}


def _run_after(params: dict):
    """after=exec recovery hook (erlamsa_monitor:do_after,
    src/erlamsa_monitor.erl:98-104)."""
    cmd = params.get("after")
    if cmd:
        subprocess.Popen(shlex.split(cmd))


class Monitor(threading.Thread):
    name_code = "base"

    def __init__(self, params: dict):
        super().__init__(daemon=True)
        self.params = params
        self._stop = threading.Event()

    def stop(self):
        self._stop.set()


class ConnectMonitor(Monitor):
    """cm: TCP listener catching connect-backs; '{event}'-prefixed payloads
    log as findings (src/erlamsa_mon_connect.erl:47-54)."""

    name_code = "cm"

    def run(self):
        port = int(self.params.get("port", DEFAULT_CM_PORT))
        CONFIG["cm_port"] = port
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            srv.bind(("0.0.0.0", port))
        except OSError as e:
            logger.log("error", "cm monitor cannot bind :%d: %s", port, e)
            return
        srv.listen(16)
        srv.settimeout(1.0)
        logger.log("info", "connect monitor listening on :%d", port)
        while not self._stop.is_set():
            try:
                conn, addr = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                conn.settimeout(2.0)
                data = conn.recv(4096)
            except OSError:
                data = b""
            finally:
                conn.close()
            if data.startswith(b"{event}"):
                logger.log("finding", "cm event from %s: %r", addr[0], data[7:200])
            else:
                logger.log("finding", "connect-back from %s:%d (%d bytes)",
                           addr[0], addr[1], len(data))
            _run_after(self.params)


class NetworkProbeMonitor(Monitor):
    """probe: periodic hello; timeout/refusal is a finding
    (src/erlamsa_mon_network.erl:48-57)."""

    name_code = "probe"

    def run(self):
        host = self.params.get("host", "127.0.0.1")
        port = int(self.params.get("port", 80))
        proto = self.params.get("proto", "tcp")
        interval = float(self.params.get("interval", 5.0))
        hello = self.params.get("hello", "hello").encode()
        while not self._stop.is_set():
            ok = False
            try:
                if proto == "udp":
                    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                    s.settimeout(3.0)
                    s.sendto(hello, (host, port))
                    ok = True
                else:
                    with socket.create_connection((host, port), timeout=3.0) as s:
                        s.sendall(hello)
                        ok = True
            except OSError as e:
                logger.log("finding", "probe: %s:%d unreachable (%s)", host, port, e)
                _run_after(self.params)
            if ok:
                logger.log("debug", "probe: %s:%d alive", host, port)
            self._stop.wait(interval)


class ExecMonitor(Monitor):
    """exec: keep a target app running; abnormal exits are findings and the
    app is restarted — the cross-platform stand-in for the cdb/r2 debugger
    monitors (src/erlamsa_mon_cdb.erl behavior)."""

    name_code = "exec"

    def run(self):
        cmd = self.params.get("app")
        if not cmd:
            logger.log("error", "exec monitor needs app=<cmdline>")
            return
        while not self._stop.is_set():
            proc = subprocess.Popen(
                shlex.split(cmd), stdout=subprocess.PIPE, stderr=subprocess.STDOUT
            )
            out, _ = proc.communicate()
            rc = proc.returncode
            if rc and not self._stop.is_set():
                level = "finding" if rc < 0 else "warning"
                logger.log(level, "exec target exited rc=%d; tail: %r",
                           rc, out[-500:] if out else b"")
                _run_after(self.params)
            time.sleep(float(self.params.get("delay", 5.0)))


class R2Monitor(Monitor):
    """r2: drive radare2 over r2pipe for crash triage; registers/backtrace
    dumps on crash (src/erlamsa_mon_r2.erl:43-58). Requires `r2`."""

    name_code = "r2"

    def run(self):
        if shutil.which("r2") is None:
            logger.log("error", "r2 monitor: radare2 not found in PATH")
            return
        app = self.params.get("app")
        while not self._stop.is_set():
            proc = subprocess.Popen(
                ["r2", "-q0", "-d", *shlex.split(app)],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            )
            try:
                proc.stdin.write(b"dc\n")
                proc.stdin.flush()
                out = proc.stdout.read()
                if b"SIGSEGV" in out or b"signal" in out:
                    proc.stdin.write(b"drj\nij\ndbt\n")
                    proc.stdin.flush()
                    dump = proc.stdout.read()
                    logger.log("finding", "r2 crash dump: %r", dump[:1000])
                    _run_after(self.params)
            except (OSError, ValueError):
                pass
            finally:
                proc.kill()
            time.sleep(float(self.params.get("delay", 2.0)))


class LogcatMonitor(Monitor):
    """lc: adb logcat crash extraction for Android targets
    (src/erlamsa_mon_logcat.erl:31-51). Requires `adb`."""

    name_code = "lc"

    def run(self):
        if shutil.which("adb") is None:
            logger.log("error", "logcat monitor: adb not found in PATH")
            return
        app = self.params.get("app", "")
        if app:
            subprocess.run(["adb", "shell", "am", "start", "-n", app], check=False)
        proc = subprocess.Popen(
            ["adb", "logcat", "*:E"], stdout=subprocess.PIPE
        )
        crash_lines: list[bytes] = []
        for line in proc.stdout:
            if self._stop.is_set():
                break
            if b"FATAL EXCEPTION" in line or b"SIGSEGV" in line:
                crash_lines = [line]
            elif crash_lines:
                crash_lines.append(line)
                if len(crash_lines) > 20:
                    logger.log("finding", "logcat crash: %r",
                               b"".join(crash_lines)[:2000])
                    _run_after(self.params)
                    crash_lines = []
        proc.kill()


class LxiMonitor(Monitor):
    """lxi: SCPI MEAS:CURR? over TCP; out-of-range measurement -> finding
    (hardware fuzzing, src/erlamsa_mon_lxi.erl:75-93)."""

    name_code = "lxi"

    def run(self):
        host = self.params.get("host", "127.0.0.1")
        port = int(self.params.get("port", 5025))
        lo = float(self.params.get("lvalue", 0.0))
        hi = float(self.params.get("uvalue", 1.0))
        interval = float(self.params.get("interval", 2.0))
        while not self._stop.is_set():
            try:
                with socket.create_connection((host, port), timeout=3.0) as s:
                    s.sendall(b"MEAS:CURR?\n")
                    v = float(s.recv(256).strip())
                    if not (lo <= v <= hi):
                        logger.log("finding",
                                   "lxi measurement %g outside [%g, %g]", v, lo, hi)
                        _run_after(self.params)
            except (OSError, ValueError) as e:
                logger.log("warning", "lxi probe failed: %s", e)
            self._stop.wait(interval)


MONITORS = {
    m.name_code: m
    for m in (ConnectMonitor, NetworkProbeMonitor, ExecMonitor, R2Monitor,
              LogcatMonitor, LxiMonitor)
}


def parse_monitor_spec(spec: str):
    """'+name:k=v,k=v' enables, '!name:off' disables
    (erlamsa_cmdparse monitor parsing, src/erlamsa_cmdparse.erl:436-451)."""
    if spec.startswith("!"):
        return None
    spec = spec.lstrip("+")
    name, _, rest = spec.partition(":")
    params = {}
    for kv in rest.split(","):
        if "=" in kv:
            k, v = kv.split("=", 1)
            params[k] = v
    return name, params


def start_monitors(specs: list[str], default_cm: bool = False) -> list[Monitor]:
    """Start requested monitors; with default_cm the connect monitor starts
    unless disabled (erlamsa_monitor:default/0, src/erlamsa_monitor.erl:33)."""
    started = []
    disabled = {s.lstrip("!").partition(":")[0] for s in specs if s.startswith("!")}
    wanted = [parse_monitor_spec(s) for s in specs if not s.startswith("!")]
    wanted = [w for w in wanted if w]
    if default_cm and "cm" not in disabled and not any(n == "cm" for n, _ in wanted):
        wanted.append(("cm", {}))
    for name, params in wanted:
        cls = MONITORS.get(name)
        if cls is None:
            logger.log("error", "unknown monitor %s", name)
            continue
        mon = cls(params)
        mon.start()
        started.append(mon)
    return started
