"""Case-parallel workers (-w N).

Reference: erlamsa_main:get_threading_mode + run_fuzzing_loop
(src/erlamsa_main.erl:89-108, 249-280): N cases split into per-worker
ranges plus a remainder; each worker runs the same loop with a seed drawn
from the parent stream (or the same seed with --workers-same-seed).
Processes (not threads) so oracle CPU work scales.
"""

from __future__ import annotations

import multiprocessing as mp

from ..utils.erlrand import ErlRand


def _worker_main(opts: dict, lo: int, hi: int, extra: int, wseed):
    from ..oracle.engine import Engine
    from ..utils.watchdog import CaseTimeout, run_with_timeout
    from . import out as outmod

    wopts = dict(opts)
    wopts["seed"] = wseed
    writer, _ = outmod.string_outputs(opts.get("output", "-"))
    eng = Engine(wopts)
    budget = opts.get("maxrunningtime") or 0

    def one_case(idx: int):
        try:
            data, meta = run_with_timeout(eng.run_case, budget, idx)
        except CaseTimeout:
            return  # abandoned like the reference's per-case kill
        if writer is not None and data:
            try:
                run_with_timeout(writer, budget, idx, data, meta)
            except (ConnectionError, CaseTimeout):
                pass

    for i in range(max(lo, 1), hi + 1):
        one_case(i)
    if extra:
        one_case(extra)


def split_ranges(n: int, workers: int) -> list[tuple[int, int, int]]:
    """[(lo, hi, extra_case)] per worker covering cases 1..n exactly:
    worker w owns [w*div, (w+1)*div - 1] and workers 0..rem additionally
    own case div*workers + w (get_threading_mode,
    src/erlamsa_main.erl:95-108)."""
    div = n // workers
    rem = n % workers
    out = []
    for w in range(workers):
        lo = w * div
        hi = (w + 1) * div - 1
        extra = div * workers + w if w <= rem else 0
        if w == 0:
            lo = 1
        if w == workers - 1:
            hi = min(hi, n)
        out.append((lo, hi, extra if extra and extra <= n else 0))
    return out


def run_workers(opts: dict, _writer) -> int:
    n = opts.get("n", 1)
    workers = opts.get("workers", 1)
    parent = ErlRand(opts["seed"])
    same_seed = opts.get("workers_same_seed", False)
    procs = []
    for lo, hi, extra in split_ranges(n, workers):
        wseed = (
            opts["seed"]
            if same_seed
            else (parent.erand(99999), parent.erand(99999), parent.erand(99999))
        )
        p = mp.Process(target=_worker_main, args=(opts, lo, hi, extra, wseed))
        p.start()
        procs.append(p)
    for p in procs:
        p.join()
    return 0
