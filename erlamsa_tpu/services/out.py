"""Output writers: route fuzzed cases to stdout / files / sockets / HTTP /
spawned processes.

Reference: src/erlamsa_out.erl — string_outputs maps the -o spec onto a
writer; network failure raises so the main loop can back off
({cantconnect,...}, src/erlamsa_main.erl:203-207). Spec forms:

    "-"                      stdout
    "template%n.ext"         per-case files (%n = case number)
    "tcp://host:port"        TCP client     "tcp://:port" listen
    "udp://host:port"        UDP client     "udp://:port" listen (reply
                             to whoever sends a datagram first)
    "http://url"             HTTP POST      "http://:port[,Content-Type]"
                             serve fuzz as a 200 response per connection

    The bare ":port" listen forms bind 0.0.0.0 (all interfaces — fuzz
    output is served to ANY client that connects, matching the
    reference). To restrict the bind, use the ",listen" forms:
    "tcp://127.0.0.1:port,listen", "udp://127.0.0.1:port,listen",
    "http://127.0.0.1:port,listen[,Content-Type]".

    "exec://cmdline"         spawn target, feed stdin (erlexec analogue)
    "serial://dev:baud"      serial device (termios)
    "can://iface:id"         SocketCAN 8-byte frames
    "canisotp://iface:id"    SocketCAN with ISO-TP framing (iso_tpish)
    "cansockd://host:port:iface:id"            cansockd daemon client
    "cansockd_isotp://host:port:iface:sid:did" cansockd ISO-TP mode
"""

from __future__ import annotations

import os
import shlex
import socket
import subprocess
import sys
import urllib.request
from typing import Callable

from ..constants import DEFAULT_MAX_RUNNING_TIME
from . import logger

Writer = Callable[[int, bytes, list], None]


class CantConnect(ConnectionError):
    pass


def _stdout_writer(case_idx: int, data: bytes, meta: list) -> None:
    sys.stdout.buffer.write(data)
    sys.stdout.buffer.flush()


def _file_writer(template: str) -> Writer:
    """%n in the template becomes the case number
    (erlamsa_out.erl:109-123)."""

    def write(case_idx: int, data: bytes, meta: list) -> None:
        path = template.replace("%n", str(case_idx))
        with open(path, "wb") as f:
            f.write(data)
        logger.log("info", "wrote %d bytes to %s", len(data), path)

    return write


def _tcp_writer(host: str, port: int) -> Writer:
    def write(case_idx: int, data: bytes, meta: list) -> None:
        try:
            with socket.create_connection((host, port), timeout=5) as s:
                s.sendall(data)
        except OSError as e:
            raise CantConnect(str(e)) from e

    return write


def _tls_writer(host: str, port: int) -> Writer:
    """TLS client output (erlamsa_out.erl tls path); certificate checks are
    off — fuzzing targets rarely have valid chains."""
    import ssl

    ctx = ssl.create_default_context()
    ctx.check_hostname = False
    ctx.verify_mode = ssl.CERT_NONE

    def write(case_idx: int, data: bytes, meta: list) -> None:
        try:
            with socket.create_connection((host, port), timeout=5) as raw:
                with ctx.wrap_socket(raw, server_hostname=host) as s:
                    s.sendall(data)
        except (OSError, ssl.SSLError) as e:
            raise CantConnect(str(e)) from e

    return write


def _tcp_listen_writer(port: int, bind_host: str = "0.0.0.0") -> Writer:
    """Listen mode: serve each accepted connection one fuzzed case
    (erlamsa_out.erl tcp listen path). The bare "tcp://:port" spec binds
    all interfaces like the reference; "tcp://host:port,listen" restricts
    the bind (e.g. 127.0.0.1 keeps fuzz output off the network)."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((bind_host, port))
    srv.listen(16)

    def write(case_idx: int, data: bytes, meta: list) -> None:
        conn, _addr = srv.accept()
        try:
            conn.sendall(data)
        finally:
            conn.close()

    return write


def _udp_listen_writer(port: int, bind_host: str = "0.0.0.0") -> Writer:
    """UDP listen mode (erlamsa_out.erl udplisten_writer): bind once; each
    case blocks for an incoming datagram, then sends the fuzzed case back
    to that sender — the UDP analogue of serve-on-connect. bind_host as in
    _tcp_listen_writer ("udp://host:port,listen" restricts the bind)."""
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    sock.bind((bind_host, port))

    def write(case_idx: int, data: bytes, meta: list) -> None:
        packet, addr = sock.recvfrom(65535)
        logger.log("info", "udp message received [case %d] from %s:%d (%d bytes)",
                   case_idx, addr[0], addr[1], len(packet))
        sock.sendto(data, addr)

    return write


def _http_listen_writer(port: int, content_type: str,
                        bind_host: str = "0.0.0.0") -> Writer:
    """HTTP server mode (erlamsa_out.erl:424-445 make_http_server_reply +
    streamlisten_writer wiring): serve each connecting client one fuzzed
    case as a complete 200 response. The request itself is read best-effort
    and logged — fuzzing clients often send junk; we answer regardless."""
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((bind_host, port))
    srv.listen(16)

    def write(case_idx: int, data: bytes, meta: list) -> None:
        conn, addr = srv.accept()
        try:
            conn.settimeout(5)
            try:
                req = conn.recv(65535)
                logger.log("info",
                           "http client connect from %s:%d [case %d], "
                           "request %d bytes", addr[0], addr[1], case_idx,
                           len(req))
            except OSError:
                pass  # reply anyway, like the reference
            head = (
                f"HTTP/1.1 200 OK\r\nContent-type: {content_type}\r\n"
                f"Content-Length: {len(data)}\r\n\r\n"
            ).encode()
            conn.sendall(head + data)
        finally:
            conn.close()

    return write


def _udp_writer(host: str, port: int) -> Writer:
    sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)

    def write(case_idx: int, data: bytes, meta: list) -> None:
        try:
            sock.sendto(data, (host, port))
        except OSError as e:
            raise CantConnect(str(e)) from e

    return write


def _http_writer(url: str) -> Writer:
    def write(case_idx: int, data: bytes, meta: list) -> None:
        try:
            req = urllib.request.Request(
                url, data=data, headers={"Content-Type": "application/octet-stream"}
            )
            urllib.request.urlopen(req, timeout=10).read()
        except OSError as e:
            raise CantConnect(str(e)) from e

    return write


def _exec_writer(cmdline: str, monitor_notify=None) -> Writer:
    """Spawn the target per case and feed fuzzed data to its stdin; notify
    monitors of the PID like the erlexec path (erlamsa_out.erl:143-179).
    Prefers the C++ exec port (native/erlamsa_port.cpp) which reports
    terminating signals and rusage; falls back to subprocess."""
    argv = shlex.split(cmdline)

    def write(case_idx: int, data: bytes, meta: list) -> None:
        from . import native

        res = native.exec_feed(argv, data, int(DEFAULT_MAX_RUNNING_TIME * 1000))
        if res is not None:
            if monitor_notify:
                monitor_notify(res.pid)
            if res.exit_code == 127:
                # execvp failed: the target doesn't exist — surface it so
                # the run loop backs off and stops after maxfails
                raise CantConnect(f"exec target failed to start: {argv[0]}")
            if res.term_signal:
                logger.log(
                    "finding",
                    "exec target died with signal %d on case %d "
                    "(user %.1fms rss %dkB)",
                    res.term_signal, case_idx, res.user_usec / 1000.0,
                    res.max_rss_kb,
                )
            elif res.timed_out:
                logger.log("warning", "exec target timed out on case %d", case_idx)
            return
        proc = subprocess.Popen(
            argv,
            stdin=subprocess.PIPE,
            stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL,
        )
        if monitor_notify:
            monitor_notify(proc.pid)
        try:
            proc.communicate(data, timeout=DEFAULT_MAX_RUNNING_TIME)
        except subprocess.TimeoutExpired:
            proc.kill()
        rc = proc.returncode
        if rc and rc < 0:
            logger.log("finding", "exec target died with signal %d on case %d",
                       -rc, case_idx)

    return write


def _rawip_writer(dst_ip: str) -> Writer:
    """Raw IPv4 output (the procket path, erlamsa_out.erl:185-203): the
    fuzzed case IS the packet, IP header included. Needs CAP_NET_RAW."""

    state = {"fd": None}  # raw fd opened once, reused across cases

    def write(case_idx: int, data: bytes, meta: list) -> None:
        import socket as pysock
        import struct

        from . import native

        lib = native.get()
        if lib is None:
            raise CantConnect("native raw-socket port unavailable")
        if state["fd"] is None:
            fd = lib.erlamsa_rawsock_open()
            if fd < 0:
                raise CantConnect(f"raw socket open failed: errno {-fd}")
            state["fd"] = fd
        try:
            dst_be = struct.unpack("=I", pysock.inet_aton(dst_ip))[0]
        except OSError as e:  # non-dotted-quad destination
            raise CantConnect(f"bad raw destination {dst_ip!r}: {e}") from e
        rc = lib.erlamsa_rawsock_send(state["fd"], data, len(data), dst_be)
        if rc < 0:
            raise CantConnect(f"raw send failed: errno {-rc}")

    return write


def open_serial_raw(dev: str, baud: int) -> int:
    """Open a serial device in RAW mode at the given speed — shared by the
    serial writer and the serial proxy (the reference's erlserial C port
    configures raw mode the same way). Canonical-mode line discipline would
    otherwise mangle binary fuzz traffic (CR/NL translation, ECHO,
    withheld partial lines)."""
    import termios

    fd = os.open(dev, os.O_RDWR | os.O_NOCTTY)
    attrs = termios.tcgetattr(fd)
    speed = getattr(termios, f"B{baud}", termios.B115200)
    # cfmakeraw equivalent (the termios module here lacks it)
    attrs[0] &= ~(termios.IGNBRK | termios.BRKINT | termios.PARMRK
                  | termios.ISTRIP | termios.INLCR | termios.IGNCR
                  | termios.ICRNL | termios.IXON)
    attrs[1] &= ~termios.OPOST
    attrs[3] &= ~(termios.ECHO | termios.ECHONL | termios.ICANON
                  | termios.ISIG | termios.IEXTEN)
    attrs[2] &= ~(termios.CSIZE | termios.PARENB)
    attrs[2] |= termios.CS8 | termios.CLOCAL | termios.CREAD
    attrs[4] = attrs[5] = speed
    termios.tcsetattr(fd, termios.TCSANOW, attrs)
    return fd


def _serial_writer(dev: str, baud: int) -> Writer:
    """termios-configured serial device (the reference uses the erlserial C
    port, src/erlamsa_out.erl:129-137)."""
    fd = open_serial_raw(dev, baud)

    def write(case_idx: int, data: bytes, meta: list) -> None:
        os.write(fd, data)

    return write


def iso_tpish(data: bytes) -> bytes:
    """ISO-TP-style framing of one fuzzed case (erlamsa_out.erl:493-521
    iso_tpish): <7 bytes -> one single frame ``0x0|len``; otherwise a
    first frame ``0x1|len:12`` carrying 6 bytes, then consecutive frames
    ``0x2|idx:4`` of 7 bytes each. The index wraps at 16 via 4-bit
    truncation — and, matching the reference's clause order exactly, a
    trailing PARTIAL frame whose index has passed 15 resets to 0 rather
    than wrapping mod 16."""
    n = len(data)
    if n < 7:
        return bytes([n & 0x0F]) + data
    out = bytearray([0x10 | ((n >> 8) & 0x0F), n & 0xFF])
    out += data[:6]
    idx, off = 0, 6
    while off < n:
        chunk = data[off : off + 7]
        if len(chunk) < 7 and idx > 15:
            idx = 0
        out.append(0x20 | (idx & 0x0F))
        out += chunk
        idx += 1
        off += 7
    return bytes(out)


def _can_writer(iface: str, can_id: int, isotp: bool = False) -> Writer:
    """SocketCAN output: each fuzzed case streams as 8-byte CAN frames,
    optionally ISO-TP framed first (canisotp://). The reference reaches
    CAN through its cansockd TCP daemon (erlamsa_out.erl cansockd
    writers); talking SocketCAN directly is this framework's native
    equivalent — the daemon client forms exist too (_cansockd_writer).
    Gated on AF_CAN support and the interface existing."""
    import struct

    if not hasattr(socket, "AF_CAN"):
        raise SystemExit("can:// needs SocketCAN (AF_CAN) support")
    sock = socket.socket(socket.AF_CAN, socket.SOCK_RAW, socket.CAN_RAW)
    try:
        sock.bind((iface,))
    except OSError as e:
        raise SystemExit(f"can:// cannot bind {iface!r}: {e}")
    if can_id > 0x7FF:  # 29-bit extended arbitration id
        can_id |= socket.CAN_EFF_FLAG

    def write(case_idx: int, data: bytes, meta: list) -> None:
        payload = iso_tpish(data) if isotp else data
        try:
            for off in range(0, len(payload), 8):
                chunk = payload[off : off + 8]
                # '=' = native byte order, matching the kernel's can_frame
                frame = struct.pack("=IB3x8s", can_id, len(chunk),
                                    chunk.ljust(8, b"\x00"))
                sock.send(frame)
        except OSError as e:
            raise CantConnect(str(e)) from e

    return write


def _hexstr(data: bytes, sep: str) -> str:
    return sep.join(f"{b:02X}" for b in data) + (sep if sep and data else "")


def _cansockd_writer(host: str, port: int, iface: str, can_id: str) -> Writer:
    """cansockd daemon client (erlamsa_out.erl cansockd_writer /
    make_cansockd_cmd): one persistent TCP connection; every case opens
    with ``< open iface >`` and streams 8-byte chunks as
    ``< send ID LEN HH HH ... >`` text commands."""
    state: dict = {"sock": None}

    def _sock() -> socket.socket:
        if state["sock"] is None:
            try:
                state["sock"] = socket.create_connection((host, port), timeout=5)
            except OSError as e:
                raise CantConnect(str(e)) from e
        return state["sock"]

    def write(case_idx: int, data: bytes, meta: list) -> None:
        cmds = [f"< open {iface} >"]
        for off in range(0, len(data), 8):
            chunk = data[off : off + 8]
            cmds.append(f"< send {can_id} {len(chunk)} {_hexstr(chunk, ' ')}>")
        try:
            _sock().sendall("".join(cmds).encode())
        except OSError as e:
            state["sock"] = None
            raise CantConnect(str(e)) from e

    return write


def _cansockd_isotp_writer(host: str, port: int, iface: str,
                           sid: str, did: str) -> Writer:
    """cansockd ISO-TP mode client (erlamsa_out.erl:560-576): the banner
    switches the daemon into isotpmode with the source/destination ids,
    then each case ships as one ``< sendpdu HEX >`` — the daemon does the
    ISO-TP segmentation (for direct SocketCAN segmentation use
    canisotp://)."""
    state: dict = {"sock": None}
    banner = (f"< open {iface} >< isotpmode >"
              f"< isotpconf {sid} {did} 0 0 0 >")

    def _sock() -> socket.socket:
        if state["sock"] is None:
            try:
                s = socket.create_connection((host, port), timeout=5)
                s.sendall(banner.encode())
                state["sock"] = s
            except OSError as e:
                raise CantConnect(str(e)) from e
        return state["sock"]

    def write(case_idx: int, data: bytes, meta: list) -> None:
        if not data:
            return
        cmd = f"< sendpdu {_hexstr(data, '')} >"
        try:
            _sock().sendall(cmd.encode())
        except OSError as e:
            state["sock"] = None
            raise CantConnect(str(e)) from e

    return write


class ReturnCollector:
    """output=return mode: collect results for the library caller."""

    def __init__(self):
        self.results: list[bytes] = []

    def __call__(self, case_idx: int, data: bytes, meta: list) -> None:
        self.results.append(data)


def string_outputs(spec, monitor_notify=None) -> tuple[Writer | None, float]:
    """-o spec -> (writer, max_running_time_s)
    (erlamsa_out:string_outputs, src/erlamsa_out.erl:581-633).
    None writer means return mode."""
    if spec in (None, "return", "direct"):
        return None, DEFAULT_MAX_RUNNING_TIME
    if spec == "-":
        return _stdout_writer, DEFAULT_MAX_RUNNING_TIME
    if spec.startswith("tcp://"):
        rest = spec[6:]
        # "tcp://host:port,listen": listen bound to host (loopback keeps
        # fuzz output off the network); bare "tcp://:port" binds 0.0.0.0
        # like the reference
        if rest.endswith(",listen"):
            host, _, port = rest[: -len(",listen")].rpartition(":")
            return (
                _tcp_listen_writer(int(port), host or "0.0.0.0"),
                DEFAULT_MAX_RUNNING_TIME,
            )
        host, _, port = rest.rpartition(":")
        if host == "":
            return _tcp_listen_writer(int(port)), DEFAULT_MAX_RUNNING_TIME
        return _tcp_writer(host, int(port)), DEFAULT_MAX_RUNNING_TIME
    if spec.startswith("tls://"):
        host, _, port = spec[6:].rpartition(":")
        return _tls_writer(host or "127.0.0.1", int(port)), DEFAULT_MAX_RUNNING_TIME
    if spec.startswith("udp://"):
        rest = spec[6:]
        if rest.endswith(",listen"):  # bound listen form, same as tcp://
            host, _, port = rest[: -len(",listen")].rpartition(":")
            return (
                _udp_listen_writer(int(port), host or "0.0.0.0"),
                DEFAULT_MAX_RUNNING_TIME,
            )
        if rest.startswith(":"):
            # only the explicit "udp://:port" form listens, mirroring tcp://
            return _udp_listen_writer(int(rest[1:])), DEFAULT_MAX_RUNNING_TIME
        host, _, port = rest.rpartition(":")
        return _udp_writer(host or "127.0.0.1", int(port)), DEFAULT_MAX_RUNNING_TIME
    if spec.startswith(("http://", "https://")):
        # "http://:port[,Content-Type]" = server mode (reference
        # erlamsa_out.erl http_writer empty-host clauses); anything with a
        # host is a POST client
        scheme, rest = spec.split("://", 1)
        if (",listen" in rest) and scheme == "https":
            raise SystemExit(
                "https server mode is not supported; use "
                "http://host:port,listen (plaintext) or terminate TLS in "
                "front"
            )
        if (",listen" in rest) and scheme == "http":
            # "http://host:port,listen[,CT]": server mode bound to host
            hostport, _, ctype = rest.partition(",listen")
            host, _, port_s = hostport.rpartition(":")
            return (
                _http_listen_writer(
                    int(port_s),
                    ctype.lstrip(",").strip() or "application/octet-stream",
                    host or "0.0.0.0",
                ),
                DEFAULT_MAX_RUNNING_TIME,
            )
        if rest.startswith(":"):
            if scheme == "https":
                # the reference's https server mode needs cert/key files
                # that this spec-only seam cannot carry; refuse loudly
                # rather than serve plaintext on a port named https
                raise SystemExit(
                    "https://:port server mode is not supported; use "
                    "http://:port (plaintext) or terminate TLS in front"
                )
            port_s, _, ctype = rest[1:].partition(",")
            return (
                _http_listen_writer(
                    int(port_s), ctype.strip() or "application/octet-stream"
                ),
                DEFAULT_MAX_RUNNING_TIME,
            )
        return _http_writer(spec), DEFAULT_MAX_RUNNING_TIME
    if spec.startswith("exec://"):
        return _exec_writer(spec[7:], monitor_notify), DEFAULT_MAX_RUNNING_TIME
    if spec.startswith("ip://"):
        return _rawip_writer(spec[5:]), DEFAULT_MAX_RUNNING_TIME
    if spec.startswith("cansockd://"):
        host, port, iface, can_id = spec[11:].split(":", 3)
        return (
            _cansockd_writer(host or "127.0.0.1", int(port), iface, can_id),
            DEFAULT_MAX_RUNNING_TIME,
        )
    if spec.startswith("cansockd_isotp://"):
        host, port, iface, sid, did = spec[17:].split(":", 4)
        return (
            _cansockd_isotp_writer(host or "127.0.0.1", int(port), iface,
                                   sid, did),
            DEFAULT_MAX_RUNNING_TIME,
        )
    if spec.startswith("canisotp://"):
        iface, _, can_id = spec[11:].partition(":")
        return (
            _can_writer(iface, int(can_id or "0", 0), isotp=True),
            DEFAULT_MAX_RUNNING_TIME,
        )
    if spec.startswith("can://"):
        iface, _, can_id = spec[6:].partition(":")
        return _can_writer(iface, int(can_id or "0", 0)), DEFAULT_MAX_RUNNING_TIME
    if spec.startswith("serial://"):
        dev, _, baud = spec[9:].rpartition(":")
        return _serial_writer(dev or spec[9:], int(baud or 115200)), DEFAULT_MAX_RUNNING_TIME
    return _file_writer(spec), DEFAULT_MAX_RUNNING_TIME
