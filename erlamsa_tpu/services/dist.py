"""Distributed fuzzing nodes: join/keepalive control plane.

Reference: src/erlamsa_app.erl:144-246 — worker nodes join a parent over
Erlang distribution with {join, Pid} keepalives every 15s, the parent
evicts nodes silent for >17s and routes each fuzz request to a random live
node. Here the control plane is a JSON-lines TCP protocol:

    {"op": "join", "port": N}            worker -> parent (keepalive)
    {"op": "fuzz", "data": b64, ...}     parent -> worker / client -> parent
    {"op": "result", "data": b64}        reply

The data plane stays local to each node (its own oracle pool or TPU batch
engine) — DCN-style corpus fan-out between hosts, device-local mutation,
matching SURVEY.md §5.8's design obligation.

Resilience (services/resilience.py): the parent's node table is
health-scored with a per-node circuit breaker — repeated request failures
open a node's breaker (it stops receiving traffic without waiting for the
17s keepalive eviction), a cooled-down breaker admits one probe request,
and a successful probe re-admits the node. route_fuzz retries each node
and fails over across distinct nodes before falling back to local
fuzzing, with every hop visible in metrics events. remote_fuzz raises
ProtocolError on a malformed/missing reply — "the node failed" is an
exception, never a forged empty fuzz result. Fault sites dist.send /
dist.recv (services/chaos.py) make all of it deterministically testable.
"""

from __future__ import annotations

import base64
import json
import random as _pyrandom
import socket
import threading
import time

from ..constants import NODE_ALIVE_DELTA, NODE_KEEPALIVE, NODES_CHECKTIMER
from ..obs import trace
from ..utils.erlrand import gen_urandom_seed
from . import chaos, logger, metrics
from .batcher import make_batcher
from .resilience import HealthTable, RetryExhausted, RetryPolicy
from .supervisor import supervise


class ProtocolError(ValueError):
    """The peer answered with garbage (or nothing): a node-side failure
    the caller must treat as retriable, distinct from a fuzzer that
    legitimately produced empty output."""


def _send_json(sock: socket.socket, obj: dict):
    chaos.fault_point("dist.send")
    sock.sendall(json.dumps(obj).encode() + b"\n")


# a peer streaming one endless line must not exhaust memory; 64 MiB covers
# any legitimate base64 fuzz payload (10 MB log cap * 4/3 with headroom)
MAX_LINE = 64 * 1024 * 1024


def _recv_json(f) -> dict | None:
    chaos.fault_point("dist.recv")
    line = f.readline(MAX_LINE + 1)
    if not line:
        return None
    if len(line) > MAX_LINE:
        raise ValueError("oversized protocol line")
    return json.loads(line)


# per-node request retry: short, bounded — failover to ANOTHER node beats
# hammering a sick one (the reference just picks a random node per call)
NODE_RETRY = RetryPolicy(attempts=2, base=0.05, max_delay=0.5,
                         retry_on=(OSError, ValueError))
MAX_FAILOVER_NODES = 3  # distinct nodes tried before local fallback


class NodePool:
    """Parent-side registry of live worker nodes
    (erlamsa_app:loop/3, src/erlamsa_app.erl:210-246), health-scored:
    keepalives keep a node listed, request outcomes move its score and
    breaker, and pick() routes around open breakers."""

    def __init__(self):
        self._rng = _pyrandom.Random(str(gen_urandom_seed()))
        # breaker cool-down ~ keepalive period: a node evicted for request
        # failures gets its re-admission probe about when the reference
        # would first notice it died
        self.table = HealthTable(self._rng, failure_threshold=2,
                                 reset_timeout=NODE_KEEPALIVE / 3.0)
        supervise("nodepool-evict", self._evict_loop)

    def join(self, host: str, port: int):
        if self.table.touch((host, port)):
            logger.log("info", "node %s:%d joined", host, port)

    def _evict_loop(self):
        while True:
            time.sleep(NODES_CHECKTIMER)
            for host, port in self.table.drop_stale(NODE_ALIVE_DELTA):
                metrics.GLOBAL.record_event("node_evicted")
                logger.log("info", "node %s:%d evicted (silent)", host, port)

    def pick(self, exclude=()) -> tuple[str, int] | None:
        """A routable node (get_free_node, src/erlamsa_app.erl:185-190) —
        healthy nodes weighted by score, open breakers skipped, one probe
        admitted per cooled-down breaker."""
        return self.table.pick(exclude=exclude)

    def report(self, node: tuple[str, int], ok: bool):
        self.table.report(node, ok)

    def count(self) -> int:
        return self.table.count()


class ParentServer:
    """Accepts joins and fuzz requests; routes requests across healthy
    worker nodes with retry + failover, falling back to local fuzzing
    when no node can serve."""

    def __init__(self, port: int, opts: dict, backend: str = "oracle"):
        self.port = port
        self.pool = NodePool()
        self.local = make_batcher(backend, workers=opts.get("workers", 10),
                                  seed=opts.get("seed"))
        self.opts = opts
        self._stop = threading.Event()

    def _handle(self, conn: socket.socket, addr):
        f = conn.makefile("rb")
        try:
            while True:
                msg = _recv_json(f)
                if msg is None:
                    return
                if msg.get("op") == "join":
                    self.pool.join(addr[0], int(msg.get("port", 0)))
                    _send_json(conn, {"op": "joined"})
                elif msg.get("op") == "fuzz":
                    data = base64.b64decode(msg.get("data", ""))
                    out = self.route_fuzz(data)
                    _send_json(conn, {"op": "result",
                                      "data": base64.b64encode(out).decode()})
        except (OSError, ValueError) as e:
            # a dead/garbling peer must not kill the handler thread, but
            # it must not vanish either — silent swallowing here hid every
            # protocol bug and truncated request
            logger.log("warning", "dist: dropping connection from %s:%d: %s",
                       addr[0], addr[1], e)
        finally:
            conn.close()

    def route_fuzz(self, data: bytes, timeout: float = 90.0) -> bytes:
        """Route one request: up to MAX_FAILOVER_NODES distinct healthy
        nodes, each under the per-node retry policy, then the local
        engine. Outcomes feed the health table, so a failing node's
        breaker opens after a couple of requests and traffic routes
        around it until its re-admission probe succeeds."""
        deadline = time.monotonic() + timeout
        tried: set = set()
        while len(tried) < MAX_FAILOVER_NODES:
            node = self.pool.pick(exclude=tried)
            if node is None:
                break
            tried.add(node)
            try:
                with trace.span("dist.route", node=f"{node[0]}:{node[1]}",
                                attempt=len(tried)):
                    out = NODE_RETRY.call(
                        remote_fuzz, node[0], node[1], data,
                        site=f"dist:{node[0]}:{node[1]}", deadline=deadline,
                    )
                self.pool.report(node, True)
                return out
            except (RetryExhausted, OSError, ValueError):
                self.pool.report(node, False)
                metrics.GLOBAL.record_event("failover")
                logger.log("warning", "node %s:%d failed, failing over "
                           "(%d tried)", node[0], node[1], len(tried))
        if tried:
            metrics.GLOBAL.record_event("dist_local_fallback")
            logger.log("warning", "all %d node(s) failed, fuzzing locally",
                       len(tried))
        return self.local.fuzz(data, dict(self.opts))

    def serve(self, block: bool = True):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", self.port))
        srv.listen(64)
        self._srv = srv
        logger.log("info", "distribution parent on :%d", self.port)

        def loop():
            while not self._stop.is_set():
                try:
                    conn, addr = srv.accept()
                except OSError:
                    break
                threading.Thread(target=self._handle, args=(conn, addr),
                                 daemon=True).start()

        if block:
            loop()
            return 0
        supervise("dist-parent-accept", loop)
        return self

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass


def remote_fuzz(host: str, port: int, data: bytes, timeout: float = 90.0) -> bytes:
    """Client call into a node (erlamsa_app:call/2,
    src/erlamsa_app.erl:248-253). Raises ProtocolError when the node
    closes without answering or answers with a non-result — callers can
    then distinguish "node failed" (failover) from "fuzzer produced empty
    output" (a legitimate result)."""
    with trace.span("dist.remote_fuzz", node=f"{host}:{port}",
                    bytes=len(data)):
        with socket.create_connection((host, port), timeout=timeout) as s:
            _send_json(s, {"op": "fuzz",
                           "data": base64.b64encode(data).decode()})
            resp = _recv_json(s.makefile("rb"))
            if resp is None:
                raise ProtocolError(f"node {host}:{port} closed without "
                                    "a reply")
            if resp.get("op") != "result" or "data" not in resp:
                raise ProtocolError(f"node {host}:{port} sent a malformed "
                                    f"reply: {str(resp)[:120]}")
            return base64.b64decode(resp["data"])


class WorkerNode:
    """Joins a parent with keepalives and serves fuzz requests
    (erlamsa_app:loop_node, src/erlamsa_app.erl:165-182)."""

    def __init__(self, parent_host: str, parent_port: int, opts: dict,
                 backend: str = "oracle", listen_port: int = 0):
        self.parent = (parent_host, parent_port)
        self.server = ParentServer(listen_port or 0, opts, backend)
        self.opts = opts
        self._stop = threading.Event()

    def start(self, block: bool = True):
        self.server.serve(block=False)
        my_port = self.server._srv.getsockname()[1]

        def keepalive():
            while not self._stop.is_set():
                try:
                    with socket.create_connection(self.parent, timeout=5) as s:
                        _send_json(s, {"op": "join", "port": my_port})
                        _recv_json(s.makefile("rb"))
                except (OSError, ValueError) as e:
                    logger.log("warning", "keepalive to parent failed: %s", e)
                self._stop.wait(NODE_KEEPALIVE)

        t = supervise("node-keepalive", keepalive)
        if block:
            t.join()
            return 0
        return self

    def stop(self):
        self._stop.set()
        self.server.stop()


def run_node(host: str, port: int, opts: dict) -> int:
    return WorkerNode(host, port, opts).start(block=True)
