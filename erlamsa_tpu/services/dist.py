"""Distributed fuzzing nodes: join/keepalive control plane, plus the
cross-host fleet's shard-lease data plane.

Reference: src/erlamsa_app.erl:144-246 — worker nodes join a parent over
Erlang distribution with {join, Pid} keepalives every 15s, the parent
evicts nodes silent for >17s and routes each fuzz request to a random live
node. Here the control plane is a JSON-lines TCP protocol:

    {"op": "join", "port": N}            worker -> parent (keepalive)
    {"op": "fuzz", "data": b64, ...}     parent -> worker / client -> parent
    {"op": "result", "data": b64}        reply

The data plane stays local to each node (its own oracle pool or TPU batch
engine) — DCN-style corpus fan-out between hosts, device-local mutation,
matching SURVEY.md §5.8's design obligation.

The corpus fleet (corpus/fleet.py --fleet-nodes) extends the protocol
with a shard-lease handshake so a fleet shard can live on another host:

    {"op": "shard_lease", "shard": i, "epoch": e, ...cfg}  -> shard_leased
    {"op": "shard_step", "shard": i, "epoch": e, "case": c,
     "slots": [...], "data": [b64...], "scores": [[...]]}  -> shard_result
    {"op": "shard_revoke", "shard": i, "epoch": e}         -> shard_revoked
    {"op": "shard_probe"}                                  -> shard_alive

Framed shard streams (r15): the fleet's data plane no longer rides
JSON-lines. A coordinator opens ONE persistent connection per remote
shard and speaks length-prefixed binary frames (FRAME_MAGIC + header
length + blob length + JSON header + raw byte panel — no base64, no
per-case connect). The listener sniffs the first byte: FRAME_MAGIC
can never begin a JSON line, so framed streams and legacy JSON peers
share one port (RemoteShard, the JSON client, stays for compatibility
and tests). Framed ops extend the lease protocol:

    shard_step      header carries slots/sids/scores + inline seed
                    payloads in the frame blob; seeds covered by the
                    lease's warm-start snapshot ship by id only
    shard_snapshot  arena warm-start image for the shard's partitions
                    (page payloads in the blob, crc32 + lease epoch in
                    the header) — cached in the lease entry, fenced
                    like any step
    shard_sync      window barrier: the only awaited exchange on the
                    steady-state step path — the coordinator writes
                    step frames fire-and-forget and syncs every
                    --fleet-window cases, so round trips amortize W x

Frames on one stream are processed strictly in arrival order and
replies come back FIFO, which is what lets the coordinator's reduce
thread consume step results while the dispatch thread writes the next
case's frames on the same socket (one writer, one reader per stream).

Leases carry a monotonically increasing **fencing epoch** (the
FleetPlacement migration epoch, parallel/shards.py). The worker rejects
any step whose epoch is not its current lease (`shard_fenced`), and the
coordinator rejects any reply that does not echo the epoch/case/shard it
sent (`validate_shard_reply`) — a zombie worker's late reply is logged
and dropped, never merged into the reduce. The worker itself is
STATELESS between steps: each shard_step ships the slice's bytes and
score rows, and the worker mirrors the local per-class dispatch recipe
exactly (corpus/fleet.run_remote_slice), which is what makes
remote-N == local-N == 1-shard byte-identity hold at a fixed seed.

Resilience (services/resilience.py): the parent's node table is
health-scored with a per-node circuit breaker — repeated request failures
open a node's breaker (it stops receiving traffic without waiting for the
17s keepalive eviction), a cooled-down breaker admits one probe request,
and a successful probe re-admits the node. route_fuzz retries each node
and fails over across distinct nodes before falling back to local
fuzzing, with every hop visible in metrics events; the caller's remaining
deadline propagates into each remote socket timeout so one slow node
cannot eat the whole request budget. remote_fuzz raises ProtocolError on
a malformed/missing reply — "the node failed" is an exception, never a
forged empty fuzz result. Fault sites dist.send / dist.recv and
dist.shard.send / dist.shard.recv (services/chaos.py) make all of it
deterministically testable.
"""

from __future__ import annotations

import base64
import functools
import json
import os
import random as _pyrandom
import signal
import socket
import struct
import threading
import time
import zlib

from ..constants import NODE_ALIVE_DELTA, NODE_KEEPALIVE, NODES_CHECKTIMER
from ..obs import flight, trace
from ..utils.erlrand import gen_urandom_seed
from . import chaos, logger, metrics
from .batcher import make_batcher
from .resilience import HealthTable, RetryExhausted, RetryPolicy
from .supervisor import supervise


class ProtocolError(ValueError):
    """The peer answered with garbage (or nothing): a node-side failure
    the caller must treat as retriable, distinct from a fuzzer that
    legitimately produced empty output."""


class RemoteShardError(OSError):
    """A remote fleet shard failed (connect/timeout/protocol/worker
    error). OSError subclass on purpose: the fleet coordinator treats it
    exactly like a local device loss — revoke the lease, redistribute,
    re-dispatch the slice on survivors within the case."""


class StaleEpochError(RemoteShardError):
    """Fencing verdict: a message carried an epoch that is not the
    current lease — either the worker fenced a stale coordinator
    request, or the coordinator rejected a stale (zombie) worker reply.
    The carried data is dropped, never merged."""


class WorkerClosing(RemoteShardError):
    """The worker announced a GRACEFUL shutdown (`worker_closing` frame,
    r20): it is closing its streams on purpose, not dying on the wire.
    Flows into the same revoke/redispatch path as any shard loss (it is
    a RemoteShardError), but coordinators log and count it distinctly so
    operators can tell a drain from a network partition."""


def _send_json(sock: socket.socket, obj: dict):
    chaos.fault_point("dist.send")
    sock.sendall(json.dumps(obj).encode() + b"\n")


# a peer streaming one endless line must not exhaust memory; 64 MiB covers
# any legitimate base64 fuzz payload (10 MB log cap * 4/3 with headroom)
MAX_LINE = 64 * 1024 * 1024


def _recv_json(f) -> dict | None:
    chaos.fault_point("dist.recv")
    line = f.readline(MAX_LINE + 1)
    if not line:
        return None
    if len(line) > MAX_LINE:
        raise ValueError("oversized protocol line")
    return json.loads(line)


def _send_shard_json(sock: socket.socket, obj: dict):
    """Coordinator -> shard-worker transmission: its own fault site so a
    chaos spec can kill the fleet's data plane without touching the
    join/fuzz control plane (dist.send)."""
    chaos.fault_point("dist.shard.send")
    sock.sendall(json.dumps(obj).encode() + b"\n")


def _recv_shard_json(f) -> dict | None:
    """Coordinator-side shard reply read (fault site dist.shard.recv)."""
    chaos.fault_point("dist.shard.recv")
    line = f.readline(MAX_LINE + 1)
    if not line:
        return None
    if len(line) > MAX_LINE:
        raise ValueError("oversized protocol line")
    return json.loads(line)


# -- framed shard streams (r15) ------------------------------------------

#: first byte 0x8f can never start a JSON line, so the listener sniffs
#: one byte to route a connection to the framed or the JSON-lines loop
FRAME_MAGIC = b"\x8fEF1"
_FRAME_HDR = struct.Struct("<II")  # header_len, blob_len
#: raw byte panels (seed payloads, outputs, snapshot pages) ride the
#: frame blob un-encoded; 1 GiB is far past any legitimate batch slice
MAX_FRAME = 1 << 30


def _pack_frame(header: dict, blob: bytes = b"") -> bytes:
    """Encode one frame: MAGIC + (header_len, blob_len) + JSON header +
    raw blob. Pure — the fault sites live on the send/recv wrappers."""
    hdr = json.dumps(header, separators=(",", ":")).encode()
    if len(hdr) > MAX_LINE or len(blob) > MAX_FRAME:
        raise ValueError("oversized frame")
    return b"".join((FRAME_MAGIC, _FRAME_HDR.pack(len(hdr), len(blob)),
                     hdr, blob))


def _read_frame(f) -> tuple[dict, bytes] | None:
    """Read one frame from a buffered reader; None on clean EOF. The
    reader's read(n) loops internally, so a short result outside EOF is
    impossible; any malformed prefix raises ValueError (a garbling peer
    is an error, never a hang)."""
    want = len(FRAME_MAGIC) + _FRAME_HDR.size
    head = f.read(want)
    if not head:
        return None
    if len(head) < want or head[:len(FRAME_MAGIC)] != FRAME_MAGIC:
        raise ValueError("malformed frame header")
    hlen, blen = _FRAME_HDR.unpack(head[len(FRAME_MAGIC):])
    if hlen > MAX_LINE or blen > MAX_FRAME:
        raise ValueError("oversized frame")
    hdr = f.read(hlen)
    blob = f.read(blen)
    if len(hdr) < hlen or len(blob) < blen:
        raise ValueError("truncated frame")
    return json.loads(hdr), blob


#: physical frame payload ceiling (r19): a blob larger than this is
#: split into continuation frames so one oversized step/snapshot can
#: never monopolize a stream's socket buffer for seconds — the max
#: PHYSICAL frame on the wire stays bounded and observable
#: (TransportTally.frame_bytes_max / erlamsa_fleet_frame_bytes_max)
FRAME_CHUNK = int(os.environ.get("ERLAMSA_FRAME_CHUNK", str(4 << 20)))


def _frames_for(header: dict, blob: bytes = b"") -> list[bytes]:
    """Split one LOGICAL frame into its physical wire frames. Blobs at
    or under FRAME_CHUNK ride a single frame byte-identical to the r15
    codec; larger blobs become a first frame carrying the header plus a
    ``_cont`` count and chunk 0, then ``{"op": "_cont", "i": k}``
    continuation frames with the remaining chunks. Deterministic in
    (header, blob), so the receive side can re-run it to reproduce the
    exact wire length for transport accounting."""
    if len(blob) <= FRAME_CHUNK:
        return [_pack_frame(header, blob)]  # lint: span-coverage-ok codec primitive; send/recv wrapper callers carry the span
    parts = [blob[i:i + FRAME_CHUNK]
             for i in range(0, len(blob), FRAME_CHUNK)]
    frames = [_pack_frame({**header, "_cont": len(parts) - 1}, parts[0])]  # lint: span-coverage-ok codec primitive; send/recv wrapper callers carry the span
    frames.extend(_pack_frame({"op": "_cont", "i": i}, p)  # lint: span-coverage-ok codec primitive; send/recv wrapper callers carry the span
                  for i, p in enumerate(parts[1:], 1))
    return frames


def _read_frames(f) -> tuple[dict, bytes] | None:
    """Read one LOGICAL frame: the r15 single-frame read plus r19
    continuation reassembly. Continuations must arrive in order on the
    same stream (frames are never interleaved within one connection);
    any gap or mislabel raises ValueError like a garbled frame."""
    got = _read_frame(f)  # lint: span-coverage-ok codec primitive; send/recv wrapper callers carry the span
    if got is None:
        return None
    header, blob = got
    more = int(header.pop("_cont", 0))
    if more <= 0:
        return header, blob
    chunks = [blob]
    for i in range(1, more + 1):
        nxt = _read_frame(f)  # lint: span-coverage-ok codec primitive; send/recv wrapper callers carry the span
        if (nxt is None or nxt[0].get("op") != "_cont"
                or int(nxt[0].get("i", -1)) != i):
            raise ValueError("truncated chunked frame")
        chunks.append(nxt[1])
    return header, b"".join(chunks)


def _shard_frame_send(sock: socket.socket, header: dict,
                      blob: bytes = b"") -> tuple[int, int]:
    """Coordinator -> worker framed transmission. Two fault sites, each
    fired ONCE per LOGICAL frame regardless of chunking (the r14
    per-invocation chaos counters keep counting sends, not chunks):
    dist.shard.frame (the codec — a frame-level fault reads as a shard
    loss exactly like a wire fault) and dist.shard.send (the wire, the
    same site the legacy JSON client fires). Returns (total bytes
    written, largest physical frame)."""
    chaos.fault_point("dist.shard.frame")
    parts = _frames_for(header, blob)  # lint: span-coverage-ok codec primitive; ShardStream callers carry the span
    chaos.fault_point("dist.shard.send")
    sock.sendall(b"".join(parts))
    return sum(len(p) for p in parts), max(len(p) for p in parts)


def _shard_frame_recv(f) -> tuple[dict, bytes] | None:
    """Coordinator-side framed reply read (fault site dist.shard.recv,
    shared with the legacy JSON client; fires once per logical frame —
    continuation reads ride the same invocation)."""
    chaos.fault_point("dist.shard.recv")
    return _read_frames(f)  # lint: span-coverage-ok codec primitive; read_reply callers carry the span


def _node_frame_send(sock: socket.socket, header: dict,
                     blob: bytes = b"") -> int:
    """Worker-side framed reply — fires dist.send like the legacy
    _send_json reply path, NOT the coordinator's dist.shard.* sites, so
    a dist.shard.* chaos spec keeps meaning 'the coordinator's view of
    the wire' with per-invocation counters the r14 tests rely on."""
    chaos.fault_point("dist.send")
    parts = _frames_for(header, blob)  # lint: span-coverage-ok codec primitive; ShardHost op handlers carry the span
    payload = b"".join(parts)
    sock.sendall(payload)
    return len(payload)


def _node_frame_recv(f) -> tuple[dict, bytes] | None:
    """Worker-side frame read (site dist.recv, like _recv_json)."""
    chaos.fault_point("dist.recv")
    return _read_frames(f)  # lint: span-coverage-ok codec primitive; ShardHost op handlers carry the span


class TransportTally:
    """Thread-safe per-campaign transport accounting, shared by every
    ShardStream of one fleet run: raw frame bytes by direction plus
    AWAITED round trips (lease / snapshot / probe / revoke / window
    sync — fire-and-forget step frames are pipelined data flow, not
    round trips). Mirrors into metrics.GLOBAL.record_transport for
    /metrics."""

    def __init__(self):
        self._lock = threading.Lock()
        self.bytes_sent = 0
        self.bytes_recv = 0
        self.round_trips = 0
        #: largest PHYSICAL frame seen in either direction (max-merge,
        #: r19): with chunked continuation frames this stays bounded by
        #: FRAME_CHUNK + header overhead — the observable proof that no
        #: oversized step/snapshot monopolized a stream
        self.frame_bytes_max = 0

    def add(self, sent: int = 0, recv: int = 0, round_trips: int = 0,
            frame_bytes: int = 0):
        with self._lock:
            self.bytes_sent += int(sent)
            self.bytes_recv += int(recv)
            self.round_trips += int(round_trips)
            if int(frame_bytes) > self.frame_bytes_max:
                self.frame_bytes_max = int(frame_bytes)
        metrics.GLOBAL.record_transport(sent=sent, recv=recv,
                                        round_trips=round_trips,
                                        frame_bytes=frame_bytes)

    def snapshot(self) -> dict:
        with self._lock:
            return {"bytes_sent": self.bytes_sent,
                    "bytes_recv": self.bytes_recv,
                    "round_trips": self.round_trips,
                    "frame_bytes_max": self.frame_bytes_max}


def validate_shard_reply(resp: dict | None, shard: int, epoch: int | None,
                         expect: str, case: int | None = None) -> dict:
    """Coordinator-side fencing gate: every shard reply must be the
    expected op AND echo the (shard, epoch, case) the request carried.
    A `shard_fenced` verdict from the worker, or any stale echo — a
    zombie worker answering after its lease was revoked and re-granted —
    raises StaleEpochError after logging + a `fence_rejected` metrics
    event and flight note. The reply's payload is never returned to the
    reduce on that path."""
    if resp is None:
        raise RemoteShardError(
            f"shard {shard}: peer closed without a reply")
    op = resp.get("op")
    if op == "shard_fenced":
        metrics.GLOBAL.record_event("shard_fenced")
        raise StaleEpochError(
            f"shard {shard}: worker fenced the request "
            f"(lease epoch {resp.get('have')}, sent {resp.get('got')})")
    if op == "worker_closing":
        metrics.GLOBAL.record_event("worker_closing")
        flight.GLOBAL.note("worker_closing", shard=int(shard))
        logger.log("warning", "fleet: shard %d announced a graceful "
                   "shutdown (worker_closing) — planned departure, not "
                   "a wire loss", shard)
        raise WorkerClosing(
            f"shard {shard}: worker closing (graceful shutdown)")
    if op == "shard_error":
        raise RemoteShardError(
            f"shard {shard}: worker step failed: {resp.get('error')}")
    if op != expect:
        raise RemoteShardError(
            f"shard {shard}: malformed reply: {str(resp)[:120]}")
    stale = int(resp.get("shard", -1)) != int(shard)
    if epoch is not None and int(resp.get("epoch", -1)) != int(epoch):
        stale = True
    if case is not None and int(resp.get("case", -1)) != int(case):
        stale = True
    if stale:
        metrics.GLOBAL.record_event("fence_rejected")
        flight.GLOBAL.note("fence_rejected", shard=int(shard),
                           want_epoch=epoch, want_case=case,
                           got_epoch=resp.get("epoch"),
                           got_case=resp.get("case"),
                           got_shard=resp.get("shard"))
        logger.log("warning", "fleet: stale reply for shard %d rejected "
                   "(want epoch=%s case=%s, got epoch=%s case=%s "
                   "shard=%s) — fenced, not merged", shard, epoch, case,
                   resp.get("epoch"), resp.get("case"), resp.get("shard"))
        raise StaleEpochError(
            f"shard {shard}: stale reply fenced (want epoch {epoch}, "
            f"got {resp.get('epoch')})")
    return resp


#: the per-lease configuration keys a shard_lease ships to the worker —
#: everything run_remote_slice needs to reproduce the local bytes
LEASE_CFG_KEYS = ("seed", "pri", "classes", "device_max", "batch",
                  "spmd")


def new_campaign_token() -> str:
    """Mint the identity for ONE coordinator campaign. Fencing epochs
    are scoped by this token on the worker: a fresh campaign (new
    token) starts from floor 0 even on a long-lived worker that served
    earlier runs, while a zombie of the SAME campaign stays fenced by
    its stale epoch and a zombie of an OLD campaign is fenced by its
    stale token. Transport metadata only — never mixed into sample
    bytes, so replay determinism is untouched."""
    return "".join(f"{x:04x}" for x in gen_urandom_seed())


class RemoteShard:
    """Coordinator-side client for one leased remote shard: lease /
    step / revoke / probe over the shard protocol, one connection per
    call (a dead worker costs one connect timeout, never a wedged
    persistent socket). Every call raises RemoteShardError on transport
    failure and StaleEpochError on a fencing verdict — both flow into
    the fleet's revoke/redispatch path."""

    def __init__(self, shard_id: int, host: str, port: int,
                 timeout: float = 90.0, token: str = ""):
        self.id = int(shard_id)
        self.host = host
        self.port = int(port)
        self.timeout = timeout
        self.token = token

    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    def _call(self, msg: dict, expect: str,
              timeout: float | None = None) -> dict:
        tmo = self.timeout if timeout is None else timeout
        try:
            with socket.create_connection((self.host, self.port),
                                          timeout=tmo) as s:
                _send_shard_json(s, msg)
                resp = _recv_shard_json(s.makefile("rb"))
        except (OSError, ValueError) as e:
            if isinstance(e, StaleEpochError):
                raise
            raise RemoteShardError(
                f"shard {self.id} @{self.endpoint()}: {e}") from e
        return validate_shard_reply(resp, self.id, msg.get("epoch"),
                                    expect, case=msg.get("case"))

    def lease(self, epoch: int, cfg: dict) -> dict:
        """Grant/refresh this shard's lease at `epoch`; ships the step
        configuration the worker caches for the lease's lifetime."""
        msg = {"op": "shard_lease", "shard": self.id, "epoch": int(epoch),
               "token": self.token}
        msg.update({k: cfg.get(k) for k in LEASE_CFG_KEYS})
        return self._call(msg, "shard_leased")

    def probe(self) -> dict:
        """Liveness probe (the fleet's re-admission check)."""
        return self._call({"op": "shard_probe", "shard": self.id},
                          "shard_alive", timeout=min(self.timeout, 10.0))

    def revoke(self, epoch: int) -> dict:
        """Fence the worker at `epoch` (best-effort: the caller ignores
        failures — an unreachable worker is already fenced by the
        epoch its next readmit lease will carry)."""
        return self._call({"op": "shard_revoke", "shard": self.id,
                           "epoch": int(epoch), "token": self.token},
                          "shard_revoked")

    def step(self, epoch: int, case: int, slots, payloads, score_rows,
             deadline: float | None = None):
        """One per-case slice dispatch: ship (slots, bytes, score rows)
        under the lease epoch, return (outs, score_rows, applied,
        shapes) decoded from the validated reply. The caller's remaining
        deadline caps the socket timeout (deadline propagation)."""
        timeout = self.timeout
        if deadline is not None:
            timeout = max(0.05, min(timeout,
                                    deadline - time.monotonic()))
        msg = {
            "op": "shard_step", "shard": self.id, "epoch": int(epoch),
            "token": self.token,
            "case": int(case), "slots": [int(s) for s in slots],
            "data": [base64.b64encode(p).decode() for p in payloads],
            "scores": [[int(x) for x in row] for row in score_rows],
        }
        with trace.span("dist.shard_step", shard=self.id, case=case,
                        rows=len(msg["slots"])):
            resp = self._call(msg, "shard_result", timeout=timeout)
        outs = [base64.b64decode(d) for d in resp.get("data", [])]
        if len(outs) != len(msg["slots"]):
            raise RemoteShardError(
                f"shard {self.id}: reply carries {len(outs)} rows for "
                f"{len(msg['slots'])} slots")
        return (outs, resp.get("scores", []), resp.get("applied", []),
                [tuple(sh) for sh in resp.get("shapes", [])])


class ShardStream:
    """Persistent framed connection to one remote shard (r15). Unlike
    RemoteShard (one connect per call), a stream amortizes the TCP setup
    across the lease's whole lifetime and supports the window protocol:
    `send` is fire-and-forget (the coordinator's dispatch thread writes
    step frames without waiting), `read_reply` consumes the FIFO reply
    stream (the reduce thread), and `request` is the awaited pair for
    lease / snapshot / probe / sync — the only calls counted as round
    trips. One writer + one reader per stream; `_wlock` serializes
    writers, reads are owned by whichever thread drains that shard's
    replies. Any transport or protocol failure closes the stream and
    raises RemoteShardError (StaleEpochError for fencing verdicts) into
    the fleet's revoke/redispatch path; a closed stream reconnects
    lazily on the next send."""

    def __init__(self, shard_id: int, host: str, port: int,
                 timeout: float = 90.0, token: str = "",
                 tally: TransportTally | None = None):
        self.id = int(shard_id)
        self.host = host
        self.port = int(port)
        self.timeout = timeout
        self.token = token
        self.tally = tally
        self._sock: socket.socket | None = None
        self._rfile = None
        self._wlock = threading.Lock()
        #: step frames written since the last acknowledged window sync —
        #: bumped by the dispatcher after each fire-and-forget step,
        #: reset when the sync ack is consumed; the coordinator reads it
        #: to decide when the window is full
        self.unsynced = 0
        #: sticky drain announcement (r20): set when any reply header
        #: carries ``"draining": true`` — the worker received SIGTERM
        #: and wants a graceful drain. The reduce thread sets it, the
        #: coordinator reads it at the next window fence (a bool under
        #: the GIL; no lock needed). Never reset — a draining worker's
        #: backend is dropped at the fence, or replaced on re-join.
        self.draining = False

    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    @property
    def connected(self) -> bool:
        return self._sock is not None

    def _connect(self):
        sock = socket.create_connection((self.host, self.port),
                                        timeout=self.timeout)
        self._sock = sock
        self._rfile = sock.makefile("rb")

    def send(self, header: dict, blob: bytes = b""):
        """Fire-and-forget frame write. Does NOT wait for a reply — the
        matching reply arrives on the FIFO stream and is consumed by a
        later recv. The campaign token is stamped in here so callers
        build headers with only op-specific fields."""
        header.setdefault("token", self.token)
        try:
            with self._wlock:
                if self._sock is None:
                    self._connect()
                n, fmax = _shard_frame_send(self._sock, header, blob)  # lint: span-coverage-ok transport primitive; dispatch spans live in corpus/fleet.py callers
        except StaleEpochError:
            raise
        except (OSError, ValueError) as e:
            self.close()
            raise RemoteShardError(
                f"shard {self.id} @{self.endpoint()}: {e}") from e
        if self.tally is not None:
            self.tally.add(sent=n, frame_bytes=fmax)

    def read_reply(self, expect: str, epoch: int | None,
                   case: int | None = None,
                   timeout: float | None = None) -> tuple[dict, bytes]:
        """Consume the next FIFO reply frame and fence-validate it
        against (expect, epoch, case). Reader-thread only."""
        if self._sock is None:
            raise RemoteShardError(
                f"shard {self.id} @{self.endpoint()}: stream closed")
        tmo = self.timeout if timeout is None else timeout
        try:
            self._sock.settimeout(tmo)
            got = _shard_frame_recv(self._rfile)  # lint: span-coverage-ok transport primitive; reply-consuming callers carry the span
        except StaleEpochError:
            raise
        except (OSError, ValueError) as e:
            self.close()
            raise RemoteShardError(
                f"shard {self.id} @{self.endpoint()}: {e}") from e
        if got is None:
            self.close()
            raise RemoteShardError(
                f"shard {self.id} @{self.endpoint()}: peer closed "
                "mid-stream")
        header, blob = got
        if header.get("draining"):
            self.draining = True
        if self.tally is not None:
            # exact: the worker packs replies with the same compact
            # separators AND the same deterministic chunk split, so
            # re-running the splitter reproduces the wire length and
            # the largest physical frame the reply actually used
            parts = _frames_for(header, blob)  # lint: span-coverage-ok accounting re-split, no wire traffic; reply-consuming callers carry the span
            self.tally.add(recv=sum(len(p) for p in parts),
                           frame_bytes=max(len(p) for p in parts))
        validate_shard_reply(header, self.id, epoch, expect, case=case)
        return header, blob

    def request(self, header: dict, blob: bytes = b"", *, expect: str,
                timeout: float | None = None) -> tuple[dict, bytes]:
        """Awaited send+recv pair — a genuine round trip (lease,
        snapshot, probe, revoke, window sync)."""
        self.send(header, blob)
        out = self.read_reply(expect, header.get("epoch"),  # lint: span-coverage-ok round-trip callers (fleet.lease/snapshot/probe/revoke) carry the span
                              case=header.get("case"), timeout=timeout)
        if self.tally is not None:
            self.tally.add(round_trips=1)
        return out

    def close(self):
        self.unsynced = 0
        sock, self._sock, self._rfile = self._sock, None, None
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


def request_telemetry(stream: ShardStream, epoch: int, case: int) -> bool:
    """Fire the out-of-band shard_telemetry frame right after a window
    fence (corpus/fleet.py remote_dispatch). The ``obs.telemetry`` chaos
    site gates the WHOLE exchange: a firing drops the request before any
    bytes move, the FIFO stream stays aligned, and the only evidence is
    a telemetry_lost count — the campaign itself must be unaffected.
    Returns True when the request went out (a matching consume_telemetry
    is then owed on the reply stream)."""
    try:
        chaos.fault_point("obs.telemetry")
        with trace.span("fleet.telemetry", shard=stream.id, case=case):
            stream.send({"op": "shard_telemetry", "shard": stream.id,
                         "epoch": epoch, "case": case})
        return True
    except (OSError, ValueError) as e:
        metrics.GLOBAL.record_event("telemetry_lost")
        logger.log("warning", "fleet: telemetry request to shard %d "
                   "dropped: %s", stream.id, e)
        return False


def consume_telemetry(stream: ShardStream, epoch: int, case: int) -> bool:
    """Read one shard_telemetered reply and fold it into the federation
    plane (obs/federate.py). Every failure — wire loss, fencing, a
    malformed payload — degrades to a telemetry_lost count; telemetry
    must never raise into the campaign's reduce path."""
    try:
        with trace.span("fleet.telemetry_fold", shard=stream.id,
                        case=case):
            header, blob = stream.read_reply("shard_telemetered", epoch,
                                             case=case)
            payload = json.loads(blob.decode()) if blob else {}
            from ..obs import federate

            federate.GLOBAL.ingest(stream.endpoint(), payload)
        if stream.tally is not None:
            stream.tally.add(round_trips=1)
        return True
    except (OSError, ValueError, TypeError, KeyError) as e:
        metrics.GLOBAL.record_event("telemetry_lost")
        logger.log("warning", "fleet: telemetry from shard %d lost: %s",
                   stream.id, e)
        return False


class ShardHost:
    """Worker-side half of the lease handshake: the lease table plus the
    stateless slice executor. A lease pins (epoch, step config) for a
    shard id; a revoke drops the lease and raises the shard's fence
    floor so any later message from the revoking coordinator's past —
    or a stale coordinator after a checkpoint resume — is rejected.
    Floors are scoped per campaign token: a NEW campaign reaching a
    long-lived worker starts from floor 0 (the old campaign's floors
    must not fence it), while messages carrying an old token are
    rejected outright. The compute itself
    (corpus/fleet.run_remote_slice) is a pure function of the shipped
    request, so fencing is the only state that matters."""

    def __init__(self):
        self._lock = threading.Lock()
        self._leases: dict[int, dict] = {}
        self._floor: dict[int, int] = {}
        self._token: dict[int, str] = {}
        # telemetry ship cursors (flight-ring seq, trace-event index):
        # process-wide, not per-shard, so a worker hosting several
        # shards ships each tail entry exactly once
        self._tele = {"flight": 0, "trace": 0}
        #: graceful-drain request (r20): set by SIGTERM in the worker
        #: entrypoint. While set, every framed reply is stamped
        #: ``"draining": true`` so the coordinator learns of the wish at
        #: the next reply it reads — the worker cannot send unsolicited
        #: frames on the FIFO stream, so the flag rides the replies.
        self.draining = threading.Event()
        #: set once a requested drain completed (fleet_drain consumed
        #: the last lease while `draining` was up) — the worker
        #: entrypoint exits on it
        self.drained = threading.Event()

    def handle(self, msg: dict) -> dict:
        op = msg.get("op")
        if op == "shard_probe":
            return {"op": "shard_alive", "shard": int(msg.get("shard", -1))}
        shard = int(msg.get("shard", -1))
        epoch = int(msg.get("epoch", -1))
        token = str(msg.get("token", ""))
        if op == "shard_lease":
            with self._lock:
                if self._token.get(shard, token) != token:
                    # new campaign: its epochs restart at 0, so the old
                    # campaign's floor must not apply to it
                    floor = 0
                else:
                    floor = self._floor.get(shard, 0)
                if epoch < floor:
                    metrics.GLOBAL.record_event("shard_fenced")
                    logger.log("warning", "shard host: stale lease for "
                               "shard %d fenced (epoch %d < floor %d)",
                               shard, epoch, floor)
                    return {"op": "shard_fenced", "shard": shard,
                            "got": epoch, "have": floor}
                self._leases[shard] = {
                    "epoch": epoch, "token": token,
                    "cfg": {k: msg.get(k) for k in LEASE_CFG_KEYS},
                }
                self._floor[shard] = epoch
                self._token[shard] = token
            logger.log("info", "shard host: lease granted shard=%d "
                       "epoch=%d", shard, epoch)
            return {"op": "shard_leased", "shard": shard, "epoch": epoch}
        if op == "shard_revoke":
            with self._lock:
                if self._token.get(shard, token) != token:
                    # a stale campaign's zombie cannot fence the
                    # current one; best-effort semantics make the ack
                    # harmless
                    return {"op": "shard_revoked", "shard": shard,
                            "epoch": epoch}
                self._leases.pop(shard, None)
                self._floor[shard] = max(self._floor.get(shard, 0), epoch)
                self._token[shard] = token
            logger.log("info", "shard host: lease revoked shard=%d, "
                       "fenced below epoch %d", shard, epoch)
            return {"op": "shard_revoked", "shard": shard, "epoch": epoch}
        if op == "fleet_drain":
            # graceful departure (r20): drop the lease AND raise the
            # fence floor to the drain epoch — exactly the revoke fence.
            # A later re-join of this worker must lease strictly above
            # this floor (the placement join() bumps the epoch first),
            # so a zombie of the drained life can never pass validation.
            with self._lock:
                if self._token.get(shard, token) != token:
                    # a stale campaign's drain is harmless to ack — the
                    # current campaign's floors are untouched
                    return {"op": "fleet_drained", "shard": shard,
                            "epoch": epoch}
                self._leases.pop(shard, None)
                self._floor[shard] = max(self._floor.get(shard, 0), epoch)
                self._token[shard] = token
                remaining = len(self._leases)
            logger.log("info", "shard host: lease drained shard=%d, "
                       "fenced below epoch %d (%d lease(s) left)",
                       shard, epoch, remaining)
            if self.draining.is_set() and remaining == 0:
                self.drained.set()
            return {"op": "fleet_drained", "shard": shard, "epoch": epoch}
        if op == "shard_step":
            with self._lock:
                lease = self._leases.get(shard)
            if (lease is None or epoch != lease["epoch"]
                    or token != lease["token"]):
                have = lease["epoch"] if lease else -1
                metrics.GLOBAL.record_event("shard_fenced")
                logger.log("warning", "shard host: fenced stale step for "
                           "shard %d (epoch %d, lease %d)", shard, epoch,
                           have)
                return {"op": "shard_fenced", "shard": shard,
                        "got": epoch, "have": have}
            cfg = lease["cfg"]
            case = int(msg.get("case", 0))
            slots = [int(s) for s in msg.get("slots", [])]
            payloads = [base64.b64decode(d) for d in msg.get("data", [])]
            try:
                from ..corpus.fleet import run_remote_slice

                outs, sc_out, applied, shapes = run_remote_slice(
                    tuple(cfg["seed"]), case, int(cfg["batch"]), slots,
                    payloads, msg.get("scores", []), cfg["pri"],
                    cfg["classes"], int(cfg["device_max"]),
                    spmd=bool(cfg.get("spmd")))
            except Exception as e:  # lint: broad-except-ok a worker device failure becomes a protocol-level shard_error the coordinator revokes on, not a dead handler thread
                logger.log("warning", "shard host: step failed shard=%d "
                           "case=%d: %s", shard, case, e)
                return {"op": "shard_error", "shard": shard,
                        "epoch": epoch, "error": str(e)[:200]}
            return {
                "op": "shard_result", "shard": shard, "epoch": epoch,
                "case": case,
                "data": [base64.b64encode(o).decode() for o in outs],
                "scores": [[int(x) for x in row] for row in sc_out],
                "applied": [[int(x) for x in row] for row in applied],
                "shapes": [list(sh) for sh in shapes],
            }
        return {"op": "shard_error", "shard": shard, "epoch": epoch,
                "error": f"unknown shard op {op!r}"}

    # -- framed ops (r15) ------------------------------------------------

    def _check_lease(self, shard: int, epoch: int,
                     token: str) -> tuple[dict | None, dict | None]:
        """Framed-path fencing gate: (lease, None) when (epoch, token)
        match the current lease, (None, shard_fenced header) otherwise.
        Same verdict the JSON shard_step path produces."""
        with self._lock:
            lease = self._leases.get(shard)
        if (lease is None or epoch != lease["epoch"]
                or token != lease["token"]):
            have = lease["epoch"] if lease else -1
            metrics.GLOBAL.record_event("shard_fenced")
            logger.log("warning", "shard host: fenced stale frame for "
                       "shard %d (epoch %d, lease %d)", shard, epoch, have)
            return None, {"op": "shard_fenced", "shard": shard,
                          "got": epoch, "have": have}
        return lease, None

    def handle_frame(self, header: dict,
                     blob: bytes) -> tuple[dict, bytes]:
        """Framed-op dispatch: the binary-stream twin of handle().
        shard_step / shard_snapshot / shard_sync are frame-native;
        everything else (lease, revoke, probe) reuses the JSON handler
        with an empty reply blob, so both transports share one lease
        table and one fencing discipline."""
        reply, rblob = self._dispatch_frame(header, blob)
        if self.draining.is_set():
            # piggyback the drain wish on every reply (transport
            # metadata only — validate_shard_reply ignores extra keys,
            # and the coordinator acts on it at its window fence, so
            # sample bytes never depend on when the flag appears)
            reply["draining"] = True
        return reply, rblob

    def _dispatch_frame(self, header: dict,
                        blob: bytes) -> tuple[dict, bytes]:
        op = header.get("op")
        if op == "shard_step":
            return self._step_framed(header, blob)
        if op == "shard_snapshot":
            return self._snapshot_framed(header, blob)
        if op == "shard_telemetry":
            return self._telemetry_framed(header)
        if op == "shard_sync":
            shard = int(header.get("shard", -1))
            epoch = int(header.get("epoch", -1))
            _, fenced = self._check_lease(shard, epoch,
                                          str(header.get("token", "")))
            if fenced is not None:
                return fenced, b""
            return ({"op": "shard_synced", "shard": shard, "epoch": epoch,
                     "case": int(header.get("case", -1))}, b"")
        return self.handle(header), b""

    def _step_framed(self, header: dict,
                     blob: bytes) -> tuple[dict, bytes]:
        """Framed shard_step: slots/sids/scores in the header, inline
        seed payloads packed back-to-back in the blob; sids absent from
        the inline set resolve against the lease's warm-start snapshot.
        Outputs return as raw concatenated bytes with a lens table —
        no base64 in either direction."""
        shard = int(header.get("shard", -1))
        epoch = int(header.get("epoch", -1))
        lease, fenced = self._check_lease(shard, epoch,
                                          str(header.get("token", "")))
        if fenced is not None:
            return fenced, b""
        cfg = lease["cfg"]
        case = int(header.get("case", 0))
        slots = [int(s) for s in header.get("slots", [])]
        try:
            inline: dict[str, bytes] = {}
            off = 0
            for sid, ln in zip(header.get("inline_sids", []),
                               [int(x) for x in
                                header.get("inline_lens", [])]):
                inline[str(sid)] = blob[off:off + ln]
                off += ln
            snap = lease.get("snap", {})
            payloads = []
            for sid in header.get("sids", []):
                p = inline.get(str(sid))
                if p is None:
                    p = snap.get(str(sid))
                if p is None:
                    return ({"op": "shard_error", "shard": shard,
                             "epoch": epoch,
                             "error": f"seed {sid} not resident "
                                      "(no inline payload, not in "
                                      "snapshot)"}, b"")
                payloads.append(p)
            from ..corpus.fleet import run_remote_slice

            # parent this worker's step span onto the coordinator's
            # per-case span via the propagated (trace, span) context —
            # the merged Chrome trace shows one fleet-wide timeline
            with trace.span_remote(
                    "shard.step",
                    trace_id=str(header.get("trace", "")),
                    parent=int(header.get("span", 0) or 0),
                    shard=shard, case=case, slots=len(slots)):
                outs, sc_out, applied, shapes = run_remote_slice(
                    tuple(cfg["seed"]), case, int(cfg["batch"]), slots,
                    payloads, header.get("scores", []), cfg["pri"],
                    cfg["classes"], int(cfg["device_max"]),
                    spmd=bool(cfg.get("spmd")))
        except Exception as e:  # lint: broad-except-ok a worker device failure becomes a protocol-level shard_error the coordinator revokes on, not a dead stream thread
            logger.log("warning", "shard host: framed step failed "
                       "shard=%d case=%d: %s", shard, case, e)
            return ({"op": "shard_error", "shard": shard, "epoch": epoch,
                     "error": str(e)[:200]}, b"")
        return ({
            "op": "shard_result", "shard": shard, "epoch": epoch,
            "case": case, "lens": [len(o) for o in outs],
            "scores": [[int(x) for x in row] for row in sc_out],
            "applied": [[int(x) for x in row] for row in applied],
            "shapes": [list(sh) for sh in shapes],
        }, b"".join(outs))

    def _telemetry_framed(self, header: dict) -> tuple[dict, bytes]:
        """Ship this worker's telemetry: cumulative metric totals plus
        the flight-ring and span-event tails since the last ship. Pure
        read — fencing applies (a zombie coordinator must not drain the
        tails the live one is due) but nothing about the campaign state
        changes, so a lost reply costs stale telemetry for one window
        and nothing else."""
        shard = int(header.get("shard", -1))
        epoch = int(header.get("epoch", -1))
        _, fenced = self._check_lease(shard, epoch,
                                      str(header.get("token", "")))
        if fenced is not None:
            return fenced, b""
        with self._lock:
            fcur, tcur = self._tele["flight"], self._tele["trace"]
        fl_entries, fnext = flight.GLOBAL.tail_since(fcur)
        tr_events, tnext = trace.GLOBAL.take_events(tcur)
        with self._lock:
            self._tele["flight"] = fnext
            self._tele["trace"] = tnext
        payload = {"pid": os.getpid(),
                   "metrics": metrics.GLOBAL.federation_totals(),
                   "flight": fl_entries, "trace": tr_events}
        try:
            blob = json.dumps(payload, separators=(",", ":"),
                              default=str).encode()
        except (TypeError, ValueError):
            # a non-serializable stowaway in a ring entry must not kill
            # the stream — degrade to metrics-only for this window
            blob = json.dumps({"pid": payload["pid"],
                               "metrics": payload["metrics"]},
                              separators=(",", ":"), default=str).encode()
        return ({"op": "shard_telemetered", "shard": shard,
                 "epoch": epoch,
                 "case": int(header.get("case", -1))}, blob)

    def _snapshot_framed(self, header: dict,
                         blob: bytes) -> tuple[dict, bytes]:
        """Install an arena warm-start snapshot into the lease: the blob
        carries page-padded payloads, the header their sids/lens, the
        page size, and a crc32 over the blob. Fenced like any step (the
        epoch stamp is what stops a zombie restore from serving a stale
        partition), and crc-checked so a corrupt image is rejected
        rather than silently served."""
        shard = int(header.get("shard", -1))
        epoch = int(header.get("epoch", -1))
        lease, fenced = self._check_lease(shard, epoch,
                                          str(header.get("token", "")))
        if fenced is not None:
            return fenced, b""
        want_crc = int(header.get("crc", -1)) & 0xFFFFFFFF
        if zlib.crc32(blob) & 0xFFFFFFFF != want_crc:
            metrics.GLOBAL.record_event("snapshot_crc_rejected")
            logger.log("warning", "shard host: snapshot crc mismatch "
                       "shard=%d epoch=%d — rejected", shard, epoch)
            return ({"op": "shard_error", "shard": shard, "epoch": epoch,
                     "error": "snapshot crc mismatch"}, b"")
        page = max(1, int(header.get("page", 1)))
        snap: dict[str, bytes] = {}
        off = 0
        for sid, ln in zip(header.get("sids", []),
                           [int(x) for x in header.get("lens", [])]):
            snap[str(sid)] = blob[off:off + ln]
            off += max(1, -(-ln // page)) * page
        with self._lock:
            if self._leases.get(shard) is lease:
                lease["snap"] = snap
        logger.log("info", "shard host: snapshot installed shard=%d "
                   "epoch=%d seeds=%d", shard, epoch, len(snap))
        return ({"op": "shard_snapshotted", "shard": shard,
                 "epoch": epoch, "count": len(snap)}, b"")


# per-node request retry: short, bounded — failover to ANOTHER node beats
# hammering a sick one (the reference just picks a random node per call)
NODE_RETRY = RetryPolicy(attempts=2, base=0.05, max_delay=0.5,
                         retry_on=(OSError, ValueError))
MAX_FAILOVER_NODES = 3  # distinct nodes tried before local fallback


class NodePool:
    """Parent-side registry of live worker nodes
    (erlamsa_app:loop/3, src/erlamsa_app.erl:210-246), health-scored:
    keepalives keep a node listed, request outcomes move its score and
    breaker, and pick() routes around open breakers."""

    def __init__(self, check_interval: float = NODES_CHECKTIMER,
                 max_age: float = NODE_ALIVE_DELTA):
        self._rng = _pyrandom.Random(str(gen_urandom_seed()))
        # breaker cool-down ~ keepalive period: a node evicted for request
        # failures gets its re-admission probe about when the reference
        # would first notice it died
        self.table = HealthTable(self._rng, failure_threshold=2,
                                 reset_timeout=NODE_KEEPALIVE / 3.0)
        # eviction lives in HealthTable.start_eviction so dist node health
        # and fleet shard health share one drop_stale implementation (and
        # one `dropped_stale` accounting path)
        self.table.start_eviction("nodepool-evict", check_interval, max_age,
                                  on_drop=self._on_evicted)

    @staticmethod
    def _on_evicted(node):
        host, port = node
        metrics.GLOBAL.record_event("node_evicted")
        logger.log("info", "node %s:%d evicted (silent)", host, port)

    def join(self, host: str, port: int):
        if self.table.touch((host, port)):
            logger.log("info", "node %s:%d joined", host, port)

    def pick(self, exclude=()) -> tuple[str, int] | None:
        """A routable node (get_free_node, src/erlamsa_app.erl:185-190) —
        healthy nodes weighted by score, open breakers skipped, one probe
        admitted per cooled-down breaker."""
        return self.table.pick(exclude=exclude)

    def report(self, node: tuple[str, int], ok: bool):
        self.table.report(node, ok)

    def count(self) -> int:
        return self.table.count()


class ParentServer:
    """Accepts joins and fuzz requests; routes requests across healthy
    worker nodes with retry + failover, falling back to local fuzzing
    when no node can serve."""

    def __init__(self, port: int, opts: dict, backend: str = "oracle"):
        self.port = port
        self.pool = NodePool()
        self.local = make_batcher(backend, workers=opts.get("workers", 10),
                                  seed=opts.get("seed"))
        self.opts = opts
        self.shards = ShardHost()  # fleet shard-lease handshake host
        self._stop = threading.Event()
        # open peer connections (conn -> framed?), tracked so stop()
        # can announce worker_closing instead of silently dropping them
        self._conns: dict[socket.socket, bool] = {}
        self._conns_lock = threading.Lock()

    def _handle(self, conn: socket.socket, addr):
        f = conn.makefile("rb")
        with self._conns_lock:
            self._conns[conn] = False
        try:
            # one-byte sniff routes the connection: FRAME_MAGIC's first
            # byte (0x8f) can never begin a JSON line, so framed fleet
            # streams and legacy JSON peers share this listener
            if f.peek(1)[:1] == FRAME_MAGIC[:1]:
                with self._conns_lock:
                    self._conns[conn] = True
                self._handle_frames(conn, f)
                return
            while True:
                msg = _recv_json(f)
                if msg is None:
                    return
                if msg.get("op") == "join":
                    self.pool.join(addr[0], int(msg.get("port", 0)))
                    _send_json(conn, {"op": "joined"})
                elif msg.get("op") in ("shard_lease", "shard_step",
                                       "shard_revoke", "shard_probe",
                                       "fleet_drain"):
                    _send_json(conn, self.shards.handle(msg))
                elif msg.get("op") == "fuzz":
                    data = base64.b64decode(msg.get("data", ""))
                    out = self.route_fuzz(data)
                    _send_json(conn, {"op": "result",
                                      "data": base64.b64encode(out).decode()})
        except (OSError, ValueError) as e:
            # a dead/garbling peer must not kill the handler thread, but
            # it must not vanish either — silent swallowing here hid every
            # protocol bug and truncated request
            logger.log("warning", "dist: dropping connection from %s:%d: %s",
                       addr[0], addr[1], e)
        finally:
            with self._conns_lock:
                self._conns.pop(conn, None)
            conn.close()

    def _handle_frames(self, conn: socket.socket, f):
        """Framed shard-stream loop: strict FIFO request -> reply on one
        persistent connection (the ordering ShardStream's one-writer /
        one-reader split depends on). Runs until clean EOF; transport
        and codec failures ride _handle's logged-drop path."""
        while True:
            got = _node_frame_recv(f)  # lint: span-coverage-ok dispatch loop; per-op spans live in ShardHost.handle_frame handlers
            if got is None:
                return
            header, blob = got
            reply, rblob = self.shards.handle_frame(header, blob)
            _node_frame_send(conn, reply, rblob)  # lint: span-coverage-ok same handlers carry the span

    def route_fuzz(self, data: bytes, timeout: float = 90.0) -> bytes:
        """Route one request: up to MAX_FAILOVER_NODES distinct healthy
        nodes, each under the per-node retry policy, then the local
        engine. Outcomes feed the health table, so a failing node's
        breaker opens after a couple of requests and traffic routes
        around it until its re-admission probe succeeds."""
        deadline = time.monotonic() + timeout
        tried: set = set()
        while len(tried) < MAX_FAILOVER_NODES:
            node = self.pool.pick(exclude=tried)
            if node is None:
                break
            tried.add(node)
            try:
                with trace.span("dist.route", node=f"{node[0]}:{node[1]}",
                                attempt=len(tried)):
                    # the partial carries the deadline INTO remote_fuzz
                    # (socket timeout = time remaining); the call kwarg
                    # caps the retry loop itself — RetryPolicy.call
                    # consumes `deadline`, it does not forward it
                    out = NODE_RETRY.call(
                        functools.partial(remote_fuzz, node[0], node[1],
                                          data, deadline=deadline),
                        site=f"dist:{node[0]}:{node[1]}", deadline=deadline,
                    )
                self.pool.report(node, True)
                return out
            except (RetryExhausted, OSError, ValueError):
                self.pool.report(node, False)
                metrics.GLOBAL.record_event("failover")
                logger.log("warning", "node %s:%d failed, failing over "
                           "(%d tried)", node[0], node[1], len(tried))
        if tried:
            metrics.GLOBAL.record_event("dist_local_fallback")
            logger.log("warning", "all %d node(s) failed, fuzzing locally",
                       len(tried))
        return self.local.fuzz(data, dict(self.opts))

    def serve(self, block: bool = True):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", self.port))
        srv.listen(64)
        self._srv = srv
        logger.log("info", "distribution parent on :%d", self.port)

        def loop():
            while not self._stop.is_set():
                try:
                    conn, addr = srv.accept()
                except OSError:
                    break
                threading.Thread(target=self._handle, args=(conn, addr),
                                 daemon=True).start()

        if block:
            loop()
            return 0
        supervise("dist-parent-accept", loop)
        return self

    def stop(self):
        """Shut the listener down and announce it. Every still-open peer
        gets an explicit ``worker_closing`` frame (or JSON line) before
        its socket closes (r20) — a coordinator mid-stream sees a
        protocol-level verdict (dist.WorkerClosing) instead of a bare
        connection reset, so logs and metrics distinguish a planned
        shutdown from network loss. Best-effort: a peer that is already
        gone, or racing a concurrent reply write, degrades to the old
        silent-close behavior."""
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._conns_lock:
            peers = list(self._conns.items())
        for conn, framed in peers:
            try:
                # dist.send fault = the goodbye never leaves: the peer
                # sees the pre-r20 silent close, nothing worse
                chaos.fault_point("dist.send")
                if framed:
                    conn.sendall(_pack_frame({"op": "worker_closing"}))  # lint: span-coverage-ok best-effort shutdown courtesy, no reply expected
                else:
                    conn.sendall(json.dumps({"op": "worker_closing"})
                                 .encode() + b"\n")
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass


def remote_fuzz(host: str, port: int, data: bytes, timeout: float = 90.0,
                deadline: float | None = None) -> bytes:
    """Client call into a node (erlamsa_app:call/2,
    src/erlamsa_app.erl:248-253). Raises ProtocolError when the node
    closes without answering or answers with a non-result — callers can
    then distinguish "node failed" (failover) from "fuzzer produced empty
    output" (a legitimate result).

    deadline: absolute time.monotonic() bound from the caller; when set,
    the socket timeout is the time REMAINING, not the flat default — a
    slow node fails this hop fast enough that failover still fits inside
    the caller's budget (resilience.RetryPolicy deadline propagation,
    extended to the blocking I/O itself)."""
    if deadline is not None:
        timeout = max(0.05, min(timeout, deadline - time.monotonic()))
    with trace.span("dist.remote_fuzz", node=f"{host}:{port}",
                    bytes=len(data)):
        with socket.create_connection((host, port), timeout=timeout) as s:
            _send_json(s, {"op": "fuzz",
                           "data": base64.b64encode(data).decode()})
            resp = _recv_json(s.makefile("rb"))
            if resp is None:
                raise ProtocolError(f"node {host}:{port} closed without "
                                    "a reply")
            if resp.get("op") != "result" or "data" not in resp:
                raise ProtocolError(f"node {host}:{port} sent a malformed "
                                    f"reply: {str(resp)[:120]}")
            return base64.b64decode(resp["data"])


class WorkerNode:
    """Joins a parent with keepalives and serves fuzz requests
    (erlamsa_app:loop_node, src/erlamsa_app.erl:165-182)."""

    def __init__(self, parent_host: str, parent_port: int, opts: dict,
                 backend: str = "oracle", listen_port: int = 0):
        self.parent = (parent_host, parent_port)
        self.server = ParentServer(listen_port or 0, opts, backend)
        self.opts = opts
        self._stop = threading.Event()

    def start(self, block: bool = True):
        self.server.serve(block=False)
        my_port = self.server._srv.getsockname()[1]

        def keepalive():
            while not self._stop.is_set():
                try:
                    with socket.create_connection(self.parent, timeout=5) as s:
                        _send_json(s, {"op": "join", "port": my_port})
                        _recv_json(s.makefile("rb"))
                except (OSError, ValueError) as e:
                    logger.log("warning", "keepalive to parent failed: %s", e)
                self._stop.wait(NODE_KEEPALIVE)

        t = supervise("node-keepalive", keepalive)
        if block:
            t.join()
            return 0
        return self

    def stop(self):
        self._stop.set()
        self.server.stop()


def run_node(host: str, port: int, opts: dict) -> int:
    return WorkerNode(host, port, opts).start(block=True)


class MembershipListener:
    """Coordinator-side hot-join intake (`--fleet-accept PORT`, r20): a
    tiny TCP listener that accepts ``fleet_join`` announcements from
    workers (framed or JSON-lines, one-byte sniff like ParentServer),
    acks them, and queues the candidate for the fleet coordinator to
    ADMIT AT ITS NEXT WINDOW FENCE. Admission is deliberately deferred:
    the fence is the only point with zero steps in flight, so joining
    there re-derives placement without fencing live work — and because
    placement is pure and PRNG streams are counter-keyed, WHEN a join
    lands can shift which worker serves which slots but never the bytes.

    The listener thread only parses and queues; it never touches the
    placement table (single-threaded by design, like the arena)."""

    def __init__(self, port: int = 0):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", int(port)))
        srv.listen(16)
        self._srv = srv
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._pending: list[dict] = []
        supervise("fleet-membership-accept", self._loop)
        logger.log("info", "fleet membership listener on :%d", self.port)

    @property
    def port(self) -> int:
        return self._srv.getsockname()[1]

    def _loop(self):
        while not self._stop.is_set():
            try:
                conn, addr = self._srv.accept()
            except OSError:
                break
            threading.Thread(target=self._intake, args=(conn, addr),
                             daemon=True).start()

    def _intake(self, conn: socket.socket, addr):
        """Parse one fleet_join announcement and ack it. The handshake
        carries the worker's capabilities (serve port, spmd flag,
        optional classes, optional campaign token) — capability
        VALIDATION happens at the admit fence, not here; the listener's
        ack only means 'queued'."""
        try:
            # dist.recv fault = the announcement drops on the floor;
            # the announcer's retry loop (announce_fleet_join) covers it
            chaos.fault_point("dist.recv")
            conn.settimeout(10.0)
            f = conn.makefile("rb")
            framed = f.peek(1)[:1] == FRAME_MAGIC[:1]
            if framed:
                got = _read_frames(f)  # lint: span-coverage-ok join intake handshake; the admit fence in corpus/fleet.py carries the span
                header = got[0] if got else None
            else:
                line = f.readline(MAX_LINE + 1)
                header = json.loads(line) if line else None
            if header is None or header.get("op") != "fleet_join":
                raise ProtocolError(
                    f"expected fleet_join, got {str(header)[:80]}")
            ev = {
                "host": str(header.get("host") or addr[0]),
                "port": int(header.get("port", 0)),
                "spmd": bool(header.get("spmd")),
                "classes": header.get("classes"),
                "token": str(header.get("token", "")),
            }
            if not (0 < ev["port"] < 65536):
                raise ProtocolError(f"bad join port {ev['port']}")
            # queue BEFORE acking: an announcer that saw the ack must be
            # visible to the very next fence take()
            with self._lock:
                self._pending.append(ev)
            ack = {"op": "fleet_join_ack", "port": ev["port"]}
            if framed:
                conn.sendall(_pack_frame(ack))  # lint: span-coverage-ok join intake handshake; the admit fence carries the span
            else:
                conn.sendall(json.dumps(ack).encode() + b"\n")
            metrics.GLOBAL.record_event("fleet_join_announced")
            logger.log("info", "fleet: join announced from %s:%d "
                       "(spmd=%s) — queued for the next fence",
                       ev["host"], ev["port"], ev["spmd"])
        except (OSError, ValueError) as e:
            logger.log("warning", "fleet: dropping join announcement "
                       "from %s: %s", addr[0], e)
        finally:
            conn.close()

    def take(self) -> list[dict]:
        """Drain the pending-join queue (fence-time, coordinator
        thread). Arrival order is preserved."""
        with self._lock:
            out, self._pending = self._pending, []
        return out

    def close(self):
        self._stop.set()
        try:
            # shutdown BEFORE close: a plain close() while the accept
            # thread is blocked in the syscall leaves the kernel socket
            # alive (the in-flight accept pins it), silently accepting
            # joins after the coordinator stopped listening
            self._srv.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._srv.close()
        except OSError:
            pass


def announce_fleet_join(host: str, port: int, my_port: int,
                        caps: dict | None = None, attempts: int = 40,
                        delay: float = 0.25) -> dict:
    """Worker -> coordinator hot-join handshake (`--fleet-join`): send
    one framed ``fleet_join`` frame carrying this worker's serve port
    and capabilities, wait for the ack. Retries cover the races a real
    elastic deploy hits (worker up before the coordinator's listener, a
    coordinator restarting between campaigns). Raises RemoteShardError
    once the attempts are exhausted."""
    msg = {"op": "fleet_join", "port": int(my_port), **(caps or {})}
    last: Exception | None = None
    for _ in range(max(1, attempts)):
        try:
            chaos.fault_point("dist.send")
            with socket.create_connection((host, int(port)),
                                          timeout=10.0) as s:
                s.sendall(_pack_frame(msg))  # lint: span-coverage-ok one-shot handshake; the admit fence carries the span
                resp = _read_frames(s.makefile("rb"))  # lint: span-coverage-ok one-shot handshake; the admit fence carries the span
            if resp is None or resp[0].get("op") != "fleet_join_ack":
                raise ProtocolError(
                    f"bad fleet_join ack: {str(resp and resp[0])[:80]}")
            logger.log("info", "fleet: join announced to %s:%d "
                       "(serving on :%d)", host, port, my_port)
            return resp[0]
        except (OSError, ValueError) as e:
            last = e
            time.sleep(delay)
    raise RemoteShardError(
        f"fleet join to {host}:{port} failed after {attempts} "
        f"attempts: {last}")


def run_shard_worker(port: int, opts: dict,
                     join: str | None = None) -> int:
    """`--fleet-worker PORT` / `--fleet-join COORD:PORT`: serve fleet
    shard leases on this host. A plain ParentServer — the shard protocol
    rides the same listener as join/fuzz (framed streams AND legacy
    JSON, routed by first-byte sniff), so one process can serve both
    roles; the ShardHost keeps the lease table and the compute is
    rebuilt per step from the shipped request (stateless worker: a
    restart costs a re-lease plus a snapshot re-ship, nothing else).

    r20 lifecycle: with `join=COORD:PORT` the worker binds an ephemeral
    (or given) port first, then announces itself to the coordinator's
    membership listener — admission happens at the coordinator's next
    window fence. SIGTERM requests a GRACEFUL DRAIN instead of dying:
    replies start carrying ``draining: true``, the coordinator hands the
    partitions back with a ``fleet_drain`` fence at its next window
    boundary, and only then does this process stop its listener (with
    worker_closing courtesy frames) and exit — zero rewinds, zero
    replayed cases."""
    srv = ParentServer(port, opts)
    srv.serve(block=False)
    my_port = srv._srv.getsockname()[1]
    logger.log("info", "fleet shard worker on :%d", my_port)

    def _sigterm(_signum, _frame):
        logger.log("info", "fleet worker :%d: SIGTERM — requesting "
                   "graceful drain", my_port)
        metrics.GLOBAL.record_event("worker_drain_requested")
        srv.shards.draining.set()

    try:
        signal.signal(signal.SIGTERM, _sigterm)
    except ValueError:
        pass  # not the main thread (tests drive shards.draining directly)
    if join:
        host, _, cport = str(join).rpartition(":")
        announce_fleet_join(host or "127.0.0.1", int(cport), my_port,
                            caps={"spmd": bool(opts.get("spmd")),
                                  "token": str(opts.get("fleet_token")
                                               or "")})
    try:
        while not srv.shards.drained.wait(0.2):
            if not srv.shards.draining.is_set():
                continue
            # a drain is also complete when there is nothing to hand
            # back: SIGTERM on an idle worker (no lease held), or the
            # campaign already ended — the coordinator closed its
            # persistent streams at teardown without a fence, so no
            # fleet_drain will ever arrive for the stale lease
            with srv._conns_lock:
                attached = bool(srv._conns)
            if not srv.shards._leases or not attached:
                break
        logger.log("info", "fleet worker :%d: drain complete — exiting",
                   my_port)
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
    return 0
