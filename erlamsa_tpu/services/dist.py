"""Distributed fuzzing nodes: join/keepalive control plane.

Reference: src/erlamsa_app.erl:144-246 — worker nodes join a parent over
Erlang distribution with {join, Pid} keepalives every 15s, the parent
evicts nodes silent for >17s and routes each fuzz request to a random live
node. Here the control plane is a JSON-lines TCP protocol:

    {"op": "join", "port": N}            worker -> parent (keepalive)
    {"op": "fuzz", "data": b64, ...}     parent -> worker / client -> parent
    {"op": "result", "data": b64}        reply

The data plane stays local to each node (its own oracle pool or TPU batch
engine) — DCN-style corpus fan-out between hosts, device-local mutation,
matching SURVEY.md §5.8's design obligation.
"""

from __future__ import annotations

import base64
import json
import socket
import threading
import time

from ..constants import NODE_ALIVE_DELTA, NODE_KEEPALIVE, NODES_CHECKTIMER
from ..utils.erlrand import gen_urandom_seed
from . import logger
from .batcher import make_batcher
from .supervisor import supervise


def _send_json(sock: socket.socket, obj: dict):
    sock.sendall(json.dumps(obj).encode() + b"\n")


# a peer streaming one endless line must not exhaust memory; 64 MiB covers
# any legitimate base64 fuzz payload (10 MB log cap * 4/3 with headroom)
MAX_LINE = 64 * 1024 * 1024


def _recv_json(f) -> dict | None:
    line = f.readline(MAX_LINE + 1)
    if not line:
        return None
    if len(line) > MAX_LINE:
        raise ValueError("oversized protocol line")
    return json.loads(line)


class NodePool:
    """Parent-side registry of live worker nodes
    (erlamsa_app:loop/3, src/erlamsa_app.erl:210-246)."""

    def __init__(self):
        self._nodes: dict[tuple[str, int], float] = {}
        self._lock = threading.Lock()
        import random as _pyrandom

        self._rng = _pyrandom.Random(str(gen_urandom_seed()))
        supervise("nodepool-evict", self._evict_loop)

    def join(self, host: str, port: int):
        with self._lock:
            fresh = (host, port) not in self._nodes
            self._nodes[(host, port)] = time.time()
        if fresh:
            logger.log("info", "node %s:%d joined", host, port)

    def _evict_loop(self):
        while True:
            time.sleep(NODES_CHECKTIMER)
            now = time.time()
            with self._lock:
                dead = [k for k, t in self._nodes.items()
                        if now - t > NODE_ALIVE_DELTA]
                for k in dead:
                    del self._nodes[k]
                    logger.log("info", "node %s:%d evicted", *k)

    def pick(self) -> tuple[str, int] | None:
        """Random live node (get_free_node, src/erlamsa_app.erl:185-190)."""
        with self._lock:
            if not self._nodes:
                return None
            return self._rng.choice(list(self._nodes))

    def count(self) -> int:
        with self._lock:
            return len(self._nodes)


class ParentServer:
    """Accepts joins and fuzz requests; routes requests to a random worker
    node, falling back to local fuzzing when no nodes joined."""

    def __init__(self, port: int, opts: dict, backend: str = "oracle"):
        self.port = port
        self.pool = NodePool()
        self.local = make_batcher(backend, workers=opts.get("workers", 10),
                                  seed=opts.get("seed"))
        self.opts = opts
        self._stop = threading.Event()

    def _handle(self, conn: socket.socket, addr):
        f = conn.makefile("rb")
        try:
            while True:
                msg = _recv_json(f)
                if msg is None:
                    return
                if msg.get("op") == "join":
                    self.pool.join(addr[0], int(msg.get("port", 0)))
                    _send_json(conn, {"op": "joined"})
                elif msg.get("op") == "fuzz":
                    data = base64.b64decode(msg.get("data", ""))
                    out = self.route_fuzz(data)
                    _send_json(conn, {"op": "result",
                                      "data": base64.b64encode(out).decode()})
        except (OSError, ValueError):
            pass
        finally:
            conn.close()

    def route_fuzz(self, data: bytes) -> bytes:
        node = self.pool.pick()
        if node is not None:
            try:
                return remote_fuzz(node[0], node[1], data)
            except (OSError, ValueError):
                logger.log("warning", "node %s:%d failed, fuzzing locally", *node)
        return self.local.fuzz(data, dict(self.opts))

    def serve(self, block: bool = True):
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind(("0.0.0.0", self.port))
        srv.listen(64)
        self._srv = srv
        logger.log("info", "distribution parent on :%d", self.port)

        def loop():
            while not self._stop.is_set():
                try:
                    conn, addr = srv.accept()
                except OSError:
                    break
                threading.Thread(target=self._handle, args=(conn, addr),
                                 daemon=True).start()

        if block:
            loop()
            return 0
        supervise("dist-parent-accept", loop)
        return self

    def stop(self):
        self._stop.set()
        try:
            self._srv.close()
        except Exception:
            pass


def remote_fuzz(host: str, port: int, data: bytes, timeout: float = 90.0) -> bytes:
    """Client call into a node (erlamsa_app:call/2,
    src/erlamsa_app.erl:248-253)."""
    with socket.create_connection((host, port), timeout=timeout) as s:
        _send_json(s, {"op": "fuzz", "data": base64.b64encode(data).decode()})
        resp = _recv_json(s.makefile("rb"))
        if resp and resp.get("op") == "result":
            return base64.b64decode(resp.get("data", ""))
    return b""


class WorkerNode:
    """Joins a parent with keepalives and serves fuzz requests
    (erlamsa_app:loop_node, src/erlamsa_app.erl:165-182)."""

    def __init__(self, parent_host: str, parent_port: int, opts: dict,
                 backend: str = "oracle", listen_port: int = 0):
        self.parent = (parent_host, parent_port)
        self.server = ParentServer(listen_port or 0, opts, backend)
        self.opts = opts
        self._stop = threading.Event()

    def start(self, block: bool = True):
        self.server.serve(block=False)
        my_port = self.server._srv.getsockname()[1]

        def keepalive():
            while not self._stop.is_set():
                try:
                    with socket.create_connection(self.parent, timeout=5) as s:
                        _send_json(s, {"op": "join", "port": my_port})
                        _recv_json(s.makefile("rb"))
                except (OSError, ValueError) as e:
                    logger.log("warning", "keepalive to parent failed: %s", e)
                self._stop.wait(NODE_KEEPALIVE)

        t = supervise("node-keepalive", keepalive)
        if block:
            t.join()
            return 0
        return self

    def stop(self):
        self._stop.set()
        self.server.stop()


def run_node(host: str, port: int, opts: dict) -> int:
    return WorkerNode(host, port, opts).start(block=True)
